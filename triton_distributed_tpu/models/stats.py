"""Shared serving-stats schema.

``Engine.last_stats`` (a plain dict rebuilt per serve) and
``ContinuousEngine.last_stats`` (a property over live counters) grew
independently across PRs 1–4 and drifted silently — a dashboard keyed
on one engine's shape broke on the other. The CORE key set below is
the contract both engines MUST expose (asserted by
``tests/test_obs.py::test_core_stats_keys_unified``); everything else
remains engine-specific.

=====================  ================================================
``decode_steps``       batched decode device programs run — verify
                       chunks excluded; speculative serving counts
                       those in ``spec_verify_steps``, and BOTH engines
                       expose ``target_steps = decode_steps +
                       spec_verify_steps`` when speculation is on (the
                       "target forwards" a throughput model needs)
``prefill_tokens``     prompt tokens actually prefilled (prefix-cache
                       hits excluded — this is work DONE, not accepted)
``generated_tokens``   tokens emitted to callers (partials included)
``kv_bytes_per_token`` per-token KV footprint of the active cache
``kv_dtype``           KV storage dtype (the PR 4 quantization knob)
=====================  ================================================
"""

from __future__ import annotations

CORE_STATS_KEYS = (
    "decode_steps",
    "prefill_tokens",
    "generated_tokens",
    "kv_bytes_per_token",
    "kv_dtype",
)


def missing_core_stats(stats: dict) -> list[str]:
    """Core keys absent from ``stats`` (empty == conforming)."""
    return [k for k in CORE_STATS_KEYS if k not in stats]


# Registry metric (name, help) for each serving counter mirrored into
# the process metrics registry (docs/observability.md). ONE table for
# both engines: Registry._get_or_create keeps the first help string it
# sees for a name, so duplicated literals would drift silently with
# engine construction order.
STAT_METRICS = {
    "admitted": ("tdt_engine_admitted_total",
                 "Requests admitted to a decode slot."),
    "decode_steps": ("tdt_engine_decode_steps_total",
                     "Batched decode device programs run."),
    "prefill_tokens": ("tdt_engine_prefill_tokens_total",
                       "Prompt tokens prefilled (prefix hits excluded)."),
    "prefill_chunks": ("tdt_engine_prefill_chunks_total",
                       "Chunked-prefill programs run."),
    "prefix_hit_tokens": ("tdt_engine_prefix_hit_tokens_total",
                          "Prompt tokens served from the radix tree."),
    "pages_cow_copied": ("tdt_engine_pages_cow_total",
                         "Pages COW-cloned at admission."),
    "admission_stalls": ("tdt_engine_admission_stalls_total",
                         "Admission scans stalled for pool pages."),
    "generated_tokens": ("tdt_engine_generated_tokens_total",
                         "Tokens emitted (partials included)."),
    "spec_verify_steps": ("tdt_engine_spec_verify_steps_total",
                          "Speculative verify chunk programs run."),
    "spec_draft_tokens": ("tdt_engine_spec_draft_tokens_total",
                          "Draft tokens proposed."),
    "spec_accepted_tokens": ("tdt_engine_spec_accepted_tokens_total",
                             "Draft tokens accepted by verify."),
    "spec_rollback_tokens": ("tdt_engine_spec_rollback_tokens_total",
                             "Draft tokens rolled back after verify."),
    # Tree speculation (docs/serving.md "Speculative decoding"): multi-
    # branch draft trees verified in one forward. ``nodes`` counts
    # drafted trie nodes (root excluded — they are the spec_draft_tokens
    # of tree rounds), ``depth`` accumulates each tree's deepest drafted
    # path (divide by rounds for the mean), and ``branch_accepts``
    # counts rounds whose accepted path left the primary branch — the
    # rounds a linear draft would have lost outright.
    "spec_tree_rounds": ("tdt_spec_tree_rounds_total",
                         "Tree-speculation verify rounds (multi-branch "
                         "draft chunks)."),
    "spec_tree_nodes": ("tdt_spec_tree_nodes_total",
                        "Draft tree nodes verified (root excluded)."),
    "spec_tree_depth": ("tdt_spec_tree_depth_total",
                        "Cumulative deepest-drafted-path depth across "
                        "tree rounds."),
    "spec_tree_branch_accepts": ("tdt_spec_tree_branch_accepts_total",
                                 "Tree rounds whose accepted path left "
                                 "the primary branch (commit needed a "
                                 "KV row-move)."),
    "failed_requests": ("tdt_engine_failed_requests_total",
                        "Requests finished with a non-ok status "
                        "(client cancellations excluded — those count "
                        "in cancelled_requests)."),
    "cancelled_requests": ("tdt_engine_cancelled_requests_total",
                           "Requests torn down by a client "
                           "cancellation (the cancel verb or a "
                           "mid-stream disconnect)."),
    "shed_requests": ("tdt_engine_shed_requests_total",
                      "Requests shed by the bounded admission queue."),
    "deadline_expired": ("tdt_engine_deadline_expired_total",
                         "Requests failed on a wall-clock deadline."),
    "nonfinite_logits": ("tdt_engine_nonfinite_logits_total",
                         "Steps guarded for non-finite logits."),
    "decode_faults": ("tdt_engine_decode_faults_total",
                      "Exceptions isolated by the decode-phase step "
                      "guard."),
    # Megakernel serving fast path (docs/megakernel.md "Serving fast
    # path"): NS-step fused launches vs the rounds that had to fall
    # back to single-step decode (max_length tail, top-k/top-p slots).
    "mega_launches": ("tdt_mega_launches_total",
                      "Megakernel NS-step decode launches."),
    "mega_fallback_steps": ("tdt_mega_single_step_fallbacks_total",
                            "Mega-mode rounds served by the single-step "
                            "fallback (tail or filtered sampling)."),
    # Device task tracer (docs/observability.md "Device task tracer").
    "mega_trace_launches": ("tdt_mega_trace_launches_total",
                            "Megakernel launches whose device trace "
                            "ring was decoded."),
    # Resident decode (docs/megakernel.md "Resident decode"): the host
    # work ring, in-kernel filtered sampling, batch-bucket launch
    # programs, and device-side stop-token retire.
    "mega_ring_items": ("tdt_mega_ring_items_total",
                        "Admit/retire/cancel work items pushed into "
                        "the host work ring."),
    "mega_ring_doorbells": ("tdt_mega_ring_doorbells_total",
                            "Work-ring doorbell publishes (one per "
                            "resident round)."),
    "mega_ring_host_drains": ("tdt_mega_ring_host_drains_total",
                              "Work-ring items drained host-side "
                              "(single-step fallback rounds, batch "
                              "teardown) — no device loop observed "
                              "them."),
    "mega_device_retires": ("tdt_mega_device_retires_total",
                            "Slots retired by the in-kernel stop-token "
                            "test (no host round trip)."),
    "mega_resident_rounds": ("tdt_mega_resident_rounds_total",
                             "Resident-session rounds issued before "
                             "the previous round's drain (pipelined "
                             "dispatch)."),
    "mega_bucket_launches": ("tdt_mega_bucket_launches_total",
                             "Mega launches served by a batch-bucket "
                             "program narrower than max_batch."),
    "mega_filtered_rounds": ("tdt_mega_filtered_rounds_total",
                             "Mega rounds sampled in-kernel through "
                             "the top-k/top-p bisection filter "
                             "(previously single-step fallbacks)."),
    # MoE serving (docs/serving.md "MoE serving"): token positions
    # routed through the expert FFN × top_k, and EP all-to-all drops —
    # the serving paths are LOSSLESS (splits-exchange protocol /
    # full-expert streaming), so a nonzero drop count is always a
    # detected error surfaced from ``DispatchState.num_dropped``
    # (ops/moe/ep_a2a.py), never silent truncation.
    "moe_routed_tokens": ("tdt_moe_routed_tokens_total",
                          "Expert assignments routed (token positions "
                          "through the MoE FFN × top_k)."),
    "a2a_dropped": ("tdt_moe_a2a_dropped_total",
                    "EP all-to-all assignments dropped (capacity-mode "
                    "overflow; 0 on the lossless serving paths)."),
    # Durable KV tier (docs/serving.md "Tiered KV"): radix evictions
    # spilled to host-RAM/disk instead of dropped, and admissions whose
    # prefix coverage was extended by faulting those pages back —
    # cheaper than re-prefilling them.
    "tier_spilled_pages": ("tdt_tier_spilled_pages_total",
                           "Evicted radix pages exported to the KV "
                           "tier instead of dropped."),
    "tier_hits": ("tdt_tier_hits_total",
                  "Admissions whose prefix coverage was extended by "
                  "the KV tier (≥1 page faulted back)."),
    "tier_faults": ("tdt_tier_faulted_pages_total",
                    "Pages faulted back from the KV tier into HBM "
                    "(written via write_page, mapped as tree pages)."),
    "tier_bytes": ("tdt_tier_bytes_faulted_total",
                   "Payload bytes faulted back from the KV tier."),
    "tier_remote_pages": ("tdt_tier_remote_pages_total",
                          "Tier pages faulted back from a PEER replica "
                          "over the KV fabric (subset of "
                          "tdt_tier_faulted_pages_total)."),
    # Long-context serving (docs/serving.md "Long-context serving"):
    # context-parallel prefill (cp>1 — one request's prompt chunks
    # round-robined over cp virtual ranks, per-block KV exchange fired
    # split-phase under the next block's attention) and sharded-slot
    # decode (a slot whose KV exceeds the per-rank page budget keeps a
    # resident paged window plus tier-backed cold pages, merged by
    # log-sum-exp partial combine each step).
    "cp_prefills": ("tdt_cp_prefills_total",
                    "Context-parallel (cp>1) prefills run."),
    "cp_blocks": ("tdt_cp_blocks_total",
                  "Prefill chunks executed under a cp>1 plan."),
    "cp_exchange_bytes": ("tdt_cp_exchange_bytes_total",
                          "KV bytes staged through the split-phase "
                          "cp block exchange."),
    "cp_exchange_us": ("tdt_cp_exchange_us_total",
                       "Wall microseconds spent in cp KV-exchange "
                       "send windows (tracer-stamped)."),
    "cp_hidden_us": ("tdt_cp_hidden_us_total",
                     "Microseconds of cp KV-exchange overlapped "
                     "under attention compute (subset of "
                     "tdt_cp_exchange_us_total)."),
    "longctx_sharded_slots": ("tdt_longctx_sharded_slots_total",
                              "Slots admitted in sharded (over-budget) "
                              "long-context mode."),
    "longctx_demoted_pages": ("tdt_longctx_demoted_pages_total",
                              "Cold KV pages of live long slots "
                              "demoted to the KV tier."),
    "longctx_tier_faults": ("tdt_longctx_tier_faults_total",
                            "Cold pages faulted back from the KV tier "
                            "to rebuild a long slot's attention "
                            "window."),
    "longctx_tier_bytes": ("tdt_longctx_tier_bytes_total",
                           "Payload bytes faulted back for long-slot "
                           "cold windows."),
    "longctx_decode_steps": ("tdt_longctx_decode_steps_total",
                             "Per-slot sharded decode programs run "
                             "(cold + resident partial merge)."),
}

# Extra registry names mirroring the SAME counter as a STAT_METRICS
# entry — fleet spec-health dashboards key on the short ``tdt_spec_*``
# family while the per-engine ``tdt_engine_spec_*`` names stay the
# drill-down. ``_bump`` increments every handle of a key, so the alias
# can never drift from its primary.
STAT_METRIC_ALIASES = {
    "spec_draft_tokens": (
        ("tdt_spec_draft_tokens_total",
         "Draft tokens proposed (alias of "
         "tdt_engine_spec_draft_tokens_total for fleet spec-health "
         "dashboards)."),
    ),
    "spec_rollback_tokens": (
        ("tdt_spec_rollback_tokens_total",
         "Draft tokens rolled back after verify (alias of "
         "tdt_engine_spec_rollback_tokens_total for fleet spec-health "
         "dashboards)."),
    ),
}
