"""Context-parallel prefill plumbing (docs/serving.md "Long-context
serving").

A cp>1 prefill shards ONE request's prompt over ``cp`` virtual ranks:
chunk (block) ``i`` belongs to rank ``i % cp``, and the KV a block just
wrote must reach the next block's rank before that rank can extend the
context — the ring-attention dataflow
(``ops/attention/ring_attention.py``), driven at serving granularity.
On this host-emulated mesh every rank computes on the same devices, so
the blocks still execute in program order through the SAME
``prefill_paged_chunk`` call sequence a cp=1 prefill runs — cp>1 logits
are bit-exact with cp=1 **by construction** — and what cp adds is the
EXCHANGE schedule: after block i's program is dispatched and its pages
are gathered, the staging of those bytes toward rank ``(i+1) % cp``
runs on a background thread while the main thread blocks on block
i+1's attention compute. That is the split-phase discipline the AR/A2A
kernels use (fire the send for tile i+1 under tile i's GEMM,
``AR_SEND``/``AR_WAIT``); here the windows are host-stamped
(``time.perf_counter_ns``) around genuinely concurrent work — the
staging thread runs NumPy materialize/copy/checksum (GIL-released C
loops) while the main thread sits in ``block_until_ready`` — so the
tracer's ``hidden_fraction`` is a measurement, not an assertion.

The tracer mirrors the device-side AR_SEND/AR_WAIT taxonomy:

- ``CP_ATTN``  — block i's chunk program, dispatch → blocked-ready;
- ``CP_SEND``  — block i's KV bytes staged toward rank (i+1) % cp
  (device gather → host materialize → staging copy → crc32);
- ``CP_WAIT``  — the receiving block joining the stage thread (the
  exposed, un-hidden remainder of the exchange).

``validate_cp_ring`` checks the schedule the way the collective tests
check a ring: every non-final block exchanged exactly once to its
successor rank, sends paired with waits, per-rank attention windows
monotone — a gap or a duplicate is a bug report, not a perf footnote.
"""

from __future__ import annotations

import dataclasses
import threading
import time
import zlib

import numpy as np

CP_ATTN = "CP_ATTN"
CP_SEND = "CP_SEND"
CP_WAIT = "CP_WAIT"


def cp_block_rank(block: int, cp: int) -> int:
    """The virtual rank owning prefill block ``block`` (round-robin —
    contiguous ranges would idle rank 0 for the whole tail of a long
    prompt; round-robin keeps every rank's compute interleaved, the
    layout ring attention assumes)."""
    return int(block) % max(int(cp), 1)


@dataclasses.dataclass(frozen=True)
class CPWindow:
    """One stamped interval of the cp prefill schedule.

    ``block`` is the prefill chunk index; ``src``/``dst`` the virtual
    ranks (for ``CP_ATTN`` both are the computing rank); ``t0``/``t1``
    are ``time.perf_counter_ns`` stamps; ``nbytes`` the staged payload
    (sends only)."""

    kind: str
    block: int
    src: int
    dst: int
    t0: int
    t1: int
    nbytes: int = 0

    @property
    def dur_ns(self) -> int:
        return max(int(self.t1) - int(self.t0), 0)


class CPTracer:
    """Append-only window log for one (or more) cp prefills.

    Thread-safe: the staging thread records ``CP_SEND`` windows while
    the main thread records ``CP_ATTN``/``CP_WAIT``."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.windows: list[CPWindow] = []

    def record(self, kind: str, block: int, src: int, dst: int,
               t0: int, t1: int, nbytes: int = 0) -> CPWindow:
        w = CPWindow(kind=kind, block=int(block), src=int(src),
                     dst=int(dst), t0=int(t0), t1=int(t1),
                     nbytes=int(nbytes))
        with self._lock:
            self.windows.append(w)
        return w

    def by_kind(self, kind: str) -> list[CPWindow]:
        with self._lock:
            return [w for w in self.windows if w.kind == kind]


class SplitPhaseExchange:
    """Stage each block's KV toward its successor rank under the next
    block's attention.

    ``dispatch(block, arrays, ...)`` takes device arrays whose gather
    is ALREADY enqueued (the caller must dispatch the ``jnp.take``
    before the next chunk program donates the cache — enqueue order is
    what keeps the read ahead of the donation) and hands them to a
    worker thread that materializes them host-side, copies them into a
    staging buffer, and checksums the bytes — the host half of a real
    inter-rank send, all GIL-released, so it genuinely overlaps the
    main thread's ``block_until_ready``. ``join(...)`` is the receive
    barrier: it stamps the exposed ``CP_WAIT`` window."""

    def __init__(self, tracer: CPTracer, cp: int) -> None:
        self.tracer = tracer
        self.cp = max(int(cp), 1)
        self._pending: list[dict] = []
        self.total_bytes = 0
        self.checksums: dict[int, int] = {}

    def dispatch(self, block: int, arrays) -> None:
        src = cp_block_rank(block, self.cp)
        dst = cp_block_rank(block + 1, self.cp)
        entry = {"block": int(block), "src": src, "dst": dst}
        th = threading.Thread(
            target=self._stage, args=(entry, list(arrays)), daemon=True
        )
        entry["thread"] = th
        self._pending.append(entry)
        th.start()

    def _stage(self, entry: dict, arrays) -> None:
        t0 = time.perf_counter_ns()
        crc = 0
        nbytes = 0
        staged = []
        for a in arrays:
            host = np.asarray(a)        # device → host materialize
            buf = host.copy()           # staging copy (the TX buffer)
            crc = zlib.crc32(buf.tobytes(), crc)
            nbytes += buf.nbytes
            staged.append(buf)
        t1 = time.perf_counter_ns()
        entry["staged"] = staged
        entry["crc"] = crc
        entry["nbytes"] = nbytes
        self.tracer.record(CP_SEND, entry["block"], entry["src"],
                           entry["dst"], t0, t1, nbytes)

    def join_oldest(self):
        """Barrier on the oldest in-flight exchange; stamps its
        ``CP_WAIT`` window and returns the entry (or None)."""
        if not self._pending:
            return None
        entry = self._pending.pop(0)
        t0 = time.perf_counter_ns()
        entry["thread"].join()
        t1 = time.perf_counter_ns()
        self.tracer.record(CP_WAIT, entry["block"], entry["src"],
                           entry["dst"], t0, t1, entry["nbytes"])
        self.total_bytes += entry["nbytes"]
        self.checksums[entry["block"]] = entry["crc"]
        return entry

    def join_all(self) -> None:
        while self._pending:
            self.join_oldest()


def _merge_intervals(ivals):
    ivals = sorted((int(a), int(b)) for a, b in ivals if b > a)
    out = []
    for a, b in ivals:
        if out and a <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], b))
        else:
            out.append((a, b))
    return out


def _overlap_ns(window: CPWindow, merged) -> int:
    hid = 0
    for a, b in merged:
        hid += max(0, min(window.t1, b) - max(window.t0, a))
    return hid


def cp_overlap_report(tracer: CPTracer) -> dict:
    """Fold a tracer's windows into the overlap accounting the bench
    and the ``tdt_cp_*`` counters publish: how much of the exchange
    flew UNDER attention compute.

    ``hidden_fraction`` = (send time inside any ``CP_ATTN`` window) /
    (total send time) — the same hidden/exposed split the A2A overlap
    report uses. ``wait_ns`` is the exposed receive tail actually paid
    by the critical path."""
    attn = tracer.by_kind(CP_ATTN)
    sends = tracer.by_kind(CP_SEND)
    waits = tracer.by_kind(CP_WAIT)
    merged = _merge_intervals((w.t0, w.t1) for w in attn)
    send_ns = sum(w.dur_ns for w in sends)
    hidden_ns = sum(_overlap_ns(w, merged) for w in sends)
    return {
        "blocks": len(attn),
        "exchanges": len(sends),
        "attn_ns": sum(w.dur_ns for w in attn),
        "send_ns": send_ns,
        "hidden_ns": hidden_ns,
        "wait_ns": sum(w.dur_ns for w in waits),
        "exchange_bytes": sum(w.nbytes for w in sends),
        "hidden_fraction": (hidden_ns / send_ns) if send_ns else 0.0,
    }


def validate_cp_ring(tracer: CPTracer, n_blocks: int, cp: int) -> list[str]:
    """Audit one cp prefill's schedule; empty list == gap-free ring.

    Checks (the collective-test discipline, applied to the serving
    schedule): every block ran exactly one ``CP_ATTN`` window; every
    non-final block was exchanged exactly once, from its own rank to
    its successor's; every send has a receive (``CP_WAIT``) that ends
    no earlier than the send; per-rank attention windows are monotone
    and non-overlapping (a rank never computes two blocks at once)."""
    problems: list[str] = []
    n_blocks = int(n_blocks)
    cp = max(int(cp), 1)
    attn = sorted(tracer.by_kind(CP_ATTN), key=lambda w: w.block)
    sends = tracer.by_kind(CP_SEND)
    waits = tracer.by_kind(CP_WAIT)
    seen = [w.block for w in attn]
    if seen != list(range(n_blocks)):
        problems.append(f"attn blocks {seen} != 0..{n_blocks - 1}")
    by_block: dict[int, list[CPWindow]] = {}
    for w in sends:
        by_block.setdefault(w.block, []).append(w)
    for blk in range(n_blocks - 1):
        got = by_block.pop(blk, [])
        if len(got) != 1:
            problems.append(
                f"block {blk} exchanged {len(got)} times (want 1)")
            continue
        s = got[0]
        want_src = cp_block_rank(blk, cp)
        want_dst = cp_block_rank(blk + 1, cp)
        if (s.src, s.dst) != (want_src, want_dst):
            problems.append(
                f"block {blk} sent {s.src}->{s.dst}, "
                f"want {want_src}->{want_dst}")
        wmatch = [w for w in waits if w.block == blk]
        if len(wmatch) != 1:
            problems.append(
                f"block {blk} has {len(wmatch)} waits (want 1)")
        elif wmatch[0].t1 < s.t1:
            problems.append(
                f"block {blk} wait ended before its send completed")
    for blk in sorted(by_block):
        problems.append(f"unexpected exchange for block {blk}")
    for rank in range(cp):
        mine = [w for w in attn if cp_block_rank(w.block, cp) == rank]
        for prev, cur in zip(mine, mine[1:]):
            if cur.t0 < prev.t1:
                problems.append(
                    f"rank {rank} attn windows overlap "
                    f"(block {prev.block} vs {cur.block})")
    return problems
