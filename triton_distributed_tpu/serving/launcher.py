"""Pluggable replica launchers: the host boundary behind ``spawn``.

Everything above the transport is already host-agnostic — a
:class:`~triton_distributed_tpu.serving.remote.RemoteReplica` speaks
line-JSON to any address, snapshots ride the wire as base64, and the
supervisor classifies failures without assuming co-residence. The one
place "which machine" still leaks in is the *spawn*: the port-file
handshake is a filesystem rendezvous, and files do not cross hosts.
This module makes that seam explicit (docs/scale-out.md "Multi-host
fleet"):

- :class:`LocalLauncher` — today's subprocess + port-file path,
  byte-identical to the original ``spawn_replica`` (which now
  delegates here). The default; single-host fleets never see a
  behavior change.
- :class:`SSHLauncher` — command-template spawn of ``run_server`` on a
  remote host. The port-file handshake becomes a bounded
  ``healthz``-poll *wire* handshake: the launcher assigns the port
  up front (a child binding port 0 on another machine has no way to
  tell us what it got), starts the remote command, and polls
  ``{"cmd": "healthz"}`` against ``host:port`` until the child answers
  or the spawn deadline passes. The template is just argv prefix
  tokens (``{host}`` substituted), so tests exercise the wire
  handshake with an empty template — no real ssh in tier-1.
- :class:`FakeHostLauncher` — local process groups tagged as named
  "hosts". Children already spawn with ``start_new_session=True``
  (their own process group), so killing or SIGSTOPping *every replica
  on a host in one call* is exactly ``killpg`` over the host's tag —
  which is how the chaos suite and ``perf/host_loss_bench.py`` lose a
  whole machine without owning two.

Fault seam: every launcher offers ``launcher.spawn`` (ctx:
``replica``, ``host``) before doing any work — an armed plan rule
(``FaultPlan.refuse_spawn``) turns into a :class:`SpawnError`, which
drives the supervisor's spawn-FAILOVER path deterministically.
"""

from __future__ import annotations

import os
import signal
import subprocess
import tempfile
import threading
import time

from triton_distributed_tpu.runtime.faults import fault_point
from triton_distributed_tpu.serving.remote import RemoteEngine, RemoteReplica


class SpawnError(RuntimeError):
    """A replica child never reached its handshake."""


def _gen_name(spec, generation: int) -> str:
    return spec.name if generation == 0 else f"{spec.name}#{generation}"


def _spawn_gate(name: str, host: str | None) -> None:
    """The ``launcher.spawn`` fault seam: an armed refusal (or any
    injected exception) surfaces as a :class:`SpawnError`, so chaos
    plans drive the supervisor's failover path through the same
    exception type a real failed bind raises."""
    try:
        fault_point("launcher.spawn", replica=name, host=host or "")
    except Exception as e:
        raise SpawnError(
            f"replica {name} spawn refused on host "
            f"{host or 'local'}: {e}"
        ) from e


def _log_tail(log_path: str, n: int = 800) -> str:
    try:
        with open(log_path, "rb") as f:
            return f.read()[-n:].decode(errors="replace")
    except OSError:
        return ""


class Launcher:
    """The spawn seam: one method, returning a connected
    :class:`RemoteReplica` (``.proc`` holds the handle the supervisor
    reaps) or raising :class:`SpawnError`. ``hosts()``/``host_up()``
    feed the supervisor's spread-aware placement and spawn failover;
    a launcher with no host notion (local) reports no hosts and the
    supervisor's host machinery stays entirely dormant."""

    def spawn(self, spec, *, generation: int = 0,
              spawn_timeout_s: float = 120.0, max_pending: int = 8,
              log_dir: str | None = None,
              connect_timeout_s: float = 10.0) -> RemoteReplica:
        raise NotImplementedError

    def hosts(self) -> list[str]:
        """Named hosts this launcher can place on ([] = no host
        notion; placement stays flat)."""
        return []

    def host_up(self, host: str) -> bool:
        """Launcher-side liveness of a host (the supervisor keeps its
        own down-ledger on top; both must agree up for placement)."""
        return True

    def reap(self) -> None:
        """Kill anything the launcher still tracks — shutdown hook for
        zombies the supervisor deliberately did NOT kill (a fenced
        host's children are unreachable in production; locally they
        would leak without this)."""


def local_spawn(spec, *, generation: int = 0,
                spawn_timeout_s: float = 120.0, max_pending: int = 8,
                log_dir: str | None = None,
                connect_timeout_s: float = 10.0,
                host_tag: str | None = None) -> RemoteReplica:
    """Launch one replica child on THIS machine and wait for its
    port-file handshake — the original ``spawn_replica`` path, moved
    behind the launcher seam verbatim. Returns a connected
    :class:`RemoteReplica`; raises :class:`SpawnError` — with the
    child's log tail attached — when the child dies or stalls before
    binding."""
    name = _gen_name(spec, generation)
    _spawn_gate(name, host_tag)
    if log_dir is None:
        log_dir = tempfile.mkdtemp(prefix="tdt-fleet-")
    os.makedirs(log_dir, exist_ok=True)
    port_file = os.path.join(log_dir, f"{name.replace('#', '_')}.port")
    log_path = os.path.join(log_dir, f"{name.replace('#', '_')}.log")
    if os.path.exists(port_file):
        os.unlink(port_file)
    env = dict(os.environ)
    if spec.env:
        env.update(spec.env)
    with open(log_path, "ab") as log_f:
        proc = subprocess.Popen(
            spec.argv + ["--port-file", port_file],
            stdout=log_f, stderr=subprocess.STDOUT, env=env,
            start_new_session=True,
        )
    deadline = time.monotonic() + spawn_timeout_s
    addr = None
    while time.monotonic() < deadline:
        if os.path.exists(port_file):
            with open(port_file) as f:
                text = f.read().strip()
            if text:  # the rename made this atomic; non-empty == done
                addr = text
                break
        if proc.poll() is not None:
            break
        time.sleep(0.02)
    if addr is None:
        tail = _log_tail(log_path)
        if proc.poll() is None:
            proc.kill()
        proc.wait(timeout=10)
        raise SpawnError(
            f"replica {name} never bound within {spawn_timeout_s}s "
            f"(rc={proc.returncode}); log tail:\n{tail}"
        )
    host, _, port = addr.rpartition(":")
    rep = RemoteReplica(host, int(port), name=name, proc=proc,
                        max_pending=max_pending,
                        role=getattr(spec, "role", "mixed"),
                        connect_timeout_s=connect_timeout_s,
                        host_tag=host_tag)
    return rep


class LocalLauncher(Launcher):
    """Today's behavior, verbatim: subprocess + port-file rendezvous
    on the local machine. ``spec.host`` is ignored (there is only one
    host) and ``hosts()`` is empty, so every host-domain feature in
    the supervisor stays dormant."""

    def spawn(self, spec, *, generation: int = 0,
              spawn_timeout_s: float = 120.0, max_pending: int = 8,
              log_dir: str | None = None,
              connect_timeout_s: float = 10.0) -> RemoteReplica:
        return local_spawn(
            spec, generation=generation,
            spawn_timeout_s=spawn_timeout_s, max_pending=max_pending,
            log_dir=log_dir, connect_timeout_s=connect_timeout_s,
        )


class SSHLauncher(Launcher):
    """Spawn ``run_server`` children on remote hosts via a command
    template, with a wire handshake instead of a port file.

    ``cmd_template`` is an argv *prefix* — each token is
    ``str.format``-ed with ``host=...`` and prepended to the child
    command (default: ``("ssh", "-o", "BatchMode=yes", "{host}")``).
    An empty template runs the child locally, which is how the tests
    exercise the healthz-poll handshake without ssh.

    Because the child cannot hand its bound port back across machines,
    the launcher owns port assignment: each spawn takes the next port
    from ``port_base`` and rewrites the child's ``--port``. The child
    is told to bind ``0.0.0.0`` and advertise its host name, so the
    addresses that flow into heartbeats and fabric peer lists are
    routable from everywhere (docs/scale-out.md "Multi-host fleet").
    ``spec.env`` rides as ``env K=V`` prefix tokens (ssh does not
    forward the local environment)."""

    def __init__(self, hosts, *,
                 cmd_template=("ssh", "-o", "BatchMode=yes", "{host}"),
                 port_base: int = 47311,
                 handshake_poll_s: float = 0.1):
        if not hosts:
            raise ValueError("SSHLauncher needs at least one host")
        self._hosts = [str(h) for h in hosts]
        self.cmd_template = tuple(cmd_template)
        self.handshake_poll_s = float(handshake_poll_s)
        self._next_port = int(port_base)
        self._spawned: dict[str, int] = {h: 0 for h in self._hosts}
        self._lock = threading.Lock()

    def hosts(self) -> list[str]:
        return list(self._hosts)

    def _alloc(self, spec) -> tuple[str, int]:
        with self._lock:
            host = getattr(spec, "host", None)
            if host is None:
                # Least-loaded fallback; the supervisor normally
                # assigns spec.host before spawning.
                host = min(self._hosts, key=lambda h: self._spawned[h])
            host = str(host)
            self._spawned.setdefault(host, 0)
            self._spawned[host] += 1
            port = self._next_port
            self._next_port += 1
            return host, port

    @staticmethod
    def _child_argv(spec, port: int, host: str) -> list[str]:
        argv = list(spec.argv)
        try:
            i = argv.index("--port")
            argv[i + 1] = str(port)
        except (ValueError, IndexError):
            argv += ["--port", str(port)]
        if "--host" not in argv:
            argv += ["--host", "0.0.0.0"]
        if "--advertise-host" not in argv:
            argv += ["--advertise-host", host]
        if spec.env:
            argv = ["env", *(f"{k}={v}" for k, v in spec.env.items()),
                    *argv]
        return argv

    def spawn(self, spec, *, generation: int = 0,
              spawn_timeout_s: float = 120.0, max_pending: int = 8,
              log_dir: str | None = None,
              connect_timeout_s: float = 10.0) -> RemoteReplica:
        name = _gen_name(spec, generation)
        host, port = self._alloc(spec)
        _spawn_gate(name, host)
        if log_dir is None:
            log_dir = tempfile.mkdtemp(prefix="tdt-fleet-")
        os.makedirs(log_dir, exist_ok=True)
        log_path = os.path.join(log_dir,
                                f"{name.replace('#', '_')}.log")
        argv = [
            *(part.format(host=host) for part in self.cmd_template),
            *self._child_argv(spec, port, host),
        ]
        with open(log_path, "ab") as log_f:
            proc = subprocess.Popen(
                argv, stdout=log_f, stderr=subprocess.STDOUT,
                start_new_session=True,
            )
        # Wire handshake: poll healthz until the child answers. Each
        # probe's connect is bounded — an unroutable host must fail
        # the spawn at the deadline, not hang on the OS default.
        probe = RemoteEngine(
            host, port, name=name,
            connect_timeout_s=min(connect_timeout_s, 1.0),
        )
        deadline = time.monotonic() + spawn_timeout_s
        up = False
        while time.monotonic() < deadline:
            try:
                if probe.healthz(timeout=1.0).get("ok"):
                    up = True
                    break
            except (OSError, ConnectionError, ValueError):
                pass
            if proc.poll() is not None:
                break
            time.sleep(self.handshake_poll_s)
        if not up:
            tail = _log_tail(log_path)
            if proc.poll() is None:
                proc.kill()
            proc.wait(timeout=10)
            raise SpawnError(
                f"replica {name} on {host}:{port} never answered "
                f"healthz within {spawn_timeout_s}s "
                f"(rc={proc.returncode}); log tail:\n{tail}"
            )
        return RemoteReplica(host, port, name=name, proc=proc,
                             max_pending=max_pending,
                             role=getattr(spec, "role", "mixed"),
                             connect_timeout_s=connect_timeout_s,
                             host_tag=host)


class FakeHostLauncher(Launcher):
    """Named fake hosts over local process groups — multi-host chaos
    on one machine. Each child already runs in its own process group
    (``start_new_session=True``), so the launcher tags groups with a
    host name and takes a WHOLE host down in one call:
    :meth:`kill_host` (SIGKILL — the machine died),
    :meth:`hang_host` (SIGSTOP — the machine wedged; a later
    :meth:`thaw_host` SIGCONTs it back into a zombie the epoch fence
    must refuse). A host marked down refuses spawns with
    :class:`SpawnError`, which is what exercises the supervisor's
    spawn failover."""

    def __init__(self, hosts=("h0", "h1"), *, log_dir: str | None = None):
        if not hosts:
            raise ValueError("FakeHostLauncher needs at least one host")
        self._state = {
            str(h): {"procs": [], "down": False} for h in hosts
        }
        self.log_dir = log_dir
        self._lock = threading.Lock()

    def hosts(self) -> list[str]:
        return list(self._state)

    def host_up(self, host: str) -> bool:
        st = self._state.get(str(host))
        return st is not None and not st["down"]

    def set_down(self, host: str, down: bool = True) -> None:
        self._state[str(host)]["down"] = bool(down)

    def spawn(self, spec, *, generation: int = 0,
              spawn_timeout_s: float = 120.0, max_pending: int = 8,
              log_dir: str | None = None,
              connect_timeout_s: float = 10.0) -> RemoteReplica:
        name = _gen_name(spec, generation)
        with self._lock:
            host = getattr(spec, "host", None)
            if host is None:
                host = min(
                    (h for h, st in self._state.items()
                     if not st["down"]),
                    key=lambda h: len(self._state[h]["procs"]),
                    default=None,
                )
                if host is None:
                    raise SpawnError(
                        f"replica {name}: every fake host is down"
                    )
                spec.host = host
            host = str(host)
            st = self._state.get(host)
        if st is None:
            raise SpawnError(
                f"replica {name}: unknown fake host {host!r} "
                f"(have {sorted(self._state)})"
            )
        _spawn_gate(name, host)
        if st["down"]:
            raise SpawnError(
                f"replica {name}: fake host {host} is down"
            )
        rep = local_spawn(
            spec, generation=generation,
            spawn_timeout_s=spawn_timeout_s, max_pending=max_pending,
            log_dir=log_dir or self.log_dir,
            connect_timeout_s=connect_timeout_s, host_tag=host,
        )
        with self._lock:
            st["procs"].append(rep.proc)
        return rep

    # -- whole-host chaos ---------------------------------------------------

    def _signal_host(self, host: str, sig: int) -> int:
        """Signal every live process GROUP on ``host``; returns how
        many groups were hit. Children are session leaders, so the
        group id is the child pid."""
        with self._lock:
            procs = list(self._state[str(host)]["procs"])
        hit = 0
        for proc in procs:
            if proc.poll() is not None:
                continue
            try:
                os.killpg(proc.pid, sig)
                hit += 1
            except (ProcessLookupError, PermissionError):
                pass
        return hit

    def kill_host(self, host: str) -> int:
        """The machine died: SIGKILL every process group on ``host``
        in one call and mark it down."""
        self.set_down(host, True)
        return self._signal_host(host, signal.SIGKILL)

    def hang_host(self, host: str) -> int:
        """The machine wedged (NIC down, scheduler stall): SIGSTOP
        every process group on ``host`` and mark it down. Processes
        survive — :meth:`thaw_host` turns them into zombies."""
        self.set_down(host, True)
        if not hasattr(signal, "SIGSTOP"):  # pragma: no cover
            raise RuntimeError("SIGSTOP unavailable on this platform")
        return self._signal_host(host, signal.SIGSTOP)

    def thaw_host(self, host: str) -> int:
        """SIGCONT a hung host's process groups: the zombie case. The
        host stays marked down — a thawed machine does not rejoin by
        itself; the supervisor's epoch fence is what keeps its stale
        results out (tests/test_multihost.py)."""
        return self._signal_host(host, signal.SIGCONT)

    def reap(self) -> None:
        """SIGKILL every tracked process group (stopped ones
        included — SIGKILL does not queue behind SIGSTOP) and wait."""
        with self._lock:
            procs = [p for st in self._state.values()
                     for p in st["procs"]]
        for proc in procs:
            if proc.poll() is None:
                try:
                    os.killpg(proc.pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass
        for proc in procs:
            try:
                proc.wait(timeout=10)
            except Exception:  # noqa: BLE001 — best-effort reap
                pass
