"""Role-typed replica pools: placement scoring and SLO-aware
scheduling (docs/scale-out.md "Disaggregated pools & autoscaling").

The serving tier so far ran one undifferentiated pool: a prefill burst
steals decode slots and a long decode tail starves admissions. This
module is the pure half of the elastic control plane:

- **Roles** — a replica carries ``role`` ∈ {``prefill``, ``decode``,
  ``mixed``}. Roles are ROUTER-SIDE metadata: the engines behind the
  replicas stay identical (any replica CAN do either phase — that is
  what makes degraded fallback lossless), the role only steers
  placement and scaling.
- **Placement scoring** — :func:`decode_score` weighs a decode
  target's radix-digest match against its pressure (slot occupancy +
  free pages) instead of digest-match-only; the ``Router``'s
  ``policy="pools"`` uses it to place migrated (post-prefill) work.
- **Scheduler** — priority admission classes (PR 13's ``slo_class``),
  per-step prefill/decode token budgets, and deadline-aware shedding
  that prefers to shed requests already past their SLO.
- **Pool gauges** — ``tdt_pool_*`` per-role fleet pressure, the
  signals the :class:`~triton_distributed_tpu.serving.autoscaler.
  Autoscaler` reads (docs/observability.md).

Everything here is deterministic, process-local, and duck-typed
against the replica surface (``role``/``state``/``pending``/
``max_pending``/``free_pages``), so unit tests drive it with plain
fakes and the router drives it with live replicas.
"""

from __future__ import annotations

import time

from triton_distributed_tpu.obs import events as obs_events
from triton_distributed_tpu.obs import metrics as obs_metrics

PREFILL = "prefill"
DECODE = "decode"
MIXED = "mixed"
ROLES = (PREFILL, DECODE, MIXED)

# decode_score weights: a full-prompt digest match is worth crossing a
# fully-occupied replica's pressure penalty (2 > 1), but not twice —
# pressure can still outvote a short match, which is the whole point
# of weighing match against occupancy instead of match-only.
MATCH_WEIGHT = 2.0
PRESSURE_WEIGHT = 1.0
FREE_WEIGHT = 0.25
# Tier coverage BEYOND the radix match (docs/scale-out.md "KV
# fabric"): faulting a page back from the replica's tier is cheaper
# than re-prefilling it but dearer than a radix hit (write_page +
# graft vs an already-mapped node), so the increment scores at half
# the radix weight — a pure-tier full match (2·0 + 1·1 = 1) exactly
# offsets full occupancy, while a radix match (2) still clears it.
TIER_MATCH_WEIGHT = 1.0


def replica_role(rep) -> str:
    """A replica's role; anything that never declared one is
    ``mixed`` (every pre-pools replica keeps its old behavior)."""
    role = getattr(rep, "role", MIXED) or MIXED
    return role if role in ROLES else MIXED


def prefill_capable(rep) -> bool:
    return replica_role(rep) in (PREFILL, MIXED)


def decode_capable(rep) -> bool:
    return replica_role(rep) in (DECODE, MIXED)


def validate_role(role: str) -> str:
    if role not in ROLES:
        raise ValueError(
            f"replica role must be one of {ROLES}, got {role!r}"
        )
    return role


def occupancy(rep) -> float:
    """Slot occupancy in [0, 1]: queued+in-flight over the routing
    bound. The same pending/max_pending the shed-aware skip uses, as a
    fraction — the decode-pressure half of placement and the
    autoscaler's primary signal."""
    cap = max(int(getattr(rep, "max_pending", 1)), 1)
    return min(rep.pending / cap, 1.0)


def decode_score(rep, matched: int, prompt_len: int, *,
                 max_free: int = 0, tier_matched: int = 0) -> float:
    """Placement score for a decode hop: higher is better.

    ``matched`` is the replica's radix-digest match in tokens for this
    request's prompt; ``max_free`` normalizes the free-page term
    across the candidate pool (pass the pool's max ``free_pages``; 0
    disables the term — remote replicas report 0 free pages until
    their first batch). ``tier_matched`` is the replica's TIER-digest
    match in tokens: only its coverage BEYOND the radix match counts
    (pages the radix already holds would never fault back), at
    ``TIER_MATCH_WEIGHT``. A saturated replica with a perfect match
    can still lose to an idle one with none: match wins ties, pressure
    breaks monopolies."""
    match_frac = matched / max(prompt_len, 1)
    score = MATCH_WEIGHT * match_frac - PRESSURE_WEIGHT * occupancy(rep)
    tier_extra = max(int(tier_matched) - int(matched), 0)
    if tier_extra:
        score += TIER_MATCH_WEIGHT * tier_extra / max(prompt_len, 1)
    if max_free > 0:
        score += FREE_WEIGHT * (rep.free_pages / max_free)
    return score


def pool_shape(replicas) -> dict:
    """Per-role replica counts: total and healthy (state ==
    ``healthy``). The ``server_stats``/``stats``-verb surface of the
    pool layout."""
    shape = {r: {"replicas": 0, "healthy": 0} for r in ROLES}
    for rep in replicas:
        row = shape[replica_role(rep)]
        row["replicas"] += 1
        if getattr(rep, "state", "healthy") == "healthy":
            row["healthy"] += 1
    return shape


def _handles(reg):
    """Per-registry metric handles, resolved once (the obs/slo.py
    caching pattern): pool pressure publishes on every autoscaler tick
    and must not pay get-or-create lookups."""
    h = getattr(reg, "_pool_handles", None)
    if h is None:
        h = {
            "replicas": reg.gauge(
                "tdt_pool_replicas",
                "Healthy replicas per pool role.", labels=("role",)),
            "pending": reg.gauge(
                "tdt_pool_pending",
                "Queued + in-flight tickets per pool role.",
                labels=("role",)),
            "free_pages": reg.gauge(
                "tdt_pool_free_pages",
                "KV pool pages free across a pool role's replicas.",
                labels=("role",)),
            "occupancy": reg.gauge(
                "tdt_pool_occupancy",
                "Mean slot occupancy (pending/max_pending) per pool "
                "role, healthy replicas only.", labels=("role",)),
            "shed": reg.counter(
                "tdt_pool_sched_shed_total",
                "Tickets shed by the pool scheduler (already past "
                "their SLO deadline), by class.",
                labels=("slo_class",)),
            "deferred": reg.counter(
                "tdt_pool_sched_deferred_total",
                "Tickets deferred past a dispatch wave by the "
                "prefill/decode token budgets."),
        }
        reg._pool_handles = h
    return h


def publish_pool_gauges(replicas, reg=None) -> dict:
    """Fold the fleet's per-replica pressure into the ``tdt_pool_*``
    gauges, per role, and return the computed summary (role →
    replicas/pending/free_pages/occupancy). Healthy replicas only:
    a draining or dead replica is not capacity."""
    reg = reg if reg is not None else obs_metrics.default_registry()
    h = _handles(reg)
    out: dict = {}
    for role in ROLES:
        live = [r for r in replicas
                if replica_role(r) == role
                and getattr(r, "state", "healthy") == "healthy"]
        pending = sum(r.pending for r in live)
        free = sum(r.free_pages for r in live)
        occ = (sum(occupancy(r) for r in live) / len(live)
               if live else 0.0)
        out[role] = {"replicas": len(live), "pending": pending,
                     "free_pages": free, "occupancy": occ}
        h["replicas"].set(len(live), role=role)
        h["pending"].set(pending, role=role)
        h["free_pages"].set(free, role=role)
        h["occupancy"].set(occ, role=role)
    return out


class Scheduler:
    """Priority admission + token budgets + deadline-aware shedding.

    ``class_priority`` maps ``slo_class`` → rank (lower runs first;
    unknown classes rank after every named one, in arrival order).
    ``prefill_token_budget`` bounds the PROMPT tokens of fresh tickets
    per dispatch wave; ``decode_token_budget`` bounds the remaining
    GENERATION tokens of snapshot-resumed tickets per wave (0 = no
    bound). A ticket larger than its whole budget still gets a wave of
    its own — budgets pace, they never starve.

    Shedding is deadline-aware and prefers the already-lost: a ticket
    whose ``deadline_s`` has ALREADY elapsed (measured from its
    enqueue stamp) is completed as ``deadline_exceeded`` up front —
    the engine would shed it at admission anyway (PR 3), so spending a
    dispatch hop on it only steals budget from requests that can still
    meet their SLO.
    """

    def __init__(self, *, class_priority: dict | None = None,
                 prefill_token_budget: int = 0,
                 decode_token_budget: int = 0):
        self.class_priority = dict(class_priority or {})
        self.prefill_token_budget = int(prefill_token_budget)
        self.decode_token_budget = int(decode_token_budget)

    def priority(self, slo_class) -> int:
        return self.class_priority.get(
            slo_class or "default", len(self.class_priority)
        )

    def _cost(self, ticket) -> tuple[str, int]:
        """(budget kind, token cost) for one ticket: fresh work costs
        its prompt against the prefill budget; resumed work costs its
        remaining generation against the decode budget."""
        snap = getattr(ticket, "snapshot", None)
        if snap is not None:
            done = len(snap.get("out") or []) if isinstance(snap, dict) \
                else 0
            return "decode", max(int(ticket.gen_len) - done, 1)
        return "prefill", max(len(ticket.prompt), 1)

    def plan(self, tickets, now: float | None = None):
        """Partition ``tickets`` into ``(waves, shed)``.

        ``waves`` is a list of ticket lists: priority-ordered
        (class rank, then arrival), each wave respecting both token
        budgets. ``shed`` holds tickets already past their SLO
        deadline — the caller completes them without dispatching."""
        now = time.monotonic() if now is None else now
        live, shed = [], []
        for t in tickets:
            dl = getattr(t, "deadline_s", None)
            enq = getattr(t, "enqueue_t", None)
            if dl is not None and enq is not None and now > enq + dl:
                shed.append(t)
            else:
                live.append(t)
        order = sorted(
            range(len(live)),
            key=lambda i: (self.priority(getattr(live[i], "slo_class",
                                                 None)), i),
        )
        waves: list[list] = []
        budgets = {"prefill": self.prefill_token_budget,
                   "decode": self.decode_token_budget}
        wave: list = []
        spent = {"prefill": 0, "decode": 0}
        for i in order:
            t = live[i]
            kind, cost = self._cost(t)
            cap = budgets[kind]
            if wave and cap > 0 and spent[kind] + cost > cap:
                waves.append(wave)
                wave, spent = [], {"prefill": 0, "decode": 0}
            wave.append(t)
            spent[kind] += cost
        if wave:
            waves.append(wave)
        return waves, shed

    def record_plan(self, waves, shed, reg=None) -> None:
        """Fold one plan into the scheduler telemetry: shed tickets by
        class, deferred = everything past the first wave."""
        reg = reg if reg is not None else obs_metrics.default_registry()
        h = _handles(reg)
        for t in shed:
            h["shed"].inc(
                slo_class=getattr(t, "slo_class", None) or "default")
        deferred = sum(len(w) for w in waves[1:])
        if deferred:
            h["deferred"].inc(deferred)
        if shed:
            obs_events.emit(
                "sched_shed", count=len(shed),
                classes=sorted({getattr(t, "slo_class", None) or
                                "default" for t in shed}),
            )
