"""Prefix-affinity router over replicated engines (the serving tier).

One ``ContinuousEngine`` behind one socket is the single-process scale
ceiling; the measured 64% prefill-work saving (perf/PREFIX_CACHE.json)
only survives scale-out if requests sharing a prefix land on the
replica whose radix tree already holds that KV. This module is the
front tier that preserves it (docs/scale-out.md):

- **Prefix-affinity routing** — each request is scored by longest
  cached prefix against a router-side mirror of every replica's radix
  population (``PrefixCache.prefix_digest`` snapshots, re-published by
  each replica at batch boundaries) and lands on the best match;
  least-loaded wins when no prefix does.
- **Shed-aware balancing** — a replica whose queued+in-flight load
  reaches its ``max_pending`` bound is skipped BEFORE the request
  bounces off the engine's own ``overloaded`` shed; when every healthy
  replica is saturated the router queues to the least-loaded one
  rather than dropping (the engine-side bounds still apply).
- **Health, drain, re-route** — a replica whose engine raises, whose
  batch exceeds ``request_timeout_s``, or that is killed through the
  ``replica.run`` fault seam is marked dead; its queued (and, on
  death, in-flight) tickets are re-routed to surviving replicas up to
  ``max_reroutes`` times, then failed with a structured status from
  the PR 3 taxonomy. Nothing is ever silently dropped.
- **Telemetry** — routing decisions, affinity hits, shed-skips,
  re-routes, and replica lifecycle land in the process metrics
  registry (``tdt_router_*``) and event ring (``route``/``reroute``/
  ``replica_dead``/``replica_drain``), so the server's existing
  ``{"cmd": "metrics"}``/``{"cmd": "events"}`` verbs scrape the tier
  with no new protocol.

The router duck-types the engine surface the model server speaks —
``run(requests, results=True)``, ``last_stats``, ``audit()`` — so
``ModelServer(Router(...))`` is the deployment form: the wire server
stays the transport, the router is the brain behind it. It also sets
``concurrent_safe = True``, telling the server to dispatch generation
payloads WITHOUT the engine lock: payloads from many connections fan
out across replicas concurrently instead of serializing on one.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from triton_distributed_tpu.models.continuous import (
    RequestFailedError,
    RequestResult,
)
from triton_distributed_tpu.obs import events as obs_events
from triton_distributed_tpu.obs import metrics as obs_metrics
from triton_distributed_tpu.serving import pools as pools_mod
from triton_distributed_tpu.serving.replica import (
    DEAD,
    DRAINED,
    FLEET_TOTAL_KEYS,
    HEALTHY,
    EngineReplica,
    Ticket,
)


class Router:
    """Front tier over N :class:`EngineReplica`\\ s.

    ``engines`` entries may be ContinuousEngines (wrapped into
    replicas named ``r0..rN-1``) or pre-built replicas. ``policy`` is
    ``"affinity"`` (longest-prefix match, least-loaded fallback) or
    ``"round_robin"`` (the scale-out baseline the bench compares
    against). ``drain_grace_s`` mirrors the server's connection-drain
    knob: how long :meth:`drain_replica`/:meth:`shutdown` wait for a
    replica's in-flight work before giving up on a clean drain.
    """

    # The model server dispatches generation payloads to a
    # concurrent-safe engine without its engine lock (ticket routing
    # and per-replica queues do the serialization).
    concurrent_safe = True

    def __init__(
        self,
        engines,
        *,
        policy: str = "affinity",
        drain_grace_s: float = 2.0,
        max_reroutes: int = 2,
        request_timeout_s: float | None = None,
        replica_max_pending: int = 8,
        scheduler=None,
    ):
        if policy not in ("affinity", "round_robin",
                          "migrate_after_prefill", "pools"):
            raise ValueError(
                "policy must be 'affinity', 'round_robin', "
                "'migrate_after_prefill', or 'pools', got "
                f"{policy!r}"
            )
        self.replicas: list[EngineReplica] = [
            e if isinstance(e, EngineReplica)
            else EngineReplica(e, name=f"r{i}", max_pending=replica_max_pending)
            for i, e in enumerate(engines)
        ]
        if not self.replicas:
            raise ValueError("Router needs at least one replica")
        names = [r.name for r in self.replicas]
        if len(set(names)) != len(names):
            raise ValueError(f"replica names must be unique, got {names}")
        self.policy = policy
        self.drain_grace_s = float(drain_grace_s)
        self.max_reroutes = int(max_reroutes)
        self.request_timeout_s = request_timeout_s
        # Pool scheduler (docs/scale-out.md "Disaggregated pools &
        # autoscaling"): when set, run() orders each payload by
        # priority class, paces it against the prefill/decode token
        # budgets, and sheds tickets already past their SLO deadline
        # before they cost a dispatch hop.
        self.scheduler = scheduler
        # Crash-recovery snapshot feed (docs/scale-out.md "Slot
        # migration & handoff"): when set (the FleetSupervisor installs
        # it), a re-routed ticket without a snapshot asks the provider
        # for one — a ticket whose dead replica had published progress
        # resumes from it instead of replaying from the prompt.
        self.snapshot_provider = None
        # Replicas swapped out by the supervisor's respawn path: kept
        # for name lookups (a timed-out ticket may still hold a stamp
        # naming one) and for fleet-total aggregation (their counters
        # must stay in the cumulative stats — monotone, never
        # vanishing on a respawn).
        self._retired: list[EngineReplica] = []
        self._rr = 0  # round-robin cursor
        self._lock = threading.Lock()  # router counters + rr cursor
        self.stats = {
            "routed": 0,
            "affinity_hits": 0,
            "affinity_hit_tokens": 0,
            # KV-fabric placement (docs/scale-out.md "KV fabric"):
            # decisions where a replica's TIER digest (pages it would
            # fault back instead of re-prefilling) beat every radix
            # match, and the tokens so covered.
            "tier_affinity_hits": 0,
            "tier_affinity_hit_tokens": 0,
            "least_loaded": 0,
            "round_robin": 0,
            "shed_skips": 0,
            "reroutes": 0,
            "failed_no_replica": 0,
            # Slot migration (docs/scale-out.md "Slot migration &
            # handoff"): tickets re-dispatched with exported state —
            # handoff drains and prefill→decode handoffs.
            "migrations": 0,
            "prefill_migrations": 0,
            # Pool placement (policy="pools"): fresh hops landed on
            # the prefill pool / migrated hops scored onto the decode
            # pool, plus scheduler sheds (past-SLO tickets completed
            # without a dispatch).
            "pool_prefill": 0,
            "pool_decode": 0,
            "sched_sheds": 0,
        }
        for r in self.replicas:
            r.on_failure = self._on_replica_failure
            r.on_migrate = self._on_replica_migrate
        # Metric handles resolved ONCE (engine convention): routing is
        # on every request's path and must not pay registry
        # get-or-create lookups.
        self._m_routed = obs_metrics.counter(
            "tdt_router_requests_total",
            "Requests routed, by replica and decision kind.",
            labels=("replica", "decision"),
        )
        self._m_affinity = obs_metrics.counter(
            "tdt_router_affinity_hit_tokens_total",
            "Prompt tokens routed onto a replica already caching them.",
        )
        self._m_tier_affinity = obs_metrics.counter(
            "tdt_router_tier_affinity_hit_tokens_total",
            "Prompt tokens routed onto a replica whose KV TIER holds "
            "them (fault-back beats re-prefill; docs/scale-out.md "
            "'KV fabric').",
        )
        self._m_reroutes = obs_metrics.counter(
            "tdt_router_reroutes_total",
            "Tickets re-routed off a dead or timed-out replica.",
        )
        self._m_shed_skips = obs_metrics.counter(
            "tdt_router_shed_skips_total",
            "Routing decisions that skipped an overloaded replica.",
        )
        self._m_migrations = obs_metrics.counter(
            "tdt_router_migrations_total",
            "Tickets re-dispatched with exported slot state, by kind.",
            labels=("kind",),
        )
        self._g_healthy = obs_metrics.gauge(
            "tdt_router_healthy_replicas",
            "Replicas currently accepting new work.",
        )
        self._g_healthy.set(len(self.replicas))

    # -- engine-compatible surface ----------------------------------------

    def run(self, requests, *, results: bool = False):
        """Serve ``requests`` across the replica fleet; same contract
        as ``ContinuousEngine.run`` (the model server calls this with
        ``results=True``). Requests are routed individually; results
        come back in submission order."""
        tickets = [Ticket.of(r) for r in requests]
        for t in tickets:
            if t.snapshot is None and self.snapshot_provider is not None:
                # Dispatch-time consult (docs/scale-out.md "Durable
                # snapshots"): a FRESH ticket can still have recovery
                # state — a supervisor restarted over its resume store
                # matches re-submitted requests by (prompt, gen_len)
                # digest, since the pre-crash ticket ids are gone. The
                # provider answers None for everything else, so the
                # common path costs one call.
                try:
                    t.snapshot = self.snapshot_provider(t)
                except Exception:  # noqa: BLE001 — recovery is best-effort
                    t.snapshot = None
        if self.scheduler is not None:
            # Pool scheduling (docs/scale-out.md "Disaggregated pools
            # & autoscaling"): priority-ordered waves under the token
            # budgets; tickets already past their SLO deadline shed
            # HERE — the engine would deadline-shed them at admission
            # anyway, so the hop they save goes to requests that can
            # still meet their SLO.
            waves, shed = self.scheduler.plan(tickets)
            self.scheduler.record_plan(waves, shed)
            for t in shed:
                if t.complete(RequestResult(
                    np.zeros(0, np.int32), "deadline_exceeded",
                    "shed by pool scheduler: past SLO deadline "
                    "before dispatch",
                )):
                    self._bump("sched_sheds")
            for wave in waves:
                for t in wave:
                    self._dispatch(t)
        else:
            for t in tickets:
                self._dispatch(t)
        outs = [self._await(t) for t in tickets]
        if results:
            return outs
        failures = []
        for i, (t, r) in enumerate(zip(tickets, outs)):
            if r.status == "ok":
                continue
            # RequestFailedError documents ``failures`` as (index,
            # Request) — callers read .prompt/.out off the entries, so
            # hand them a real Request carrying the failed attempt's
            # outcome, not a bare RequestResult.
            req = t.make_request()
            req.status, req.reason = r.status, r.reason
            req.out = [int(x) for x in r.tokens]
            failures.append((i, req))
        if failures:
            raise RequestFailedError(failures)
        return [np.asarray(r.tokens, np.int32) for r in outs]

    @property
    def last_stats(self) -> dict:
        """Aggregated serving counters: the core stats keys summed
        CUMULATIVELY across every batch each replica ever ran (the
        engines zero their own stats per run; mixing "last batch"
        snapshots from replicas that ran at different times would
        double-count), plus the router's own ledger under
        ``router``."""
        agg: dict = {k: 0 for k in FLEET_TOTAL_KEYS}
        # Work served by since-replaced replicas stays counted.
        for r in self._retired:
            for k in agg:
                agg[k] += r.totals.get(k, 0)
        reps = []
        kv_bpt, kv_dtype = None, None
        for r in self.replicas:
            st = r.engine.last_stats
            for k in agg:
                agg[k] += r.totals.get(k, 0)
            if kv_bpt is None:
                kv_bpt = st.get("kv_bytes_per_token")
                kv_dtype = st.get("kv_dtype")
            snap = r.snapshot()
            snap["prefix_hit_rate"] = st.get("prefix_hit_rate")
            snap["tree_pages"] = st.get("tree_pages")
            reps.append(snap)
        agg["kv_bytes_per_token"] = kv_bpt
        agg["kv_dtype"] = kv_dtype
        with self._lock:
            router = dict(self.stats)
        router["policy"] = self.policy
        router["replicas"] = reps
        router["pools"] = self.pool_shape()
        router["retired_replicas"] = len(self._retired)
        router["healthy_replicas"] = self._refresh_healthy()
        router["affinity_hit_rate"] = (
            router["affinity_hits"] / max(router["routed"], 1)
        )
        agg["router"] = router
        return agg

    def cancel(self, ticket_ids) -> int:
        """Client-driven cancellation across the fleet (docs/serving.md
        "Streaming & cancellation"): every live replica gets the ids —
        queued tickets complete ``cancelled`` immediately, in-flight
        ones tear down at their engine's next round (over the wire for
        process replicas). The router cannot know which replica holds
        which ticket without racing dispatch, so the fan-out IS the
        protocol; ids matching nothing are pruned engine-side. Returns
        how many queued tickets were cancelled synchronously."""
        tids = [str(t) for t in ticket_ids]
        if not tids:
            return 0
        n = 0
        for r in self.replicas:
            if r.state in (DEAD, DRAINED):
                continue
            try:
                n += r.cancel(tids)
            except Exception:  # noqa: BLE001 — best-effort per replica
                continue
        obs_events.emit("cancel", requested=len(tids), queued_hits=n)
        return n

    def kernel_trace_summary(self) -> dict:
        """Fleet device-tracer state for the server's
        ``{"cmd": "kernel_trace"}`` verb (docs/observability.md
        "Device task tracer"): one per-replica summary per replica
        whose engine exposes a tracer — the router itself has no
        device ring, it only fans the question out."""
        out: dict = {"replicas": {}}
        for r in self.replicas:
            summary = getattr(r.engine, "kernel_trace_summary", None)
            if summary is not None:
                out["replicas"][r.name] = summary()
        out["enabled"] = any(
            s.get("enabled") for s in out["replicas"].values()
        )
        return out

    def kernel_trace_launches(self) -> list:
        """Every replica's recent traced launches, flattened (oldest
        first by launch wall start) — what
        ``obs.kernel_trace.merge_with_host_profile`` consumes."""
        launches: list = []
        for r in self.replicas:
            get = getattr(r.engine, "kernel_trace_launches", None)
            if get is not None:
                launches.extend(get())
        return sorted(launches, key=lambda ln: ln.t0)

    def audit(self, *, raise_on_violation: bool = False) -> list[str]:
        """Every replica engine's pool/radix audit, replica-labeled.

        Best run on a quiesced fleet (after :meth:`shutdown` /
        :meth:`drain_replica`, or between batches): the audit walks
        live engine state that a mid-batch worker is mutating, so a
        concurrent run can report transient phantoms or trip on a
        resizing dict — such trips are surfaced as a labeled problem
        string (with a raced-live-work caveat), never an escape."""
        problems: list[str] = []
        for r in self.replicas:
            try:
                problems += [
                    f"replica {r.name}: {p}" for p in r.engine.audit()
                ]
            except Exception as e:  # noqa: BLE001 — racing a live batch
                if r.state in (DEAD, DRAINED):
                    # A dead or drained replica that cannot be REACHED
                    # (a killed replica process, or a drained one whose
                    # child exited on the shutdown verb) has nothing
                    # left to audit; the live survivors' verdicts are
                    # what "clean" means. Dead/drained IN-process
                    # replicas still audit above — their engines
                    # outlive the worker.
                    continue
                problems.append(
                    f"replica {r.name}: audit raced in-flight work "
                    f"({type(e).__name__}: {e}); re-run quiesced"
                )
        if problems and raise_on_violation:
            from triton_distributed_tpu.models.paged_kv_cache import (
                PoolAuditError,
            )

            raise PoolAuditError("; ".join(problems))
        return problems

    # -- lifecycle ---------------------------------------------------------

    def replica(self, name: str) -> EngineReplica:
        for r in self.replicas:
            if r.name == name:
                return r
        # A ticket's hop stamp can outlive a respawn swap: resolve
        # retired names too (newest first), so the timeout path never
        # KeyErrors judging a hop on a since-replaced replica.
        for r in reversed(self._retired):
            if r.name == name:
                return r
        raise KeyError(f"no replica named {name!r}")

    def add_replica(self, replica: EngineReplica) -> None:
        """Grow the rotation (a supervisor bringing a replica up after
        its initial spawn failed). The replica joins routing as soon as
        its state reads healthy."""
        if any(r.name == replica.name for r in self.replicas):
            raise ValueError(f"replica name {replica.name!r} already live")
        replica.on_failure = self._on_replica_failure
        replica.on_migrate = self._on_replica_migrate
        self.replicas.append(replica)
        self._refresh_healthy()

    def replace_replica(self, old_name: str,
                        replica: EngineReplica) -> EngineReplica:
        """Swap a dead replica for its respawned successor (the
        supervisor's rejoin path, docs/scale-out.md "Process fleet").
        The old replica is retired, not forgotten: its totals stay in
        the fleet stats and its name keeps resolving for late hop
        judgments. The successor must carry a FRESH name — reusing the
        dead name would let a stale reroute claim against the old hop
        block the new replica's own failure handling."""
        if any(r.name == replica.name for r in self.replicas):
            raise ValueError(f"replica name {replica.name!r} already live")
        for i, r in enumerate(self.replicas):
            if r.name == old_name:
                self._retired.append(r)
                replica.on_failure = self._on_replica_failure
                replica.on_migrate = self._on_replica_migrate
                self.replicas[i] = replica
                self._refresh_healthy()
                return r
        raise KeyError(f"no replica named {old_name!r}")

    def drain_replica(self, name: str, grace_s: float | None = None,
                      *, handoff: bool = False) -> bool:
        """Gracefully take one replica out of rotation; waits up to
        ``grace_s`` (default: the router's ``drain_grace_s``).

        ``handoff=False`` finishes queued + in-flight work HERE before
        draining. ``handoff=True`` is the lossless drain
        (docs/scale-out.md "Slot migration & handoff"): unfinished
        slots export and re-admit on surviving replicas — generated
        tokens carry over, nothing is recomputed, nothing double-emits
        (latch-first tickets)."""
        grace = self.drain_grace_s if grace_s is None else grace_s
        rep = self.replica(name)
        if handoff and not any(
            r.state == HEALTHY and r.name != name for r in self.replicas
        ):
            # Nowhere to hand off to: exporting would FAIL the work a
            # plain drain finishes — degrade to the finishing drain,
            # which is what "lossless either way" means here.
            handoff = False
        rep.begin_drain(handoff=handoff)
        ok = rep.drain(grace)
        self._refresh_healthy()
        return ok

    def shutdown(self) -> None:
        """Drain the whole fleet against ONE shared ``drain_grace_s``
        deadline (flip everyone to draining first, then wait — N
        sequential full drains would cost N × grace) and join the
        worker threads. Idempotent — the model server calls this from
        its own shutdown path."""
        for r in self.replicas:
            r.begin_drain()
        deadline = time.monotonic() + self.drain_grace_s
        for r in self.replicas:
            r.drain(max(deadline - time.monotonic(), 0.0))
        # One shared join deadline too: K wedged workers must not hold
        # shutdown K × timeout beyond the grace already spent.
        join_by = time.monotonic() + max(self.drain_grace_s, 5.0)
        for r in self.replicas:
            r.join(timeout=max(join_by - time.monotonic(), 0.0))
        self._g_healthy.set(0)

    # -- routing -----------------------------------------------------------

    def _bump(self, key: str, n: int = 1) -> None:
        with self._lock:
            self.stats[key] += n

    def _refresh_healthy(self) -> int:
        """Recompute the accepting-work count and sync its gauge — the
        ONE definition every state transition and stats read shares."""
        n = sum(1 for r in self.replicas if r.state == HEALTHY)
        self._g_healthy.set(n)
        return n

    def _candidates(self) -> list[EngineReplica]:
        return [r for r in self.replicas if r.state == HEALTHY]

    def _pick(self, ticket: Ticket, *, count_sheds: bool = True,
              exclude: str | None = None):
        """One routing decision: ``(replica, matched_tokens, decision)``
        or ``(None, 0, reason)`` when nothing can take the ticket.
        ``count_sheds=False`` on pick-to-submit-race retries keeps the
        shed-skip ledger one-entry-per-decision. ``exclude`` skips one
        replica by name when alternatives exist — a migrated ticket
        should land AWAY from its source (falling back to the source
        beats failing when it is the only replica left)."""
        live = self._candidates()
        if not live:
            return None, 0, "no healthy replica"
        if exclude is not None and len(live) > 1:
            live = [r for r in live if r.name != exclude] or live
        open_ = [r for r in live if not r.overloaded]
        if len(open_) < len(live) and count_sheds:
            skipped = len(live) - len(open_)
            self._bump("shed_skips", skipped)
            self._m_shed_skips.inc(skipped)
        # All saturated: queue to the least-loaded healthy replica
        # anyway — the router never bounces a request it could hold
        # (the engine-side max_queue/deadline bounds still shed).
        pool = open_ or live
        if self.policy == "pools":
            return self._pick_pools(ticket, pool)
        if self.policy == "round_robin":
            with self._lock:
                rep = pool[self._rr % len(pool)]
                self._rr += 1
            return rep, 0, "round_robin"
        best, best_len, best_radix = None, 0, 0
        toks = ticket.prompt_tokens  # converted once, scored N times
        for r in pool:
            m = r.match_len(toks)
            # Tier affinity (docs/scale-out.md "KV fabric"): pages a
            # replica would FAULT BACK from its tier are nearly as good
            # as radix-resident ones — both beat re-prefilling on a
            # cold neighbor. The max keeps radix and tier coverage on
            # one scale (tokens of prompt already held).
            tl = getattr(r, "tier_match_len", None)
            eff = max(m, tl(toks) if tl is not None else 0)
            if eff > best_len or (
                eff == best_len and best is not None and eff > 0
                and r.pending < best.pending
            ):
                best, best_len, best_radix = r, eff, m
        if best is not None and best_len > 0:
            return best, best_len, (
                "affinity" if best_radix >= best_len else "tier_affinity"
            )
        rep = min(pool, key=lambda r: (r.pending, -r.free_pages))
        return rep, 0, "least_loaded"

    def _pick_pools(self, ticket: Ticket, pool):
        """Role-aware placement (docs/scale-out.md "Disaggregated
        pools & autoscaling"): a FRESH ticket prefills on the prefill
        pool (prefix-affinity within it, least-loaded fallback); a
        MIGRATED ticket decodes on the decode pool scored by
        ``pools.decode_score`` — radix-digest match weighed against
        slot occupancy and free pages instead of match-only. Either
        pool being empty falls back to every open replica: roles
        steer, they never strand."""
        toks = ticket.prompt_tokens
        if ticket.snapshot is not None:
            cands = [r for r in pool if pools_mod.decode_capable(r)]
            cands = cands or pool
            max_free = max((r.free_pages for r in cands), default=0)
            best, best_score, best_m = None, None, 0
            for r in cands:
                m = r.match_len(toks)
                tl = getattr(r, "tier_match_len", None)
                t = tl(toks) if tl is not None else 0
                s = pools_mod.decode_score(r, m, len(toks),
                                           max_free=max_free,
                                           tier_matched=t)
                if best_score is None or s > best_score:
                    best, best_score, best_m = r, s, max(m, t)
            return best, best_m, "pool_decode"
        cands = [r for r in pool if pools_mod.prefill_capable(r)]
        cands = cands or pool
        best, best_len = None, 0
        for r in cands:
            m = r.match_len(toks)
            if m > best_len or (
                m == best_len and best is not None and m > 0
                and r.pending < best.pending
            ):
                best, best_len = r, m
        if best is not None and best_len > 0:
            return best, best_len, "pool_prefill"
        rep = min(cands, key=lambda r: (r.pending, -r.free_pages))
        return rep, 0, "pool_prefill"

    def pool_shape(self) -> dict:
        """Per-role replica counts (total + healthy) — the pool-layout
        surface ``server_stats`` and the stats verb expose."""
        return pools_mod.pool_shape(self.replicas)

    def _dispatch(self, ticket: Ticket, exclude: str | None = None) -> None:
        # migrate_after_prefill (docs/scale-out.md "Slot migration &
        # handoff"): a fresh ticket's first hop only PREFILLS — the
        # engine exports the slot right after admission and the
        # migrated snapshot re-dispatches to a decode replica. Needs
        # somewhere else to decode; with one live replica the flag
        # stays off and the request serves end-to-end locally.
        if self.policy == "migrate_after_prefill":
            ticket.prefill_only = (
                ticket.snapshot is None and len(self._candidates()) > 1
            )
        elif self.policy == "pools":
            # Disaggregation proper: prefill-only iff the handoff has
            # a decode-capable target to land on — otherwise the
            # chosen replica serves end-to-end (a one-replica or
            # prefill-only fleet stays correct, just not split).
            live = self._candidates()
            ticket.prefill_only = (
                ticket.snapshot is None and len(live) > 1
                and any(pools_mod.decode_capable(r) for r in live)
            )
        first = True
        while True:
            rep, matched, decision = self._pick(
                ticket, count_sheds=first, exclude=exclude
            )
            first = False
            if rep is None:
                self._fail_ticket(ticket, decision)
                return
            if not rep.submit(ticket):
                # Lost the race with the replica dying between pick and
                # submit — re-pick (the state filter now excludes it).
                continue
            # (submit already appended rep.name to replica_history,
            # atomically with the enqueue, under the replica's lock.)
            self._bump("routed")
            if decision == "affinity":
                self._bump("affinity_hits")
                self._bump("affinity_hit_tokens", matched)
                self._m_affinity.inc(matched)
            elif decision == "tier_affinity":
                self._bump("tier_affinity_hits")
                self._bump("tier_affinity_hit_tokens", matched)
                self._m_tier_affinity.inc(matched)
            elif decision == "least_loaded":
                self._bump("least_loaded")
            elif decision == "round_robin":
                self._bump("round_robin")
            elif decision in ("pool_prefill", "pool_decode"):
                self._bump(decision)
                if matched > 0:
                    self._bump("affinity_hit_tokens", matched)
                    self._m_affinity.inc(matched)
            self._m_routed.inc(replica=rep.name, decision=decision)
            obs_events.emit(
                "route", replica=rep.name, decision=decision,
                matched=matched, prompt_len=len(ticket.prompt),
                reroutes=ticket.reroutes,
            )
            return

    def _await(self, ticket: Ticket) -> RequestResult:
        """Block until the ticket latches a result. With
        ``request_timeout_s`` set, a replica that sits on a ticket too
        long is marked unhealthy (its queue re-routes, the in-flight
        batch finishes into latched-ignored results) and the ticket is
        retried elsewhere."""
        if self.request_timeout_s is None:
            ticket.wait()
            return ticket.result
        while ticket.result is None:
            # Per-HOP budget: the timer arms from the CURRENT hop's
            # dispatch stamp, not from when this wait started — a
            # ticket rerouted mid-wait (a death callback beat this
            # timer) gives its new replica a full window, because
            # killing a replica that has held the ticket only a
            # fraction of the budget would cascade a healthy fleet to
            # zero.
            dispatched = ticket.last_dispatch_t
            wait_s = self.request_timeout_s
            if dispatched is not None:
                wait_s = dispatched + wait_s - time.monotonic()
            # Floor the wait: a stale stamp with an expired budget
            # (e.g. a lost reroute claim whose winner hasn't
            # re-submitted yet) must poll, not busy-spin.
            if ticket.wait(max(wait_s, 0.05)):
                break
            if ticket.result is not None:
                # Lost the race with a completion right at the timeout:
                # the work was delivered — the replica must NOT be
                # killed for finishing slowly but in time.
                break
            # Atomic hop judgment (name + stamp under the ticket
            # lock): a reroute racing this expiry can't get the NEW
            # replica killed for the old hop's stale stamp.
            overdue = ticket.expired_hop(self.request_timeout_s)
            if overdue is None:
                continue  # re-dispatched/completed during the wait
            rep = self.replica(overdue)
            if rep.state != DEAD:
                orphans = rep.mark_unhealthy(
                    f"router-observed timeout: a ticket waited "
                    f">{self.request_timeout_s}s"
                )
                self._refresh_healthy()
                for t in orphans:
                    if t is not ticket:
                        self._reroute(t, "replica timeout (queued)",
                                      source=rep)
            if ticket.result is None:
                self._reroute(ticket, "replica timeout", source=rep)
        return ticket.result

    # -- failure handling --------------------------------------------------

    def _on_replica_failure(self, replica: EngineReplica,
                            tickets: list[Ticket]) -> None:
        """A replica died mid-batch (engine raise / injected kill):
        re-route every orphaned ticket. Runs on the dead replica's
        worker thread."""
        self._refresh_healthy()
        for t in tickets:
            self._reroute(
                t, f"replica {replica.name} died: {replica.last_error}",
                source=replica,
            )

    def _on_replica_migrate(self, replica: EngineReplica,
                            tickets: list[Ticket]) -> None:
        """A replica exported tickets instead of finishing them (a
        handoff drain or a prefill→decode handoff): re-dispatch each
        with its snapshot. Runs on the source replica's worker
        thread."""
        self._refresh_healthy()
        for t in tickets:
            self._migrate_ticket(t, replica)

    def _migrate_ticket(self, ticket: Ticket,
                        source: EngineReplica) -> None:
        # The same atomic per-hop claim re-routing uses: a latched
        # result or a concurrent claim (a death callback racing the
        # handoff) skips — the ticket is never double-dispatched. A
        # migration consumes one hop of the re-route budget, which is
        # what bounds a pathological migration loop.
        if not ticket.claim_reroute(source.name):
            return
        if ticket.reroutes > self.max_reroutes:
            self._fail_ticket(
                ticket,
                f"re-route budget exhausted ({self.max_reroutes}) "
                f"after migration off {source.name}",
            )
            return
        # Kind from the TICKET's provenance (was it dispatched as a
        # prefill-only hop?), not the global policy — a handoff DRAIN
        # under migrate_after_prefill is still a drain.
        kind = "prefill_handoff" if ticket.prefill_only else "handoff"
        ticket.prefill_only = False  # the next hop decodes
        self._bump("migrations")
        if kind == "prefill_handoff":
            self._bump("prefill_migrations")
        self._m_migrations.inc(kind=kind)
        obs_events.emit(
            "migrate", source=source.name, migration=kind,
            tokens=len((ticket.snapshot or {}).get("out") or []),
            prompt_len=len(ticket.prompt),
        )
        self._dispatch(ticket, exclude=source.name)

    def _reroute(self, ticket: Ticket, reason: str,
                 source: EngineReplica | None = None) -> None:
        # Atomic per-hop claim (Ticket.claim_reroute): a latched
        # result, a ticket already re-dispatched off this replica, or
        # a concurrent claim for the same hop (the timeout path racing
        # the death callback) all skip — a ticket is never
        # double-dispatched, and never guard-skipped into a hang.
        if not ticket.claim_reroute(source.name if source else None):
            return
        if ticket.reroutes > self.max_reroutes:
            self._fail_ticket(
                ticket,
                f"re-route budget exhausted ({self.max_reroutes}) after: "
                f"{reason}",
            )
            return
        if ticket.snapshot is None and self.snapshot_provider is not None:
            try:
                ticket.snapshot = self.snapshot_provider(ticket)
            except Exception:  # noqa: BLE001 — recovery is best-effort
                ticket.snapshot = None
        self._bump("reroutes")
        self._m_reroutes.inc()
        obs_events.emit(
            "reroute", attempt=ticket.reroutes, reason=str(reason)[:200],
            prompt_len=len(ticket.prompt),
        )
        self._dispatch(ticket)

    def _fail_ticket(self, ticket: Ticket, reason: str) -> None:
        """Terminal routing failure: a structured PR 3-taxonomy result,
        never a silent drop. Counted only when the failure actually
        latches — a late completion winning the race delivered real
        tokens, and the ledger must not report a failure no client
        saw."""
        if ticket.complete(RequestResult(
            np.zeros(0, np.int32), "failed", f"routing failed: {reason}"
        )):
            self._bump("failed_no_replica")
            obs_events.emit(
                "route_failed", reason=str(reason)[:200],
                reroutes=ticket.reroutes,
            )
