"""Process-fleet supervision: spawn, heartbeat, classify, respawn.

The cross-process serving tier (docs/scale-out.md "Process fleet") is
``Router`` over :class:`~triton_distributed_tpu.serving.remote.RemoteReplica`\\ s;
this module owns the part neither of them can see — the *processes*.
:class:`FleetSupervisor` spawns one replica child per
:class:`ReplicaSpec` (reusing the ``run_server`` entry with its
``--port-file`` handshake), then drives a monitor loop that:

- **detects** failures via a cheap ``{"cmd": "healthz"}`` heartbeat on
  a deadline, plus process exit codes, plus the router's own
  observations (a wire ``_die`` or a router request-timeout both leave
  the replica ``dead`` for the monitor to find);
- **classifies** every failure into a small taxonomy — ``conn``
  (refused/RST while the process looked alive), ``exit`` (the process
  is gone; rc attached), ``heartbeat_timeout`` (alive but not
  answering — the SIGSTOP/wedged case), ``hung_request`` (the router
  timed out a batch on a live process), ``spawn`` (never came up);
- **recovers** in-flight work by marking the replica dead and handing
  its orphaned tickets to the router's existing
  ``_on_replica_failure`` path — the same latch-first re-route that
  serves thread-replica deaths, which the ticket-id wire dedup makes
  safe across processes (survivors stay bit-exact; a finished-but-
  unreported batch can only latch-lose);
- **respawns** the slot with exponential backoff (capped) under a
  crash-loop circuit breaker: ``crash_limit`` failures inside
  ``crash_window_s`` PARKS the slot — an event and a counter fire, the
  fleet keeps serving degraded on the survivors — instead of burning
  the host on a doomed spawn loop. A respawned replica joins under a
  fresh generation-suffixed name (``r0#2``) via
  ``Router.replace_replica`` and rejoins routing with a fresh prefix
  digest.

Hosts are failure domains (docs/scale-out.md "Multi-host fleet"):
specs carry an optional ``host`` (a launcher placement target), and
when EVERY replica on one host goes missing inside one window the
monitor classifies a single correlated ``host_down`` — fencing the
dead host's replicas under a bumped epoch (a zombie that thaws can
neither latch results nor take new placements), re-routing all their
work in the same tick, and re-placing their respawns on surviving
hosts (spawn failover). Spawning itself hides behind the pluggable
:class:`~triton_distributed_tpu.serving.launcher.Launcher` seam; the
default ``LocalLauncher`` is today's subprocess + port-file path,
byte-identical.

Everything observable lands in the PR 5 telemetry:
``tdt_supervisor_failures_total{replica,kind}``,
``tdt_supervisor_respawns_total{replica}``,
``tdt_supervisor_parked_replicas``, and the per-slot
``tdt_replica_heartbeat_age_seconds{replica}`` gauge, plus
``replica_proc_failed`` / ``replica_respawn`` / ``replica_parked``
events — all scrapeable through the front server's existing
``metrics``/``events`` verbs (docs/observability.md).
"""

from __future__ import annotations

import dataclasses
import os
import socket
import subprocess
import sys
import tempfile
import threading
import time

from triton_distributed_tpu.obs import events as obs_events
from triton_distributed_tpu.obs import metrics as obs_metrics
from triton_distributed_tpu.serving.launcher import (  # noqa: F401 —
    # SpawnError is re-exported: it predates the launcher seam and
    # callers import it from here.
    Launcher,
    LocalLauncher,
    SpawnError,
    local_spawn,
)
from triton_distributed_tpu.serving.remote import RemoteReplica
from triton_distributed_tpu.serving.replica import (
    DEAD,
    DRAINED,
    DRAINING,
    HEALTHY,
)
from triton_distributed_tpu.serving.router import Router


@dataclasses.dataclass
class ReplicaSpec:
    """How to launch one replica slot. ``argv`` is the full child
    command; the supervisor appends ``--port-file <path>`` per spawn.
    ``name`` is the SLOT name: generation 0 serves as ``name``, every
    respawn as ``name#<generation>`` (router identities must be unique
    across a slot's lifetime — see ``Router.replace_replica``), while
    metrics stay labeled by the slot so respawns don't grow label
    cardinality. ``role`` tags the replica's pool (prefill / decode /
    mixed, serving/pools.py) — router-side placement metadata only;
    the child process is identical either way, and respawns keep the
    slot's role across generations. ``host`` names the failure domain
    the replica is placed in (a launcher host, docs/scale-out.md
    "Multi-host fleet"); None means no host notion — every host-domain
    feature (correlated classification, fencing, failover) stays
    dormant, which is the single-machine default. Unlike ``role``, the
    host may CHANGE across respawns: spawn failover re-places a slot
    whose host died onto a surviving one."""

    name: str
    argv: list[str]
    env: dict | None = None
    role: str = "mixed"
    host: str | None = None


def stub_spec(name: str, *, delay_s: float = 0.0, num_pages: int = 256,
              page_size: int = 16, role: str = "mixed",
              max_batch: int = 0, extra: tuple = ()) -> ReplicaSpec:
    """A deterministic stub-engine replica (models/stub.py) — what the
    chaos suite and ``perf/fleet_bench.py`` spawn: full wire server,
    real radix control plane, no model load. ``max_batch`` bounds the
    child's per-round decode slots (0 = unbounded), giving it finite
    throughput for capacity benches (perf/pools_bench.py)."""
    return ReplicaSpec(name, [
        sys.executable, "-m", "triton_distributed_tpu.serving.run_server",
        "--model", "stub", "--port", "0",
        "--stub-delay", str(delay_s),
        "--stub-pages", str(num_pages),
        "--stub-page-size", str(page_size),
        "--stub-max-batch", str(max_batch),
        *extra,
    ], role=role)


def model_spec(name: str, model: str = "tiny", *, role: str = "mixed",
               extra: tuple = ()) -> ReplicaSpec:
    """A real-model replica child (the production shape)."""
    return ReplicaSpec(name, [
        sys.executable, "-m", "triton_distributed_tpu.serving.run_server",
        "--model", model, "--port", "0", *extra,
    ], role=role)


def spawn_replica(spec: ReplicaSpec, *, generation: int = 0,
                  spawn_timeout_s: float = 120.0, max_pending: int = 8,
                  log_dir: str | None = None) -> RemoteReplica:
    """Launch one replica child and wait for its port handshake.
    Returns a connected :class:`RemoteReplica` (``.proc`` holds the
    ``Popen``); raises :class:`SpawnError` — with the child's log tail
    attached — when the child dies or stalls before binding. The
    implementation lives behind the launcher seam now
    (serving/launcher.py); this is the local path, verbatim."""
    return local_spawn(
        spec, generation=generation, spawn_timeout_s=spawn_timeout_s,
        max_pending=max_pending, log_dir=log_dir,
    )


@dataclasses.dataclass
class _Slot:
    """Supervisor-internal state for one replica slot."""

    spec: ReplicaSpec
    generation: int = 0
    replica: RemoteReplica | None = None
    parked: bool = False
    # The name this slot's replica last joined the router under (set
    # on every successful spawn; survives _fail clearing `replica`) —
    # the respawn path retires EXACTLY this entry instead of
    # re-deriving the generation-suffix naming rule.
    last_name: str | None = None
    crash_times: list = dataclasses.field(default_factory=list)
    fails_in_a_row: int = 0
    missed_beats: int = 0
    next_respawn_t: float | None = None
    last_beat_t: float | None = None
    last_failure: str | None = None
    respawns: int = 0


class FleetSupervisor:
    """Own a fleet of replica processes behind one :class:`Router`.

    ``start()`` spawns every spec, builds the router, and starts the
    monitor thread; ``shutdown()`` drains the fleet and reaps the
    children. The monitor is a single loop ticking every
    ``heartbeat_s`` — with a handful of replica processes, one thread
    beating them in sequence keeps detection latency ≈ the interval
    without a thread per child.
    """

    def __init__(
        self,
        specs: list[ReplicaSpec],
        *,
        policy: str = "affinity",
        heartbeat_s: float = 0.25,
        heartbeat_timeout_s: float = 2.0,
        heartbeat_misses: int = 2,
        spawn_timeout_s: float = 120.0,
        respawn_backoff_s: float = 0.25,
        max_backoff_s: float = 4.0,
        crash_limit: int = 3,
        crash_window_s: float = 30.0,
        replica_max_pending: int = 8,
        log_dir: str | None = None,
        router_kw: dict | None = None,
        snapshot_s: float = 0.0,
        resume_dir: str | None = None,
        tier_fabric: bool = False,
        launcher: Launcher | None = None,
        connect_timeout_s: float = 10.0,
    ):
        if not specs:
            raise ValueError("FleetSupervisor needs at least one spec")
        names = [s.name for s in specs]
        if len(set(names)) != len(names):
            raise ValueError(f"spec names must be unique, got {names}")
        self._slots = [_Slot(spec=s) for s in specs]
        self.policy = policy
        # The spawn seam (serving/launcher.py): default is the local
        # subprocess + port-file path, byte-identical to before the
        # seam existed. Every dial the supervisor makes is bounded by
        # ``connect_timeout_s`` — against an unroutable host, refusal
        # must arrive on OUR deadline, not the OS connect default.
        self.launcher: Launcher = launcher or LocalLauncher()
        self.connect_timeout_s = float(connect_timeout_s)
        # Host failure domains (docs/scale-out.md "Multi-host fleet").
        # Ledger per named host: ``down`` gates placement and spawns,
        # ``epoch`` is the fence generation (bumped every time the
        # host is declared dead — a zombie thawing under an old epoch
        # can neither latch results nor get spawns placed on it until
        # an operator revives the host), ``crash_times`` feeds the
        # per-host crash-loop breaker.
        self._hosts: dict[str, dict] = {}
        for h in list(self.launcher.hosts()) + [
            s.host for s in specs if getattr(s, "host", None)
        ]:
            self._hosts.setdefault(
                str(h), {"down": False, "epoch": 0, "crash_times": []}
            )
        # Children a host_down deliberately did NOT kill (unreachable
        # in production; locally they would leak) — reaped at shutdown.
        self._zombies: list = []
        self.heartbeat_s = float(heartbeat_s)
        self.heartbeat_timeout_s = float(heartbeat_timeout_s)
        # Deadline tolerance: a wedged process is declared after this
        # many CONSECUTIVE missed beats (a single slow accept on a
        # loaded host is not a verdict); refused/reset classify on the
        # first, they are definitive.
        self.heartbeat_misses = max(int(heartbeat_misses), 1)
        self.spawn_timeout_s = float(spawn_timeout_s)
        self.respawn_backoff_s = float(respawn_backoff_s)
        self.max_backoff_s = float(max_backoff_s)
        self.crash_limit = int(crash_limit)
        self.crash_window_s = float(crash_window_s)
        self.replica_max_pending = int(replica_max_pending)
        # Snapshot-based crash recovery (docs/scale-out.md "Slot
        # migration & handoff"): every ``snapshot_s`` seconds (0 =
        # off) the monitor pulls each healthy child's incremental
        # slot snapshots ({"cmd": "export_slots"}); when a replica
        # fails, its orphaned tickets resume from the last snapshot
        # instead of replaying from the prompt. A stale or garbled
        # snapshot degrades to replay on the target — never worse
        # than PR 9's recovery.
        self.snapshot_s = float(snapshot_s)
        if resume_dir and not self.snapshot_s:
            # A resume store without a pull cadence never persists
            # anything — "restart-safe fleet from one flag" would be a
            # lie (the store only ever REPLAYED pre-existing
            # leftovers). Durability implies pulling; an explicit
            # snapshot_s still wins.
            self.snapshot_s = 1.0
        self._snaps: dict[str, dict] = {}  # slot name → {tid: snap}
        self._snap_lock = threading.Lock()  # monitor vs reroute threads
        self._next_snap_t = 0.0
        # Durable snapshot store (docs/scale-out.md "Durable
        # snapshots"): with ``resume_dir`` set, every pulled snapshot
        # is ALSO persisted to a disk-backed PageStore (atomic
        # write-then-rename, per-entry checksum — models/kv_tier.py),
        # and a fresh supervisor booting over the same dir loads the
        # crash leftovers: a re-submitted request whose (prompt,
        # gen_len) digest matches a leftover resumes mid-generation
        # instead of replaying — the supervisor-restart case a
        # process-memory-only buffer forfeits. Integrity failures drop
        # the entry (the request replays: degraded, never wrong), and
        # a CLEAN shutdown clears the store — leftovers mean a crash.
        self.resume_dir = resume_dir
        # KV fabric peer wiring (docs/scale-out.md "KV fabric"):
        # opt-in — after every membership change (boot, add_slot,
        # retire_slot, respawn) each live child learns its peers via
        # the ``tier_peers`` verb, so tier entries one replica spilled
        # are pullable by the others. Off by default: the broadcast is
        # probe traffic, and fleets without tiers (or chaos tests with
        # probe-narrowed wire seams) must not see it.
        self.tier_fabric = bool(tier_fabric)
        self._store = None
        self._store_keys: dict[str, set] = {}  # slot name → persisted tids
        self._resume: dict[str, tuple[str, dict]] = {}  # digest → (tid, snap)
        if resume_dir:
            from triton_distributed_tpu.models.kv_tier import (
                SNAP_KIND,
                PageStore,
                request_digest,
            )

            self._store = PageStore(dir=resume_dir)
            for tid in self._store.keys(SNAP_KIND):
                snap = self._store.get(SNAP_KIND, tid)  # checksum-verified
                if not isinstance(snap, dict) or not snap.get("out"):
                    continue
                try:
                    digest = request_digest(
                        snap["prompt"], snap["gen_len"]
                    )
                except (KeyError, TypeError, ValueError):
                    continue
                self._resume[digest] = (tid, snap)
        self.log_dir = log_dir or tempfile.mkdtemp(prefix="tdt-fleet-")
        self._router_kw = dict(router_kw or {})
        self._router_kw.setdefault("policy", policy)
        self.router: Router | None = None
        # Fleet-scope telemetry aggregation (docs/scale-out.md
        # "Fleet-scope telemetry"). TWO locks, deliberately:
        # ``_scrape_lock`` serializes whole fleet_events scrapes
        # (shared cursors mean concurrent scrapes would double-pull)
        # and is held ACROSS the child RPCs — so nothing the monitor/
        # respawn path needs may ever take it; ``_cursor_lock`` guards
        # the cursor/seq state itself and is only ever held briefly,
        # which is the one the respawn path's cursor reset uses.
        self._scrape_lock = threading.Lock()
        self._cursor_lock = threading.Lock()
        self._event_cursors: dict[str, int] = {}
        self._fleet_seq = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # Monitor-vs-shutdown exclusion: a tick must not respawn into a
        # fleet that is draining.
        self._lock = threading.Lock()
        self._m_failures = obs_metrics.counter(
            "tdt_supervisor_failures_total",
            "Replica process failures, by slot and classified kind.",
            labels=("replica", "kind"),
        )
        self._m_respawns = obs_metrics.counter(
            "tdt_supervisor_respawns_total",
            "Replica processes respawned, by slot.",
            labels=("replica",),
        )
        self._g_parked = obs_metrics.gauge(
            "tdt_supervisor_parked_replicas",
            "Slots taken out of service by the crash-loop breaker.",
        )
        self._g_beat_age = obs_metrics.gauge(
            "tdt_replica_heartbeat_age_seconds",
            "Seconds since the last successful heartbeat, by slot.",
            labels=("replica",),
        )
        self._m_resumes = obs_metrics.counter(
            "tdt_supervisor_snapshot_resumes_total",
            "Orphaned tickets re-dispatched WITH a crash-recovery "
            "snapshot (vs plain replay), by slot.",
            labels=("replica",),
        )
        self._m_pull_failures = obs_metrics.counter(
            "tdt_supervisor_snapshot_pull_failures_total",
            "Snapshot pulls (export_slots) that failed, by slot — a "
            "permanently wedged exporter shows as a monotone ramp "
            "here instead of silently degrading every recovery to "
            "replay.",
            labels=("replica",),
        )
        self._g_host_up = obs_metrics.gauge(
            "tdt_host_up",
            "1 while the named host is in service, 0 after it was "
            "declared down (host_down classification or operator "
            "mark); revive_host restores it.",
            labels=("host",),
        )
        self._m_host_down = obs_metrics.counter(
            "tdt_supervisor_host_down_total",
            "Whole-host failures: ALL replicas on one host missing "
            "heartbeats inside one window classifies as a single "
            "correlated host_down, not N independent timeouts.",
            labels=("host",),
        )
        self._m_failovers = obs_metrics.counter(
            "tdt_supervisor_spawn_failovers_total",
            "Slots re-placed onto another host after their spawn "
            "target failed or was down, by slot.",
            labels=("slot",),
        )
        for h in self._hosts:
            self._g_host_up.set(1.0, host=h)
            self._m_host_down.inc(0, host=h)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> Router:
        """Spawn the fleet, build the router, start monitoring. A slot
        whose INITIAL spawn fails is scheduled for retry through the
        normal backoff/park path; at least one replica must come up."""
        # Spawn concurrently: child startup is import-bound, and N
        # sequential spawns would cost N × the interpreter cold start.
        outcomes: dict[str, object] = {}

        def boot(slot: _Slot) -> None:
            try:
                outcomes[slot.spec.name] = self._spawn(slot)
            except SpawnError as e:
                outcomes[slot.spec.name] = e

        threads = [
            threading.Thread(target=boot, args=(s,), daemon=True)
            for s in self._slots
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        replicas = []
        for slot in self._slots:
            got = outcomes.get(slot.spec.name)
            if isinstance(got, RemoteReplica):
                slot.replica = got
                slot.last_name = got.name
                replicas.append(got)
            else:
                self._record_failure(slot, "spawn", str(got))
        if not replicas:
            raise SpawnError(
                "no replica in the fleet reached its port handshake; "
                f"logs under {self.log_dir}"
            )
        self.router = Router(
            replicas, replica_max_pending=self.replica_max_pending,
            **self._router_kw,
        )
        if self.snapshot_s or self._store is not None:
            # Crash recovery consults the snapshot store on EVERY
            # re-route claim — wire-detected deaths included, which
            # never pass through this supervisor's _fail — and (via
            # the router's dispatch-time consult) on every FRESH
            # ticket, which is how a restart-leftover snapshot finds
            # its re-submitted request.
            self.router.snapshot_provider = self._snapshot_for
        # Fleet-scope scrape hand-off: the front ModelServer reaches
        # fleet_metrics()/fleet_events() through its engine — the
        # router IS that engine, so it carries the back-reference
        # ({"cmd": "metrics", "scope": "fleet"}, docs/scale-out.md).
        self.router.fleet = self
        self._broadcast_tier_peers()
        self._thread = threading.Thread(
            target=self._monitor, daemon=True, name="fleet-supervisor",
        )
        self._thread.start()
        return self.router

    def shutdown(self) -> None:
        """Stop monitoring, drain the router (remote drains ask each
        child to shut down), then reap every child — SIGKILLing any
        that outlive the drain grace. Idempotent. A clean shutdown
        CLEARS the durable resume store: requests in flight completed
        or failed structurally through the drain, so leftovers would
        only ever mis-resume a future unrelated request — the store's
        contract is "an entry means a crash" (docs/scale-out.md
        "Durable snapshots")."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30)
        with self._lock:
            if self.router is not None:
                self.router.shutdown()
            for slot in self._slots:
                rep = slot.replica
                proc = rep.proc if rep is not None else None
                if proc is None:
                    continue
                try:
                    proc.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait(timeout=10)
            # Fenced hosts' children were deliberately left unkilled
            # (unreachable in production); locally they must not
            # outlive the fleet. SIGKILL lands on SIGSTOPped zombies
            # too.
            for proc in self._zombies:
                if proc.poll() is None:
                    proc.kill()
                    try:
                        proc.wait(timeout=10)
                    except subprocess.TimeoutExpired:  # pragma: no cover
                        pass
            self._zombies.clear()
            self.launcher.reap()
            if self._store is not None:
                from triton_distributed_tpu.models.kv_tier import SNAP_KIND

                self._store.clear(SNAP_KIND)
                with self._snap_lock:
                    self._resume.clear()
                self._store_keys.clear()

    # -- sync hooks (tests, bench) -----------------------------------------

    def wait_for(self, predicate, timeout_s: float = 30.0,
                 poll_s: float = 0.02) -> bool:
        """Deadline-poll ``predicate()`` — the chaos suite's
        synchronization primitive (condition waits, not sleeps)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if predicate():
                return True
            time.sleep(poll_s)
        return bool(predicate())

    def wait_healthy(self, n: int | None = None,
                     timeout_s: float = 60.0) -> bool:
        """Block until ``n`` (default: every non-parked slot) replicas
        are healthy in the router's rotation."""

        def healthy() -> int:
            return sum(
                1 for s in self._slots
                if s.replica is not None and s.replica.state == HEALTHY
            )

        want = n if n is not None else sum(
            1 for s in self._slots if not s.parked
        )
        return self.wait_for(lambda: healthy() >= want, timeout_s)

    def slot(self, name: str) -> _Slot:
        for s in self._slots:
            if s.spec.name == name:
                return s
        raise KeyError(f"no slot named {name!r}")

    # -- elastic slots (serving/autoscaler.py) ------------------------------

    def pool_slots(self, role: str) -> list[dict]:
        """Snapshot of every slot whose spec carries ``role`` — the
        autoscaler's view of one pool (park/drain/respawn state per
        slot), decoupled from the router's rotation."""
        with self._lock:
            rows = []
            for s in self._slots:
                if getattr(s.spec, "role", "mixed") != role:
                    continue
                rep = s.replica
                rows.append({
                    "name": s.spec.name,
                    "parked": s.parked,
                    "down": rep is None,
                    "host": getattr(s.spec, "host", None),
                    "replica_name": (rep.name if rep is not None
                                     else s.last_name),
                    "replica_state": (rep.state if rep is not None
                                      else None),
                    "pending": rep.pending if rep is not None else 0,
                })
            return rows

    def add_slot(self, spec: ReplicaSpec) -> RemoteReplica:
        """Grow the fleet by one slot at runtime — the autoscaler's
        scale-up path, riding the same spawn/handshake machinery as
        boot. The child joins the router the moment it binds, and from
        then on the monitor heartbeats/respawns/parks the new slot
        exactly like a boot-time one. Raises :class:`SpawnError` (the
        fleet is unchanged) when the child never binds."""
        with self._lock:
            if any(s.spec.name == spec.name for s in self._slots):
                raise ValueError(f"slot {spec.name!r} already exists")
            if getattr(spec, "host", None) is None:
                # Spread-aware placement (docs/scale-out.md
                # "Multi-host fleet"): with ≥2 hosts up, scale-up goes
                # to the host carrying the fewest replicas of this
                # role — the autoscaler must not stack a pool onto one
                # failure domain. Hostless launchers return None and
                # placement stays flat.
                picked = self._pick_host(
                    role=getattr(spec, "role", "mixed")
                )
                if picked is not None:
                    spec.host = picked
            slot = _Slot(spec=spec)
            rep = self._spawn(slot)
            slot.replica = rep
            slot.last_name = rep.name
            self._slots.append(slot)
            if self.router is not None:
                self.router.add_replica(rep)
            obs_events.emit(
                "slot_added", slot=spec.name, replica=rep.name,
                role=getattr(spec, "role", "mixed"), pid=rep.pid,
            )
        # Outside the lock: the broadcast is N wire calls and must not
        # hold the monitor off while they run.
        self._broadcast_tier_peers()
        return rep

    def retire_slot(self, name: str) -> bool:
        """Remove one slot from supervision — the autoscaler's
        scale-down path, called AFTER ``Router.drain_replica`` moved
        the replica off rotation (its unfinished slots handed off
        losslessly). Reaps the child process (the remote drain already
        asked it to exit) and drops the slot's monitor/snapshot/cursor
        state; the drained replica entry stays in the router so its
        lifetime totals keep aggregating. Returns False for an unknown
        slot."""
        with self._lock:
            for i, s in enumerate(self._slots):
                if s.spec.name == name:
                    slot = self._slots.pop(i)
                    break
            else:
                return False
            rep = slot.replica
            proc = rep.proc if rep is not None else None
            if proc is not None and proc.poll() is None:
                try:
                    proc.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait(timeout=10)
            with self._snap_lock:
                self._snaps.pop(name, None)
            with self._cursor_lock:
                self._event_cursors.pop(name, None)
            obs_events.emit(
                "slot_retired", slot=name,
                replica=rep.name if rep is not None else slot.last_name,
            )
        self._broadcast_tier_peers()
        return True

    def stats(self) -> dict:
        """The supervisor ledger (per-slot generation/parked/failure
        state) — surfaced by the fleet bench and debuggable from a
        REPL; the scrape path is the tdt_supervisor_* series."""
        return {
            "slots": [
                {
                    "name": s.spec.name,
                    "role": getattr(s.spec, "role", "mixed"),
                    "host": getattr(s.spec, "host", None),
                    "generation": s.generation,
                    "respawns": s.respawns,
                    "parked": s.parked,
                    "state": (s.replica.state if s.replica is not None
                              else "down"),
                    "pid": (s.replica.pid if s.replica is not None
                            else None),
                    "last_failure": s.last_failure,
                }
                for s in self._slots
            ],
            "hosts": self.host_stats(),
            "log_dir": self.log_dir,
        }

    # -- fleet-scope telemetry (docs/scale-out.md) --------------------------

    def fleet_metrics(self) -> dict:
        """ONE scrape for the whole fleet: fan the ``metrics`` verb
        out to every live child, merge the expositions with a
        ``replica`` label (``obs.metrics.merge_expositions``), and
        include THIS process's registry as ``replica="router"`` — the
        front tier's own tdt_router_*/tdt_server_*/tdt_slo_* series.
        Each child's counters stay distinct series, so summing across
        the replica label reproduces the children's own scrapes
        exactly (tested). Unreachable children land in ``errors``
        instead of failing the scrape — a fleet with a crashed replica
        is precisely when you want the survivors' numbers. Children
        are scraped serially (worst case N × the per-child timeout):
        fine at this supervisor's single-host fleet sizes; fan the
        calls out on threads before pointing it at a big fleet."""
        from triton_distributed_tpu.obs.metrics import merge_expositions

        parts: dict[str, str] = {"router": obs_metrics.prometheus_text()}
        errors: dict[str, str] = {}
        for slot in self._slots:
            rep = slot.replica
            if rep is None:
                errors[slot.spec.name] = slot.last_failure or "down"
                continue
            remote = getattr(rep, "_remote", None)
            if remote is None:
                continue  # in-process replica: already in the registry
            try:
                resp = remote.call(
                    {"cmd": "metrics"},
                    timeout=max(self.heartbeat_timeout_s * 4, 2.0),
                )
                err = resp.get("error")
                if err is not None:
                    raise RuntimeError(str(err))
                parts[rep.name] = str(resp.get("prometheus") or "")
            except Exception as e:  # noqa: BLE001 — scrape survivors
                errors[rep.name] = f"{type(e).__name__}: {e}"
        merged = merge_expositions(parts, label="replica")
        return {
            "prometheus": merged,
            "replicas": [n for n in parts if n != "router"],
            "errors": errors,
        }

    def fleet_events(self, limit: int | None = None) -> dict:
        """ONE event stream for the whole fleet: tail every child's
        ring (per-child cursors persist across calls, so repeated
        scrapes page forward drop-aware) plus this process's own ring,
        tag each event with its ``replica``, and stitch them into one
        ``fleet_seq`` order. Events are merged by their monotonic
        stamps — CLOCK_MONOTONIC is system-wide on a host, and the
        fleet is single-host by construction (the supervisor spawned
        the children), so cross-process ordering by ``t`` is sound.
        ``limit`` bounds each SOURCE's page, not the merged total.

        No ``kind`` filter, deliberately: the cursors are SHARED state
        — a kind-filtered pull would advance them past every
        other-kind event with ``dropped=0``, silently hiding those
        events from all later scrapes. Consumers filter the merged
        rows client-side; likewise the stream assumes ONE logical
        consumer (two independent fleet tailers steal from each
        other)."""
        from triton_distributed_tpu.obs import events as _events

        with self._scrape_lock:  # serialize scrapes; respawn never
            rows: list[dict] = []  # takes this lock (see __init__)
            dropped = 0
            errors: dict[str, str] = {}
            for slot in self._slots:
                rep = slot.replica
                if rep is None:
                    # Same visibility rule as fleet_metrics: a down
                    # child's ABSENT events must read as "down", not
                    # as "nothing happened" — this is exactly the
                    # crash window whose events an operator needs.
                    errors[slot.spec.name] = slot.last_failure or "down"
                    continue
                remote = getattr(rep, "_remote", None)
                if remote is None:
                    continue
                with self._cursor_lock:
                    since = self._event_cursors.get(slot.spec.name, 0)
                payload: dict = {"cmd": "events", "since": since}
                if limit is not None:
                    payload["limit"] = limit
                try:
                    resp = remote.call(
                        payload,
                        timeout=max(self.heartbeat_timeout_s * 4, 2.0),
                    )
                    err = resp.get("error")
                    if err is not None:
                        raise RuntimeError(str(err))
                except Exception as e:  # noqa: BLE001 — scrape survivors
                    errors[rep.name] = f"{type(e).__name__}: {e}"
                    continue
                with self._cursor_lock:
                    self._event_cursors[slot.spec.name] = int(
                        resp.get("next_since", since)
                    )
                dropped += int(resp.get("dropped", 0) or 0)
                for e in resp.get("events", []):
                    if isinstance(e, dict):
                        e = dict(e)
                        e["replica"] = rep.name
                        rows.append(e)
            ring = _events.default_ring()
            with self._cursor_lock:
                since = self._event_cursors.get("__local__", 0)
            evts, d = ring.tail(since, limit)
            dropped += d
            with self._cursor_lock:
                self._event_cursors["__local__"] = (
                    evts[-1].seq if evts else since + d
                )
            for e in evts:
                row = e.as_dict()
                row["replica"] = "router"
                rows.append(row)
            rows.sort(key=lambda e: e.get("t") or 0.0)
            with self._cursor_lock:
                base = self._fleet_seq
                self._fleet_seq += len(rows)
        for i, e in enumerate(rows):
            e["fleet_seq"] = base + i + 1
        return {"events": rows, "dropped": dropped, "errors": errors}

    # -- monitor -----------------------------------------------------------

    def _monitor(self) -> None:
        while not self._stop.is_set():
            with self._lock:
                if self._stop.is_set():
                    break
                self._tick()
            self._stop.wait(self.heartbeat_s)

    def _tick(self) -> None:
        now = time.monotonic()
        if self.snapshot_s and now >= self._next_snap_t:
            self._next_snap_t = now + self.snapshot_s
            self._pull_snapshots()
        # Two phases: collect every slot's failure VERDICT first, act
        # second — so failures sharing a host classify as one
        # correlated host_down instead of N independent timeouts
        # (docs/scale-out.md "Multi-host fleet"). Hostless slots act
        # exactly as before.
        verdicts: list[tuple[_Slot, str, str]] = []
        for slot in self._slots:
            if slot.parked:
                continue
            rep = slot.replica
            if rep is None:
                if (slot.next_respawn_t is not None
                        and now >= slot.next_respawn_t):
                    self._respawn(slot)
                continue
            if rep.state in (DRAINING, DRAINED):
                continue  # an operator drain is not a failure
            rc = rep.proc.poll() if rep.proc is not None else None
            if rep.state == DEAD:
                # The router/wire path saw it first (recv EOF, RST,
                # garble, or a router-observed request timeout): the
                # orphans are already re-routed; classify for the
                # ledger and move to respawn. A socket-level batch
                # failure on a live process is a `conn` (the wire
                # broke), not a `hung_request` (only a router timeout
                # earns that).
                err = rep.last_error or "router marked dead"
                if rc is not None:
                    kind = "exit"
                elif err.startswith(("wire failure",
                                     "malformed remote response",
                                     "remote")):
                    kind = "conn"
                else:
                    kind = "hung_request"
                verdicts.append((slot, kind, err))
            elif rc is not None:
                verdicts.append(
                    (slot, "exit", f"process exited rc={rc}")
                )
            else:
                v = self._heartbeat(slot, now)
                if v is not None:
                    verdicts.append((slot, v[0], v[1]))
        if verdicts:
            self._classify(verdicts)

    def _classify(self, verdicts: list) -> None:
        """Act on this tick's failure verdicts, folding same-host
        failures into ONE ``host_down``. A verdict on a hosted slot
        with live siblings triggers an immediate out-of-band probe of
        each sibling — all siblings failing inside the same window is
        a machine, not a process; any sibling answering means the
        failures are independent and classify as before."""
        vmap = {id(s): (k, w) for s, k, w in verdicts}
        handled: set[int] = set()
        for slot, kind, why in verdicts:
            if id(slot) in handled:
                continue
            host = getattr(slot.spec, "host", None)
            # The launcher's own liveness view is authoritative when
            # it has one (an ssh launcher can ping the machine; the
            # fake launcher knows what it took down) — it settles the
            # machine-vs-process call even for a host with a single
            # replica, where sibling corroboration has no one to ask.
            launcher_down = (host is not None
                             and not self.launcher.host_up(host))
            siblings = [
                s for s in self._slots
                if s is not slot and not s.parked
                and getattr(s.spec, "host", None) == host
            ] if host is not None else []
            if not siblings and not launcher_down:
                handled.add(id(slot))
                self._fail(slot, kind, why)
                continue
            corroborated = [(slot, kind, why)]
            all_down = True
            for sib in siblings:
                v = vmap.get(id(sib))
                if v is None:
                    v = self._probe_sibling(sib)
                if v is None:
                    if launcher_down:
                        v = ("down", "launcher reports host down")
                    else:
                        all_down = False
                        break
                corroborated.append((sib, v[0], v[1]))
            if all_down:
                for s, _, _ in corroborated:
                    handled.add(id(s))
                self._declare_host_down(host, corroborated)
            else:
                handled.add(id(slot))
                self._fail(slot, kind, why)

    def _probe_sibling(self, slot: _Slot):
        """Out-of-band corroboration probe for correlated-failure
        classification: does this same-host sibling ALSO look gone
        right now? Returns a (kind, why) verdict, or None while the
        sibling still answers. One failed probe corroborates here even
        below ``heartbeat_misses`` — the sibling is not being declared
        on its own, it is tie-breaking a machine-vs-process call."""
        rep = slot.replica
        if rep is None:
            # Already down — but only a RECENT fall corroborates "the
            # machine died"; an old independent crash (mid-backoff)
            # must not upgrade a sibling's process failure into a
            # host_down.
            last = slot.crash_times[-1] if slot.crash_times else None
            window = max(
                self.heartbeat_s * self.heartbeat_misses,
                self.heartbeat_timeout_s,
            )
            if (last is not None
                    and time.monotonic() - last <= window):
                return ("down", slot.last_failure or "already down")
            return None
        if rep.state in (DRAINING, DRAINED):
            return None  # deliberately out of rotation, not a casualty
        rc = rep.proc.poll() if rep.proc is not None else None
        if rc is not None:
            return ("exit", f"process exited rc={rc}")
        if rep.state == DEAD:
            return ("conn", rep.last_error or "router marked dead")
        try:
            resp = rep.healthz(timeout=self.heartbeat_timeout_s)
            if resp.get("ok"):
                return None
            return ("conn", f"healthz answered {resp!r}")
        except Exception as e:  # noqa: BLE001 — timeout or refusal,
            # either way the host claim is corroborated
            return ("conn", f"{type(e).__name__}: {e}")

    def _declare_host_down(self, host: str, items: list) -> None:
        """One whole-host failure, end to end: bump the fence epoch,
        emit a SINGLE ``host_down`` event, fence + fail every affected
        slot (their reroutes all land this tick — the parallel part),
        and re-place their respawns onto surviving hosts."""
        st = self._hosts.setdefault(
            host, {"down": False, "epoch": 0, "crash_times": []}
        )
        already = st["down"]
        st["down"] = True
        st["epoch"] += 1
        if not already:
            self._m_host_down.inc(host=host)
            self._g_host_up.set(0.0, host=host)
            obs_events.emit(
                "host_down", host=host, epoch=st["epoch"],
                slots=[s.spec.name for s, _, _ in items],
                reasons={s.spec.name: f"{k}: {str(w)[:120]}"
                         for s, k, w in items},
            )
        for slot, kind, why in items:
            if slot.replica is not None:
                # _fail → _record_failure sees the host down and
                # re-places the slot (spawn failover); already-down
                # siblings fail over when their next respawn attempt
                # is refused.
                self._fail(
                    slot, "host_down",
                    f"host {host} down ({kind}: {why})",
                    unreachable=True,
                )

    def _failover_placement(self, slot: _Slot, from_host: str) -> None:
        """Re-place a slot whose host is gone onto the next surviving
        host (spawn FAILOVER). With nowhere to go the spec keeps its
        host — respawns against it are refused and the crash-loop
        breaker eventually parks the slot."""
        nxt = self._pick_host(
            role=getattr(slot.spec, "role", "mixed"),
            exclude={from_host},
        )
        if nxt is None or nxt == slot.spec.host:
            return
        slot.spec.host = nxt
        self._m_failovers.inc(slot=slot.spec.name)
        obs_events.emit(
            "spawn_failover", slot=slot.spec.name,
            from_host=from_host, to_host=nxt,
        )

    def _pick_host(self, *, role: str = "mixed",
                   exclude: set | None = None) -> str | None:
        """Least-loaded UP host for placing ``role`` — ties broken by
        total slot count, then name (deterministic). None when the
        launcher has no host notion or nothing is up."""
        exclude = exclude or set()
        up = [
            h for h in dict.fromkeys(
                list(self.launcher.hosts()) + list(self._hosts)
            )
            if h not in exclude
            and not self._hosts.get(h, {}).get("down")
            and self.launcher.host_up(h)
        ]
        if not up:
            return None

        def load(h: str) -> tuple:
            mine = [
                s for s in self._slots
                if getattr(s.spec, "host", None) == h and not s.parked
            ]
            in_role = sum(
                1 for s in mine
                if getattr(s.spec, "role", "mixed") == role
            )
            return (in_role, len(mine), h)

        return min(up, key=load)

    def mark_host_down(self, host: str) -> None:
        """Operator/ chaos hook: declare ``host`` down out-of-band.
        Spawns and placement refuse it until :meth:`revive_host`;
        live replicas on it classify through the normal monitor
        path."""
        st = self._hosts.setdefault(
            str(host), {"down": False, "epoch": 0, "crash_times": []}
        )
        if not st["down"]:
            st["down"] = True
            st["epoch"] += 1
            self._g_host_up.set(0.0, host=str(host))
            obs_events.emit("host_down", host=str(host),
                            epoch=st["epoch"], slots=[], operator=True)

    def revive_host(self, host: str) -> None:
        """Bring a down host back into placement. Its fence epoch
        stays bumped: anything fenced under the old epoch stays
        fenced — only NEW generations spawn there."""
        st = self._hosts.get(str(host))
        if st is not None and st["down"]:
            st["down"] = False
            st["crash_times"] = []
            self._g_host_up.set(1.0, host=str(host))
            obs_events.emit("host_revived", host=str(host),
                            epoch=st["epoch"])

    def host_stats(self) -> dict:
        """The host ledger (down/epoch/slot placement), for benches
        and debugging; the scrape path is tdt_host_up /
        tdt_supervisor_host_down_total."""
        return {
            h: {
                "down": st["down"],
                "epoch": st["epoch"],
                "slots": [
                    s.spec.name for s in self._slots
                    if getattr(s.spec, "host", None) == h
                ],
            }
            for h, st in self._hosts.items()
        }

    def _pull_snapshots(self) -> None:
        """One snapshot sweep: replace each healthy slot's snapshot
        map with the child's current buffer. Wholesale replacement IS
        the pruning (finished tickets drop out); a failed pull keeps
        the PREVIOUS map — stale beats empty, and a stale resume can
        only latch-lose or degrade to replay."""
        for slot in self._slots:
            rep = slot.replica
            if rep is None or rep.state != HEALTHY:
                continue
            exporter = getattr(rep, "export_slots", None)
            if exporter is None:
                continue
            try:
                snaps = exporter(timeout=self.heartbeat_timeout_s)
                if not isinstance(snaps, dict):
                    raise TypeError(
                        f"export_slots answered {type(snaps).__name__}"
                    )
            except Exception as e:  # noqa: BLE001 — best-effort feed,
                # but VISIBLY so: a permanently wedged exporter would
                # otherwise silently downgrade every recovery to
                # replay with nothing on any dashboard.
                self._m_pull_failures.inc(replica=slot.spec.name)
                obs_events.emit(
                    "snapshot_pull_failed", slot=slot.spec.name,
                    replica=rep.name,
                    reason=f"{type(e).__name__}: {str(e)[:160]}",
                )
                continue
            with self._snap_lock:
                self._snaps[slot.spec.name] = snaps
            self._persist_snaps(slot.spec.name, snaps)

    def _persist_snaps(self, slot_name: str, snaps: dict) -> None:
        """Write-through one slot's pulled snapshots to the durable
        resume store (no-op without ``resume_dir``); entries whose
        ticket finished since the last pull are deleted — the store
        mirrors the child's live buffer, so restart leftovers are
        exactly the in-flight set at the moment of death."""
        if self._store is None:
            return
        from triton_distributed_tpu.models.kv_tier import SNAP_KIND

        prev = self._store_keys.get(slot_name, set())
        for tid, snap in snaps.items():
            if isinstance(snap, dict):
                self._store.put(SNAP_KIND, tid, snap)
        self._store_keys[slot_name] = set(snaps)
        for tid in prev - set(snaps):
            # "Finished" from THIS slot's view — but a ticket that
            # MIGRATED carries its id to another slot, and deleting
            # here would remove the live copy that slot just
            # persisted. Only prune ids no slot claims. (Runs on the
            # monitor thread only, like every _store_keys access.)
            if any(tid in keys for s, keys in self._store_keys.items()
                   if s != slot_name):
                continue
            self._store.delete(SNAP_KIND, tid)

    def _snapshot_for(self, ticket) -> dict | None:
        """Router snapshot-provider hook (``Router.snapshot_provider``):
        the last pulled snapshot for a re-routed ticket, from whichever
        slot published it. Runs on router/replica worker threads."""
        with self._snap_lock:
            items = list(self._snaps.items())
        for name, snaps in items:
            snap = snaps.get(ticket.tid)
            if snap is not None:
                self._m_resumes.inc(replica=name)
                obs_events.emit(
                    "snapshot_resume", slot=name, ticket=ticket.tid,
                    tokens=(len(snap.get("out") or [])
                            if isinstance(snap, dict) else 0),
                )
                return snap
        # Restart resume (docs/scale-out.md "Durable snapshots"):
        # ticket ids do not survive a supervisor restart, so leftovers
        # loaded from ``resume_dir`` match by (prompt, gen_len) digest
        # instead. Popped on use — a snapshot resumes exactly one
        # re-submitted request; the target validates it (prompt
        # equality / geometry) and degrades to replay if stale.
        if self._resume:
            from triton_distributed_tpu.models.kv_tier import (
                SNAP_KIND,
                request_digest,
            )

            digest = request_digest(ticket.prompt, ticket.gen_len)
            with self._snap_lock:
                entry = self._resume.pop(digest, None)
            if entry is not None:
                tid, snap = entry
                if self._store is not None:
                    self._store.delete(SNAP_KIND, tid)
                self._m_resumes.inc(replica="resume")
                obs_events.emit(
                    "snapshot_resume", slot="resume", ticket=ticket.tid,
                    tokens=len(snap.get("out") or []), restart=True,
                )
                return snap
        return None

    def _heartbeat(self, slot: _Slot,
                   now: float) -> tuple[str, str] | None:
        """One heartbeat probe. Returns the failure VERDICT (kind,
        why) instead of acting on it — _classify folds same-host
        verdicts into a correlated host_down; None means healthy (or
        not yet enough misses for a verdict)."""
        rep = slot.replica
        try:
            resp = rep.healthz(timeout=self.heartbeat_timeout_s)
            if not resp.get("ok"):
                raise ConnectionError(f"healthz answered {resp!r}")
            slot.last_beat_t = time.monotonic()
            slot.missed_beats = 0
            self._g_beat_age.set(0.0, replica=slot.spec.name)
            if (resp.get("state") == "shutting_down"
                    and rep.state == HEALTHY):
                # An externally-initiated drain (an operator sent the
                # child {"cmd": "shutdown"} directly): take the
                # replica out of rotation as a DRAIN, not a crash —
                # routing another batch into it would be refused and
                # misread as a failure, burning crash-loop budget on
                # a voluntary exit.
                rep.begin_drain()
                if self.router is not None:
                    self.router._refresh_healthy()
                obs_events.emit(
                    "replica_drain", replica=rep.name,
                    slot=slot.spec.name, external=True,
                )
            return None
        except Exception as e:  # noqa: BLE001 — every flavor classifies
            age = (time.monotonic() - slot.last_beat_t
                   if slot.last_beat_t is not None else float("inf"))
            self._g_beat_age.set(
                min(age, 9e6), replica=slot.spec.name
            )
            # `socket.timeout` is `TimeoutError` on modern Pythons;
            # keep both spellings for makefile-surfaced reads.
            timeout_like = isinstance(e, (socket.timeout, TimeoutError))
            rc = rep.proc.poll() if rep.proc is not None else None
            if rc is not None:
                kind, why = "exit", f"process exited rc={rc}"
            elif timeout_like:
                slot.missed_beats += 1
                if slot.missed_beats < self.heartbeat_misses:
                    return None  # not yet a verdict — next tick retries
                kind, why = "heartbeat_timeout", (
                    f"{slot.missed_beats} consecutive beats missed "
                    f"(deadline {self.heartbeat_timeout_s}s, "
                    f"age {age:.2f}s)"
                )
            else:
                kind, why = "conn", f"{type(e).__name__}: {e}"
            return kind, why

    def _fail(self, slot: _Slot, kind: str, reason: str, *,
              unreachable: bool = False) -> None:
        """One replica failure, end to end: mark dead through the
        router's re-route path, make sure the process is gone, then
        schedule (or refuse) the respawn. ``unreachable`` is the
        host_down shape: the machine cannot be reached, so instead of
        killing the process (impossible out there, and locally it
        would hide the zombie case) the replica is EPOCH-FENCED — any
        result its process ever produces again latches nothing."""
        rep = slot.replica
        if unreachable:
            host = getattr(slot.spec, "host", None)
            epoch = self._hosts.get(host, {}).get("epoch")
            if hasattr(rep, "fence"):
                rep.fence(epoch)
        if rep.state != DEAD:
            orphans = rep.mark_unhealthy(f"supervisor: {kind}: {reason}")
            if self.router is not None:
                # The existing thread-replica failure path: every
                # orphaned ticket re-routes latch-first; the wire
                # ticket-id dedup makes the overlap with any
                # still-in-flight remote batch harmless.
                self.router._on_replica_failure(rep, orphans)
        if rep.proc is not None and rep.proc.poll() is None:
            if unreachable:
                self._zombies.append(rep.proc)
            else:
                rep.proc.kill()
                try:
                    rep.proc.wait(timeout=10)
                except subprocess.TimeoutExpired:  # pragma: no cover
                    pass
        obs_events.emit(
            "replica_proc_failed", replica=rep.name,
            slot=slot.spec.name, failure=kind, reason=str(reason)[:200],
        )
        slot.replica = None  # the router retires it on replace
        slot.last_beat_t = None
        self._record_failure(slot, kind, reason)

    def _record_failure(self, slot: _Slot, kind: str,
                        reason: str) -> None:
        """Crash bookkeeping shared by monitor failures and failed
        spawns: counter, crash-loop window, park-or-backoff."""
        self._m_failures.inc(replica=slot.spec.name, kind=kind)
        slot.last_failure = f"{kind}: {str(reason)[:200]}"
        now = time.monotonic()
        slot.crash_times = [
            t for t in slot.crash_times if now - t <= self.crash_window_s
        ] + [now]
        slot.fails_in_a_row += 1
        host = getattr(slot.spec, "host", None)
        if host is not None:
            # Per-host crash-loop breaker: a host eating failures
            # across ITS slots faster than any single slot would park
            # is a bad machine — stop placing there before every slot
            # burns its own budget. (Double the per-slot budget: one
            # flapping slot alone must not condemn its host.)
            st = self._hosts.setdefault(
                host, {"down": False, "epoch": 0, "crash_times": []}
            )
            st["crash_times"] = [
                t for t in st["crash_times"]
                if now - t <= self.crash_window_s
            ] + [now]
            if (not st["down"]
                    and len(st["crash_times"]) >= 2 * self.crash_limit):
                st["down"] = True
                st["epoch"] += 1
                self._m_host_down.inc(host=host)
                self._g_host_up.set(0.0, host=host)
                obs_events.emit(
                    "host_down", host=host, epoch=st["epoch"],
                    breaker=True,
                    crashes=len(st["crash_times"]),
                    window_s=self.crash_window_s,
                )
            if kind == "spawn" or st["down"]:
                # Spawn FAILOVER: a host that failed (or refused) the
                # spawn gets this slot re-placed on the next up host;
                # the pending backoff still applies, so the re-placed
                # spawn happens "under backoff", not immediately.
                self._failover_placement(slot, host)
        if len(slot.crash_times) >= self.crash_limit:
            slot.parked = True
            slot.next_respawn_t = None
            self._g_parked.set(
                sum(1 for s in self._slots if s.parked)
            )
            obs_events.emit(
                "replica_parked", slot=slot.spec.name,
                crashes=len(slot.crash_times),
                window_s=self.crash_window_s, last=slot.last_failure,
            )
            return
        backoff = min(
            self.respawn_backoff_s * (2 ** (slot.fails_in_a_row - 1)),
            self.max_backoff_s,
        )
        slot.next_respawn_t = now + backoff

    def _respawn(self, slot: _Slot) -> None:
        slot.generation += 1
        try:
            rep = self._spawn(slot)
        except SpawnError as e:
            slot.generation -= 1
            self._record_failure(slot, "spawn", str(e))
            return
        slot.replica = rep
        slot.respawns += 1
        # The dead child's orphans were already resumed (or replayed);
        # its snapshots must not outlive it into the fresh generation.
        with self._snap_lock:
            self._snaps.pop(slot.spec.name, None)
        # A fresh child's event ring restarts at seq 1: the dead
        # generation's cursor would make every event below it
        # invisible to the fleet stream (with dropped=0) until the new
        # ring caught up — exactly the crash-recovery events an
        # operator needs most. _cursor_lock, NOT _scrape_lock: a slow
        # fleet scrape must never stall a respawn.
        with self._cursor_lock:
            self._event_cursors.pop(slot.spec.name, None)
        slot.fails_in_a_row = 0  # a successful bind resets the backoff
        slot.missed_beats = 0
        slot.next_respawn_t = None
        if self.router is not None:
            if slot.last_name is not None:
                # Retire the predecessor this slot actually joined as.
                self.router.replace_replica(slot.last_name, rep)
            else:
                # The slot never came up (initial spawn failed): grow
                # the rotation instead.
                self.router.add_replica(rep)
        slot.last_name = rep.name
        self._m_respawns.inc(replica=slot.spec.name)
        obs_events.emit(
            "replica_respawn", replica=rep.name, slot=slot.spec.name,
            generation=slot.generation, pid=rep.pid,
        )
        self._broadcast_tier_peers()

    def _broadcast_tier_peers(self) -> None:
        """Best-effort KV-fabric (re)wiring (docs/scale-out.md "KV
        fabric"): tell every live child who its peers are via the
        ``tier_peers`` verb, so each engine's ``FabricClient`` can
        pull tier entries its neighbors spilled. Called after every
        membership change; failures (and children without a fabric —
        their server answers ``bad_request``) are skipped, never
        fatal: a child that missed a broadcast keeps its last peer set
        and pays at most one cooldown per dead peer."""
        if not self.tier_fabric:
            return
        live = []
        for slot in self._slots:
            rep = slot.replica
            remote = (getattr(rep, "_remote", None)
                      if rep is not None else None)
            if remote is not None and rep.state == "healthy":
                live.append((slot, rep, remote))
        for _, rep, remote in live:
            peers = []
            for oslot, o, orem in live:
                if o is rep:
                    continue
                # Routable addressing: a child that bound the
                # wildcard (0.0.0.0) without advertising is reachable
                # only through its spec's host name; the port-file /
                # handshake address is authoritative otherwise.
                h = orem.host
                if (h in ("", "0.0.0.0")
                        and getattr(oslot.spec, "host", None)):
                    h = oslot.spec.host
                peers.append(
                    {"name": o.name, "host": h, "port": orem.port}
                )
            try:
                remote.call(
                    {"cmd": "tier_peers", "peers": peers},
                    timeout=max(self.heartbeat_timeout_s, 1.0),
                )
            except Exception as e:  # noqa: BLE001 — best-effort wiring
                obs_events.emit(
                    "fabric_wire_failed", replica=rep.name,
                    reason=f"{type(e).__name__}: {e}"[:160],
                )

    def _spawn(self, slot: _Slot) -> RemoteReplica:
        host = getattr(slot.spec, "host", None)
        if host is not None and self._hosts.get(host, {}).get("down"):
            # Epoch fence, spawn side: a host declared dead takes no
            # placements — a zombie machine that thaws cannot rejoin
            # under its stale generation; only revive_host (operator)
            # reopens it.
            st = self._hosts[host]
            raise SpawnError(
                f"replica {slot.spec.name}: host {host} is marked "
                f"down (fence epoch {st['epoch']}); spawn refused"
            )
        rep = self.launcher.spawn(
            slot.spec, generation=slot.generation,
            spawn_timeout_s=self.spawn_timeout_s,
            max_pending=self.replica_max_pending,
            log_dir=self.log_dir,
            connect_timeout_s=self.connect_timeout_s,
        )
        if getattr(slot.spec, "host", None) is not None:
            self._hosts.setdefault(
                slot.spec.host,
                {"down": False, "epoch": 0, "crash_times": []},
            )
        return rep
