"""One engine replica behind a worker thread (the scale-out unit).

The multi-engine serving tier (docs/scale-out.md) replicates the
continuous-batching engine N times behind a prefix-affinity router
(``serving/router.py``). This module is the replica half: ONE
:class:`~triton_distributed_tpu.models.continuous.ContinuousEngine`
owned by ONE worker thread, fed through a queue of :class:`Ticket`\\ s.
The engine itself is single-threaded by design (host-side slot/pool
bookkeeping); the replica boundary is what makes N of them safely
concurrent — no engine state is ever touched from outside its worker.

Lifecycle (one-way: replicas are cattle, not pets)::

    healthy ──drain()──▶ draining ──queue empties──▶ drained
       │
       └─ engine.run raises / injected ``replica.run`` fault /
          router-observed timeout ──▶ dead

A ``dead`` or ``draining`` replica refuses new tickets; whatever was
queued (and, on death, the in-flight batch) is handed to the router's
``on_failure`` callback for re-routing — requests are NEVER silently
dropped. The in-flight batch of a *timed-out* replica cannot be
aborted in-process; its late results latch harmlessly (a ticket keeps
its first result).

**Prefix view**: after every engine batch the worker re-publishes the
radix tree's :meth:`prefix_digest`, the router-side mirror affinity
routing scores against (``models/prefix_cache.py::digest_match_len``).
Publishing happens on the worker thread at batch boundaries, so the
router never reads live tree state across threads.
"""

from __future__ import annotations

import itertools
import os
import threading
import time

import numpy as np

from triton_distributed_tpu.models.continuous import Request, RequestResult
from triton_distributed_tpu.obs import events as obs_events
from triton_distributed_tpu.obs.timeline import Timeline
from triton_distributed_tpu.runtime.faults import fault_point

HEALTHY = "healthy"
DRAINING = "draining"
DRAINED = "drained"
DEAD = "dead"

# Core serving counters accumulated per replica across every batch it
# ever ran — ONE definition; the router's fleet aggregation iterates
# the same tuple, so a new key can't silently read 0 fleet-wide (the
# models/stats.py::CORE_STATS_KEYS lesson, applied to the tier).
FLEET_TOTAL_KEYS = (
    "decode_steps", "prefill_tokens", "generated_tokens",
    "prefix_hit_tokens", "migrated_in_tokens",
)

# Process-unique ticket ids. They ride the wire (`ticket_ids` payload
# key, echoed by the server) so a RemoteReplica matches results to
# tickets BY ID, never by position — and a re-dispatched ticket keeps
# its id across hops, which is what makes the at-least-once recovery
# path dedup-safe: whichever attempt finishes first latches, the loser
# is recognized by id and discarded (docs/scale-out.md "Process
# fleet"). The pid suffix keeps ids unique even across routers talking
# to one shared replica.
_TICKET_IDS = itertools.count(1)


class Ticket:
    """One routed request and its latched outcome.

    A ticket is the routing-independent *description* of a request
    (prompt, gen_len, sampling knobs, deadline) — NOT an engine
    ``Request``. Each dispatch builds a FRESH ``Request`` via
    :meth:`make_request`, because a dead replica's Request object
    carries a failed status and partial tokens that must not leak into
    the retry. The result latches first-write-wins: a late completion
    from a timed-out replica's still-running batch cannot overwrite
    the re-routed attempt's outcome (or vice versa — whoever finishes
    first wins, which is the at-least-once contract re-routing buys).
    """

    __slots__ = ("prompt", "gen_len", "temperature", "top_p", "top_k",
                 "deadline_s", "enqueue_t", "reroutes", "replica_history",
                 "result", "_event", "_lock", "_rerouted_from",
                 "last_dispatch_t", "_prompt_list", "tid", "snapshot",
                 "prefill_only", "on_token", "client_tid", "slo_class")

    def __init__(self, prompt, gen_len: int, *, temperature=None,
                 top_p=None, top_k=None, deadline_s=None, enqueue_t=None,
                 slo_class=None):
        self.tid = f"t{next(_TICKET_IDS)}p{os.getpid()}"
        self.prompt = np.asarray(prompt, np.int32)
        self.gen_len = int(gen_len)
        self.temperature = temperature
        self.top_p = top_p
        self.top_k = top_k
        self.deadline_s = deadline_s
        self.enqueue_t = enqueue_t
        # Priority class (PR 13's ``slo_class``): rides the ticket so
        # the pool scheduler can order and shed by class, and every
        # dispatch (local or wire) rebuilds the Request with it — a
        # migrated hop is judged under the SAME class it arrived with.
        self.slo_class = slo_class
        self.reroutes = 0
        # Replica names in dispatch order. Appended by
        # EngineReplica.submit UNDER the replica's lock, atomically
        # with enqueue — so any ticket found in a replica's queue
        # already names that replica as its last hop.
        self.replica_history: list[str] = []
        self.result: RequestResult | None = None
        self._event = threading.Event()
        self._lock = threading.Lock()
        self._rerouted_from: str | None = None
        # When the CURRENT hop was dispatched (set by submit): the
        # router's timeout watches per-hop time, not total wait — a
        # ticket rerouted mid-wait gives its new replica a full budget.
        self.last_dispatch_t: float | None = None
        self._prompt_list: list[int] | None = None
        # Slot migration (docs/scale-out.md "Slot migration &
        # handoff"): a portable snapshot of this request's in-flight
        # state, attached by a handoff drain, a prefill→decode
        # migration, or the supervisor's crash recovery. The next
        # dispatch RESUMES from it instead of re-prefilling.
        # ``prefill_only`` asks the target engine to export right
        # after admission (the migrate_after_prefill policy's first
        # hop).
        self.snapshot: dict | None = None
        self.prefill_only: bool = False
        # Streaming sink (docs/serving.md "Streaming & cancellation"):
        # ``on_token(index, token_id)`` fires per emitted token — on
        # the replica worker thread for in-process replicas, on frame
        # receipt for RemoteReplicas. Re-dispatches re-fire earlier
        # indices (at-least-once); the server's stream sink dedups by
        # index, so the wire sees each token once.
        self.on_token = None
        # The CLIENT's id for this request (None when it gave none).
        # Kept ALONGSIDE the generated ``tid``, never instead of it:
        # everything wire-side (result latching, frames, the child's
        # duplicate-id refusal) keys by the process-unique ``tid``, so
        # two payloads reusing one client id can be co-batched without
        # conflating — while ``EngineReplica.cancel`` matches either,
        # so the id a client holds still cancels end-to-end.
        self.client_tid: str | None = None

    @property
    def prompt_tokens(self) -> list[int]:
        """The prompt as a plain int list, converted ONCE — affinity
        scoring walks it against every replica's digest per routing
        decision."""
        if self._prompt_list is None:
            self._prompt_list = [int(t) for t in self.prompt]
        return self._prompt_list

    @classmethod
    def of(cls, req) -> "Ticket":
        """Build from an engine :class:`Request` (the server's form) or
        a ``(prompt, gen_len)`` tuple. A request's ``ticket_id`` rides
        as ``client_tid`` NEXT TO the generated process-unique ``tid``
        — cancellation matches either (``EngineReplica.cancel``), but
        the wire keys by ``tid`` alone, so a client id reused across
        concurrent payloads can never conflate two requests in one
        child batch (or get a healthy child's duplicate-id refusal
        read as a replica death)."""
        if isinstance(req, Request):
            tl = req.timeline
            t = cls(
                req.prompt, req.gen_len, temperature=req.temperature,
                top_p=req.top_p, top_k=req.top_k, deadline_s=req.deadline_s,
                enqueue_t=tl.enqueue_t if tl is not None else None,
                slo_class=getattr(req, "slo_class", None),
            )
            if req.ticket_id is not None:
                t.client_tid = str(req.ticket_id)
            t.on_token = req.on_token
            return t
        prompt, gen_len = req
        return cls(prompt, gen_len)

    def make_request(self) -> Request:
        """A fresh engine Request for one dispatch attempt. The
        timeline keeps the ORIGINAL enqueue stamp (queue-wait measures
        what the client experienced, re-routes included) and carries
        the reroute count for ``tdt_request_reroutes_total``."""
        tl = Timeline()
        tl.enqueue_t = self.enqueue_t
        tl.stamp_enqueue()  # no-op when enqueue_t already set (latched)
        tl.reroutes = self.reroutes
        return Request(
            self.prompt, self.gen_len, temperature=self.temperature,
            top_p=self.top_p, top_k=self.top_k, deadline_s=self.deadline_s,
            timeline=tl, snapshot=self.snapshot,
            prefill_only=self.prefill_only, ticket_id=self.tid,
            on_token=self.on_token, slo_class=self.slo_class,
        )

    def complete(self, result: RequestResult) -> bool:
        """Latch ``result``; True exactly once."""
        with self._lock:
            if self.result is not None:
                return False
            self.result = result
        self._event.set()
        return True

    def claim_reroute(self, source_name: str | None) -> bool:
        """Atomically claim the right to re-dispatch this ticket off
        ``source_name`` (its observed-failing replica). Exactly one
        claimant wins per hop: a latched result, a ticket already
        re-dispatched to a DIFFERENT replica, or a concurrent claim
        for the SAME hop (the timeout path racing the death callback)
        all lose — so a ticket can never be double-dispatched or
        guard-skipped into a silent hang. Increments ``reroutes`` on
        success."""
        with self._lock:
            if self.result is not None:
                return False
            if source_name is not None:
                if (self.replica_history
                        and self.replica_history[-1] != source_name):
                    return False  # already re-dispatched elsewhere
                if self._rerouted_from == source_name:
                    return False  # another thread claimed this hop
                self._rerouted_from = source_name
            self.reroutes += 1
            return True

    def wait(self, timeout: float | None = None) -> bool:
        return self._event.wait(timeout)

    def expired_hop(self, timeout_s: float) -> str | None:
        """Atomically judge the CURRENT hop: the replica name iff that
        replica has held this ticket longer than ``timeout_s``, else
        None. Name and stamp are read under the ticket lock (and
        written under it by ``submit``/batch start), so a reroute
        racing the expiry can never get the ticket's NEW healthy
        replica killed for the old hop's stale stamp."""
        with self._lock:
            if self.result is not None or not self.replica_history:
                return None
            t0 = self.last_dispatch_t
            if t0 is None or time.monotonic() < t0 + timeout_s:
                return None
            return self.replica_history[-1]


class EngineReplica:
    """One ContinuousEngine + its worker thread, with health state.

    The router talks to a replica ONLY through :meth:`submit`,
    :meth:`snapshot`, :meth:`match_len`, :meth:`drain`, and
    :meth:`mark_unhealthy` — the engine never escapes its worker
    thread. ``max_pending`` is the shed-aware routing bound: a replica
    whose queued+in-flight tickets reach it reports ``overloaded`` and
    the router skips it before the request would bounce off the
    engine's own admission shed (docs/scale-out.md).
    """

    # One engine batch admits at most this many tickets; the engine's
    # own admission loop interleaves them onto its decode slots.
    MAX_RUN_BATCH = 64

    def __init__(self, engine, name: str | None = None, *,
                 max_pending: int = 8, role: str = "mixed"):
        if not hasattr(engine, "run"):
            raise ValueError(
                "EngineReplica wraps a ContinuousEngine (needs .run); "
                f"got {type(engine).__name__}"
            )
        if role not in ("prefill", "decode", "mixed"):
            raise ValueError(
                f"role must be 'prefill', 'decode', or 'mixed', "
                f"got {role!r}"
            )
        self.engine = engine
        self.name = name if name is not None else f"replica-{id(engine):x}"
        # Pool role (docs/scale-out.md "Disaggregated pools &
        # autoscaling"): router-side placement metadata — the engine
        # behind a prefill replica is identical to a decode one, so
        # degraded fallback (serving end-to-end on either) stays legal.
        self.role = role
        self.max_pending = int(max_pending)
        self._cond = threading.Condition()
        self._queue: list[Ticket] = []
        self._current_batch: list[Ticket] = []
        self._state = HEALTHY
        self._inflight = 0
        self.last_error: str | None = None
        self.runs = 0          # engine batches completed
        self.served = 0        # tickets completed (any status)
        # Cumulative core serving counters across every batch this
        # replica ran (the engine zeroes its own stats per run; the
        # router's fleet-wide ``last_stats`` needs monotone numbers).
        self.totals = {k: 0 for k in FLEET_TOTAL_KEYS}
        # Router-installed failure callback: (replica, orphan_tickets).
        self.on_failure = None
        # Router-installed migration callback: (replica, tickets whose
        # batch exported them). Falls back to on_failure when unset —
        # both re-dispatch through the latch-first ticket machinery.
        self.on_migrate = None
        self._handoff = False
        self._digest_lock = threading.Lock()
        self._prefix_digest = None
        self._tier_digest = None
        self._digest_version: int | None = None
        self._publish_digest()
        self._thread = threading.Thread(
            target=self._worker, daemon=True, name=f"replica:{self.name}"
        )
        self._thread.start()

    # -- router-facing surface --------------------------------------------

    @property
    def state(self) -> str:
        return self._state

    @property
    def pending(self) -> int:
        """Tickets queued or in flight — the load signal balancing
        uses (the same number the engine mirrors into the PR 5
        pending/free-pages gauges, read replica-side)."""
        with self._cond:
            return len(self._queue) + self._inflight

    def _over(self, pending: int) -> bool:
        """ONE definition of the shed threshold — the routing property
        and stats snapshots must never disagree on it."""
        return pending >= self.max_pending

    @property
    def overloaded(self) -> bool:
        return self._over(self.pending)

    @property
    def free_pages(self) -> int:
        # Host-side list length: racy-but-benign as a load signal (the
        # worker mutates the free list mid-run); exact accounting
        # lives in the engine's own audit.
        return len(self.engine.pool.free)

    def submit(self, ticket: Ticket) -> bool:
        """Queue one ticket; False when the replica is not accepting
        work (the router picks another). The history append rides the
        SAME lock as the enqueue: a death that harvests this queue an
        instant later must see the ticket already naming this replica
        as its last hop, or the re-route claim would misread it as
        dispatched elsewhere and strand it."""
        with self._cond:
            if self._state != HEALTHY:
                return False
            with ticket._lock:  # atomic vs Ticket.expired_hop
                ticket.replica_history.append(self.name)
                ticket.last_dispatch_t = time.monotonic()
            self._queue.append(ticket)
            self._cond.notify_all()
        return True

    def match_len(self, tokens) -> int:
        """Affinity score: longest cached prefix of ``tokens`` in this
        replica's last published digest, in tokens."""
        from triton_distributed_tpu.models.prefix_cache import (
            digest_match_len,
        )

        with self._digest_lock:
            digest = self._prefix_digest
        return digest_match_len(digest, tokens)

    def tier_match_len(self, tokens) -> int:
        """Tier-affinity score (docs/scale-out.md "KV fabric"):
        longest whole-page prefix of ``tokens`` resident in this
        replica's last published TIER digest — pages the engine would
        fault back from its tier instead of re-prefilling. 0 without a
        tier."""
        from triton_distributed_tpu.models.kv_tier import (
            tier_digest_match_len,
        )

        with self._digest_lock:
            digest = self._tier_digest
        return tier_digest_match_len(digest, tokens)

    def snapshot(self) -> dict:
        with self._cond:
            queued = len(self._queue)
            inflight = self._inflight
            state = self._state
        return {
            "name": self.name,
            "state": state,
            "role": self.role,
            "pending": queued + inflight,
            "inflight": inflight,
            "free_pages": self.free_pages,
            "overloaded": self._over(queued + inflight),
            "runs": self.runs,
            "served": self.served,
            "last_error": self.last_error,
        }

    def cancel(self, ticket_ids) -> int:
        """Client-driven cancellation (docs/serving.md "Streaming &
        cancellation"). Ids match a ticket's unique ``tid`` OR its
        ``client_tid``: queued matches complete immediately with
        status ``cancelled`` (removed before the worker can run
        them); IN-FLIGHT matches forward their UNIQUE tids to the
        engine's own ``cancel`` (over the wire for a RemoteReplica) —
        the engine only ever sees tids it was dispatched, so a
        client id reused across payloads cancels every carrier
        without spraying foreign ids. Returns how many QUEUED tickets
        were cancelled here — in-flight cancels surface through their
        tickets' eventual ``cancelled`` results."""
        ids = {str(t) for t in ticket_ids}
        if not ids:
            return 0

        def hit(t: Ticket) -> bool:
            return t.tid in ids or (t.client_tid is not None
                                    and t.client_tid in ids)

        with self._cond:
            queued = [t for t in self._queue if hit(t)]
            if queued:
                self._queue = [t for t in self._queue if not hit(t)]
            inflight = [t.tid for t in self._current_batch if hit(t)]
        n = 0
        for t in queued:
            if t.complete(RequestResult(
                np.zeros(0, np.int32), "cancelled",
                "cancelled by client before dispatch",
            )):
                n += 1
        canceller = getattr(self.engine, "cancel", None)
        if inflight and canceller is not None:
            try:
                canceller(sorted(inflight))
            except Exception:  # noqa: BLE001 — remote best-effort
                pass
        return n

    # -- lifecycle ---------------------------------------------------------

    def begin_drain(self, handoff: bool = False) -> None:
        """Flip to DRAINING without waiting (the router flips the whole
        fleet first, then waits everyone against one shared deadline —
        sequential full drains would cost N × grace).

        ``handoff=True`` is the LOSSLESS drain (docs/scale-out.md
        "Slot migration & handoff"): instead of finishing queued and
        in-flight work here, the engine exports every unfinished slot
        at its next round boundary and the queue hands back un-run —
        the router re-admits everything elsewhere with the existing
        latch-first ticket dedup, so a rolling restart loses zero
        tokens of generated work."""
        with self._cond:
            if self._state == HEALTHY:
                self._state = DRAINING
                self._handoff = bool(handoff)
                self._cond.notify_all()
            elif self._state == DRAINING and handoff:
                self._handoff = True
                self._cond.notify_all()
            else:
                return
        if handoff:
            rh = getattr(self.engine, "request_handoff", None)
            if rh is not None:
                rh()

    def drain(self, grace_s: float | None = None) -> bool:
        """PR 3-style graceful drain: refuse new work, let queued and
        in-flight tickets finish; the WORKER then flushes the radix
        tree back to the pool before marking itself drained (it owns
        the engine — and a grace that expires mid-batch only makes
        this call return False early, the flush still happens when the
        batch ends). Returns True when the replica is QUIESCED within
        ``grace_s`` (None waits indefinitely) — drained cleanly OR
        already dead; check ``.state`` to tell a crash from a clean
        drain before e.g. decommissioning a node on the result."""
        self.begin_drain()
        with self._cond:
            deadline = (
                None if grace_s is None else time.monotonic() + grace_s
            )
            while self._state == DRAINING:
                if deadline is None:
                    self._cond.wait(0.1)
                else:
                    left = deadline - time.monotonic()
                    if left <= 0:
                        break
                    self._cond.wait(left)
            complete = self._state in (DRAINED, DEAD)
        if complete:
            self._thread.join(timeout=5.0)
        else:
            obs_events.emit(
                "replica_drain", replica=self.name, complete=False,
                pages_released=0,
            )
        return complete

    def mark_unhealthy(self, reason: str) -> list[Ticket]:
        """Take the replica out of rotation NOW (router-observed
        timeout, operator action): refuses new work and returns every
        affected ticket — the not-yet-started queue AND the in-flight
        batch — for re-routing. The in-flight batch itself cannot be
        aborted in-process; its late results latch harmlessly against
        the re-routed attempts."""
        return self._take_dead(reason)

    def join(self, timeout: float | None = None) -> None:
        """Wait for the worker thread to exit (call after drain/death;
        a healthy replica's worker never exits)."""
        self._thread.join(timeout)

    # -- worker ------------------------------------------------------------

    def _worker(self) -> None:
        while True:
            handoff_orphans: list[Ticket] = []
            with self._cond:
                while not self._queue and self._state == HEALTHY:
                    self._cond.wait(0.1)
                if self._state == DRAINING and self._handoff:
                    # Lossless drain: NOTHING queued runs here — the
                    # queue hands back for re-dispatch (the in-flight
                    # batch, if any, already returned with its slots
                    # exported before the worker got back here).
                    handoff_orphans = self._queue
                    self._queue = []
                    batch = None
                elif self._queue and self._state in (HEALTHY, DRAINING):
                    batch = self._queue[: self.MAX_RUN_BATCH]
                    del self._queue[: self.MAX_RUN_BATCH]
                    self._inflight = len(batch)
                    # Visible to _take_dead: a death harvests the
                    # in-flight batch too, so the router re-routes it
                    # immediately instead of each ticket serially
                    # paying its own timeout.
                    self._current_batch = batch
                    # Re-arm hop timers at BATCH START — for the batch
                    # AND for everything still queued: time spent
                    # behind earlier (healthy, long) batches is not
                    # hang evidence; a batch boundary is proof the
                    # replica is making progress, so queued tickets
                    # must not accrue it toward the router's timeout
                    # either (a deep queue would otherwise read as a
                    # hang and cascade kills under overload). Sizing
                    # rule for operators: request_timeout_s must still
                    # exceed one legal batch (docs/scale-out.md).
                    now = time.monotonic()
                    for t in batch + self._queue:
                        with t._lock:  # atomic vs Ticket.expired_hop
                            t.last_dispatch_t = now
                elif self._state == DRAINING:
                    batch = None  # drain finalization, outside the lock
                else:
                    return
            if handoff_orphans:
                # Re-dispatch OUTSIDE the lock (the router's dispatch
                # takes other replicas' locks).
                self._migrate_tickets(handoff_orphans)
            if batch is None:
                # The worker owns the engine: flush the radix tree back
                # to the pool and publish the (now empty) digest BEFORE
                # announcing drained, so a caller that saw DRAINED can
                # rely on the pages being home.
                released = (
                    self.engine.drain()
                    if hasattr(self.engine, "drain") else 0
                )
                self._publish_digest()
                with self._cond:
                    if self._state == DRAINING:  # a racing kill wins
                        self._state = DRAINED
                    final = self._state
                    self._cond.notify_all()
                # Report the state that actually stuck: a kill racing
                # the finalization must not leave BOTH a replica_dead
                # and a complete=True drain in the ring.
                obs_events.emit(
                    "replica_drain", replica=self.name,
                    complete=final == DRAINED, pages_released=released,
                )
                return
            try:
                self._run_batch(batch)
            except Exception as e:  # noqa: BLE001 — tickets must never hang
                # _run_batch already isolates engine.run failures; this
                # catches anything outside that try (request
                # construction, stats accounting) so a worker bug can
                # never strand tickets with no result.
                self._die(f"{type(e).__name__}: {e}")
            with self._cond:
                self._inflight = 0
                self._current_batch = []
                self._cond.notify_all()
                if self._state == DEAD:
                    return

    def _run_batch(self, tickets: list[Ticket]) -> None:
        reqs = [t.make_request() for t in tickets]
        try:
            # The replica-kill/hang seam (docs/scale-out.md): BEFORE
            # the engine runs, so a killed batch re-routes wholesale
            # with nothing half-admitted.
            fault_point("replica.run", replica=self.name, batch=len(reqs))
            results = self.engine.run(reqs, results=True)
        except Exception as e:  # noqa: BLE001 — replica isolation boundary
            self._die(f"{type(e).__name__}: {e}")
            return
        if self._state == DEAD:
            # A late batch on a replica the router already timed out:
            # still try to latch results (if the re-routed attempt
            # hasn't won, delivering beats discarding), but fold
            # NOTHING into the fleet accounting — a duplicate batch
            # must not double-count runs/served/totals or refresh a
            # digest nothing routes to. (The engine-side timeline of a
            # duplicate still observes; that is the documented
            # at-least-once telemetry cost of timeout re-routing.)
            # Migrated results stay unlatched either way — the router
            # already re-routed the ticket when it marked us dead.
            for t, r in zip(tickets, results):
                if r.status != "migrated":
                    t.complete(r)
            return
        self.runs += 1
        st = self.engine.last_stats
        for k in self.totals:
            self.totals[k] += st.get(k, 0)
        migrated: list[Ticket] = []
        done = 0
        for t, r in zip(tickets, results):
            if r.status == "migrated":
                # The slot was exported, not finished: carry the
                # snapshot (None for a request that never admitted —
                # it keeps any snapshot it already had) and hand the
                # ticket back for re-dispatch. NEVER latched here, so
                # the eventual completion elsewhere is the one and
                # only emission. ``prefill_only`` is left as-is: the
                # router reads it to classify the migration, then
                # clears it before dispatching the decode hop.
                if r.snapshot is not None:
                    t.snapshot = r.snapshot
                migrated.append(t)
                continue
            done += 1
            t.complete(r)
        self.served += done
        self._publish_digest()
        if migrated:
            self._migrate_tickets(migrated)

    def _migrate_tickets(self, tickets: list[Ticket]) -> None:
        """Hand exported tickets to the router for re-dispatch (the
        latch-first machinery dedups exactly as for failures). With no
        router attached (unit tests), fail them in place — never a
        silent drop."""
        cb = self.on_migrate or self.on_failure
        if cb is not None:
            cb(self, tickets)
            return
        for t in tickets:
            t.complete(RequestResult(
                np.zeros(0, np.int32), "failed",
                f"replica {self.name} exported a slot with no router "
                "attached to resume it",
            ))

    def _publish_digest(self) -> None:
        """Re-snapshot the radix population for the router — but only
        when the tree actually CHANGED shape: re-serializing a large
        warm cache after every decode-only batch would pay O(cached
        tokens) on the worker's hot path for an identical digest.
        Inserted+evicted page counts version every shape mutation
        (in-place tail upgrades count as insertions; dedupes/COW touch
        no chain)."""
        # Tier digest rides every publish: the store memoizes it on
        # its own mutation counter, so an unchanged tier costs a dict
        # ref — no scan — and a spill/adoption between radix versions
        # still lands (docs/scale-out.md "KV fabric").
        td = getattr(self.engine, "tier_digest", None)
        tier_digest = td() if td is not None else None
        prefix = getattr(self.engine, "prefix", None)
        if prefix is not None:
            version = (
                prefix.stats["inserted_pages"]
                + prefix.stats["evicted_pages"]
            )
            if version == self._digest_version:
                with self._digest_lock:
                    self._tier_digest = tier_digest
                return
            self._digest_version = version
        digest = (
            self.engine.prefix_digest()
            if hasattr(self.engine, "prefix_digest") else None
        )
        with self._digest_lock:
            self._prefix_digest = digest
            self._tier_digest = tier_digest

    # -- death -------------------------------------------------------------

    def _take_dead(self, reason: str) -> list[Ticket]:
        """Mark dead and harvest every affected ticket: the untouched
        queue AND the in-flight batch (late results on completed
        in-flight tickets latch-lose against the re-route; the atomic
        per-hop claim makes the overlap safe)."""
        with self._cond:
            already = self._state == DEAD
            self._state = DEAD
            if not already:
                self.last_error = str(reason)
            orphans = self._current_batch + self._queue
            self._current_batch = []
            self._queue = []
            self._cond.notify_all()
        if not already:
            obs_events.emit(
                "replica_dead", replica=self.name,
                reason=str(reason)[:200], orphaned=len(orphans),
            )
        return orphans

    def _die(self, reason: str) -> None:
        """The engine loop raised out of ``run`` (its own teardown
        already released pages/pins — the audit stays clean): mark
        dead and hand EVERY affected ticket (the failed in-flight
        batch plus the untouched queue, both harvested by
        ``_take_dead``) to the router for re-routing."""
        orphans = self._take_dead(reason)
        cb = self.on_failure
        if cb is not None:
            cb(self, orphans)
            return
        # No router attached (unit tests): fail tickets in place —
        # never silently dropped.
        for t in orphans:
            t.complete(RequestResult(
                np.zeros(0, np.int32), "failed",
                f"replica {self.name} died: {reason}",
            ))
