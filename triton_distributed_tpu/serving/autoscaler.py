"""Goodput-driven pool autoscaler (docs/scale-out.md "Disaggregated
pools & autoscaling").

A control loop over the signals the fleet already exports — per-role
slot occupancy and free pages (``tdt_pool_*``, ``serving/pools.py``),
the router's shed-skip ledger (prefill queue pressure), and the SLO
violation counters (``obs/slo.py``: TTFT violations indict the
prefill pool, TPOT/e2e the decode pool) — resizing role pools through
the supervisor:

- **Scale-up** rides the existing respawn path: a fresh role-tagged
  ``ReplicaSpec`` through ``FleetSupervisor.add_slot`` joins routing
  the moment it spawns healthy.
- **Scale-down** is the LOSSLESS drain: ``Router.drain_replica(name,
  handoff=True)`` exports unfinished slots onto survivors (zero
  tokens lost, zero duplicates — the PR 10 snapshot machinery), then
  ``FleetSupervisor.retire_slot`` reaps the child.
- **Stability** — hysteresis (separate up/down thresholds, scale-down
  only after ``down_ticks`` consecutive calm ticks), per-pool
  ``cooldown_s`` after any action, hard min/max bounds, and
  crash-loop-breaker awareness: PARKED slots count toward the max AND
  veto scale-up for their pool — the breaker parked that spec because
  it crash-loops, and spawning more of the same would fight the
  supervisor instead of serving anyone.

The fleet surface is duck-typed (``router`` / ``pool_slots`` /
``add_slot`` / ``retire_slot``) so unit tests drive the loop with a
fake fleet and deterministic ``tick()`` calls; production wires it to
a live ``FleetSupervisor`` (``run_server --autoscale``). Decisions
land in ``tdt_autoscaler_*`` counters and ``autoscale`` events —
emitted in the supervisor process, so a fleet-scope
``{"cmd": "events", "scope": "fleet"}`` scrape shows every scaling
decision tagged ``replica="router"`` (docs/observability.md).
"""

from __future__ import annotations

import itertools
import threading
import time

from triton_distributed_tpu.obs import events as obs_events
from triton_distributed_tpu.obs import metrics as obs_metrics
from triton_distributed_tpu.serving import pools
from triton_distributed_tpu.serving.replica import DRAINED, HEALTHY


def _handles(reg):
    h = getattr(reg, "_autoscaler_handles", None)
    if h is None:
        h = {
            "decisions": reg.counter(
                "tdt_autoscaler_decisions_total",
                "Autoscaler scaling actions taken, by action and "
                "pool role.", labels=("action", "role")),
            "skips": reg.counter(
                "tdt_autoscaler_skips_total",
                "Scaling intents vetoed (bounds, cooldown, parked "
                "slots, respawn in progress), by reason.",
                labels=("reason",)),
            "pool_size": reg.gauge(
                "tdt_autoscaler_pool_size",
                "Pool size the autoscaler currently accounts for "
                "(slots incl. parked), by role.", labels=("role",)),
        }
        reg._autoscaler_handles = h
    return h


def _violation_deltas(reg, last: dict) -> dict:
    """Per-deadline SLO-violation deltas since the previous tick
    (``tdt_slo_violations_total{slo_class,deadline}``): ``ttft``
    indicts admission (prefill pool), ``tpot``/``e2e`` the decode
    tail. Reads the live series racy-but-benign — a tick sees at
    worst one sample late."""
    out = {"ttft": 0, "tpot": 0, "e2e": 0}
    m = reg.get("tdt_slo_violations_total")
    if m is None:
        return out
    try:
        di = m.label_names.index("deadline")
    except ValueError:
        return out
    totals = {k: 0 for k in out}
    for key, v in list(m._series.items()):
        d = key[di]
        if d in totals:
            totals[d] += v
    for k, total in totals.items():
        out[k] = max(total - last.get(k, 0), 0)
        last[k] = total
    return out


class Autoscaler:
    """Resize role pools against fleet pressure.

    ``pool_bounds`` maps role → ``(min, max)`` replica counts; only
    roles listed there are managed. ``spec_factory(role, name)``
    builds the ``ReplicaSpec`` a scale-up spawns. ``fleet`` is a
    :class:`~triton_distributed_tpu.serving.supervisor.FleetSupervisor`
    (or any object with the same ``router``/``pool_slots``/
    ``add_slot``/``retire_slot`` surface).
    """

    def __init__(self, fleet, spec_factory, *,
                 pool_bounds: dict,
                 interval_s: float = 0.5,
                 cooldown_s: float = 4.0,
                 up_occupancy: float = 0.75,
                 down_occupancy: float = 0.25,
                 down_ticks: int = 4,
                 drain_grace_s: float | None = None):
        for role, (lo, hi) in pool_bounds.items():
            pools.validate_role(role)
            if not (0 <= lo <= hi):
                raise ValueError(
                    f"pool {role}: bad bounds min={lo} max={hi}")
        if not (0.0 <= down_occupancy < up_occupancy <= 1.0):
            raise ValueError(
                f"need 0 <= down_occupancy < up_occupancy <= 1, got "
                f"{down_occupancy}/{up_occupancy}")
        self.fleet = fleet
        self.spec_factory = spec_factory
        self.pool_bounds = {r: (int(lo), int(hi))
                            for r, (lo, hi) in pool_bounds.items()}
        self.interval_s = float(interval_s)
        self.cooldown_s = float(cooldown_s)
        self.up_occupancy = float(up_occupancy)
        self.down_occupancy = float(down_occupancy)
        self.down_ticks = int(down_ticks)
        self.drain_grace_s = drain_grace_s
        self.stats = {"ticks": 0, "scale_ups": 0, "scale_downs": 0,
                      "skips": 0}
        self._cooldown_until = {r: 0.0 for r in self.pool_bounds}
        self._calm_ticks = {r: 0 for r in self.pool_bounds}
        self._last_shed_skips = None
        self._last_violations: dict = {}
        self._draining: dict[str, str] = {}  # slot name -> role
        self._ids = itertools.count(1)
        self._reg = obs_metrics.default_registry()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._lock = threading.Lock()

    # -- loop --------------------------------------------------------------

    def start(self) -> "Autoscaler":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="autoscaler")
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception as e:  # noqa: BLE001 — the loop must survive
                obs_events.emit("autoscale", action="error",
                                reason=f"{type(e).__name__}: {e}"[:200])

    # -- one decision round ------------------------------------------------

    def tick(self, now: float | None = None) -> list[dict]:
        """One control round; returns the decisions taken (also
        emitted as ``autoscale`` events). Thread-safe but serialized —
        the loop and a test driving ``tick()`` directly never overlap
        decisions."""
        with self._lock:
            return self._tick_locked(
                time.monotonic() if now is None else now)

    def _tick_locked(self, now: float) -> list[dict]:
        self.stats["ticks"] += 1
        h = _handles(self._reg)
        router = self.fleet.router
        summary = pools.publish_pool_gauges(
            router.replicas, self._reg)
        shed_delta = self._shed_delta(router)
        viol = _violation_deltas(self._reg, self._last_violations)
        decisions: list[dict] = []
        self._finish_retires(decisions)
        for role, (lo, hi) in self.pool_bounds.items():
            slots = self.fleet.pool_slots(role)
            parked = [s for s in slots if s.get("parked")]
            total = len(slots)
            h["pool_size"].set(total, role=role)
            sig = summary.get(role) or {"occupancy": 0.0, "replicas": 0,
                                        "pending": 0, "free_pages": 0}
            occ = sig["occupancy"]
            # Pool-specific urgency on top of raw occupancy: prefill
            # answers admission pressure (router shed-skips, TTFT
            # violations), decode the generation tail (TPOT/e2e).
            if role == pools.PREFILL and (shed_delta > 0
                                          or viol["ttft"] > 0):
                occ = max(occ, 1.0)
            if role == pools.DECODE and (viol["tpot"] > 0
                                         or viol["e2e"] > 0):
                occ = max(occ, 1.0)
            if role == pools.MIXED and (
                    shed_delta > 0 or any(viol.values())):
                occ = max(occ, 1.0)
            healthy = sig["replicas"]
            if occ >= self.up_occupancy:
                self._calm_ticks[role] = 0
                self._try_scale_up(role, total, hi, parked, occ, sig,
                                   now, decisions)
            elif occ <= self.down_occupancy and healthy > 0:
                self._calm_ticks[role] += 1
                if self._calm_ticks[role] >= self.down_ticks:
                    self._try_scale_down(role, lo, occ, sig, now,
                                         decisions)
            else:
                self._calm_ticks[role] = 0
        return decisions

    def _shed_delta(self, router) -> int:
        cur = router.stats.get("shed_skips", 0)
        last = self._last_shed_skips
        self._last_shed_skips = cur
        if last is None:
            return 0
        return max(cur - last, 0)

    def _skip(self, role: str, reason: str, decisions: list) -> None:
        self.stats["skips"] += 1
        _handles(self._reg)["skips"].inc(reason=reason)
        decisions.append({"action": "skip", "role": role,
                          "reason": reason})
        obs_events.emit("autoscale", action="skip", role=role,
                        reason=reason)

    def _try_scale_up(self, role, total, hi, parked, occ, sig, now,
                      decisions) -> None:
        if now < self._cooldown_until[role]:
            self._skip(role, "cooldown", decisions)
            return
        if parked:
            # Crash-loop breaker awareness: the supervisor parked this
            # pool's spec because it crash-loops; spawning more of the
            # same fights the breaker, not the load.
            self._skip(role, "parked", decisions)
            return
        if total >= hi:
            self._skip(role, "at_max", decisions)
            return
        if any(s.get("replica_state") not in (HEALTHY, DRAINED)
               or s.get("down") for s in self.fleet.pool_slots(role)):
            # A slot is already down/respawning: the supervisor is
            # mid-recovery — adding capacity now would race it.
            self._skip(role, "respawn_in_progress", decisions)
            return
        name = f"{role}-as{next(self._ids)}"
        spec = self.spec_factory(role, name)
        try:
            self.fleet.add_slot(spec)
        except Exception as e:  # noqa: BLE001 — spawn failures are data
            self._skip(role, f"spawn_failed:{type(e).__name__}",
                       decisions)
            return
        self.stats["scale_ups"] += 1
        self._cooldown_until[role] = now + self.cooldown_s
        h = _handles(self._reg)
        h["decisions"].inc(action="scale_up", role=role)
        decisions.append({"action": "scale_up", "role": role,
                          "replica": name})
        obs_events.emit(
            "autoscale", action="scale_up", role=role, replica=name,
            occupancy=round(occ, 3), pending=sig["pending"],
            pool=total + 1,
        )

    def _try_scale_down(self, role, lo, occ, sig, now,
                        decisions) -> None:
        if now < self._cooldown_until[role]:
            self._skip(role, "cooldown", decisions)
            return
        slots = self.fleet.pool_slots(role)
        live = [s for s in slots
                if not s.get("parked") and s["name"] not in
                self._draining]
        if len(live) <= lo:
            self._skip(role, "at_min", decisions)
            return
        healthy = [s for s in live
                   if s.get("replica_state") == HEALTHY]
        if len(healthy) <= lo:
            self._skip(role, "at_min", decisions)
            return
        victim = min(healthy, key=lambda s: s.get("pending", 0))
        self._calm_ticks[role] = 0
        self._cooldown_until[role] = now + self.cooldown_s
        # Lossless drain: unfinished slots export and re-admit on the
        # survivors through the snapshot machinery before the child is
        # reaped — zero tokens lost, zero duplicates.
        ok = self.fleet.router.drain_replica(
            victim["replica_name"], self.drain_grace_s, handoff=True)
        self.stats["scale_downs"] += 1
        h = _handles(self._reg)
        h["decisions"].inc(action="scale_down", role=role)
        action = {"action": "scale_down", "role": role,
                  "replica": victim["name"], "drained": bool(ok)}
        obs_events.emit(
            "autoscale", action="scale_down", role=role,
            replica=victim["name"], drained=bool(ok),
            occupancy=round(occ, 3), pool=len(live) - 1,
        )
        if ok:
            self.fleet.retire_slot(victim["name"])
        else:
            # Drain timed out: the replica is DRAINING and off
            # rotation (no new work lands on it); retire once its
            # worker reports drained instead of killing in-flight
            # work.
            self._draining[victim["name"]] = role
        decisions.append(action)

    def _finish_retires(self, decisions: list) -> None:
        for name, role in list(self._draining.items()):
            for s in self.fleet.pool_slots(role):
                if s["name"] == name:
                    if s.get("replica_state") in (DRAINED, None):
                        self.fleet.retire_slot(name)
                        self._draining.pop(name, None)
                        decisions.append({"action": "retired",
                                          "role": role,
                                          "replica": name})
                    break
            else:
                self._draining.pop(name, None)
