"""Socket model server over the Engine.

Parity: reference ``mega_triton_kernel/test/models/model_server.py`` —
a TCP server (:112-198) that owns the compiled model and answers
generation requests, with the chat/bench clients speaking a small
framed protocol. Here the protocol is newline-delimited JSON over TCP:

    → {"input_ids": [[...]], "gen_len": 32}
    ← {"output_ids": [[...]], "stats": {...}}
    → {"requests": [[...], ...], "gen_lens": [4, ...],   (continuous
       "temperatures": [0.8, ...], "top_ps": [...],       batching;
       "top_ks": [...], "deadline_s": [5.0, ...],         knobs optional)
       "ticket_ids": ["t1p9", ...], "want_digest": true}
    ← {"outputs": [[...], ...],                 (partial on failure)
       "results": [{"status": "ok"|..., "reason": ...}, ...],
       "ticket_ids": [...],  "prefix_digest": [...],   (when requested)
       "stats": {...}}
    → {"cmd": "stats"}           ← {"stats": {..., "server": {...}}}
    → {"cmd": "metrics"}         ← {"prometheus": "...", "metrics": {...}}
    → {"cmd": "metrics", "scope": "fleet"}
                                 ← {"prometheus": <replica-labeled merge
                                    of every child's exposition>,
                                    "replicas": [...], "errors": {...}}
    → {"cmd": "events", "since": 0, "limit": 100, "kind": "span"}
                                 ← {"events": [...], "dropped": 0,
                                    "next_since": 17}
    → {"cmd": "events", "scope": "fleet"}
                                 ← {"events": [replica-tagged,
                                    fleet_seq-stitched], "dropped": n}
    → {"cmd": "cancel", "ticket_ids": ["t1p9"]}
                                 ← {"ok": true, "requested": 1}
    → {"cmd": "slo"}             ← {"slo": {"classes": {...},
                                    "specs": {...}}}
    → {"cmd": "kernel_trace"}    ← {"kernel_trace": {"launches": ...,
                                    "recent": [...]}}
    → {"cmd": "ping"}            ← {"ok": true, "draining": false}
    → {"cmd": "healthz"}         ← {"ok": true, "state": "serving"}
    → {"cmd": "audit"}           ← {"problems": []}   (engine lock held)
    → {"cmd": "export_slots"}    ← {"slots": {tid: snapshot, ...}}
    → {"cmd": "handoff"}         ← {"ok": true}  (in-flight batch then
                                    returns its slots as snapshots)
    → {"cmd": "shutdown"}        ← {"ok": true}   (server then drains)

A ``requests`` payload may also carry ``snapshots`` (per-request slot
snapshots to RESUME from — docs/scale-out.md "Slot migration &
handoff") and ``prefill_only`` flags (export right after admission:
the prefill→decode handoff); a ``migrated`` result entry then carries
its ``snapshot`` back.

**Streaming** (docs/serving.md "Streaming & cancellation"): a
``requests`` payload with ``"stream": true`` pushes one line-JSON
frame per EMITTED token before the final response line::

    ← {"frame": "token", "tid": "t1p9", "i": 0, "token": 17,
       "t": <monotonic stamp taken at the wire write>}
    ← ... one per token, per request, "i" strictly increasing ...
    ← {"frame": "summary", "outputs": [...], "results": [...],
       "ticket_ids": [...], "wire": [{"ttft_s": ..., "tpot_s": ...,
       "e2e_s": ..., "tokens_out": ..., "outcome": "met"}, ...],
       "stats": {...}}

``t`` stamps are taken AT the frame write — TTFT/TPOT measured from
them are what the user saw, not an engine-side latch; the per-request
``wire`` entries in the summary carry the derived wire-side numbers
and the SLO outcome (``obs/slo.py``). Requests without client
``ticket_ids`` get server-assigned ids (echoed in frames and the
summary) so a mid-stream ``{"cmd": "cancel"}`` on a second connection
can target them; a client that simply disconnects mid-stream is
detected at the next frame write and its requests are cancelled the
same way — slots torn down, pages freed, status ``cancelled`` with
the partial tokens. Re-dispatched work (router reroutes, migrations)
may re-emit earlier tokens; the sink dedups by index so each token
crosses the wire exactly once, and tokens a resume skipped are
back-filled before the summary.

The per-request sampling/deadline keys are scalars (applied to every
request) or per-request lists; omitted/null entries fall back to the
engine's defaults. ``stats`` payloads surface the engine's serving
counters verbatim — including, on paged engines, ``kv_bytes_per_token``
and ``kv_dtype`` (the quantized-KV knob, docs/serving.md "Quantized KV
cache"), so a client can read the storage mode through the wire.

**Telemetry** (docs/observability.md): ``{"cmd": "metrics"}`` returns
the process metrics registry as a Prometheus-text-format string AND a
JSON snapshot with derived p50/p90/p99; ``{"cmd": "events"}`` tails
the bounded structured-event ring drop-aware by seq number (``kind=``
pulls one stream — ``span``/``mega:launch``/``fault``/… — server-side);
``{"cmd": "kernel_trace"}`` returns the device task tracer's recent
decoded launches (mode='mega' engines; docs/observability.md "Device
task tracer"). A ``requests`` payload may carry per-request
``trace_ids`` that follow each request through admit events, launch
events, and device task rows. All are probe verbs: they never touch
the engine lock, so scraping works mid-generation. Every payload is also counted/timed per verb
(``tdt_server_requests_total``, ``tdt_server_request_seconds``,
``tdt_server_errors_total``).

**Concurrency + fault tolerance** (docs/serving.md "Fault tolerance"):
each connection is served on its own thread; generation payloads
serialize on an engine lock (the accelerator is serial anyway), while
``ping``/``stats`` bypass it — the server answers health probes even
mid-generation. At most ``max_pending`` generation payloads may wait on
the lock; excess load is shed with a structured ``overloaded`` error
(clients retry with backoff — see :func:`request`). Errors are
structured ``{"error": {"status": ..., "reason": ...}}`` objects:
``bad_request`` (malformed JSON, oversized line, unknown payload,
validation), ``overloaded``, ``shutting_down`` (graceful drain: the
server finishes in-flight work, answers pings, refuses new generation),
``internal``. Per-request failures inside a ``requests`` payload do NOT
fail the payload — the response carries per-request statuses.

The ``overloaded`` shed reply carries a load-proportional
``retry_after_s`` hint; the :func:`request` retry loop honors it over
its local exponential backoff. ``drain_grace_s`` bounds the
oversized-line connection drain (was a hardcoded 2.0) and is surfaced
in ``server_stats``.

A ``requests`` payload routes to a
:class:`~triton_distributed_tpu.models.continuous.ContinuousEngine`'s
admission/eviction loop (mixed prompt/gen lengths, paged pool, prefix
cache when the engine enables it); ``input_ids`` routes to
``Engine.serve`` fixed-batch serving. A server constructed over a
ContinuousEngine only speaks the former, over an Engine only the
latter. A server over a ``Router`` (``serving/router.py``,
docs/scale-out.md) speaks the continuous form, dispatches generation
payloads WITHOUT the engine lock (the router's per-replica queues
serialize), and drains the replica fleet on shutdown.
"""

from __future__ import annotations

import base64
import itertools
import json
import os
import random
import socket
import threading
import time

import numpy as np

from triton_distributed_tpu.models.engine import Engine
from triton_distributed_tpu.obs import events as obs_events
from triton_distributed_tpu.obs import metrics as obs_metrics
from triton_distributed_tpu.obs import slo as obs_slo
from triton_distributed_tpu.obs.metrics import prometheus_text
from triton_distributed_tpu.obs.timeline import Timeline
from triton_distributed_tpu.runtime.faults import fault_point, mutate_point


# The probe verbs _dispatch_inner answers. ONE tuple: the metrics
# label in _verb_of and the `accepted payloads` help both derive from
# it, so a new verb can't silently label its traffic `unknown`. All
# are engine-lock-free EXCEPT `audit` (it walks live engine state, so
# it serializes behind generation — run it quiesced).
PROBE_CMDS = ("ping", "healthz", "stats", "metrics", "events",
              "kernel_trace", "audit", "shutdown", "export_slots",
              "handoff", "cancel", "slo", "tier_probe", "tier_get",
              "tier_peers")

# Bound on one tier_probe's key list: probes are per-page walks, and a
# prompt's page count is small — an unbounded list is a client bug.
MAX_TIER_PROBE_KEYS = 256

# Server-assigned stream ticket ids (payloads that stream without
# client ticket_ids still need cancellable identities); pid-suffixed
# like replica tids so they stay unique across routers sharing a
# replica.
_STREAM_IDS = itertools.count(1)


class _BadRequest(ValueError):
    """Client-side protocol error: mapped to status ``bad_request``."""


class _StreamSink:
    """Per-payload streaming state (docs/serving.md "Streaming &
    cancellation"): ONE wire write path for every token frame of a
    streamed ``requests`` payload, with the three properties the wire
    grammar promises:

    - **exactly-once frames** — engines re-emit earlier indices on
      re-dispatch (router reroutes, migration replays; at-least-once
      by design); the sink dedups by per-request index so each token
      crosses the wire once, and :meth:`finish` back-fills tokens a
      snapshot resume skipped before the summary goes out;
    - **wire-side stamps** — each frame's departure stamps the
      request's wire :class:`Timeline` (``stamp_token``), the numbers
      TTFT/TPOT/goodput are derived from;
    - **disconnect → cancel** — a failed frame write (client gone, or
      the injected ``stream.send`` fault) marks the sink broken and
      cancels the payload's requests through the engine's ``cancel``,
      so an abandoned stream frees its slots and pages instead of
      generating tokens nobody reads.

    Callbacks arrive on the engine thread (single engine) or replica
    worker threads (router) — the internal lock serializes writes.
    Back-pressure caveat: a frame write blocks ITS emitter, which for
    a single engine is only that payload's loop, but on a router a
    replica worker streaming for client A stalls any work co-batched
    with A on that replica (bounded by the connection's socket
    timeout). A per-connection writer thread with a bounded queue
    would decouple it — not built until a workload needs it.
    """

    def __init__(self, server: "ModelServer", f, tids: list):
        self._server = server
        self._f = f
        self.tids = tids
        self._lock = threading.Lock()
        self._sent = [0] * len(tids)
        self.timelines = [Timeline() for _ in tids]
        self.broken = False
        self._closed = False

    def attach_enqueue(self, enqueue_t: float | None) -> None:
        for tl in self.timelines:
            tl.enqueue_t = enqueue_t
            tl.stamp_enqueue()

    def seed(self, ri: int, n: int) -> None:
        """Start request ``ri``'s stream at index ``n`` — the tokens a
        payload-carried snapshot already restored. The client
        resubmitting its own snapshot HOLDS that prefix; without the
        seed, the first live token (index n) would read as a gap and
        every post-resume frame would defer to the summary back-fill,
        freezing the stream for exactly the migration-resume case."""
        with self._lock:
            self._sent[ri] = max(self._sent[ri], int(n))

    def sink_for(self, ri: int):
        """The ``on_token`` callback for request index ``ri``."""

        def cb(i, token):
            self.push(ri, int(i), int(token))

        return cb

    def push(self, ri: int, i: int, token: int) -> None:
        with self._lock:
            if self._closed or self.broken:
                return
            if i != self._sent[ri]:
                # i < sent: re-dispatch replay, already delivered.
                # i > sent: a resume skipped past frames this sink
                # never carried (lost with a dying child's socket) —
                # streaming the jump would violate the in-order
                # contract, and the missing tokens aren't known HERE;
                # finish() back-fills the whole ordered tail from the
                # final result instead.
                return
            self._write(ri, i, token)

    def _write(self, ri: int, i: int, token: int) -> None:
        """One frame out (caller holds the lock). The ``t`` stamp is
        taken at the write — the wire-side clock."""
        frame = {"frame": "token", "tid": self.tids[ri], "i": i,
                 "token": token, "t": time.monotonic()}
        try:
            data = json.dumps(frame).encode() + b"\n"
            data = mutate_point("stream.send", data,
                                tid=self.tids[ri], i=i)
            self._f.write(data)
            self._f.flush()
        except Exception:  # noqa: BLE001 — the client vanished
            self.broken = True
            self._disconnect()
            return
        self._sent[ri] = i + 1
        self.timelines[ri].stamp_token()
        if obs_metrics.default_registry().enabled:
            self._server._m_frames.inc()

    def _disconnect(self) -> None:
        self._server._m_disconnects.inc()
        obs_events.emit("stream_disconnect", requests=len(self.tids))
        if self._closed:
            # The disconnect surfaced during finish()'s back-fill —
            # the engine batch already returned AND pruned this
            # batch's cancel ids, so arming them now would only go
            # stale and kill a future request that reuses the same
            # client ticket id. There is nothing left to cancel.
            return
        canceller = getattr(self._server.engine, "cancel", None)
        if canceller is not None:
            try:
                canceller(self.tids)
            except Exception:  # noqa: BLE001 — teardown is best-effort
                pass

    def finish(self, results) -> None:
        """Close the sink (late worker callbacks become no-ops) and
        back-fill any tokens the frames never carried — a snapshot
        resume on another replica starts past what ITS engine emitted,
        and those earlier tokens may predate this sink entirely. They
        reached the user NOW, so their stamps are now: wire-honest."""
        with self._lock:
            self._closed = True
            if self.broken:
                return
            for ri, r in enumerate(results):
                toks = [int(t) for t in r.tokens]
                for i in range(self._sent[ri], len(toks)):
                    self._write(ri, i, toks[i])
                    if self.broken:
                        return


class ModelServer:
    """Own a listening socket + an Engine; serve generation requests."""

    # An idle client must not wedge a connection thread forever: a
    # connection that sends nothing within this window is dropped.
    IDLE_TIMEOUT_S = 10.0
    # Bound on one accepted request line: a giant payload must not OOM
    # the server before JSON parsing even starts.
    MAX_LINE_BYTES = 1 << 20
    # Graceful-drain bound: how long serve_forever waits for in-flight
    # connections after shutdown (threads are daemonized — a wedged
    # client cannot hold process exit hostage).
    DRAIN_TIMEOUT_S = 30.0

    def __init__(
        self,
        engine: Engine,
        host: str = "127.0.0.1",
        port: int = 0,
        *,
        max_pending: int = 8,
        drain_grace_s: float = 2.0,
        trace_dir: str | None = None,
        slo=None,
        advertise_host: str | None = None,
    ):
        self.engine = engine
        self.max_pending = max_pending
        # SLO specs (docs/observability.md "SLO goodput"): a single
        # SLOSpec, a {class: spec} dict, or None — normalized so a
        # `default` class always exists. Streaming payloads judge
        # their wire-side timelines against the request's class; the
        # {"cmd": "slo"} verb reports the resulting goodput.
        self.slo_specs = obs_slo.normalize_specs(slo)
        # Informational: where a --trace run merges its host+device
        # timeline (run_server owns the actual group_profile capture;
        # the server only surfaces the knob in server_stats so a
        # scraper can see tracing is deployed).
        self.trace_dir = trace_dir
        # Connection-drain budget (was a hardcoded 2.0): bounds how
        # long an oversized-line tail is drained before the conn
        # closes, and rides into the router's replica-drain grace when
        # this server fronts a Router (docs/scale-out.md). Surfaced in
        # ``server_stats`` so a scraper can see the deployed value.
        self.drain_grace_s = float(drain_grace_s)
        # Routers serialize internally (per-replica queues): dispatch
        # their generation payloads WITHOUT the engine lock so
        # payloads from many connections fan out across replicas.
        self._concurrent = bool(getattr(engine, "concurrent_safe", False))
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(16)
        self.host, self.port = self._sock.getsockname()
        # The address peers should DIAL (docs/scale-out.md "Multi-host
        # fleet"): binding 0.0.0.0 (or any wildcard) makes the bound
        # host meaningless to other machines, so port files, peer
        # lists, and server_stats carry this instead. Defaults to the
        # bound host — single-host setups see no change.
        self.advertise_host = (str(advertise_host) if advertise_host
                               else self.host)
        self._shutdown = threading.Event()
        self._thread: threading.Thread | None = None
        # One generation at a time (the accelerator is serial); probes
        # (ping/stats) never take this lock, so the server answers them
        # mid-generation.
        self._engine_lock = threading.Lock()
        self._pending = 0
        self._pending_lock = threading.Lock()
        self._counters = {
            "connections": 0,
            "requests": 0,
            "errors": 0,       # per-payload failures (bad/unknown/internal)
            "conn_errors": 0,  # per-connection failures (drop/timeout)
            "shed": 0,         # generation payloads shed as overloaded
            "refused": 0,      # generation payloads refused while draining
        }
        self._counters_lock = threading.Lock()
        self._last_conn_error: str | None = None
        self._t0 = time.monotonic()
        # Metric handles resolved ONCE (engine-convention): a payload
        # must not pay registry get-or-create lookups on the same
        # global lock the decode loop's counters contend on.
        self._m_requests = obs_metrics.counter(
            "tdt_server_requests_total",
            "Payloads dispatched, by verb.", labels=("verb",),
        )
        self._m_seconds = obs_metrics.histogram(
            "tdt_server_request_seconds",
            "Wall time handling one payload, by verb.",
            labels=("verb",),
        )
        self._m_errors = obs_metrics.counter(
            "tdt_server_errors_total",
            "Structured error responses, by verb and status.",
            labels=("verb", "status"),
        )
        self._m_frames = obs_metrics.counter(
            "tdt_server_stream_frames_total",
            "Token frames pushed to streaming clients.",
        )
        self._m_disconnects = obs_metrics.counter(
            "tdt_server_stream_disconnects_total",
            "Streaming payloads whose client vanished mid-stream "
            "(their requests are cancelled).",
        )

    def _count(self, key: str) -> None:
        with self._counters_lock:
            self._counters[key] += 1

    @property
    def server_stats(self) -> dict:
        with self._counters_lock:
            stats = dict(self._counters)
            stats["last_conn_error"] = self._last_conn_error
        with self._pending_lock:
            stats["pending"] = self._pending
        stats["draining"] = self._shutdown.is_set()
        stats["drain_grace_s"] = self.drain_grace_s
        stats["advertise_host"] = self.advertise_host
        # Deployed engine knobs (docs/serving.md): scrapers see what
        # configuration is actually serving without shelling into the
        # host. Routers surface per-replica details in the stats verb's
        # ``router`` ledger instead; these getattrs then report the
        # fleet-level defaults (None/0).
        engine_cfg = getattr(
            getattr(self.engine, "model", None), "cfg", None
        )
        stats["engine"] = {
            "mode": getattr(self.engine, "mode", None),
            "kv_dtype": getattr(self.engine, "kv_dtype", None),
            "speculative": getattr(self.engine, "speculative", 0),
            "kernel_trace": getattr(self.engine, "kernel_trace", False),
            # MoE knobs (docs/serving.md "MoE serving"): 0 for dense
            # models and fleet routers (whose per-replica details ride
            # the stats verb's ``router`` ledger).
            "num_experts": getattr(engine_cfg, "num_experts", 0),
            "experts_per_tok": getattr(
                engine_cfg, "num_experts_per_tok", 0
            ),
        }
        # Durable KV tier (docs/serving.md "Tiered KV"): the deployed
        # capacity/dir, next to kv_dtype — 0/None when no tier is
        # attached (or when a Router fronts per-replica tiers, whose
        # details ride the stats verb's per-replica snapshots).
        tier = getattr(self.engine, "tier", None)
        stats["engine"]["tier_bytes"] = (
            int(getattr(tier, "capacity_bytes", 0)) if tier is not None
            else 0
        )
        stats["engine"]["tier_dir"] = (
            getattr(tier, "dir", None) if tier is not None else None
        )
        # Deployed SLO deadlines (docs/observability.md "SLO
        # goodput"): scrapers see what the goodput numbers are judged
        # against without shelling into the host.
        stats["engine"]["slo"] = {
            name: spec.as_dict()
            for name, spec in sorted(self.slo_specs.items())
        }
        # Pool shape (docs/scale-out.md "Disaggregated pools &
        # autoscaling"): per-role replica counts when a pool-aware
        # Router fronts the engine — absent for single-engine servers.
        shape = getattr(self.engine, "pool_shape", None)
        if callable(shape):
            try:
                stats["pools"] = shape()
            except Exception:  # noqa: BLE001 — stats must answer
                pass
        # --trace DIR deployments (run_server) surface where the
        # merged host+device timeline will land.
        stats["trace_dir"] = self.trace_dir
        # ``snapshot_at`` is the same monotonic clock the per-request
        # timelines use, so a scraper can order stats snapshots against
        # event-ring timestamps without wall-clock skew.
        now = time.monotonic()
        stats["uptime_s"] = now - self._t0
        stats["snapshot_at"] = now
        return stats

    # -- request handling ------------------------------------------------

    @staticmethod
    def _error(status: str, reason: str, **extra) -> dict:
        return {"error": {"status": status, "reason": reason, **extra}}

    @staticmethod
    def _verb_of(req) -> str:
        """Metrics label for a payload: its probe cmd, or which
        generation form it takes (bounded cardinality by construction —
        unknown cmds all land under ``unknown``)."""
        if not isinstance(req, dict):
            return "unknown"
        cmd = req.get("cmd")
        if cmd in PROBE_CMDS:
            return cmd
        if "requests" in req:
            return "requests"
        if "input_ids" in req:
            return "generate"
        return "unknown"

    def _dispatch(self, req, stream_f=None) -> dict:
        """Route one parsed payload with per-verb telemetry; every
        failure becomes a structured error response — nothing escapes
        to kill the connection. ``stream_f`` is the connection's
        buffered file: a ``"stream": true`` generation payload pushes
        its token frames through it before the returned summary."""
        verb = self._verb_of(req)
        t0 = time.monotonic()
        resp = self._dispatch_inner(req, stream_f)
        if obs_metrics.default_registry().enabled:
            self._m_requests.inc(verb=verb)
            self._m_seconds.observe(time.monotonic() - t0, verb=verb)
            err = resp.get("error")
            if isinstance(err, dict):
                self._m_errors.inc(verb=verb, status=str(err.get("status")))
        return resp

    def _dispatch_inner(self, req, stream_f=None) -> dict:
        try:
            if not isinstance(req, dict):
                raise _BadRequest("payload must be a JSON object")
            cmd = req.get("cmd")
            if cmd == "ping":
                return {"ok": True, "draining": self._shutdown.is_set()}
            if cmd == "cancel":
                # Client-driven cancellation (docs/serving.md
                # "Streaming & cancellation"). Engine-lock-FREE (a set
                # add / queue filter): the whole point is landing
                # MID-generation, from a second connection, against a
                # batch the engine lock is busy serving.
                tids = req.get("ticket_ids")
                if (not isinstance(tids, list) or not tids
                        or not all(isinstance(t, (str, int))
                                   for t in tids)):
                    raise _BadRequest(
                        "cancel needs a non-empty ticket_ids list of "
                        "strings/ints"
                    )
                canceller = getattr(self.engine, "cancel", None)
                if canceller is None:
                    raise _BadRequest(
                        "this engine has no cancel() "
                        "(ContinuousEngine/StubEngine/Router expose it; "
                        "see docs/serving.md 'Streaming & cancellation')"
                    )
                canceller([str(t) for t in tids])
                return {"ok": True, "requested": len(tids)}
            if cmd == "slo":
                # Goodput readout (docs/observability.md "SLO
                # goodput"): per-class met/missed/cancelled counts,
                # goodput, and wire-side latency quantiles, judged
                # against this server's deployed specs. Probe verb —
                # registry reads only.
                return {"slo": obs_slo.snapshot(self.slo_specs)}
            if cmd == "healthz":
                # The heartbeat target (docs/scale-out.md "Process
                # fleet"): liveness ONLY. No engine lock, no
                # server_stats construction — it must answer fast
                # mid-generation, because a missed deadline here is
                # what the supervisor reads as a wedged process.
                # `state` lets it tell a draining replica from a dead
                # one before classifying an exit as a crash.
                return {
                    "ok": True,
                    "state": ("shutting_down" if self._shutdown.is_set()
                              else "serving"),
                }
            if cmd == "audit":
                # Fleet-audit verb: the router's `Router.audit` reaches
                # remote replicas' pool/radix invariants through this.
                # NOT engine-lock-free — the audit walks live slot and
                # tree state, so it queues behind in-flight generation
                # instead of racing it.
                auditor = getattr(self.engine, "audit", None)
                if auditor is None:
                    raise _BadRequest("this engine has no audit()")
                with self._engine_lock:
                    return {"problems": [str(p) for p in auditor()]}
            if cmd == "export_slots":
                # Slot-migration probe (docs/scale-out.md "Slot
                # migration & handoff"): the engine's incremental
                # per-ticket snapshot buffer, refreshed at scheduling-
                # round boundaries. Engine-lock-FREE (the buffer has
                # its own lock) — the supervisor polls this MID-batch;
                # that is the whole point of snapshot-based crash
                # recovery.
                exporter = getattr(self.engine, "export_slots", None)
                if exporter is None:
                    raise _BadRequest(
                        "this engine has no slot snapshots "
                        "(ContinuousEngine/StubEngine expose them; see "
                        "docs/scale-out.md 'Slot migration & handoff')"
                    )
                return {"slots": exporter()}
            if cmd == "handoff":
                # Lossless-drain trigger: arm the engine's handoff
                # sweep so the in-flight batch returns its unfinished
                # slots as exported snapshots instead of finishing
                # them here. Engine-lock-free (an event/int write) —
                # it must land WHILE the batch runs.
                rh = getattr(self.engine, "request_handoff", None)
                if rh is None:
                    raise _BadRequest(
                        "this engine has no handoff support "
                        "(ContinuousEngine/StubEngine expose it)"
                    )
                rh()
                return {"ok": True}
            if cmd in ("tier_probe", "tier_get"):
                # KV fabric serve side (docs/scale-out.md "KV fabric").
                # Engine-lock-FREE like metrics/healthz: the PageStore
                # has its own lock, and peers probe/pull MID-batch —
                # that is the point of cross-replica fault-back.
                # ``prefix`` entries only: snapshots are per-ticket
                # crash-recovery state, not shareable cache.
                from triton_distributed_tpu.models import kv_tier

                tier = getattr(self.engine, "tier", None)
                if tier is None:
                    raise _BadRequest(
                        "this engine has no KV tier (run with "
                        "tier_bytes/tier_dir; see docs/serving.md "
                        "'Tiered KV')"
                    )
                kind = req.get("kind", kv_tier.PREFIX_KIND)
                if kind != kv_tier.PREFIX_KIND:
                    raise _BadRequest(
                        "the KV fabric serves 'prefix' entries only"
                    )
                if cmd == "tier_probe":
                    keys = req.get("keys")
                    if (not isinstance(keys, list) or not keys
                            or len(keys) > MAX_TIER_PROBE_KEYS
                            or not all(isinstance(k, str) for k in keys)):
                        raise _BadRequest(
                            "tier_probe needs a non-empty keys list of "
                            f"<= {MAX_TIER_PROBE_KEYS} strings"
                        )
                    return {
                        "have": [bool(tier.contains(kind, k))
                                 for k in keys],
                    }
                key = req.get("key")
                if not isinstance(key, str) or not key:
                    raise _BadRequest("tier_get needs a string key")
                blob = tier.get_blob(kind, key)
                if blob is None:
                    return {"found": False}
                b64 = base64.b64encode(blob).decode()
                if len(b64) > self.MAX_LINE_BYTES - 4096:
                    # The response must fit one wire line; an oversized
                    # entry reads as a miss — the puller re-prefills.
                    return {"found": False, "reason": "oversized"}
                return {"found": True, "blob": b64}
            if cmd == "tier_peers":
                # Supervisor broadcast: (re)wire this replica's fabric
                # client at the engine's peer set. Engine-lock-free (a
                # list swap under the client's own lock).
                fabric = getattr(self.engine, "fabric", None)
                if fabric is None:
                    raise _BadRequest(
                        "this engine has no KV fabric client (run with "
                        "a tier + fabric; see docs/scale-out.md "
                        "'KV fabric')"
                    )
                peers = req.get("peers")
                if not isinstance(peers, list):
                    raise _BadRequest("tier_peers needs a peers list")
                fabric.set_wire_peers(peers)
                return {"ok": True, "peers": len(fabric.peers)}
            if cmd == "shutdown":
                self._shutdown.set()
                return {"ok": True}
            if cmd == "stats":
                stats = dict(self.engine.last_stats)
                stats["server"] = self.server_stats
                return {"stats": stats}
            if cmd == "metrics":
                # Probe verb: reads the registry under its own short
                # lock, never the engine lock — scraping answers
                # mid-generation (docs/observability.md).
                scope = req.get("scope")
                if scope not in (None, "process", "fleet"):
                    raise _BadRequest(
                        "metrics scope must be 'process' or 'fleet'"
                    )
                if scope == "fleet":
                    fleet = getattr(self.engine, "fleet", None)
                    if fleet is not None and hasattr(fleet,
                                                     "fleet_metrics"):
                        # Process fleet (docs/scale-out.md "Fleet-scope
                        # telemetry"): the supervisor fans the metrics
                        # verb out to every child and merges the
                        # expositions replica-labeled — one scrape
                        # sees the whole fleet.
                        out = fleet.fleet_metrics()
                        return {
                            "prometheus": out["prometheus"],
                            "scope": "fleet",
                            "replicas": out["replicas"],
                            "errors": out["errors"],
                        }
                    # No process fleet behind this server: in-process
                    # replicas share THIS registry, so the process
                    # scrape already IS the fleet view.
                    reg = obs_metrics.default_registry()
                    return {
                        "prometheus": prometheus_text(reg),
                        "metrics": reg.snapshot(),
                        "scope": "process",
                    }
                reg = obs_metrics.default_registry()
                return {
                    "prometheus": prometheus_text(reg),
                    "metrics": reg.snapshot(),
                }
            if (cmd == "events"
                    and req.get("scope") not in (None, "process")):
                # Same validation rule as metrics: a typo'd scope must
                # not silently degrade a fleet scraper to one process.
                if req.get("scope") != "fleet":
                    raise _BadRequest(
                        "events scope must be 'process' or 'fleet'"
                    )
                fleet = getattr(self.engine, "fleet", None)
                if fleet is None or not hasattr(fleet, "fleet_events"):
                    raise _BadRequest(
                        "events scope 'fleet' needs a supervised "
                        "process fleet behind this server "
                        "(docs/scale-out.md 'Fleet-scope telemetry')"
                    )
                limit = req.get("limit")
                if limit is not None and (not isinstance(limit, int)
                                          or limit < 0):
                    raise _BadRequest(
                        "events limit must be an integer >= 0"
                    )
                if req.get("kind") is not None or "since" in req:
                    # The fleet stream's per-child cursors are SHARED
                    # server-side state: a kind-filtered pull would
                    # advance them past every other-kind event
                    # (dropped=0) and hide those events forever, and a
                    # client `since` cannot seek them — refusing both
                    # loudly beats silently returning an arbitrary
                    # window.
                    raise _BadRequest(
                        "fleet-scope events supports neither kind nor "
                        "since (server-side shared cursors page "
                        "forward); filter the merged rows client-side"
                    )
                return fleet.fleet_events(limit=limit)
            if cmd == "events":
                try:
                    # JSON null is a natural "from the start" / "no
                    # cap" spelling; anything else must be an int —
                    # and a wrong TYPE is the client's fault, not an
                    # `internal` server error.
                    since = req.get("since")
                    since = 0 if since is None else int(since)
                    limit = req.get("limit")
                    limit = None if limit is None else int(limit)
                except (TypeError, ValueError) as e:
                    raise _BadRequest(
                        f"events since/limit must be integers: {e}"
                    )
                if since < 0 or (limit is not None and limit < 0):
                    # A negative cursor would manufacture phantom
                    # `dropped` counts (tail reports events[0].seq -
                    # since - 1), corrupting drop-summing consumers.
                    raise _BadRequest(
                        "events since/limit must be >= 0"
                    )
                # kind= pulls one stream (span / mega:launch / fault /
                # admit / ...) server-side instead of every consumer
                # re-filtering the full firehose client-side.
                kind = req.get("kind")
                if kind is not None and not isinstance(kind, str):
                    raise _BadRequest("events kind must be a string")
                ring = obs_events.default_ring()
                # Snapshot the newest seq BEFORE tailing: a
                # kind-filtered empty page may safely skip everything
                # scanned (all non-matching), but not events emitted
                # after the scan.
                newest_pre = ring.next_seq - 1
                evts, dropped = ring.tail(since, limit, kind=kind)
                # Empty tail still advances the cursor past anything
                # the ring dropped (e.g. a clear()), or a drop-summing
                # consumer would re-count the same loss every poll —
                # but never past events a `limit` deferred to the next
                # page (tail keeps the oldest, so since+dropped is
                # always the seq just before the first undelivered
                # event). A kind-filtered empty page additionally
                # skips the scanned non-matching events.
                if evts:
                    next_since = evts[-1].seq
                elif kind is not None and limit != 0:
                    # Zero matches in the WHOLE scanned range (a
                    # nonzero limit can only truncate matches, and
                    # there were none): safe to skip the scanned
                    # non-matching events. limit == 0 returns an empty
                    # page regardless of matches, so it must NOT skip
                    # — matching events may sit in (since, newest].
                    next_since = max(since, newest_pre)
                else:
                    next_since = since + dropped
                return {
                    "events": [e.as_dict() for e in evts],
                    "dropped": dropped,
                    "next_since": next_since,
                }
            if cmd == "kernel_trace":
                # Probe verb (engine-lock-free): the engines keep the
                # decoded launches under their own bounded deque, so a
                # scrape mid-generation reads a recent snapshot.
                summary = getattr(
                    self.engine, "kernel_trace_summary", None
                )
                if summary is None:
                    raise _BadRequest(
                        "this engine has no device kernel tracer "
                        "(mode='mega' engines expose it; see "
                        "docs/observability.md 'Device task tracer')"
                    )
                return {"kernel_trace": summary()}
            if "requests" in req or "input_ids" in req:
                return self._generate_guarded(req, stream_f)
            accepted = [
                f"cmd ({'|'.join(PROBE_CMDS)})",
                "requests + gen_lens/temperatures/top_ps/top_ks/"
                "deadline_s/trace_ids/ticket_ids/want_digest/"
                "want_tier_digest/snapshots/prefill_only/stream/"
                "slo_class (continuous batching)",
                "input_ids + gen_len/prompt_start (fixed batch)",
            ]
            raise _BadRequest(
                f"unknown request with keys {sorted(req.keys())}; "
                f"accepted payloads: {accepted}"
            )
        except _BadRequest as e:
            self._count("errors")
            return self._error("bad_request", str(e))
        except ValueError as e:
            # Engine-side request validation (knob/gen_lens mismatch,
            # prompt_start out of range, oversized fixed-batch serve)
            # is the client's fault; anything else escaping the engine
            # (TypeError/KeyError deep in a forward pass) is OURS and
            # must read as `internal`, not as a malformed request.
            self._count("errors")
            return self._error("bad_request", f"{type(e).__name__}: {e}")
        except Exception as e:  # noqa: BLE001 — keep the server alive
            self._count("errors")
            return self._error("internal", f"{type(e).__name__}: {e}")

    def _generate_guarded(self, req: dict, stream_f=None) -> dict:
        """Admission control around the engine: refuse while draining,
        shed when too many payloads already wait on the engine lock."""
        if self._shutdown.is_set():
            self._count("refused")
            return self._error(
                "shutting_down",
                "server is draining; no new generation work accepted",
            )
        shed_depth = None
        with self._pending_lock:
            if self._pending >= self.max_pending:
                shed_depth = self._pending
            else:
                self._pending += 1
        if shed_depth is not None:
            self._count("shed")
            # Front-door sheds are MISSES: the user got nothing, and
            # a server that sheds its way past the engine must not
            # read as 100% goodput (the invariant
            # docs/observability.md states; engine-level sheds are
            # judged through their results the same way). Outside the
            # pending lock: the ledger fold must not serialize the
            # admission gate during exactly the storm that sheds.
            self._observe_shed(req)
            # Load-proportional backoff hint: clients that honor
            # ``retry_after_s`` (see :func:`request`) spread their
            # retries with the depth of the queue they bounced off,
            # instead of hammering a shedding server in lockstep.
            return self._error(
                "overloaded",
                f"{shed_depth} generation payloads already "
                f"pending (bound {self.max_pending}); retry with "
                "backoff",
                retry_after_s=round(
                    min(max(0.1 * shed_depth, 0.05), 2.0), 3
                ),
            )
        # Enqueue stamp BEFORE the engine lock: a request's queue-wait
        # must include the time its payload spent waiting on other
        # generations, not just the engine's admission queue.
        enqueue_t = time.monotonic()
        try:
            if self._concurrent:
                self._count("requests")
                return self._generate(req, enqueue_t, stream_f)
            with self._engine_lock:
                self._count("requests")
                return self._generate(req, enqueue_t, stream_f)
        finally:
            with self._pending_lock:
                self._pending -= 1

    def _observe_synthetic(self, n: int, slo_class, enqueue_t,
                           status: str, tokens_out: int = 0) -> None:
        """Fold ``n`` synthetic wire timelines (no per-token stamps)
        into the SLO ledger — THE shared implementation for front-door
        sheds and fixed-batch serves, so the class-resolution rule
        (unknown → ``default``, bounded cardinality) lives once."""
        spec = self.slo_specs.get(
            slo_class if isinstance(slo_class, str) else "default"
        ) or self.slo_specs["default"]
        for _ in range(max(int(n), 1)):
            tl = Timeline()
            if status == "ok":
                # Only a SERVED synthetic gets measurable durations; a
                # shed's ~0-second "e2e" would evaluate UNDER any e2e
                # bound, recording a miss with zero violations — the
                # unmeasurable-on-failure rule (obs/slo.py) is what
                # makes violations explain every miss.
                tl.enqueue_t = enqueue_t
                tl.stamp_enqueue()
            tl.tokens_out = tokens_out
            tl.finish(status)
            obs_slo.observe_wire(tl, spec)

    def _observe_shed(self, req) -> None:
        """Fold a front-door shed into the SLO ledger: one ``missed``
        per request the refused payload carried (best-effort — the
        payload was never validated). Internal fan-out payloads skip,
        same as :meth:`_judge_wire`."""
        if not isinstance(req, dict) or req.get("fanout"):
            return
        reqs = req.get("requests")
        if isinstance(reqs, list):
            n = len(reqs)
        else:
            rows = req.get("input_ids")
            n = len(rows) if isinstance(rows, list) else 1
        self._observe_synthetic(n, req.get("slo_class"), None,
                                "overloaded")

    def _generate(self, req: dict, enqueue_t: float | None = None,
                  stream_f=None) -> dict:
        if "requests" in req:
            if not hasattr(self.engine, "run"):
                raise _BadRequest(
                    "'requests' payloads need a ContinuousEngine; this "
                    "server wraps a fixed-batch Engine"
                )
            prompts = [np.asarray(p, np.int32) for p in req["requests"]]
            gen_lens = req.get("gen_lens")
            if gen_lens is None:  # [] is malformed, not "use defaults"
                gen_lens = [16] * len(prompts)
            if len(gen_lens) != len(prompts):
                raise ValueError(
                    f"{len(prompts)} requests but {len(gen_lens)} gen_lens"
                )

            def knob(name, cast):
                """Per-request knob: scalar → broadcast, list → per
                request, absent/null → engine default."""
                v = req.get(name)
                if v is None:
                    return [None] * len(prompts)
                if isinstance(v, (int, float)):
                    return [cast(v)] * len(prompts)
                if len(v) != len(prompts):
                    raise ValueError(
                        f"{len(prompts)} requests but {len(v)} {name}"
                    )
                return [None if x is None else cast(x) for x in v]

            temps = knob("temperatures", float)
            top_ps = knob("top_ps", float)
            top_ks = knob("top_ks", int)
            deadlines = knob("deadline_s", float)
            # Client-supplied trace ids (docs/observability.md "Device
            # task tracer"): follow each request through admit events,
            # mega:launch events, and device-task ring records. Always
            # a list (no scalar broadcast — ids must stay per-request
            # unique); omitted/null entries get engine-assigned ids.
            trace_ids = req.get("trace_ids")
            if trace_ids is None:
                trace_ids = [None] * len(prompts)
            elif (not isinstance(trace_ids, list)
                  or len(trace_ids) != len(prompts)):
                raise ValueError(
                    f"{len(prompts)} requests but trace_ids is "
                    f"{trace_ids!r} (want a {len(prompts)}-entry list)"
                )
            else:
                trace_ids = [
                    None if x is None else str(x) for x in trace_ids
                ]
            # Ticket ids (docs/scale-out.md "Process fleet",
            # docs/serving.md "Streaming & cancellation"): per-request
            # identities. A RemoteReplica latches results by them, the
            # engines match cancellations against them, stream frames
            # carry them — and they are echoed verbatim in the
            # response, so a response carrying an id the caller no
            # longer waits on is recognized and discarded (the
            # at-least-once dedup). All of that keys BY id, so
            # duplicates within one payload would silently conflate
            # two requests — refused here, next to the shape check.
            ticket_ids = req.get("ticket_ids")
            if ticket_ids is not None and (
                    not isinstance(ticket_ids, list)
                    or len(ticket_ids) != len(prompts)):
                raise ValueError(
                    f"{len(prompts)} requests but ticket_ids is "
                    f"{ticket_ids!r} (want a {len(prompts)}-entry list)"
                )
            if ticket_ids is not None:
                given = [str(t) for t in ticket_ids if t is not None]
                if len(given) != len(set(given)):
                    raise ValueError(
                        "ticket_ids must be unique within a payload "
                        "(results latch, cancellations match, and "
                        "stream frames key by id)"
                    )
            # Slot migration (docs/scale-out.md "Slot migration &
            # handoff"): per-request snapshots resume migrated work
            # (the engine imports instead of re-prefilling);
            # ``prefill_only`` asks the engine to export right after
            # admission (the prefill→decode handoff's first hop).
            snapshots = req.get("snapshots")
            if snapshots is None:
                snapshots = [None] * len(prompts)
            elif (not isinstance(snapshots, list)
                  or len(snapshots) != len(prompts)):
                raise ValueError(
                    f"{len(prompts)} requests but snapshots is a "
                    f"{type(snapshots).__name__} of wrong shape "
                    f"(want a {len(prompts)}-entry list)"
                )
            prefill_only = req.get("prefill_only")
            if prefill_only is None:
                prefill_only = [False] * len(prompts)
            elif (not isinstance(prefill_only, list)
                  or len(prefill_only) != len(prompts)):
                raise ValueError(
                    f"{len(prompts)} requests but prefill_only is "
                    f"{prefill_only!r} (want a {len(prompts)}-entry "
                    "list)"
                )
            # SLO class (docs/observability.md "SLO goodput"): scalar
            # or per-request list. Unknown classes collapse into the
            # deployed `default` spec — outcome labels come from the
            # CONFIGURED spec names, so a client can't grow the label
            # cardinality with arbitrary strings.
            slo_cls = req.get("slo_class")
            if slo_cls is None:
                slo_classes = ["default"] * len(prompts)
            elif isinstance(slo_cls, str):
                slo_classes = [slo_cls] * len(prompts)
            elif (isinstance(slo_cls, list)
                  and len(slo_cls) == len(prompts)):
                slo_classes = [
                    "default" if c is None else str(c) for c in slo_cls
                ]
            else:
                raise ValueError(
                    f"{len(prompts)} requests but slo_class is "
                    f"{slo_cls!r} (want a string or a "
                    f"{len(prompts)}-entry list)"
                )
            # Streaming (docs/serving.md "Streaming & cancellation"):
            # per-token frames need cancellable identities — client
            # ticket_ids when given, server-assigned otherwise (echoed
            # in every frame and the summary).
            stream = bool(req.get("stream"))
            # Engine-side ids are ALWAYS strings: the cancel verb
            # coerces its ids to str, so an int ticket_id here would
            # make cancellation a silent no-op. The wire echo below
            # still returns the client's ids verbatim.
            eff_tids = (
                None if ticket_ids is None
                else [None if t is None else str(t) for t in ticket_ids]
            )
            sink = None
            if stream:
                if stream_f is None:
                    raise _BadRequest(
                        "streaming is only available over the socket "
                        "transport"
                    )
                if eff_tids is None:
                    eff_tids = [None] * len(prompts)
                eff_tids = [
                    t if t is not None
                    else f"s{next(_STREAM_IDS)}p{os.getpid()}"
                    for t in eff_tids
                ]
                sink = _StreamSink(self, stream_f, eff_tids)
                sink.attach_enqueue(enqueue_t)
                for i, sn in enumerate(snapshots):
                    if isinstance(sn, dict):
                        sink.seed(i, len(sn.get("out") or []))
            from triton_distributed_tpu.models.continuous import Request

            def _timeline() -> Timeline:
                tl = Timeline()
                tl.enqueue_t = enqueue_t  # pre-engine-lock arrival
                return tl

            results = self.engine.run(
                [
                    Request(
                        p, int(g), temperature=t, top_p=tp, top_k=tk,
                        deadline_s=dl, timeline=_timeline(),
                        trace_id=tid, snapshot=sn,
                        prefill_only=bool(po),
                        slo_class=slo_classes[i],
                        ticket_id=(
                            None if eff_tids is None else eff_tids[i]
                        ),
                        on_token=(
                            None if sink is None else sink.sink_for(i)
                        ),
                    )
                    for i, (p, g, t, tp, tk, dl, tid, sn, po) in enumerate(
                        zip(
                            prompts, gen_lens, temps, top_ps, top_ks,
                            deadlines, trace_ids, snapshots, prefill_only,
                        )
                    )
                ],
                results=True,
            )
            resp = {
                "outputs": [r.tokens.tolist() for r in results],
                # A migrated result carries its portable snapshot —
                # the caller (RemoteReplica) re-dispatches it; the
                # entry shape stays {status, reason} otherwise.
                "results": [
                    (
                        {"status": r.status, "reason": r.reason,
                         "snapshot": r.snapshot}
                        if r.snapshot is not None
                        else {"status": r.status, "reason": r.reason}
                    )
                    for r in results
                ],
                "stats": self.engine.last_stats,
            }
            # Wire-side SLO accounting belongs at the USER-facing hop:
            # internal fan-out payloads (a RemoteReplica batch carries
            # "fanout") skip it, or the fleet scrape would double-count
            # every request at the child AND the front.
            judge = not req.get("fanout")
            if sink is not None:
                # Late worker callbacks stop, tokens a resume skipped
                # back-fill, THEN the summary rides _respond.
                sink.finish(results)
                resp["frame"] = "summary"
                # Client ids echo VERBATIM (the non-streaming
                # contract); entries the client left null — and fully
                # absent lists — surface the server-ASSIGNED ids the
                # frames carried, so the summary always names every
                # request's cancellable identity.
                resp["ticket_ids"] = (
                    eff_tids if ticket_ids is None
                    else [t if t is not None else eff_tids[i]
                          for i, t in enumerate(ticket_ids)]
                )
                resp["wire"] = self._judge_wire(
                    sink.timelines, results, prompts, slo_classes,
                    observe=judge,
                )
            else:
                if judge:
                    # Non-streamed payloads still fold an e2e-only
                    # wire timeline into the SLO ledger (TTFT/TPOT
                    # need frames; see docs/observability.md).
                    tls = []
                    for p in prompts:
                        tl = Timeline()
                        tl.enqueue_t = enqueue_t
                        tl.stamp_enqueue()
                        tls.append(tl)
                    self._judge_wire(
                        tls, results, prompts, slo_classes, observe=True,
                    )
                if ticket_ids is not None:
                    resp["ticket_ids"] = ticket_ids
            if req.get("want_digest"):
                # Batch-boundary digest publication over the wire: the
                # RemoteReplica mirrors the in-process replica's
                # protocol (re-publish after every batch) without a
                # second round trip or an extra lock — the engine is
                # already quiesced here, under the same dispatch that
                # ran the batch.
                digest = getattr(self.engine, "prefix_digest", None)
                resp["prefix_digest"] = (
                    digest() if digest is not None else None
                )
            if req.get("want_tier_digest"):
                # Tier-digest piggyback (docs/scale-out.md "KV
                # fabric"): same batch-boundary publication protocol as
                # want_digest, one response field over — the remote
                # replica's router scores tier affinity from this.
                td = getattr(self.engine, "tier_digest", None)
                resp["tier_digest"] = td() if td is not None else None
            return resp
        if req.get("stream"):
            raise _BadRequest(
                "streaming needs a 'requests' payload (continuous "
                "batching); the fixed-batch input_ids path has no "
                "per-token emission (docs/serving.md 'Streaming & "
                "cancellation')"
            )
        input_ids = np.asarray(req["input_ids"], np.int32)
        gen_len = int(req.get("gen_len", 16))
        out = self.engine.serve(
            input_ids, gen_len, prompt_start=req.get("prompt_start")
        )
        if not req.get("fanout"):
            # Fixed-batch serves are judged too (e2e only, one per
            # batch row): without this, a workload driving only
            # input_ids payloads would record its SHEDS as missed but
            # never a met — goodput would read 0 on a healthy server.
            self._observe_synthetic(
                int(input_ids.shape[0]), req.get("slo_class"),
                enqueue_t, "ok", tokens_out=gen_len,
            )
        return {
            "output_ids": out.tolist(),
            "stats": self.engine.last_stats,
        }

    def _judge_wire(self, timelines, results, prompts, slo_classes,
                    *, observe: bool) -> list:
        """Finish each request's WIRE-side timeline, judge it against
        its SLO class, and (when ``observe``) fold it into the
        ``tdt_slo_*`` ledger. Returns the summary's per-request
        ``wire`` entries. Unknown classes resolve to the deployed
        ``default`` spec (bounded label cardinality)."""
        entries = []
        for i, r in enumerate(results):
            tl = timelines[i]
            if r.status == "migrated":
                # NON-terminal: the serving tier re-dispatches the
                # snapshot and the request is judged exactly once, at
                # its eventual completion — folding the export leg in
                # would record a spurious miss per healthy migration.
                entries.append({
                    "slo_class": slo_classes[i],
                    "outcome": "migrated",
                    "status": r.status,
                    "tokens_out": len(r.tokens),
                    "ttft_s": None, "tpot_s": None, "e2e_s": None,
                })
                continue
            tl.tokens_in = len(prompts[i])
            tl.tokens_out = len(r.tokens)
            tl.finish(r.status)
            spec = self.slo_specs.get(slo_classes[i])
            if spec is None:
                spec = self.slo_specs["default"]
            outcome = (
                obs_slo.observe_wire(tl, spec) if observe
                else obs_slo.judge(tl, spec)
            )
            entries.append({
                "slo_class": spec.name,
                "outcome": outcome,
                "status": r.status,
                "tokens_out": tl.tokens_out,
                "ttft_s": tl.ttft_s,
                "tpot_s": tl.tpot_s,
                "e2e_s": tl.e2e_s,
            })
        return entries

    def _serve_conn(self, conn: socket.socket) -> None:
        conn.settimeout(self.IDLE_TIMEOUT_S)
        try:
            with conn:
                self._serve_lines(conn)
        except Exception as e:  # noqa: BLE001 — a conn thread never dies loud
            # Connection-level failure: client vanished mid-request,
            # injected drop/recv fault, idle timeout. Catching broadly
            # keeps the contract that per-connection failures are
            # COUNTED (an injected FaultError is a RuntimeError, not an
            # OSError) — and the last failure is kept diagnosable in
            # the stats instead of vanishing into a bare counter. The
            # `with conn` above already closed the socket — the old
            # except-path conn.close() double-close could itself raise.
            with self._counters_lock:
                self._last_conn_error = f"{type(e).__name__}: {e}"
            self._count("conn_errors")

    def _serve_lines(self, conn: socket.socket) -> None:
        with conn.makefile("rwb") as f:
            while True:
                fault_point("server.recv")
                line = f.readline(self.MAX_LINE_BYTES + 1)
                if not line:
                    return  # client closed cleanly
                if len(line) > self.MAX_LINE_BYTES:
                    # Framing is lost beyond the bound (the line's tail
                    # is still in flight): answer, then drop the conn.
                    self._count("errors")
                    self._respond(f, self._error(
                        "bad_request",
                        f"request line exceeds {self.MAX_LINE_BYTES} "
                        "bytes; connection closed",
                    ))
                    # Drain the line's remainder before closing:
                    # unread bytes in the kernel queue turn close()
                    # into an RST, which makes the client discard the
                    # error response we just sent. The socket timeout
                    # is dropped to the drain budget too — the wall
                    # deadline alone only bounds the number of
                    # readline calls, not one call's duration, and a
                    # client dripping bytes could otherwise pin the
                    # thread (each drip resetting the 10 s idle
                    # timeout). A timeout here raises and is counted
                    # as a conn error, which a hostile client is.
                    conn.settimeout(self.drain_grace_s)
                    drain_deadline = time.monotonic() + self.drain_grace_s
                    while time.monotonic() < drain_deadline:
                        rest = f.readline(self.MAX_LINE_BYTES)
                        if not rest or rest.endswith(b"\n"):
                            break
                    return
                line = line.strip()
                if not line:
                    continue
                try:
                    payload = json.loads(line)
                except Exception as e:  # report, keep serving
                    self._count("errors")
                    self._respond(f, self._error(
                        "bad_request",
                        f"malformed JSON: {type(e).__name__}: {e}",
                    ))
                    continue
                self._respond(f, self._dispatch(payload, stream_f=f))
                if self._shutdown.is_set():
                    return

    def _respond(self, f, resp: dict) -> None:
        fault_point("server.send")
        f.write(json.dumps(resp).encode() + b"\n")
        f.flush()

    def serve_forever(self) -> None:
        """Accept loop; spawns one thread per connection and returns
        after a shutdown request has drained in-flight connections."""
        self._sock.settimeout(0.2)
        threads: list[threading.Thread] = []
        while not self._shutdown.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                threads = [t for t in threads if t.is_alive()]
                continue
            except OSError:
                break  # listener closed under us
            # Prune on EVERY accept, not just idle timeouts — under
            # continuous traffic the timeout branch never runs and the
            # list would grow one dead Thread per connection.
            threads = [t for t in threads if t.is_alive()]
            self._count("connections")
            t = threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            )
            t.start()
            threads.append(t)
        self._sock.close()
        # Graceful drain: in-flight payloads (generation included)
        # finish and answer; connection threads then exit on their own
        # (new generation payloads are refused with `shutting_down`).
        deadline = time.monotonic() + self.DRAIN_TIMEOUT_S
        for t in threads:
            t.join(max(deadline - time.monotonic(), 0.0))

    # -- lifecycle -------------------------------------------------------
    def start(self) -> "ModelServer":
        """Run the accept loop on a background thread (tests/demos)."""
        self._thread = threading.Thread(target=self.serve_forever, daemon=True)
        self._thread.start()
        return self

    def shutdown(self) -> None:
        self._shutdown.set()
        if self._thread is not None:
            # Cover serve_forever's full drain window: returning while
            # a connection thread is still inside engine.run() would
            # let callers (and the test-suite audit fixture) observe
            # the engine mid-mutation.
            self._thread.join(timeout=self.DRAIN_TIMEOUT_S + 5)
        # A Router engine owns replica worker threads: drain them too
        # (bounded by its drain_grace_s per replica) so a server
        # shutdown quiesces the whole tier, not just the socket.
        engine_shutdown = getattr(self.engine, "shutdown", None)
        if callable(engine_shutdown):
            engine_shutdown()


def _retry_backoff(attempt: int, backoff_s: float,
                   max_backoff_s: float) -> float:
    """One retry delay: exponential from ``backoff_s``, CAPPED at
    ``max_backoff_s``, with ±20% jitter. The cap keeps a long retry
    loop from sleeping for minutes once ``2**attempt`` runs away; the
    jitter keeps a fleet of clients that all bounced off the same
    respawning replica from re-arriving in lockstep and re-shedding
    each other forever (docs/scale-out.md "Process fleet")."""
    base = min(backoff_s * (2 ** attempt), max_backoff_s)
    return base * random.uniform(0.8, 1.2)


def request(
    host: str,
    port: int,
    payload: dict,
    timeout: float = 120.0,
    *,
    retries: int = 0,
    backoff_s: float = 0.25,
    max_backoff_s: float = 5.0,
) -> dict:
    """One JSON request/response round trip (client side).

    With ``retries > 0`` transient failures — connection refused/reset,
    the server vanishing mid-response, and structured ``overloaded``
    shedding — are retried with exponential backoff
    (``backoff_s * 2**attempt``, capped at ``max_backoff_s``, ±20%
    jitter — see :func:`_retry_backoff`). A shed reply carrying a
    ``retry_after_s`` hint overrides the local backoff for that
    attempt: the server knows its own queue depth, so router- or
    script-driven retries spread out instead of hammering a shedding
    replica in lockstep. Non-transient server errors raise
    ``RuntimeError`` immediately.
    """
    attempt = 0
    while True:
        try:
            with socket.create_connection((host, port), timeout=timeout) \
                    as s, s.makefile("rwb") as f:
                f.write(json.dumps(payload).encode() + b"\n")
                f.flush()
                line = f.readline()
            if not line:
                raise ConnectionError(
                    "server closed connection without a response"
                )
            resp = json.loads(line)
        except (ConnectionError, socket.timeout, TimeoutError, OSError,
                json.JSONDecodeError):
            # JSONDecodeError covers the server dying mid-response: a
            # truncated line is as transient as no line at all.
            if attempt >= retries:
                raise
            time.sleep(_retry_backoff(attempt, backoff_s, max_backoff_s))
            attempt += 1
            continue
        err = resp.get("error")
        if err is not None:
            status = err.get("status") if isinstance(err, dict) else None
            if status == "overloaded" and attempt < retries:
                hint = err.get("retry_after_s")
                # hint > 0 only (zero/absent/bogus must not collapse
                # the retry loop into back-to-back hammering), and
                # clamped: the client trusts ANY peer speaking the
                # protocol, and an arbitrary server value must not be
                # able to stall it for hours.
                if isinstance(hint, (int, float)) and hint > 0:
                    time.sleep(min(float(hint), 30.0))
                else:
                    time.sleep(
                        _retry_backoff(attempt, backoff_s, max_backoff_s)
                    )
                attempt += 1
                continue
            raise RuntimeError(f"server error: {err}")
        return resp


def request_stream(host: str, port: int, payload: dict,
                   timeout: float = 120.0):
    """Streaming client (docs/serving.md "Streaming & cancellation"):
    a generator over the wire frames of one ``requests`` payload —
    token frames as they arrive, then the summary frame, then it
    stops. ``"stream": true`` is added to the payload. A structured
    server error raises ``RuntimeError``; a connection that dies
    mid-stream raises ``ConnectionError`` (whatever frames already
    arrived were already yielded). To cancel mid-stream, send
    ``{"cmd": "cancel", "ticket_ids": [...]}`` on a SECOND connection
    using the tids the frames carry — or just close this one: the
    server detects the disconnect at its next frame write and cancels
    the payload's requests itself."""
    payload = dict(payload)
    payload["stream"] = True
    with socket.create_connection((host, port), timeout=timeout) as s, \
            s.makefile("rwb") as f:
        f.write(json.dumps(payload).encode() + b"\n")
        f.flush()
        while True:
            line = f.readline()
            if not line:
                raise ConnectionError("server closed mid-stream")
            obj = json.loads(line)
            if isinstance(obj, dict) and obj.get("error") is not None:
                raise RuntimeError(f"server error: {obj['error']}")
            yield obj
            if not (isinstance(obj, dict)
                    and obj.get("frame") == "token"):
                return
