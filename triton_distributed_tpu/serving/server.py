"""Socket model server over the Engine.

Parity: reference ``mega_triton_kernel/test/models/model_server.py`` —
a TCP server (:112-198) that owns the compiled model and answers
generation requests, with the chat/bench clients speaking a small
framed protocol. Here the protocol is newline-delimited JSON over TCP:

    → {"input_ids": [[...]], "gen_len": 32}
    ← {"output_ids": [[...]], "stats": {...}}
    → {"requests": [[...], ...], "gen_lens": [4, ...],   (continuous
       "temperatures": [0.8, ...], "top_ps": [...],       batching;
       "top_ks": [...]}                                   sampling keys
    ← {"outputs": [[...], ...], "stats": {...}}           optional)
    → {"cmd": "stats"}           ← {"stats": {...}}
    → {"cmd": "ping"}            ← {"ok": true}
    → {"cmd": "shutdown"}        ← {"ok": true}   (server then exits)

The per-request sampling keys are scalars (applied to every request)
or per-request lists; omitted/null entries fall back to the engine's
defaults.

One request at a time (the accelerator is serial anyway — the reference
server is likewise single-stream). A ``requests`` payload routes to a
:class:`~triton_distributed_tpu.models.continuous.ContinuousEngine`'s
admission/eviction loop (mixed prompt/gen lengths, paged pool, prefix
cache when the engine enables it); ``input_ids`` routes to
``Engine.serve`` fixed-batch serving. A server constructed over a
ContinuousEngine only speaks the former, over an Engine only the latter.
"""

from __future__ import annotations

import json
import socket
import threading

import numpy as np

from triton_distributed_tpu.models.engine import Engine


class ModelServer:
    """Own a listening socket + an Engine; serve generation requests."""

    def __init__(self, engine: Engine, host: str = "127.0.0.1", port: int = 0):
        self.engine = engine
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(4)
        self.host, self.port = self._sock.getsockname()
        self._shutdown = threading.Event()
        self._thread: threading.Thread | None = None

    # -- request handling ------------------------------------------------
    def _handle(self, req: dict) -> dict:
        if req.get("cmd") == "ping":
            return {"ok": True}
        if req.get("cmd") == "shutdown":
            self._shutdown.set()
            return {"ok": True}
        if req.get("cmd") == "stats":
            return {"stats": self.engine.last_stats}
        if "requests" in req:
            if not hasattr(self.engine, "run"):
                raise TypeError(
                    "'requests' payloads need a ContinuousEngine; this "
                    "server wraps a fixed-batch Engine"
                )
            prompts = [np.asarray(p, np.int32) for p in req["requests"]]
            gen_lens = req.get("gen_lens")
            if gen_lens is None:  # [] is malformed, not "use defaults"
                gen_lens = [16] * len(prompts)
            if len(gen_lens) != len(prompts):
                raise ValueError(
                    f"{len(prompts)} requests but {len(gen_lens)} gen_lens"
                )

            def knob(name, cast):
                """Per-request sampling knob: scalar → broadcast,
                list → per request, absent/null → engine default."""
                v = req.get(name)
                if v is None:
                    return [None] * len(prompts)
                if isinstance(v, (int, float)):
                    return [cast(v)] * len(prompts)
                if len(v) != len(prompts):
                    raise ValueError(
                        f"{len(prompts)} requests but {len(v)} {name}"
                    )
                return [None if x is None else cast(x) for x in v]

            temps = knob("temperatures", float)
            top_ps = knob("top_ps", float)
            top_ks = knob("top_ks", int)
            from triton_distributed_tpu.models.continuous import Request

            outs = self.engine.run([
                Request(p, int(g), temperature=t, top_p=tp, top_k=tk)
                for p, g, t, tp, tk in zip(
                    prompts, gen_lens, temps, top_ps, top_ks
                )
            ])
            return {
                "outputs": [o.tolist() for o in outs],
                "stats": self.engine.last_stats,
            }
        input_ids = np.asarray(req["input_ids"], np.int32)
        gen_len = int(req.get("gen_len", 16))
        out = self.engine.serve(
            input_ids, gen_len, prompt_start=req.get("prompt_start")
        )
        return {
            "output_ids": out.tolist(),
            "stats": self.engine.last_stats,
        }

    # An idle client must not wedge the single-threaded accept loop: a
    # connection that sends nothing within this window is dropped.
    IDLE_TIMEOUT_S = 10.0

    def _serve_conn(self, conn: socket.socket) -> None:
        conn.settimeout(self.IDLE_TIMEOUT_S)
        try:
            self._serve_lines(conn)
        except (socket.timeout, TimeoutError, OSError):
            conn.close()

    def _serve_lines(self, conn: socket.socket) -> None:
        with conn, conn.makefile("rwb") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    resp = self._handle(json.loads(line))
                except Exception as e:  # report, keep serving
                    resp = {"error": f"{type(e).__name__}: {e}"}
                f.write(json.dumps(resp).encode() + b"\n")
                f.flush()
                if self._shutdown.is_set():
                    return

    def serve_forever(self) -> None:
        """Accept loop; returns after a shutdown request."""
        self._sock.settimeout(0.2)
        while not self._shutdown.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            self._serve_conn(conn)
        self._sock.close()

    # -- lifecycle -------------------------------------------------------
    def start(self) -> "ModelServer":
        """Run the accept loop on a background thread (tests/demos)."""
        self._thread = threading.Thread(target=self.serve_forever, daemon=True)
        self._thread.start()
        return self

    def shutdown(self) -> None:
        self._shutdown.set()
        if self._thread is not None:
            self._thread.join(timeout=5)


def request(host: str, port: int, payload: dict, timeout: float = 120.0) -> dict:
    """One JSON request/response round trip (client side)."""
    with socket.create_connection((host, port), timeout=timeout) as s, \
            s.makefile("rwb") as f:
        f.write(json.dumps(payload).encode() + b"\n")
        f.flush()
        line = f.readline()
    if not line:
        raise ConnectionError("server closed connection without a response")
    resp = json.loads(line)
    if "error" in resp:
        raise RuntimeError(f"server error: {resp['error']}")
    return resp
