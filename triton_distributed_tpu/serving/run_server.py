"""Model-server entry point.

Parity: the reference's server launch path
(``mega_triton_kernel/test/models/model_server.py`` ``__main__``).
Beyond parity, ``--replicas N`` stands the multi-engine serving tier
up behind the same socket: N ``ContinuousEngine`` replicas behind the
prefix-affinity router (docs/scale-out.md), served by the same wire
protocol (``requests`` payloads only — the router speaks continuous
batching).

It is also the process-fleet replica entry (docs/scale-out.md
"Process fleet"): ``serving/supervisor.py`` spawns one of these per
replica with ``--port-file`` (the child binds port 0 and writes the
address it got, atomically, for the supervisor to pick up) and — in
tests and the fleet bench — ``--model stub``, which serves the
deterministic :class:`~triton_distributed_tpu.models.stub.StubEngine`
(real radix control plane, hash-function "model", no JAX model load)
behind the production wire server.

Usage:
    python -m triton_distributed_tpu.serving.run_server \
        --model tiny --tp 1 --port 8765
    python -m triton_distributed_tpu.serving.run_server \
        --model tiny --replicas 2 --policy affinity
    python -m triton_distributed_tpu.serving.run_server \
        --model stub --port-file /tmp/r0.port --stub-delay 0.2
"""

from __future__ import annotations

import argparse
import os
import sys

import jax


def resolve_model_args(
    model: str, num_experts: int = 0, top_k: int = 0,
    moe_intermediate: int = 0,
) -> tuple[str, dict]:
    """``--model moe`` alias resolution (ONE definition for main and
    tests): the tiny-moe Qwen3MoE preset, with the expert knobs as
    config overrides. Non-moe names pass through with the same
    overrides applied (an MoE checkpoint dir can be resized too)."""
    name = "tiny-moe" if model == "moe" else model
    overrides: dict = {}
    if num_experts:
        overrides["num_experts"] = num_experts
    if top_k:
        overrides["num_experts_per_tok"] = top_k
    if moe_intermediate:
        overrides["moe_intermediate_size"] = moe_intermediate
    return name, overrides


def _write_port_file(path: str | None, host: str, port: int) -> None:
    """Atomic port handshake: the supervisor polls for PATH, so the
    write must never be observable half-done — write a sibling temp
    file, then rename (atomic on POSIX)."""
    if not path:
        return
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(f"{host}:{port}\n")
    os.replace(tmp, path)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--model", default="tiny",
                   help="model preset, checkpoint dir, 'stub', or "
                   "'moe' (the tiny-moe Qwen3MoE preset; size it with "
                   "--num-experts/--top-k/--moe-intermediate — "
                   "docs/serving.md 'MoE serving')")
    p.add_argument("--num-experts", type=int, default=0,
                   help="override the MoE preset's expert count "
                   "(routed experts; must divide by --tp for "
                   "--mode mega's EP sharding)")
    p.add_argument("--top-k", type=int, default=0,
                   help="override the MoE preset's experts-per-token")
    p.add_argument("--moe-intermediate", type=int, default=0,
                   help="override the MoE preset's per-expert FFN width")
    p.add_argument("--tp", type=int, default=1)
    p.add_argument("--host", default="127.0.0.1",
                   help="bind address; 0.0.0.0 listens on every "
                   "interface (pair with --advertise-host so peers "
                   "get a ROUTABLE address, docs/scale-out.md "
                   "'Multi-host fleet')")
    p.add_argument("--advertise-host", default=None, metavar="ADDR",
                   help="the address OTHER machines reach this server "
                   "at — written to the --port-file handshake, "
                   "reported in server_stats, and broadcast in fabric "
                   "peer tables instead of the bind address (which "
                   "with --host 0.0.0.0 is unroutable). Default: the "
                   "bind address.")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--temperature", type=float, default=0.0)
    p.add_argument("--mode", default="xla",
                   choices=["xla", "pallas", "mega"])
    p.add_argument("--ns", type=int, default=8,
                   help="with --mode mega: tokens fused per decode "
                   "launch (the NS-step chunk; docs/megakernel.md "
                   "'Serving fast path'). Larger NS amortizes more "
                   "host dispatch per token at coarser admission "
                   "granularity; perf/mega_serve_bench.py sweeps it.")
    p.add_argument("--resident", action="store_true",
                   help="with --mode mega: resident decode — pipeline "
                   "round i+1's launch before draining round i, with "
                   "admit/retire/cancel flowing through the host work "
                   "ring (docs/megakernel.md 'Resident decode'). "
                   "Continuous-batching engines only.")
    p.add_argument("--kv-dtype", default=None, choices=["int8"],
                   help="int8-quantized paged KV pool (docs/serving.md "
                   "'Quantized KV cache'); composes with every --mode "
                   "including mega (in-kernel dequant). The single-"
                   "Engine path then serves paged.")
    p.add_argument("--speculative", type=int, default=0, metavar="K",
                   help="self-drafting speculative decoding, up to K "
                   "draft tokens per row (docs/serving.md 'Speculative "
                   "decoding'); excluded with --mode mega — the NS-step "
                   "fused launch already amortizes dispatch")
    p.add_argument("--replicas", type=int, default=0,
                   help="serve N ContinuousEngine replicas behind the "
                   "prefix-affinity router (0 = single fixed-batch "
                   "Engine, the legacy path); docs/scale-out.md")
    p.add_argument("--fleet", type=int, default=0,
                   help="boot a SUPERVISED PROCESS fleet of N run_server "
                   "children (FleetSupervisor: heartbeats, crash "
                   "respawn, snapshot-based recovery — docs/scale-out.md "
                   "'Process fleet') and serve the router in THIS "
                   "process; children inherit --model/--mode/--kv-dtype/"
                   "--speculative/--ns/--resident/--max-batch (or the "
                   "--stub-* knobs with --model stub)")
    p.add_argument("--continuous", action="store_true",
                   help="serve ONE ContinuousEngine (continuous "
                   "batching, 'requests' payloads) instead of the "
                   "fixed-batch Engine — the process-fleet child shape")
    p.add_argument("--policy", default=None,
                   choices=["affinity", "round_robin",
                            "migrate_after_prefill", "pools"],
                   help="router policy with --replicas/--fleet "
                   "(migrate_after_prefill = prefill→decode handoff; "
                   "pools = role-aware placement over prefill/decode "
                   "pools, docs/scale-out.md 'Disaggregated pools & "
                   "autoscaling'). Default: affinity, or pools when "
                   "--prefill-replicas/--decode-replicas shape the "
                   "fleet")
    p.add_argument("--prefill-replicas", type=int, default=0,
                   help="boot a ROLE-TYPED process fleet: N children "
                   "tagged prefill (fresh requests land here; the "
                   "pools policy hands their slots to the decode pool "
                   "after the first token — docs/scale-out.md "
                   "'Disaggregated pools & autoscaling'). Goes with "
                   "--decode-replicas; sizes the fleet itself, so "
                   "drop --fleet N")
    p.add_argument("--decode-replicas", type=int, default=0,
                   help="role-typed fleet: N children tagged decode "
                   "(migrated post-prefill slots decode here, placed "
                   "by digest-match vs pool pressure)")
    p.add_argument("--autoscale", action="store_true",
                   help="run the goodput-driven pool autoscaler over "
                   "the role-typed fleet (scale-up spawns role-tagged "
                   "children, scale-down drains losslessly; bounds "
                   "[N, N+2] per pool) — needs --prefill-replicas/"
                   "--decode-replicas")
    p.add_argument("--snapshot-every", type=int, default=0,
                   help="ContinuousEngine incremental slot snapshots "
                   "every N scheduling rounds (0 = off) — the "
                   "export_slots verb's crash-recovery feed "
                   "(docs/scale-out.md 'Slot migration & handoff')")
    p.add_argument("--tier-bytes", type=int, default=0,
                   help="host-RAM durable KV tier capacity in bytes "
                   "per engine (0 = off): evicted radix pages spill "
                   "to the tier and fault back on digest match, "
                   "cheaper than re-prefill (docs/serving.md 'Tiered "
                   "KV'); applies to --continuous/--replicas engines "
                   "and is inherited by --fleet children")
    p.add_argument("--tier-dir", default=None, metavar="DIR",
                   help="disk tier directory (write-through, atomic "
                   "rename, checksummed entries): spilled pages AND "
                   "the snapshot buffer survive a process restart. "
                   "With --replicas/--fleet each engine gets DIR/r<i> "
                   "unless --tier-shared makes DIR one fleet-wide "
                   "fabric dir; with --fleet the supervisor also "
                   "persists pulled snapshots under DIR/resume, so ONE "
                   "flag boots a restart-safe fleet (docs/scale-out.md "
                   "'Durable snapshots')")
    p.add_argument("--tier-shared", action="store_true",
                   help="share ONE KV tier across the replicas instead "
                   "of per-engine DIR/r<i> splits (docs/scale-out.md "
                   "'KV fabric'): with --fleet every child mounts the "
                   "same --tier-dir (digest-keyed, checksummed entries "
                   "make concurrent writers safe, and a fresh "
                   "autoscaler replica boots warm from the pool's "
                   "spills); with --replicas the engines share one "
                   "in-process PageStore")
    p.add_argument("--hosts", default=None, metavar="H1,H2,...",
                   help="with --fleet/--prefill-replicas: spread the "
                   "children across these ssh-reachable hosts "
                   "(SSHLauncher, docs/scale-out.md 'Multi-host "
                   "fleet'); replicas are assigned round-robin and "
                   "the supervisor treats each host as a failure "
                   "domain (whole-host loss classifies as ONE "
                   "host_down, survivors are re-placed)")
    p.add_argument("--fake-hosts", type=int, default=0, metavar="N",
                   help="with --fleet/--prefill-replicas: partition "
                   "the LOCAL children into N named fake hosts "
                   "(process groups h0..h{N-1}) so host-loss "
                   "semantics run without real ssh — the chaos-suite "
                   "and host_loss_bench shape")
    p.add_argument("--connect-timeout", type=float, default=10.0,
                   help="supervisor-side dial timeout in seconds for "
                   "replica connections (cross-host dials to a dead "
                   "machine fail on THIS deadline instead of the OS "
                   "default)")
    p.add_argument("--snapshot-s", type=float, default=0.0,
                   help="with --fleet: supervisor snapshot-pull period "
                   "in seconds (0 = off) — failed replicas' requests "
                   "then resume from the last snapshot instead of "
                   "replaying from the prompt")
    p.add_argument("--max-batch", type=int, default=4,
                   help="decode slots per replica with --replicas")
    p.add_argument("--drain-grace", type=float, default=2.0,
                   help="drain grace (seconds) for server connections "
                   "AND router replica drains")
    p.add_argument("--request-timeout", type=float, default=0.0,
                   help="with --replicas: router-observed replica "
                   "timeout in seconds — a replica sitting on a "
                   "request this long is marked dead and the request "
                   "re-routed (0 = off, the default: a cold first "
                   "request compiles for minutes and must not read as "
                   "a hang)")
    p.add_argument("--port-file", default=None, metavar="PATH",
                   help="after binding, atomically write 'host:port' "
                   "to PATH — the supervisor's port-discovery "
                   "handshake for children launched with --port 0 "
                   "(docs/scale-out.md 'Process fleet')")
    p.add_argument("--stub-delay", type=float, default=0.0,
                   help="with --model stub: per-batch wall-time floor "
                   "in seconds (holds a batch in flight so chaos "
                   "tests can kill the process mid-batch)")
    p.add_argument("--stub-pages", type=int, default=256,
                   help="with --model stub: page-pool size")
    p.add_argument("--stub-page-size", type=int, default=16,
                   help="with --model stub: tokens per page")
    p.add_argument("--stub-max-batch", type=int, default=0,
                   help="with --model stub: decode-slot capacity per "
                   "continuous-batching round (an N-request batch "
                   "costs ceil(N/cap) rounds of --stub-delay wall "
                   "time; 0 = unbounded). Gives a stub replica FINITE "
                   "throughput so capacity benches can saturate it "
                   "(perf/pools_bench.py)")
    p.add_argument("--cp", type=int, default=1, metavar="N",
                   help="context-parallel prefill width (docs/"
                   "serving.md 'Long-context serving'): shard ONE "
                   "request's chunked prefill over N virtual ranks "
                   "with the block-KV exchange overlapped under the "
                   "next block's attention. Continuous engines only; "
                   "excluded with --mode mega, --resident, "
                   "--speculative and --model stub.")
    p.add_argument("--rank-page-budget", type=int, default=0,
                   metavar="TOKENS",
                   help="per-rank resident KV budget in tokens "
                   "(docs/serving.md 'Long-context serving'): a "
                   "request whose KV exceeds it serves as a SHARDED "
                   "slot — resident pages up to the budget, cold "
                   "pages demoted to the KV tier and faulted back on "
                   "demand. Requires --tier-bytes/--tier-dir and the "
                   "continuous stack; excluded with --mode mega, "
                   "--resident, --speculative and --model stub.")
    p.add_argument("--slo-ttft-ms", type=float, default=0.0,
                   help="default-class SLO deadline on WIRE-side time "
                   "to first token, milliseconds (0 = unbounded); the "
                   "{'cmd':'slo'} verb reports goodput against it "
                   "(docs/observability.md 'SLO goodput')")
    p.add_argument("--slo-tpot-ms", type=float, default=0.0,
                   help="default-class SLO deadline on wire-side "
                   "per-token time, milliseconds (0 = unbounded)")
    p.add_argument("--slo-e2e-ms", type=float, default=0.0,
                   help="default-class SLO deadline on wire-side "
                   "end-to-end latency, milliseconds (0 = unbounded)")
    p.add_argument("--trace", default=None, metavar="DIR",
                   help="wrap the whole run in group_profile(DIR) and "
                   "merge ONE chrome timeline on exit — host "
                   "trace_spans plus, with --mode mega, the device "
                   "task tracer's per-task rows (docs/profiling.md "
                   "'Device task tracer'); prints the merged path. "
                   "Also turns the engines' kernel_trace knob on and "
                   "surfaces both in server_stats.")
    args = p.parse_args(argv)
    if args.speculative and args.mode == "mega":
        # Explicit, named-knob refusal naming the ACTUAL conflicting
        # pair — speculative × mega — and fired BEFORE any model-name
        # resolution so every --model (qwen/moe/stub) gets the same
        # named-flag message instead of whatever resolve_model_args
        # surfaces first. (The engines raise the same conflict; failing
        # at the CLI names the flags to change.)
        p.error(
            "--speculative and --mode mega do not compose: the "
            "megakernel's NS-step fused launch advances all slots in "
            "lockstep and already amortizes per-step dispatch, and "
            "the resident work ring splices whole slots between "
            "rounds — never a mid-launch verify/rollback "
            "(docs/megakernel.md 'Resident decode'). Drop "
            "--speculative or use --mode xla/pallas."
        )
    if args.resident and args.mode != "mega":
        # Same fail-fast convention: resident decode IS the megakernel's
        # work-ring round loop — there is nothing to make resident on
        # the xla/pallas paths, and silently ignoring the flag would
        # leave an operator believing the pipelined dispatch is on.
        p.error("--resident requires --mode mega (resident decode is "
                "the megakernel's work-ring round loop; "
                "docs/megakernel.md 'Resident decode')")
    if args.ns < 1:
        p.error("--ns must be >= 1")
    # Long-context flags (docs/serving.md "Long-context serving") —
    # the same fail-fast-by-flag-name convention: every path that
    # would silently ignore them refuses up front.
    if args.cp < 1:
        p.error("--cp takes a width >= 1")
    longctx = args.cp > 1 or args.rank_page_budget
    if longctx:
        if args.model == "stub":
            p.error(
                "--cp/--rank-page-budget do nothing on --model stub "
                "(the control-plane stub runs no attention to shard); "
                "use a real --model."
            )
        if args.mode == "mega" or args.resident:
            p.error(
                "--cp/--rank-page-budget compose with the xla/pallas "
                "paths only: --mode mega and --resident drive slots "
                "through fused programs that bypass the per-chunk "
                "exchange schedule and the sharded partial-merge "
                "decode. Drop those flags or use --mode xla/pallas."
            )
        if args.speculative:
            p.error(
                "--cp/--rank-page-budget and --speculative do not "
                "compose (verify chunks bypass the sharded-slot "
                "programs); drop one."
            )
        if not (args.continuous or args.replicas or args.fleet > 0):
            p.error(
                "--cp/--rank-page-budget ride the continuous serving "
                "stack only: add --continuous, --replicas N, or "
                "--fleet N."
            )
    if args.rank_page_budget and not (args.tier_bytes or args.tier_dir):
        p.error(
            "--rank-page-budget needs a KV tier for the demoted cold "
            "pages: add --tier-bytes N and/or --tier-dir DIR."
        )
    # --model moe: the Qwen3MoE serving alias (tiny-moe preset so a
    # laptop/CI run needs no checkpoint), sized by the knob overrides.
    model_name, overrides = resolve_model_args(
        args.model, args.num_experts, args.top_k, args.moe_intermediate
    )
    if (args.tier_bytes or args.tier_dir) and args.fleet == 0 and (
            args.model == "stub"
            or not (args.replicas or args.continuous)):
        # Same fail-fast convention: the fixed-batch Engine (and the
        # single stub server) has no tier — silently ignoring the
        # flags would leave an operator believing restart-safety is on.
        p.error(
            "--tier-bytes/--tier-dir ride the continuous serving "
            "stack only (docs/serving.md 'Tiered KV'): add "
            "--continuous, --replicas N, or --fleet N."
        )
    if args.tier_bytes and args.fleet > 0 and args.model == "stub":
        p.error(
            "--tier-bytes does nothing on a stub fleet (stub children "
            "have no KV tier); --tier-dir still arms the supervisor's "
            "durable resume store, or use a real --model."
        )
    if args.tier_shared and (args.hosts or args.fake_hosts):
        # A shared tier dir is files on ONE machine's disk; children
        # on another host would mount a path that isn't there (or
        # worse, a same-named local dir holding nothing). Refuse by
        # flag name — the cross-host KV path is the wire fabric, which
        # per-child tiers get for free from the supervisor's
        # tier_peers broadcast.
        p.error(
            "--tier-shared shares a tier through ONE host's "
            "filesystem and cannot cross --hosts/--fake-hosts "
            "boundaries; drop --tier-shared (per-child --tier-dir "
            "tiers reach each other over the wire KV fabric, "
            "docs/scale-out.md 'KV fabric')."
        )
    if args.tier_shared:
        # Same fail-fast-by-flag-name convention: a shared tier only
        # means something when there are multiple engines to share it.
        many = (args.fleet > 0 or args.replicas > 1
                or args.prefill_replicas > 0 or args.decode_replicas > 0)
        if not many:
            p.error(
                "--tier-shared shares ONE KV tier ACROSS replicas "
                "(docs/scale-out.md 'KV fabric'); add --fleet N, "
                "--replicas N (N >= 2), or the --prefill-replicas/"
                "--decode-replicas pool shape."
            )
        if args.model == "stub" and args.replicas == 0:
            p.error(
                "--tier-shared does nothing on a stub fleet (stub "
                "children have no KV tier); use a real --model."
            )
        if (args.fleet > 0 or args.prefill_replicas > 0
                or args.decode_replicas > 0) and not args.tier_dir:
            p.error(
                "--tier-shared on a PROCESS fleet shares through disk "
                "— the children are separate processes, so give the "
                "common directory with --tier-dir DIR."
            )
        if args.replicas > 1 and not (args.tier_bytes or args.tier_dir):
            p.error(
                "--tier-shared needs a tier to share: add --tier-bytes "
                "N and/or --tier-dir DIR."
            )
    # Role-typed pools (docs/scale-out.md "Disaggregated pools &
    # autoscaling") — fail-fast by flag name on every path that would
    # silently ignore them (the PR 12 guardrail convention).
    pool_fleet = args.prefill_replicas > 0 or args.decode_replicas > 0
    if pool_fleet:
        if args.prefill_replicas <= 0 or args.decode_replicas <= 0:
            p.error(
                "--prefill-replicas and --decode-replicas go together "
                "(a one-role fleet has nowhere to hand prefilled "
                "slots); give both, each >= 1."
            )
        if args.fleet:
            p.error(
                "--prefill-replicas/--decode-replicas size the fleet "
                "themselves (prefill+decode children); drop --fleet N."
            )
        if args.replicas or args.continuous:
            p.error(
                "--prefill-replicas/--decode-replicas are PROCESS-"
                "fleet pool shapes; --replicas/--continuous serve "
                "in-process engines that would silently ignore the "
                "role tags. Drop those flags."
            )
        if args.policy not in (None, "pools"):
            p.error(
                f"--policy {args.policy} ignores replica roles; a "
                "role-typed fleet routes with --policy pools (the "
                "default when --prefill-replicas/--decode-replicas "
                "are given)."
            )
    if args.autoscale and not pool_fleet:
        p.error(
            "--autoscale resizes role pools: add --prefill-replicas N "
            "and --decode-replicas M (docs/scale-out.md "
            "'Disaggregated pools & autoscaling')."
        )
    if args.hosts and args.fake_hosts:
        p.error(
            "--hosts and --fake-hosts are rival launchers (real ssh "
            "spawns vs local process-group fakes); give one."
        )
    if (args.hosts or args.fake_hosts) and not (
            args.fleet > 0 or pool_fleet):
        p.error(
            "--hosts/--fake-hosts place PROCESS-fleet children on "
            "failure domains; add --fleet N or the "
            "--prefill-replicas/--decode-replicas pool shape "
            "(docs/scale-out.md 'Multi-host fleet')."
        )
    if args.fake_hosts < 0:
        p.error("--fake-hosts takes N >= 1 fake hosts.")
    policy = args.policy or ("pools" if pool_fleet else "affinity")

    from triton_distributed_tpu.serving.server import ModelServer

    # Default-class SLO deadlines (docs/observability.md "SLO
    # goodput"): the FRONT server judges wire-side timelines against
    # these; fleet children never need them (their batches are
    # internal fan-out and skip the ledger).
    slo = None
    if args.slo_ttft_ms or args.slo_tpot_ms or args.slo_e2e_ms:
        from triton_distributed_tpu.obs.slo import SLOSpec

        slo = SLOSpec(
            "default",
            ttft_s=(args.slo_ttft_ms / 1e3) if args.slo_ttft_ms else None,
            tpot_s=(args.slo_tpot_ms / 1e3) if args.slo_tpot_ms else None,
            e2e_s=(args.slo_e2e_ms / 1e3) if args.slo_e2e_ms else None,
        )

    if args.fleet > 0 or pool_fleet:
        # Supervised process fleet (docs/scale-out.md "Process
        # fleet"): N run_server children under the FleetSupervisor,
        # the router served from THIS process — no model loads here.
        # --prefill-replicas/--decode-replicas shape the same fleet
        # into role-typed pools (docs/scale-out.md "Disaggregated
        # pools & autoscaling").
        from triton_distributed_tpu.serving.supervisor import (
            FleetSupervisor,
            ReplicaSpec,
            stub_spec,
        )

        if pool_fleet:
            members = (
                [(f"p{i}", "prefill")
                 for i in range(args.prefill_replicas)]
                + [(f"d{i}", "decode")
                   for i in range(args.decode_replicas)]
            )
        else:
            members = [(f"r{i}", "mixed") for i in range(args.fleet)]
        if args.model == "stub":
            def make_spec(name: str, role: str = "mixed") -> ReplicaSpec:
                return stub_spec(
                    name, delay_s=args.stub_delay,
                    num_pages=args.stub_pages,
                    page_size=args.stub_page_size, role=role,
                    max_batch=args.stub_max_batch,
                )
        else:
            child = [
                sys.executable, "-m",
                "triton_distributed_tpu.serving.run_server",
                "--model", args.model, "--port", "0", "--continuous",
                "--mode", args.mode, "--tp", str(args.tp),
                "--max-batch", str(args.max_batch),
                "--temperature", str(args.temperature),
            ]
            if args.kv_dtype:
                child += ["--kv-dtype", args.kv_dtype]
            if args.speculative:
                child += ["--speculative", str(args.speculative)]
            if args.ns != 8:
                child += ["--ns", str(args.ns)]
            if args.resident:
                child += ["--resident"]
            # --tier-dir promises a restart-safe fleet from one flag:
            # children must actually EXPORT snapshots for the
            # supervisor's resume store to hold anything (the
            # supervisor derives its pull cadence from resume_dir the
            # same way). An explicit --snapshot-every still wins.
            snap_every = args.snapshot_every or (8 if args.tier_dir else 0)
            if snap_every:
                child += ["--snapshot-every", str(snap_every)]
            if args.num_experts:
                child += ["--num-experts", str(args.num_experts)]
            if args.top_k:
                child += ["--top-k", str(args.top_k)]
            if args.moe_intermediate:
                child += ["--moe-intermediate", str(args.moe_intermediate)]
            if args.tier_bytes:
                child += ["--tier-bytes", str(args.tier_bytes)]
            if args.cp > 1:
                child += ["--cp", str(args.cp)]
            if args.rank_page_budget:
                child += ["--rank-page-budget",
                          str(args.rank_page_budget)]

            def make_spec(name: str, role: str = "mixed") -> ReplicaSpec:
                argv_i = list(child)
                if args.tier_dir:
                    # Default: per-child tier dirs — one disk tier per
                    # engine (digest-keyed entries would be content-
                    # identical across children, but per-child dirs
                    # keep snapshot buffers and byte accounting
                    # disjoint). --tier-shared mounts every child on
                    # the SAME dir instead (docs/scale-out.md "KV
                    # fabric"): atomic-rename writes and checksummed,
                    # digest-keyed entries make concurrent writers
                    # safe, and a fresh autoscaler replica's disk
                    # prescan finds the pool's spills at boot — the
                    # warm-boot path.
                    argv_i += [
                        "--tier-dir",
                        (args.tier_dir if args.tier_shared
                         else os.path.join(args.tier_dir, name)),
                    ]
                return ReplicaSpec(name, argv_i, role=role)

        specs = [make_spec(name, role) for name, role in members]
        launcher = None
        if args.hosts or args.fake_hosts:
            # Multi-host fleet (docs/scale-out.md "Multi-host fleet"):
            # spread the children round-robin across named failure
            # domains so losing a whole host is ONE host_down event
            # with parallel re-placement, not N independent timeouts.
            from triton_distributed_tpu.serving.launcher import (
                FakeHostLauncher,
                SSHLauncher,
            )

            if args.hosts:
                host_names = [h.strip() for h in args.hosts.split(",")
                              if h.strip()]
                if not host_names:
                    p.error("--hosts got no host names.")
                launcher = SSHLauncher(host_names)
            else:
                host_names = [f"h{i}" for i in range(args.fake_hosts)]
                launcher = FakeHostLauncher(host_names)
            for i, spec in enumerate(specs):
                spec.host = host_names[i % len(host_names)]
        sup = FleetSupervisor(
            specs, policy=policy, snapshot_s=args.snapshot_s,
            launcher=launcher,
            connect_timeout_s=args.connect_timeout,
            # --tier-dir makes the FLEET restart-safe too: pulled
            # snapshots persist under DIR/resume and a restarted
            # supervisor resumes re-submitted requests from them.
            resume_dir=(os.path.join(args.tier_dir, "resume")
                        if args.tier_dir else None),
            # Tiered real-model children carry a FabricClient; the
            # supervisor broadcasts the peer table so local misses can
            # fault back over the wire (docs/scale-out.md "KV fabric").
            tier_fabric=(args.model != "stub"
                         and bool(args.tier_bytes or args.tier_dir)),
            router_kw={
                "drain_grace_s": args.drain_grace,
                "request_timeout_s": args.request_timeout or None,
            },
        )
        router = sup.start()
        scaler = None
        if args.autoscale:
            from triton_distributed_tpu.serving.autoscaler import (
                Autoscaler,
            )

            scaler = Autoscaler(
                sup, lambda role, name: make_spec(name, role),
                pool_bounds={
                    "prefill": (args.prefill_replicas,
                                args.prefill_replicas + 2),
                    "decode": (args.decode_replicas,
                               args.decode_replicas + 2),
                },
                drain_grace_s=args.drain_grace,
            ).start()
        server = ModelServer(
            router, host=args.host, port=args.port,
            advertise_host=args.advertise_host,
            drain_grace_s=args.drain_grace, slo=slo,
        )
        shape = (f"{args.prefill_replicas}p+{args.decode_replicas}d"
                 if pool_fleet else f"x{args.fleet}")
        print(f"serving {args.model} fleet {shape} "
              f"({policy} router"
              f"{', autoscaled' if scaler is not None else ''}, "
              f"logs {sup.log_dir}) on "
              f"{server.host}:{server.port}")
        _write_port_file(args.port_file, server.advertise_host, server.port)
        try:
            server.serve_forever()
        finally:
            if scaler is not None:
                scaler.stop()
            sup.shutdown()
        return 0

    if args.model == "stub":
        # Process-fleet replica stub: the full wire server over the
        # deterministic control-plane engine — no mesh, no model load,
        # ~import-cost startup (models/stub.py).
        from triton_distributed_tpu.models.stub import StubEngine

        engine = StubEngine(
            num_pages=args.stub_pages, page_size=args.stub_page_size,
            delay_s=args.stub_delay, max_batch=args.stub_max_batch,
        )
        server = ModelServer(
            engine, host=args.host, port=args.port,
            advertise_host=args.advertise_host,
            drain_grace_s=args.drain_grace, slo=slo,
        )
        print(f"serving stub on {server.host}:{server.port}")
        _write_port_file(args.port_file, server.advertise_host, server.port)
        server.serve_forever()
        return 0

    from triton_distributed_tpu.models import AutoLLM
    from triton_distributed_tpu.models.engine import Engine
    from triton_distributed_tpu.runtime.mesh import initialize_distributed

    ctx = initialize_distributed(tp=args.tp, devices=jax.devices()[: args.tp])
    model = AutoLLM.from_pretrained(model_name, ctx=ctx, **overrides)
    # --trace: device-side kernel tracing rides the mega engines only
    # (the xla/pallas paths have no device ring); host profiling wraps
    # the run regardless of mode.
    kernel_trace = bool(args.trace) and args.mode == "mega"
    if args.replicas > 0:
        from triton_distributed_tpu.models.continuous import ContinuousEngine
        from triton_distributed_tpu.serving.router import Router

        tiered = bool(args.tier_bytes or args.tier_dir)
        shared_tier = None
        if tiered and args.tier_shared:
            # One in-process PageStore behind every replica
            # (docs/scale-out.md "KV fabric"): each engine's spills
            # land where its siblings' fault-backs look, no fabric
            # round-trip needed. Owner-only deletes keep eviction safe.
            from triton_distributed_tpu.models.kv_tier import PageStore

            shared_tier = PageStore(
                capacity_bytes=args.tier_bytes or (64 << 20),
                dir=args.tier_dir, fsync=False,
            )
        engines = [
            ContinuousEngine(
                model, max_batch=args.max_batch, mode=args.mode,
                temperature=args.temperature, prefix_cache=True,
                kv_dtype=args.kv_dtype, speculative=args.speculative,
                kernel_trace=kernel_trace,
                ns=args.ns, resident=args.resident,
                snapshot_every=args.snapshot_every,
                cp=args.cp, rank_page_budget=args.rank_page_budget,
                tier=shared_tier,
                tier_bytes=args.tier_bytes,
                tier_dir=(os.path.join(args.tier_dir, f"r{i}")
                          if args.tier_dir and shared_tier is None
                          else None),
            )
            for i in range(args.replicas)
        ]
        if tiered and shared_tier is None and len(engines) > 1:
            # Per-replica tiers → cross-wire the KV fabric in-process
            # (docs/scale-out.md "KV fabric"): each engine's local tier
            # miss probes its siblings' stores before re-prefilling.
            from triton_distributed_tpu.models.kv_tier import (
                FabricClient,
                LocalFabricPeer,
            )

            for i, eng in enumerate(engines):
                fc = FabricClient()
                fc.set_peers([
                    LocalFabricPeer(f"r{j}", other.tier)
                    for j, other in enumerate(engines)
                    if j != i and other.tier is not None
                ])
                eng.fabric = fc
        engine = Router(
            engines, policy=policy, drain_grace_s=args.drain_grace,
            request_timeout_s=args.request_timeout or None,
        )
        what = f"{args.model} x{args.replicas} ({policy} router)"
    elif args.continuous:
        # The process-fleet child shape (docs/scale-out.md): ONE
        # ContinuousEngine speaking 'requests' payloads, with the
        # migration surface (export_slots/handoff verbs) live.
        from triton_distributed_tpu.models.continuous import ContinuousEngine

        fabric = None
        if args.tier_bytes or args.tier_dir:
            # Every tiered fleet child carries a FabricClient so the
            # supervisor's tier_peers broadcast has somewhere to land
            # (docs/scale-out.md "KV fabric"); peerless it is inert —
            # _tier_fill treats an empty peer table as fabric-off.
            from triton_distributed_tpu.models.kv_tier import FabricClient

            fabric = FabricClient()
        engine = ContinuousEngine(
            model, max_batch=args.max_batch, mode=args.mode,
            temperature=args.temperature, prefix_cache=True,
            kv_dtype=args.kv_dtype, speculative=args.speculative,
            kernel_trace=kernel_trace,
            ns=args.ns, resident=args.resident,
            snapshot_every=args.snapshot_every,
            cp=args.cp, rank_page_budget=args.rank_page_budget,
            tier_bytes=args.tier_bytes, tier_dir=args.tier_dir,
            fabric=fabric,
        )
        what = f"{args.model} (continuous, tp={args.tp})"
    else:
        engine = Engine(
            model, temperature=args.temperature, mode=args.mode,
            verbose=True,
            # Both knobs ride the paged engine (scales/verify chunks
            # live on the page pool).
            paged=bool(args.kv_dtype or args.speculative),
            kv_dtype=args.kv_dtype, speculative=args.speculative,
            kernel_trace=kernel_trace,
        )
        what = f"{args.model} (tp={args.tp})"
    server = ModelServer(
        engine, host=args.host, port=args.port,
        advertise_host=args.advertise_host,
        drain_grace_s=args.drain_grace, trace_dir=args.trace, slo=slo,
    )
    print(f"serving {what} on {server.host}:{server.port}")
    _write_port_file(args.port_file, server.advertise_host, server.port)
    if args.trace:
        # Host capture wraps the whole serving run; on exit the ranks'
        # chrome traces AND every traced mega launch's device task rows
        # merge into ONE timeline (docs/profiling.md).
        from triton_distributed_tpu.obs import kernel_trace as _kt
        from triton_distributed_tpu.runtime.profiling import group_profile

        with group_profile("serve", out_dir=args.trace, merge=False):
            server.serve_forever()
        launches = getattr(engine, "kernel_trace_launches", lambda: [])()
        merged = _kt.merge_with_host_profile("serve", args.trace, launches)
        print(f"merged trace: {merged} "
              f"({len(launches)} traced mega launches)")
    else:
        server.serve_forever()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
