"""Model-server entry point.

Parity: the reference's server launch path
(``mega_triton_kernel/test/models/model_server.py`` ``__main__``).

Usage:
    python -m triton_distributed_tpu.serving.run_server \
        --model tiny --tp 1 --port 8765
"""

from __future__ import annotations

import argparse

import jax


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--model", default="tiny")
    p.add_argument("--tp", type=int, default=1)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--temperature", type=float, default=0.0)
    p.add_argument("--mode", default="xla",
                   choices=["xla", "pallas", "mega"])
    args = p.parse_args(argv)

    from triton_distributed_tpu.models import AutoLLM
    from triton_distributed_tpu.models.engine import Engine
    from triton_distributed_tpu.runtime.mesh import initialize_distributed
    from triton_distributed_tpu.serving.server import ModelServer

    ctx = initialize_distributed(tp=args.tp, devices=jax.devices()[: args.tp])
    model = AutoLLM.from_pretrained(args.model, ctx=ctx)
    engine = Engine(
        model, temperature=args.temperature, mode=args.mode, verbose=True
    )
    server = ModelServer(engine, host=args.host, port=args.port)
    print(f"serving {args.model} (tp={args.tp}) on {server.host}:{server.port}")
    server.serve_forever()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
