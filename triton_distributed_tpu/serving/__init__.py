"""Serving front-ends: socket model server, chat client, and the
multi-engine scale-out tier.

Parity: reference ``mega_triton_kernel/test/models/model_server.py``
(socket server :112-198) and ``chat.py`` (interactive client) — the
demo/deployment surface on top of the Engine. Beyond parity, the
replicated serving tier (docs/scale-out.md): ``Router`` fans requests
across N ``EngineReplica``\\ s by prefix affinity with replica
health/drain and shed-aware balancing; ``ModelServer(Router(...))``
keeps the wire server as the transport.
"""

from triton_distributed_tpu.serving.replica import EngineReplica, Ticket
from triton_distributed_tpu.serving.router import Router
from triton_distributed_tpu.serving.server import ModelServer, request

__all__ = ["EngineReplica", "ModelServer", "Router", "Ticket", "request"]
