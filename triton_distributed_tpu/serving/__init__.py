"""Serving front-ends: socket model server, chat client, and the
multi-engine scale-out tier.

Parity: reference ``mega_triton_kernel/test/models/model_server.py``
(socket server :112-198) and ``chat.py`` (interactive client) — the
demo/deployment surface on top of the Engine. Beyond parity, the
replicated serving tier (docs/scale-out.md): ``Router`` fans requests
across N ``EngineReplica``\\ s by prefix affinity with replica
health/drain and shed-aware balancing; ``ModelServer(Router(...))``
keeps the wire server as the transport. The process fleet
(docs/scale-out.md "Process fleet") crosses the process boundary:
``RemoteReplica`` speaks the wire protocol to a child-process
``ModelServer`` and ``FleetSupervisor`` owns spawn/heartbeat/respawn.
"""

from triton_distributed_tpu.serving.autoscaler import Autoscaler
from triton_distributed_tpu.serving.pools import Scheduler
from triton_distributed_tpu.serving.remote import (
    RemoteEngine,
    RemoteReplica,
)
from triton_distributed_tpu.serving.replica import EngineReplica, Ticket
from triton_distributed_tpu.serving.router import Router
from triton_distributed_tpu.serving.server import (
    ModelServer,
    request,
    request_stream,
)
from triton_distributed_tpu.serving.supervisor import (
    FleetSupervisor,
    ReplicaSpec,
    SpawnError,
    model_spec,
    spawn_replica,
    stub_spec,
)

__all__ = [
    "Autoscaler", "EngineReplica", "FleetSupervisor", "ModelServer",
    "RemoteEngine", "RemoteReplica", "ReplicaSpec", "Router",
    "Scheduler", "SpawnError", "Ticket", "model_spec", "request",
    "request_stream", "spawn_replica", "stub_spec",
]
