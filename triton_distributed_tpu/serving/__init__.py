"""Serving front-ends: socket model server + chat client.

Parity: reference ``mega_triton_kernel/test/models/model_server.py``
(socket server :112-198) and ``chat.py`` (interactive client) — the
demo/deployment surface on top of the Engine.
"""

from triton_distributed_tpu.serving.server import ModelServer, request

__all__ = ["ModelServer", "request"]
