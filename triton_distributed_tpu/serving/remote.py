"""A router replica behind a socket: the cross-process scale-out unit.

PR 6's fleet replicates engines on *threads*; this module crosses the
process boundary (ROADMAP item 1, docs/scale-out.md "Process fleet"):
:class:`RemoteReplica` duck-types :class:`EngineReplica`'s
router-facing surface — ``submit``/``pending``/``snapshot``/
``match_len``/``begin_drain``/``drain``/``state``/``mark_unhealthy`` —
but its batches travel the existing line-JSON wire protocol to a
``ModelServer`` in a child process. ``Router`` composes UNCHANGED: the
same latch-first :class:`Ticket` machinery that re-routes a dead
thread replica's work is the recovery path for an OOM-killed process.

The pieces that make the process boundary safe:

- **Ticket ids on the wire.** Every generation payload carries
  ``ticket_ids``; the server echoes them; results latch BY ID, never
  by position. A re-dispatched request whose "dead" replica actually
  finished produces a second completion for the same id — whichever
  arrives first latches, the loser is recognized and discarded. No
  double-emit, no misattribution across a garbled wire.
- **Connection-per-batch.** The worker opens one connection per engine
  batch (and per probe), so an idle replica never trips the server's
  idle timeout into a phantom death, and a late response arrives on
  the exact connection the (possibly already-rerouted) batch still
  owns.
- **Digest piggyback.** The batch response carries ``prefix_digest``
  (the ``want_digest`` payload key), mirroring the in-process rule —
  replicas publish their radix population at batch boundaries — with
  zero extra round trips. A respawned replica naturally rejoins with a
  fresh (empty) digest.
- **Deterministic chaos.** The wire seams (``wire.connect`` /
  ``wire.send`` / ``wire.recv``) and the mid-batch process seams
  (``proc.kill`` / ``proc.hang``) live HERE, on the router-process
  side, because a :class:`~triton_distributed_tpu.runtime.faults.FaultPlan`
  is process-global — arming the parent is what makes killing a child
  mid-batch reproducible (tests/test_fleet.py).

Liveness, crash classification, and respawn belong to
``serving/supervisor.py`` — this class only detects what the wire
shows it (EOF, RST, refused, garbage) and dies through the same
``_die`` → ``on_failure`` path a thread replica uses.
"""

from __future__ import annotations

import json
import socket

import numpy as np

from triton_distributed_tpu.models.continuous import RequestResult
from triton_distributed_tpu.obs import events as obs_events
from triton_distributed_tpu.runtime.faults import fault_point, mutate_point
from triton_distributed_tpu.serving.replica import (
    DEAD,
    EngineReplica,
    Ticket,
)


class RemoteEngine:
    """Client-side proxy for the engine living in a replica process.

    Duck-types the fragments of the engine surface the router actually
    touches through ``replica.engine``: ``last_stats`` (refreshed from
    every batch response), ``audit()`` (the server's ``audit`` verb),
    and ``prefix_digest()`` (the digest piggybacked on the last batch).
    Generation itself goes through :meth:`generate`, called only by
    the owning :class:`RemoteReplica` worker.
    """

    def __init__(self, host: str, port: int, *, name: str,
                 pid: int | None = None,
                 connect_timeout_s: float = 10.0,
                 probe_timeout_s: float = 10.0,
                 recv_timeout_s: float | None = None):
        self.host, self.port = host, int(port)
        self.name = name
        self.pid = pid
        self.connect_timeout_s = float(connect_timeout_s)
        self.probe_timeout_s = float(probe_timeout_s)
        # Batch recv: None blocks until the child answers or its socket
        # dies — a wedged child is the router timeout's (and the
        # supervisor heartbeat's) job to detect, exactly like a wedged
        # in-process worker.
        self.recv_timeout_s = recv_timeout_s
        # Launcher-assigned host tag (docs/scale-out.md "Multi-host
        # fleet"): names the failure domain this child lives in and
        # arms the mid-batch `host.down` seam. None = no host notion.
        self.host_tag: str | None = None
        self.last_stats: dict = {}
        self._digest = None
        self._tier_digest = None

    # -- wire --------------------------------------------------------------

    def call(self, payload: dict, *, timeout: float | None = None,
             generation: bool = False, on_token: dict | None = None
             ) -> dict:
        """One request/response round trip on a fresh connection, with
        every fault seam on the path. ``generation=True`` additionally
        offers the child's pid to the mid-batch ``proc.*`` seams right
        after the payload goes out — the instant a real OOM-kill would
        land. The seams carry ``what`` ("batch"/"probe") so chaos
        plans can target generation traffic without a supervisor
        heartbeat racing them for the hit (the fault conveniences
        match ``what="batch"`` by default).

        ``on_token`` (streaming batches, docs/serving.md "Streaming &
        cancellation"): a ``{tid: callback}`` map — token frames the
        child pushes before its response line forward to
        ``on_token[tid](i, token)`` as they arrive, and the returned
        dict is the summary frame. ONE wire implementation for both
        shapes, so every seam/timeout behavior stays shared."""
        what = "batch" if generation else "probe"
        # A caller deadline bounds the WHOLE round trip, connect
        # included: the supervisor's heartbeat deadline must not
        # stretch to the (longer) default connect timeout against a
        # SYN-black-holed child.
        conn_to = self.connect_timeout_s
        if timeout is not None:
            conn_to = min(conn_to, timeout)
        sinks = dict(on_token) if on_token else None
        fault_point("wire.connect", replica=self.name, what=what)
        with socket.create_connection(
            (self.host, self.port), timeout=conn_to
        ) as s:
            s.settimeout(timeout)
            with s.makefile("rwb") as f:
                data = json.dumps(payload).encode() + b"\n"
                data = mutate_point("wire.send", data, replica=self.name,
                                    what=what)
                f.write(data)
                f.flush()
                if generation:
                    mutate_point("proc.kill", self.pid, replica=self.name)
                    mutate_point("proc.hang", self.pid, replica=self.name)
                    if self.host_tag is not None:
                        # Whole-host chaos lands mid-batch too: the
                        # seam offers the host TAG (the plan's mutate
                        # closure holds the launcher that can kill or
                        # freeze the whole group).
                        mutate_point("host.down", self.host_tag,
                                     replica=self.name,
                                     host=self.host_tag)
                while True:
                    line = f.readline()
                    if not line:
                        raise ConnectionError(
                            f"replica {self.name} closed the "
                            "connection mid-request"
                        )
                    line = mutate_point("wire.recv", line,
                                        replica=self.name, what=what)
                    try:
                        obj = json.loads(line)
                    except ValueError as e:
                        raise ConnectionError(
                            f"replica {self.name} sent a garbled "
                            f"response: {e}"
                        ) from e
                    if (sinks is not None and isinstance(obj, dict)
                            and obj.get("frame") == "token"):
                        cb = sinks.get(obj.get("tid"))
                        if cb is not None:
                            try:
                                cb(int(obj["i"]), int(obj["token"]))
                            except Exception:  # noqa: BLE001 — a
                                # broken sink detaches, the stream
                                # (and the batch behind it) lives on
                                sinks.pop(obj.get("tid"), None)
                        continue
                    return obj

    def generate(self, payload: dict) -> dict:
        return self.call(payload, timeout=self.recv_timeout_s,
                         generation=True)

    def generate_stream(self, payload: dict, on_token: dict) -> dict:
        """A streaming batch round trip: :meth:`call` with the frame
        sinks attached (the payload carries ``"stream": true``).
        Returns the summary frame; wire failures raise exactly like
        :meth:`generate` — whatever frames already flowed were already
        delivered (at-least-once, deduped by index at the front
        sink)."""
        return self.call(payload, timeout=self.recv_timeout_s,
                         generation=True, on_token=on_token)

    def cancel(self, ticket_ids) -> None:
        """Forward a cancellation to the child (its cancel verb is
        engine-lock-free, so it lands mid-batch). A wire error means
        the child is already gone — its batch dies with it."""
        try:
            self.call(
                {"cmd": "cancel", "ticket_ids": list(ticket_ids)},
                timeout=self.probe_timeout_s,
            )
        except (OSError, ConnectionError):
            pass

    # -- engine surface the router touches ---------------------------------

    def run(self, requests, *, results: bool = False):  # pragma: no cover
        raise RuntimeError(
            "RemoteEngine.run is never called directly — "
            "RemoteReplica._run_batch speaks the wire"
        )

    def audit(self, *, raise_on_violation: bool = False) -> list[str]:
        resp = self.call({"cmd": "audit"}, timeout=self.probe_timeout_s)
        err = resp.get("error")
        if err is not None:
            raise RuntimeError(f"remote audit failed: {err}")
        problems = [str(p) for p in resp.get("problems", [])]
        if problems and raise_on_violation:
            from triton_distributed_tpu.models.paged_kv_cache import (
                PoolAuditError,
            )

            raise PoolAuditError("; ".join(problems))
        return problems

    def healthz(self, timeout: float | None = None) -> dict:
        return self.call({"cmd": "healthz"},
                         timeout=timeout or self.probe_timeout_s)

    def export_slots(self, timeout: float | None = None) -> dict:
        """The child's incremental slot-snapshot buffer, by ticket id
        (docs/scale-out.md "Slot migration & handoff") — what the
        supervisor's snapshot-based crash recovery polls."""
        resp = self.call({"cmd": "export_slots"},
                         timeout=timeout or self.probe_timeout_s)
        slots = resp.get("slots")
        return slots if isinstance(slots, dict) else {}

    def request_handoff(self, after_rounds: int = 0) -> None:
        """Arm the child engine's lossless-drain sweep (the in-flight
        batch returns its unfinished slots as snapshots). A wire error
        means the child is already gone — the batch path will classify
        that; nothing to do here."""
        del after_rounds  # the child exports at its next boundary
        try:
            self.call({"cmd": "handoff"}, timeout=self.probe_timeout_s)
        except (OSError, ConnectionError):
            pass

    def prefix_digest(self):
        return self._digest

    def set_digest(self, digest) -> None:
        self._digest = digest

    def tier_digest(self):
        """The child's tier digest as last piggybacked on a batch
        response (docs/scale-out.md "KV fabric") — the inherited
        ``_publish_digest`` reads this exactly like the in-process
        replica reads its engine's."""
        return self._tier_digest

    def set_tier_digest(self, digest) -> None:
        self._tier_digest = digest

    def drain(self) -> int:
        """Replica drain, remote form: ask the child to shut down (its
        server refuses new work, finishes in flight, exits). A wire
        error here means the child is already gone — which is drained
        enough; the supervisor reaps the process either way."""
        try:
            self.call({"cmd": "shutdown"}, timeout=self.probe_timeout_s)
        except (OSError, ConnectionError):
            pass
        self._digest = []
        self._tier_digest = None
        return 0


class RemoteReplica(EngineReplica):
    """One replica process behind the thread-replica surface.

    The queue/worker/ticket lifecycle is inherited verbatim from
    :class:`EngineReplica` — same states, same drain semantics, same
    ``on_failure`` re-route hand-off — only the batch execution
    crosses the wire. ``proc`` (a ``subprocess.Popen``, optional) is
    carried for the supervisor; an unmanaged RemoteReplica over an
    already-running server works too (that is what makes the fleet
    host-agnostic: nothing below the supervisor assumes the process is
    local).
    """

    def __init__(self, host: str, port: int, *, name: str,
                 proc=None, max_pending: int = 8, role: str = "mixed",
                 connect_timeout_s: float = 10.0,
                 recv_timeout_s: float | None = None,
                 host_tag: str | None = None):
        self.proc = proc
        remote = RemoteEngine(
            host, port, name=name,
            pid=proc.pid if proc is not None else None,
            connect_timeout_s=connect_timeout_s,
            recv_timeout_s=recv_timeout_s,
        )
        remote.host_tag = host_tag
        self._remote = remote
        # Epoch fence (docs/scale-out.md "Multi-host fleet"): set when
        # the supervisor declares this replica's HOST dead without
        # being able to kill the process (you cannot SIGKILL a machine
        # you cannot reach). A fenced replica's late batch responses
        # latch NOTHING — stronger than the plain-DEAD rule, because a
        # zombie host that thaws minutes later must not race the
        # reroutes that already ran under a newer epoch.
        self._fenced = False
        self._fence_epoch: int | None = None
        super().__init__(remote, name=name, max_pending=max_pending,
                         role=role)

    @property
    def pid(self) -> int | None:
        return self._remote.pid

    @property
    def host_tag(self) -> str | None:
        return self._remote.host_tag

    @property
    def fenced(self) -> bool:
        return self._fenced

    @property
    def fence_epoch(self) -> int | None:
        return self._fence_epoch

    def fence(self, epoch: int | None = None) -> None:
        """Drop the fence: from now on NO result from this replica's
        process may latch — not even harmlessly. Called by the
        supervisor when the replica's host is declared down (the
        process may still be alive out there)."""
        self._fenced = True
        self._fence_epoch = epoch

    def healthz(self, timeout: float | None = None) -> dict:
        """The supervisor's heartbeat probe (lock-free on the child)."""
        return self._remote.healthz(timeout)

    def export_slots(self, timeout: float | None = None) -> dict:
        """The child's slot-snapshot buffer by ticket id — the
        supervisor's snapshot-based crash-recovery feed."""
        return self._remote.export_slots(timeout)

    @property
    def free_pages(self) -> int:
        # Best-effort load tiebreak from the last stats the wire
        # carried (the in-process replica reads the live pool instead).
        return int(self._remote.last_stats.get("free_pages", 0) or 0)

    def _run_batch(self, tickets: list[Ticket]) -> None:
        payload = {
            "requests": [t.prompt_tokens for t in tickets],
            "gen_lens": [t.gen_len for t in tickets],
            "ticket_ids": [t.tid for t in tickets],
            "want_digest": True,
            "want_tier_digest": True,
            # Internal fan-out marker: the child must not fold these
            # into ITS wire-side SLO ledger — the user-facing hop (the
            # front server) judges goodput exactly once per request
            # (docs/observability.md "SLO goodput").
            "fanout": True,
        }
        # Sampling/deadline knobs ride as per-request lists; None
        # entries fall back to the child engine's defaults (the
        # server's knob() contract).
        for key, attr in (("temperatures", "temperature"),
                          ("top_ps", "top_p"), ("top_ks", "top_k"),
                          ("deadline_s", "deadline_s"),
                          ("slo_class", "slo_class")):
            vals = [getattr(t, attr) for t in tickets]
            if any(v is not None for v in vals):
                payload[key] = vals
        # Slot migration: snapshots resume exported work on this
        # child; prefill_only asks it to export right after admission
        # (docs/scale-out.md "Slot migration & handoff").
        if any(t.snapshot is not None for t in tickets):
            payload["snapshots"] = [t.snapshot for t in tickets]
            # A payload over the child's request-line bound would be
            # refused as bad_request — which the wire path below reads
            # as a REPLICA failure, killing a healthy target (and the
            # still-oversized ticket would then kill the next one).
            # Ship nothing instead: the requests replay from the
            # prompt — PR 9 recovery, never a cascade.
            from triton_distributed_tpu.serving.server import ModelServer

            probe = len(json.dumps(payload))
            if probe > ModelServer.MAX_LINE_BYTES - 4096:
                payload.pop("snapshots")
                obs_events.emit(
                    "snapshot_dropped", replica=self.name,
                    bytes=probe, tickets=len(tickets),
                )
        if any(t.prefill_only for t in tickets):
            payload["prefill_only"] = [
                bool(t.prefill_only) for t in tickets
            ]
        # Streaming fan-in (docs/serving.md "Streaming & cancellation"):
        # a batch with token sinks asks the child to stream, and each
        # arriving frame forwards to its ticket's sink — so the front
        # server's wire stamps cover the cross-process hop too.
        sinks = {t.tid: t.on_token for t in tickets
                 if t.on_token is not None}
        try:
            if sinks:
                payload["stream"] = True
                resp = self._remote.generate_stream(payload, sinks)
            else:
                resp = self._remote.generate(payload)
        except Exception as e:  # noqa: BLE001 — the wire is the boundary
            self._die(f"wire failure: {type(e).__name__}: {e}")
            return
        err = resp.get("error")
        if err is not None:
            # Structured refusal (shutting_down mid-drain-race,
            # overloaded, internal): the whole batch re-routes; the
            # child may still be healthy but this replica's slot in
            # the rotation is not.
            self._die(f"remote replica refused batch: {err}")
            return
        try:
            ids = resp.get("ticket_ids")
            if ids is None:
                ids = [t.tid for t in tickets]  # pre-echo server
            by_id = {
                tid: RequestResult(
                    np.asarray(out, np.int32),
                    str(res.get("status", "ok")),
                    str(res.get("reason", "")),
                    res.get("snapshot"),
                )
                for tid, out, res in zip(
                    ids, resp["outputs"], resp["results"]
                )
            }
        except (KeyError, TypeError, ValueError) as e:
            self._die(f"malformed remote response: {type(e).__name__}: {e}")
            return
        if self._state == DEAD:
            if self._fenced:
                # Epoch-fenced: the supervisor declared this replica's
                # HOST dead (and could not kill the process). A thawed
                # zombie's late results must latch ZERO — the fleet
                # already re-dispatched these tickets under a newer
                # epoch, and "harmless latch-first" only holds for
                # processes known to be gone, not for machines that
                # may keep computing stale state indefinitely.
                obs_events.emit(
                    "fenced_result_dropped", replica=self.name,
                    host=self.host_tag, epoch=self._fence_epoch,
                    tickets=len(tickets),
                )
                return
            # Late batch on a replica the router already gave up on:
            # latch what we can (latch-first dedup by ticket id makes
            # this harmless), fold NOTHING into fleet accounting — the
            # same duplicate-batch rule as the thread replica. Migrated
            # results stay unlatched (the router already re-routed).
            for t in tickets:
                r = by_id.get(t.tid)
                if r is not None and r.status != "migrated":
                    t.complete(r)
            return
        stats = resp.get("stats") or {}
        self._remote.last_stats = stats
        self._remote.set_digest(resp.get("prefix_digest"))
        self._remote.set_tier_digest(resp.get("tier_digest"))
        self.runs += 1
        for k in self.totals:
            self.totals[k] += stats.get(k, 0)
        missing = 0
        done = 0
        migrated = []
        for t in tickets:
            r = by_id.get(t.tid)
            if r is None:
                missing += 1
            elif r.status == "migrated":
                # The child exported this slot (handoff drain /
                # prefill→decode): carry the snapshot across the wire
                # and hand the ticket back for re-dispatch — same
                # contract as the thread replica, never latched here
                # (prefill_only stays set for the router's kind
                # classification; it clears it pre-dispatch).
                if r.snapshot is not None:
                    t.snapshot = r.snapshot
                migrated.append(t)
            else:
                done += 1
                t.complete(r)
        self.served += done
        self._publish_digest()
        if migrated:
            self._migrate_tickets(migrated)
        if missing:
            # The response named ids we never sent (or dropped some):
            # protocol corruption. Kill the replica; _take_dead hands
            # the unlatched tickets back for re-routing — latched ones
            # lose their claim harmlessly. Never strand a ticket.
            self._die(
                f"remote response missing {missing} of "
                f"{len(tickets)} ticket ids"
            )
