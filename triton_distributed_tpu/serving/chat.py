"""Interactive chat client for the model server.

Parity: reference ``mega_triton_kernel/test/models/chat.py`` — connects
to the socket server, tokenizes with the HF tokenizer when available,
streams turns in a REPL.

Usage:
    # terminal 1
    python -m triton_distributed_tpu.serving.run_server --model tiny
    # terminal 2
    python -m triton_distributed_tpu.serving.chat --port <printed port>
"""

from __future__ import annotations

import argparse

from triton_distributed_tpu.serving.server import request


def get_tokenizer(model_name: str):
    """HF tokenizer when installed/downloadable; else a byte-level
    fallback so the demo runs in hermetic environments."""
    try:
        from transformers import AutoTokenizer

        return AutoTokenizer.from_pretrained(model_name)
    except Exception:
        class ByteTok:
            def encode(self, text):
                return list(text.encode("utf-8"))

            def decode(self, ids):
                return bytes(int(i) % 256 for i in ids).decode(
                    "utf-8", errors="replace"
                )

        return ByteTok()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, required=True)
    p.add_argument("--tokenizer", default="Qwen/Qwen3-0.6B")
    p.add_argument("--gen-len", type=int, default=64)
    p.add_argument("--pad-to", type=int, default=8,
                   help="pad prompts to a multiple (tp divisibility)")
    args = p.parse_args(argv)

    tok = get_tokenizer(args.tokenizer)
    print("chat ready — empty line to quit")
    while True:
        try:
            text = input("you> ")
        except EOFError:
            break
        if not text.strip():
            break
        ids = tok.encode(text)
        pad = (-len(ids)) % args.pad_to
        # Left-pad with the tokenizer's pad/BOS id; the engine masks
        # padded prefill positions via prompt_start so pads are inert.
        pad_id = next(
            i
            for i in (
                getattr(tok, "pad_token_id", None),
                getattr(tok, "bos_token_id", None),
                0,
            )
            if i is not None
        )
        ids = [int(pad_id)] * pad + list(ids)
        resp = request(
            args.host, args.port,
            {"input_ids": [ids], "gen_len": args.gen_len,
             "prompt_start": [pad]},
        )
        out = resp["output_ids"][0][len(ids):]
        stats = resp.get("stats", {})
        print(f"bot> {tok.decode(out)}")
        if stats:
            print(
                f"     [{stats.get('decode_ms_per_step', 0):.2f} ms/step, "
                f"{stats.get('tokens_per_s', 0):.1f} tok/s]"
            )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
