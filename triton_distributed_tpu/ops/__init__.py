"""Kernel library: collectives and compute–communication overlap ops.

Parity: reference ``python/triton_dist/kernels/`` (SURVEY.md §2.2 L8).
All ops come in (at least) two method flavors:

- ``pallas``: device-initiated ICI protocols (remote DMA + semaphores),
  the analog of the reference's NVSHMEM device kernels;
- ``xla``: XLA collectives (``jax.lax.all_gather`` etc.), the analog of
  the reference's NCCL golden path — also the DCN/multi-slice fallback
  and the CPU-simulator default for layers that don't need overlap.

Every op takes per-shard arrays and axis names and must be called inside
``shard_map`` (or through the host-level ``*_op`` wrappers that build one).
"""

from triton_distributed_tpu.ops.collectives.all_gather import (  # noqa: F401
    all_gather_torus_2d,
    AllGatherMethod,
    all_gather,
    all_gather_op,
)
from triton_distributed_tpu.ops.collectives.reduce_scatter import (  # noqa: F401
    ReduceScatterMethod,
    reduce_scatter,
    reduce_scatter_op,
)
from triton_distributed_tpu.ops.collectives.all_reduce import (  # noqa: F401
    AllReduceMethod,
    all_reduce,
    all_reduce_op,
    get_auto_allreduce_method,
)
from triton_distributed_tpu.ops.collectives.all_to_all import (  # noqa: F401
    all_to_all,
    all_to_all_op,
)
from triton_distributed_tpu.ops.collectives.broadcast import (  # noqa: F401
    BroadcastMethod,
    broadcast,
    broadcast_op,
)
from triton_distributed_tpu.ops.collectives.hierarchical import (  # noqa: F401
    all_gather_2d,
    all_gather_2d_op,
    all_reduce_2level,
    all_reduce_2level_op,
    reduce_scatter_2d,
)
from triton_distributed_tpu.ops.collectives.low_latency import (  # noqa: F401
    ll_all_gather,
    ll_all_gather_op,
    ll_all_gather_workspace,
)
from triton_distributed_tpu.ops.overlap.ag_gemm import (  # noqa: F401
    AGGemmConfig,
    ag_gemm,
    ag_gemm_op,
    create_ag_gemm_context,
)
from triton_distributed_tpu.ops.overlap.gemm_ar import (  # noqa: F401
    gemm_ar,
    gemm_ar_op,
)
from triton_distributed_tpu.ops.overlap.gemm_rs import (  # noqa: F401
    GemmRSConfig,
    gemm_rs,
    gemm_rs_op,
)
