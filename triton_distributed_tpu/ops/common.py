"""Shared helpers for comm kernels: pallas_call builder, collective ids.

Parity role: reference ``kernels/nvidia/common_ops.py`` (grid barriers,
stream signal ops) — on TPU the equivalents are mostly folded into Mosaic,
so what remains shared is boilerplate: interpret-mode selection, collective
id allocation, VMEM budgeting.
"""

from __future__ import annotations

import contextlib
import itertools
from typing import Any, Sequence

import jax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from triton_distributed_tpu.runtime.mesh import DistContext, current_context

# Distinct collective_id per kernel *site* so barrier semaphores of
# different collectives in one program never alias. Stable across traces
# of the same site because allocation happens at import/def time.
_collective_ids = itertools.count(1)


def next_collective_id() -> int:
    return next(_collective_ids)


# Crossover between VMEM-resident comm kernels (payload + peer slots all
# on-chip — lowest latency) and the HBM-chunked / DMA-only variants that
# have no payload ceiling (all_gather ANY-kernels, reduce_scatter
# PALLAS_RING_HBM, tiled overlap staging). AUTO dispatch switches
# variant here, never to XLA on size grounds.
VMEM_COMM_MAX_BYTES = 4 * 1024 * 1024


def pick_stage_tile(
    m: int, row_bytes: int, budget: int, floor: int = 128
) -> int:
    """Largest divisor tile of ``m`` (by halving) whose staging buffer
    ``tile * row_bytes`` fits ``budget``; never below ``floor`` unless
    divisibility demands it. Shared by the HBM-chunked kernels
    (ag_gemm / gemm_rs staging, reduce_scatter tiled adds)."""
    tile = m
    while tile > floor and tile * row_bytes > budget:
        tile //= 2
    while m % tile:
        tile //= 2
    return max(tile, 1)


# Hard ceiling for the overlap kernels' scoped VMEM (below v5e's 128 MB
# physical VMEM); configs whose estimated need exceeds it can't compile.
OVERLAP_VMEM_CAP = 110 * 1024 * 1024


def overlap_vmem_bytes(
    tile_m: int, k: int, tile_n: int, itemsize: int, out_tile_bufs: int = 3
) -> int:
    """Estimated scoped-VMEM need of a fused overlap GEMM config.

    Mosaic's own accounting runs ~1.5x the raw buffer bytes (pipelined
    operand copies, stack), hence the 3x-per-double-buffer coefficients
    plus a fixed margin. ``out_tile_bufs`` scales the (tile_m, tile_n)
    term — gemm_rs keeps three double-buffered output-sized tiles where
    ag_gemm keeps one.
    """
    return (
        (3 * tile_m * k + 3 * k * tile_n
         + 3 * out_tile_bufs * tile_m * tile_n) * itemsize
        + 16 * 1024 * 1024
    )


def overlap_vmem_limit(
    tile_m: int, k: int, tile_n: int, itemsize: int, out_tile_bufs: int = 3
) -> int:
    """Scoped-VMEM limit for the fused overlap GEMM kernels."""
    return min(
        OVERLAP_VMEM_CAP,
        max(
            64 * 1024 * 1024,
            overlap_vmem_bytes(tile_m, k, tile_n, itemsize, out_tile_bufs),
        ),
    )


def pick_tile(n: int, preferred: int = 512) -> int:
    """Largest power-of-two-ish tile dividing ``n`` (shared by the
    overlap-GEMM context builders; parity: the reference's per-shape tile
    heuristics in its ``create_*_context`` helpers)."""
    tile = min(preferred, n)
    while n % tile:
        tile //= 2
    return max(tile, 128 if n % 128 == 0 else 1)


# jax.export cannot serialize host callbacks, which is what interpret-mode
# Pallas lowers to off-TPU. Ops with a pure-XLA equivalent consult
# exporting_portable() and take it while an export is being traced.
_EXPORT_PORTABLE = False


@contextlib.contextmanager
def portable_export():
    """Trace-for-export mode: ops avoid interpret-mode Pallas."""
    global _EXPORT_PORTABLE
    prev = _EXPORT_PORTABLE
    _EXPORT_PORTABLE = True
    try:
        yield
    finally:
        _EXPORT_PORTABLE = prev


def exporting_portable() -> bool:
    return _EXPORT_PORTABLE


def interpret_mode(ctx: DistContext | None = None):
    """Interpret params when not on real TPU (CPU simulator mesh)."""
    if ctx is None:
        try:
            ctx = current_context()
        except RuntimeError:
            ctx = None
    if ctx is not None:
        return ctx.pallas_interpret()
    return False if jax.default_backend() == "tpu" else pltpu.InterpretParams()


def comm_pallas_call(
    kernel,
    out_shape: Any,
    *,
    in_specs: Sequence[pl.BlockSpec] | None = None,
    out_specs: Any = None,
    scratch_shapes: Sequence[Any] = (),
    grid: tuple[int, ...] | None = None,
    collective_id: int | None = None,
    ctx: DistContext | None = None,
    vmem_limit_bytes: int | None = None,
    cost_estimate: pl.CostEstimate | None = None,
    dimension_semantics: Sequence[str] | None = None,
    input_output_aliases: dict[int, int] | None = None,
):
    """Build a pallas_call configured for communication kernels.

    Applies: side-effect marking (DMA-only kernels must not be DCE'd),
    collective id (barrier semaphore scoping), and interpret-mode
    selection for the CPU simulator.
    """
    params: dict[str, Any] = dict(has_side_effects=True)
    if collective_id is not None:
        params["collective_id"] = collective_id
        # Our comm kernels sequence via DMA semaphores; not every one
        # touches the barrier semaphore the id also scopes.
        params["allow_collective_id_without_custom_barrier"] = True
    if vmem_limit_bytes is not None:
        params["vmem_limit_bytes"] = vmem_limit_bytes
    if dimension_semantics is not None:
        params["dimension_semantics"] = tuple(dimension_semantics)
    kwargs: dict[str, Any] = {}
    if grid is not None:
        kwargs["grid"] = grid
    if cost_estimate is not None:
        kwargs["cost_estimate"] = cost_estimate
    if input_output_aliases is not None:
        kwargs["input_output_aliases"] = input_output_aliases
    return pl.pallas_call(
        kernel,
        out_shape=out_shape,
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=list(scratch_shapes),
        compiler_params=pltpu.CompilerParams(**params),
        interpret=interpret_mode(ctx),
        **kwargs,
    )


def comm_cost(
    flops: int = 0, bytes_accessed: int = 0, transcendentals: int = 0
) -> pl.CostEstimate:
    """FLOPs/bytes annotation for a comm kernel so profiles and XLA's
    scheduler see real costs (parity: the reference's ``launch_metadata``
    hooks, e.g. ``allgather_gemm.py:145-156``, which label each kernel
    launch with its flop/byte counts for nsys traces)."""
    return pl.CostEstimate(
        flops=int(flops),
        bytes_accessed=int(bytes_accessed),
        transcendentals=int(transcendentals),
    )


def _on_tpu(ctx: DistContext | None = None) -> bool:
    """True when kernels will compile through Mosaic (real TPU)."""
    if ctx is not None:
        return ctx.on_tpu
    try:
        return current_context().on_tpu
    except RuntimeError:
        return jax.default_backend() == "tpu"


def device_initiable(axis: str, ctx: DistContext | None = None) -> bool:
    """True when a device-push Pallas kernel is legal on ``axis``: real
    TPU AND the axis stays inside one slice (ICI). DCN-spanning axes
    are host-driven — AUTO dispatchers must fall back to XLA there
    (the 2-level ops in ``collectives/hierarchical.py`` exist for
    exactly that split)."""
    if not _on_tpu(ctx):
        return False
    if ctx is None:
        try:
            ctx = current_context()
        except RuntimeError:
            return True  # single-device scripts: no axis to cross
    return ctx.axis_is_ici(axis)
