"""Ring MoE: fused AG+GroupGEMM → MoE+ReduceScatter with XLA overlap.

Parity: reference ``kernels/nvidia/allgather_group_gemm.py`` (tokens
all-gathered while a grouped GEMM consumes per-rank chunks as they
arrive — ``kernel_consumer_m_parallel_scatter_group_gemm``:535, with the
rank-aware tile swizzle) and ``moe_reduce_rs.py`` (grouped GEMM fused
with the topk-reduce + reduce-scatter, :569).

TPU redesign: instead of a device-side scoreboard over gathered chunks,
the ring structure makes the overlap compiler-visible. Token chunks and
their partial outputs circulate as ``lax.ppermute`` pairs; each step
computes this rank's expert contribution to the visiting chunk while
XLA's async collective engine moves the next pair over ICI — compute
hides the transfer, the fusion the reference builds by hand. After n
hops every pair is back home carrying the full sum: the all-gather
(tokens visit every rank) and the reduce-scatter (partials accumulate
along the ring) never materialize a gathered buffer.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from triton_distributed_tpu.ops.moe.grouped_gemm import grouped_ffn
from triton_distributed_tpu.ops.moe.routing import (
    moe_combine,
    moe_sort,
    router_topk,
)


def moe_ffn_ring(
    x: jax.Array,         # [t_loc, d] — this rank's token chunk
    w_router: jax.Array,  # [d, E] replicated
    w1: jax.Array,        # [E, d, 2*f_loc] — gate|up fused column shard
    w2: jax.Array,        # [E, f_loc, d] — row shard
    k: int,
    *,
    axis: str = "tp",
    norm_topk_prob: bool = True,
) -> jax.Array:
    """Full TP-MoE FFN inside ``shard_map``: ``[t_loc, d] → [t_loc, d]``
    with activations staying sequence-sharded (the reference's
    AG-scatter-groupGEMM → gather-RS pipeline, ``tp_moe.py:237``,
    collapsed into one ring)."""
    n = jax.lax.axis_size(axis)
    t, d = x.shape
    num_experts = w_router.shape[1]
    perm = [(i, (i + 1) % n) for i in range(n)]

    def contribution(tok: jax.Array) -> jax.Array:
        """This rank's partial FFN output for a token chunk (partial over
        the f shard; full after ring accumulation)."""
        route = router_topk(tok, w_router, k, norm_topk_prob=norm_topk_prob)
        st = moe_sort(route, num_experts)
        out_rows = grouped_ffn(tok[st.token_ids], w1, w2, st.group_sizes)
        return moe_combine(out_rows, st, t)

    def step(carry, _):
        tok, acc = carry
        acc = acc + contribution(tok).astype(jnp.float32)
        # Pass the pair to the right; XLA overlaps this ppermute with the
        # next step's grouped GEMM (async collective scheduling).
        tok = jax.lax.ppermute(tok, axis, perm)
        acc = jax.lax.ppermute(acc, axis, perm)
        return (tok, acc), None

    init = (x, jnp.zeros((t, d), jnp.float32))
    # n-1 full hops (tok + acc travel together), then a final local
    # contribution with an acc-only hop home — the token chunk's last
    # ppermute would be unused payload, so it is skipped.
    (tok, acc), _ = jax.lax.scan(step, init, None, length=n - 1)
    acc = acc + contribution(tok).astype(jnp.float32)
    acc = jax.lax.ppermute(acc, axis, perm)
    # After n hops the accumulator that started here is home again,
    # carrying every rank's contribution to OUR tokens.
    return acc.astype(x.dtype)
