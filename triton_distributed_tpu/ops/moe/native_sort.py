"""Native (C++) MoE align/sort entry points.

Parity: the reference binds ``moe_ag_scatter_align_block_size`` as a
torch-extension host op (``csrc/lib/op_pybind.cc:31``); here the same
C++ routine (``csrc/moe_utils.cc``) is reachable two ways:

- :func:`moe_align_block_size_host` — ctypes call on host numpy arrays
  (planner path, no XLA involved);
- :func:`moe_align_block_size_ffi` — XLA FFI custom call, jit-safe on
  the CPU platform (custom calls execute on host; TPU in-jit paths use
  the pure-JAX ``routing.moe_align_block_size``).

Both share the output contract of :class:`routing.AlignedBlocks`.
"""

from __future__ import annotations

import ctypes

import jax
import jax.numpy as jnp
import numpy as np

from triton_distributed_tpu.native import get_native
from triton_distributed_tpu.ops.moe.routing import (
    AlignedBlocks,
    align_capacities,
)


def moe_align_block_size_host(
    expert_ids: np.ndarray,  # [T, k] or [N] int32
    num_experts: int,
    block_size: int,
) -> AlignedBlocks:
    """C++ host planner (raises RuntimeError without a native build)."""
    lib = get_native()
    if lib is None:
        raise RuntimeError("native library unavailable (no g++?)")
    flat = np.ascontiguousarray(expert_ids.reshape(-1), np.int32)
    n = flat.shape[0]
    cap, bcap = align_capacities(n, num_experts, block_size)
    sorted_ids = np.empty((cap,), np.int32)
    block_expert = np.empty((bcap,), np.int32)
    counts = np.empty((2,), np.int32)
    i32p = ctypes.POINTER(ctypes.c_int32)
    rc = lib.cdll.tdt_moe_align_block_size_host(
        flat.ctypes.data_as(i32p), n, num_experts, block_size,
        sorted_ids.ctypes.data_as(i32p), cap,
        block_expert.ctypes.data_as(i32p), bcap,
        counts.ctypes.data_as(i32p),
    )
    if rc != 0:
        raise ValueError(f"moe_align_block_size failed (rc={rc})")
    return AlignedBlocks(
        sorted_ids=sorted_ids,
        block_expert=block_expert,
        num_blocks=np.int32(counts[0]),
        num_padded=np.int32(counts[1]),
    )


def moe_align_block_size_ffi(
    expert_ids: jax.Array,  # [T, k] or [N] int32
    num_experts: int,
    block_size: int,
) -> AlignedBlocks:
    """XLA FFI custom-call form (CPU platform, usable inside jit)."""
    lib = get_native()
    if lib is None:
        raise RuntimeError("native library unavailable (no g++?)")
    lib.register_ffi_targets()
    flat = expert_ids.reshape(-1).astype(jnp.int32)
    cap, bcap = align_capacities(flat.shape[0], num_experts, block_size)
    sorted_ids, block_expert, counts = jax.ffi.ffi_call(
        "tdt_moe_align_block_size",
        (
            jax.ShapeDtypeStruct((cap,), jnp.int32),
            jax.ShapeDtypeStruct((bcap,), jnp.int32),
            jax.ShapeDtypeStruct((2,), jnp.int32),
        ),
    )(flat, num_experts=np.int32(num_experts), block_size=np.int32(block_size))
    return AlignedBlocks(
        sorted_ids=sorted_ids,
        block_expert=block_expert,
        num_blocks=counts[0],
        num_padded=counts[1],
    )
