"""MoE kernels: routing/sort, grouped GEMM, TP and EP data paths.

Parity: reference MoE stack — ``csrc/lib/moe_utils.cu`` (token sort),
``kernels/nvidia/allgather_group_gemm.py`` (AG+GroupGEMM),
``moe_reduce_rs.py`` (MoE+RS), ``ep_a2a.py`` /
``low_latency_all_to_all.py`` (EP dispatch/combine) — SURVEY.md §2.2.
"""

from triton_distributed_tpu.ops.moe.routing import (  # noqa: F401
    moe_combine,
    moe_sort,
    router_topk,
)
from triton_distributed_tpu.ops.moe.grouped_gemm import (  # noqa: F401
    grouped_ffn,
    grouped_gemm,
)
from triton_distributed_tpu.ops.moe.ep_a2a import (  # noqa: F401
    ep_combine,
    ep_dispatch,
    ep_moe_ffn,
)
from triton_distributed_tpu.ops.moe.ring_moe import moe_ffn_ring  # noqa: F401
