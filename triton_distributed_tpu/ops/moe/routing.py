"""MoE routing: top-k gating, expert-sort, weighted combine.

Parity: the reference's token sorting lives in CUDA
(``csrc/lib/moe_utils.cu:61-356`` ``moe_ag_scatter_align_block_size`` —
sorts topk token→expert assignments into block-aligned expert batches)
with a Triton reimpl (``threadblock_swizzle_ag_moe_triton.py``).

TPU design: XLA's sort is a first-class TPU op, so the sort/align is a
``jnp.argsort`` + ``bincount`` composition; grouped GEMM consumes the
``group_sizes`` vector directly (``jax.lax.ragged_dot``), no block
alignment pass needed — the alignment the CUDA kernel creates by hand is
what ragged_dot's tiling does internally.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class RouterOut(NamedTuple):
    expert_ids: jax.Array   # [T, k] int32
    weights: jax.Array      # [T, k] f32 — normalized gate weights


class SortedTokens(NamedTuple):
    order: jax.Array        # [T*k] — argsort of flattened expert ids
    token_ids: jax.Array    # [T*k] — source token per sorted slot
    expert_ids: jax.Array   # [T*k] — expert per sorted slot (ascending)
    weights: jax.Array      # [T*k] f32 — gate weight per sorted slot
    group_sizes: jax.Array  # [E] int32 — tokens per expert


def router_topk(
    x: jax.Array,         # [T, d]
    w_router: jax.Array,  # [d, E]
    k: int,
    *,
    norm_topk_prob: bool = True,
) -> RouterOut:
    """Qwen3-MoE gate: softmax over all experts, take top-k, renormalize
    (HF ``norm_topk_prob``)."""
    logits = jnp.dot(
        x.astype(jnp.float32), w_router.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    probs = jax.nn.softmax(logits, axis=-1)
    weights, ids = jax.lax.top_k(probs, k)
    if norm_topk_prob:
        weights = weights / jnp.sum(weights, axis=-1, keepdims=True)
    return RouterOut(ids.astype(jnp.int32), weights)


def moe_sort(route: RouterOut, num_experts: int) -> SortedTokens:
    """Sort (token, expert) assignments into expert-contiguous order
    (parity: the CUDA align kernel's output contract)."""
    flat_e = route.expert_ids.reshape(-1)
    flat_w = route.weights.reshape(-1)
    k = route.expert_ids.shape[1]
    order = jnp.argsort(flat_e, stable=True)
    return SortedTokens(
        order=order,
        token_ids=(order // k).astype(jnp.int32),
        expert_ids=flat_e[order],
        weights=flat_w[order],
        group_sizes=jnp.bincount(flat_e, length=num_experts).astype(jnp.int32),
    )


class AlignedBlocks(NamedTuple):
    """Block-aligned grouped-GEMM schedule (the CUDA align kernel's
    output contract, ``moe_utils.cu:61-193``)."""

    sorted_ids: jax.Array    # [cap] — slot → flattened source index; pad = N
    block_expert: jax.Array  # [bcap] — tile → expert id; past-end = -1
    num_blocks: jax.Array    # [] int32
    num_padded: jax.Array    # [] int32


def align_capacities(n: int, num_experts: int, block_size: int) -> tuple[int, int]:
    """Static worst-case output sizes: every expert padded by up to
    ``block_size - 1`` slots."""
    cap = n + num_experts * (block_size - 1)
    cap = (cap + block_size - 1) // block_size * block_size
    return cap, cap // block_size


def moe_align_block_size(
    expert_ids: jax.Array,  # [T, k] or [N] int32
    num_experts: int,
    block_size: int,
) -> AlignedBlocks:
    """Pure-JAX block-aligned expert sort (jit-safe, static shapes).

    Parity: ``moe_ag_scatter_align_block_size`` (``moe_utils.cu:61-356``).
    The native XLA-FFI/C++ variant with identical semantics lives in
    ``csrc/moe_utils.cc`` (host planning path); this composition is the
    on-device default — XLA sorts/scans are first-class TPU ops.
    """
    flat = expert_ids.reshape(-1).astype(jnp.int32)
    n = flat.shape[0]
    cap, bcap = align_capacities(n, num_experts, block_size)
    counts = jnp.bincount(flat, length=num_experts)
    padded = (counts + block_size - 1) // block_size * block_size
    start = jnp.cumsum(padded) - padded  # exclusive prefix
    order = jnp.argsort(flat, stable=True)
    es = flat[order]
    # Within-expert rank of each sorted slot = position - first slot of
    # that expert in plain sorted order.
    first_sorted = jnp.cumsum(counts) - counts
    within = jnp.arange(n) - first_sorted[es]
    dest = start[es] + within
    sorted_ids = jnp.full((cap,), n, jnp.int32).at[dest].set(
        order.astype(jnp.int32)
    )
    bounds = jnp.cumsum(padded) // block_size  # block-end per expert
    blk = jnp.arange(bcap)
    block_expert = jnp.searchsorted(bounds, blk, side="right").astype(jnp.int32)
    num_blocks = (jnp.sum(padded) // block_size).astype(jnp.int32)
    block_expert = jnp.where(blk < num_blocks, block_expert, -1)
    return AlignedBlocks(
        sorted_ids=sorted_ids,
        block_expert=block_expert,
        num_blocks=num_blocks,
        num_padded=jnp.sum(padded).astype(jnp.int32),
    )


def moe_combine(
    expert_out: jax.Array,  # [T*k, d] — per sorted slot
    sorted_tokens: SortedTokens,
    num_tokens: int,
) -> jax.Array:
    """Weighted scatter-add back to token order → [T, d] (parity: the
    gather-topk-reduce stage of ``moe_reduce_rs.py:293``)."""
    weighted = expert_out.astype(jnp.float32) * sorted_tokens.weights[:, None]
    out = jnp.zeros((num_tokens, expert_out.shape[1]), jnp.float32)
    out = out.at[sorted_tokens.token_ids].add(weighted)
    return out.astype(expert_out.dtype)
