"""MoE routing: top-k gating, expert-sort, weighted combine.

Parity: the reference's token sorting lives in CUDA
(``csrc/lib/moe_utils.cu:61-356`` ``moe_ag_scatter_align_block_size`` —
sorts topk token→expert assignments into block-aligned expert batches)
with a Triton reimpl (``threadblock_swizzle_ag_moe_triton.py``).

TPU design: XLA's sort is a first-class TPU op, so the sort/align is a
``jnp.argsort`` + ``bincount`` composition; grouped GEMM consumes the
``group_sizes`` vector directly (``jax.lax.ragged_dot``), no block
alignment pass needed — the alignment the CUDA kernel creates by hand is
what ragged_dot's tiling does internally.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class RouterOut(NamedTuple):
    expert_ids: jax.Array   # [T, k] int32
    weights: jax.Array      # [T, k] f32 — normalized gate weights


class SortedTokens(NamedTuple):
    order: jax.Array        # [T*k] — argsort of flattened expert ids
    token_ids: jax.Array    # [T*k] — source token per sorted slot
    expert_ids: jax.Array   # [T*k] — expert per sorted slot (ascending)
    weights: jax.Array      # [T*k] f32 — gate weight per sorted slot
    group_sizes: jax.Array  # [E] int32 — tokens per expert


def router_topk(
    x: jax.Array,         # [T, d]
    w_router: jax.Array,  # [d, E]
    k: int,
    *,
    norm_topk_prob: bool = True,
) -> RouterOut:
    """Qwen3-MoE gate: softmax over all experts, take top-k, renormalize
    (HF ``norm_topk_prob``)."""
    logits = jnp.dot(
        x.astype(jnp.float32), w_router.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    probs = jax.nn.softmax(logits, axis=-1)
    weights, ids = jax.lax.top_k(probs, k)
    if norm_topk_prob:
        weights = weights / jnp.sum(weights, axis=-1, keepdims=True)
    return RouterOut(ids.astype(jnp.int32), weights)


def moe_sort(route: RouterOut, num_experts: int) -> SortedTokens:
    """Sort (token, expert) assignments into expert-contiguous order
    (parity: the CUDA align kernel's output contract)."""
    flat_e = route.expert_ids.reshape(-1)
    flat_w = route.weights.reshape(-1)
    k = route.expert_ids.shape[1]
    order = jnp.argsort(flat_e, stable=True)
    return SortedTokens(
        order=order,
        token_ids=(order // k).astype(jnp.int32),
        expert_ids=flat_e[order],
        weights=flat_w[order],
        group_sizes=jnp.bincount(flat_e, length=num_experts).astype(jnp.int32),
    )


def moe_combine(
    expert_out: jax.Array,  # [T*k, d] — per sorted slot
    sorted_tokens: SortedTokens,
    num_tokens: int,
) -> jax.Array:
    """Weighted scatter-add back to token order → [T, d] (parity: the
    gather-topk-reduce stage of ``moe_reduce_rs.py:293``)."""
    weighted = expert_out.astype(jnp.float32) * sorted_tokens.weights[:, None]
    out = jnp.zeros((num_tokens, expert_out.shape[1]), jnp.float32)
    out = out.at[sorted_tokens.token_ids].add(weighted)
    return out.astype(expert_out.dtype)
