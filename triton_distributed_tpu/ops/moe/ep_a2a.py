"""Expert-parallel all-to-all dispatch/combine (DeepEP-style).

Parity: reference ``kernels/nvidia/ep_a2a.py`` —
``kernel_dispatch_token``:37 (route token copies to expert-owner ranks),
``kernel_combine_token``:152 (return + weighted reduce),
``kernel_get_ag_splits_and_recv_offset``:244 (splits exchange) — and the
low-latency variant ``low_latency_all_to_all.py`` (putmem_signal +
fp8+scale payloads, README.md:101-187).

TPU design (SURVEY.md §7 hard part "dynamic shapes"): XLA wants static
shapes, so receive buffers are max-padded — but like the reference the
protocol is LOSSLESS: real splits are exchanged (the
``kernel_get_ag_splits_and_recv_offset`` analog) and the static
per-source segment is sized at the provable worst case ``t*k`` (one
source rank can never send more than its own assignment count), so no
token is ever dropped. EP a2a is a decode-scale op (the reference's
headline is 128 tokens/rank), so worst-case padding costs MBs, not GBs.

A bounded-memory ``capacity`` mode remains for experimentation: it
KEEPS the overflow count (``DispatchState.num_dropped``) so exceeding
capacity is a *detected error* the caller can assert on, never silent
corruption.

Low-latency payload mode (``payload_dtype="fp8"``): tokens are
quantized to float8_e4m3 with per-row scales before the exchange and
dequantized after — half the ICI bytes, the reference's
``low_latency_all_to_all`` fp8+scales codec (:36-125) in XLA form.

Transports (``method=``):

- ``"pallas"`` — device-initiated: payload + scales + expert ids pack
  into one uint8 row and move through ``ep_exchange`` (per-destination
  ``put_signal`` block pushes, only the filled prefix crosses the wire
  — the reference's flagship ``low_latency_all_to_all.py`` shape).
- ``"xla"`` — the whole max-padded segments ride ``lax.all_to_all``.
- ``"auto"`` — pallas on real TPU when the EP axis is ICI-reachable
  (``device_initiable`` — a DCN-spanning axis is host-driven and falls
  back to xla), xla elsewhere. No size gate: the segments live in
  ANY/HBM on both ends, so unlike the VMEM-resident dense a2a there is
  no payload ceiling to dodge.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from triton_distributed_tpu.ops.collectives.all_to_all import all_to_all
from triton_distributed_tpu.ops.moe.grouped_gemm import grouped_ffn
from triton_distributed_tpu.ops.moe.routing import RouterOut


class DispatchState(NamedTuple):
    """Everything the source rank needs to route results back."""

    dest: jax.Array      # [T*k] destination rank per assignment
    slot: jax.Array      # [T*k] slot in the dest buffer
    valid: jax.Array     # [T*k] bool — False only in capacity mode
    weights: jax.Array   # [T*k] f32 gate weights
    token_ids: jax.Array  # [T*k] source token index
    num_dropped: jax.Array  # [] int32 — 0 in lossless mode, by construction
    splits: jax.Array       # [n] int32 — rows sent per dest (capacity-clipped)
    recv_counts: jax.Array  # [n] int32 — rows received per source


def _fp8_encode(x: jax.Array):
    """Per-row fp8 quantization (reference LL codec: fp8 + scales)."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1, keepdims=True)
    scale = jnp.maximum(amax, 1e-12) / 448.0  # e4m3 max normal
    q = (x.astype(jnp.float32) / scale).astype(jnp.float8_e4m3fn)
    return q, scale.astype(jnp.float32)


def _resolve_method(method: str, axis: str, ctx) -> str:
    """``auto`` → the device-push kernel on real TPU when ``axis`` is
    ICI-reachable, XLA elsewhere (interpret-mode Pallas is a
    correctness tool, not a fast path; a DCN-spanning EP axis is
    host-driven — the reference's cross-node analog is IBGDA RDMA,
    which ICI has no device-initiated counterpart for)."""
    if method != "auto":
        return method
    from triton_distributed_tpu.ops.common import device_initiable

    return "pallas" if device_initiable(axis, ctx) else "xla"


def ep_dispatch(
    x: jax.Array,        # [T, d] — this rank's tokens
    route: RouterOut,
    num_experts: int,
    capacity: int | None = None,
    axis: str = "ep",
    method: str = "auto",
    ctx=None,
    payload_dtype: str | None = None,
):
    """Send each (token, expert) assignment to the expert's owner rank.

    ``capacity=None`` (default) is the lossless path: per-source segments
    are ``t*k`` wide and real splits ride along, so nothing can drop.
    Returns ``(recv_x [n*C, d], recv_expert [n*C] local expert ids,
    recv_valid [n*C], state)`` — parity: ``kernel_dispatch_token`` +
    ``kernel_get_ag_splits_and_recv_offset``. Contract on BOTH
    transports: rows where ``recv_valid`` is False hold expert 0 and a
    zero payload (the XLA path by buffer construction, the pallas path
    by masking the unwritten wire-trimmed tail).
    """
    n = jax.lax.axis_size(axis)
    t, d = x.shape
    k = route.expert_ids.shape[1]
    epr = num_experts // n  # experts per rank
    lossless = capacity is None
    if lossless:
        capacity = t * k  # provable per-source worst case

    flat_e = route.expert_ids.reshape(-1)      # [T*k]
    dest = (flat_e // epr).astype(jnp.int32)
    token_ids = (jnp.arange(t * k) // k).astype(jnp.int32)

    # Slot = occurrence index among assignments with the same destination
    # (the cumsum the CUDA align kernel computes per expert block).
    onehot = jax.nn.one_hot(dest, n, dtype=jnp.int32)  # [T*k, n]
    occ = jnp.cumsum(onehot, axis=0) - onehot          # exclusive
    slot = jnp.take_along_axis(occ, dest[:, None], axis=1)[:, 0]
    valid = slot < capacity
    splits = jnp.sum(onehot, axis=0).astype(jnp.int32)  # [n] true counts
    num_dropped = jnp.sum(
        jnp.maximum(splits - capacity, 0), dtype=jnp.int32
    )

    # Scatter into per-destination buffers. In lossless mode "drop" can
    # never trigger (slot < t*k = capacity by construction).
    send_x = jnp.zeros((n, capacity, d), x.dtype)
    send_x = send_x.at[dest, slot].set(
        x[token_ids], mode="drop", unique_indices=True
    )
    local_e = (flat_e % epr).astype(jnp.int32)
    send_e = jnp.zeros((n, capacity), jnp.int32)
    send_e = send_e.at[dest, slot].set(local_e, mode="drop", unique_indices=True)

    # Splits exchange (tiny [n] payload, XLA control plane — see
    # ``ep_exchange`` module docstring): receiver learns each source
    # segment's true fill. Replaces per-slot valid bytes.
    splits_c = jnp.minimum(splits, capacity)
    recv_counts = all_to_all(
        splits_c[:, None, None], axis=axis, method="xla", ctx=ctx,
    )[:, 0, 0]  # [n]

    method = _resolve_method(method, axis, ctx)
    recv_v = (
        jax.lax.broadcasted_iota(jnp.int32, (n, capacity), 1)
        < recv_counts[:, None]
    ).reshape(n * capacity)

    if payload_dtype == "fp8":
        q, scale = _fp8_encode(send_x.reshape(n * capacity, d))

    if method == "pallas":
        # Device-initiated transport: payload (+scale) + expert id pack
        # into one uint8 row; only filled blocks cross the wire.
        from triton_distributed_tpu.ops.moe.ep_exchange import (
            ep_exchange,
            pack_rows,
            unpack_row,
        )

        if payload_dtype == "fp8":
            parts = [
                q.reshape(n, capacity, d),
                scale.reshape(n, capacity, 1),
                send_e[..., None],
            ]
        else:
            parts = [send_x, send_e[..., None]]
        rows, offs = pack_rows(parts)
        out_rows = ep_exchange(
            rows, splits_c, recv_counts, axis=axis, ctx=ctx
        )
        if payload_dtype == "fp8":
            recv_q = unpack_row(out_rows, offs[0], jnp.float8_e4m3fn, d)
            recv_scale = unpack_row(out_rows, offs[1], jnp.float32, 1)
            recv_x = (recv_q.astype(jnp.float32) * recv_scale).astype(x.dtype)
            e_off = offs[2]
        else:
            recv_x = unpack_row(out_rows, offs[0], x.dtype, d)
            e_off = offs[1]
        recv_e = unpack_row(out_rows, e_off, jnp.int32, 1)[..., 0]
        # Rows past each source's count are unwritten garbage (the wire
        # savings); zero them so the contract matches the XLA path.
        recv_x = jnp.where(
            recv_v[:, None], recv_x.reshape(n * capacity, d), 0
        ).astype(x.dtype)
        recv_e = jnp.where(recv_v, recv_e.reshape(n * capacity), 0)
    else:
        if payload_dtype == "fp8":
            recv_q = all_to_all(
                q.reshape(n, capacity, d), axis=axis, method="xla", ctx=ctx
            )
            recv_scale = all_to_all(
                scale.reshape(n, capacity, 1), axis=axis, method="xla", ctx=ctx
            )
            recv_x = (recv_q.astype(jnp.float32) * recv_scale).astype(x.dtype)
        else:
            recv_x = all_to_all(send_x, axis=axis, method=method, ctx=ctx)
        recv_x = recv_x.reshape(n * capacity, d)
        recv_e = all_to_all(
            send_e[..., None], axis=axis, method="xla", ctx=ctx
        )[..., 0].reshape(n * capacity)
    state = DispatchState(
        dest, slot, valid, route.weights.reshape(-1), token_ids, num_dropped,
        splits_c, recv_counts,
    )
    return recv_x, recv_e, recv_v, state


def ep_combine(
    expert_out: jax.Array,  # [n*C, d] — receiver order (same slots)
    state: DispatchState,
    num_tokens: int,
    axis: str = "ep",
    method: str = "auto",
    ctx=None,
) -> jax.Array:
    """Route results back and reduce weighted per token → [T, d]
    (parity: ``kernel_combine_token``). The combine payload stays in the
    model dtype (the reference's combine is bf16 too — quantization
    error must not enter the weighted reduce twice)."""
    n = jax.lax.axis_size(axis)
    capacity = expert_out.shape[0] // n
    d = expert_out.shape[1]
    method = _resolve_method(method, axis, ctx)
    if method == "pallas":
        # Return direction mirrors dispatch: this rank holds
        # recv_counts[s] result rows for source s and gets back its own
        # splits[p] rows from dest p — same kernel, counts swapped.
        from triton_distributed_tpu.ops.moe.ep_exchange import (
            ep_exchange,
            pack_rows,
            unpack_row,
        )

        rows, offs = pack_rows([expert_out.reshape(n, capacity, d)])
        out_rows = ep_exchange(
            rows, state.recv_counts, state.splits, axis=axis, ctx=ctx
        )
        back = unpack_row(out_rows, offs[0], expert_out.dtype, d)
        # Unwritten rows past each dest's count would poison the
        # weighted sum through clamped gathers (NaN * 0 = NaN).
        sent = (
            jax.lax.broadcasted_iota(jnp.int32, (n, capacity), 1)
            < state.splits[:, None]
        )
        back = jnp.where(sent[..., None], back, 0)
    else:
        back = all_to_all(
            expert_out.reshape(n, capacity, d), axis=axis, method=method,
            ctx=ctx,
        )  # [n, C, d] — slot layout mirrors what this rank sent
    picked = back[state.dest, state.slot]  # [T*k, d]
    w = jnp.where(state.valid, state.weights, 0.0)
    out = jnp.zeros((num_tokens, d), jnp.float32)
    out = out.at[state.token_ids].add(picked.astype(jnp.float32) * w[:, None])
    return out.astype(expert_out.dtype)


def ep_moe_ffn(
    x: jax.Array,         # [T, d] — this rank's tokens
    w_router: jax.Array,  # [d, E] replicated
    w1: jax.Array,        # [E_loc, d, 2*f] — this rank's experts
    w2: jax.Array,        # [E_loc, f, d]
    k: int,
    *,
    capacity_factor: float | None = None,
    axis: str = "ep",
    method: str = "auto",
    norm_topk_prob: bool = True,
    payload_dtype: str | None = None,
    ctx=None,
    return_state: bool = False,
):
    """Full EP MoE FFN inside ``shard_map`` (parity:
    ``EPAll2AllLayer.forward`` — ``ep_a2a_layer.py:195/240``).

    ``capacity_factor=None`` (default): lossless splits-exchange path.
    A float bounds memory instead; overflow then surfaces in
    ``DispatchState.num_dropped`` (detected, never silent) — see module
    docstring.

    ``return_state=True`` returns ``(out, state)`` so callers can
    surface the :class:`DispatchState` ledger — in particular
    ``num_dropped``, which serving stats report as ``a2a_dropped``
    (docs/serving.md "MoE serving") in BOTH modes: 0 by construction on
    the lossless path, the detected overflow count under a capacity
    factor.
    """
    from triton_distributed_tpu.ops.moe.routing import router_topk

    n = jax.lax.axis_size(axis)
    t, d = x.shape
    num_experts = w1.shape[0] * n
    epr = w1.shape[0]
    if capacity_factor is None:
        capacity = None
    else:
        # Expected load per destination is t*k/n; round capacity to a
        # lane-friendly multiple of 8.
        capacity = int(-(-(t * k * capacity_factor / n) // 8) * 8)

    route = router_topk(x, w_router, k, norm_topk_prob=norm_topk_prob)
    recv_x, recv_e, recv_v, state = ep_dispatch(
        x, route, num_experts, capacity, axis, method, ctx,
        payload_dtype=payload_dtype,
    )
    # Invalid (padding) rows arrive as expert 0 with zero payload —
    # ep_dispatch's contract on both transports — so they contribute
    # nothing and cost one extra group row.
    del recv_v  # contract: already folded into recv_x/recv_e
    order = jnp.argsort(recv_e, stable=True)
    inv = jnp.argsort(order)
    sorted_x = recv_x[order]
    group_sizes = jnp.bincount(recv_e, length=epr).astype(jnp.int32)
    out_sorted = grouped_ffn(sorted_x, w1, w2, group_sizes)
    expert_out = out_sorted[inv]
    out = ep_combine(expert_out, state, t, axis, method, ctx)
    return (out, state) if return_state else out
