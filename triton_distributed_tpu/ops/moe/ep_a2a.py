"""Expert-parallel all-to-all dispatch/combine (DeepEP-style).

Parity: reference ``kernels/nvidia/ep_a2a.py`` —
``kernel_dispatch_token``:37 (route token copies to expert-owner ranks),
``kernel_combine_token``:152 (return + weighted reduce),
``kernel_get_ag_splits_and_recv_offset``:244 (splits exchange) — and the
low-latency variant ``low_latency_all_to_all.py`` (putmem_signal +
double buffering, README.md:101-187).

TPU design (SURVEY.md §7 hard part "dynamic shapes"): XLA wants static
shapes, so the variable per-rank splits become a fixed per-destination
``capacity`` with drop-on-overflow (the reference also pads its grouped
GEMM batches). Dispatch builds ``[n_ranks, capacity]`` send buffers with
a cumulative-occurrence slot assignment (the ``bincount``+offset logic of
the CUDA align kernel), exchanges them with one all-to-all (XLA or the
device-initiated Pallas ring), runs the local expert FFN expert-sorted,
and combine reverses the same slots — no splits exchange needed because
slots, not offsets, carry identity.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from triton_distributed_tpu.ops.collectives.all_to_all import all_to_all
from triton_distributed_tpu.ops.moe.grouped_gemm import grouped_ffn
from triton_distributed_tpu.ops.moe.routing import RouterOut


class DispatchState(NamedTuple):
    """Everything the source rank needs to route results back."""

    dest: jax.Array      # [T*k] destination rank per assignment
    slot: jax.Array      # [T*k] slot in the dest buffer
    valid: jax.Array     # [T*k] bool — False when dropped (over capacity)
    weights: jax.Array   # [T*k] f32 gate weights
    token_ids: jax.Array  # [T*k] source token index


def ep_dispatch(
    x: jax.Array,        # [T, d] — this rank's tokens
    route: RouterOut,
    num_experts: int,
    capacity: int,
    axis: str = "ep",
    method: str = "auto",
    ctx=None,
):
    """Send each (token, expert) assignment to the expert's owner rank.

    Returns ``(recv_x [n*C, d], recv_expert [n*C] local expert ids,
    recv_valid [n*C], state)`` — parity: ``kernel_dispatch_token``.
    """
    n = jax.lax.axis_size(axis)
    t, d = x.shape
    k = route.expert_ids.shape[1]
    epr = num_experts // n  # experts per rank

    flat_e = route.expert_ids.reshape(-1)      # [T*k]
    dest = (flat_e // epr).astype(jnp.int32)
    token_ids = (jnp.arange(t * k) // k).astype(jnp.int32)

    # Slot = occurrence index among assignments with the same destination
    # (the cumsum the CUDA align kernel computes per expert block).
    onehot = jax.nn.one_hot(dest, n, dtype=jnp.int32)  # [T*k, n]
    occ = jnp.cumsum(onehot, axis=0) - onehot          # exclusive
    slot = jnp.take_along_axis(occ, dest[:, None], axis=1)[:, 0]
    valid = slot < capacity

    # Scatter into per-destination buffers; out-of-capacity rows drop.
    send_x = jnp.zeros((n, capacity, d), x.dtype)
    send_x = send_x.at[dest, slot].set(
        x[token_ids], mode="drop", unique_indices=True
    )
    local_e = (flat_e % epr).astype(jnp.int32)
    # Invalid slots carry expert 0 with zero payload (harmless rows).
    send_e = jnp.zeros((n, capacity), jnp.int32)
    send_e = send_e.at[dest, slot].set(local_e, mode="drop", unique_indices=True)
    send_v = jnp.zeros((n, capacity), jnp.int32)
    send_v = send_v.at[dest, slot].set(1, mode="drop", unique_indices=True)

    recv_x = all_to_all(send_x, axis=axis, method=method, ctx=ctx)
    meta = jnp.concatenate(
        [send_e.astype(jnp.int32)[..., None], send_v[..., None]], axis=-1
    )
    recv_meta = all_to_all(meta, axis=axis, method=method, ctx=ctx)
    recv_e = recv_meta[..., 0].reshape(n * capacity)
    recv_v = recv_meta[..., 1].reshape(n * capacity).astype(bool)
    state = DispatchState(dest, slot, valid, route.weights.reshape(-1), token_ids)
    return recv_x.reshape(n * capacity, d), recv_e, recv_v, state


def ep_combine(
    expert_out: jax.Array,  # [n*C, d] — receiver order (same slots)
    state: DispatchState,
    num_tokens: int,
    axis: str = "ep",
    method: str = "auto",
    ctx=None,
) -> jax.Array:
    """Route results back and reduce weighted per token → [T, d]
    (parity: ``kernel_combine_token``)."""
    n = jax.lax.axis_size(axis)
    capacity = expert_out.shape[0] // n
    d = expert_out.shape[1]
    back = all_to_all(
        expert_out.reshape(n, capacity, d), axis=axis, method=method, ctx=ctx
    )  # [n, C, d] — slot layout mirrors what this rank sent
    picked = back[state.dest, state.slot]  # [T*k, d]
    w = jnp.where(state.valid, state.weights, 0.0)
    out = jnp.zeros((num_tokens, d), jnp.float32)
    out = out.at[state.token_ids].add(picked.astype(jnp.float32) * w[:, None])
    return out.astype(expert_out.dtype)


def ep_moe_ffn(
    x: jax.Array,         # [T, d] — this rank's tokens
    w_router: jax.Array,  # [d, E] replicated
    w1: jax.Array,        # [E_loc, d, 2*f] — this rank's experts
    w2: jax.Array,        # [E_loc, f, d]
    k: int,
    *,
    capacity_factor: float = 1.3,
    axis: str = "ep",
    method: str = "auto",
    norm_topk_prob: bool = True,
    ctx=None,
) -> jax.Array:
    """Full EP MoE FFN inside ``shard_map`` (parity:
    ``EPAll2AllLayer.forward`` — ``ep_a2a_layer.py:195/240``)."""
    from triton_distributed_tpu.ops.moe.routing import router_topk

    n = jax.lax.axis_size(axis)
    t, d = x.shape
    num_experts = w1.shape[0] * n
    epr = w1.shape[0]
    # Expected load per destination is t*k/n; round capacity to a
    # lane-friendly multiple of 8.
    capacity = int(-(-(t * k * capacity_factor / n) // 8) * 8)

    route = router_topk(x, w_router, k, norm_topk_prob=norm_topk_prob)
    recv_x, recv_e, recv_v, state = ep_dispatch(
        x, route, num_experts, capacity, axis, method, ctx
    )
    # Expert-sort received rows (invalid rows ride along in expert 0 with
    # zero payload — they contribute nothing and cost one extra group row).
    order = jnp.argsort(recv_e, stable=True)
    inv = jnp.argsort(order)
    sorted_x = recv_x[order]
    group_sizes = jnp.bincount(recv_e, length=epr).astype(jnp.int32)
    out_sorted = grouped_ffn(sorted_x, w1, w2, group_sizes)
    expert_out = out_sorted[inv]
    return ep_combine(expert_out, state, t, axis, method, ctx)
