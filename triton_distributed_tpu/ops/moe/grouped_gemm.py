"""Grouped (expert-batched) GEMM on the MXU.

Parity: reference grouped GEMMs inside ``allgather_group_gemm.py``
(``kernel_consumer_m_parallel_scatter_group_gemm``:535) and
``moe_reduce_rs.py`` (:167). There the kernel walks expert segments of
the sorted token array; here ``jax.lax.ragged_dot`` expresses exactly
that contraction (rows grouped by ``group_sizes``, one rhs matrix per
group) and XLA/Mosaic does the segment tiling.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def grouped_gemm(
    x: jax.Array,           # [M, d] — rows sorted by group
    w: jax.Array,           # [E, d, f]
    group_sizes: jax.Array,  # [E] int32, sum == M
    *,
    acc_dtype=jnp.float32,
) -> jax.Array:
    """``out[i] = x[i] @ w[group_of_row(i)]`` → [M, f]."""
    return jax.lax.ragged_dot(
        x, w, group_sizes, preferred_element_type=acc_dtype
    ).astype(x.dtype)


def grouped_ffn(
    x: jax.Array,            # [M, d] expert-sorted
    w1: jax.Array,           # [E, d, 2*f] — gate|up fused per expert
    w2: jax.Array,           # [E, f, d]
    group_sizes: jax.Array,  # [E]
) -> jax.Array:
    """SwiGLU expert FFN over sorted tokens → [M, d] (un-combined)."""
    h = grouped_gemm(x, w1, group_sizes)
    gate, up = jnp.split(h, 2, axis=-1)
    act = (jax.nn.silu(gate.astype(jnp.float32)) * up.astype(jnp.float32)).astype(
        x.dtype
    )
    return grouped_gemm(act, w2, group_sizes)
