"""Device-initiated EP all-to-all transport (Pallas, per-destination puts).

Parity: reference ``kernels/nvidia/low_latency_all_to_all.py`` —
``all_to_all_kernel``:36-125 pushes each destination's token rows with
``putmem_signal`` and the receiver spins on per-source signals — and the
device dispatch/combine pair ``kernels/nvidia/ep_a2a.py:37,152``. This
module is the TPU translation: ONE Pallas kernel per direction whose
DMAs push only the FILLED prefix of each per-destination segment, block
by block, with the DMA arrival semaphore as the signal.

Design notes (vs the XLA ``all_to_all`` transport in ``ep_a2a.py``):

- **Wire bytes scale with the real splits**, not the worst-case padding:
  peer ``p`` receives ``ceil(splits[p]/block)*block`` rows instead of the
  full ``capacity``-row segment. At the reference's headline config
  (128 tok/rank, topk=8, 8 ranks, lossless capacity = t*k = 1024) the
  uniform-routing fill is ~128 rows/segment — ~8x fewer wire bytes.
- **Splits stay on the XLA control plane.** The reference exchanges
  splits with a device kernel (``kernel_get_ag_splits_and_recv_offset``,
  ``ep_a2a.py:244``) because a CUDA launch is the only way to touch the
  NIC; under ``jit`` the [n]-int splits exchange compiles into the SAME
  program as the payload kernel and rides ICI as an async collective, so
  device-initiating it would only re-implement XLA's scalar path. The
  payload — where the bytes are — is what the kernel owns: the counts
  are scalar-prefetched into SMEM and every bulk byte moves by
  device-issued ``put_signal``.
- **Payload rows are packed** (fp8/bf16 payload + f32 scale + int32
  expert id in one uint8 row, lane-padded) so ONE exchange moves
  everything — the reference's flag-in-data LL codec shape, with the
  byte-counting DMA semaphore standing in for the flag word.

The receiver's segment rows past ``recv_counts[src]`` are NOT written
(that's the point); callers must mask by count, as ``ep_moe_ffn`` does.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from triton_distributed_tpu import language as dl
from triton_distributed_tpu.ops.common import (
    comm_cost,
    comm_pallas_call,
    next_collective_id,
)
from triton_distributed_tpu.runtime.mesh import DistContext

_EP_EXCHANGE_COLLECTIVE_ID = next_collective_id()

# Rows per DMA block. 32 sublanes is the int8 native tile height, and a
# multiple of every coarser dtype's tile height, so block DMAs stay
# aligned for any packed row width.
EP_BLOCK_ROWS = 32


def _for_each_run(count_blocks, nbits: int, fn):
    """Invoke ``fn(off_blocks, size_blocks)`` once per power-of-two run
    of ``count_blocks``'s binary decomposition (``off`` traced, ``size``
    static). Exactly ``popcount(count_blocks)`` <= ``nbits`` DMA-sized
    runs cover the filled prefix — the descriptor-count lever that
    replaced the old block-by-block loops (VERDICT r3 task 5: the n=1
    floor was ~5 ms because the kernel issued O(capacity/block)
    predicated DMAs; runs make it O(log))."""
    off = jnp.int32(0)
    for b in reversed(range(nbits)):
        sz = 1 << b
        bit = (count_blocks >> b) & 1

        @pl.when(bit == 1)
        def _(off=off, sz=sz):
            fn(off, sz)

        off = off + bit * sz


def _ep_exchange_kernel(
    splits_ref,   # [n] SMEM int32 — rows this rank sends to each dest
    expect_ref,   # [n] SMEM int32 — rows each source sends this rank
    x_ref,        # [n, NB, block, R] ANY uint8 — send segments, blocked
    o_ref,        # [n, NB, block, R] ANY uint8 — recv segments, blocked
    send_sems,    # DMA (n-1,)
    recv_sem,     # DMA ()
    local_sem,    # DMA ()
    *,
    axis: str,
    block: int,
    straggler_rank: int | None = None,
    straggle_nanos: int = 0,
):
    me = dl.rank(axis)
    n = dl.num_ranks(axis)
    nb_cap = x_ref.shape[1]
    # Blocks are a LEADING (untiled) dim so power-of-two runs can slice
    # at traced offsets with no sublane-alignment proof (the tiled dims
    # are the static [block, R] tail).
    nbits = max(nb_cap.bit_length(), 1)

    def seg_run(ref, seg, off, sz):
        return ref.at[seg, pl.ds(off, sz)]

    # Peers' o_ref must exist before any put (same contract as the dense
    # a2a); also fences reuse of THIS call's buffers across calls.
    dl.barrier_all(axis)
    dl.straggle_if_rank(straggler_rank, axis, straggle_nanos)

    # Own segment never crosses the wire: local DMA of the filled
    # prefix, one descriptor per binary run.
    own_nb = pl.cdiv(splits_ref[me], block)
    _for_each_run(own_nb, nbits, lambda off, sz: pltpu.make_async_copy(
        seg_run(x_ref, me, off, sz), seg_run(o_ref, me, off, sz), local_sem
    ).start())

    # Push the filled prefix of every peer segment, run by run. Data
    # from rank ``me`` lands in the peer's segment ``me`` (the dense-a2a
    # slot convention), so receivers never contend for a slot.
    for i in range(1, n):
        peer = jax.lax.rem(me + i, n)
        nb = pl.cdiv(splits_ref[peer], block)
        _for_each_run(nb, nbits, lambda off, sz, peer=peer, i=i:
                      dl.put_signal(
                          seg_run(x_ref, peer, off, sz),
                          seg_run(o_ref, me, off, sz),
                          peer,
                          send_sems.at[i - 1],
                          recv_sem,
                          axis=axis,
                      ))

    # DMA semaphores only accept descriptor-expressed waits (Pallas
    # rejects a raw semaphore_wait on a dma_sem), so waits mirror the
    # senders' run structure: one descriptor per binary run. A count
    # can exceed one segment's capacity (arrivals sum over sources), so
    # full-segment descriptors cover the quotient — <= n-1 of them —
    # and binary runs the remainder: O(n + log) waits total, vs the old
    # O(n * capacity/block) wait loop.
    def wait_runs(count_blocks, sem):
        full = count_blocks // nb_cap

        def one_full(_, carry):
            dl.wait_recv(sem, o_ref.at[0])
            return carry

        jax.lax.fori_loop(0, full, one_full, None)
        _for_each_run(count_blocks - full * nb_cap, nbits, lambda off, sz:
                      dl.wait_recv(sem, seg_run(o_ref, 0, 0, sz)))

    # Arrivals: the shared recv semaphore counts bytes, so WHICH sized
    # descriptors express the wait doesn't matter — only their total.
    total_in = jnp.int32(0)
    for i in range(1, n):
        src = jax.lax.rem(me + i, n)
        total_in = total_in + pl.cdiv(expect_ref[src], block)

    wait_runs(total_in, recv_sem)

    # Drain own-segment local copies.
    wait_runs(own_nb, local_sem)

    # Quiet: drain sends so x_ref is reusable after the call returns.
    # Send semaphores also count bytes — runs per peer cover every
    # byte pushed to it.
    for i in range(1, n):
        peer = jax.lax.rem(me + i, n)
        nb = pl.cdiv(splits_ref[peer], block)
        _for_each_run(nb, nbits, lambda off, sz, peer=peer, i=i:
                      dl.remote_copy(
                          seg_run(x_ref, peer, off, sz),
                          seg_run(o_ref, me, off, sz),
                          peer,
                          send_sems.at[i - 1],
                          recv_sem,
                          axis=axis,
                      ).wait_send())


def ep_exchange(
    rows: jax.Array,         # [n, C, R] uint8 — per-destination segments
    splits: jax.Array,       # [n] int32 — rows really sent per dest (<= C)
    recv_counts: jax.Array,  # [n] int32 — rows each source sends here
    axis: str = "ep",
    ctx: DistContext | None = None,
    block: int = EP_BLOCK_ROWS,
    straggler_rank: int | None = None,
    straggle_nanos: int = 0,
) -> jax.Array:
    """Block-granular device-push all-to-all of packed uint8 rows.

    Call inside ``shard_map``. Segment ``p`` of ``rows`` goes to device
    ``p``'s segment ``me``; only ``ceil(splits[p]/block)`` blocks cross
    the wire. Returns ``[n, C, R]`` whose segment ``s`` holds
    ``recv_counts[s]`` valid rows — rows past the count (and past the
    last sent block) are unwritten garbage the caller must mask.
    """
    n, c, r = rows.shape
    if rows.dtype != jnp.uint8:
        raise ValueError(f"ep_exchange moves packed uint8 rows, got {rows.dtype}")
    if r % 128:
        raise ValueError(f"packed row width {r} must be lane-aligned (128)")
    pad_c = (-c) % block
    if pad_c:
        rows = jnp.pad(rows, ((0, 0), (0, pad_c), (0, 0)))
    cp = c + pad_c
    # Blocked layout [n, NB, block, R]: the block index becomes a
    # LEADING (untiled) dim, so the kernel's power-of-two runs can DMA
    # from traced block offsets (dynamic sublane slices of [C, R] would
    # need an alignment proof Mosaic can't make on a run sum).
    rows = rows.reshape(n, cp // block, block, r)

    out = comm_pallas_call(
        functools.partial(
            _ep_exchange_kernel,
            axis=axis,
            block=block,
            straggler_rank=straggler_rank,
            straggle_nanos=straggle_nanos,
        ),
        jax.ShapeDtypeStruct((n, cp // block, block, r), jnp.uint8),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[
            pltpu.SemaphoreType.DMA((max(n - 1, 1),)),
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA(()),
        ],
        collective_id=_EP_EXCHANGE_COLLECTIVE_ID,
        ctx=ctx,
        cost_estimate=comm_cost(bytes_accessed=2 * n * cp * r),
    )(splits.astype(jnp.int32), recv_counts.astype(jnp.int32), rows)
    out = out.reshape(n, cp, r)
    return out[:, :c] if pad_c else out


# -- row packing (the LL codec: payload + scale + metadata in one row) ------

def _to_u8(x: jax.Array) -> jax.Array:
    """Bitcast any-dtype [..., d] to uint8 [..., d*itemsize]."""
    if x.dtype == jnp.uint8:
        return x
    u8 = jax.lax.bitcast_convert_type(x, jnp.uint8)
    return u8.reshape(*x.shape[:-1], x.shape[-1] * x.dtype.itemsize)


def _from_u8(u8: jax.Array, dtype, d: int) -> jax.Array:
    """Inverse of :func:`_to_u8` for the leading ``d*itemsize`` bytes."""
    it = jnp.dtype(dtype).itemsize
    if it == 1:
        return jax.lax.bitcast_convert_type(u8[..., :d], dtype)
    return jax.lax.bitcast_convert_type(
        u8[..., : d * it].reshape(*u8.shape[:-1], d, it), dtype
    )


def pack_rows(parts: list[jax.Array]) -> tuple[jax.Array, list[int]]:
    """Pack per-row arrays (same leading shape) into lane-padded uint8
    rows. Returns ``(rows_u8, byte_offsets)`` — offsets index the start
    of each part for :func:`unpack_rows`."""
    chunks = [_to_u8(p) for p in parts]
    offsets, off = [], 0
    for ch in chunks:
        offsets.append(off)
        off += ch.shape[-1]
    pad = (-off) % 128
    if pad:
        chunks.append(jnp.zeros((*chunks[0].shape[:-1], pad), jnp.uint8))
    return jnp.concatenate(chunks, axis=-1), offsets


def unpack_row(rows_u8: jax.Array, offset: int, dtype, d: int) -> jax.Array:
    """Slice one packed part back out (see :func:`pack_rows`)."""
    it = jnp.dtype(dtype).itemsize
    return _from_u8(rows_u8[..., offset : offset + d * it], dtype, d)
