"""Rotary position embeddings (RoPE).

Parity role: the reference applies rotary inside ``TP_Attn``
(``layers/nvidia/tp_attn.py:120-160``) with precomputed cos/sin caches.
Here it's a pure function over positions — XLA fuses the trig + rotate
into the surrounding kernels, so no cache tensor is materialized.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rope_freqs(head_dim: int, theta: float = 1e6) -> jax.Array:
    """Inverse frequencies [head_dim/2] (Qwen3 default theta=1e6)."""
    return 1.0 / (
        theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim)
    )


def apply_rope(
    x: jax.Array,          # [..., S, head_dim] or [..., head_dim]
    positions: jax.Array,  # [..., S] or [...] int32 absolute positions
    theta: float = 1e6,
) -> jax.Array:
    """Rotate-half RoPE (HF convention: first/second half pairing)."""
    head_dim = x.shape[-1]
    inv = rope_freqs(head_dim, theta)
    ang = positions[..., None].astype(jnp.float32) * inv  # [..., hd/2]
    cos = jnp.cos(ang)
    sin = jnp.sin(ang)
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)
