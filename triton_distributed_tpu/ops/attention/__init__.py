"""Attention kernels: flash prefill, GQA flash-decode, distributed decode,
sequence-parallel attention.

Parity: reference ``kernels/nvidia/flash_decode.py`` (split-KV :130,
combine :393/:482), ``sp_ag_attention_{intra,inter}_node.py``, plus ring
attention as the TPU-native long-context addition (SURVEY.md §5).
"""

from triton_distributed_tpu.ops.attention.flash_attention import (  # noqa: F401
    flash_attention,
    mha_reference,
)
from triton_distributed_tpu.ops.attention.flash_decode import (  # noqa: F401
    flash_decode,
    gqa_decode_reference,
    distributed_flash_decode,
    distributed_flash_decode_2level,
    paged_flash_decode,
)
from triton_distributed_tpu.ops.attention.sp_ag_attention import (  # noqa: F401
    sp_ag_attention,
    sp_ag_attention_2level,
)
from triton_distributed_tpu.ops.attention.ring_attention import (  # noqa: F401
    ring_attention,
)
