"""GQA flash-decode: split-KV kernel + cross-rank combine.

Parity: reference ``kernels/nvidia/flash_decode.py`` — split-KV kernel
:130 (each program attends q over one KV chunk, emitting a partial
output + log-sum-exp), intra-rank combine :393, and the **inter-rank**
combine :482 where ranks exchange (partial O, LSE) via ``putmem_signal``
and merge with a log-sum-exp weighting — scaling decode 1→32 GPUs
(README "Scaling of Distributed Flash-Decode").

TPU design: the split-KV pass is one Pallas kernel, grid =
(batch, kv_heads, kv_chunks) with the GQA head group riding the sublane
dimension (q block ``[group, d]``), context length masked per chunk from
a scalar-prefetch ``kv_len``. The combine is a log-sum-exp merge —
intra-chip over the chunk axis, and for the distributed form across the
``sp`` mesh axis after an all-gather of the (O, LSE) partials (XLA
collective or our Pallas ring — the device-initiated putmem analog).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from triton_distributed_tpu.ops.collectives.all_gather import all_gather
from triton_distributed_tpu.ops.common import exporting_portable, interpret_mode

_NEG_INF = -1e30


def _decode_body(
    kv_len_ref,  # [B] int32 SMEM (scalar prefetch)
    q_ref,       # [1, 1, group, d] VMEM
    k_ref,       # [1, 1, chunk, d] VMEM — full-width, or int8 codes
    v_ref,       # [1, 1, chunk, d] VMEM
    ks_ref,      # [1, 1, 1] VMEM f32 or None — this chunk's K dequant scale
    vs_ref,      # [1, 1, 1] VMEM f32 or None — this chunk's V dequant scale
    o_ref,       # [1, 1, 1, group, d] VMEM f32 — partial output, chunk ci
    lse_ref,     # [1, 1, C, group] VMEM f32 — full chunk column, row ci
                 # written per step (Mosaic needs the block's trailing two
                 # dims to match the array, so the block spans all chunks)
    *,
    sm_scale: float,
    chunk_k: int,
):
    b = pl.program_id(0)
    ci = pl.program_id(2)
    start = ci * chunk_k
    valid = kv_len_ref[b] - start  # may be <=0 (fully masked chunk)

    @pl.when(valid > 0)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        group = q.shape[0]
        # In-register dequant: the symmetric per-chunk scale is a
        # scalar, so it folds into the softmax multiplier AFTER QK^T —
        # the MXU sees the raw int8-widened codes and full-width K
        # never exists anywhere (not even in VMEM).
        mult = sm_scale if ks_ref is None else sm_scale * ks_ref[0, 0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * mult  # [group, chunk]
        cols = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(cols < valid, s, _NEG_INF)
        m = jnp.max(s, axis=1, keepdims=True)
        p = jnp.exp(s - m)
        l = jnp.sum(p, axis=1, keepdims=True)
        if vs_ref is None:
            o = jnp.dot(
                p.astype(v_ref.dtype), v_ref[0, 0],
                preferred_element_type=jnp.float32,
            )
        else:
            # P·V over the codes, scale folded after the matmul.
            o = jnp.dot(
                p, v_ref[0, 0].astype(jnp.float32),
                preferred_element_type=jnp.float32,
            ) * vs_ref[0, 0, 0]
        o_ref[0, 0, 0] = o / l
        lse_ref[0, 0, ci] = (m + jnp.log(l))[:, 0]

    @pl.when(valid <= 0)
    def _skip():
        o_ref[:] = jnp.zeros_like(o_ref)
        lse_ref[0, 0, ci] = jnp.full(lse_ref.shape[-1:], _NEG_INF, jnp.float32)


def _decode_kernel(kv_len_ref, q_ref, k_ref, v_ref, o_ref, lse_ref, **kw):
    _decode_body(
        kv_len_ref, q_ref, k_ref, v_ref, None, None, o_ref, lse_ref, **kw
    )


def _decode_kernel_q(
    kv_len_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref, lse_ref, **kw
):
    _decode_body(
        kv_len_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref, lse_ref, **kw
    )


def lse_combine(o_parts: jax.Array, lse_parts: jax.Array, part_axis: int = 0):
    """Merge partial attention outputs by log-sum-exp weighting.

    Parity: reference combine kernels (``flash_decode.py:393,482``).
    ``o_parts [..., P, ..., d]`` f32 with partials on ``part_axis``;
    ``lse_parts`` matching without d. Returns (o, lse) reduced over P.
    """
    m = jnp.max(lse_parts, axis=part_axis, keepdims=True)
    m = jnp.maximum(m, _NEG_INF)  # all-masked guard
    w = jnp.exp(lse_parts - m)
    den = jnp.sum(w, axis=part_axis)
    o = jnp.sum(o_parts * w[..., None], axis=part_axis) / jnp.maximum(
        den[..., None], 1e-30
    )
    lse = jnp.squeeze(m, part_axis) + jnp.log(jnp.maximum(den, 1e-30))
    return o, lse


def flash_decode(
    q: jax.Array,        # [B, Hq, D]
    k_cache: jax.Array,  # [B, Hkv, S, D]
    v_cache: jax.Array,  # [B, Hkv, S, D]
    kv_len: jax.Array,   # [B] int32 — valid context length per sequence
    *,
    sm_scale: float | None = None,
    chunk_k: int = 256,
    return_lse: bool = False,
    k_scale: jax.Array | None = None,  # [B, Hkv, S/chunk_k] f32
    v_scale: jax.Array | None = None,
    interpret=None,
):
    """Single-token GQA decode attention over a (possibly padded) KV cache.

    Parity: ``gqa_fwd_batch_decode`` (``flash_decode.py:763``). Returns
    ``o [B, Hq, D]`` (q.dtype) and optionally ``lse [B, Hq]`` f32 for the
    cross-rank combine.

    ``k_scale``/``v_scale`` enable the int8 storage mode: ``k_cache``/
    ``v_cache`` hold int8 codes and the per-chunk-per-head symmetric
    scales (one f32 per ``chunk_k`` block) dequantize IN-REGISTER inside
    the kernel — full-width KV never materializes.
    """
    b, hq, d = q.shape
    _, hkv, s, _ = k_cache.shape
    if hq % hkv:
        raise ValueError(f"q heads {hq} not a multiple of kv heads {hkv}")
    group = hq // hkv
    if sm_scale is None:
        sm_scale = d**-0.5
    chunk_k = min(chunk_k, s)
    if s % chunk_k:
        raise ValueError(f"cache len {s} not divisible by chunk_k {chunk_k}")
    num_chunks = s // chunk_k
    kv_len = jnp.broadcast_to(jnp.asarray(kv_len, jnp.int32), (b,))
    quant = k_scale is not None
    if quant != (v_scale is not None):
        raise ValueError("k_scale and v_scale must be passed together")
    for name, sc in (("k_scale", k_scale), ("v_scale", v_scale)):
        if quant and sc.shape != (b, hkv, num_chunks):
            raise ValueError(
                f"{name} shape {sc.shape} != per-chunk layout "
                f"{(b, hkv, num_chunks)} (chunk_k={chunk_k})"
            )

    # jax.export can't serialize the host callbacks interpret-mode Pallas
    # lowers to; exports traced off-TPU take the pure-XLA reference path.
    resolved = interpret_mode() if interpret is None else interpret
    if resolved and exporting_portable():
        if quant:
            k_cache = k_cache.astype(jnp.float32) * jnp.repeat(
                k_scale, chunk_k, axis=-1
            )[..., None]
            v_cache = v_cache.astype(jnp.float32) * jnp.repeat(
                v_scale, chunk_k, axis=-1
            )[..., None]
        return gqa_decode_reference(
            q, k_cache, v_cache, kv_len,
            sm_scale=sm_scale, return_lse=return_lse,
        )

    qg = q.reshape(b, hkv, group, d)
    grid = (b, hkv, num_chunks)
    in_specs = [
        pl.BlockSpec((1, 1, group, d), lambda b, h, ci, _: (b, h, 0, 0)),
        pl.BlockSpec(
            (1, 1, chunk_k, d), lambda b, h, ci, _: (b, h, ci, 0)
        ),
        pl.BlockSpec(
            (1, 1, chunk_k, d), lambda b, h, ci, _: (b, h, ci, 0)
        ),
    ]
    operands = [qg, k_cache, v_cache]
    if quant:
        in_specs += [
            pl.BlockSpec((1, 1, 1), lambda b, h, ci, _: (b, h, ci)),
            pl.BlockSpec((1, 1, 1), lambda b, h, ci, _: (b, h, ci)),
        ]
        operands += [k_scale, v_scale]
    kernel = functools.partial(
        _decode_kernel_q if quant else _decode_kernel,
        sm_scale=sm_scale, chunk_k=chunk_k,
    )
    o_parts, lse_parts = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            # index maps receive the scalar-prefetch ref as a trailing arg
            in_specs=in_specs,
            out_specs=[
                pl.BlockSpec(
                    (1, 1, 1, group, d), lambda b, h, ci, _: (b, h, ci, 0, 0)
                ),
                pl.BlockSpec(
                    (1, 1, num_chunks, group), lambda b, h, ci, _: (b, h, 0, 0)
                ),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((b, hkv, num_chunks, group, d), jnp.float32),
            jax.ShapeDtypeStruct((b, hkv, num_chunks, group), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=resolved,
    )(kv_len, *operands)

    o, lse = lse_combine(o_parts, lse_parts, part_axis=2)  # [B, Hkv, group, d]
    o = o.reshape(b, hq, d).astype(q.dtype)
    if return_lse:
        return o, lse.reshape(b, hq)
    return o


def paged_flash_decode(
    q: jax.Array,        # [B, Hq, D]
    k_pages: jax.Array,  # [P, Hkv, page, D] — page pool (one layer)
    v_pages: jax.Array,
    page_table: jax.Array,  # [B, pages_per_seq] int32
    kv_len: jax.Array,      # [B] int32 — valid context length
    *,
    sm_scale: float | None = None,
    return_lse: bool = False,
    k_scale: jax.Array | None = None,  # [P, Hkv] f32 — per-page-per-head
    v_scale: jax.Array | None = None,
    interpret=None,
):
    """Single-token GQA decode attention straight over a paged KV pool.

    Parity: the reference megakernel's paged decode
    (``mega_triton_kernel/models/paged_kv_cache.py:58`` + its attention
    task reading through the page table). TPU design: the page table
    rides as a scalar-prefetch operand and the K/V BlockSpec index maps
    dereference it — ``block ci of sequence b`` fetches pool page
    ``table[b, ci]``, so the kernel body is exactly the dense split-KV
    kernel with ``chunk_k = page_size`` and no gather materializes.

    With ``k_scale``/``v_scale`` (the pool's per-page-per-head int8
    scales), the K/V blocks are int8 codes and each program fetches its
    page's scale through the SAME table indirection, dequantizing
    in-register after QK^T / P·V — the decode step streams HALF the
    bf16 pool's HBM bytes and full-width KV never exists.
    """
    b, hq, d = q.shape
    p, hkv, page, _ = k_pages.shape
    if hq % hkv:
        raise ValueError(f"q heads {hq} not a multiple of kv heads {hkv}")
    group = hq // hkv
    if sm_scale is None:
        sm_scale = d**-0.5
    pps = page_table.shape[1]
    kv_len = jnp.broadcast_to(jnp.asarray(kv_len, jnp.int32), (b,))
    quant = k_scale is not None
    if quant != (v_scale is not None):
        raise ValueError("k_scale and v_scale must be passed together")
    for name, sc in (("k_scale", k_scale), ("v_scale", v_scale)):
        if quant and sc.shape != (p, hkv):
            raise ValueError(
                f"{name} shape {sc.shape} != per-page layout {(p, hkv)}"
            )

    resolved = interpret_mode() if interpret is None else interpret
    if resolved and exporting_portable():
        k_d, v_d = _pages_to_dense(k_pages, v_pages, page_table)
        if quant:
            k_d = k_d.astype(jnp.float32) * scales_to_dense(
                k_scale, page_table, page
            )[..., None]
            v_d = v_d.astype(jnp.float32) * scales_to_dense(
                v_scale, page_table, page
            )[..., None]
        return gqa_decode_reference(
            q, k_d, v_d, kv_len, sm_scale=sm_scale, return_lse=return_lse
        )

    qg = q.reshape(b, hkv, group, d)
    grid = (b, hkv, pps)
    in_specs = [
        pl.BlockSpec(
            (1, 1, group, d), lambda b, h, ci, _, __: (b, h, 0, 0)
        ),
        # The paged part: block ci of row b is pool page
        # table[b, ci].
        pl.BlockSpec(
            (1, 1, page, d),
            lambda b, h, ci, _, tab: (tab[b, ci], h, 0, 0),
        ),
        pl.BlockSpec(
            (1, 1, page, d),
            lambda b, h, ci, _, tab: (tab[b, ci], h, 0, 0),
        ),
    ]
    operands = [qg, k_pages, v_pages]
    if quant:
        # Scales ride the same table indirection as their pages
        # (trailing singleton so the kernel reads a uniform [1,1,1]
        # block in both the dense and paged layouts).
        in_specs += [
            pl.BlockSpec(
                (1, 1, 1), lambda b, h, ci, _, tab: (tab[b, ci], h, 0)
            ),
            pl.BlockSpec(
                (1, 1, 1), lambda b, h, ci, _, tab: (tab[b, ci], h, 0)
            ),
        ]
        operands += [k_scale[..., None], v_scale[..., None]]
    kernel = functools.partial(
        _paged_decode_kernel_q if quant else _paged_decode_kernel,
        sm_scale=sm_scale, chunk_k=page,
    )
    o_parts, lse_parts = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,  # kv_len, page_table
            grid=grid,
            in_specs=in_specs,
            out_specs=[
                pl.BlockSpec(
                    (1, 1, 1, group, d), lambda b, h, ci, _, __: (b, h, ci, 0, 0)
                ),
                pl.BlockSpec(
                    (1, 1, pps, group), lambda b, h, ci, _, __: (b, h, 0, 0)
                ),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((b, hkv, pps, group, d), jnp.float32),
            jax.ShapeDtypeStruct((b, hkv, pps, group), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=resolved,
    )(kv_len, page_table, *operands)

    o, lse = lse_combine(o_parts, lse_parts, part_axis=2)
    o = o.reshape(b, hq, d).astype(q.dtype)
    if return_lse:
        return o, lse.reshape(b, hq)
    return o


def _paged_decode_kernel(kv_len_ref, table_ref, *args, **kw):
    del table_ref  # consumed by the BlockSpec index maps
    return _decode_kernel(kv_len_ref, *args, **kw)


def _paged_decode_kernel_q(kv_len_ref, table_ref, *args, **kw):
    del table_ref  # consumed by the BlockSpec index maps
    return _decode_kernel_q(kv_len_ref, *args, **kw)


def pages_to_dense(pages: jax.Array, page_table: jax.Array) -> jax.Array:
    """Gather a page pool ``[..., P, H, page, d]`` into a dense
    ``[..., B, H, S, d]`` view through the table. Single source of the
    gather layout — ``models.paged_kv_cache.as_dense`` delegates here."""
    g = jnp.take(pages, page_table, axis=-4)  # [..., B, pps, H, page, d]
    g = jnp.swapaxes(g, -4, -3)               # [..., B, H, pps, page, d]
    s = g.shape
    return g.reshape(*s[:-3], s[-3] * s[-2], s[-1])


def scales_to_dense(scales: jax.Array, page_table: jax.Array, page: int):
    """Per-position dequant scales matching a :func:`pages_to_dense`
    view: ``[..., P, H] → [..., B, H, S]`` through the table (every
    position of a page shares its page's scale)."""
    g = jnp.take(scales, page_table, axis=-2)  # [..., B, pps, H]
    g = jnp.swapaxes(g, -2, -1)                # [..., B, H, pps]
    return jnp.repeat(g, page, axis=-1)        # [..., B, H, S]


def _pages_to_dense(k_pages, v_pages, page_table):
    return pages_to_dense(k_pages, page_table), pages_to_dense(
        v_pages, page_table
    )


def _gather_merge(o, lse, axis: str, method: str, ctx=None):
    """Gather per-rank partial (O, LSE) over ``axis`` and LSE-merge.

    ``method='pallas'`` packs the partials into one [b·hq, d+1] payload
    and rides the device-initiated ring all-gather; ``'xla'`` uses the
    XLA collective. Shared by the one- and two-level decode merges.
    """
    b, hq, d = o.shape
    if method == "pallas":
        flat = jnp.concatenate([o.reshape(b * hq, d), lse.reshape(b * hq, 1)], 1)
        gathered = all_gather(flat, axis=axis, ctx=ctx)  # [n*b*hq, d+1]
        gathered = gathered.reshape(-1, b * hq, d + 1)
        o_all = gathered[..., :d].reshape(-1, b, hq, d)
        lse_all = gathered[..., d].reshape(-1, b, hq)
    else:
        o_all = jax.lax.all_gather(o, axis)      # [n, B, Hq, D]
        lse_all = jax.lax.all_gather(lse, axis)  # [n, B, Hq]
    return lse_combine(o_all, lse_all, part_axis=0)


def distributed_flash_decode(
    q: jax.Array,        # [B, Hq, D] replicated
    k_shard: jax.Array,  # [B, Hkv, S_loc, D] — this rank's KV slice
    v_shard: jax.Array,
    kv_len: jax.Array,   # [B] int32 GLOBAL context length
    *,
    axis: str = "sp",
    sm_scale: float | None = None,
    chunk_k: int = 256,
    method: str = "xla",
    k_scale: jax.Array | None = None,  # [B, Hkv, S_loc/chunk_k] f32
    v_scale: jax.Array | None = None,
    ctx=None,
):
    """Decode attention with the KV cache sequence-sharded over ``axis``.

    Runs inside ``shard_map``. Each rank attends q over its local KV slice
    (split-KV kernel), then partial (O, LSE) are exchanged across ranks
    and merged — parity with the reference's inter-rank combine
    (``flash_decode.py:482``) which putmem_signals partials between GPUs.
    ``method='pallas'`` uses the device-initiated ring all-gather;
    ``'xla'`` the XLA collective.

    ``k_scale``/``v_scale`` (this rank's per-chunk-per-head int8 scales)
    switch the local split-KV pass to in-kernel dequant over int8
    shards — exactly the regime the paper's low-latency decode kernels
    target: the ICI exchange already ships only (O, LSE) partials, so
    quantization halves the HBM stream on every rank without touching
    the combine.
    """
    me = jax.lax.axis_index(axis)
    s_loc = k_shard.shape[2]
    # Positions covered locally: [me*s_loc, me*s_loc + s_loc).
    local_len = jnp.clip(kv_len - me * s_loc, 0, s_loc)
    o, lse = flash_decode(
        q, k_shard, v_shard, local_len,
        sm_scale=sm_scale, chunk_k=chunk_k, return_lse=True,
        k_scale=k_scale, v_scale=v_scale,
    )
    merged, _ = _gather_merge(o.astype(jnp.float32), lse, axis, method, ctx)
    return merged.astype(q.dtype)


def distributed_flash_decode_2level(
    q: jax.Array,        # [B, Hq, D] replicated
    k_shard: jax.Array,  # [B, Hkv, S_loc, D] — this rank's KV slice
    v_shard: jax.Array,
    kv_len: jax.Array,   # [B] int32 GLOBAL context length
    *,
    inner_axis: str = "sp",
    outer_axis: str = "dcn",
    sm_scale: float | None = None,
    chunk_k: int = 256,
    method: str = "xla",
    k_scale: jax.Array | None = None,  # [B, Hkv, S_loc/chunk_k] f32
    v_scale: jax.Array | None = None,
    ctx=None,
):
    """Decode attention with the KV cache sequence-sharded over
    ``(outer_axis, inner_axis)`` in rank order — slices over DCN, ranks
    within a slice over ICI.

    Parity: the reference's multi-node flash-decode scaling
    (``README.md:202-209``, 32 GPUs = 4 nodes × 8) with its two-level
    combine: each rank reduces its local split-KV partials, partial
    (O, LSE) merge first across the fast intra-slice fabric (optionally
    the device-initiated Pallas ring when ``method='pallas'``), then the
    per-slice results merge once over DCN with XLA collectives.
    ``k_scale``/``v_scale`` switch the local pass to int8 shards with
    in-kernel dequant (see :func:`distributed_flash_decode`).
    """
    n_in = jax.lax.axis_size(inner_axis)
    me = jax.lax.axis_index(outer_axis) * n_in + jax.lax.axis_index(inner_axis)
    s_loc = k_shard.shape[2]
    local_len = jnp.clip(kv_len - me * s_loc, 0, s_loc)
    o, lse = flash_decode(
        q, k_shard, v_shard, local_len,
        sm_scale=sm_scale, chunk_k=chunk_k, return_lse=True,
        k_scale=k_scale, v_scale=v_scale,
    )
    # Level 1: intra-slice merge over ICI; level 2: one inter-slice
    # merge over DCN (always XLA — DCN traffic is XLA's domain).
    o_sl, lse_sl = _gather_merge(
        o.astype(jnp.float32), lse, inner_axis, method, ctx
    )
    merged, _ = _gather_merge(o_sl, lse_sl, outer_axis, "xla", ctx)
    return merged.astype(q.dtype)


def gqa_decode_reference(
    q, k_cache, v_cache, kv_len, *, sm_scale=None, return_lse=False
):
    """Golden decode (parity: the reference's torch goldens); also the
    portable-export path of :func:`flash_decode`."""
    b, hq, d = q.shape
    _, hkv, s, _ = k_cache.shape
    if sm_scale is None:
        sm_scale = d**-0.5
    k = jnp.repeat(k_cache, hq // hkv, axis=1).astype(jnp.float32)
    v = jnp.repeat(v_cache, hq // hkv, axis=1).astype(jnp.float32)
    s_ = jnp.einsum("bhd,bhkd->bhk", q.astype(jnp.float32), k) * sm_scale
    kv_len = jnp.broadcast_to(jnp.asarray(kv_len, jnp.int32), (b,))
    mask = jnp.arange(s)[None, None, :] < kv_len[:, None, None]
    s_ = jnp.where(mask, s_, _NEG_INF)
    p = jax.nn.softmax(s_, axis=-1)
    o = jnp.einsum("bhk,bhkd->bhd", p, v).astype(q.dtype)
    if return_lse:
        return o, jax.nn.logsumexp(s_, axis=-1)
    return o
