"""Ring attention over the ICI torus — the idiomatic TPU long-context
(context-parallel) kernel.

Parity role: the reference fills the SP slot with AG-attention only
(SURVEY.md §2.3: "CP / ring attention / Ulysses: absent"); ring attention
is the TPU-native addition the survey calls for (§5) — KV circulates the
ring via ``ppermute`` (XLA double-buffers the collective-permute against
compute, the stream-overlap analog) while each device accumulates
blockwise-softmax partials with its flash-attention kernel, merged by
log-sum-exp — the same merge the distributed decode uses.

Causal load: chunks from later ranks contribute nothing to earlier
ranks' queries; they are masked (full lse=-inf partials) rather than
skipped so every ring step is a static program. A zig-zag sharding (half
from each sequence end per device) would rebalance — left for a later
round.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from triton_distributed_tpu.ops.attention.flash_attention import flash_attention
from triton_distributed_tpu.ops.attention.flash_decode import lse_combine


def ring_attention(
    q: jax.Array,  # [hq, s_loc, hd] — this device's q shard (rank order)
    k: jax.Array,  # [hkv, s_loc, hd]
    v: jax.Array,
    *,
    axis: str = "sp",
    causal: bool = True,
    sm_scale: float | None = None,
    block_q: int = 128,
    block_k: int = 128,
) -> jax.Array:
    """Causal ring attention inside ``shard_map``; returns [hq, s_loc, hd].

    Uses the Pallas flash kernel per step (LSE out) + ppermute rotation;
    n steps visit every KV chunk once.
    """
    n = jax.lax.axis_size(axis)
    me = jax.lax.axis_index(axis)
    hq, s_loc, hd = q.shape
    if sm_scale is None:
        sm_scale = hd**-0.5
    perm = [(i, (i + 1) % n) for i in range(n)]  # chunk r hops right

    def step(carry, i):
        k_cur, v_cur = carry
        src = jax.lax.rem(me - i + n, n)  # rank that produced this chunk
        # Block-level mask: src < me → fully visible; src == me → causal
        # within; src > me → fully masked (future rows).
        o_i, lse_i = flash_attention(
            q[None], k_cur[None], v_cur[None],
            causal=False, sm_scale=sm_scale,
            block_q=block_q, block_k=block_k, return_lse=True,
        )
        if causal:
            # Recompute own-chunk causal variant and select by src (src is
            # dynamic, so both variants trace; the causal one only matters
            # one step out of n — acceptable until zig-zag sharding lands).
            o_c, lse_c = flash_attention(
                q[None], k_cur[None], v_cur[None],
                causal=True, sm_scale=sm_scale,
                block_q=block_q, block_k=block_k, return_lse=True,
            )
            own = src == me
            visible = src < me
            o_i = jnp.where(own, o_c, jnp.where(visible, o_i, 0.0))
            lse_i = jnp.where(
                own, lse_c, jnp.where(visible, lse_i, -jnp.inf)
            )
        k_nxt = jax.lax.ppermute(k_cur, axis, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis, perm)
        return (k_nxt, v_nxt), (o_i[0].astype(jnp.float32), lse_i[0])

    (_, _), (o_parts, lse_parts) = jax.lax.scan(
        step, (k, v), jnp.arange(n)
    )  # o_parts [n, hq, s_loc, hd], lse [n, hq, s_loc]
    o, _ = lse_combine(o_parts, lse_parts, part_axis=0)
    return o.astype(q.dtype)
