"""Sequence-parallel AllGather attention (long-context prefill) — the
KV-gather and the causal flash-attention consumer fused in ONE kernel.

Parity: reference ``kernels/nvidia/sp_ag_attention_intra_node.py`` /
``_inter_node.py`` — KV shards are allgathered chunk-by-chunk on a comm
stream (CE push :105 / NVSHMEM push kernel :115) while a causal
flash-attn consumer ``dl.wait``s per-chunk signals (:256/:328); entry
points ``fused_sp_ag_attn_*`` (:432/:504).

TPU design (no streams — SURVEY.md §7): each device pushes its local KV
shard over ICI to every later-ranked peer at kernel start (causal
attention only looks backward), then sweeps its q blocks against KV
chunks 0..me, waiting on each chunk's arrival semaphore at first touch.
The DMA engines carry the gather while the MXU runs flash attention on
already-arrived chunks — the reference's producer/consumer overlap with
the semaphore replacing the tile-barrier spin.

Grid = (hq, q_blocks, n_chunks), chunk innermost so the running-softmax
accumulators live across the chunk sweep; chunks beyond ``me`` are
predicated off (those rows attend only to earlier ranks).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from triton_distributed_tpu import language as dl
from triton_distributed_tpu.ops.common import comm_pallas_call, next_collective_id

_SP_AG_COLLECTIVE_ID = next_collective_id()
_NEG_INF = -1e30


def _sp_ag_attn_kernel(
    q_ref,     # [1, bq, hd] VMEM — q block (head h, block qb)
    kv_ref,    # [2, hkv, s_loc, hd] ANY — local KV shard (k=0, v=1)
    o_ref,     # [1, bq, hd] VMEM — output block (written at r == me)
    lse_ref,   # [1, bq, 1] VMEM — log-sum-exp per q row (same schedule)
    ws,        # [n, 2, hkv, s_loc, hd] ANY out — arrived KV chunks
    k_vmem,    # [s_loc, hd] VMEM scratch
    v_vmem,    # [s_loc, hd] VMEM scratch
    acc,       # [bq, hd] f32
    m_i,       # [bq, 1] f32
    l_i,       # [bq, 1] f32
    stage_sems,  # DMA (2,)
    copy_sem,    # DMA ()
    send_sems,   # DMA (n,) — slot i for the push to peer i
    recv_sems,   # DMA (n,) — slot r signaled when chunk r lands
    *,
    axis: str,
    group: int,
    sm_scale: float,
    bq: int,
):
    me = dl.rank(axis)
    n = dl.num_ranks(axis)
    h = pl.program_id(0)
    qb = pl.program_id(1)
    r = pl.program_id(2)
    num_h = pl.num_programs(0)
    num_qb = pl.num_programs(1)
    s_loc = kv_ref.shape[2]
    g = h // group  # kv head for this q head

    @pl.when(jnp.logical_and(h == 0, jnp.logical_and(qb == 0, r == 0)))
    def _produce():
        # Entry barrier: peers' ws must be allocated before pushes land.
        dl.barrier_all(axis)
        # Own chunk into the local workspace slot...
        dma = pltpu.make_async_copy(kv_ref, ws.at[me], copy_sem)
        dma.start()
        # ...and pushed to every later-ranked peer (they look back at us).
        def push(i, _):
            dl.put_signal(
                kv_ref, ws.at[me], i, send_sems.at[i], recv_sems.at[me],
                axis=axis,
            )
            return _
        jax.lax.fori_loop(me + 1, n, push, None)
        dma.wait()

    # First touch of a remote chunk: wait for its arrival signal.
    @pl.when(jnp.logical_and(h == 0, jnp.logical_and(qb == 0, r < me)))
    def _await_chunk():
        dl.wait_recv(recv_sems.at[r], ws.at[r])

    @pl.when(r == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_i[:] = jnp.full_like(m_i, _NEG_INF)
        l_i[:] = jnp.zeros_like(l_i)

    @pl.when(r <= me)
    def _consume():
        # Stage chunk r's K/V for this kv head into VMEM.
        kdma = pltpu.make_async_copy(ws.at[r, 0, g], k_vmem, stage_sems.at[0])
        vdma = pltpu.make_async_copy(ws.at[r, 1, g], v_vmem, stage_sems.at[1])
        kdma.start()
        vdma.start()
        kdma.wait()
        vdma.wait()

        q = q_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_vmem[:].astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale  # [bq, s_loc]

        # Causal mask only applies within the own chunk (earlier ranks'
        # chunks are fully visible); folded into one jnp.where so the
        # softmax update traces once.
        rows = qb * bq + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        visible = jnp.logical_or(r < me, cols <= rows)
        scores = jnp.where(visible, s, _NEG_INF)

        m_new = jnp.maximum(m_i[:], jnp.max(scores, axis=1, keepdims=True))
        p = jnp.exp(scores - m_new)
        alpha = jnp.exp(m_i[:] - m_new)
        l_i[:] = l_i[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc[:] = acc[:] * alpha + jnp.dot(
            p.astype(v_vmem.dtype), v_vmem[:],
            preferred_element_type=jnp.float32,
        )
        m_i[:] = m_new

    @pl.when(r == me)
    def _finalize():
        l = jnp.maximum(l_i[:], 1e-30)
        o_ref[0] = (acc[:] / l).astype(o_ref.dtype)
        lse_ref[0] = m_i[:] + jnp.log(l)

    @pl.when(
        jnp.logical_and(
            h == num_h - 1, jnp.logical_and(qb == num_qb - 1, r == n - 1)
        )
    )
    def _drain():
        def drain_one(i, _):
            pltpu.make_async_copy(kv_ref, kv_ref, send_sems.at[i]).wait()
            return _
        jax.lax.fori_loop(me + 1, n, drain_one, None)


def sp_ag_attention(
    q: jax.Array,  # [hq, s_loc, hd] — this device's q shard
    k: jax.Array,  # [hkv, s_loc, hd] — this device's KV shard
    v: jax.Array,
    *,
    axis: str = "sp",
    sm_scale: float | None = None,
    block_q: int = 256,
    return_lse: bool = False,
    ctx=None,
) -> jax.Array:
    """Causal SP attention inside ``shard_map``; sequence sharded over
    ``axis`` in rank order. Returns ``o [hq, s_loc, hd]`` (q layout),
    plus the per-row log-sum-exp ``[hq, s_loc]`` when ``return_lse``
    (for hierarchical/DCN-level merges).

    Parity: ``fused_sp_ag_attn_intra_node``
    (``sp_ag_attention_intra_node.py:432``).
    """
    n = jax.lax.axis_size(axis)
    hq, s_loc, hd = q.shape
    hkv = k.shape[0]
    if hq % hkv:
        raise ValueError(f"q heads {hq} not a multiple of kv heads {hkv}")
    if sm_scale is None:
        sm_scale = hd**-0.5
    bq = min(block_q, s_loc)
    if s_loc % bq:
        raise ValueError(f"s_loc={s_loc} not divisible by block_q={bq}")
    kv = jnp.stack([k, v])  # [2, hkv, s_loc, hd]

    out, lse, _ws = comm_pallas_call(
        functools.partial(
            _sp_ag_attn_kernel,
            axis=axis, group=hq // hkv, sm_scale=sm_scale, bq=bq,
        ),
        (
            jax.ShapeDtypeStruct((hq, s_loc, hd), q.dtype),
            jax.ShapeDtypeStruct((hq, s_loc, 1), jnp.float32),
            jax.ShapeDtypeStruct((n, 2, hkv, s_loc, hd), k.dtype),
        ),
        grid=(hq, s_loc // bq, n),
        in_specs=[
            pl.BlockSpec((1, bq, hd), lambda h, qb, r: (h, qb, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=(
            pl.BlockSpec((1, bq, hd), lambda h, qb, r: (h, qb, 0)),
            pl.BlockSpec((1, bq, 1), lambda h, qb, r: (h, qb, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
        ),
        scratch_shapes=[
            pltpu.VMEM((s_loc, hd), k.dtype),
            pltpu.VMEM((s_loc, hd), v.dtype),
            pltpu.VMEM((bq, hd), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA((n,)),
            pltpu.SemaphoreType.DMA((n,)),
        ],
        collective_id=_SP_AG_COLLECTIVE_ID,
        dimension_semantics=("arbitrary", "arbitrary", "arbitrary"),
        ctx=ctx,
    )(q, kv)
    return (out, lse[..., 0]) if return_lse else out


def sp_ag_attention_2level(
    q: jax.Array,  # [hq, s_loc, hd] — this device's q shard
    k: jax.Array,  # [hkv, s_loc, hd]
    v: jax.Array,
    *,
    inner_axis: str = "sp",
    outer_axis: str = "dcn",
    sm_scale: float | None = None,
    block_q: int = 256,
    ctx=None,
) -> jax.Array:
    """Two-level causal SP attention: sequence sharded over
    ``(outer_axis, inner_axis)`` in rank order — slices over DCN, ranks
    within a slice over ICI.

    Parity: ``fused_sp_ag_attn_inter_node``
    (``sp_ag_attention_inter_node.py:115,504``) — there the intra-node
    gather rides NVSHMEM while inter-node chunks arrive over IB. TPU
    redesign: the intra-slice half runs the fused one-kernel Pallas
    gather+attention (ICI); the inter-slice half attends the q shard
    over earlier slices' KV gathered with XLA collectives (DCN), and the
    two partial softmaxes merge by log-sum-exp — the reference's
    combine step (``flash_decode.py:482`` pattern) at slice granularity.
    """
    n_out = jax.lax.axis_size(outer_axis)
    me_out = jax.lax.axis_index(outer_axis)
    hq, s_loc, hd = q.shape
    hkv = k.shape[0]
    g = hq // hkv
    if sm_scale is None:
        sm_scale = hd**-0.5

    # Intra-slice: fused Pallas kernel over the ICI axis.
    o_intra, lse_intra = sp_ag_attention(
        q, k, v, axis=inner_axis, sm_scale=sm_scale, block_q=block_q,
        return_lse=True, ctx=ctx,
    )
    o_intra = o_intra.astype(jnp.float32)
    if n_out == 1:
        return o_intra.astype(q.dtype)

    # Inter-slice: earlier slices are fully visible (causal order). KV
    # is gathered slice-major over both axes with XLA collectives (the
    # DCN leg — the reference's inter-node buffer likewise holds the
    # gathered sequence, sp_ag_attention_inter_node.py:115), then the
    # online softmax streams slice by slice: score memory stays
    # O(g·s_loc × s_slice) instead of one dense matrix over the global
    # sequence, and the fori upper bound is me_out, so slice 0 does no
    # masked busywork.
    k_slice = jax.lax.all_gather(k, inner_axis, axis=1, tiled=True)
    v_slice = jax.lax.all_gather(v, inner_axis, axis=1, tiled=True)
    k_all = jax.lax.all_gather(k_slice, outer_axis)  # [n_out, hkv, s_sl, hd]
    v_all = jax.lax.all_gather(v_slice, outer_axis)
    s_slice = k_slice.shape[1]

    qg = q.reshape(hkv, g * s_loc, hd).astype(jnp.float32)
    rows = g * s_loc

    def slice_step(r, carry):
        m, l, acc = carry
        kr = k_all[r].astype(jnp.float32)  # [hkv, s_slice, hd]
        vr = v_all[r].astype(jnp.float32)
        s = jax.lax.dot_general(
            qg, kr, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ) * sm_scale  # [hkv, g*s_loc, s_slice]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jax.lax.dot_general(
            p, vr, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )
        return m_new, l, acc

    m0 = jnp.full((hkv, rows, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((hkv, rows, 1), jnp.float32)
    a0 = jnp.zeros((hkv, rows, hd), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, me_out, slice_step, (m0, l0, a0))
    o_prev = (acc / jnp.maximum(l, 1e-30)).reshape(hq, s_loc, hd)
    lse_prev = (m + jnp.log(jnp.maximum(l, 1e-30))).reshape(hq, s_loc)

    from triton_distributed_tpu.ops.attention.flash_decode import lse_combine

    o, _ = lse_combine(
        jnp.stack([o_intra, o_prev]),
        jnp.stack([lse_intra, lse_prev]),
        part_axis=0,
    )
    return o.astype(q.dtype)
