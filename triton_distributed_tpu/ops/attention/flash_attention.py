"""Blockwise flash attention (prefill) — Pallas TPU kernel.

Parity role: the reference consumes flash attention from its own Triton
kernels inside SP-AG attention (``sp_ag_attention_intra_node.py:256`` —
causal consumer) and from torch SDPA in layers (``tp_attn.py:203-271``).
Here the kernel is first-class: causal/GQA flash attention with an
optional log-sum-exp output, which the distributed decode and SP paths
reuse for cross-shard softmax merging (``flash_decode.py:482`` analog).

TPU design: grid = (batch·q_heads, q_blocks, kv_blocks), kv innermost so
the f32 accumulator + running (m, l) live in VMEM scratch across the kv
sweep; the MXU sees [block_q, d] @ [d, block_k] and [block_q, block_k] @
[block_k, d] shapes; causal blocks above the diagonal are skipped via
``pl.when`` (zero-work predication, the analog of the reference's early
``continue`` on masked tiles).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from triton_distributed_tpu.ops.common import exporting_portable, interpret_mode

_NEG_INF = -1e30


def _attn_kernel(
    off_ref,  # [1] int32 SMEM (scalar prefetch) or None — kv offset
    q_ref,    # [1, block_q, d] VMEM
    k_ref,    # [1, block_k, d] VMEM — full-width, or int8 codes
    v_ref,    # [1, block_k, d] VMEM
    ks_ref,   # [1, 1] VMEM f32 or None — this kv block's K dequant scale
    vs_ref,   # [1, 1] VMEM f32 or None — this kv block's V dequant scale
    b_ref,    # [block_q, block_k] VMEM f32 or None — additive score bias
    o_ref,    # [1, block_q, d] VMEM
    lse_ref,  # [1, 1, sq] VMEM or None — full row; slice qi written at
              # finalize (Mosaic requires the block's trailing dims to
              # match the array, so the block spans the whole q length)
    acc,      # [block_q, d] f32 scratch
    m_i,      # [block_q, 1] f32 scratch — running max
    l_i,      # [block_q, 1] f32 scratch — running sum-exp
    *,
    sm_scale: float,
    causal: bool,
    kv_offset: int,
    block_q: int,
    block_k: int,
):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    num_k = pl.num_programs(2)
    kv_offset = kv_offset if off_ref is None else off_ref[0]

    @pl.when(ki == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_i[:] = jnp.full_like(m_i, _NEG_INF)
        l_i[:] = jnp.zeros_like(l_i)

    # Causal skip: the kv block starts after the last q row can see.
    q_end = kv_offset + (qi + 1) * block_q - 1  # last absolute q position
    run = (ki * block_k <= q_end) if causal else True

    @pl.when(run)
    def _body():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        # In-register dequant (int8 KV): the per-block symmetric scale
        # is a scalar, so it folds into the softmax multiplier after
        # QK^T — full-width K never materializes.
        mult = sm_scale if ks_ref is None else sm_scale * ks_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * mult  # [block_q, block_k]
        if b_ref is not None:
            # Additive score bias (0 / -inf): the tree-attention mask of
            # the speculative verify chunk. Applied before the causal
            # mask — the bias only ever masks MORE than causality, so
            # the causal block-skip above stays sound.
            s = s + b_ref[...]
        if causal:
            rows = kv_offset + qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            cols = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(cols <= rows, s, _NEG_INF)
        m_new = jnp.maximum(m_i[:], jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_i[:] - m_new)
        l_i[:] = l_i[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
        if vs_ref is None:
            pv = jnp.dot(
                p.astype(v_ref.dtype), v_ref[0],
                preferred_element_type=jnp.float32,
            )
        else:
            pv = jnp.dot(
                p, v_ref[0].astype(jnp.float32),
                preferred_element_type=jnp.float32,
            ) * vs_ref[0, 0]
        acc[:] = acc[:] * alpha + pv
        m_i[:] = m_new

    @pl.when(ki == num_k - 1)
    def _finalize():
        l = jnp.maximum(l_i[:], 1e-30)
        o_ref[0] = (acc[:] / l).astype(o_ref.dtype)
        if lse_ref is not None:
            lse_ref[0, 0, pl.ds(qi * block_q, block_q)] = (m_i[:] + jnp.log(l))[
                :, 0
            ]


def flash_attention(
    q: jax.Array,  # [B, Hq, Sq, D]
    k: jax.Array,  # [B, Hkv, Sk, D]
    v: jax.Array,  # [B, Hkv, Sk, D]
    *,
    causal: bool = True,
    sm_scale: float | None = None,
    kv_offset: int | jax.Array = 0,
    block_q: int = 128,
    block_k: int = 128,
    return_lse: bool = False,
    k_scale: jax.Array | None = None,  # [B, Hkv, Sk/block_k] f32
    v_scale: jax.Array | None = None,
    bias: jax.Array | None = None,     # [Sq, Sk] f32 additive score bias
    interpret=None,
):
    """Causal/GQA flash attention. ``kv_offset``: absolute position of
    ``q[..., 0, :]`` within the kv sequence (non-zero for chunked prefill
    against a KV cache — parity with the reference's offset handling in
    ``flash_decode.py`` host wrappers). A traced/array ``kv_offset``
    rides as a scalar-prefetch operand, so one compiled kernel serves
    every chunk offset of a chunked prefill (a static int keeps the
    constant-folded path).

    ``k_scale``/``v_scale`` enable the int8 KV mode (the paged-prefill
    chunk path over a quantized pool): ``k``/``v`` hold int8 codes and
    one symmetric f32 scale per ``block_k`` block per head dequantizes
    in-register after QK^T / P·V. Callers align ``block_k`` with the
    quantization granularity (the chunk path sets ``block_k =
    page_size`` so per-page pool scales ARE per-block scales).

    ``bias`` is an optional ``[Sq, Sk]`` f32 additive score bias shared
    across batch and heads (0 = visible, ``-1e30`` = masked) — the
    tree-attention mask of speculative verify chunks, where sibling
    draft branches must not attend to each other. It composes with
    ``causal=True``: tree masks only ever REMOVE visibility relative to
    storage-order causality (ancestors precede descendants in storage),
    so the causal block skip stays valid.

    Returns ``o [B, Hq, Sq, D]`` (and ``lse [B, Hq, Sq]`` f32 when
    ``return_lse`` — base-e log-sum-exp of scaled scores, the quantity the
    distributed combine merges).
    """
    b, hq, sq, d = q.shape
    _, hkv, sk, _ = k.shape
    if hq % hkv:
        raise ValueError(f"q heads {hq} not a multiple of kv heads {hkv}")
    group = hq // hkv
    if sm_scale is None:
        sm_scale = d**-0.5
    quant = k_scale is not None
    if quant != (v_scale is not None):
        raise ValueError("k_scale and v_scale must be passed together")
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    # Validate BOTH scale layouts BEFORE the portable early-return: the
    # reference path below would otherwise dequantize a mis-shaped
    # scale at the wrong granularity, and the Pallas path's clamped
    # block indices would silently read the wrong page's scale.
    for name, sc in (("k_scale", k_scale), ("v_scale", v_scale)):
        if quant and sc.shape != (b, hkv, sk // block_k):
            raise ValueError(
                f"{name} shape {sc.shape} != per-block layout "
                f"{(b, hkv, sk // block_k)} (block_k={block_k})"
            )
    if bias is not None and bias.shape != (sq, sk):
        raise ValueError(f"bias shape {bias.shape} != {(sq, sk)}")
    # jax.export can't serialize the host callbacks interpret-mode
    # Pallas lowers to; portable exports take the XLA-reference path
    # (same contract as flash_decode's portable fallback).
    interpret = interpret_mode() if interpret is None else interpret
    if interpret and exporting_portable():
        if quant:
            k = k.astype(jnp.float32) * jnp.repeat(
                k_scale, block_k, axis=-1
            )[..., None]
            v = v.astype(jnp.float32) * jnp.repeat(
                v_scale, block_k, axis=-1
            )[..., None]
        return mha_reference(
            q, k, v, causal=causal, sm_scale=sm_scale,
            kv_offset=kv_offset, return_lse=return_lse, bias=bias,
        )
    if sq % block_q or sk % block_k:
        raise ValueError(f"seq ({sq},{sk}) not divisible by blocks "
                         f"({block_q},{block_k}); pad upstream")

    qf = q.reshape(b * hq, sq, d)
    kf = k.reshape(b * hkv, sk, d)
    vf = v.reshape(b * hkv, sk, d)
    grid = (b * hq, sq // block_q, sk // block_k)
    dynamic_off = not isinstance(kv_offset, int)

    out_shape = [jax.ShapeDtypeStruct((b * hq, sq, d), q.dtype)]
    out_specs = [
        pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
    ]
    if return_lse:
        out_shape.append(jax.ShapeDtypeStruct((b * hq, 1, sq), jnp.float32))
        out_specs.append(
            pl.BlockSpec((1, 1, sq), lambda bh, qi, ki: (bh, 0, 0))
        )

    kernel = functools.partial(
        _attn_kernel,
        sm_scale=sm_scale,
        causal=causal,
        kv_offset=0 if dynamic_off else kv_offset,
        block_q=block_q,
        block_k=block_k,
    )
    kernel = functools.partial(
        _adapt_refs, kernel, dynamic_off, quant, bias is not None,
        return_lse,
    )
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
        pl.BlockSpec(
            (1, block_k, d), lambda bh, qi, ki, g=group: (bh // g, ki, 0)
        ),
        pl.BlockSpec(
            (1, block_k, d), lambda bh, qi, ki, g=group: (bh // g, ki, 0)
        ),
    ]
    operands = [qf, kf, vf]
    if quant:
        in_specs += [
            pl.BlockSpec(
                (1, 1), lambda bh, qi, ki, g=group: (bh // g, ki)
            ),
            pl.BlockSpec(
                (1, 1), lambda bh, qi, ki, g=group: (bh // g, ki)
            ),
        ]
        operands += [
            k_scale.reshape(b * hkv, -1), v_scale.reshape(b * hkv, -1)
        ]
    if bias is not None:
        in_specs.append(
            pl.BlockSpec((block_q, block_k), lambda bh, qi, ki: (qi, ki))
        )
        operands.append(bias.astype(jnp.float32))
    scratch_shapes = [
        pltpu.VMEM((block_q, d), jnp.float32),
        pltpu.VMEM((block_q, 1), jnp.float32),
        pltpu.VMEM((block_q, 1), jnp.float32),
    ]
    compiler_params = pltpu.CompilerParams(
        dimension_semantics=("parallel", "arbitrary", "arbitrary"),
    )
    if dynamic_off:
        # Dynamic offset rides as scalar prefetch; index maps gain the
        # scalar ref as a trailing arg (flash_decode's paged idiom).
        off = jnp.asarray(kv_offset, jnp.int32).reshape(1)
        res = pl.pallas_call(
            kernel,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=1,
                grid=grid,
                in_specs=[
                    pl.BlockSpec(s.block_shape, _drop_scalar_arg(s.index_map))
                    for s in in_specs
                ],
                out_specs=[
                    pl.BlockSpec(s.block_shape, _drop_scalar_arg(s.index_map))
                    for s in out_specs
                ],
                scratch_shapes=scratch_shapes,
            ),
            out_shape=out_shape,
            compiler_params=compiler_params,
            interpret=interpret,
        )(off, *operands)
    else:
        res = pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=in_specs,
            out_specs=out_specs,
            out_shape=out_shape,
            scratch_shapes=scratch_shapes,
            compiler_params=compiler_params,
            interpret=interpret,
        )(*operands)

    o = res[0].reshape(b, hq, sq, d)
    if return_lse:
        return o, res[1].reshape(b, hq, sq)
    return o


def _drop_scalar_arg(index_map):
    """Index map adapted for PrefetchScalarGridSpec (which appends the
    scalar-prefetch ref as a trailing arg the plain map doesn't take)."""
    return lambda bh, qi, ki, _off: index_map(bh, qi, ki)


def _adapt_refs(kernel, has_off: bool, has_scales: bool, has_bias: bool,
                has_lse: bool, *refs):
    """Route pallas_call's positional refs into ``_attn_kernel``'s
    keyword-stable signature: optional scalar-prefetch offset first,
    optional int8 dequant scales after v, optional score bias, optional
    lse output, then the three scratch refs."""
    refs = list(refs)
    off_ref = refs.pop(0) if has_off else None
    q_ref, k_ref, v_ref = refs[:3]
    nxt = 3
    ks_ref = vs_ref = None
    if has_scales:
        ks_ref, vs_ref = refs[3:5]
        nxt = 5
    b_ref = None
    if has_bias:
        b_ref = refs[nxt]
        nxt += 1
    o_ref = refs[nxt]
    lse_ref = refs[nxt + 1] if has_lse else None
    acc, m_i, l_i = refs[-3:]
    kernel(off_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref, b_ref, o_ref,
           lse_ref, acc, m_i, l_i)


def mha_reference(
    q, k, v, *, causal=True, sm_scale=None, kv_offset: int = 0,
    return_lse: bool = False, bias=None,
):
    """Golden attention (parity: the reference's torch-SDPA goldens)."""
    b, hq, sq, d = q.shape
    _, hkv, sk, _ = k.shape
    if sm_scale is None:
        sm_scale = d**-0.5
    k = jnp.repeat(k, hq // hkv, axis=1)
    v = jnp.repeat(v, hq // hkv, axis=1)
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32))
    s *= sm_scale
    if bias is not None:
        s = s + bias.astype(jnp.float32)[None, None]
    if causal:
        rows = kv_offset + jnp.arange(sq)[:, None]
        cols = jnp.arange(sk)[None, :]
        s = jnp.where(cols <= rows, s, _NEG_INF)
    lse = jax.scipy.special.logsumexp(s, axis=-1)
    p = jnp.exp(s - lse[..., None])
    o = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)).astype(q.dtype)
    if return_lse:
        return o, lse
    return o
