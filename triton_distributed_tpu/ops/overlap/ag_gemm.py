"""AllGather + GEMM overlap — the TP prefill archetype, in ONE Pallas kernel.

Parity: reference ``kernels/nvidia/allgather_gemm.py`` —
``AllGatherGEMMTensorParallelContext``:417 (symmetric workspace + barrier
alloc), ``create_ag_gemm_context``:489, ``ag_gemm``:534, consumer GEMM
``kernel_consumer_gemm_persistent``:158 (per-tile ``dl.wait`` then
``dl.consume_token`` then ``tl.dot``).

TPU design (SURVEY.md §7 hard part "overlap without streams"): the
reference splits producer (copy-engine/NVSHMEM pushes on a comm stream)
from consumer (GEMM kernel spinning on tile barriers). TPU has no user
streams — instead ONE kernel drives both: the ICI DMA engines carry the
all-gather in the background while the MXU computes, and semaphores
sequence chunk arrival → compute, exactly replacing the reference's
tile-barrier spin loops.

Protocol per device (tp axis, n ranks, A row-sharded [m_per, K], B
column-sharded [K, n_loc]):

1. grid = (n, num_n_tiles); step s computes A-chunk ``(me + s) mod n``
   against B tiles. Starting with the own chunk means compute begins
   with zero comm latency (the reference's rank-swizzled tile order,
   ``threadblock_swizzle``, exists for the same reason).
2. At (0, 0): push own chunk to every peer's workspace slot ``me``
   (single-hop; DMA engines route + progress it concurrently with MXU
   work — the "copy-engine producer" analog).
3. At (s, 0): wait for chunk ``(me+s+1)``'s arrival semaphore and start
   its HBM→VMEM stage into the idle half of a double buffer — the wait
   only stalls if comm is slower than the previous chunk's compute.
4. Compute c[s, j] = a_vmem[s%2] @ b[j] on the MXU.

Output rows come back permuted (step-major); ``ag_gemm`` un-permutes with
a cheap gather, keeping the kernel free of data-dependent output maps.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from triton_distributed_tpu import language as dl
from triton_distributed_tpu.ops.common import (
    comm_cost,
    comm_pallas_call,
    next_collective_id,
    overlap_vmem_limit,
    pick_tile,
)
from triton_distributed_tpu.runtime.mesh import DistContext, current_context

_AG_GEMM_COLLECTIVE_ID = next_collective_id()


@dataclasses.dataclass(frozen=True)
class AGGemmConfig:
    """Tile configuration (parity: the tile fields of
    ``AllGatherGEMMTensorParallelContext``, ``allgather_gemm.py:417``).

    The reference context also owns symmetric workspace tensors; here the
    workspace is kernel-scratch HBM, allocated by Mosaic per call site, so
    the config is pure numbers. ``tile_m`` chunks the per-rank A shard's
    HBM→VMEM staging (parity: the reference's persistent M tiling,
    ``allgather_gemm.py:158``) so baseline shapes — m_per×K far beyond
    VMEM — stream instead of resident-staging.
    """

    tile_n: int = 512
    tile_m: int | None = None  # None → whole m_per (small shapes)
    acc_dtype: jnp.dtype = jnp.float32
    # Arrival-adaptive chunk scheduling (parity: the reference's
    # rank-aware tile-order swizzles, ``threadblock_swizzle_ag_moe.py``
    # / ``ag_gemm_threadblock_swizzle.py`` — compute lands on
    # already-arrived data). At each step boundary the kernel probes
    # every unprocessed chunk's arrival semaphore (non-blocking
    # ``semaphore_read``) and computes the first one that has fully
    # landed, falling back to ring order when none has. In the overlap
    # regime (per-chunk compute ≥ chunk wire time — the regime these
    # kernels are tuned for) every non-laggard chunk has landed by the
    # first boundary, so a straggler is deferred to the END of the
    # schedule and (n-2) other chunks' compute covers most of the lag.
    # Outside that regime the probe can be inconclusive and the
    # schedule degrades toward ring order (the fallback blocks on the
    # ring-next chunk, laggard or not). The realized order is emitted
    # so callers/benchmarks can observe the schedule. TPU-only: ``semaphore_read`` has no
    # interpret-mode lowering, so off-TPU the kernel keeps the static
    # ring order (same split as the LL all-gather's barrier-free mode).
    # None = auto (on real TPU), True/False = forced.
    adaptive: bool | None = None
    # Race-provocation fixtures (parity: ``for_correctness`` producer
    # sleeps, ``allgather_gemm.py:507-508``, and ``straggler_option``,
    # :534). Static: production traces carry zero overhead.
    for_correctness: bool = False
    straggler_rank: int | None = None
    straggler_nanos: int = 500_000


# Per-buffer VMEM staging budget for the A double buffer. Tiles are
# shrunk until tile_m * K * itemsize fits (each of the two buffers gets
# this much). 8 MB (tile_m=1024 at K=4096 bf16) measured best on v5e at
# north-star shapes (perf/sweep_overlap_tiles.py): larger M tiles cut
# the per-(step, tile) B re-streaming, and 1024-wide B tiles keep the
# MXU pipeline full.
_AG_STAGE_BUDGET = 8 * 1024 * 1024


def create_ag_gemm_context(
    m_per: int, n_loc: int, k: int, dtype=jnp.bfloat16, tile_n: int | None = None
) -> AGGemmConfig:
    """Pick tiles for the shapes (parity: ``create_ag_gemm_context``:489)."""
    itemsize = jnp.dtype(dtype).itemsize
    tile_m = m_per
    while tile_m > 128 and tile_m * k * itemsize > _AG_STAGE_BUDGET:
        tile_m //= 2
    while m_per % tile_m:
        tile_m //= 2
    return AGGemmConfig(
        tile_n=pick_tile(n_loc, 1024) if tile_n is None else tile_n,
        tile_m=max(tile_m, 1),
    )


def adaptive_pick(done_smem, recv_sems, chunk_bytes, me, n):
    """Arrival-adaptive chunk pick: first unprocessed chunk whose
    arrival semaphore already counts a full chunk; ring order (first
    unprocessed) when none has landed yet. The probe is non-consuming —
    the caller's blocking wait still drains the chosen chunk's
    semaphore.

    Shared by the overlap kernel and ``perf/adaptive_order_probe.py``
    (the single-chip straggler-reaction observation) so the probe
    exercises EXACTLY the production scheduler logic. Parity: the
    reference's rank-aware tile-order swizzles
    (``threadblock_swizzle_ag_moe.py``)."""
    def scan(off, carry):
        ready_pick, any_pick = carry
        c = jax.lax.rem(me + off, n)
        unproc = done_smem[c] == 0
        ready = dl.read(recv_sems.at[c]) >= chunk_bytes
        any_pick = jnp.where(
            jnp.logical_and(any_pick < 0, unproc), c, any_pick
        )
        ready_pick = jnp.where(
            jnp.logical_and(
                ready_pick < 0, jnp.logical_and(unproc, ready)
            ),
            c,
            ready_pick,
        )
        return ready_pick, any_pick

    ready_pick, any_pick = jax.lax.fori_loop(
        1, n, scan, (jnp.int32(-1), jnp.int32(-1))
    )
    return jnp.where(ready_pick >= 0, ready_pick, any_pick)


def _ag_gemm_kernel(
    a_ref,      # [m_per, K] ANY/HBM — this device's A shard
    b_ref,      # [K, tile_n] VMEM — B tile j (pipelined by BlockSpec)
    c_ref,      # [1, tile_m, tile_n] VMEM — output tile (s, i, j)
    ws,         # [n, m_per, K] ANY/HBM output — gathered A chunks
                # (a workspace; Mosaic only allows VMEM/SMEM/semaphore
                # scratch, so HBM workspaces are extra outputs)
    order_ref,  # [n] SMEM int32 output — chunk processed at each step
    a_vmem,     # [2, tile_m, K] VMEM — double-buffered compute M-tile
    load_sems,  # DMA (2,) — HBM→VMEM stage
    send_sems,  # DMA (n-1,)
    recv_sems,  # DMA (n,) — slot r signaled when chunk r lands
    done_smem,  # [n] SMEM int32 scratch — processed bitmask
    *,
    axis: str,
    acc_dtype,
    adaptive: bool = False,
    for_correctness: bool = False,
    straggler_rank: int | None = None,
    straggler_nanos: int = 0,
):
    me = dl.rank(axis)
    n = dl.num_ranks(axis)
    s = pl.program_id(0)
    i = pl.program_id(1)
    j = pl.program_id(2)
    num_i = pl.num_programs(1)
    num_j = pl.num_programs(2)
    tile_m = a_vmem.shape[1]
    chunk_bytes = ws.shape[1] * ws.shape[2] * jnp.dtype(ws.dtype).itemsize

    def rows(ti):
        return pl.ds(ti * tile_m, tile_m)

    def buf(step, ti):
        return jax.lax.rem(step * num_i + ti, 2)

    def stage(step, ti, chunk=None):
        """HBM→VMEM stage of chunk's M-tile ``ti`` (own shard at step 0)."""
        b = buf(step, ti)
        if chunk is None:  # step 0: own chunk, straight from a_ref
            src = a_ref.at[rows(ti)]
        else:
            src = ws.at[chunk, rows(ti)]
        return pltpu.make_async_copy(src, a_vmem.at[b], load_sems.at[b])

    @pl.when(jnp.logical_and(s == 0, jnp.logical_and(i == 0, j == 0)))
    def _start():
        # Stage own first tile for immediate compute (overlaps barrier).
        stage(0, 0).start()
        # Schedule state: own chunk is step 0 (zero-latency start — the
        # same reason as the reference's rank-swizzled tile order).
        def init(c, carry):
            done_smem[c] = jnp.where(c == me, 1, 0)
            return carry

        jax.lax.fori_loop(0, n, init, None)
        order_ref[0] = me
        # Entry barrier: peers' ws outputs must be allocated before any
        # remote write lands.
        dl.barrier_all(axis)
        # Race fixtures: lag this rank's pushes so any consumer missing a
        # wait reads stale workspace (reference for_correctness sleep /
        # straggler injection).
        dl.straggle_if_rank(straggler_rank, axis, straggler_nanos)
        if for_correctness:
            dl.maybe_delay(200_000)
        # Push own chunk (whole shard, HBM→HBM over ICI) to every peer
        # (slot index = source rank, so consumers wait per-chunk).
        for p in range(1, n):
            peer = jax.lax.rem(me + p, n)
            dl.put_signal(
                a_ref, ws.at[me], peer,
                send_sems.at[p - 1], recv_sems.at[me], axis=axis,
            )
        stage(0, 0).wait()

    @pl.when(jnp.logical_and(s + i > 0, j == 0))
    def _land_current():
        # VMEM stage for (s, i) was started at the previous tile's last j.
        b = buf(s, i)
        pltpu.make_async_copy(
            a_vmem.at[b], a_vmem.at[b], load_sems.at[b]
        ).wait()

    c_ref[0] = jnp.dot(
        a_vmem[buf(s, i)], b_ref[:], preferred_element_type=acc_dtype
    ).astype(c_ref.dtype)

    @pl.when(jnp.logical_and(i + 1 < num_i, j == num_j - 1))
    def _prefetch_same_chunk():
        # Next M-tile of the current chunk — already resident in HBM.
        @pl.when(s == 0)
        def _():
            stage(s, i + 1).start()

        @pl.when(s > 0)
        def _():
            stage(s, i + 1, chunk=order_ref[s]).start()

    # n == 1: the next-chunk block is unreachable (s+1 < n never holds),
    # but Mosaic still compiles the body — where the arrival scan
    # constant-folds to a -1 semaphore index and trips a lowering check
    # (`d >> 32 == 0` seen on-chip). Don't emit it at all.
    @pl.when(
        jnp.logical_and(
            i == num_i - 1, jnp.logical_and(s + 1 < n, j == num_j - 1)
        )
    )
    def _prefetch_next_chunk():
        if n == 1:
            return
        # Arrival fence + first-tile stage for the next chunk, placed
        # after this step's last tile is issued so the blocking wait sits
        # at the end of the step's compute, not ahead of it (keeps the
        # MXU busy while the ICI push is in flight).
        if adaptive:
            nxt = adaptive_pick(done_smem, recv_sems, chunk_bytes, me, n)
        else:
            nxt = jax.lax.rem(me + s + 1, n)
        done_smem[nxt] = 1
        order_ref[s + 1] = nxt
        dl.wait_recv(recv_sems.at[nxt], ws.at[nxt])
        stage(s + 1, 0, chunk=nxt).start()

    @pl.when(
        jnp.logical_and(
            s == n - 1, jnp.logical_and(i == num_i - 1, j == num_j - 1)
        )
    )
    def _drain():
        for p in range(1, n):
            pltpu.make_async_copy(a_ref, a_ref, send_sems.at[p - 1]).wait()


def ag_gemm(
    a: jax.Array,
    b: jax.Array,
    axis: str = "tp",
    config: AGGemmConfig | None = None,
    ctx: DistContext | None = None,
) -> jax.Array:
    """Overlapped ``all_gather(a) @ b`` inside ``shard_map``.

    ``a``: ``[m_per, K]`` row shard; ``b``: ``[K, n_loc]`` column shard.
    Returns ``[n * m_per, n_loc]`` (full rows, local columns) — same
    contract as reference ``ag_gemm`` (``allgather_gemm.py:534``).
    """
    n = jax.lax.axis_size(axis)
    m_per, k = a.shape
    k2, n_loc = b.shape
    if k != k2:
        raise ValueError(f"K mismatch {a.shape} @ {b.shape}")
    config = config or create_ag_gemm_context(m_per, n_loc, k, a.dtype)
    tile_n = min(config.tile_n, n_loc)
    if n_loc % tile_n:
        raise ValueError(f"n_loc={n_loc} not divisible by tile_n={tile_n}")
    num_j = n_loc // tile_n
    tile_m = min(config.tile_m or m_per, m_per)
    if m_per % tile_m:
        raise ValueError(f"m_per={m_per} not divisible by tile_m={tile_m}")
    num_i = m_per // tile_m

    adaptive = config.adaptive
    if adaptive is None:
        from triton_distributed_tpu.ops.common import _on_tpu

        # semaphore_read (the non-blocking arrival probe) has no
        # interpret-mode lowering; off-TPU the kernel keeps ring order.
        adaptive = _on_tpu(ctx)

    grid = (n, num_i, num_j)
    out, _ws, order = comm_pallas_call(
        functools.partial(
            _ag_gemm_kernel, axis=axis, acc_dtype=config.acc_dtype,
            adaptive=adaptive,
            for_correctness=config.for_correctness,
            straggler_rank=config.straggler_rank,
            straggler_nanos=config.straggler_nanos,
        ),
        (
            jax.ShapeDtypeStruct((n, m_per, n_loc), a.dtype),
            jax.ShapeDtypeStruct((n, m_per, k), a.dtype),
            jax.ShapeDtypeStruct((n,), jnp.int32),
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),  # a: manual DMA
            pl.BlockSpec(
                (k, tile_n), lambda s, i, j: (0, j), memory_space=pltpu.VMEM
            ),
        ],
        out_specs=(
            pl.BlockSpec(
                (1, tile_m, tile_n),
                lambda s, i, j: (s, i, j),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ),
        scratch_shapes=[
            pltpu.VMEM((2, tile_m, k), a.dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((max(n - 1, 1),)),
            pltpu.SemaphoreType.DMA((n,)),
            pltpu.SMEM((max(n, 1),), jnp.int32),
        ],
        collective_id=_AG_GEMM_COLLECTIVE_ID,
        # Mosaic double-buffers the BlockSpec-pipelined operands; at
        # north-star shapes that exceeds the 16 MB default scoped-VMEM
        # limit (v5e/v5p have 128 MB physical). Large-tile configs (the
        # sweep-tuned defaults) need headroom above 64 MB.
        vmem_limit_bytes=overlap_vmem_limit(
            tile_m, k, tile_n, a.dtype.itemsize, out_tile_bufs=1
        ),
        dimension_semantics=("arbitrary", "arbitrary", "arbitrary"),
        cost_estimate=comm_cost(
            flops=2 * n * m_per * k * n_loc,
            # A streamed in + pushed around the ring, B read per step,
            # gathered A and the output written once.
            bytes_accessed=(2 * n * a.size + n * b.size + n * a.size
                            + n * m_per * n_loc) * a.dtype.itemsize,
        ),
        ctx=ctx,
    )(a, b)

    # The kernel emits the realized schedule (order[s] = chunk computed
    # at step s — ring order, or arrival order when adaptive). Global
    # row-chunk r sits at the step where order[step] == r; argsort of a
    # permutation inverts it. One gather puts rows in global order.
    return out[jnp.argsort(order)].reshape(n * m_per, n_loc)


def ag_gemm_op(
    a: jax.Array,
    b: jax.Array,
    axis: str = "tp",
    config: AGGemmConfig | None = None,
    ctx: DistContext | None = None,
) -> jax.Array:
    """Host-level wrapper: ``a`` row-sharded over ``axis``, ``b``
    column-sharded; returns C with columns sharded (host shape [M, N])."""
    ctx = ctx or current_context()
    f = ctx.shard_map(
        functools.partial(ag_gemm, axis=axis, config=config, ctx=ctx),
        in_specs=(P(axis, None), P(None, axis)),
        out_specs=P(None, axis),
    )
    return f(a, b)
