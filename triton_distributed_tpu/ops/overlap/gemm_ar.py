"""GEMM + AllReduce overlap — the TP decode-latency archetype.

Parity: reference ``kernels/nvidia/gemm_allreduce.py`` —
``GemmARContext``/``LLGemmARContext``:48/74, persistent GEMM-with-notify
:329/389, ``consumer_all_reduce_kernel``:124, fused one-kernel variant
:233, ops :509/546 — whose role is the row-parallel o-proj/fc2 GEMM of a
TP decode step where the partial products must be summed across ranks
and *every* rank needs the full result.

TPU design, two methods (mirroring the reference's LL one-shot vs
two-shot split):

- ``ONE_SHOT``: one fused Pallas kernel. The GEMM is tiled over N; as
  each output tile comes off the MXU it is broadcast to every peer's
  arrival slot with ``put_signal`` while the MXU moves on to the next
  tile (comm of tile j hides under compute of tile j+1 — the same
  per-tile notify pipelining as the reference's persistent GEMM
  producer). A second grid phase waits per-(peer, tile) arrival
  semaphores and reduces the n partials locally. Latency-optimal for
  decode shapes (small M·N): every payload crosses the ICI once.
- ``TWO_SHOT``: composition of the overlapped ring ``gemm_rs`` kernel
  (GEMM hidden under ring reduce-scatter) with a bidirectional-ring
  all-gather — bandwidth-optimal for prefill shapes, the same
  RS-then-AG structure XLA uses for large psums.
"""

from __future__ import annotations

import dataclasses
import enum
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from triton_distributed_tpu import language as dl
from triton_distributed_tpu.ops.common import (
    device_initiable,
    VMEM_COMM_MAX_BYTES,
    comm_cost,
    comm_pallas_call,
    next_collective_id,
    pick_tile,
)
from triton_distributed_tpu.ops.collectives.all_gather import (
    AllGatherMethod,
    all_gather,
)
from triton_distributed_tpu.ops.overlap.gemm_rs import GemmRSConfig, gemm_rs
from triton_distributed_tpu.runtime.mesh import DistContext, current_context

_GEMM_AR_COLLECTIVE_ID = next_collective_id()

# Above this full-output size the one-shot kernel's n-copy arrival
# buffer stops paying for its single-hop latency win (parity: the
# size-based LL/two-shot dispatch in ``gemm_allreduce.py:509-546``).
_ONE_SHOT_MAX_BYTES = 512 * 1024


class GemmARMethod(enum.Enum):
    AUTO = "auto"
    XLA = "xla"  # psum(a @ b) — XLA's own overlap scheduling
    ONE_SHOT = "one_shot"  # fused per-tile broadcast + local reduce
    TWO_SHOT = "two_shot"  # overlapped gemm_rs ring + ring all-gather


@dataclasses.dataclass(frozen=True)
class GemmARConfig:
    """Parity: tile fields of ``GemmARContext`` (``gemm_allreduce.py:48``)."""

    tile_n: int = 512
    acc_dtype: jnp.dtype = jnp.float32


def create_gemm_ar_context(
    m: int, n_out: int, k_loc: int, dtype=jnp.bfloat16, tile_n: int | None = None
) -> GemmARConfig:
    return GemmARConfig(tile_n=pick_tile(n_out) if tile_n is None else tile_n)


def _gemm_ar_one_shot_kernel(
    a_ref,      # [M, k_loc] VMEM — this device's K shard of A (resident)
    b_ref,      # [k_loc, tile_n] VMEM — B tile min(s, num_j-1)
    o_ref,      # [M, tile_n] VMEM — reduced output tile max(s-1, 0)
    ws,         # [n, M, N] ANY/HBM output — slot p holds peer p's partial
    *rest,      # [tr (SMEM ring, trace only)], sbuf, vbuf, sems, [clk]
    axis: str,
    acc_dtype,
    trace: bool = False,
):
    if trace:
        tr, sbuf, vbuf, stage_sem, send_sems, recv_sems, clk = rest
    else:
        tr = clk = None
        sbuf, vbuf, stage_sem, send_sems, recv_sems = rest
    me = dl.rank(axis)
    n = dl.num_ranks(axis)
    s = pl.program_id(0)
    num_j = pl.num_programs(0) - 1

    # Device task-tracer seam (docs/observability.md "Device task
    # tracer"): the standalone overlap kernel records the SAME ring
    # format as the megakernel — produce phases as AR_SEND rows (mid =
    # puts in flight), reduce phases as AR_WAIT rows (mid = partials
    # landed), the drain as a BARRIER row — decoded by the one
    # obs/kernel_trace.py decoder (strict=False: iterations only run
    # the phases their grid position owns). Phase rows sit in
    # EXECUTION order (0 produce, 1 reduce, 2 drain — the order the
    # pl.when blocks run within an iteration), so the decoder's
    # per-step clock-monotonicity check holds on real rings.
    def tick():
        c = clk[0] + 1
        clk[0] = c
        return c

    def record(phase, opcode, slot, begin, end, mid):
        tr[s, phase, 0] = s          # task_id = grid iteration
        tr[s, phase, 1] = opcode
        tr[s, phase, 2] = 0          # layer
        tr[s, phase, 3] = slot       # tile index
        tr[s, phase, 4] = begin
        tr[s, phase, 5] = end
        tr[s, phase, 6] = mid
        tr[s, phase, 7] = 1

    @pl.when(s == 0)
    def _entry():
        # Peers' ws slots must exist before the first remote put lands.
        if trace:
            clk[0] = 0
        dl.barrier_all(axis)

    @pl.when(s < num_j)
    def _produce():
        # Partial tile s off the MXU → local slot (HBM) → broadcast. The
        # remote puts are non-blocking: tile s's n-1 sends drain while
        # tile s-1 is being reduced and tile s+1 is on the MXU (per-tile
        # notify pipelining, as the reference's producer GEMM does with
        # its tile barriers).
        begin = tick() if trace else None
        tile_n = b_ref.shape[1]
        jsl = pl.ds(s * tile_n, tile_n)
        sbuf[:] = jnp.dot(
            a_ref[:], b_ref[:], preferred_element_type=acc_dtype
        ).astype(sbuf.dtype)
        dma = dl.local_copy(sbuf, ws.at[me].at[:, jsl], stage_sem)
        dma.start()
        dma.wait()
        for i in range(1, n):
            peer = jax.lax.rem(me + i, n)
            dl.put_signal(
                ws.at[me].at[:, jsl], ws.at[me].at[:, jsl], peer,
                send_sems.at[i - 1], recv_sems.at[me, s], axis=axis,
            )
        if trace:
            mid = tick()  # puts in flight
            record(0, 12, s, begin, tick(), mid)  # TaskType.AR_SEND

    @pl.when(s > 0)
    def _reduce():
        # Reduce tile s-1: wait its n-1 inbound partials (per-(src, tile)
        # semaphores — the analog of the reference consumer's per-tile
        # ``dl.wait`` + ``consume_token``), stage, sum locally.
        begin = tick() if trace else None
        tile_n = o_ref.shape[1]
        j = s - 1
        jsl = pl.ds(j * tile_n, tile_n)
        for i in range(1, n):
            src = jax.lax.rem(me + i, n)
            dl.wait_recv(recv_sems.at[src, j], ws.at[src].at[:, jsl])
        if trace:
            mid = tick()  # partials landed; the rest is the local fold
        dma = dl.local_copy(ws.at[:, :, jsl], vbuf, stage_sem)
        dma.start()
        dma.wait()
        acc = vbuf[0].astype(acc_dtype)
        for i in range(1, n):
            acc = acc + vbuf[i].astype(acc_dtype)
        o_ref[:] = acc.astype(o_ref.dtype)
        if trace:
            record(1, 13, j, begin, tick(), mid)  # TaskType.AR_WAIT

    @pl.when(s == num_j)
    def _drain():
        # All num_j tiles were sent to each peer: [M, N] bytes per peer.
        begin = tick() if trace else None
        for i in range(1, n):
            pltpu.make_async_copy(
                ws.at[me], ws.at[me], send_sems.at[i - 1]
            ).wait()
        if trace:
            # Phase row 2: the drain runs AFTER this iteration's
            # reduce — its row index must follow reduce's or the
            # decoder's monotonicity check would misfire.
            record(2, 9, 0, begin, tick(), 0)  # TaskType.BARRIER


def gemm_ar(
    a: jax.Array,
    b: jax.Array,
    axis: str = "tp",
    method: GemmARMethod = GemmARMethod.AUTO,
    config: GemmARConfig | None = None,
    ctx: DistContext | None = None,
    trace: bool = False,
) -> jax.Array:
    """Overlapped ``psum(a @ b)`` inside ``shard_map``.

    ``a``: ``[M, k_loc]`` column shard; ``b``: ``[k_loc, N]`` row shard.
    Every device returns the full reduced ``[M, N]`` — same contract as
    reference ``gemm_allreduce_op`` (``gemm_allreduce.py:509``).

    ``trace=True`` (ONE_SHOT only) additionally returns this shard's
    device task ring ``[num_j+1, 3, 8]`` int32 — produce/reduce/drain
    phase rows IN EXECUTION ORDER per grid iteration (produce < reduce
    < drain, so ``validate_ring``'s per-step monotonicity holds), in
    the megakernel tracer's format, decoded by
    ``obs.kernel_trace.decode_trace(..., strict=False)`` — iterations
    only write the phases their grid position owns
    (docs/observability.md "Device task tracer"). Note the decoder's
    ``overlap_report`` windows pair AR_SEND/AR_WAIT within one step:
    this kernel's send (tile j, iteration j) and its wait (iteration
    j+1) land in different steps — reshape the ring to one step
    (``ring.reshape(ranks, 1, -1, 8)``) to pair them.
    """
    n = jax.lax.axis_size(axis)
    m, k_loc = a.shape
    _, n_out = b.shape
    config = config or create_gemm_ar_context(m, n_out, k_loc, a.dtype)
    if trace and method is not GemmARMethod.ONE_SHOT:
        raise ValueError(
            "trace=True requires method=ONE_SHOT (the ring rides the "
            "fused kernel; XLA/TWO_SHOT paths have no device ring)"
        )

    if n == 1:
        out = jnp.dot(
            a, b, preferred_element_type=config.acc_dtype
        ).astype(a.dtype)
        if trace:
            # No fused kernel ran (single rank: nothing to overlap) —
            # keep the documented (out, ring) arity with an all-zero
            # (= all-unwritten) ring so strict=False decodes to [].
            tile_n = min(config.tile_n, n_out)
            num_j = n_out // max(tile_n, 1)
            return out, jnp.zeros((num_j + 1, 3, 8), jnp.int32)
        return out

    out_bytes = m * n_out * a.dtype.itemsize
    if method == GemmARMethod.AUTO:
        if not device_initiable(axis, ctx):
            method = GemmARMethod.XLA
        elif out_bytes <= _ONE_SHOT_MAX_BYTES:
            method = GemmARMethod.ONE_SHOT
        elif m % n == 0 and out_bytes <= VMEM_COMM_MAX_BYTES:
            # The trailing ring all-gather holds the full [M, N] in VMEM.
            method = GemmARMethod.TWO_SHOT
        else:
            method = GemmARMethod.XLA

    if method == GemmARMethod.XLA:
        return jax.lax.psum(
            jnp.dot(a, b, preferred_element_type=config.acc_dtype).astype(a.dtype),
            axis,
        )

    if method == GemmARMethod.TWO_SHOT:
        reduced = gemm_rs(
            a, b, axis=axis,
            config=GemmRSConfig(
                tile_n=config.tile_n, acc_dtype=config.acc_dtype
            ),
            ctx=ctx,
        )
        # AUTO applies the VMEM-size / on-TPU guards inside all_gather.
        return all_gather(reduced, axis, AllGatherMethod.AUTO, ctx)

    # ONE_SHOT
    tile_n = min(config.tile_n, n_out)
    if n_out % tile_n:
        raise ValueError(f"n_out={n_out} not divisible by tile_n={tile_n}")
    num_j = n_out // tile_n

    outs = comm_pallas_call(
        functools.partial(
            _gemm_ar_one_shot_kernel, axis=axis,
            acc_dtype=config.acc_dtype, trace=trace,
        ),
        (
            jax.ShapeDtypeStruct((m, n_out), a.dtype),
            jax.ShapeDtypeStruct((n, m, n_out), a.dtype),
        ) + ((
            # Device task ring: [grid, phase, TRACE_INTS] — phases in
            # execution order (0 produce, 1 reduce, 2 drain); not every
            # iteration runs every phase, so the decoder skips
            # unwritten rows with strict=False.
            jax.ShapeDtypeStruct((num_j + 1, 3, 8), jnp.int32),
        ) if trace else ()),
        grid=(num_j + 1,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(
                (k_loc, tile_n),
                lambda s: (0, jnp.minimum(s, num_j - 1)),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=(
            pl.BlockSpec(
                (m, tile_n),
                lambda s: (0, jnp.maximum(s - 1, 0)),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(memory_space=pl.ANY),
        ) + ((pl.BlockSpec(memory_space=pltpu.SMEM),) if trace else ()),
        scratch_shapes=[
            pltpu.VMEM((m, tile_n), a.dtype),
            pltpu.VMEM((n, m, tile_n), a.dtype),
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA((n - 1,)),
            pltpu.SemaphoreType.DMA((n, num_j)),
        ] + ([pltpu.SMEM((1,), jnp.int32)] if trace else []),
        collective_id=_GEMM_AR_COLLECTIVE_ID,
        # Mosaic double-buffers the BlockSpec-pipelined operands; at
        # north-star shapes that exceeds the 16 MB default scoped-VMEM
        # limit (v5e/v5p have 128 MB physical).
        vmem_limit_bytes=64 * 1024 * 1024,
        dimension_semantics=("arbitrary",),
        cost_estimate=comm_cost(
            flops=2 * m * k_loc * n_out,
            # A + B read, partials broadcast to n peers, n landed
            # partials re-read for the reduction, output written.
            bytes_accessed=(a.size + b.size
                            + 2 * n * m * n_out + m * n_out)
            * a.dtype.itemsize,
        ),
        ctx=ctx,
    )(a, b)
    if trace:
        return outs[0], outs[2]
    return outs[0]


def gemm_ar_op(
    a: jax.Array,
    b: jax.Array,
    axis: str = "tp",
    method: GemmARMethod = GemmARMethod.AUTO,
    config: GemmARConfig | None = None,
    ctx: DistContext | None = None,
    trace: bool = False,
) -> jax.Array:
    """Host-level wrapper: ``a [M, K]`` column-sharded over ``axis``,
    ``b [K, N]`` row-sharded; returns the full ``[M, N]`` (replicated) —
    the summed GEMM on every device. ``trace=True`` (ONE_SHOT only)
    returns ``(out, ring [n_ranks, num_j+1, 3, 8])`` — the per-rank
    device task rings (docs/observability.md "Device task tracer")."""
    ctx = ctx or current_context()
    if trace:
        def shard(a_, b_):
            out, ring = gemm_ar(
                a_, b_, axis=axis, method=method, config=config,
                ctx=ctx, trace=True,
            )
            return out, ring[None]

        f = ctx.shard_map(
            shard,
            in_specs=(P(None, axis), P(axis, None)),
            out_specs=(P(None, None), P(axis)),
        )
        return f(a, b)
    f = ctx.shard_map(
        functools.partial(gemm_ar, axis=axis, method=method, config=config, ctx=ctx),
        in_specs=(P(None, axis), P(axis, None)),
        out_specs=P(None, None),
    )
    return f(a, b)
