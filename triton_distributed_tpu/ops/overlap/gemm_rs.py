"""GEMM + ReduceScatter overlap — the TP output-projection archetype.

Parity: reference ``kernels/nvidia/gemm_reduce_scatter.py`` —
``GEMMReduceScatterTensorParallelContext``:42, producer GEMM with
per-tile notify :122-413, ``gemm_rs_op``:508, ``gemm_rs``:569 — plus the
ring-reduce consumer from ``reduce_scatter.py:674-744``.

TPU design: one kernel fuses producer and consumer. Row-parallel GEMM
(``a [M, k_loc] @ b [k_loc, N]`` giving partial C) is computed chunk by
chunk in *ring-reduce order*: at step s the device computes its partial
for destination chunk ``(me-1-s) mod n``, adds the accumulated partial
arriving from its left neighbor, and forwards the sum right — so each
row chunk circulates once around the ring, gathering every device's
contribution, while the MXU stays busy producing the next chunk. The
final step's chunk is the device's own output. Per-step receive slots in
HBM make the protocol flow-control-free (slot s is written exactly once,
by the left neighbor's step s-1).
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from triton_distributed_tpu import language as dl
from triton_distributed_tpu.ops.common import (
    comm_pallas_call,
    next_collective_id,
    pick_tile,
)
from triton_distributed_tpu.runtime.mesh import DistContext, current_context

_GEMM_RS_COLLECTIVE_ID = next_collective_id()


@dataclasses.dataclass(frozen=True)
class GemmRSConfig:
    """Parity: tile fields of ``GEMMReduceScatterTensorParallelContext``."""

    tile_n: int = 512
    acc_dtype: jnp.dtype = jnp.float32


def create_gemm_rs_context(
    m: int, n_out: int, k_loc: int, dtype=jnp.bfloat16, tile_n: int | None = None
) -> GemmRSConfig:
    return GemmRSConfig(tile_n=pick_tile(n_out) if tile_n is None else tile_n)


def _gemm_rs_kernel(
    a_ref,      # [M, k_loc] ANY/HBM — this device's column shard of A
    b_ref,      # [k_loc, tile_n] VMEM — B tile j
    o_ref,      # [m_per, N] ANY/HBM — final reduced chunk (written once)
    ws,         # [n-1, m_per, N] ANY/HBM output — per-step inbound slots
                # (workspace-as-output; Mosaic forbids HBM scratch)
    a_vmem,     # [2, m_per, k_loc] VMEM — A chunk double buffer
    acc,        # [2, m_per, N] VMEM — outbound accumulated partial
    inbound,    # [m_per, N] VMEM — staged inbound partial
    load_sems,  # DMA (2,)
    stage_sem,  # DMA ()
    send_sems,  # DMA (n-1,)
    recv_sems,  # DMA (n-1,)
    *,
    axis: str,
    acc_dtype,
):
    me = dl.rank(axis)
    n = dl.num_ranks(axis)
    s = pl.program_id(0)
    j = pl.program_id(1)
    num_j = pl.num_programs(1)
    m_per = o_ref.shape[0]
    tile_n = b_ref.shape[1]
    right = jax.lax.rem(me + 1, n)

    def chunk_rows(c):
        return pl.ds(c * m_per, m_per)

    def a_chunk(step):
        return jax.lax.rem(me - 1 - step + 2 * n, n)

    @pl.when(jnp.logical_and(s == 0, j == 0))
    def _start():
        # Entry barrier: the first remote put (end of step 0) targets the
        # right neighbor's ws output, which must already be allocated.
        dl.barrier_all(axis)
        dma = pltpu.make_async_copy(
            a_ref.at[chunk_rows(a_chunk(0))], a_vmem.at[0], load_sems.at[0]
        )
        dma.start()
        dma.wait()

    @pl.when(jnp.logical_and(s + 1 < n, j == 0))
    def _prefetch_next_a():
        pltpu.make_async_copy(
            a_ref.at[chunk_rows(a_chunk(s + 1))],
            a_vmem.at[(s + 1) % 2],
            load_sems.at[(s + 1) % 2],
        ).start()

    @pl.when(jnp.logical_and(s > 0, j == 0))
    def _land():
        # A chunk staged during the previous step.
        pltpu.make_async_copy(
            a_ref.at[chunk_rows(0)], a_vmem.at[s % 2], load_sems.at[s % 2]
        ).wait()
        # Inbound accumulated partial for this step's chunk (left's step s-1).
        dl.wait_recv(recv_sems.at[s - 1], ws.at[s - 1])
        dma = pltpu.make_async_copy(ws.at[s - 1], inbound, stage_sem)
        dma.start()
        dma.wait()
        # Before reusing acc slot s%2 (last used at step s-2), drain its send.
        @pl.when(s >= 2)
        def _():
            pltpu.make_async_copy(
                acc.at[s % 2], acc.at[s % 2], send_sems.at[s - 2]
            ).wait()

    partial = jnp.dot(
        a_vmem[s % 2], b_ref[:], preferred_element_type=acc_dtype
    )

    jsl = pl.ds(j * tile_n, tile_n)

    @pl.when(s == 0)
    def _first_step():
        acc[0, :, jsl] = partial.astype(acc.dtype)

    @pl.when(s > 0)
    def _accumulate():
        acc[s % 2, :, jsl] = (
            partial + inbound[:, jsl].astype(acc_dtype)
        ).astype(acc.dtype)

    @pl.when(jnp.logical_and(s < n - 1, j == num_j - 1))
    def _forward():
        # Receiver consumes this at its step s+1 from slot s.
        dl.put_signal(
            acc.at[s % 2], ws.at[s], right,
            send_sems.at[s], recv_sems.at[s], axis=axis,
        )

    @pl.when(jnp.logical_and(s == n - 1, j == num_j - 1))
    def _finish():
        # Write the final chunk out in one DMA (o_ref lives in HBM; its
        # block is never revisited across grid steps).
        dma = pltpu.make_async_copy(acc.at[(n - 1) % 2], o_ref, stage_sem)
        dma.start()
        dma.wait()
        # Steps 0..n-3 were drained on acc-slot reuse; only n-2 remains.
        step = n - 2
        pltpu.make_async_copy(
            acc.at[step % 2], acc.at[step % 2], send_sems.at[step]
        ).wait()


def gemm_rs(
    a: jax.Array,
    b: jax.Array,
    axis: str = "tp",
    config: GemmRSConfig | None = None,
    ctx: DistContext | None = None,
) -> jax.Array:
    """Overlapped ``reduce_scatter(a @ b)`` inside ``shard_map``.

    ``a``: ``[M, k_loc]`` column shard; ``b``: ``[k_loc, N]`` row shard.
    Returns this device's reduced row chunk ``[M/n, N]`` — same contract
    as reference ``gemm_rs`` (``gemm_reduce_scatter.py:569``).
    """
    n = jax.lax.axis_size(axis)
    m, k_loc = a.shape
    _, n_out = b.shape
    if m % n:
        raise ValueError(f"M={m} not divisible by axis size {n}")
    m_per = m // n
    config = config or create_gemm_rs_context(m, n_out, k_loc, a.dtype)
    tile_n = min(config.tile_n, n_out)
    if n_out % tile_n:
        raise ValueError(f"n_out={n_out} not divisible by tile_n={tile_n}")
    num_j = n_out // tile_n

    if n == 1:
        return jnp.dot(a, b, preferred_element_type=config.acc_dtype).astype(a.dtype)

    out, _ws = comm_pallas_call(
        functools.partial(_gemm_rs_kernel, axis=axis, acc_dtype=config.acc_dtype),
        (
            jax.ShapeDtypeStruct((m_per, n_out), a.dtype),
            jax.ShapeDtypeStruct((n - 1, m_per, n_out), a.dtype),
        ),
        grid=(n, num_j),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(
                (k_loc, tile_n), lambda s, j: (0, j), memory_space=pltpu.VMEM
            ),
        ],
        out_specs=(
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ),
        scratch_shapes=[
            pltpu.VMEM((2, m_per, k_loc), a.dtype),
            pltpu.VMEM((2, m_per, n_out), a.dtype),
            pltpu.VMEM((m_per, n_out), a.dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA((n - 1,)),
            pltpu.SemaphoreType.DMA((n - 1,)),
        ],
        collective_id=_GEMM_RS_COLLECTIVE_ID,
        dimension_semantics=("arbitrary", "arbitrary"),
        ctx=ctx,
    )(a, b)
    return out


def gemm_rs_op(
    a: jax.Array,
    b: jax.Array,
    axis: str = "tp",
    config: GemmRSConfig | None = None,
    ctx: DistContext | None = None,
) -> jax.Array:
    """Host-level wrapper: ``a [M, K]`` column-sharded over ``axis``,
    ``b [K, N]`` row-sharded; returns ``[M, N]`` row-sharded (the summed
    GEMM, scattered)."""
    ctx = ctx or current_context()
    f = ctx.shard_map(
        functools.partial(gemm_rs, axis=axis, config=config, ctx=ctx),
        in_specs=(P(None, axis), P(axis, None)),
        out_specs=P(axis, None),
    )
    return f(a, b)
