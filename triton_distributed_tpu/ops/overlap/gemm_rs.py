"""GEMM + ReduceScatter overlap — the TP output-projection archetype.

Parity: reference ``kernels/nvidia/gemm_reduce_scatter.py`` —
``GEMMReduceScatterTensorParallelContext``:42, producer GEMM with
per-tile notify :122-413, ``gemm_rs_op``:508, ``gemm_rs``:569 — plus the
ring-reduce consumer from ``reduce_scatter.py:674-744``.

TPU design: one kernel fuses producer and consumer. Row-parallel GEMM
(``a [M, k_loc] @ b [k_loc, N]`` giving partial C) is computed chunk by
chunk in *ring-reduce order*: at step s the device computes its partial
for destination chunk ``(me-1-s) mod n``, adds the accumulated partial
arriving from its left neighbor, and forwards the sum right — so each
row chunk circulates once around the ring, gathering every device's
contribution, while the MXU stays busy producing the next chunk. The
final step's chunk is the device's own output. Per-step receive slots in
HBM make the protocol flow-control-free (slot s is written exactly once,
by the left neighbor's step s-1).

Scale: the accumulated partial lives in HBM (``accbuf``), streamed
through VMEM in (tile_m × tile_n) tiles (parity: the reference's
persistent M tiling, ``gemm_reduce_scatter.py:122``) — baseline shapes
(m_per × N ≫ VMEM) never resident-stage.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from triton_distributed_tpu import language as dl
from triton_distributed_tpu.ops.common import (
    comm_cost,
    comm_pallas_call,
    next_collective_id,
    overlap_vmem_limit,
    pick_tile,
)
from triton_distributed_tpu.runtime.mesh import DistContext, current_context

_GEMM_RS_COLLECTIVE_ID = next_collective_id()


@dataclasses.dataclass(frozen=True)
class GemmRSConfig:
    """Parity: tile fields of ``GEMMReduceScatterTensorParallelContext``.

    ``bidir``: split each circulating chunk's rows in half and run TWO
    counter-rotating rings (top half clockwise, bottom half counter-
    clockwise) — both directions of the ICI torus axis carry payload,
    2x wire bandwidth in the comm-bound regime (the same lever as the
    bidirectional all-gather; the reference's analog is its NUMA-split
    dual rings, ``reduce_scatter.py:285``). Requires an even number of
    row tiles; auto-falls back to the single ring otherwise.

    ``wire_dtype``: dtype of the RING HOP payload only (local
    accumulation stays ``acc_dtype``; the final output stays the input
    dtype). Default None = input dtype — for bf16 inputs that is
    already the reference's reduce-in-output-dtype scheme
    (``kernel_ring_reduce_tma``, ``reduce_scatter.py:674-744``): one
    bf16 rounding per hop. ``jnp.float8_e4m3fn`` halves wire bytes
    again. Error model (documented, tested): each hop rounds the
    accumulated partial to e4m3 (~2^-4 relative half-ulp), so a chunk
    crossing h hops carries ~sqrt(h)·2^-4 RMS relative error on the
    PARTIAL-SUM magnitude — safe when partials don't catastrophically
    cancel (inference activations); not for gradients. e4m3's ±448
    dynamic range is the caller's responsibility (pre-scaled
    activations); overflow saturates to ±448 rather than inf.
    """

    tile_n: int = 512
    tile_m: int | None = None  # None → whole m_per (small shapes)
    acc_dtype: jnp.dtype = jnp.float32
    bidir: bool = True
    wire_dtype: jnp.dtype | None = None
    # n=1 normally short-circuits to a plain XLA dot; the tile sweep
    # (perf/sweep_overlap_tiles.py) needs the KERNEL's staging pipeline
    # measured on one chip — without this flag its gemm_rs numbers
    # would silently time XLA at every tile config.
    force_kernel: bool = False


# 8 MB (tile_m=1024 at K=4096 bf16) measured best on v5e — see
# perf/sweep_overlap_tiles.py and the ag_gemm budget note.
_RS_STAGE_BUDGET = 8 * 1024 * 1024


def create_gemm_rs_context(
    m: int, n_out: int, k_loc: int, dtype=jnp.bfloat16, tile_n: int | None = None,
    n_ranks: int = 8, bidir: bool = True,
) -> GemmRSConfig:
    itemsize = jnp.dtype(dtype).itemsize
    m_per = max(m // max(n_ranks, 1), 1)
    tile_m = m_per
    while tile_m > 128 and tile_m * k_loc * itemsize > _RS_STAGE_BUDGET:
        tile_m //= 2
    while m_per % tile_m:
        tile_m //= 2
    # The dual-ring (bidir) kernel needs an even row-tile count to split
    # each chunk between the two directions; a whole-chunk tile would
    # silently fall back to the single ring (half the wire bandwidth).
    if bidir and tile_m == m_per and m_per % 2 == 0 and m_per >= 16:
        tile_m //= 2
    return GemmRSConfig(
        tile_n=pick_tile(n_out, 1024) if tile_n is None else tile_n,
        tile_m=max(tile_m, 1),
        bidir=bidir,
    )


def _gemm_rs_kernel(
    a_ref,      # [M, k_loc] ANY/HBM — this device's column shard of A
    b_ref,      # [k_loc, tile_n] VMEM — B tile j
    o_ref,      # [m_per, N] ANY/HBM — final reduced chunk (written once)
    ws,         # [n-1, m_per, N] ANY/HBM output (wire dtype) — per-step
                # inbound slots (workspace-as-output; no HBM scratch)
    accbuf,     # [2, m_per, N] ANY/HBM output (wire dtype) — outbound
    a_vmem,     # [2, tile_m, k_loc] VMEM — A tile double buffer
    inb_vmem,   # [2, tile_m, tile_n] VMEM (wire dtype) — inbound tile
    out_vmem,   # [2, tile_m, tile_n] VMEM (wire dtype) — outbound tile
    fin_vmem,   # [2, tile_m, tile_n] VMEM (input dtype) — final-step
                # tile, or None when wire dtype == input dtype
    load_sems,  # DMA (2,)
    inb_sems,   # DMA (2,)
    out_sems,   # DMA (2,)
    send_sems,  # DMA (ndir, n-1)
    recv_sems,  # DMA (ndir, n-1)
    *,
    axis: str,
    acc_dtype,
    bidir: bool,
):
    me = dl.rank(axis)
    n = dl.num_ranks(axis)
    s = pl.program_id(0)
    i = pl.program_id(1)
    j = pl.program_id(2)
    num_i = pl.num_programs(1)
    num_j = pl.num_programs(2)
    tile_m = a_vmem.shape[1]
    tile_n = b_ref.shape[1]
    right = jax.lax.rem(me + 1, n)
    left = jax.lax.rem(me - 1 + n, n)
    t = i * num_j + j          # tile linear index within the step
    num_t = num_i * num_j
    p = jax.lax.rem(t, 2)      # inbound/outbound buffer parity
    # Bidir: row tiles [0, ni2) ride the clockwise ring (dir 0, to the
    # right neighbor), [ni2, num_i) the counter-clockwise ring (dir 1).
    ndir = 2 if bidir else 1
    ni2 = num_i // 2 if bidir else num_i
    half_m = ni2 * tile_m
    m_per = num_i * tile_m

    def rows(ti):
        return pl.ds(ti * tile_m, tile_m)

    def cols(tj):
        return pl.ds(tj * tile_n, tile_n)

    def dir_rows(d):
        # Direction d's row span of a chunk-sized [m_per, N] buffer.
        if d == 0:
            return pl.ds(0, half_m)
        return pl.ds(half_m, m_per - half_m)

    def a_chunk(step, ti):
        # Destination chunk this step's row-tile belongs to: clockwise
        # rows serve chunk me-1-step (flowing right), counter-clockwise
        # rows chunk me+1+step (flowing left); both reach the own chunk
        # at step n-1.
        cw = jax.lax.rem(me - 1 - step + 2 * n, n)
        if not bidir:
            return cw
        ccw = jax.lax.rem(me + 1 + step, n)
        return jnp.where(ti < ni2, cw, ccw)

    def a_buf(step, ti):
        return jax.lax.rem(step * num_i + ti, 2)

    def stage_a(step, ti):
        b = a_buf(step, ti)
        return pltpu.make_async_copy(
            a_ref.at[pl.ds(a_chunk(step, ti) * m_per + ti * tile_m, tile_m)],
            a_vmem.at[b],
            load_sems.at[b],
        )

    def stage_inb(step, ti, tj, par):
        return pltpu.make_async_copy(
            ws.at[step - 1, rows(ti), cols(tj)],
            inb_vmem.at[par],
            inb_sems.at[par],
        )

    @pl.when(jnp.logical_and(s == 0, t == 0))
    def _start():
        # Entry barrier: the first remote put (end of step 0) targets the
        # right neighbor's ws output, which must already be allocated.
        dl.barrier_all(axis)
        dma = stage_a(0, 0)
        dma.start()
        dma.wait()

    @pl.when(jnp.logical_and(s > 0, t == 0))
    def _step_begin():
        # A tile 0 staged at the end of the previous step.
        b = a_buf(s, 0)
        pltpu.make_async_copy(
            a_vmem.at[b], a_vmem.at[b], load_sems.at[b]
        ).wait()
        # Inbound accumulated partials (per direction) must have landed.
        for d in range(ndir):
            dl.wait_recv(recv_sems.at[d, s - 1], ws.at[s - 1, dir_rows(d)])
        dma = stage_inb(s, 0, 0, 0)
        dma.start()
        dma.wait()
        # accbuf slot s%2 was last pushed at step s-2; drain before reuse.
        @pl.when(s >= 2)
        def _():
            for d in range(ndir):
                pltpu.make_async_copy(
                    accbuf.at[s % 2, dir_rows(d)],
                    accbuf.at[s % 2, dir_rows(d)],
                    send_sems.at[d, s - 2],
                ).wait()

    @pl.when(jnp.logical_and(jnp.logical_and(s > 0, t > 0), t < num_t))
    def _land_inb():
        # Inbound tile t staged at tile t-1.
        pltpu.make_async_copy(
            inb_vmem.at[p], inb_vmem.at[p], inb_sems.at[p]
        ).wait()

    @pl.when(jnp.logical_and(t > 0, j == 0))
    def _land_a():
        b = a_buf(s, i)
        pltpu.make_async_copy(
            a_vmem.at[b], a_vmem.at[b], load_sems.at[b]
        ).wait()

    # Prefetches for tile t+1 (inbound) and row-tile i+1 (A), issued
    # before the matmul so the DMA engines run under MXU work.
    @pl.when(jnp.logical_and(s > 0, t + 1 < num_t))
    def _prefetch_inb():
        ni = (t + 1) // num_j
        nj = jax.lax.rem(t + 1, num_j)
        stage_inb(s, ni, nj, 1 - p).start()

    @pl.when(jnp.logical_and(i + 1 < num_i, j == num_j - 1))
    def _prefetch_a():
        stage_a(s, i + 1).start()

    @pl.when(jnp.logical_and(s + 1 < n, t == num_t - 1))
    def _prefetch_a_next_step():
        stage_a(s + 1, 0).start()

    partial = jnp.dot(
        a_vmem[a_buf(s, i)], b_ref[:], preferred_element_type=acc_dtype
    )

    def drain_tile(buf, par):
        pltpu.make_async_copy(
            buf.at[par], buf.at[par], out_sems.at[par]
        ).wait()

    # Reuse of the outbound tile buffer: its previous DMA-out (tile t-2,
    # same step, same buffer kind) must be done.
    @pl.when(jnp.logical_and(t >= 2, s < n - 1))
    def _drain_out():
        drain_tile(out_vmem, p)

    @pl.when(jnp.logical_and(t >= 2, s == n - 1))
    def _drain_fin():
        drain_tile(fin_vmem if fin_vmem is not None else out_vmem, p)

    @pl.when(jnp.logical_and(s == 0, s < n - 1))
    def _first_step():
        out_vmem[p] = partial.astype(out_vmem.dtype)

    @pl.when(jnp.logical_and(s > 0, s < n - 1))
    def _accumulate():
        out_vmem[p] = (
            partial + inb_vmem[p].astype(acc_dtype)
        ).astype(out_vmem.dtype)

    fbuf = fin_vmem if fin_vmem is not None else out_vmem

    @pl.when(s == n - 1)
    def _final_accumulate():
        if n == 1:
            # Degenerate ring (force_kernel at tp=1): no inbound partial
            # exists — the tile is the full reduction.
            fbuf[p] = partial.astype(fbuf.dtype)
        else:
            fbuf[p] = (
                partial + inb_vmem[p].astype(acc_dtype)
            ).astype(fbuf.dtype)

    @pl.when(s < n - 1)
    def _to_accbuf():
        pltpu.make_async_copy(
            out_vmem.at[p], accbuf.at[s % 2, rows(i), cols(j)],
            out_sems.at[p],
        ).start()

    @pl.when(s == n - 1)
    def _to_out():
        pltpu.make_async_copy(
            fbuf.at[p], o_ref.at[rows(i), cols(j)], out_sems.at[p]
        ).start()

    @pl.when(t == num_t - 1)
    def _step_end():
        # All outbound tile DMAs of this step must have landed in HBM
        # before the chunk is forwarded (or the kernel exits).
        def _drain_step_bufs(buf):
            pltpu.make_async_copy(
                buf.at[p], buf.at[p], out_sems.at[p]
            ).wait()

            @pl.when(num_t > 1)
            def _():
                pltpu.make_async_copy(
                    buf.at[1 - p], buf.at[1 - p], out_sems.at[1 - p]
                ).wait()

        @pl.when(s < n - 1)
        def _drain_hop():
            _drain_step_bufs(out_vmem)

        @pl.when(s == n - 1)
        def _drain_final():
            _drain_step_bufs(fbuf)

        @pl.when(s < n - 1)
        def _forward():
            # Receiver consumes this at its step s+1 from slot s: dir 0
            # rows go right, dir 1 rows go left.
            dl.put_signal(
                accbuf.at[s % 2, dir_rows(0)], ws.at[s, dir_rows(0)],
                right, send_sems.at[0, s], recv_sems.at[0, s], axis=axis,
            )
            if bidir:
                dl.put_signal(
                    accbuf.at[s % 2, dir_rows(1)], ws.at[s, dir_rows(1)],
                    left, send_sems.at[1, s], recv_sems.at[1, s], axis=axis,
                )

        @pl.when(s == n - 1)
        def _finish():
            # Steps 0..n-3 drained on accbuf reuse; only n-2 remains.
            if n > 1:
                step = n - 2
                for d in range(ndir):
                    pltpu.make_async_copy(
                        accbuf.at[step % 2, dir_rows(d)],
                        accbuf.at[step % 2, dir_rows(d)],
                        send_sems.at[d, step],
                    ).wait()


def gemm_rs(
    a: jax.Array,
    b: jax.Array,
    axis: str = "tp",
    config: GemmRSConfig | None = None,
    ctx: DistContext | None = None,
) -> jax.Array:
    """Overlapped ``reduce_scatter(a @ b)`` inside ``shard_map``.

    ``a``: ``[M, k_loc]`` column shard; ``b``: ``[k_loc, N]`` row shard.
    Returns this device's reduced row chunk ``[M/n, N]`` — same contract
    as reference ``gemm_rs`` (``gemm_reduce_scatter.py:569``).
    """
    n = jax.lax.axis_size(axis)
    m, k_loc = a.shape
    _, n_out = b.shape
    if m % n:
        raise ValueError(f"M={m} not divisible by axis size {n}")
    m_per = m // n
    config = config or create_gemm_rs_context(
        m, n_out, k_loc, a.dtype, n_ranks=n
    )
    tile_n = min(config.tile_n, n_out)
    if n_out % tile_n:
        raise ValueError(f"n_out={n_out} not divisible by tile_n={tile_n}")
    num_j = n_out // tile_n
    tile_m = min(config.tile_m or m_per, m_per)
    if m_per % tile_m:
        raise ValueError(f"m_per={m_per} not divisible by tile_m={tile_m}")
    num_i = m_per // tile_m

    if n == 1 and not config.force_kernel:
        return jnp.dot(a, b, preferred_element_type=config.acc_dtype).astype(a.dtype)

    wire = jnp.dtype(config.wire_dtype or a.dtype)
    # Bidir needs an even row-tile split of each chunk; degenerate
    # configs fall back to the single ring.
    bidir = bool(config.bidir) and num_i % 2 == 0 and num_i >= 2
    ndir = 2 if bidir else 1
    separate_final = wire != jnp.dtype(a.dtype)

    def kernel(a_ref, b_ref, o_ref, ws, accbuf, a_vmem, inb_vmem, out_vmem,
               *rest):
        if separate_final:
            fin_vmem, *sems = rest
        else:
            fin_vmem, sems = None, list(rest)
        _gemm_rs_kernel(
            a_ref, b_ref, o_ref, ws, accbuf, a_vmem, inb_vmem, out_vmem,
            fin_vmem, *sems, axis=axis, acc_dtype=config.acc_dtype,
            bidir=bidir,
        )

    scratch = [
        pltpu.VMEM((2, tile_m, k_loc), a.dtype),
        pltpu.VMEM((2, tile_m, tile_n), wire),
        pltpu.VMEM((2, tile_m, tile_n), wire),
    ]
    if separate_final:
        scratch.append(pltpu.VMEM((2, tile_m, tile_n), a.dtype))
    scratch += [
        pltpu.SemaphoreType.DMA((2,)),
        pltpu.SemaphoreType.DMA((2,)),
        pltpu.SemaphoreType.DMA((2,)),
        pltpu.SemaphoreType.DMA((ndir, max(n - 1, 1))),
        pltpu.SemaphoreType.DMA((ndir, max(n - 1, 1))),
    ]

    out, _ws, _acc = comm_pallas_call(
        kernel,
        (
            jax.ShapeDtypeStruct((m_per, n_out), a.dtype),
            # n=1 (force_kernel): every ws/accbuf access is RUNTIME-
            # guarded (s>0 / s<n-1 / n>1) but still TRACED, so the dummy
            # shapes must fit each static slice size (≤ m_per rows,
            # ≤ tile_n cols) while dropping the n_out/tile_n-fold dead
            # HBM the full workspaces would allocate.
            jax.ShapeDtypeStruct(
                (n - 1, m_per, n_out) if n > 1 else (1, m_per, tile_n),
                wire,
            ),
            jax.ShapeDtypeStruct(
                (2, m_per, n_out) if n > 1 else (2, m_per, tile_n), wire
            ),
        ),
        grid=(n, num_i, num_j),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(
                (k_loc, tile_n), lambda s, i, j: (0, j), memory_space=pltpu.VMEM
            ),
        ],
        out_specs=(
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ),
        scratch_shapes=scratch,
        collective_id=_GEMM_RS_COLLECTIVE_ID,
        # Mosaic double-buffers the BlockSpec-pipelined operands; at
        # north-star shapes that exceeds the 16 MB default scoped-VMEM
        # limit (v5e/v5p have 128 MB physical). Large-tile configs (the
        # sweep-tuned defaults) need headroom above 64 MB.
        vmem_limit_bytes=overlap_vmem_limit(
            tile_m, k_loc, tile_n, a.dtype.itemsize, out_tile_bufs=3
        ),
        dimension_semantics=("arbitrary", "arbitrary", "arbitrary"),
        cost_estimate=comm_cost(
            flops=2 * m * k_loc * n_out,
            # A + B read once, partials pushed around the ring(s) in the
            # wire dtype and re-read for the local adds, chunk written.
            bytes_accessed=(a.size + b.size + m_per * n_out)
            * a.dtype.itemsize
            + 3 * (n - 1) * m_per * n_out * wire.itemsize,
        ),
        ctx=ctx,
    )(a, b)
    return out


def gemm_rs_op(
    a: jax.Array,
    b: jax.Array,
    axis: str = "tp",
    config: GemmRSConfig | None = None,
    ctx: DistContext | None = None,
) -> jax.Array:
    """Host-level wrapper: ``a [M, K]`` column-sharded over ``axis``,
    ``b [K, N]`` row-sharded; returns ``[M, N]`` row-sharded (the summed
    GEMM, scattered)."""
    ctx = ctx or current_context()
    f = ctx.shard_map(
        functools.partial(gemm_rs, axis=axis, config=config, ctx=ctx),
        in_specs=(P(None, axis), P(axis, None)),
        out_specs=P(axis, None),
    )
    return f(a, b)
