"""Autotuned entry points for the overlap ops.

Parity: the reference wires its kernels to ``contextual_autotune``
inside the tests/layers (``test/nvidia/test_ag_gemm.py`` wrapping
``ag_gemm`` runs; ``autotuner.py:97``); here the tuned entry points are
part of the op library so layers/models can opt in directly.

The config space is the tile grid the on-chip sweep explores
(``perf/sweep_overlap_tiles.py``); configs whose staging buffers
cannot fit the scoped-VMEM cap are pruned before compiling anything
(parity role: the reference pruning sweeps by ``gemm_perf_model``).
Winning configs persist to the autotuner's disk cache keyed by
(shard shapes, dtype, axis name + size).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from triton_distributed_tpu.ops.overlap.ag_gemm import AGGemmConfig, ag_gemm_op
from triton_distributed_tpu.ops.overlap.gemm_rs import GemmRSConfig, gemm_rs_op
from triton_distributed_tpu.runtime.mesh import DistContext, current_context
from triton_distributed_tpu.tools.autotuner import Autotuner, Config

_TILE_MS = (256, 512, 1024, 2048)
_TILE_NS = (256, 512, 1024)


def _tile_grid(m_per: int, n_loc: int) -> list[tuple[int, int]]:
    """Valid, deduplicated (tile_m, tile_n) pairs (tiles clamp to the
    shard dims, so several grid points can collapse to one config)."""
    seen = set()
    for tm in _TILE_MS:
        tm = min(tm, m_per)
        if m_per % tm:
            continue
        for tn in _TILE_NS:
            tn = min(tn, n_loc)
            if n_loc % tn:
                continue
            seen.add((tm, tn))
    return sorted(seen)


def _ag_configs(m_per: int, n_loc: int, k: int) -> list[Config]:
    out = [
        Config({"config": AGGemmConfig(tile_n=tn, tile_m=tm)})
        for tm, tn in _tile_grid(m_per, n_loc)
    ]
    return out or [Config({"config": None})]


def _fits_vmem(cfg, k: int, itemsize: int, out_tile_bufs: int) -> bool:
    """Config's staging buffers fit the scoped-VMEM cap."""
    from triton_distributed_tpu.ops.common import (
        OVERLAP_VMEM_CAP,
        overlap_vmem_bytes,
    )

    need = overlap_vmem_bytes(
        cfg.tile_m, k, cfg.tile_n, itemsize, out_tile_bufs
    )
    return need <= OVERLAP_VMEM_CAP


@functools.lru_cache(maxsize=64)
def _ag_tuner(
    m_per: int, n_loc: int, k: int, axis: str, n_ranks: int, dtype: str,
    is_dist: bool,
):
    def run(a, b, config=None, *, _ctx=None):
        return ag_gemm_op(a, b, axis, config, _ctx or current_context())

    itemsize = jnp.dtype(dtype).itemsize

    def prune(configs):
        kept = [
            c for c in configs
            if c.kwargs["config"] is None
            or _fits_vmem(c.kwargs["config"], k, itemsize, 1)
        ]
        return kept or list(configs)[:1]

    return Autotuner(
        run,
        _ag_configs(m_per, n_loc, k),
        key=lambda *a, **kw: (m_per, n_loc, k, axis, n_ranks, dtype),
        prune=prune,
        is_dist=is_dist,
    )


def ag_gemm_tuned(
    a: jax.Array,
    b: jax.Array,
    axis: str = "tp",
    ctx: DistContext | None = None,
) -> jax.Array:
    """``ag_gemm_op`` with the tile config autotuned per shape.

    ``a`` ``[M, K]`` row-sharded over ``axis``, ``b`` ``[K, N]``
    column-sharded (host shapes). First call per (shape, axis) sweeps
    the tile grid; later calls (and later processes, via the disk
    cache) replay the argmin.
    """
    ctx = ctx or current_context()
    n = ctx.mesh.shape[axis]
    m_per = a.shape[0] // n
    n_loc = b.shape[1] // n
    tuner = _ag_tuner(
        m_per, n_loc, a.shape[1], axis, n, jnp.dtype(a.dtype).name,
        jax.process_count() > 1,
    )
    return tuner(a, b, _ctx=ctx)


def _rs_configs(m: int, n_out: int, n_ranks: int) -> list[Config]:
    m_per = max(m // max(n_ranks, 1), 1)
    out = [
        Config({"config": GemmRSConfig(tile_n=tn, tile_m=tm)})
        for tm, tn in _tile_grid(m_per, n_out)
    ]
    return out or [Config({"config": None})]


@functools.lru_cache(maxsize=64)
def _rs_tuner(m: int, n_out: int, k_loc: int, axis: str, n_ranks: int,
              dtype: str, is_dist: bool):
    def run(a, b, config=None, *, _ctx=None):
        return gemm_rs_op(a, b, axis, config, _ctx or current_context())

    itemsize = jnp.dtype(dtype).itemsize

    def prune(configs):
        kept = [
            c for c in configs
            if c.kwargs["config"] is None
            or _fits_vmem(c.kwargs["config"], k_loc, itemsize, 3)
        ]
        return kept or list(configs)[:1]

    return Autotuner(
        run,
        _rs_configs(m, n_out, n_ranks),
        key=lambda *a, **kw: (m, n_out, k_loc, axis, n_ranks, dtype),
        prune=prune,
        is_dist=is_dist,
    )


def gemm_rs_tuned(
    a: jax.Array,
    b: jax.Array,
    axis: str = "tp",
    ctx: DistContext | None = None,
) -> jax.Array:
    """``gemm_rs_op`` with the tile config autotuned per shape."""
    ctx = ctx or current_context()
    n = ctx.mesh.shape[axis]
    k_loc = a.shape[1] // n
    tuner = _rs_tuner(
        a.shape[0], b.shape[1], k_loc, axis, n, jnp.dtype(a.dtype).name,
        jax.process_count() > 1,
    )
    return tuner(a, b, _ctx=ctx)
