from triton_distributed_tpu.ops.overlap.ag_gemm import (  # noqa: F401
    AGGemmConfig,
    ag_gemm,
    ag_gemm_op,
    create_ag_gemm_context,
)
from triton_distributed_tpu.ops.overlap.gemm_ar import (  # noqa: F401
    GemmARConfig,
    GemmARMethod,
    create_gemm_ar_context,
    gemm_ar,
    gemm_ar_op,
)
from triton_distributed_tpu.ops.overlap.gemm_rs import (  # noqa: F401
    GemmRSConfig,
    create_gemm_rs_context,
    gemm_rs,
    gemm_rs_op,
)
from triton_distributed_tpu.ops.overlap.tuned import (  # noqa: F401
    ag_gemm_tuned,
    gemm_rs_tuned,
)
