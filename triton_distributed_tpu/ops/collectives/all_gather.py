"""AllGather: XLA path + device-initiated Pallas ring protocols over ICI.

Parity: reference ``kernels/nvidia/allgather.py`` — ``AllGatherMethod``
enum (:46, FullMesh/Ring1D/Ring2D push/pull) and the copy-engine /
NVSHMEM producers (:81-471).

TPU design: ICI is a torus of point-to-point links, so the native
protocols are rings; a "full mesh" push (every peer DMAs to every peer
simultaneously) is also expressible and wins at small sizes (one hop
latency instead of n-1). The XLA method is the NCCL-analog golden path.
Ring step count and peer index arithmetic are static at trace time
(axis sizes are Python ints), so protocols unroll fully — no scalar
loops on the core.
"""

from __future__ import annotations

import enum
import functools

import jax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from triton_distributed_tpu import language as dl
from triton_distributed_tpu.ops.common import (
    device_initiable,
    comm_pallas_call,
    next_collective_id,
)
from triton_distributed_tpu.runtime.mesh import DistContext, current_context


class AllGatherMethod(enum.Enum):
    """Parity: ``allgather.py:46`` (auto/full-mesh/ring variants)."""

    AUTO = "auto"
    XLA = "xla"
    PALLAS_RING = "pallas_ring"
    PALLAS_BIDIR_RING = "pallas_bidir_ring"
    PALLAS_FULL_MESH = "pallas_full_mesh"
    PALLAS_PULL = "pallas_pull"


_AG_COLLECTIVE_ID = next_collective_id()


def _ring_kernel(x_ref, o_ref, copy_sem, send_sems, recv_sems, *, axis: str):
    """Unidirectional ring: at step s forward the chunk received at step
    s-1 to the right neighbor; chunks land at their global row offset.

    Equivalent role: ``cp_engine_producer_all_gather_ring_push_1d``
    (reference ``allgather.py:140``), with the copy engine replaced by the
    ICI DMA engine and the tile barrier by per-step recv semaphores.

    All refs live in ANY/HBM and every byte moves by DMA — the kernel is
    pure orchestration, so payload size is bounded by HBM, not VMEM.
    """
    me = dl.rank(axis)
    n = dl.num_ranks(axis)
    m_per = x_ref.shape[0]
    right = jax.lax.rem(me + 1, n)

    # Own shard lands at its global offset (local HBM→HBM DMA), started
    # under the barrier.
    cp = pltpu.make_async_copy(
        x_ref, o_ref.at[pl.ds(me * m_per, m_per)], copy_sem
    )
    cp.start()
    # Entry barrier: peers must have entered (their o_ref allocated and
    # no longer owned by preceding XLA ops) before any remote write.
    dl.barrier_all(axis)
    cp.wait()

    dmas = []
    for s in range(n - 1):
        # Chunk to send this step originated at (me - s) mod n.
        src_rank = jax.lax.rem(me - s + n, n)
        sl = pl.ds(src_rank * m_per, m_per)
        dmas.append(
            dl.put_signal(
                o_ref.at[sl], o_ref.at[sl], right,
                send_sems.at[s], recv_sems.at[s], axis=axis,
            )
        )
        # This step's incoming chunk originated at (me - s - 1) mod n.
        in_rank = jax.lax.rem(me - s - 1 + n, n)
        dl.wait_recv(recv_sems.at[s], o_ref.at[pl.ds(in_rank * m_per, m_per)])
    dl.quiet(*dmas)


def _bidir_ring_kernel(
    x_ref, o_ref, copy_sem, send_sems, recv_sems, *, axis: str
):
    """Bidirectional ring: each shard's top half travels clockwise and
    bottom half counter-clockwise, using both directions of the torus
    axis — 2x effective ICI bandwidth, (n-1) steps of half-chunks.

    Equivalent role: the reference's NUMA-aware 2D rings
    (``allgather.py:196``) — different topology, same idea: use every
    link concurrently. ANY/HBM refs, DMA-only (no VMEM ceiling).
    """
    me = dl.rank(axis)
    n = dl.num_ranks(axis)
    m_per = x_ref.shape[0]
    half = m_per // 2
    right = jax.lax.rem(me + 1, n)
    left = jax.lax.rem(me - 1 + n, n)

    cp = pltpu.make_async_copy(
        x_ref, o_ref.at[pl.ds(me * m_per, m_per)], copy_sem
    )
    cp.start()
    dl.barrier_all(axis)
    cp.wait()

    dmas = []
    for s in range(n - 1):
        cw_src = jax.lax.rem(me - s + n, n)
        cw_sl = pl.ds(cw_src * m_per, half)
        dmas.append(
            dl.put_signal(
                o_ref.at[cw_sl], o_ref.at[cw_sl], right,
                send_sems.at[0, s], recv_sems.at[0, s], axis=axis,
            )
        )
        ccw_src = jax.lax.rem(me + s, n)
        ccw_sl = pl.ds(ccw_src * m_per + half, m_per - half)
        dmas.append(
            dl.put_signal(
                o_ref.at[ccw_sl], o_ref.at[ccw_sl], left,
                send_sems.at[1, s], recv_sems.at[1, s], axis=axis,
            )
        )
        cw_in = jax.lax.rem(me - s - 1 + n, n)
        ccw_in = jax.lax.rem(me + s + 1, n)
        dl.wait_recv(recv_sems.at[0, s], o_ref.at[pl.ds(cw_in * m_per, half)])
        dl.wait_recv(
            recv_sems.at[1, s],
            o_ref.at[pl.ds(ccw_in * m_per + half, m_per - half)],
        )
    dl.quiet(*dmas)


def _full_mesh_kernel(
    x_ref, o_ref, copy_sem, send_sems, recv_sems, *, axis: str
):
    """Every device pushes its shard directly to every peer (1 hop).

    Equivalent role: ``cp_engine_producer_all_gather_full_mesh_push``
    (reference ``allgather.py:81``). Best at small sizes where per-hop
    latency dominates; the fabric routes concurrent DMAs.

    All arrivals share one recv semaphore: shards are equal-sized, so
    waiting (n-1) shard-sizes is order-independent. ANY/HBM, DMA-only.
    """
    me = dl.rank(axis)
    n = dl.num_ranks(axis)
    m_per = x_ref.shape[0]
    own = pl.ds(me * m_per, m_per)

    cp = pltpu.make_async_copy(x_ref, o_ref.at[own], copy_sem)
    cp.start()
    dl.barrier_all(axis)
    cp.wait()

    dmas = []
    for i in range(1, n):
        peer = jax.lax.rem(me + i, n)
        dmas.append(
            dl.put_signal(
                o_ref.at[own], o_ref.at[own], peer,
                send_sems.at[i - 1], recv_sems, axis=axis,
            )
        )
    for _ in range(1, n):
        dl.wait_recv(recv_sems, o_ref.at[own])
    dl.quiet(*dmas)


def _pull_kernel(
    x_ref, o_ref, copy_sem, send_sems, recv_sems, req_sems,
    *, axis: str, window: int
):
    """Receiver-driven (pull) full-mesh gather.

    Equivalent role: the reference's pull producers —
    ``cp_engine_producer_all_gather_full_mesh_pull`` (``allgather.py:106``)
    and the LL ``_forward_pull`` (``low_latency_allgather.py:48``). The
    ICI DMA engine is push-only, so "pull" is the :func:`dl.request` /
    :func:`dl.serve_get` rendezvous: shard ``s`` only moves after the
    receiver asks for it, paced ``window`` requests at a time, so a rank
    never suffers n-1 simultaneous inbound DMAs (the incast the push
    full-mesh creates and a straggler amplifies).

    NO entry barrier — a serve is gated on the requester's own request,
    which proves its ``o_ref`` is live (see :func:`dl.request`). At
    ``window >= n-1`` this is latency-equivalent to full-mesh push minus
    the barrier hop, plus one request signal.

    Deadlock-freedom (serve order is ascending step ``s``): serve step
    ``s`` consumes request #``s``, which rank ``me-s`` issues either up
    front (``s <= window``) or after its arrival ``s-window`` — produced
    by serve step ``s-window`` of another rank. Every wait therefore
    depends only on strictly smaller serve steps; induction on ``s``
    closes the cycle-free argument.
    """
    me = dl.rank(axis)
    n = dl.num_ranks(axis)
    m_per = x_ref.shape[0]
    own = pl.ds(me * m_per, m_per)
    # n=1: both loops must be empty (w=0) — a self-request would leave
    # req_sems[0] signaled but never served at kernel exit.
    w = min(max(window, 1), n - 1)

    cp = pltpu.make_async_copy(x_ref, o_ref.at[own], copy_sem)
    cp.start()

    # Window of outstanding pull requests: ask peers me+1 .. me+w first.
    for i in range(1, w + 1):
        dl.request(req_sems.at[i - 1], jax.lax.rem(me + i, n), axis)

    dmas = []
    for s in range(1, n):
        # Serve: requester me-s asked for my shard with its request #s.
        requester = jax.lax.rem(me - s + n, n)
        dmas.append(
            dl.serve_get(
                req_sems.at[s - 1], x_ref, o_ref.at[own], requester,
                send_sems.at[s - 1], recv_sems.at[s - 1], axis,
            )
        )
        # My own request #s has now been served by peer me+s.
        src = jax.lax.rem(me + s, n)
        dl.wait_recv(recv_sems.at[s - 1], o_ref.at[pl.ds(src * m_per, m_per)])
        if s + w <= n - 1:
            dl.request(
                req_sems.at[s + w - 1], jax.lax.rem(me + s + w, n), axis
            )
    cp.wait()
    dl.quiet(*dmas)


def all_gather(
    x: jax.Array,
    axis: str = "tp",
    method: AllGatherMethod = AllGatherMethod.AUTO,
    ctx: DistContext | None = None,
    pull_window: int = 2,
) -> jax.Array:
    """Gather shards along ``axis`` into the leading dim. Call inside
    ``shard_map``; ``x`` is this device's shard ``[m_per, ...]`` and the
    result is ``[n * m_per, ...]``.
    """
    n = jax.lax.axis_size(axis)
    if method == AllGatherMethod.AUTO:
        if not device_initiable(axis, ctx) or x.ndim < 2:
            # CPU-simulator meshes run Pallas in interpret mode, which is
            # for explicit kernel tests only; 1-D payloads (biases etc.)
            # also take the XLA path the Pallas kernels don't cover.
            method = AllGatherMethod.XLA
        else:
            # DMA-only kernels: no VMEM ceiling (payload stays in HBM).
            nbytes = x.size * x.dtype.itemsize
            if n <= 2 or nbytes <= 64 * 1024:
                method = AllGatherMethod.PALLAS_FULL_MESH
            else:
                method = AllGatherMethod.PALLAS_BIDIR_RING

    if method == AllGatherMethod.XLA:
        return jax.lax.all_gather(x, axis, tiled=True)

    if x.ndim < 2:
        raise ValueError("pallas all_gather needs >=2D input (rows, lanes)")
    m_per = x.shape[0]
    out_shape = jax.ShapeDtypeStruct((n * m_per, *x.shape[1:]), x.dtype)

    if method == AllGatherMethod.PALLAS_BIDIR_RING and (m_per < 2 or n <= 2):
        method = AllGatherMethod.PALLAS_RING  # halves degenerate

    if method == AllGatherMethod.PALLAS_RING:
        kernel = functools.partial(_ring_kernel, axis=axis)
        scratch = [
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA((max(n - 1, 1),)),
            pltpu.SemaphoreType.DMA((max(n - 1, 1),)),
        ]
    elif method == AllGatherMethod.PALLAS_BIDIR_RING:
        kernel = functools.partial(_bidir_ring_kernel, axis=axis)
        scratch = [
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA((2, max(n - 1, 1))),
            pltpu.SemaphoreType.DMA((2, max(n - 1, 1))),
        ]
    elif method == AllGatherMethod.PALLAS_FULL_MESH:
        kernel = functools.partial(_full_mesh_kernel, axis=axis)
        scratch = [
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA((max(n - 1, 1),)),
            pltpu.SemaphoreType.DMA(()),
        ]
    elif method == AllGatherMethod.PALLAS_PULL:
        kernel = functools.partial(_pull_kernel, axis=axis, window=pull_window)
        scratch = [
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA((max(n - 1, 1),)),
            pltpu.SemaphoreType.DMA((max(n - 1, 1),)),
            pltpu.SemaphoreType.REGULAR((max(n - 1, 1),)),
        ]
    else:
        raise ValueError(f"unknown method {method}")

    return comm_pallas_call(
        kernel,
        out_shape,
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=scratch,
        collective_id=_AG_COLLECTIVE_ID,
        ctx=ctx,
    )(x)


_AG_2D_COLLECTIVE_ID = next_collective_id()


def _torus_2d_kernel(
    x_ref,       # [m_per, L] ANY — own shard
    o_ref,       # [nx*ny*m_per, L] ANY — gathered, rank-major slots
    copy_sem,    # DMA ()
    send_y_sems,  # DMA (ny-1,)
    send_x_sems,  # DMA (nx-1, ny)
    recv_y_sems,  # DMA (ny,) — slot j' for column chunk (me_x, j')
    recv_x_sem,   # DMA () — byte counter for all row arrivals
    *,
    ax: str,
    ay: str,
):
    """Fused 2D-torus all-gather (equivalent role: the reference's
    NUMA-aware 2D producers, ``allgather.py:196`` ``ring_push_numa_2d``
    — use BOTH torus axes' links concurrently).

    Phase y: own chunk full-mesh along the column (``ay``). Phase x:
    every column chunk — own immediately, peers' AS EACH ARRIVES — is
    forwarded full-mesh along the row (``ax``), so row links carry
    traffic while column pushes are still in flight; no phase barrier.
    All transfers are row-or-column, so ONE combined row+column entry
    barrier (``dl.barrier_cross`` — NOT two sequential per-axis
    barriers, whose anonymous signals would alias on the kernel's
    single barrier semaphore) gives peer-buffer liveness without a
    diagonal handshake.
    """
    mx = dl.rank(ax)
    my = dl.rank(ay)
    nx = dl.num_ranks(ax)
    ny = dl.num_ranks(ay)
    m_per = x_ref.shape[0]

    def slot(gx, gy):
        return pl.ds((gx * ny + gy) * m_per, m_per)

    own = slot(mx, my)
    cp = pltpu.make_async_copy(x_ref, o_ref.at[own], copy_sem)
    cp.start()
    dl.barrier_cross(ax, ay)
    cp.wait()

    dmas = []
    # Column broadcast of the own chunk (y links busy first).
    for q in range(1, ny):
        peer = jax.lax.rem(my + q, ny)
        dmas.append(
            dl.put_signal(
                o_ref.at[own], o_ref.at[own], peer,
                send_y_sems.at[q - 1], recv_y_sems.at[my], axis=ay,
            )
        )
    # Row broadcast of the own chunk — x links busy concurrently.
    for p in range(1, nx):
        peer = jax.lax.rem(mx + p, nx)
        dmas.append(
            dl.put_signal(
                o_ref.at[own], o_ref.at[own], peer,
                send_x_sems.at[p - 1, my], recv_x_sem, axis=ax,
            )
        )
    # Forward each column chunk along the row as it arrives.
    for q in range(1, ny):
        src_y = jax.lax.rem(my + q, ny)
        sl = slot(mx, src_y)
        dl.wait_recv(recv_y_sems.at[src_y], o_ref.at[sl])
        for p in range(1, nx):
            peer = jax.lax.rem(mx + p, nx)
            dmas.append(
                dl.put_signal(
                    o_ref.at[sl], o_ref.at[sl], peer,
                    send_x_sems.at[p - 1, src_y], recv_x_sem, axis=ax,
                )
            )
    # Row arrivals: (nx-1) stripes of ny chunks, all chunk-sized, on one
    # byte-counting semaphore.
    for _ in range((nx - 1) * ny):
        dl.wait_recv(recv_x_sem, o_ref.at[own])
    dl.quiet(*dmas)


def all_gather_torus_2d(
    x: jax.Array,
    axes: tuple[str, str] = ("dp", "tp"),
    ctx: DistContext | None = None,
) -> jax.Array:
    """Fused all-gather over a 2D torus mesh (distinct from the 2-LEVEL
    ``hierarchical.all_gather_2d_op``, which splits ICI/DCN — here BOTH
    axes are ICI and one kernel drives all four link directions): shards gathered across
    BOTH axes in one kernel, rank-major ((ax, ay) row-major) row order.
    Call inside ``shard_map``; ``x`` is ``[m_per, ...]``, result
    ``[nx*ny*m_per, ...]``."""
    ax, ay = axes
    nx = jax.lax.axis_size(ax)
    ny = jax.lax.axis_size(ay)
    if x.ndim < 2:
        raise ValueError("pallas all_gather_torus_2d needs >=2D input")
    m_per = x.shape[0]
    out_shape = jax.ShapeDtypeStruct((nx * ny * m_per, *x.shape[1:]), x.dtype)
    return comm_pallas_call(
        functools.partial(_torus_2d_kernel, ax=ax, ay=ay),
        out_shape,
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA((max(ny - 1, 1),)),
            pltpu.SemaphoreType.DMA((max(nx - 1, 1), ny)),
            pltpu.SemaphoreType.DMA((ny,)),
            pltpu.SemaphoreType.DMA(()),
        ],
        collective_id=_AG_2D_COLLECTIVE_ID,
        ctx=ctx,
    )(x)


def all_gather_op(
    x: jax.Array,
    axis: str = "tp",
    method: AllGatherMethod = AllGatherMethod.AUTO,
    ctx: DistContext | None = None,
    pull_window: int = 2,
) -> jax.Array:
    """Host-level wrapper: ``x`` is sharded along its leading dim over
    ``axis``; result is the gathered (replicated) array. Mainly for
    tests/benchmarks — layers call :func:`all_gather` inside their own
    ``shard_map``.
    """
    ctx = ctx or current_context()
    rest = [None] * (x.ndim - 1)
    f = ctx.shard_map(
        functools.partial(
            all_gather, axis=axis, method=method, ctx=ctx,
            pull_window=pull_window,
        ),
        in_specs=P(axis, *rest),
        out_specs=P(None, *rest),
    )
    return f(x)
