"""AllToAll: XLA path + single-hop Pallas push over ICI.

Parity: reference ``kernels/nvidia/low_latency_all_to_all.py`` —
``all_to_all_kernel``:36 (putmem_signal per destination, double-buffered
by call count) and ``AllToAllContext``:125. The EP-specific variant with
token splits + fp8 scales lives in ``ops/moe/ep_a2a.py``; this is the
dense equal-split primitive.

Protocol: chunk i of the local array goes to device i's slot ``me``;
every pair exchanges directly (one ICI hop on a full axis, routed on a
torus). Arrivals share one recv semaphore since chunks are equal-sized.
"""

from __future__ import annotations

import functools

import jax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from triton_distributed_tpu import language as dl
from triton_distributed_tpu.ops.common import (
    device_initiable,
    VMEM_COMM_MAX_BYTES,
    comm_pallas_call,
    next_collective_id,
)
from triton_distributed_tpu.runtime.mesh import DistContext, current_context

_A2A_COLLECTIVE_ID = next_collective_id()


def _a2a_kernel(x_ref, o_ref, send_sems, recv_sems, *, axis: str):
    me = dl.rank(axis)
    n = dl.num_ranks(axis)
    m_per = x_ref.shape[0] // n

    def chunk(idx):
        return pl.ds(idx * m_per, m_per)

    dl.barrier_all(axis)  # peers' o_ref must exist before any put
    # Own chunk stays local.
    o_ref[chunk(me)] = x_ref[chunk(me)]

    dmas = []
    for i in range(1, n):
        peer = jax.lax.rem(me + i, n)
        dmas.append(
            dl.put_signal(
                x_ref.at[chunk(peer)],
                o_ref.at[chunk(me)],
                peer,
                send_sems.at[i - 1],
                recv_sems,
                axis=axis,
            )
        )
    for _ in range(1, n):
        dl.wait_recv(recv_sems, o_ref.at[chunk(me)])
    dl.quiet(*dmas)


def all_to_all(
    x: jax.Array,
    axis: str = "tp",
    method: str = "auto",
    ctx: DistContext | None = None,
) -> jax.Array:
    """Exchange equal chunks: row-chunk i of ``x`` lands at device i's
    row-chunk ``me``. Call inside ``shard_map``; ``x`` is
    ``[n*m_per, ...]``, result the same shape.
    """
    n = jax.lax.axis_size(axis)
    if method == "auto":
        on_chip = x.size * x.dtype.itemsize <= VMEM_COMM_MAX_BYTES
        method = "pallas" if device_initiable(axis, ctx) and on_chip else "xla"
    if method == "xla":
        return jax.lax.all_to_all(
            x.reshape(n, x.shape[0] // n, *x.shape[1:]),
            axis, split_axis=0, concat_axis=0, tiled=False,
        ).reshape(x.shape)
    if x.ndim < 2:
        raise ValueError("pallas all_to_all needs >=2D input")
    if x.shape[0] % n:
        raise ValueError(f"rows {x.shape[0]} not divisible by axis size {n}")
    return comm_pallas_call(
        functools.partial(_a2a_kernel, axis=axis),
        jax.ShapeDtypeStruct(x.shape, x.dtype),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.SemaphoreType.DMA((max(n - 1, 1),)),
            pltpu.SemaphoreType.DMA(()),
        ],
        collective_id=_A2A_COLLECTIVE_ID,
        ctx=ctx,
    )(x)


def all_to_all_op(
    x: jax.Array,
    axis: str = "tp",
    method: str = "auto",
    ctx: DistContext | None = None,
) -> jax.Array:
    """Host-level wrapper: ``x`` host shape ``[n, n*m_per, ...]`` (row i =
    device i's sends); result ``[n, n*m_per, ...]`` (row i = device i's
    receives)."""
    ctx = ctx or current_context()
    rest = [None] * (x.ndim - 2)

    def body(xi):
        return all_to_all(xi[0], axis=axis, method=method, ctx=ctx)[None]

    f = ctx.shard_map(
        body,
        in_specs=P(axis, None, *rest),
        out_specs=P(axis, None, *rest),
    )
    return f(x)
