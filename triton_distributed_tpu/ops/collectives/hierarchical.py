"""Hierarchical (two-level) collectives: ICI inner axis, DCN outer axis.

Parity: the reference's NUMA-/node-aware collective variants —
``low_latency_allgather.py`` ``_forward_push_2d``:345 / ``_forward_push_3d``
:400 (NVLink intra-node + RDMA inter-node stages), ``allgather.py``
``ring_push_numa_2d``:196 / ``ring_push_2d_inter_node``:293, and the
two-level multinode reduce-scatter ``reduce_scatter.py:828``
(``reduce_scatter_multi_node``).

TPU translation (SURVEY.md §2.4): the intra/inter-node split maps to
intra-slice **ICI** (device-initiated Pallas kernels, remote DMA +
semaphores) vs inter-slice **DCN** (XLA collectives — DCN transfers
cannot be device-initiated, SURVEY.md §7 hard parts). Each op stages the
fast level through the Pallas kernels and rides XLA across slices. The
reference's LL "flag-in-data" codecs (``_pack_ll_block``:549) have no TPU
analog — DMA completion semaphores *are* the arrival flags — so the
latency-optimized small-message path is the single-hop full-mesh kernel
(``AllGatherMethod.PALLAS_FULL_MESH``), selected by AUTO.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from triton_distributed_tpu.ops.collectives.all_gather import (
    AllGatherMethod,
    all_gather,
)
from triton_distributed_tpu.ops.collectives.reduce_scatter import (
    ReduceScatterMethod,
    reduce_scatter,
)
from triton_distributed_tpu.runtime.mesh import DistContext, current_context


def all_gather_2d(
    x: jax.Array,
    inner_axis: str = "tp",
    outer_axis: str = "dcn",
    *,
    inner_method: AllGatherMethod = AllGatherMethod.AUTO,
    ctx: DistContext | None = None,
) -> jax.Array:
    """Two-stage all-gather (call inside ``shard_map`` over both axes).

    ``x [m_per, ...]`` is the local shard of an array laid out
    outer-major over ``(outer_axis, inner_axis)``; returns the full
    ``[n_out * n_in * m_per, ...]`` array on every device. Stage 1 rides
    ICI (Pallas kernel); stage 2 rides DCN (XLA). Parity:
    ``_forward_push_2d`` — NVLink stage then inter-node stage.
    """
    y = all_gather(x, inner_axis, inner_method, ctx)   # [n_in * m, ...]
    return jax.lax.all_gather(y, outer_axis, axis=0, tiled=True)


def reduce_scatter_2d(
    x: jax.Array,
    inner_axis: str = "tp",
    outer_axis: str = "dcn",
    *,
    inner_method: ReduceScatterMethod = ReduceScatterMethod.AUTO,
    ctx: DistContext | None = None,
) -> jax.Array:
    """Two-stage reduce-scatter (call inside ``shard_map``).

    ``x [M, ...]`` (same on every device logically; summed across both
    axes) → this device's chunk ``[M / (n_in * n_out), ...]``, chunks
    assigned inner-major (chunk id = ``inner * n_out + outer``). Stage 1
    ring-reduces over ICI; stage 2 scatters the survivor over DCN.
    Parity: ``reduce_scatter_multi_node`` (``reduce_scatter.py:828``) —
    intra-node ring then the inter-node exchange.
    """
    y = reduce_scatter(x, inner_axis, inner_method, ctx)  # [M / n_in, ...]
    return jax.lax.psum_scatter(y, outer_axis, scatter_dimension=0, tiled=True)


def all_reduce_2level(
    x: jax.Array,
    inner_axis: str = "tp",
    outer_axis: str = "dcn",
    *,
    ctx: DistContext | None = None,
) -> jax.Array:
    """Two-level all-reduce: ICI reduce-scatter → DCN psum → ICI
    all-gather — the canonical slice-aware AR (parity role: the
    reference's double-tree/two-shot AR generalized across node
    boundaries, ``allreduce.py:215-700``)."""
    y = reduce_scatter(x, inner_axis, ReduceScatterMethod.AUTO, ctx)
    y = jax.lax.psum(y, outer_axis)
    return all_gather(y, inner_axis, AllGatherMethod.AUTO, ctx)


# -- host-level wrappers (tests/benchmarks) ---------------------------------

def all_gather_2d_op(
    x: jax.Array,
    inner_axis: str = "tp",
    outer_axis: str = "dcn",
    ctx: DistContext | None = None,
) -> jax.Array:
    """``x`` sharded outer-major over both axes on dim 0 → replicated."""
    ctx = ctx or current_context()
    rest = [None] * (x.ndim - 1)
    f = ctx.shard_map(
        functools.partial(
            all_gather_2d, inner_axis=inner_axis, outer_axis=outer_axis,
            ctx=ctx,
        ),
        in_specs=P((outer_axis, inner_axis), *rest),
        out_specs=P(None, *rest),
    )
    return f(x)


def all_reduce_2level_op(
    x: jax.Array,
    inner_axis: str = "tp",
    outer_axis: str = "dcn",
    ctx: DistContext | None = None,
) -> jax.Array:
    """``x [n_total, ...]`` with one addend per device → summed, replicated."""
    ctx = ctx or current_context()
    rest = [None] * (x.ndim - 1)

    def shard_fn(xi):
        return all_reduce_2level(
            xi[0], inner_axis=inner_axis, outer_axis=outer_axis, ctx=ctx
        )

    f = ctx.shard_map(
        shard_fn,
        in_specs=P((outer_axis, inner_axis), *rest),
        out_specs=P(*rest),  # addend dim consumed by the reduction
    )
    return f(x)
