"""Broadcast: root's buffer to every rank on the axis.

Parity: reference device-API broadcast family
(``libnvshmem_device.py:806-948`` ``broadcast*``/``broadcastmem``,
host-side ``nvshmem.core.broadcast``). On TPU the latency method is a
one-shot root push (root DMAs its buffer into every peer's output slot
over ICI — single hop, all sends in flight); larger payloads ride XLA's
collective machinery (a masked psum lowers to an ICI broadcast tree).
"""

from __future__ import annotations

import enum
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from triton_distributed_tpu import language as dl
from triton_distributed_tpu.ops.common import (
    device_initiable,
    VMEM_COMM_MAX_BYTES,
    comm_pallas_call,
    next_collective_id,
)
from triton_distributed_tpu.runtime.mesh import DistContext, current_context


class BroadcastMethod(enum.Enum):
    AUTO = "auto"
    XLA = "xla"
    ONE_SHOT = "one_shot"  # root pushes to every peer (small msgs)


_BCAST_COLLECTIVE_ID = next_collective_id()


def _one_shot_bcast_kernel(
    x_ref, o_ref, send_sems, recv_sem, *, axis: str, root: int
):
    me = dl.rank(axis)
    n = dl.num_ranks(axis)

    dl.barrier_all(axis)  # peers' o_ref must exist before any put

    @pl.when(me == root)
    def _send():
        o_ref[...] = x_ref[...]
        dmas = []
        for i in range(1, n):
            peer = jax.lax.rem(root + i, n)
            dmas.append(
                dl.put_signal(
                    x_ref, o_ref, peer,
                    send_sems.at[i - 1], recv_sem, axis=axis,
                )
            )
        dl.quiet(*dmas)

    @pl.when(me != root)
    def _recv():
        dl.wait_recv(recv_sem, o_ref)


def broadcast(
    x: jax.Array,
    axis: str = "tp",
    root: int = 0,
    method: BroadcastMethod = BroadcastMethod.AUTO,
    ctx: DistContext | None = None,
) -> jax.Array:
    """Every rank returns rank ``root``'s ``x``. Call inside shard_map."""
    n = jax.lax.axis_size(axis)
    if not 0 <= root < n:
        raise ValueError(f"root={root} out of range for axis size {n}")
    nbytes = x.size * x.dtype.itemsize
    if method == BroadcastMethod.AUTO:
        method = (
            BroadcastMethod.ONE_SHOT
            if device_initiable(axis, ctx) and x.ndim >= 2 and nbytes <= VMEM_COMM_MAX_BYTES
            else BroadcastMethod.XLA
        )

    if method == BroadcastMethod.XLA:
        me = jax.lax.axis_index(axis)
        masked = jnp.where(me == root, x, jnp.zeros_like(x))
        return jax.lax.psum(masked, axis)

    if x.ndim < 2:
        raise ValueError("pallas broadcast needs >=2D input")
    return comm_pallas_call(
        functools.partial(_one_shot_bcast_kernel, axis=axis, root=root),
        jax.ShapeDtypeStruct(x.shape, x.dtype),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.SemaphoreType.DMA((max(n - 1, 1),)),
            pltpu.SemaphoreType.DMA(()),
        ],
        collective_id=_BCAST_COLLECTIVE_ID,
        ctx=ctx,
    )(x)


def broadcast_op(
    x: jax.Array,
    axis: str = "tp",
    root: int = 0,
    method: BroadcastMethod = BroadcastMethod.AUTO,
    ctx: DistContext | None = None,
) -> jax.Array:
    """Host-level wrapper: ``x`` sharded over ``axis`` (host shape
    ``[n, ...]``, row i = rank i's buffer); returns root's buffer
    replicated (host shape ``[...]``)."""
    ctx = ctx or current_context()
    rest = [None] * (x.ndim - 1)

    def body(xi):
        return broadcast(xi[0], axis=axis, root=root, method=method, ctx=ctx)

    f = ctx.shard_map(
        body, in_specs=P(axis, *rest), out_specs=P(*rest)
    )
    return f(x)
