"""Low-latency (barrier-free) collectives for small messages.

Parity: reference ``kernels/nvidia/low_latency_allgather.py`` — the
pull/push LL protocols (:48-448) and the flag-in-data codecs (:549) that
let a rank push without a preceding barrier, plus the double-buffer
phase discipline of ``low_latency_all_to_all.py``.

TPU translation of the codec: the reference packs a monotonically
increasing flag next to the payload so a receiver can spin until the
CURRENT call's data (not a stale buffer) has arrived. On TPU the DMA
engine's arrival semaphore IS the flag — data visibility before signal
is the hardware contract — so what remains of the protocol is the
buffer-reuse discipline:

- symmetric slots are double-buffered on the call counter (``phase``),
  carried by the caller like the reference's ``buffer_id``;
- a producer may overwrite slot ``p`` only after every consumer of its
  previous use has ACKed (a 1-increment remote semaphore signal — the
  reference's flag-value comparison folded into semaphore counting).

No entry barrier, no trailing barrier: steady-state latency is one ICI
hop (put) + one hop (ack, off the critical path) — the same structure
that makes the reference's LL allgather win at small sizes.

Usage (the workspace threads through calls like the reference's
symmetric buffer):

    ws = ll_all_gather_workspace(ctx, m_per, lanes, dtype)
    phase = jnp.int32(0)
    for step in ...:
        out, ws = ll_all_gather(x, ws, phase, axis="tp", ctx=ctx)
        phase = phase + 1
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from triton_distributed_tpu import language as dl
from triton_distributed_tpu.ops.common import (
    comm_pallas_call,
    next_collective_id,
)
from triton_distributed_tpu.runtime.mesh import DistContext, current_context

_LL_AG_COLLECTIVE_ID = next_collective_id()


def ll_all_gather_workspace(
    n: int, m_per: int, lanes: int, dtype=jnp.float32
) -> jax.Array:
    """Per-device symmetric slots: ``[2 phases, n sources, m_per, lanes]``."""
    return jnp.zeros((2, n, m_per, lanes), dtype)


def _ll_ag_kernel(
    x_ref,       # [m_per, L] ANY — this device's shard
    ws_in,       # [2, n, m_per, L] ANY — symmetric slots (aliased to ws_out)
    phase_ref,   # [1] SMEM int32 — call counter
    o_ref,       # [n*m_per, L] ANY
    ws_out,      # aliased ws_in
    copy_sems,   # DMA (2,) — assemble copies (own + peers)
    send_sems,   # DMA (n-1,)
    recv_sems,   # DMA (2,) — arrivals per phase slot
    ack_sems,    # REGULAR (2,) — consumer acks per phase slot
    *,
    axis: str,
    barrier_free: bool,
):
    me = dl.rank(axis)
    n = dl.num_ranks(axis)
    m_per = x_ref.shape[0]
    phase = phase_ref[0]
    p = jax.lax.rem(phase, 2)

    if barrier_free:
        # Reuse discipline: slot p's previous use (call phase-2) must
        # have been consumed by every peer before we overwrite their
        # copy. Ack counts accumulate across launches — valid on real
        # TPU where sync-flag semaphores are persistent hardware
        # counters (Mosaic's drained-at-exit convention exists exactly
        # because leftovers would leak into the next kernel).
        @pl.when(phase >= 2)
        def _wait_acks():
            dl.wait(ack_sems.at[p], n - 1)

    else:
        # Interpret-mode shim: the simulator zeroes semaphores at kernel
        # exit, so cross-launch ack counting cannot work; an entry
        # barrier provides the same reuse guarantee (at +1 hop latency,
        # the cost the barrier-free path exists to shed).
        dl.barrier_all(axis)

    # Push: data lands in the peer's PERSISTENT slot, so no allocation
    # race exists; the arrival semaphore is the codec flag.
    dmas = []
    for i in range(1, n):
        peer = jax.lax.rem(me + i, n)
        dmas.append(
            dl.put_signal(
                x_ref, ws_in.at[p, me], peer,
                send_sems.at[i - 1], recv_sems.at[p], axis=axis,
            )
        )

    # Own shard → output straight away (overlaps the waits).
    own = pltpu.make_async_copy(
        x_ref, o_ref.at[pl.ds(me * m_per, m_per)], copy_sems.at[0]
    )
    own.start()

    # Wait all n-1 arrivals for THIS phase slot, then assemble.
    for _ in range(1, n):
        dl.wait_recv(recv_sems.at[p], ws_in.at[p, 0])
    for i in range(1, n):
        src = jax.lax.rem(me + i, n)
        cp = pltpu.make_async_copy(
            ws_in.at[p, src], o_ref.at[pl.ds(src * m_per, m_per)],
            copy_sems.at[1],
        )
        cp.start()
        cp.wait()
    own.wait()

    if barrier_free:
        # ACK every producer: their slot-p copy here is consumed.
        for i in range(1, n):
            src = jax.lax.rem(me + i, n)
            dl.signal(ack_sems.at[p], 1, dst=src, axis=axis)
    dl.quiet(*dmas)


def ll_all_gather(
    x: jax.Array,
    ws: jax.Array,
    phase: jax.Array | int,
    axis: str = "tp",
    ctx: DistContext | None = None,
    barrier_free: bool | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Barrier-free small-message all-gather inside ``shard_map``.

    ``x``: ``[m_per, L]``; ``ws``: persistent workspace from
    :func:`ll_all_gather_workspace` (returned updated — thread it);
    ``phase``: monotonically increasing call counter the caller carries.
    ``barrier_free`` defaults to on-TPU detection — the ack discipline
    needs hardware-persistent semaphores, which the interpret simulator
    does not model (see kernel docstring). Returns ``([n*m_per, L], ws)``.
    """
    from triton_distributed_tpu.ops.common import _on_tpu

    n = jax.lax.axis_size(axis)
    m_per, lanes = x.shape
    out_shape = jax.ShapeDtypeStruct((n * m_per, lanes), x.dtype)
    phase = jnp.asarray(phase, jnp.int32).reshape(1)
    if barrier_free is None:
        barrier_free = _on_tpu(ctx)

    out, ws_new = comm_pallas_call(
        functools.partial(_ll_ag_kernel, axis=axis, barrier_free=barrier_free),
        (out_shape, jax.ShapeDtypeStruct(ws.shape, ws.dtype)),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=(
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ),
        scratch_shapes=[
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((max(n - 1, 1),)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.REGULAR((2,)),
        ],
        collective_id=_LL_AG_COLLECTIVE_ID,
        ctx=ctx,
        input_output_aliases={1: 1},
    )(x, ws, phase)
    return out, ws_new


def ll_all_gather_op(
    x: jax.Array,
    steps: int = 1,
    axis: str = "tp",
    ctx: DistContext | None = None,
) -> jax.Array:
    """Host-level wrapper for tests/benchmarks: runs ``steps``
    back-to-back LL all-gathers (exercising the phase/ack discipline)
    and returns the final gathered array."""
    ctx = ctx or current_context()
    n = ctx.axis_size(axis)

    def body(xi):
        ws = ll_all_gather_workspace(n, xi.shape[0], xi.shape[1], xi.dtype)
        out = None
        for s in range(steps):
            out, ws = ll_all_gather(xi, ws, jnp.int32(s), axis=axis, ctx=ctx)
        return out

    f = ctx.shard_map(body, in_specs=P(axis, None), out_specs=P(None, None))
    return f(x)
