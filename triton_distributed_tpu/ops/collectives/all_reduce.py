"""AllReduce: method enum + size-based auto dispatch, Pallas + XLA paths.

Parity: reference ``kernels/nvidia/allreduce.py`` (1,208 LoC: double-tree
:215, one-shot :333-443, two-shot :447-717) and the method registry
``kernels/allreduce.py:28-61`` with ``get_auto_allreduce_method``
(:1101) picking by message size.

TPU translation: the reference's multimem/NVLS switch reductions have no
ICI analog (SURVEY.md §7 hard parts) — the latency-optimal small-message
method here is ONE_SHOT (single-hop full-mesh exchange + local reduce)
and the bandwidth method is TWO_SHOT (ring reduce-scatter + ring
all-gather), which is also how XLA lowers large psums over ICI.
"""

from __future__ import annotations

import enum
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from triton_distributed_tpu import language as dl
from triton_distributed_tpu.ops.common import (
    device_initiable,
    VMEM_COMM_MAX_BYTES,
    comm_pallas_call,
    next_collective_id,
)
from triton_distributed_tpu.ops.collectives.all_gather import (
    AllGatherMethod,
    all_gather,
)
from triton_distributed_tpu.ops.collectives.reduce_scatter import (
    ReduceScatterMethod,
    reduce_scatter,
)
from triton_distributed_tpu.runtime.mesh import DistContext, current_context


class AllReduceMethod(enum.Enum):
    """Parity: ``kernels/allreduce.py:28-41``."""

    AUTO = "auto"
    XLA = "xla"  # jax.lax.psum — XLA's own ICI collective
    ONE_SHOT = "one_shot"  # full-mesh exchange + local reduce (small msgs)
    TWO_SHOT = "two_shot"  # ring RS + ring AG (large msgs)
    DOUBLING = "doubling"  # recursive doubling — log-depth (mid msgs)


_ONESHOT_COLLECTIVE_ID = next_collective_id()
_DOUBLING_COLLECTIVE_ID = next_collective_id()

# Below this payload size the single-hop exchange beats the ring's
# 2(n-1) hops (parity: get_auto_allreduce_method, allreduce.py:1101).
_ONE_SHOT_MAX_BYTES = 256 * 1024

# Band where log-depth beats both: above the one-shot sweet spot (n
# simultaneous incoming puts congest a small mesh) but below where the
# ring's 2·(n-1)/n bytes-per-rank bandwidth optimality dominates the
# log₂(n) hop saving.
_DOUBLING_MAX_BYTES = 1024 * 1024


def get_auto_allreduce_method(nbytes: int, n: int) -> AllReduceMethod:
    if nbytes <= _ONE_SHOT_MAX_BYTES:
        return AllReduceMethod.ONE_SHOT
    if nbytes <= _DOUBLING_MAX_BYTES and n & (n - 1) == 0:
        return AllReduceMethod.DOUBLING
    # TWO_SHOT composes ring RS + ring AG; above the VMEM ceiling the RS
    # leg switches to its HBM-slot variant, so no payload cap remains.
    return AllReduceMethod.TWO_SHOT


def _one_shot_kernel(
    x_ref, o_ref, gather, send_sems, recv_sems, *,
    axis: str, straggler_rank: int | None = None, straggler_nanos: int = 0,
):
    """Push local data to every peer's slot, then reduce locally.

    Parity: one-shot push ``allreduce.py:333`` (every rank broadcasts,
    every rank reduces all n copies); straggler fixture parity:
    ``_run_straggler`` (``allreduce.py:137``).
    """
    me = dl.rank(axis)
    n = dl.num_ranks(axis)

    dl.barrier_all(axis)  # peers' gather slots must exist before any put
    dl.straggle_if_rank(straggler_rank, axis, straggler_nanos)
    gather[me] = x_ref[:]
    dmas = []
    for i in range(1, n):
        peer = jax.lax.rem(me + i, n)
        dmas.append(
            dl.put_signal(
                gather.at[me], gather.at[me], peer,
                send_sems.at[i - 1], recv_sems, axis=axis,
            )
        )
    for _ in range(1, n):
        dl.wait_recv(recv_sems, gather.at[me])
    dl.quiet(*dmas)

    acc = gather[0].astype(jnp.float32)
    for i in range(1, n):
        acc = acc + gather[i].astype(jnp.float32)
    o_ref[:] = acc.astype(o_ref.dtype)


def _doubling_kernel(
    x_ref, o_ref, src, recv, send_sems, recv_sems, *,
    axis: str, straggler_rank: int | None = None, straggler_nanos: int = 0,
):
    """Recursive halving-doubling (butterfly) allreduce: log₂(n) rounds,
    round k exchanges the running sum with partner ``me XOR 2^k``.

    This is the TPU redesign of the reference's double-binary-tree method
    (``allreduce.py:145-215``): same log-depth latency class, but the
    butterfly keeps every rank's program identical (partner is computed
    from the rank id, no parent/child tables) — a better fit for SPMD
    Pallas where all ranks trace one kernel. Power-of-two axis sizes
    only; AUTO falls back to ring methods otherwise.
    """
    me = dl.rank(axis)
    n = dl.num_ranks(axis)
    lg = n.bit_length() - 1  # n is a power of two

    dl.barrier_all(axis)  # peers' recv slots must exist before any put
    dl.straggle_if_rank(straggler_rank, axis, straggler_nanos)

    acc = x_ref[:].astype(jnp.float32)
    dmas = []
    for k in range(lg):
        partner = jax.lax.bitwise_xor(me, 1 << k)
        src[k] = acc.astype(src.dtype)
        dmas.append(
            dl.put_signal(
                src.at[k], recv.at[k], partner,
                send_sems.at[k], recv_sems.at[k], axis=axis,
            )
        )
        dl.wait_recv(recv_sems.at[k], recv.at[k])
        acc = acc + recv[k].astype(jnp.float32)
    dl.quiet(*dmas)
    o_ref[:] = acc.astype(o_ref.dtype)


def _straggle_entry(x, axis, straggler_rank, straggler_nanos, ctx):
    """Identity op that lags one rank (race fixture for composed paths
    whose leg kernels carry no injection params). Static no-op when no
    straggler is configured — production traces are untouched."""
    if straggler_rank is None or not straggler_nanos:
        return x

    def kern(x_ref, o_ref, sem):
        dl.straggle_if_rank(straggler_rank, axis, straggler_nanos)
        # HBM->HBM DMA identity: no VMEM residency, so the fixture also
        # works on the >VMEM-ceiling band the HBM-staged RS leg serves.
        cp = pltpu.make_async_copy(x_ref, o_ref, sem)
        cp.start()
        cp.wait()

    return comm_pallas_call(
        kern,
        jax.ShapeDtypeStruct(x.shape, x.dtype),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[pltpu.SemaphoreType.DMA(())],
        ctx=ctx,
    )(x)


def all_reduce(
    x: jax.Array,
    axis: str = "tp",
    method: AllReduceMethod = AllReduceMethod.AUTO,
    ctx: DistContext | None = None,
    *,
    straggler_rank: int | None = None,
    straggler_nanos: int = 500_000,
) -> jax.Array:
    """Sum ``x`` across ``axis``; every device gets the full result.

    Call inside ``shard_map``; ``x`` is this device's partial sum.
    ``straggler_rank`` lags one rank's pushes (stress fixture; parity:
    ``_run_straggler``).
    """
    n = jax.lax.axis_size(axis)
    nbytes = x.size * x.dtype.itemsize
    if method == AllReduceMethod.AUTO:
        method = (
            get_auto_allreduce_method(nbytes, n)
            if device_initiable(axis, ctx) and x.ndim >= 2
            else AllReduceMethod.XLA
        )

    if method == AllReduceMethod.XLA:
        return jax.lax.psum(x, axis)

    if method == AllReduceMethod.ONE_SHOT:
        if x.ndim < 2:
            raise ValueError("pallas all_reduce needs >=2D input")
        return comm_pallas_call(
            functools.partial(
                _one_shot_kernel, axis=axis,
                straggler_rank=straggler_rank,
                straggler_nanos=straggler_nanos,
            ),
            jax.ShapeDtypeStruct(x.shape, x.dtype),
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            scratch_shapes=[
                pltpu.VMEM((n, *x.shape), x.dtype),
                pltpu.SemaphoreType.DMA((max(n - 1, 1),)),
                pltpu.SemaphoreType.DMA(()),
            ],
            collective_id=_ONESHOT_COLLECTIVE_ID,
            ctx=ctx,
        )(x)

    if method == AllReduceMethod.DOUBLING:
        if x.ndim < 2:
            raise ValueError("pallas all_reduce needs >=2D input")
        if n & (n - 1):
            raise ValueError(f"DOUBLING needs power-of-two axis, got {n}")
        lg = max(n.bit_length() - 1, 1)
        return comm_pallas_call(
            functools.partial(
                _doubling_kernel, axis=axis,
                straggler_rank=straggler_rank,
                straggler_nanos=straggler_nanos,
            ),
            jax.ShapeDtypeStruct(x.shape, x.dtype),
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            scratch_shapes=[
                pltpu.VMEM((lg, *x.shape), x.dtype),  # per-round send
                pltpu.VMEM((lg, *x.shape), x.dtype),  # per-round recv
                pltpu.SemaphoreType.DMA((lg,)),
                pltpu.SemaphoreType.DMA((lg,)),
            ],
            collective_id=_DOUBLING_COLLECTIVE_ID,
            ctx=ctx,
        )(x)

    if method == AllReduceMethod.TWO_SHOT:
        # Ring reduce-scatter then ring all-gather; rows must split n-ways.
        if x.shape[0] % n:
            # ONE_SHOT gathers n copies into VMEM — only sane when small;
            # large indivisible payloads go to XLA.
            if nbytes <= _ONE_SHOT_MAX_BYTES:
                return all_reduce(
                    x, axis, AllReduceMethod.ONE_SHOT, ctx,
                    straggler_rank=straggler_rank,
                    straggler_nanos=straggler_nanos,
                )
            return jax.lax.psum(x, axis)
        # Straggler fixture on a COMPOSED path: the legs' kernels carry
        # no injection params, so the lag is applied as a delay-only
        # kernel that skews this rank's ENTRY into the RS leg — the
        # same late-producer class the monolithic kernels provoke
        # in-kernel.
        x = _straggle_entry(x, axis, straggler_rank, straggler_nanos, ctx)
        rs_method = (
            # Both ICI directions on the RS leg too (demotes itself on
            # degenerate shapes) — the AG leg is already bidirectional.
            ReduceScatterMethod.PALLAS_BIDIR_RING
            if nbytes <= VMEM_COMM_MAX_BYTES
            else ReduceScatterMethod.PALLAS_RING_HBM  # no VMEM ceiling
        )
        reduced = reduce_scatter(x, axis, rs_method, ctx)
        return all_gather(reduced, axis, AllGatherMethod.PALLAS_BIDIR_RING, ctx)

    raise ValueError(f"unknown method {method}")


def all_reduce_op(
    x: jax.Array,
    axis: str = "tp",
    method: AllReduceMethod = AllReduceMethod.AUTO,
    ctx: DistContext | None = None,
) -> jax.Array:
    """Host-level wrapper: ``x[i]`` is device i's partial array (host
    shape ``[n, ...]``); returns the summed array (replicated)."""
    ctx = ctx or current_context()
    rest = [None] * (x.ndim - 1)

    def body(xi):
        return all_reduce(xi[0], axis=axis, method=method, ctx=ctx)

    f = ctx.shard_map(body, in_specs=P(axis, *rest), out_specs=P(*rest))
    return f(x)
