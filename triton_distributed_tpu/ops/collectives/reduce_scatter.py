"""ReduceScatter: XLA path + device-initiated Pallas ring over ICI.

Parity: reference ``kernels/nvidia/reduce_scatter.py`` —
``ReduceScatter2DContext``:47, intra-node ring push variants :285-480,
``kernel_ring_reduce_*``:674-744. The reference's 2-level multinode split
(:828, intra-node ring then inter-node p2p) maps on TPU to: Pallas ring
within the ICI slice, XLA collectives across DCN (see SURVEY.md §2.4).

Ring protocol (sum): at step s (0..n-2) device r sends the partial
accumulator for chunk ``(r-1-s) mod n`` to its right neighbor, receives
chunk ``(r-2-s) mod n`` and adds its local contribution; after n-1 steps
device r holds the fully-reduced chunk r. Each step receives into a
distinct buffer slot, so no cross-step flow control is needed.
"""

from __future__ import annotations

import enum
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from triton_distributed_tpu import language as dl
from triton_distributed_tpu.ops.common import (
    device_initiable,
    comm_pallas_call,
    next_collective_id,
)
from triton_distributed_tpu.runtime.mesh import DistContext, current_context


class ReduceScatterMethod(enum.Enum):
    AUTO = "auto"
    XLA = "xla"
    ONE_SHOT = "one_shot"                # single-hop scatter + local add
    PALLAS_RING = "pallas_ring"          # VMEM-resident (small payloads)
    PALLAS_BIDIR_RING = "pallas_bidir_ring"  # counter-rotating half-chunks
    PALLAS_RING_HBM = "pallas_ring_hbm"  # HBM slots + tiled VMEM adds


_RS_COLLECTIVE_ID = next_collective_id()
_RS_HBM_COLLECTIVE_ID = next_collective_id()
_RS_ONESHOT_COLLECTIVE_ID = next_collective_id()

# Per-buffer budget for the HBM ring's VMEM add tiles.
_RS_TILE_BUDGET = 1024 * 1024

# Below this total payload the single-hop scatter beats the ring's n-1
# serialized hops (same latency-class crossover as the allreduce
# one-shot; parity: the reference's method dispatch by message size,
# ``reduce_scatter.py:857`` choosing a2a-style vs ring consumers).
_RS_ONE_SHOT_MAX_BYTES = 256 * 1024


def _ring_rs_kernel(x_ref, o_ref, bufs, send_sems, recv_sems, *, axis: str):
    me = dl.rank(axis)
    n = dl.num_ranks(axis)
    m_per = o_ref.shape[0]
    right = jax.lax.rem(me + 1, n)

    def chunk(idx):
        return pl.ds(idx * m_per, m_per)

    dl.barrier_all(axis)  # peers' bufs must exist before any put
    dmas = []
    for s in range(n - 1):
        send_chunk = jax.lax.rem(me - 1 - s + 2 * n, n)
        src = x_ref.at[chunk(send_chunk)] if s == 0 else bufs.at[s - 1]
        dmas.append(
            dl.put_signal(
                src, bufs.at[s], right,
                send_sems.at[s], recv_sems.at[s], axis=axis,
            )
        )
        dl.wait_recv(recv_sems.at[s], bufs.at[s])
        recv_chunk = jax.lax.rem(me - 2 - s + 2 * n, n)
        bufs[s] = bufs[s] + x_ref[chunk(recv_chunk)]
    dl.quiet(*dmas)
    if n > 1:
        o_ref[:] = bufs[n - 2]
    else:
        o_ref[:] = x_ref[:]


def _bidir_ring_rs_kernel(
    x_ref, o_ref, bufs, send_sems, recv_sems, *, axis: str
):
    """Counter-rotating dual rings: each chunk's top half reduces
    clockwise, bottom half counter-clockwise — both ICI directions
    carry payload, half the wire time of the single ring (the same
    lever as the bidir all-gather and the dual-ring ``gemm_rs``; the
    anchored perf model's default RS estimate assumes exactly this).

    Per direction the algebra mirrors :func:`_ring_rs_kernel`: cw at
    step s sends the accumulated top of chunk ``me-1-s`` right and
    receives ``me-2-s`` from the left; ccw sends the bottom of
    ``me+1+s`` left and receives ``me+2+s`` from the right; both land
    on the own chunk after n-1 steps.
    """
    me = dl.rank(axis)
    n = dl.num_ranks(axis)
    m_per = o_ref.shape[0]
    half = m_per // 2
    right = jax.lax.rem(me + 1, n)
    left = jax.lax.rem(me - 1 + n, n)

    def top(idx):
        return pl.ds(idx * m_per, half)

    def bot(idx):
        return pl.ds(idx * m_per + half, m_per - half)

    dl.barrier_all(axis)  # peers' bufs must exist before any put
    dmas = []
    for s in range(n - 1):
        cw_send = jax.lax.rem(me - 1 - s + 2 * n, n)
        ccw_send = jax.lax.rem(me + 1 + s, n)
        src_cw = x_ref.at[top(cw_send)] if s == 0 else bufs.at[0, s - 1]
        src_ccw = x_ref.at[bot(ccw_send)] if s == 0 else bufs.at[1, s - 1]
        dmas.append(
            dl.put_signal(
                src_cw, bufs.at[0, s], right,
                send_sems.at[0, s], recv_sems.at[0, s], axis=axis,
            )
        )
        dmas.append(
            dl.put_signal(
                src_ccw, bufs.at[1, s], left,
                send_sems.at[1, s], recv_sems.at[1, s], axis=axis,
            )
        )
        dl.wait_recv(recv_sems.at[0, s], bufs.at[0, s])
        cw_recv = jax.lax.rem(me - 2 - s + 2 * n, n)
        bufs[0, s] = bufs[0, s] + x_ref[top(cw_recv)]
        dl.wait_recv(recv_sems.at[1, s], bufs.at[1, s])
        ccw_recv = jax.lax.rem(me + 2 + s, n)
        bufs[1, s] = bufs[1, s] + x_ref[bot(ccw_recv)]
    dl.quiet(*dmas)
    if n > 1:
        o_ref[pl.ds(0, half)] = bufs[0, n - 2]
        o_ref[pl.ds(half, m_per - half)] = bufs[1, n - 2]
    else:
        o_ref[:] = x_ref[:]


def _one_shot_rs_kernel(x_ref, o_ref, bufs, send_sems, recv_sems, *, axis: str):
    """Single-hop scatter + local add — the latency method.

    Each device pushes chunk ``r`` of its partials straight to device
    ``r`` (one software step, all sends in flight at once), then adds
    the ``n`` received contributions locally in f32. Beats the ring's
    ``n-1`` serialized hops for small messages; loses above the
    crossover because non-neighbor hops share ICI links. Parity role:
    the reference's a2a-style reduce-scatter consumer
    (``reduce_scatter.py:674`` ``kernel_ring_reduce_tma`` run in its
    a2a ordering) and the one-shot allreduce's latency class.
    """
    me = dl.rank(axis)
    n = dl.num_ranks(axis)
    m_per = o_ref.shape[0]

    def chunk(idx):
        return pl.ds(idx * m_per, m_per)

    dl.barrier_all(axis)  # peers' bufs must exist before any put
    bufs[me] = x_ref[chunk(me)]
    dmas = []
    for p in range(1, n):
        peer = jax.lax.rem(me + p, n)
        # Our chunk destined for ``peer`` lands in peer's bufs[me].
        dmas.append(
            dl.put_signal(
                x_ref.at[chunk(peer)], bufs.at[me], peer,
                send_sems.at[p - 1], recv_sems, axis=axis,
            )
        )
    for _ in range(1, n):
        dl.wait_recv(recv_sems, bufs.at[0])
    dl.quiet(*dmas)

    acc = bufs[0].astype(jnp.float32)
    for i in range(1, n):
        acc = acc + bufs[i].astype(jnp.float32)
    o_ref[:] = acc.astype(o_ref.dtype)


def _ring_rs_hbm_kernel(
    x_ref,      # [n*m_per, C] ANY/HBM — local partial sums
    o_ref,      # [m_per, C] ANY/HBM — reduced own chunk
    bufs,       # [n-1, m_per, C] ANY/HBM output — per-step inbound slots
    vin,        # [2, tile_r, C] VMEM — inbound tile stage
    vx,         # [2, tile_r, C] VMEM — local-contribution tile stage
    vout,       # [2, tile_r, C] VMEM — added tile (DMA'd out)
    in_sems,    # DMA (2, 2)
    out_sems,   # DMA (2,)
    send_sems,  # DMA (n-1,)
    recv_sems,  # DMA (n-1,)
    *,
    axis: str,
):
    """HBM-slot ring: same protocol as :func:`_ring_rs_kernel` but the
    payload never resident-stages — adds stream through (tile_r × C)
    VMEM tiles, lifting the VMEM payload ceiling entirely (VERDICT r1
    #5; parity role: reference ``kernel_ring_reduce_*``:674-744 which
    likewise tiles its reduce loop over L2-resident chunks)."""
    me = dl.rank(axis)
    n = dl.num_ranks(axis)
    s = pl.program_id(0)
    t = pl.program_id(1)
    num_t = pl.num_programs(1)
    m_per = o_ref.shape[0]
    tile_r = vin.shape[1]
    right = jax.lax.rem(me + 1, n)
    p = jax.lax.rem(t, 2)

    def chunk(idx):
        return pl.ds(idx * m_per, m_per)

    recv_chunk = jax.lax.rem(me - 2 - s + 2 * n, n)

    def rows(ti):
        return pl.ds(ti * tile_r, tile_r)

    def stage(ti, par):
        return (
            pltpu.make_async_copy(
                bufs.at[s, rows(ti)], vin.at[par], in_sems.at[par, 0]
            ),
            pltpu.make_async_copy(
                x_ref.at[pl.ds(recv_chunk * m_per + ti * tile_r, tile_r)],
                vx.at[par],
                in_sems.at[par, 1],
            ),
        )

    @pl.when(t == 0)
    def _step_begin():
        @pl.when(s == 0)
        def _():
            dl.barrier_all(axis)  # peers' bufs must exist before any put
            dl.put_signal(
                x_ref.at[chunk(jax.lax.rem(me - 1 + n, n))], bufs.at[0],
                right, send_sems.at[0], recv_sems.at[0], axis=axis,
            )

        @pl.when(s > 0)
        def _():
            # bufs[s-1] finished its adds at step s-1's last tile.
            dl.put_signal(
                bufs.at[s - 1], bufs.at[s], right,
                send_sems.at[s], recv_sems.at[s], axis=axis,
            )

        dl.wait_recv(recv_sems.at[s], bufs.at[s])
        a, b = stage(0, 0)
        a.start()
        b.start()
        a.wait()
        b.wait()

    @pl.when(t > 0)
    def _land():
        a, b = stage(0, p)  # shapes only; waits tile t started at t-1
        a.wait()
        b.wait()

    @pl.when(t + 1 < num_t)
    def _prefetch():
        a, b = stage(t + 1, 1 - p)
        a.start()
        b.start()

    @pl.when(t >= 2)
    def _drain_out():
        pltpu.make_async_copy(
            vout.at[p], vout.at[p], out_sems.at[p]
        ).wait()

    vout[p] = vin[p] + vx[p]

    @pl.when(s < n - 2)
    def _to_buf():
        pltpu.make_async_copy(
            vout.at[p], bufs.at[s, rows(t)], out_sems.at[p]
        ).start()

    @pl.when(s == n - 2)
    def _to_out():
        # Last step's added tiles land straight in the output.
        pltpu.make_async_copy(
            vout.at[p], o_ref.at[rows(t)], out_sems.at[p]
        ).start()

    @pl.when(t == num_t - 1)
    def _step_end():
        pltpu.make_async_copy(
            vout.at[p], vout.at[p], out_sems.at[p]
        ).wait()

        @pl.when(num_t > 1)
        def _():
            pltpu.make_async_copy(
                vout.at[1 - p], vout.at[1 - p], out_sems.at[1 - p]
            ).wait()

        @pl.when(s == n - 2)
        def _drain_sends():
            for q in range(n - 1):
                pltpu.make_async_copy(
                    x_ref.at[chunk(0)], x_ref.at[chunk(0)], send_sems.at[q]
                ).wait()


def reduce_scatter(
    x: jax.Array,
    axis: str = "tp",
    method: ReduceScatterMethod = ReduceScatterMethod.AUTO,
    ctx: DistContext | None = None,
) -> jax.Array:
    """Sum-reduce ``x`` across ``axis`` and scatter along the leading dim.

    Call inside ``shard_map``: ``x`` is ``[n*m_per, ...]`` of partial
    sums; result is this device's reduced chunk ``[m_per, ...]``.
    """
    n = jax.lax.axis_size(axis)
    from triton_distributed_tpu.ops.common import VMEM_COMM_MAX_BYTES

    if method == ReduceScatterMethod.AUTO:
        if not device_initiable(axis, ctx) or x.ndim < 2:
            method = ReduceScatterMethod.XLA
        elif x.size * x.dtype.itemsize <= _RS_ONE_SHOT_MAX_BYTES:
            method = ReduceScatterMethod.ONE_SHOT
        elif x.size * x.dtype.itemsize <= VMEM_COMM_MAX_BYTES:
            # Both ICI directions; the demotion guard below handles the
            # degenerate/odd-chunk cases (single source of truth).
            method = ReduceScatterMethod.PALLAS_BIDIR_RING
        else:
            method = ReduceScatterMethod.PALLAS_RING_HBM

    if method == ReduceScatterMethod.XLA:
        return jax.lax.psum_scatter(x, axis, scatter_dimension=0, tiled=True)

    if x.ndim < 2:
        raise ValueError("pallas reduce_scatter needs >=2D input")
    if x.shape[0] % n:
        raise ValueError(f"rows {x.shape[0]} not divisible by axis size {n}")
    m_per = x.shape[0] // n
    out_shape = jax.ShapeDtypeStruct((m_per, *x.shape[1:]), x.dtype)

    if method == ReduceScatterMethod.ONE_SHOT:
        if n == 1:
            return x
        return comm_pallas_call(
            functools.partial(_one_shot_rs_kernel, axis=axis),
            out_shape,
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            scratch_shapes=[
                pltpu.VMEM((n, m_per, *x.shape[1:]), x.dtype),
                pltpu.SemaphoreType.DMA((max(n - 1, 1),)),
                pltpu.SemaphoreType.DMA(()),
            ],
            collective_id=_RS_ONESHOT_COLLECTIVE_ID,
            ctx=ctx,
        )(x)

    if method == ReduceScatterMethod.PALLAS_RING_HBM:
        if n == 1:
            return x
        row_bytes = (x.size // x.shape[0]) * x.dtype.itemsize
        tile_r = m_per
        while tile_r > 8 and tile_r * row_bytes > _RS_TILE_BUDGET:
            tile_r //= 2
        while m_per % tile_r:
            tile_r //= 2
        num_t = m_per // tile_r
        rest = x.shape[1:]
        out, _bufs = comm_pallas_call(
            functools.partial(_ring_rs_hbm_kernel, axis=axis),
            (
                out_shape,
                jax.ShapeDtypeStruct((n - 1, m_per, *rest), x.dtype),
            ),
            grid=(n - 1, num_t),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=(
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(memory_space=pl.ANY),
            ),
            scratch_shapes=[
                pltpu.VMEM((2, tile_r, *rest), x.dtype),
                pltpu.VMEM((2, tile_r, *rest), x.dtype),
                pltpu.VMEM((2, tile_r, *rest), x.dtype),
                pltpu.SemaphoreType.DMA((2, 2)),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((n - 1,)),
                pltpu.SemaphoreType.DMA((n - 1,)),
            ],
            collective_id=_RS_HBM_COLLECTIVE_ID,
            dimension_semantics=("arbitrary", "arbitrary"),
            ctx=ctx,
        )(x)
        return out

    if method == ReduceScatterMethod.PALLAS_BIDIR_RING and (
        m_per < 2 or m_per % 2 or n <= 2
    ):
        # Halves degenerate (or odd chunks would mismatch the fixed
        # half-chunk DMA slot shapes) — single ring covers it.
        method = ReduceScatterMethod.PALLAS_RING

    if method == ReduceScatterMethod.PALLAS_BIDIR_RING:
        half = m_per // 2
        return comm_pallas_call(
            functools.partial(_bidir_ring_rs_kernel, axis=axis),
            out_shape,
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            scratch_shapes=[
                # [direction, step] half-chunk slots.
                pltpu.VMEM((2, max(n - 1, 1), half, *x.shape[1:]), x.dtype),
                pltpu.SemaphoreType.DMA((2, max(n - 1, 1))),
                pltpu.SemaphoreType.DMA((2, max(n - 1, 1))),
            ],
            collective_id=_RS_COLLECTIVE_ID,
            ctx=ctx,
        )(x)

    return comm_pallas_call(
        functools.partial(_ring_rs_kernel, axis=axis),
        out_shape,
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((max(n - 1, 1), m_per, *x.shape[1:]), x.dtype),
            pltpu.SemaphoreType.DMA((max(n - 1, 1),)),
            pltpu.SemaphoreType.DMA((max(n - 1, 1),)),
        ],
        collective_id=_RS_COLLECTIVE_ID,
        ctx=ctx,
    )(x)


def reduce_scatter_op(
    x: jax.Array,
    axis: str = "tp",
    method: ReduceScatterMethod = ReduceScatterMethod.AUTO,
    ctx: DistContext | None = None,
) -> jax.Array:
    """Host-level wrapper: ``x[i]`` is device i's partial-sum array
    ``[n*m_per, ...]`` (host shape ``[n, n*m_per, ...]``); returns the
    summed array, sharded over ``axis`` (host shape ``[n*m_per, ...]``).
    For tests/benchmarks.
    """
    ctx = ctx or current_context()
    rest = [None] * (x.ndim - 2)

    def body(xi):
        return reduce_scatter(xi[0], axis=axis, method=method, ctx=ctx)

    f = ctx.shard_map(
        body,
        in_specs=P(axis, None, *rest),
        out_specs=P(axis, *rest),
    )
    return f(x)
