"""ReduceScatter: XLA path + device-initiated Pallas ring over ICI.

Parity: reference ``kernels/nvidia/reduce_scatter.py`` —
``ReduceScatter2DContext``:47, intra-node ring push variants :285-480,
``kernel_ring_reduce_*``:674-744. The reference's 2-level multinode split
(:828, intra-node ring then inter-node p2p) maps on TPU to: Pallas ring
within the ICI slice, XLA collectives across DCN (see SURVEY.md §2.4).

Ring protocol (sum): at step s (0..n-2) device r sends the partial
accumulator for chunk ``(r-1-s) mod n`` to its right neighbor, receives
chunk ``(r-2-s) mod n`` and adds its local contribution; after n-1 steps
device r holds the fully-reduced chunk r. Each step receives into a
distinct buffer slot, so no cross-step flow control is needed.
"""

from __future__ import annotations

import enum
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from triton_distributed_tpu import language as dl
from triton_distributed_tpu.ops.common import (
    comm_pallas_call,
    next_collective_id,
    _on_tpu,
)
from triton_distributed_tpu.runtime.mesh import DistContext, current_context


class ReduceScatterMethod(enum.Enum):
    AUTO = "auto"
    XLA = "xla"
    PALLAS_RING = "pallas_ring"


_RS_COLLECTIVE_ID = next_collective_id()


def _ring_rs_kernel(x_ref, o_ref, bufs, send_sems, recv_sems, *, axis: str):
    me = dl.rank(axis)
    n = dl.num_ranks(axis)
    m_per = o_ref.shape[0]
    right = jax.lax.rem(me + 1, n)

    def chunk(idx):
        return pl.ds(idx * m_per, m_per)

    dl.barrier_all(axis)  # peers' bufs must exist before any put
    dmas = []
    for s in range(n - 1):
        send_chunk = jax.lax.rem(me - 1 - s + 2 * n, n)
        src = x_ref.at[chunk(send_chunk)] if s == 0 else bufs.at[s - 1]
        dmas.append(
            dl.put_signal(
                src, bufs.at[s], right,
                send_sems.at[s], recv_sems.at[s], axis=axis,
            )
        )
        dl.wait_recv(recv_sems.at[s], bufs.at[s])
        recv_chunk = jax.lax.rem(me - 2 - s + 2 * n, n)
        bufs[s] = bufs[s] + x_ref[chunk(recv_chunk)]
    dl.quiet(*dmas)
    if n > 1:
        o_ref[:] = bufs[n - 2]
    else:
        o_ref[:] = x_ref[:]


def reduce_scatter(
    x: jax.Array,
    axis: str = "tp",
    method: ReduceScatterMethod = ReduceScatterMethod.AUTO,
    ctx: DistContext | None = None,
) -> jax.Array:
    """Sum-reduce ``x`` across ``axis`` and scatter along the leading dim.

    Call inside ``shard_map``: ``x`` is ``[n*m_per, ...]`` of partial
    sums; result is this device's reduced chunk ``[m_per, ...]``.
    """
    n = jax.lax.axis_size(axis)
    if method == ReduceScatterMethod.AUTO:
        method = (
            ReduceScatterMethod.PALLAS_RING
            if _on_tpu(ctx)
            else ReduceScatterMethod.XLA
        )

    if method == ReduceScatterMethod.XLA:
        return jax.lax.psum_scatter(x, axis, scatter_dimension=0, tiled=True)

    if x.ndim < 2:
        raise ValueError("pallas reduce_scatter needs >=2D input")
    if x.shape[0] % n:
        raise ValueError(f"rows {x.shape[0]} not divisible by axis size {n}")
    m_per = x.shape[0] // n
    out_shape = jax.ShapeDtypeStruct((m_per, *x.shape[1:]), x.dtype)

    return comm_pallas_call(
        functools.partial(_ring_rs_kernel, axis=axis),
        out_shape,
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((max(n - 1, 1), m_per, *x.shape[1:]), x.dtype),
            pltpu.SemaphoreType.DMA((max(n - 1, 1),)),
            pltpu.SemaphoreType.DMA((max(n - 1, 1),)),
        ],
        collective_id=_RS_COLLECTIVE_ID,
        ctx=ctx,
    )(x)


def reduce_scatter_op(
    x: jax.Array,
    axis: str = "tp",
    method: ReduceScatterMethod = ReduceScatterMethod.AUTO,
    ctx: DistContext | None = None,
) -> jax.Array:
    """Host-level wrapper: ``x[i]`` is device i's partial-sum array
    ``[n*m_per, ...]`` (host shape ``[n, n*m_per, ...]``); returns the
    summed array, sharded over ``axis`` (host shape ``[n*m_per, ...]``).
    For tests/benchmarks.
    """
    ctx = ctx or current_context()
    rest = [None] * (x.ndim - 2)

    def body(xi):
        return reduce_scatter(xi[0], axis=axis, method=method, ctx=ctx)

    f = ctx.shard_map(
        body,
        in_specs=P(axis, None, *rest),
        out_specs=P(axis, *rest),
    )
    return f(x)
