"""Qwen3 megakernel model: the whole TP decode step as ONE Pallas kernel.

Parity: reference ``mega_triton_kernel/models/qwen3.py`` —
``Qwen3Model``:108 building fc1/qkv/attn/allreduce/… tasks for every
layer and running the persistent kernel per decode step (the top rung of
the reference's decode ladder, ``docs/mega_triton_kernel.md:27-37``).

Reuses :class:`~triton_distributed_tpu.models.qwen.Qwen3` for parameters
and sharding, so the megakernel is a drop-in third decode mode next to
``xla`` / ``pallas``.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from triton_distributed_tpu.megakernel.code_generator import MegaConfig, MegaDims
from triton_distributed_tpu.megakernel.model_builder import ModelBuilder
from triton_distributed_tpu.megakernel.scheduler import SchedulePolicy
from triton_distributed_tpu.models.kv_cache import KVCache, cache_specs
from triton_distributed_tpu.models.paged_kv_cache import (
    PagedKVCache,
    paged_cache_specs,
)
from triton_distributed_tpu.models import paged_kv_cache as _paged
from triton_distributed_tpu.models.qwen import Qwen3, Qwen3Params, pad_vocab
from triton_distributed_tpu.runtime.pytree import register_param_dataclass


@dataclasses.dataclass
class Q8Params:
    """Weight-only int8 megakernel parameters (``MegaConfig.wq8``).

    The five projection weights are symmetric per-OUTPUT-channel int8
    (scale = max|w| / 127 over the contraction axis, computed per TP
    shard — column shards scale their local columns; row shards
    (``wo``/``w2``, partial sums) carry a per-RANK scale plane stacked
    on a tp-sharded axis and dequantize before the allreduce, which is
    exact). Everything else (embed, norms) stays full precision —
    including ``embed`` when the checkpoint ties it to ``lm_head``:
    the tied tensor is stored twice, once bf16 for the gather and once
    int8 for the head stream.
    """

    embed: jax.Array    # [V, d] full precision
    wqkv: jax.Array     # [L, d, qkv_loc] int8
    wo: jax.Array       # [L, o_k, d] int8
    w1: jax.Array       # [L, d, 2*f_loc] int8
    w2: jax.Array       # [L, f_loc, d] int8
    lm_head: jax.Array  # [d, v_loc] int8
    sc_qkv: jax.Array   # [L, 1, qkv_loc] f32
    sc_o: jax.Array     # [L, tp, d] f32 globally; [L, 1, d] per shard
    sc_w1: jax.Array    # [L, 1, 2*f_loc] f32
    sc_w2: jax.Array    # [L, tp, d] f32 globally; [L, 1, d] per shard
    sc_lm: jax.Array    # [1, v_loc] f32
    ln1: jax.Array
    ln2: jax.Array
    norm: jax.Array
    qn: jax.Array
    kn: jax.Array


register_param_dataclass(Q8Params, [
    "embed", "wqkv", "wo", "w1", "w2", "lm_head",
    "sc_qkv", "sc_o", "sc_w1", "sc_w2", "sc_lm",
    "ln1", "ln2", "norm", "qn", "kn",
])


def _quantize_shard(params: Qwen3Params) -> Q8Params:
    """Per-shard quantization (runs inside shard_map, jitted once)."""
    lp = params.layers

    def q(w, axis):
        s = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=axis, keepdims=True)
        s = jnp.maximum(s / 127.0, 1e-12)
        wi = jnp.clip(
            jnp.round(w.astype(jnp.float32) / s), -127, 127
        ).astype(jnp.int8)
        return wi, s

    wqkv8, sq = q(lp.attn.wqkv, 1)
    wo8, so = q(lp.attn.wo, 1)
    w18, s1 = q(lp.mlp.w1, 1)
    w28, s2 = q(lp.mlp.w2, 1)
    lm8, slm = q(params.lm_head, 0)
    return Q8Params(
        embed=params.embed, wqkv=wqkv8, wo=wo8, w1=w18, w2=w28,
        lm_head=lm8, sc_qkv=sq, sc_o=so, sc_w1=s1, sc_w2=s2, sc_lm=slm,
        ln1=lp.ln1, ln2=lp.ln2, norm=params.norm,
        qn=lp.attn.q_norm, kn=lp.attn.k_norm,
    )


@dataclasses.dataclass
class MoEMegaParams:
    """EP-resharded megakernel parameters for Qwen3MoE decode.

    The serving model keeps the TP expert sharding (every rank holds
    its ``f_loc`` column shard of EVERY expert — ``layers/tp_moe.py``),
    which is what the unfused decode path runs. The megernel's MoE
    graph instead streams whole experts (one MOE_FFN task per LOCAL
    expert, full FFN width) so the combine exchange carries true
    per-expert-owner partials — EXPERT-parallel sharding. This pytree
    is that resharding, built once from ``model.params`` device-side
    (the Q8Params pattern): w1/w2 gather their f shards and keep only
    this rank's ``E/n`` experts — per-rank HBM bytes are unchanged
    (E·3df/n either way) — and the router stays replicated.
    """

    embed: jax.Array    # [V, d] replicated
    wqkv: jax.Array     # [L, d, qkv_loc]
    wo: jax.Array       # [L, o_k, d]
    w1: jax.Array       # [L, E_loc, d, 2f] — gate|up fused, FULL width
    w2: jax.Array       # [L, E_loc, f, d]
    wrouter: jax.Array  # [L, d, E] replicated
    lm_head: jax.Array  # [d, v_loc]
    ln1: jax.Array
    ln2: jax.Array
    norm: jax.Array
    qn: jax.Array
    kn: jax.Array


register_param_dataclass(MoEMegaParams, [
    "embed", "wqkv", "wo", "w1", "w2", "wrouter", "lm_head",
    "ln1", "ln2", "norm", "qn", "kn",
])


def _moe_reshard_shard(params: Qwen3Params, *, axis: str, n: int):
    """Per-shard TP→EP expert resharding (runs inside shard_map,
    jitted once): an expert↔f-shard ALL-TO-ALL — rank r sends its f
    columns of expert group g to rank g and receives every rank's f
    columns of ITS group — then restore the gate-contiguous [d, 2f]
    fused layout. All-to-all (not gather-then-slice) keeps peak memory
    at the FINAL size: a full [L, E, d, 2f] gather would transiently
    hold n× each rank's steady-state MLP bytes, which at production
    expert counts is exactly the HBM a 1/n-sized shard plan doesn't
    have."""
    lp = params.layers
    mlp = lp.mlp  # TPMoEParams
    L, E, d, two_f_loc = mlp.w1.shape
    f_loc = two_f_loc // 2
    epr = E // n
    if n > 1:
        # w1 [L, E, d, 2f_loc] → [L, E/n, d, n·2f_loc], received
        # f-shards concatenated in source-rank order: [g0|u0|g1|u1|…].
        w1_ep = jax.lax.all_to_all(
            mlp.w1, axis, split_axis=1, concat_axis=3, tiled=True
        )
        # Reorder to [gate_full | up_full] (shard slices concatenate
        # back into the original column order).
        w1_ep = w1_ep.reshape(L, epr, d, n, 2, f_loc)
        w1_ep = jnp.swapaxes(w1_ep, 3, 4).reshape(
            L, epr, d, 2 * n * f_loc
        )
        # w2 [L, E, f_loc, d] → [L, E/n, f, d] (plain f split: rank
        # order IS the original row order, no reorder needed).
        w2_ep = jax.lax.all_to_all(
            mlp.w2, axis, split_axis=1, concat_axis=2, tiled=True
        )
    else:
        w1_ep, w2_ep = mlp.w1, mlp.w2
    return MoEMegaParams(
        embed=params.embed, wqkv=lp.attn.wqkv, wo=lp.attn.wo,
        w1=w1_ep, w2=w2_ep, wrouter=mlp.w_router,
        lm_head=params.lm_head, ln1=lp.ln1, ln2=lp.ln2,
        norm=params.norm, qn=lp.attn.q_norm, kn=lp.attn.k_norm,
    )


class MegaQwen3:
    """Megakernel decode wrapper around a (loaded) :class:`Qwen3`."""

    def __init__(
        self,
        model: Qwen3,
        *,
        cfg: MegaConfig | None = None,
        policy: SchedulePolicy = SchedulePolicy.ROUND_ROBIN,
    ):
        if model.params is None and not (cfg and cfg.wq8):
            # wq8 decode can run from Q8Params alone (see
            # :meth:`quantized_init` — int8 synthesis that never
            # materializes the bf16 tree); every other path needs the
            # model loaded.
            raise ValueError("load or init Qwen3 params first")
        self.model = model
        self.cfg = cfg or MegaConfig()
        self.policy = policy
        self._jit: dict = {}
        # Scheduled orders by decode_multi_fn cache key (trace
        # consumers read them back via multi_task_order).
        self._orders: dict = {}
        self._last_multi_order = None

    def _dims(
        self, batch: int, s_max: int, page: int = 0,
        kv_quant: bool = False, num_pages: int = 0,
        trace: bool = False,
    ) -> MegaDims:
        m = self.model
        c = m.cfg
        n = m.ctx.axis_size(m.axis)
        # The lm_head's vocab axis is padded to 128·tp by set_params;
        # v_loc follows the padded width (the step wrappers slice the
        # pad logits back off). Without loaded params (the wq8
        # synthetic path) the same padding is computed from the config.
        if m.params is not None:
            v_pad = m.params.lm_head.shape[1]
        else:
            v_pad = pad_vocab(c.vocab_size, n)
        moe = c.num_experts > 0
        return MegaDims(
            batch=batch,
            d=c.hidden_size,
            hq_loc=m.dims.hq_loc,
            hkv_loc=m.dims.hkv_loc,
            head_dim=c.head_dim,
            # MoE streams whole (EP-sharded) experts: f_loc is then the
            # FULL per-expert FFN width, not a TP column shard.
            f_loc=(c.moe_intermediate_size if moe
                   else c.intermediate_size // n),
            v_loc=v_pad // n,
            num_layers=c.num_layers,
            s_max=s_max,
            n_ranks=n,
            rms_eps=c.rms_eps,
            rope_theta=c.rope_theta,
            page=page,
            kv_quant=kv_quant,
            num_pages=num_pages,
            trace=trace,
            num_experts=c.num_experts,
            moe_top_k=c.num_experts_per_tok,
            norm_topk=c.norm_topk_prob,
        )

    @staticmethod
    def _scale_args(cache: PagedKVCache, kv_quant: bool):
        """The trailing scale operands of a quantized pool call:
        ``[L, P, H]`` scale planes reshaped to ``[L, P, 1, H]`` so the
        kernel's dynamic layer/page indices stay on untiled leading
        dims (the norm-weight layout trick)."""
        if not kv_quant:
            return ()
        return (
            cache.k_scale[:, :, None, :], cache.v_scale[:, :, None, :]
        )

    def build(
        self, batch: int, s_max: int, page: int = 0,
        kv_quant: bool = False, num_pages: int = 0,
        trace: bool = False,
    ):
        """Build + schedule the task graph and jit the SPMD step
        (parity: ``Qwen3Model.build_fwd`` + ``compile``). ``page`` > 0
        builds the paged-cache variant (KV read through the page table,
        attention block size = page size); ``kv_quant`` reads an int8
        pool through its per-page scales (dequant in-kernel, appends
        through the quantized_row_scatter protocol — full-width KV
        never materializes). ``trace`` adds the device task tracer's
        ring output (docs/observability.md "Device task tracer"): the
        step then returns ``(logits, cache, trace [tp, 1, T, 8])``;
        untraced builds keep the exact PR 7 operand list and contract."""
        m = self.model
        dims = self._dims(batch, s_max, page, kv_quant, num_pages, trace)
        # (s_blk == page is enforced by MegaConfig.resolve when
        # dims.page is set — single owner of that invariant.)
        mb = ModelBuilder(
            dims, cfg=self.cfg, axis=m.axis, ctx=m.ctx,
            wdtype=m.cfg.dtype, cdtype=m.cfg.dtype,
        )
        mb.build_decoder_graph()
        compiled = mb.compile(self.policy)
        per_shard = compiled.per_shard
        ax = m.axis

        kernel_args, pspecs = self._args_and_specs()

        if page:
            def shard_fn(params: Qwen3Params, tokens, cache: PagedKVCache):
                outs = per_shard(
                    cache.kv_len, tokens, cache.page_table,
                    *kernel_args(params), cache.k_pages, cache.v_pages,
                    *self._scale_args(cache, kv_quant),
                )
                logits, k_rows, v_rows, _toks = outs[:4]
                # Page-table append of the new rows [L, B, hkv, hd]
                # (the kernel never writes the pool — same reasoning as
                # the dense path below; [0] drops the step dim of the
                # single-step build). On a quantized pool, append runs
                # the ONE scale-protocol implementation
                # (quantized_row_scatter: offset-0 reset, grow+requant).
                new_cache = _paged.append(cache, k_rows[0], v_rows[0])
                if trace:  # per-rank ring, stacked on a tp leading dim
                    return logits, new_cache, outs[4][None]
                return logits, new_cache

            specs = paged_cache_specs(ax, quantized=kv_quant)
        else:
            def shard_fn(params: Qwen3Params, tokens, cache: KVCache):
                outs = per_shard(
                    cache.kv_len, tokens,
                    *kernel_args(params), cache.k, cache.v,
                )
                logits, k_rows, v_rows, _toks = outs[:4]
                k_rows, v_rows = k_rows[0], v_rows[0]  # single-step build
                # Append the new rows [L, B, hkv, hd] at each row's
                # position — one dynamic_update_slice per batch row; XLA
                # updates the donated cache in place (the kernel cannot:
                # a one-row write at a dynamic offset in a tiled cache
                # plane is an unaligned slice Mosaic rejects).
                k_new, v_new = cache.k, cache.v
                B = tokens.shape[0]
                for b in range(B):
                    at = (0, b, 0, cache.kv_len[b], 0)
                    k_new = jax.lax.dynamic_update_slice(
                        k_new, k_rows[:, b, :, None, :][:, None], at
                    )
                    v_new = jax.lax.dynamic_update_slice(
                        v_new, v_rows[:, b, :, None, :][:, None], at
                    )
                new_cache = KVCache(
                    k=k_new, v=v_new, kv_len=cache.kv_len + 1
                )
                if trace:
                    return logits, new_cache, outs[4][None]
                return logits, new_cache

            specs = cache_specs(ax)

        out_specs = (P(None, ax), specs)
        if trace:
            out_specs += (P(ax),)
        g = m.ctx.shard_map(
            shard_fn,
            in_specs=(pspecs, P(), specs),
            out_specs=out_specs,
        )
        V = m.cfg.vocab_size

        def f(params, tokens, cache):
            outs = g(params, tokens, cache)
            # Drop vocab-pad logits (zero-weight columns score 0 and
            # could beat real logits under greedy sampling).
            return (outs[0][:, :V], *outs[1:])

        step = jax.jit(f, donate_argnums=(2,))
        return compiled, step, f

    def _q8_specs(self) -> Q8Params:
        ax = self.model.axis
        return Q8Params(
            embed=P(), wqkv=P(None, None, ax), wo=P(None, ax, None),
            w1=P(None, None, ax), w2=P(None, ax, None), lm_head=P(None, ax),
            sc_qkv=P(None, None, ax),
            # Row-sharded weights carry per-RANK scales: local [L, 1, d]
            # planes stack on a tp-sharded middle axis.
            sc_o=P(None, ax, None),
            sc_w1=P(None, None, ax),
            sc_w2=P(None, ax, None),
            sc_lm=P(None, ax),
            ln1=P(), ln2=P(), norm=P(), qn=P(), kn=P(),
        )

    def quantized_params(self) -> Q8Params:
        """The int8 weight pytree ``wq8`` steps take IN PLACE of
        ``model.params`` (quantized once, device-side, per shard;
        cached on this instance)."""
        if getattr(self, "_q8", None) is None:
            m = self.model
            if m.params is None:
                raise ValueError(
                    "no bf16 params to quantize — load/init the model "
                    "first, or synthesize int8 directly with "
                    "quantized_init()"
                )
            f = m.ctx.shard_map(
                _quantize_shard,
                in_specs=(m.param_specs,),
                out_specs=self._q8_specs(),
            )
            self._q8 = jax.jit(f)(m.params)
            jax.block_until_ready(self._q8)
        return self._q8

    def quantized_init(self, key: jax.Array) -> Q8Params:
        """SYNTHETIC per-channel-int8 parameters, generated device-side
        WITHOUT ever materializing the bf16 tree — the path that puts
        an 8B-geometry model on one 16 GB v5e (the bf16 tree alone,
        ~16.4 GB, would exceed HBM; the reference serves 8B across
        8×H800 = 640 GB, ``docs/mega_triton_kernel.md:27-31``).

        Weights are uniform int8 with init-scale-magnitude uniform
        scales, so every DMA/tile/dequant path is production-shaped but
        the logits carry no knowledge — this exists for geometry/perf
        evidence. The cross-checks still bind: single- and multi-step
        chains must agree token-for-token over the same synthetic
        weights. Requires ``MegaConfig(wq8=True)``; fills the same
        cache :meth:`quantized_params` reads."""
        if not self.cfg.wq8:
            raise ValueError("quantized_init requires MegaConfig(wq8=True)")
        m = self.model
        c = m.cfg
        n = m.ctx.axis_size(m.axis)
        hd, d, L, f = c.head_dim, c.hidden_size, c.num_layers, \
            c.intermediate_size
        qkv = (c.num_q_heads + 2 * c.num_kv_heads) * hd
        o_k = c.num_q_heads * hd
        v_pad = pad_vocab(c.vocab_size, n)
        dt = c.dtype

        def build(k):
            ks = iter(jax.random.split(k, 7))

            def w8(*shape):
                return jax.random.randint(
                    next(ks), shape, -127, 128, jnp.int8
                )

            def sc(*shape):
                return jnp.full(shape, 0.02 / 127.0, jnp.float32)

            return Q8Params(
                embed=(jax.random.normal(
                    next(ks), (c.vocab_size, d), jnp.float32
                ) * 0.02).astype(dt),
                wqkv=w8(L, d, qkv), wo=w8(L, o_k, d),
                w1=w8(L, d, 2 * f), w2=w8(L, f, d),
                lm_head=w8(d, v_pad),
                sc_qkv=sc(L, 1, qkv), sc_o=sc(L, n, d),
                sc_w1=sc(L, 1, 2 * f), sc_w2=sc(L, n, d),
                sc_lm=sc(1, v_pad),
                ln1=jnp.ones((L, d), dt), ln2=jnp.ones((L, d), dt),
                norm=jnp.ones((d,), dt),
                qn=jnp.ones((L, hd), dt), kn=jnp.ones((L, hd), dt),
            )

        shardings = jax.tree.map(
            lambda s: m.ctx.sharding(*s), self._q8_specs(),
            is_leaf=lambda x: isinstance(x, P),
        )
        self._q8 = jax.jit(build, out_shardings=shardings)(key)
        jax.block_until_ready(self._q8)
        return self._q8

    @staticmethod
    def _kernel_args_q8(q: Q8Params):
        V, d = q.embed.shape
        if V % 8:
            raise ValueError(f"megakernel needs vocab_size % 8 == 0, got {V}")
        return (
            q.embed.reshape(V // 8, 8, d),
            q.wqkv, q.wo, q.w1, q.w2, q.lm_head,
            q.ln1[:, None, :], q.ln2[:, None, :], q.norm[None, :],
            q.qn[:, None, :], q.kn[:, None, :],
            q.sc_qkv, q.sc_o, q.sc_w1, q.sc_w2, q.sc_lm,
        )

    @staticmethod
    def _kernel_args(params: Qwen3Params):
        lp = params.layers
        V, d = params.embed.shape
        if V % 8:
            raise ValueError(
                f"megakernel needs vocab_size % 8 == 0, got {V}"
            )
        # Per-layer norm weights go in as [L, 1, d] / [L, 1, hd]:
        # the kernel indexes the layer with a traced scalar, and
        # Mosaic only allows dynamic indices on untiled leading
        # dims (a dynamic sublane slice of a [L, d] ref needs a
        # statically 8-aligned index it can't prove).
        return (
            params.embed.reshape(V // 8, 8, d),
            lp.attn.wqkv, lp.attn.wo, lp.mlp.w1, lp.mlp.w2,
            params.lm_head,
            lp.ln1[:, None, :], lp.ln2[:, None, :], params.norm[None, :],
            lp.attn.q_norm[:, None, :], lp.attn.k_norm[:, None, :],
        )

    def _built(self, batch: int, s_max: int, page: int = 0,
               kv_quant: bool = False, num_pages: int = 0,
               trace: bool = False):
        key = (batch, s_max, page, kv_quant, num_pages, trace)
        if key not in self._jit:
            self._jit[key] = self.build(*key)
        return self._jit[key]

    def decode_step(self, tokens: jax.Array, cache):
        """One decode step for the whole batch: ``tokens [B] int32 →
        (logits [B, V] f32, cache)`` — the megakernel rung of the decode
        ladder. Accepts a dense :class:`KVCache` or a
        :class:`PagedKVCache` (pool read through the page table —
        int8-quantized pools dequantize in-kernel via their per-page
        scales)."""
        b = int(tokens.shape[0])
        if isinstance(cache, PagedKVCache):
            page = int(cache.k_pages.shape[3])
            s_max = int(cache.page_table.shape[1]) * page
            step = self._built(
                b, s_max, page, cache.quantized,
                int(cache.k_pages.shape[1]),
            )[1]
        else:
            step = self._built(b, int(cache.k.shape[3]))[1]
        return step(self._step_params(), tokens, cache)

    @property
    def _is_moe(self) -> bool:
        return self.model.cfg.num_experts > 0

    def _args_and_specs(self):
        """(kernel_args fn, shard_map param specs) for this model/cfg:
        Q8Params under ``wq8``, the EP-resharded :class:`MoEMegaParams`
        for MoE models, the plain model tree otherwise."""
        if self.cfg.wq8:
            if self._is_moe:
                raise NotImplementedError(
                    "wq8 does not compose with MoE decode yet"
                )
            return self._kernel_args_q8, self._q8_specs()
        if self._is_moe:
            return self._kernel_args_moe, self._moe_specs()
        return self._kernel_args, self.model.param_specs

    def _moe_specs(self) -> MoEMegaParams:
        ax = self.model.axis
        return MoEMegaParams(
            embed=P(), wqkv=P(None, None, ax), wo=P(None, ax, None),
            # EP: the expert axis is the sharded one; each rank's slice
            # holds its E/n experts at FULL width.
            w1=P(None, ax, None, None), w2=P(None, ax, None, None),
            wrouter=P(), lm_head=P(None, ax),
            ln1=P(), ln2=P(), norm=P(), qn=P(), kn=P(),
        )

    def moe_params(self) -> MoEMegaParams:
        """The EP-resharded pytree MoE steps take in place of
        ``model.params`` (resharded once, device-side, per shard;
        cached on this instance — the ``quantized_params`` pattern)."""
        if getattr(self, "_moe_p", None) is None:
            m = self.model
            if m.params is None:
                raise ValueError("load or init the MoE model first")
            n = m.ctx.axis_size(m.axis)
            if m.cfg.num_experts % n:
                raise ValueError(
                    f"num_experts {m.cfg.num_experts} not divisible by "
                    f"tp={n} (the megakernel EP-shards the expert axis)"
                )
            f = m.ctx.shard_map(
                functools.partial(_moe_reshard_shard, axis=m.axis, n=n),
                in_specs=(m.param_specs,),
                out_specs=self._moe_specs(),
            )
            self._moe_p = jax.jit(f)(m.params)
            jax.block_until_ready(self._moe_p)
        return self._moe_p

    @staticmethod
    def _kernel_args_moe(mp: MoEMegaParams):
        V, d = mp.embed.shape
        if V % 8:
            raise ValueError(
                f"megakernel needs vocab_size % 8 == 0, got {V}"
            )
        return (
            mp.embed.reshape(V // 8, 8, d),
            mp.wqkv, mp.wo, mp.w1, mp.w2, mp.lm_head,
            mp.ln1[:, None, :], mp.ln2[:, None, :], mp.norm[None, :],
            mp.qn[:, None, :], mp.kn[:, None, :],
            # Router weight rides after the norms ([L, d, E] — leading
            # L untiled so the gate can index the traced layer).
            mp.wrouter,
        )

    def _step_params(self):
        """What the built steps take as their first argument: the int8
        pytree under ``wq8``, the EP-resharded MoE tree for MoE models,
        the model's params otherwise."""
        if self.cfg.wq8:
            return self.quantized_params()
        if self._is_moe:
            return self.moe_params()
        return self.model.params

    def decode_fn(self, batch: int, s_max: int, page: int = 0,
                  kv_quant: bool = False, num_pages: int = 0,
                  trace: bool = False):
        """The raw (unjitted) step ``f(params, tokens, cache) →
        (logits, cache)`` — same contract as ``Qwen3.decode_fn``, so
        callers can chain steps inside one jit (``lax.fori_loop`` greedy
        decode) instead of dispatching per step. ``trace`` appends the
        device trace ring to the returns (docs/observability.md)."""
        return self._built(batch, s_max, page, kv_quant, num_pages,
                           trace)[2]

    # -- multi-step greedy decode ----------------------------------------
    def build_multi(
        self, batch: int, s_max: int, nsteps: int, sampled: bool = False,
        page: int = 0, straggler_rank: int | None = None,
        kv_quant: bool = False, num_pages: int = 0,
        valid_arg: bool = False, trace: bool = False,
        filtered: bool = False, eos: bool = False, ring: bool = False,
    ):
        """``nsteps`` greedy decode steps in ONE kernel launch.

        The LM head argmaxes in-kernel (under TP: local argmax then a
        one-shot cross-rank (value, index) exchange over ICI) and feeds
        the token back through SMEM; attention covers the launch's
        earlier steps from the knew/vnew outputs (the in-launch band);
        the caller appends all ``nsteps`` K/V rows with one contiguous
        dynamic_update_slice per batch row. Amortizes the
        per-launch/per-op dispatch tax (measured ~2 ms/step on the v5e
        relay — the dominant cost of single-step decode at small model
        sizes) over ``nsteps``.

        ``sampled=True`` adds a ``noise [nsteps, B, V_pad]`` argument
        (column-sharded under TP) and the in-kernel argmax runs over
        ``logits + noise`` — with ``noise = temperature * gumbel`` this
        IS temperature sampling (Gumbel-max trick), with the RNG in
        JAX-land; the returned logits stay clean.

        ``page`` > 0 builds the paged-cache variant (pool reads through
        the page table; all ``nsteps`` new rows land with ONE scatter
        via :func:`paged_kv_cache.append_n`). ``sampled`` composes with
        ``page`` (the serving fast path: Gumbel-noise sampling over the
        paged pool), and ``kv_quant`` reads an int8 pool through its
        per-page scales — the in-launch attention band keeps the
        launch's own rows at full precision (they are quantized once,
        by the trailing ``append_n`` scatter; docs/megakernel.md
        "Serving fast path").

        Caller contract: ``kv_len[b] + nsteps <= s_max`` for every row
        — the dense append is a ``dynamic_update_slice``, whose clamped
        start would silently overwrite cached rows past capacity (the
        Engine gates its multi launches on this).

        ``filtered=True`` (requires ``sampled``, single-rank) adds a
        ``sampcfg [B, 4]`` f32 argument ``[1/temperature,
        top_k_effective, top_p, enable]`` and the in-kernel winner runs
        over the exact host top-k/top-p keep-set (bisection —
        kernels._filtered_winner); ``eos=True`` adds ``stop_tok [B]``
        i32 (-1 = none) + ``halt [B]`` i32 arguments and appends
        ``(stop_step [B], halt_out [B])`` to the returns: the kernel
        records each slot's FIRST EOS-hitting step (``nsteps`` = never),
        the shard fn clamps that slot's appended rows to ``stop_step +
        1`` and a carried ``halt`` flag zeroes halted slots' appends in
        later launches (resident pipelining — docs/megakernel.md
        "Resident decode"); ``ring=True`` adds the work-ring snapshot
        ``[doorbell, head, tail, occupancy]`` i32 argument observed by
        the graph's leading RING_POLL task (megakernel/ring.py).
        """
        if (eos or ring) and not page:
            raise ValueError("eos/ring modes ride the paged serving "
                             "path only")
        if eos and not valid_arg:
            raise ValueError("eos needs valid_arg: device retire clamps "
                             "the per-slot kept-row counts")
        m = self.model
        V = m.cfg.vocab_size
        base = self._dims(batch, s_max, page, kv_quant, num_pages, trace)
        dims = dataclasses.replace(
            base, nsteps=nsteps, v_real=V, sampled=sampled,
            straggler_rank=straggler_rank, filtered=filtered, eos=eos,
            ring=ring,
        )
        mb = ModelBuilder(
            dims, cfg=self.cfg, axis=m.axis, ctx=m.ctx,
            wdtype=m.cfg.dtype, cdtype=m.cfg.dtype,
        )
        mb.build_decoder_graph()
        compiled = mb.compile(self.policy)
        per_shard = compiled.per_shard
        # Scheduled order, retrievable by trace consumers: the ring
        # decoder's dependency check (obs/kernel_trace.validate_ring)
        # needs the scoreboard edges of THIS build.
        self._last_multi_order = compiled.order
        ax = m.axis
        kernel_args, pspecs = self._args_and_specs()

        if page:
            def shard_fn(params: Qwen3Params, tokens,
                         cache: PagedKVCache, *extra):
                # Serving extras, in argument order (all optional):
                # n_valid, stop_tok, halt, ring_state, noise, sampcfg.
                ex = list(extra)
                n_valid = ex.pop(0) if valid_arg else None
                stop_tok = ex.pop(0) if eos else None
                halt = ex.pop(0) if eos else None
                ring_state = ex.pop(0) if ring else None
                pre = [a for a in (stop_tok, ring_state) if a is not None]
                outs = per_shard(
                    cache.kv_len, tokens, cache.page_table, *pre, *ex,
                    *kernel_args(params), cache.k_pages, cache.v_pages,
                    *self._scale_args(cache, kv_quant),
                )
                logits, k_rows, v_rows, toks = outs[:4]
                idx = 4
                # k_rows [NS, L, B, hkv, hd] → [L, B, hkv, NS, hd]:
                # one scatter lands all nsteps rows in the pool (int8
                # pools quantize them here, through append_n's
                # quantized_row_scatter protocol; guaranteed-overshoot
                # rows of finishing slots route to the trash page so
                # retiring pages' scales never cover garbage).
                k_rows = jnp.transpose(k_rows, (1, 2, 3, 0, 4))
                v_rows = jnp.transpose(v_rows, (1, 2, 3, 0, 4))
                if eos:
                    # Device-side retire: clamp a hitting slot's kept
                    # rows to its first EOS step (+1 keeps the EOS
                    # row itself); slots halted by a PREVIOUS launch
                    # (resident pipelining issued this one before the
                    # hit drained) append nothing — their overshoot
                    # rows route to the trash page.
                    ss = outs[idx][0]  # [B]; nsteps = never hit
                    idx += 1
                    keep = jnp.minimum(n_valid, ss + 1) * (1 - halt)
                    halt_out = jnp.maximum(
                        halt, (ss < nsteps).astype(jnp.int32)
                    )
                    ret = (
                        toks[:, 0, :], logits,
                        _paged.append_n(cache, k_rows, v_rows, keep),
                        ss, halt_out,
                    )
                else:
                    ret = (
                        toks[:, 0, :], logits,
                        _paged.append_n(cache, k_rows, v_rows, n_valid),
                    )
                if trace:  # per-rank ring, stacked on a tp leading dim
                    ret += (outs[idx][None],)
                return ret

            specs = paged_cache_specs(ax, quantized=kv_quant)
        else:
            def shard_fn(params: Qwen3Params, tokens, cache: KVCache,
                         *extra):  # noise?, sampcfg? — kernel mid order
                outs = per_shard(
                    cache.kv_len, tokens, *extra,
                    *kernel_args(params), cache.k, cache.v,
                )
                logits, k_rows, v_rows, toks = outs[:4]
                # k_rows [NS, L, B, hkv, hd] → [L, B, hkv, NS, hd]: all
                # nsteps rows land with ONE contiguous update per batch
                # row.
                k_rows = jnp.transpose(k_rows, (1, 2, 3, 0, 4))
                v_rows = jnp.transpose(v_rows, (1, 2, 3, 0, 4))
                k_new, v_new = cache.k, cache.v
                B = tokens.shape[0]
                for b in range(B):
                    at = (0, b, 0, cache.kv_len[b], 0)
                    k_new = jax.lax.dynamic_update_slice(
                        k_new, k_rows[:, b:b + 1], at
                    )
                    v_new = jax.lax.dynamic_update_slice(
                        v_new, v_rows[:, b:b + 1], at
                    )
                ret = (toks[:, 0, :], logits, KVCache(
                    k=k_new, v=v_new, kv_len=cache.kv_len + nsteps
                ))
                if trace:
                    ret += (outs[4][None],)
                return ret

            specs = cache_specs(ax)

        if valid_arg and not page:
            raise ValueError("valid_arg rides the paged append only")
        extra_specs = (P(),) if valid_arg else ()
        extra_specs += (P(), P()) if eos else ()      # stop_tok, halt
        extra_specs += (P(),) if ring else ()         # ring snapshot
        extra_specs += (P(None, None, ax),) if sampled else ()
        extra_specs += (P(),) if filtered else ()     # sampcfg [B, 4]
        out_specs = (P(), P(None, ax), specs)
        if eos:
            out_specs += (P(), P())                   # stop_step, halt
        if trace:
            out_specs += (P(ax),)
        g = m.ctx.shard_map(
            shard_fn,
            in_specs=(pspecs, P(), specs, *extra_specs),
            out_specs=out_specs,
        )

        def f(params, tokens, cache, *extra):
            toks, logits, *rest = g(params, tokens, cache, *extra)
            # toks [nsteps, B]; logits are the LAST step's (pad cols
            # dropped as in the single-step path). Trace builds append
            # the device ring [tp, NS, T, 8] as a fourth return.
            return (toks, logits[:, :V], *rest)

        # Donated cache: the nsteps-row dynamic_update_slice aliases in
        # place instead of copying the whole KV cache per launch (same
        # reasoning as the single-step build).
        return jax.jit(f, donate_argnums=(2,))

    def decode_multi_fn(
        self, batch: int, s_max: int, nsteps: int, sampled: bool = False,
        page: int = 0, kv_quant: bool = False, num_pages: int = 0,
        valid_arg: bool = False, trace: bool = False,
        filtered: bool = False, eos: bool = False, ring: bool = False,
    ):
        """Jitted multi-step fn ``f(params, tokens, cache[, n_valid]
        [, noise]) → (tokens [nsteps, B], last_logits [B, V], cache
        advanced nsteps)``; the cache argument is DONATED. With
        ``sampled``, ``noise [nsteps, B, V_pad]`` f32 perturbs the
        in-kernel argmax (Gumbel-max sampling — per-slot temperatures
        ride in the noise magnitudes); ``page`` > 0 takes a
        :class:`PagedKVCache`, and ``kv_quant`` an int8 pool (both
        compose with ``sampled``). ``valid_arg`` adds the serving
        loop's ``n_valid [B]`` kept-row counts (guaranteed-overshoot
        rows route to the trash page — see ``append_n``). ``trace``
        appends the device task ring ``[tp, NS, T, 8]`` to the returns
        (docs/observability.md "Device task tracer"). ``filtered``/
        ``eos``/``ring`` are the resident-serving modes — see
        :meth:`build_multi`. Cached per the full option tuple."""
        key = self._multi_key(batch, s_max, nsteps, sampled, page,
                              kv_quant, num_pages, valid_arg, trace,
                              filtered, eos, ring)
        if key not in self._jit:
            self._jit[key] = self.build_multi(
                batch, s_max, nsteps, sampled, page,
                kv_quant=kv_quant, num_pages=num_pages,
                valid_arg=valid_arg, trace=trace,
                filtered=filtered, eos=eos, ring=ring,
            )
            # Scheduled order for this build, for trace consumers
            # (obs/kernel_trace.validate_ring's dependency check).
            self._orders[key] = self._last_multi_order
        return self._jit[key]

    @staticmethod
    def _multi_key(batch, s_max, nsteps, sampled=False, page=0,
                   kv_quant=False, num_pages=0, valid_arg=False,
                   trace=False, filtered=False, eos=False, ring=False):
        """The ONE multi-build cache key — shared by
        :meth:`decode_multi_fn` and :meth:`multi_task_order` so the
        two can never disagree on what identifies a build."""
        return ("multi", batch, s_max, nsteps, sampled, page, kv_quant,
                num_pages, valid_arg, trace, filtered, eos, ring)

    def multi_task_order(self, *args, **kw):
        """The scheduled task order of a multi-step build — same
        signature as :meth:`decode_multi_fn` (builds on first use).
        Ring consumers pass it to ``validate_ring`` so the decoder can
        check every scoreboard edge against the device clock."""
        self.decode_multi_fn(*args, **kw)
        return self._orders[self._multi_key(*args, **kw)]

    # -- prefill ---------------------------------------------------------
    def _build_prefill(self, s: int):
        """Build the prompt-prefill megakernel for an S-token prompt
        (parity: the reference's prefill TaskBuilders,
        ``model_builder.py:189-352``)."""
        if self._is_moe:
            raise NotImplementedError(
                "MoE prefill runs through the model path — the serving "
                "engines prefill with mode='xla' under mode='mega' "
                "(MegaDispatch._prefill_mode)"
            )
        m = self.model
        dims = dataclasses.replace(self._dims(s, s), prefill=True)
        mb = ModelBuilder(
            dims, cfg=self.cfg, axis=m.axis, ctx=m.ctx,
            wdtype=m.cfg.dtype, cdtype=m.cfg.dtype,
        )
        mb.build_prefill_graph()
        per_shard = mb.compile(self.policy).per_shard
        ax = m.axis
        wq8 = self.cfg.wq8
        kernel_args = self._kernel_args_q8 if wq8 else self._kernel_args
        pspecs = self._q8_specs() if wq8 else m.param_specs

        def shard_fn(params, tokens, true_len, cache: KVCache):
            x0 = jnp.take(params.embed, tokens, axis=0)  # [S, d] XLA gather
            logits, k_rows, v_rows, _toks = per_shard(
                true_len[None], jnp.zeros((1,), jnp.int32), x0,
                *kernel_args(params),
                # The prefill kernel never reads the cache; tiny
                # placeholders keep the operand list uniform.
                jnp.zeros((1, 1, 1, 8, 128), m.cfg.dtype),
                jnp.zeros((1, 1, 1, 8, 128), m.cfg.dtype),
            )
            # k_rows [L, hkv, S, hd] → cache entry 0, positions [0, S).
            k_new = jax.lax.dynamic_update_slice(
                cache.k, k_rows[:, None].astype(cache.k.dtype), (0, 0, 0, 0, 0)
            )
            v_new = jax.lax.dynamic_update_slice(
                cache.v, v_rows[:, None].astype(cache.v.dtype), (0, 0, 0, 0, 0)
            )
            kv_len = cache.kv_len.at[0].set(true_len)
            return logits[0], KVCache(k=k_new, v=v_new, kv_len=kv_len)

        g = m.ctx.shard_map(
            shard_fn,
            in_specs=(pspecs, P(), P(), cache_specs(ax)),
            out_specs=(P(ax), cache_specs(ax)),
        )
        V = m.cfg.vocab_size

        def f(params, tokens, true_len, cache):
            logits, cache = g(params, tokens, true_len, cache)
            return logits[:V], cache  # drop vocab-pad logits

        return jax.jit(f)

    def prefill(self, tokens: jax.Array, cache: KVCache, *, true_len=None):
        """Prefill one prompt (``tokens [S]``) through the megakernel;
        returns (last-real-token logits [V], cache with entry 0 filled)
        — the same return contract as ``Qwen3.prefill``. ``true_len``
        is keyword-only (there is no ``mode`` parameter here; the
        megakernel IS the mode)."""
        s = int(tokens.shape[0])
        key = ("prefill", s)
        if key not in self._jit:
            self._jit[key] = self._build_prefill(s)
        if true_len is None:
            true_len = s
        return self._jit[key](
            self._step_params(), tokens, jnp.asarray(true_len, jnp.int32),
            cache,
        )
