"""Host↔device work ring for resident megakernel decode.

Parity: the reference stack's persistent MegaTritonKernel keeps the
device looping while the host feeds it work through pinned-memory
queues (SURVEY §0: whole-model persistent kernel + task scheduler);
PAPERS.md "Eliminating Hidden Serialization in Multi-Node Megakernel
Communication" argues the dispatch win comes precisely from the host
never re-launching.

TPU redesign (docs/megakernel.md "Resident decode"): a Pallas launch
cannot yet outlive its grid, so the resident loop is EMULATED at round
granularity — the host pushes admit/retire/cancel work items into this
ring, bumps the doorbell once per round, and the round's kernel
observes the published ``[doorbell, head, tail, occupancy]`` snapshot
through a scalar-prefetch operand (the RING_POLL task stamps the
doorbell it saw into its trace record, which is how ``validate_ring``
proves no round ran against a stale ring). On hardware the same layout
is what the persistent kernel would spin on: the doorbell becomes a
host-written semaphore, RING_POLL becomes the spin + task-table splice,
and the items below become the splice arguments. The host-side
accounting (push/consume/occupancy) is identical either way, which is
why it lives here as a first-class piece rather than inline engine
state.
"""

from __future__ import annotations

import dataclasses

import numpy as np

# Work-item kinds (0 is reserved as "empty slot" so a zeroed ring is
# trivially all-empty).
RING_ADMIT = 1    # arg = prompt length admitted into the slot
RING_RETIRE = 2   # arg = generated-token count at retire
RING_CANCEL = 3   # arg = 0

# Item layout: [kind, slot, arg, seq] int32.
ITEM_INTS = 4

_KIND_NAMES = {RING_ADMIT: "admit", RING_RETIRE: "retire",
               RING_CANCEL: "cancel"}


def kind_name(kind: int) -> str:
    return _KIND_NAMES.get(int(kind), f"kind{int(kind)}")


@dataclasses.dataclass
class RingItem:
    kind: int
    slot: int
    arg: int
    seq: int

    @property
    def kind_str(self) -> str:
        return kind_name(self.kind)


class WorkRing:
    """Bounded host→device work queue with a monotonic doorbell.

    ``push`` appends an item at ``tail``; ``publish`` bumps the
    doorbell, snapshots ``tail``, and returns the ``[doorbell, head,
    tail, occupancy]`` int32 snapshot a round's kernel prefetches;
    ``consume`` retires exactly what the published round covered —
    items pushed AFTER the publish stay host-owned until the next
    doorbell (round-boundary consumption — the interpret-mode stand-in
    for the device scheduler draining the ring mid-loop). ``flush`` is
    the single-step-fallback escape hatch: rounds that cannot launch
    fused apply slot state on the host directly, so the device loop
    never observes their items — they drain here, doorbell untouched.
    The ring never silently drops work: pushing into a full ring
    raises, because a lost admit/retire item would desynchronize the
    device scheduler from the engine's slot state.
    """

    def __init__(self, capacity: int = 64):
        if capacity <= 0:
            raise ValueError(f"ring capacity must be positive: {capacity}")
        self.capacity = int(capacity)
        self.buf = np.zeros((self.capacity, ITEM_INTS), np.int32)
        self.head = 0       # consumer position (monotonic)
        self.tail = 0       # producer position (monotonic)
        self.doorbell = 0   # rounds published
        self._seq = 0       # items ever pushed
        self._published_tail = 0  # tail at the last publish
        self.peak_occupancy = 0

    @property
    def occupancy(self) -> int:
        return self.tail - self.head

    def push(self, kind: int, slot: int, arg: int = 0) -> RingItem:
        if self.occupancy >= self.capacity:
            raise RuntimeError(
                f"work ring full ({self.capacity} items): the host "
                "out-ran the device by a whole ring — raise the ring "
                "capacity or drain more often"
            )
        item = RingItem(int(kind), int(slot), int(arg), self._seq)
        self.buf[self.tail % self.capacity] = (
            item.kind, item.slot, item.arg, item.seq
        )
        self.tail += 1
        self._seq += 1
        self.peak_occupancy = max(self.peak_occupancy, self.occupancy)
        return item

    def publish(self) -> np.ndarray:
        """Ring the doorbell for one round; returns the ``[doorbell,
        head, tail, occupancy]`` int32 snapshot the round's kernel
        prefetches (RING_POLL stamps snapshot[0] into its trace mid).
        The ``tail`` snapshot bounds the next ``consume`` — items
        pushed after this publish belong to a future round."""
        self.doorbell += 1
        self._published_tail = self.tail
        return np.asarray(
            [self.doorbell, self.head, self.tail, self.occupancy],
            np.int32,
        )

    def consume(self) -> list[RingItem]:
        """Round-boundary drain: everything pushed before the last
        publish is now owned by the device scheduler. Items pushed
        since that publish stay queued for the next doorbell. Returns
        the consumed items (oldest first) for accounting/tests."""
        items = []
        while self.head < self._published_tail:
            row = self.buf[self.head % self.capacity]
            items.append(RingItem(*(int(v) for v in row)))
            self.head += 1
        return items

    def flush(self) -> list[RingItem]:
        """Host-side drain of EVERYTHING queued, published or not — the
        doorbell does not move. Single-step fallback rounds call this:
        they apply admit/retire/cancel directly through host slot
        state, so the device loop never observes the queued items;
        leaving them would overflow the ring on a workload that
        persistently falls back. Returns the drained items."""
        self._published_tail = self.tail
        items = []
        while self.head < self.tail:
            row = self.buf[self.head % self.capacity]
            items.append(RingItem(*(int(v) for v in row)))
            self.head += 1
        return items
