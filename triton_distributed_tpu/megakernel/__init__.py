"""Megakernel subsystem: whole-model single persistent Pallas kernel.

Parity: reference ``python/triton_dist/mega_triton_kernel/`` (SURVEY.md
§2.2 L11) — task graph (``core/task_base.py``), registry
(``core/registry.py``), scheduler (``core/scheduler.py``), code
generator (``core/code_generator.py``), task kernels (``kernels/``),
``ModelBuilder`` (``models/model_builder.py``) and the Qwen3 megakernel
model (``models/qwen3.py``).
"""

from triton_distributed_tpu.megakernel import kernels  # noqa: F401  (register bodies)
from triton_distributed_tpu.megakernel.code_generator import (
    MegaConfig,
    MegaDims,
)
from triton_distributed_tpu.megakernel.model_builder import (
    CompiledMegaKernel,
    ModelBuilder,
)
from triton_distributed_tpu.megakernel.qwen3 import MegaQwen3
from triton_distributed_tpu.megakernel.registry import (
    register_task,
    registered_types,
)
from triton_distributed_tpu.megakernel.scheduler import SchedulePolicy, schedule
from triton_distributed_tpu.megakernel.task import (
    Task,
    TaskDependency,
    TaskIDManager,
    TaskType,
    pack_table,
)

__all__ = [
    "CompiledMegaKernel",
    "MegaConfig",
    "MegaDims",
    "MegaQwen3",
    "ModelBuilder",
    "SchedulePolicy",
    "Task",
    "TaskDependency",
    "TaskIDManager",
    "TaskType",
    "pack_table",
    "register_task",
    "registered_types",
    "schedule",
]
