"""Megakernel code generator: one Pallas kernel for a whole task graph.

Parity: reference ``mega_triton_kernel/core/code_generator.py`` —
``make_mega_kernel_src``:31 emits ONE ``@triton.jit`` kernel that loads
8-int task headers and dispatches via generated if/elif :92-174.

TPU redesign: no source-text generation — the "generated kernel" is a
traced closure. The task table is a scalar-prefetch operand (the analog
of the per-SM int32 work queues living in SMEM), the grid is the task
count with ``dimension_semantics=("arbitrary",)`` (sequential, so
schedule order IS the dependency order), and dispatch is a ``pl.when``
chain over exactly the task types the model uses — same shape as the
reference's generated if/elif, but over Mosaic predication.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from triton_distributed_tpu.megakernel.registry import get_body_factory
from triton_distributed_tpu.megakernel.task import (
    TR_BEGIN,
    TR_END,
    TR_FLAG,
    TR_LAYER,
    TR_MID,
    TR_OPCODE,
    TR_SLOT,
    TR_TASK_ID,
    TRACE_INTS,
    Task,
    TaskType,
)
from triton_distributed_tpu.ops.common import interpret_mode, pick_tile
from triton_distributed_tpu.runtime.mesh import DistContext


def _vmem_limit_bytes(
    scratch: list, out_shapes: list, in_vmem_bytes: int = 0
) -> int:
    """Scoped-VMEM limit derived from the resolved kernel footprint.

    Sums the VMEM scratch buffers (the staging depth × tile-width
    product that actually scales with :class:`MegaConfig`), the
    VMEM-resident outputs, and ``in_vmem_bytes`` — the caller's
    analytic total for VMEM-resident in_specs (norm weights, wq8
    scales, prefill prompt block, and the Mosaic-pipelined sampled-
    noise block counted TWICE for double buffering — ADVICE r4: the
    old 1.5× headroom alone under-provisioned sampled/large-B
    configs). Applies 1.5× headroom for Mosaic's own temporaries and
    clamps to [32 MiB, 112 MiB]: the floor keeps tiny configs from
    under-shooting Mosaic's working needs, the cap stays under the
    128 MiB physical VMEM of v5e/v5p. Replaces the old flat 100 MiB
    constant that over-committed smaller-VMEM generations and
    over-asked for default configs (ADVICE r3)."""
    def _nbytes(x) -> int:
        shape = getattr(x, "shape", None)
        dtype = getattr(x, "dtype", None)
        if shape is None or dtype is None:
            return 0
        try:
            itemsize = jnp.dtype(dtype).itemsize
        except TypeError:  # semaphore "dtypes" (dma_sem etc.)
            return 0
        n = 1
        for s in shape:
            n *= int(s)
        return n * itemsize

    footprint = sum(_nbytes(s) for s in scratch)
    footprint += sum(_nbytes(o) for o in out_shapes)
    footprint += in_vmem_bytes
    mib = 1024 * 1024
    return max(32 * mib, min(112 * mib, int(footprint * 1.5) + 8 * mib))


@dataclasses.dataclass(frozen=True)
class MegaDims:
    """Static per-shard geometry of the decode step."""

    batch: int
    d: int
    hq_loc: int
    hkv_loc: int
    head_dim: int
    f_loc: int
    v_loc: int
    num_layers: int
    s_max: int
    n_ranks: int
    rms_eps: float = 1e-6
    rope_theta: float = 1e6
    # Paged-KV mode: page size (0 = dense cache). When set, the KV
    # inputs are page pools [L, P, hkv, page, hd], a page table rides as
    # a scalar-prefetch operand, and the attention block size is the
    # page size (parity: reference paged_kv_cache.py).
    page: int = 0
    # Quantized paged pool (``kv_dtype="int8"``, PR 4's storage mode):
    # the KV pools arrive as int8 codes and two per-page-per-head scale
    # operands ``[L, P, 1, Hkv]`` f32 ride as VMEM-resident inputs (the
    # [L, P, 1, H] layout is the norm-weight trick — dynamic layer/page
    # indices stay on untiled leading dims). The attention task
    # dequantizes each staged page block in-register, so full-width KV
    # never materializes in HBM — the megakernel keeps the int8 pool's
    # bytes/token. Requires ``page`` > 0 (scales live on pages).
    kv_quant: bool = False
    # Pool page count (0 = unknown): only feeds the scoped-VMEM limit
    # accounting for the VMEM-resident scale operands above.
    num_pages: int = 0
    # Prefill mode: ``batch`` is the prompt length S (rows = positions),
    # the embedded prompt arrives as an extra input (LOAD_X task), the
    # cache is not read, K/V come out as [L, hkv, S, hd], and the LM
    # head projects only the last real row → logits [1, v_loc].
    prefill: bool = False
    # Multi-step greedy decode: ``nsteps`` whole decode steps run inside
    # ONE kernel launch (grid = (nsteps, tasks)) — the LM head argmaxes
    # in-kernel (under TP: local argmax + one-shot cross-rank
    # (value, index) exchange) and feeds the token back through SMEM,
    # attention covers the launch's earlier steps from the knew/vnew
    # outputs (the "band"), and the caller appends all nsteps rows at
    # once. Amortizes the platform's per-launch/per-op tax (measured
    # ~2 ms/step on the v5e relay) over nsteps. Argmax-based: greedy,
    # or temperature sampling via the `sampled` Gumbel noise below.
    nsteps: int = 1
    # GLOBAL real (unpadded) vocab size; 0 = every column real. The
    # in-kernel argmax masks this rank's pad columns (zero weights
    # score 0, which could beat real negative logits) — rank r's real
    # width is clamp(v_real - r*v_loc, 0, v_loc).
    v_real: int = 0
    # Sampled multi-step decode: an extra [nsteps, B, v_loc] noise
    # input rides along and the LM head argmaxes logits + noise — the
    # Gumbel-max trick (noise = temperature * gumbel drawn by the
    # host) turns the greedy machinery into temperature sampling while
    # the RNG stays in JAX-land (reproducible, testable).
    sampled: bool = False
    # In-kernel top-k/top-p filtered sampling (requires ``sampled`` and
    # ``nsteps`` > 1, single-rank only): a per-row sampling config
    # ``sampcfg [B, 4]`` f32 — ``[inv_temperature, top_k_effective,
    # top_p, enable]`` — rides as a VMEM operand and the LM head, after
    # streaming the raw logits, derives the EXACT host filter_logits
    # keep-set by per-row parallel bisection (64 fixed iterations on
    # the scaled-logit axis: the top-k threshold is the largest τ with
    # #{l/T > τ} ≥ k, the top-p threshold the largest τ whose
    # above-mass ≥ p·Z over the top-k survivors — both converge to the
    # float just below the host's cutoff value, so ties survive exactly
    # as in ``models.sampling.filter_logits``), then argmaxes
    # ``logits + noise`` over the kept set. With ``noise =
    # temperature · gumbel`` this IS top-k/top-p temperature sampling
    # (Gumbel-max over the filtered support ≡ categorical over the
    # filtered softmax). Rows with enable=0 keep the whole real vocab —
    # a zero-noise greedy row in a filtered batch stays bit-identical
    # to the greedy build. Single-rank only: the filter needs the full
    # logit row, which under TP is column-sharded across ranks.
    filtered: bool = False
    # Device-side stop-token testing (requires ``page`` and ``nsteps``
    # > 1): a ``stop_tok [B]`` i32 scalar-prefetch operand (-1 = none)
    # and a ``stop_step [1, B]`` i32 SMEM output — the LM head stamps
    # the first step whose sampled token equals the row's stop token
    # (``nsteps`` = never). The caller clamps its KV append counts to
    # ``stop_step + 1`` so rows decoded past a stop route to the trash
    # page, and finished slots retire at the next host drain without a
    # KV-rollback round trip.
    eos: bool = False
    # Host work ring (resident decode): a ``ring_state [4]`` i32
    # scalar-prefetch operand ``[doorbell, head, tail, occupancy]``
    # published by ``megakernel.ring.WorkRing`` and a RING_POLL task
    # prepended to the graph that stamps the observed doorbell into its
    # trace record — the proof hook that every round consumed the ring
    # state the host rang for it (see ring.py for the hardware story).
    ring: bool = False
    # Race-provocation fixture (parity: the reference's for_correctness
    # sleeps / straggler_option): lag this rank's LM-head argmax
    # exchange so a peer missing a wait reads stale candidates.
    # None = fixture off (straggle_if_rank's own no-op convention).
    straggler_rank: int | None = None
    straggler_nanos: int = 500_000
    # Device task tracer (docs/observability.md "Device task tracer"):
    # the kernel gains an SMEM trace-ring output [nsteps, T, TRACE_INTS]
    # int32 and every grid iteration records its task's
    # (task_id, opcode, layer, slot, begin, end[, mid]) — TPU cycle
    # counter where the toolchain exposes one, a monotonic SMEM logical
    # clock otherwise (always under interpret, so the feature is
    # deterministic in tests). Off (the default) the operand list,
    # scratch, and traced program are bit-identical to the untraced
    # build — the tracer costs literally nothing when disabled.
    trace: bool = False
    # MoE decode (docs/megakernel.md "MoE serving"): num_experts > 0
    # swaps the dense FC1/FC2 pair for MOE_GATE + one MOE_FFN task per
    # LOCAL expert + the split-phase A2A combine. The w1/w2 operands
    # become EP-sharded per-expert stacks [L, E_loc, d, 2f] / [L,
    # E_loc, f, d] (full FFN width — ``f_loc`` is then the FULL
    # moe_intermediate_size), a replicated router weight [L, d, E]
    # rides as an extra VMEM operand, and the combine workspace gains a
    # phase-0 buffer so two exchanges can be in flight per layer.
    num_experts: int = 0
    moe_top_k: int = 0
    norm_topk: bool = True

    @property
    def moe(self) -> bool:
        return self.num_experts > 0

    @property
    def experts_loc(self) -> int:
        """Local experts per rank (EP shard of the expert axis)."""
        return self.num_experts // self.n_ranks if self.moe else 0

    @property
    def qkv_loc(self) -> int:
        return (self.hq_loc + 2 * self.hkv_loc) * self.head_dim

    @property
    def o_k(self) -> int:
        return self.hq_loc * self.head_dim


@dataclasses.dataclass(frozen=True)
class MegaConfig:
    """Tile configuration (parity: the reference's per-task tile configs
    in its TaskBuilders). Resolved against dims by :func:`resolve`."""

    # Defaults from a v5e sweep on Qwen3-0.6B decode (1024/1024/256 ran
    # 3.0 ms/step vs 4.1 at 512/512): wide tiles amortize the per-tile
    # DMA turnaround in the weight streams; s_blk=512 regresses the KV
    # pipeline. (2048-wide tiles used to fail to compile — that was the
    # 16 MB default scoped-VMEM limit, which build_mega_call now
    # raises; they are sweepable again via perf/mega_tile_sweep.py.)
    tile_n: int = 1024
    tile_k: int = 1024
    s_blk: int = 256
    # Weight-stream staging depth: nbuf-1 DMAs stay in flight ahead of
    # the consuming matmul (2 = classic double buffer). The decode-step
    # weight stream is the whole ladder's floor (~1.2 GB/step at 0.6B);
    # with per-tile control overhead comparable to a 2 MB tile's wire
    # time, a deeper pipeline keeps the HBM controller busy through the
    # scalar-core gaps between tiles.
    nbuf: int = 2
    # int8 weight-only quantized decode: the five projection weights
    # stream as int8 (HALF the HBM bytes of the bf16 step — decode is
    # HBM-bound, so this halves the ladder's floor) with f32
    # per-output-channel scales applied to each tile product before
    # any nonlinearity. Per-channel scales compose exactly with TP:
    # column-sharded weights scale their local columns; row-sharded
    # (o/fc2 partial sums) dequantize per shard BEFORE the allreduce.
    # Activations, norms, embed, KV stay bf16/f32 — weight-only.
    # Callers pass `MegaQwen3.quantized_params()` in place of params.
    wq8: bool = False
    # Cross-task weight prefetch: after each task body, the kernel
    # reads the NEXT task's header and — when it is a weight-streaming
    # task — starts its FIRST tile's DMA into the staging rotation,
    # with an SMEM "preloaded" flag telling that stream to skip its own
    # tile-0 start. Removes the first-tile DMA exposure at every
    # qkv/o/fc1/fc2/lm_head boundary (~5 per layer); the scalar core
    # issues the prefetch while the MXU still runs the current task's
    # trailing matmuls. Requires nbuf >= 2.
    cross_prefetch: bool = False
    # Fold the RMS norms into their consumers (qkv / fc1 / lm_head
    # compute the norm inline from x instead of reading a NORM task's h)
    # — drops 2 tasks per layer + the final norm from the grid, i.e.
    # ~28% of the megakernel's task iterations at 0.6B. The norm math
    # is identical; only the task boundary (grid-iteration dispatch +
    # the consumer's first-DMA latency exposure) goes away. A/B'd by
    # perf/mega_tile_sweep.py before becoming default.
    fuse_norms: bool = False
    # Overlapped TP collectives (the gemm_ar ONE_SHOT pattern adapted
    # to the sequential megakernel grid, ops/overlap/gemm_ar.py): each
    # layer allreduce splits into AR_SEND (remote puts start the moment
    # the producing GEMM's partial is ready) and AR_WAIT (waits the
    # inbound partials only AFTER starting the next weight stream's
    # first tile DMA), so the ICI hop hides under the next task's HBM
    # traffic — decode's actual bottleneck — instead of serializing
    # after the GEMM. The in-window prefetch needs the cross_prefetch
    # flag machinery (the consuming stream must skip its own tile-0
    # start) and pairs best with fuse_norms (the task after AR_WAIT is
    # then the weight stream itself); without cross_prefetch the split
    # still overlaps the puts with task dispatch only. No-op at
    # n_ranks == 1 (the builder emits the fused ALLREDUCE there).
    overlap_ar: bool = False

    @classmethod
    def from_spec(cls, spec: str) -> "MegaConfig":
        """Parse the sweep/bench config-string format
        ``tile_n:tile_k:nbuf[:fuse_norms[:cross_prefetch[:overlap_ar]]]``
        — the ONE parser for both ``perf/mega_tile_sweep.py`` (which
        writes these strings into ``perf/MEGA_TUNED.json``) and
        ``bench.py`` (which reads them back); a shared definition keeps
        the handoff format-compatible."""
        fields = [int(v) for v in spec.split(":")]
        if len(fields) not in (3, 4, 5, 6):
            raise ValueError(
                "want tile_n:tile_k:nbuf[:fuse_norms[:cross_prefetch"
                f"[:overlap_ar]]], got {spec!r}"
            )
        # Validate VALUES here, not just arity: a tuned-file/env spec
        # like "0:1024:2" or a negative tile would otherwise surface as
        # an obscure failure deep inside kernel build.
        if min(fields[:3]) <= 0:
            raise ValueError(
                f"tile_n/tile_k/nbuf must be positive, got {spec!r}"
            )
        if any(f not in (0, 1) for f in fields[3:]):
            raise ValueError(
                f"fuse_norms/cross_prefetch/overlap_ar flags must be 0 "
                f"or 1: {spec!r}"
            )
        return cls(
            tile_n=fields[0], tile_k=fields[1], nbuf=fields[2],
            fuse_norms=bool(fields[3]) if len(fields) > 3 else False,
            cross_prefetch=bool(fields[4]) if len(fields) > 4 else False,
            overlap_ar=bool(fields[5]) if len(fields) > 5 else False,
        )

    def spec(self) -> str:
        """Inverse of :meth:`from_spec` (what the sweep persists)."""
        return (f"{self.tile_n}:{self.tile_k}:{self.nbuf}:"
                f"{int(self.fuse_norms)}:{int(self.cross_prefetch)}:"
                f"{int(self.overlap_ar)}")

    def resolve(self, dims: MegaDims) -> "ResolvedConfig":
        if self.nbuf < 1:
            raise ValueError(f"nbuf must be >= 1, got {self.nbuf}")
        if self.cross_prefetch and self.nbuf < 2:
            # Serial mode starts each tile at its own iteration; there
            # is no rotation slot a prefetched tile could wait in.
            raise ValueError("cross_prefetch requires nbuf >= 2")
        return ResolvedConfig(
            # nbuf=1 is a valid (serial, no-prefetch) degenerate the
            # sweep uses to isolate the prefetch benefit.
            nbuf=self.nbuf,
            cross_prefetch=self.cross_prefetch,
            fuse_norms=self.fuse_norms,
            wq8=self.wq8,
            overlap_ar=self.overlap_ar,
            tn_qkv=pick_tile(dims.qkv_loc, self.tile_n),
            tn_fc1=pick_tile(dims.f_loc, self.tile_n),
            # The vocab axis rarely divides by a wide tile (Qwen3:
            # 151936 = 128·1187), so the LM head streams a wide main
            # tile plus one remainder tile (lm_head_body) instead of
            # collapsing to the largest pow-2 divisor (128-wide tiles
            # halve HBM stream efficiency on the largest weight). The
            # remainder must itself be a 128-multiple for lane
            # alignment, hence the v_loc % 128 gate — Qwen3's v_loc
            # only satisfies it at tp=1 (151936/tp carries a 64/96/48
            # residue); pad the vocab to 128·tp at load time to widen
            # lm tiles under TP.
            tn_lm=(
                min(self.tile_n, dims.v_loc)
                if dims.v_loc % 128 == 0 and self.tile_n % 128 == 0
                else pick_tile(dims.v_loc, self.tile_n)
            ),
            tk_o=pick_tile(dims.o_k, self.tile_k),
            tk_fc2=pick_tile(dims.f_loc, self.tile_k),
            # Paged mode: the KV block IS the page — pick_tile's 128
            # floor must not widen it past the page size.
            s_blk=dims.page or pick_tile(dims.s_max, self.s_blk),
        )


@dataclasses.dataclass(frozen=True)
class ResolvedConfig:
    nbuf: int
    cross_prefetch: bool
    fuse_norms: bool
    wq8: bool
    overlap_ar: bool
    tn_qkv: int
    tn_fc1: int
    tn_lm: int
    tk_o: int
    tk_fc2: int
    s_blk: int

    @property
    def tn_max(self) -> int:
        return max(self.tn_qkv, self.tn_fc1, self.tn_lm)

    @property
    def tk_max(self) -> int:
        return max(self.tk_o, self.tk_fc2)


class KernelCtx:
    """Everything a task body sees: dims, config, header fields, refs.

    Ref attributes are bound by :func:`make_mega_kernel` per trace; the
    names are the contract between the generator and ``kernels.py``.
    """

    def __init__(self, dims: MegaDims, cfg: ResolvedConfig, axis: str,
                 wdtype, cdtype, interpret: bool = False):
        self.dims = dims
        self.cfg = cfg
        self.axis = axis
        self.wdtype = wdtype
        self.cdtype = cdtype
        # True when this build runs under the interpret path (CPU
        # simulator mesh): remote DMAs discharge synchronously at their
        # program point there, so cross-rank barriers are vacuous — and
        # 0.4.x interpret has no barrier-semaphore support at all. The
        # bodies consult this to skip barrier_all; Mosaic builds
        # (including TPU-targeted AOT lowering from CPU hosts, whose
        # ctx reports on_tpu) keep every barrier.
        self.interpret = interpret
        # traced per-step header fields, bound in the kernel body:
        self.layer: Any = None
        self.arg0: Any = None
        self.arg1: Any = None
        self.table: Any = None  # page table (paged mode only)
        self.step: Any = None   # decode step within the launch (multi-step)
        self.tok_smem: Any = None   # [B] i32 — next-token feedback
        self.toks_out: Any = None   # [nsteps, 1, B] i32 — greedy tokens
        self.noise: Any = None  # [1, B, v_loc] VMEM — this step's noise
        # Filtered-sampling config [B, 4] f32 (None unless dims.filtered):
        # per-row [inv_temperature, top_k_effective, top_p, enable].
        self.sampcfg: Any = None
        # Device stop-token refs (None unless dims.eos): the [B] i32
        # stop-token scalar-prefetch operand and the [1, B] i32 SMEM
        # stop_step output the LM head stamps.
        self.stop_tok: Any = None
        self.stop_out: Any = None
        # Work-ring snapshot [4] i32 (None unless dims.ring):
        # [doorbell, head, tail, occupancy] as published by the host.
        self.ring_state: Any = None
        # cross_prefetch SMEM flags: slot 0 of col/rowstage already
        # holds the current task's tile 0 (started by the previous
        # task's prefetch block; the stream skips its own start).
        self.pre_col: Any = None
        self.pre_row: Any = None
        # wq8 dequant scale refs (None unless cfg.wq8):
        self.sc_qkv: Any = None
        self.sc_o: Any = None
        self.sc_w1: Any = None
        self.sc_w2: Any = None
        self.sc_lm: Any = None
        # int8 paged-pool dequant scales [L, P, 1, Hkv] f32 (None unless
        # dims.kv_quant): the attention task reads scalar (layer, page,
        # head) entries to dequantize staged page blocks in-register.
        self.ksc: Any = None
        self.vsc: Any = None
        # The scalar-prefetched task table + current task index, bound
        # per trace: the AR_WAIT body peeks its successor's header to
        # start that weight stream's tile-0 DMA before blocking on the
        # inbound allreduce partials (cfg.overlap_ar).
        self.task_tab: Any = None
        self.t: Any = None
        # Device task tracer refs (None unless dims.trace): the SMEM
        # trace-ring output and the logical-clock SMEM counter.
        self.trace_out: Any = None
        self.clk: Any = None
        # MoE refs (None unless dims.moe): the replicated router weight
        # [L, d, E], the per-(expert, token) combine weights the gate
        # writes ([E, 1, B] f32 — expert-leading so MOE_FFN's traced
        # expert id indexes an untiled dim, the norm-weight trick), the
        # combine accumulator [B, d] f32, and — under overlap_ar — the
        # phase-0 exchange workspace (a2src/a2buf) with its own DMA
        # semaphores (phase 1 reuses the AR workspace, whose slots the
        # layer's attention allreduce has already quiesced).
        self.wrouter: Any = None
        self.moe_w: Any = None
        self.moe_acc: Any = None
        self.a2src: Any = None
        self.a2buf: Any = None
        self.a2send: Any = None
        self.a2recv: Any = None


def make_mega_kernel(
    dims: MegaDims,
    cfg: ResolvedConfig,
    used_types: tuple[TaskType, ...],
    *,
    axis: str,
    wdtype,
    cdtype,
    interpret: bool = False,
):
    """Build the kernel function dispatching over ``used_types``."""
    kctx = KernelCtx(dims, cfg, axis, wdtype, cdtype, interpret)
    # Build one body closure per used type, in enum order.
    bodies = [(int(t), get_body_factory(t)(kctx)) for t in sorted(used_types)]

    def kernel(
        task_tab, kv_len, tokens,                      # scalar prefetch
        *rest,
    ):
        # Paged mode inserts the page table as a 4th scalar-prefetch
        # operand; eos adds the stop-token row and ring the work-ring
        # snapshot after it (both scalar-prefetch — SMEM-resident for
        # the LM head's / RING_POLL's scalar reads); prefill mode
        # inserts the embedded prompt rows x0 before the weights. The
        # operand order is otherwise identical.
        if dims.page:
            page_tab, *rest = rest
        else:
            page_tab = None
        if dims.eos:
            stop_tok, *rest = rest
        else:
            stop_tok = None
        if dims.ring:
            ring_state, *rest = rest
        else:
            ring_state = None
        (
            embed, wqkv, wo, w1, w2, lm_head,              # ANY (HBM)
            ln1, ln2, normf, qn, kn,                       # VMEM (small)
            *rest,
        ) = rest
        if dims.moe:  # replicated router weight, after the norms
            wrouter, *rest = rest
        else:
            wrouter = None
        if cfg.wq8:  # per-output-channel dequant scales, after norms
            sc_qkv, sc_o, sc_w1, sc_w2, sc_lm, *rest = rest
        else:
            sc_qkv = sc_o = sc_w1 = sc_w2 = sc_lm = None
        if dims.prefill:  # embedded prompt rows, after the weights
            x0, *rest = rest
        else:
            x0 = None
        if dims.sampled:  # per-step sampling noise, before the cache
            noise, *rest = rest
        else:
            noise = None
        if dims.filtered:  # per-row sampling config, after the noise
            sampcfg, *rest = rest
        else:
            sampcfg = None
        if dims.kv_quant:  # int8 pool: cache block is (kc, vc, ksc, vsc)
            kc, vc, ksc, vsc, *rest = rest
        else:
            kc, vc, *rest = rest
            ksc = vsc = None
        rest = list(rest)
        if dims.eos:
            # Stop-step output rides after the token output (index 4);
            # popping it first keeps the trace pop's index stable.
            stop_out = rest.pop(4)
        else:
            stop_out = None
        if dims.trace:
            # Trace builds append the SMEM ring after the outputs and
            # the logical-clock counter after the scratch; popping them
            # here keeps the canonical unpack below mode-free.
            trace_out = rest.pop(4)
            clk = rest.pop()
        else:
            trace_out = clk = None
        moe_w = moe_acc = a2src = a2buf = a2send = a2recv = None
        if dims.moe:
            # MoE scratch rides after the canonical block (before the
            # trace clock, already popped): combine weights, combine
            # accumulator, and — under overlap_ar — the phase-0
            # exchange workspace + semaphores.
            if cfg.overlap_ar:
                a2recv = rest.pop()
                a2send = rest.pop()
                a2buf = rest.pop()
                a2src = rest.pop()
            moe_acc = rest.pop()
            moe_w = rest.pop()
        (
            logits, knew_out, vnew_out, toks_out,          # outputs
            x, h, qkv, ao, mlp, estage,                    # VMEM state
            colstage, rowstage, kstage, vstage,            # weight/KV staging
            arsrc, cbuf,                                   # AR staging
            tokrow, tok_smem,                              # token feedback
            pre_col, pre_row,                              # prefetch flags
            wsem, esem, osem, ksem, vsem, arsend, arrecv,  # DMA semaphores
            tsem,
        ) = rest
        t = pl.program_id(1)       # task index within the step
        kctx.step = pl.program_id(0)  # decode step within the launch
        kctx.kv_len = kv_len
        kctx.tokens = tokens
        kctx.table = page_tab
        kctx.task_tab = task_tab
        kctx.t = t
        kctx.ksc, kctx.vsc = ksc, vsc
        kctx.x0 = x0
        kctx.noise = noise
        kctx.sampcfg = sampcfg
        kctx.stop_tok, kctx.stop_out = stop_tok, stop_out
        kctx.ring_state = ring_state
        kctx.toks_out = toks_out
        kctx.embed, kctx.wqkv, kctx.wo = embed, wqkv, wo
        kctx.w1, kctx.w2, kctx.lm_head = w1, w2, lm_head
        kctx.sc_qkv, kctx.sc_o, kctx.sc_w1 = sc_qkv, sc_o, sc_w1
        kctx.sc_w2, kctx.sc_lm = sc_w2, sc_lm
        kctx.ln1, kctx.ln2, kctx.normf = ln1, ln2, normf
        kctx.qn, kctx.kn = qn, kn
        kctx.logits, kctx.kc, kctx.vc = logits, kc, vc
        kctx.knew_out, kctx.vnew_out = knew_out, vnew_out
        kctx.x, kctx.h, kctx.qkv, kctx.ao, kctx.mlp = x, h, qkv, ao, mlp
        kctx.estage, kctx.colstage, kctx.rowstage = estage, colstage, rowstage
        kctx.kstage, kctx.vstage = kstage, vstage
        kctx.arsrc, kctx.cbuf = arsrc, cbuf
        kctx.tokrow, kctx.tok_smem = tokrow, tok_smem
        kctx.pre_col, kctx.pre_row = pre_col, pre_row
        kctx.wsem, kctx.esem, kctx.osem = wsem, esem, osem
        kctx.ksem, kctx.vsem = ksem, vsem
        kctx.arsend, kctx.arrecv = arsend, arrecv
        kctx.tsem = tsem
        kctx.trace_out, kctx.clk = trace_out, clk
        kctx.wrouter = wrouter
        kctx.moe_w, kctx.moe_acc = moe_w, moe_acc
        kctx.a2src, kctx.a2buf = a2src, a2buf
        kctx.a2send, kctx.a2recv = a2send, a2recv

        ttype = task_tab[t, 0]
        kctx.layer = task_tab[t, 1]
        kctx.arg0 = task_tab[t, 2]
        kctx.arg1 = task_tab[t, 3]

        if cfg.cross_prefetch:
            @pl.when(jnp.logical_and(kctx.step == 0, t == 0))
            def _init_flags():
                pre_col[0] = 0
                pre_row[0] = 0

        if dims.trace:
            from triton_distributed_tpu.megakernel.kernels import trace_tick

            @pl.when(jnp.logical_and(kctx.step == 0, t == 0))
            def _init_clk():
                clk[0] = 0

            # Record header fields + begin BEFORE dispatch; mid stays 0
            # unless a body stamps a phase mark (the AR bodies do).
            trace_out[kctx.step, t, TR_TASK_ID] = task_tab[t, 4]
            trace_out[kctx.step, t, TR_OPCODE] = ttype
            trace_out[kctx.step, t, TR_LAYER] = kctx.layer
            trace_out[kctx.step, t, TR_SLOT] = kctx.arg0
            trace_out[kctx.step, t, TR_MID] = 0
            trace_out[kctx.step, t, TR_BEGIN] = trace_tick(kctx)

        for value, body in bodies:
            pl.when(ttype == value)(body)

        if cfg.cross_prefetch:
            # Start the NEXT task's first weight-tile DMA now: the
            # scalar core runs ahead of the MXU, so the copy overlaps
            # this task's trailing matmuls and the next stream skips
            # its own tile-0 start (flag consumed there). Copies must
            # BYTE-MATCH the stream's own copy(0) — same refs, widths,
            # and semaphore — guaranteed by sharing fire_next_tile0
            # with the AR_WAIT body. The last task of a step prefetches
            # nothing (the next grid iteration is the next step's
            # EMBED).
            from triton_distributed_tpu.megakernel.kernels import (
                fire_next_tile0,
            )

            waits = [t for t in (TaskType.AR_WAIT, TaskType.A2A_WAIT)
                     if t in used_types]
            if waits:
                # An AR_WAIT/A2A_WAIT task already fired its
                # successor's tile-0 copy BEFORE blocking on the
                # inbound partials (that early start is the whole
                # overlap); firing it again here would double-start the
                # same DMA descriptor and corrupt the semaphore
                # accounting.
                not_wait = ttype != int(waits[0])
                for w in waits[1:]:
                    not_wait = jnp.logical_and(not_wait, ttype != int(w))
                pl.when(not_wait)(lambda: fire_next_tile0(kctx))
            else:
                fire_next_tile0(kctx)

        if dims.trace:
            # End AFTER the cross_prefetch epilogue: the prefetch fire
            # is part of this task's grid iteration, and the decoder's
            # dependency check needs end[producer] <= begin[consumer]
            # to hold for everything the iteration did.
            trace_out[kctx.step, t, TR_END] = trace_tick(kctx)
            trace_out[kctx.step, t, TR_FLAG] = 1

    return kernel


def build_mega_call(
    dims: MegaDims,
    mcfg: MegaConfig,
    tasks: list[Task],
    *,
    axis: str,
    ctx: DistContext,
    wdtype,
    cdtype,
    collective_id: int,
    table: Any,
):
    """Assemble the pallas_call for a scheduled task list.

    Returns ``f(kv_len, tokens, embed, wqkv, wo, w1, w2, lm_head, ln1,
    ln2, normf, qn, kn, kc, vc) → (logits, knew, vnew)`` — a per-shard
    function to run under ``shard_map``; ``knew``/``vnew`` are the new
    token's K/V rows ``[L, B, hkv, hd]`` for the caller to append.
    """
    cfg = mcfg.resolve(dims)
    used = tuple({t.task_type for t in tasks})
    interpret = interpret_mode(ctx)
    kernel = make_mega_kernel(
        dims, cfg, used, axis=axis, wdtype=wdtype, cdtype=cdtype,
        interpret=bool(interpret),
    )
    B, d = dims.batch, dims.d
    n = dims.n_ranks
    hkv, hd = dims.hkv_loc, dims.head_dim

    grid_spec = pltpu.PrefetchScalarGridSpec(
        # task_tab, kv_len, tokens [+ page_table] [+ stop_tok]
        # [+ ring_state] — all SMEM-resident scalar prefetch.
        num_scalar_prefetch=(3 + int(bool(dims.page)) + int(dims.eos)
                             + int(dims.ring)),
        # Outer grid dim = decode steps within the launch (1 unless
        # multi-step): one task table serves every step, the kernel
        # reads the step index from program_id(0).
        grid=(dims.nsteps, len(tasks)),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 6
        + [pl.BlockSpec(memory_space=pltpu.VMEM)] * 5
        # MoE router weight [L, d, E]: VMEM-resident like the norms —
        # MOE_GATE reads the traced layer's [d, E] plane per step.
        + ([pl.BlockSpec(memory_space=pltpu.VMEM)] if dims.moe else [])
        # wq8 dequant scales (~2 MB total at 0.6B): VMEM-resident like
        # the norm weights they sit next to.
        + ([pl.BlockSpec(memory_space=pltpu.VMEM)] * 5 if cfg.wq8 else [])
        + ([pl.BlockSpec(memory_space=pltpu.VMEM)] if dims.prefill else [])
        + (
            # Per-step noise block: Mosaic pipelines the [B, v_loc]
            # slab for step s = program_id(0) into VMEM. (Index maps
            # under PrefetchScalarGridSpec also receive the prefetch
            # refs after the grid indices.)
            [pl.BlockSpec(
                (1, B, dims.v_loc), lambda s, t, *prefetch: (s, 0, 0)
            )]
            if dims.sampled else []
        )
        # Filtered-sampling config [B, 4] f32: VMEM-resident like the
        # norms — the LM head reads the per-row columns post-stream.
        + ([pl.BlockSpec(memory_space=pltpu.VMEM)] if dims.filtered else [])
        + [pl.BlockSpec(memory_space=pl.ANY)] * 2
        # int8 pool scales [L, P, 1, Hkv] f32: VMEM-resident like the
        # norm weights — per-(layer, page, head) scalar reads inside
        # the attention block loop (~L·P·H·4 bytes; counted below).
        + ([pl.BlockSpec(memory_space=pltpu.VMEM)] * 2
           if dims.kv_quant else []),
        out_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),  # logits
            pl.BlockSpec(memory_space=pltpu.VMEM),  # new K rows
            pl.BlockSpec(memory_space=pltpu.VMEM),  # new V rows
            pl.BlockSpec(memory_space=pltpu.VMEM),  # greedy tokens
        ]
        # Stop-step output [1, B]: SMEM — per-row scalar stamps from
        # the LM head, read back by the caller's append clamp.
        + ([pl.BlockSpec(memory_space=pltpu.SMEM)] if dims.eos else [])
        # Trace ring: SMEM, because records are scalar stores at
        # dynamic (step, task) indices — natural on the scalar core,
        # while a VMEM row write at a dynamic sublane offset is exactly
        # the unaligned-slice shape Mosaic rejects. ~NS·T·32 bytes.
        + ([pl.BlockSpec(memory_space=pltpu.SMEM)] if dims.trace else []),
        scratch_shapes=(scratch := [
            pltpu.VMEM((B, d), jnp.float32),                   # x
            pltpu.VMEM((B, d), jnp.float32),                   # h
            pltpu.VMEM((B, dims.qkv_loc), jnp.float32),        # qkv
            pltpu.VMEM((B, dims.o_k), jnp.float32),            # ao
            pltpu.VMEM((B, dims.f_loc), jnp.float32),          # mlp
            # estage + KV staging serve the decode-only EMBED/ATTN
            # tasks; prefill shrinks them to placeholders (B = S would
            # otherwise blow VMEM on buffers no task reads).
            pltpu.VMEM(
                (1, 8, d) if dims.prefill else (B, 8, d), wdtype
            ),                                                 # estage
            pltpu.VMEM((cfg.nbuf, d, cfg.tn_max),
                       jnp.int8 if cfg.wq8 else wdtype),       # colstage
            pltpu.VMEM((cfg.nbuf, cfg.tk_max, d),
                       jnp.int8 if cfg.wq8 else wdtype),       # rowstage
            # int8 pools stage their codes as int8 (dequant happens
            # in-register per block) — half the staging VMEM too.
            pltpu.VMEM(
                (1,) * 5 if dims.prefill
                else (2, B, hkv, cfg.s_blk, hd),
                jnp.int8 if dims.kv_quant else cdtype
            ),                                                 # kstage
            pltpu.VMEM(
                (1,) * 5 if dims.prefill
                else (2, B, hkv, cfg.s_blk, hd),
                jnp.int8 if dims.kv_quant else cdtype
            ),                                                 # vstage
            pltpu.VMEM((B, d), jnp.float32),                   # arsrc
            pltpu.VMEM((n, B, d), jnp.float32),                # cbuf
            # Multi-step token feedback: the LM head's in-kernel argmax
            # lands in tokrow (VMEM), is DMA'd to tok_smem (SMEM) so the
            # next step's EMBED can scalar-read it as a DMA index.
            pltpu.VMEM((1, max(B, 1)), jnp.int32),             # tokrow
            pltpu.SMEM((1, max(B, 1)), jnp.int32),             # tok_smem
            pltpu.SMEM((1,), jnp.int32),                       # pre_col
            pltpu.SMEM((1,), jnp.int32),                       # pre_row
            pltpu.SemaphoreType.DMA((cfg.nbuf,)),              # wsem
            pltpu.SemaphoreType.DMA,                           # esem
            pltpu.SemaphoreType.DMA,                           # osem
            pltpu.SemaphoreType.DMA((2,)),                     # ksem
            pltpu.SemaphoreType.DMA((2,)),                     # vsem
            pltpu.SemaphoreType.DMA,                           # arsend
            pltpu.SemaphoreType.DMA((n,)),                     # arrecv
            pltpu.SemaphoreType.DMA,                           # tsem
        ] + (
            # MoE scratch: combine weights ([E, 1, B] f32,
            # expert-leading for traced-index scalar reads) + combine
            # accumulator, and — under overlap_ar — the phase-0
            # exchange workspace (phase 1 reuses arsrc/cbuf).
            [
                pltpu.VMEM((dims.num_experts, 1, max(B, 1)), jnp.float32),
                pltpu.VMEM((B, d), jnp.float32),               # moe_acc
            ] + ([
                pltpu.VMEM((B, d), jnp.float32),               # a2src
                pltpu.VMEM((n, B, d), jnp.float32),            # a2buf
                pltpu.SemaphoreType.DMA,                       # a2send
                pltpu.SemaphoreType.DMA((n,)),                 # a2recv
            ] if cfg.overlap_ar else [])
            if dims.moe else []
        ) + (
            # Logical trace clock (SMEM counter; see kernels.trace_tick).
            [pltpu.SMEM((1,), jnp.int32)] if dims.trace else []
        )),
    )

    # VMEM-resident in_specs are footprint too (ADVICE r4 — the 1.5×
    # headroom alone under-provisioned sampled/large-B configs): norm
    # weights ln1/ln2 [L,1,d] + normf [1,d] + qn/kn [L,1,hd] in wdtype;
    # wq8 dequant scales (f32: sc_qkv [L,1,qkv_loc], sc_o/sc_w2 local
    # [L,1,d], sc_w1 [L,1,2·f_loc], sc_lm [1,v_loc]); the prefill
    # prompt block [S,d]; and the pipelined sampled-noise block
    # [1,B,v_loc] f32, counted twice for double buffering.
    itw = jnp.dtype(wdtype).itemsize
    in_vmem = itw * (2 * dims.num_layers * d + d
                     + 2 * dims.num_layers * dims.head_dim)
    if dims.moe:
        # Replicated router weight [L, d, E], VMEM-resident.
        in_vmem += itw * dims.num_layers * d * dims.num_experts
    if cfg.wq8:
        in_vmem += 4 * (dims.num_layers
                        * (dims.qkv_loc + 2 * d + 2 * dims.f_loc)
                        + dims.v_loc)
    if dims.prefill:
        in_vmem += itw * B * d
    if dims.sampled:
        in_vmem += 2 * 4 * B * dims.v_loc
    if dims.filtered:
        in_vmem += 4 * 4 * max(B, 1)
    if dims.kv_quant:
        # Per-page-per-head f32 scale planes for K and V (num_pages may
        # be 0 = unknown for shape-polymorphic builds; the 1.5× headroom
        # below absorbs small pools, and engine builds pass the count).
        in_vmem += 2 * 4 * dims.num_layers * dims.num_pages * hkv

    # FLOPs/bytes annotation (parity: the reference's launch_metadata on
    # its megakernel): decode is one pass over every weight shard plus
    # the KV context; flops ≈ 2·B·(weight params) per matmul chain.
    L = dims.num_layers
    # MLP weight traffic: dense streams the f_loc shard; MoE streams
    # every LOCAL expert's full-width FFN (plus the replicated router).
    mlp_w = (
        dims.experts_loc * 3 * dims.d * dims.f_loc
        + dims.d * dims.num_experts
        if dims.moe else 3 * dims.d * dims.f_loc
    )
    wparams = L * (
        dims.d * dims.qkv_loc + dims.o_k * dims.d + mlp_w
    ) + dims.d * dims.v_loc
    kv_elems = 2 * L * B * hkv * dims.s_max * hd
    ns = dims.nsteps
    cost = pl.CostEstimate(
        flops=ns * (2 * B * wparams
                    + 4 * B * L * dims.hq_loc * dims.s_max * hd),
        bytes_accessed=ns * (wparams * jnp.dtype(wdtype).itemsize
                             + kv_elems * jnp.dtype(cdtype).itemsize),
        transcendentals=ns * B * L * (dims.hq_loc * dims.s_max + dims.f_loc),
    )

    call = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        cost_estimate=cost,
        # The kernel reads the KV cache but does not write it: appending
        # one row at a dynamic position inside a (8,128)-tiled cache
        # plane is an unaligned slice Mosaic rejects, so new K/V rows
        # come out as [L, B, hkv, hd] and the caller merges them with
        # one XLA dynamic_update_slice (which aliases in place when the
        # cache is donated).
        out_shape=(out_shapes := [
            jax.ShapeDtypeStruct(
                (1 if dims.prefill else B, dims.v_loc), jnp.float32
            ),
            # Prefill: all S rows per head; decode: one row per
            # (step, b, h) — the step dim doubles as the in-launch
            # attention band (later steps read earlier steps' rows).
            jax.ShapeDtypeStruct(
                (dims.num_layers, hkv, B, hd) if dims.prefill
                else (dims.nsteps, dims.num_layers, B, hkv, hd), cdtype
            ),
            jax.ShapeDtypeStruct(
                (dims.num_layers, hkv, B, hd) if dims.prefill
                else (dims.nsteps, dims.num_layers, B, hkv, hd), cdtype
            ),
            # Greedy tokens per step (multi-step; garbage when the LM
            # head runs in single-step mode and the caller ignores it).
            jax.ShapeDtypeStruct((dims.nsteps, 1, max(B, 1)), jnp.int32),
        ] + (
            # Device stop-step per row: first step whose token hit the
            # row's stop token (nsteps = never). SMEM scalar stamps.
            [jax.ShapeDtypeStruct((1, max(B, 1)), jnp.int32)]
            if dims.eos else []
        ) + (
            # Device trace ring: one TRACE_INTS-int record per
            # (step, task) grid iteration — dense by construction, so
            # the decoder's gap-free check is exact (every flag must
            # read 1). ``len(tasks)`` is the scheduled order's length;
            # obs/kernel_trace.py maps rows back through it.
            [jax.ShapeDtypeStruct(
                (dims.nsteps, len(tasks), TRACE_INTS), jnp.int32
            )]
            if dims.trace else []
        )),
        compiler_params=pltpu.CompilerParams(
            has_side_effects=True,
            dimension_semantics=("arbitrary", "arbitrary"),
            collective_id=collective_id,
            allow_collective_id_without_custom_barrier=True,
            # The default 16 MB scoped-VMEM limit is what made wide
            # tiles (tn=2048) fail to compile: staging alone is
            # nbuf·d·tn·2B per stream direction. Derive the limit from
            # the resolved footprint (scratch staging + VMEM-resident
            # outs) with 1.5x headroom for Mosaic's own temporaries, so
            # default configs keep the small default-ish limit and only
            # wide-tile/deep-nbuf configs raise it — capped at 112 MiB
            # to stay under the 128 MiB physical VMEM of the v5e/v5p
            # generations this targets.
            vmem_limit_bytes=_vmem_limit_bytes(
                scratch, out_shapes, in_vmem
            ),
        ),
        interpret=interpret,
    )

    if dims.page and dims.prefill:
        raise NotImplementedError("paged prefill: prefill then scatter")
    if dims.sampled and dims.prefill:
        raise NotImplementedError("sampled multi-step: decode only")
    if dims.kv_quant and not dims.page:
        raise ValueError("kv_quant requires the paged cache (scales "
                         "live on pool pages)")
    if dims.filtered:
        if not dims.sampled or dims.nsteps <= 1:
            raise ValueError("filtered sampling rides the sampled "
                             "multi-step LM head (sampled, nsteps > 1)")
        if dims.n_ranks > 1:
            raise NotImplementedError(
                "in-kernel top-k/top-p needs the full logit row, which "
                "TP column-shards across ranks — filtered builds are "
                "single-rank; tp>1 sampled-with-filters rounds keep the "
                "single-step fallback"
            )
    if dims.eos and (not dims.page or dims.nsteps <= 1):
        raise ValueError("device stop-token testing rides the paged "
                         "multi-step decode (page > 0, nsteps > 1)")
    if dims.moe:
        if cfg.wq8:
            raise NotImplementedError(
                "wq8 does not compose with MoE decode yet (per-expert "
                "per-channel scale planes)"
            )
        if dims.prefill:
            raise NotImplementedError(
                "MoE prefill runs through the model path "
                "(Engine._prefill_mode is 'xla' under mode='mega')"
            )
        if dims.num_experts % dims.n_ranks:
            raise ValueError(
                f"num_experts {dims.num_experts} not divisible by "
                f"tp={dims.n_ranks} (EP shards the expert axis)"
            )
        if not dims.moe_top_k:
            raise ValueError("MoE dims need moe_top_k > 0")
    # ``wargs`` = the kernel-args block (weights + norms [+ wq8
    # scales]) followed by the cache operands (kc, vc[, ksc, vsc]) —
    # variadic so the wq8/kv_quant paths' extra scale operands flow
    # through without per-mode signature edits. The caller-facing order
    # is ``(kv_len, tokens, [page_table], [stop_tok], [ring_state],
    # [x0], [noise], [sampcfg], *wargs)``; the mode operands are
    # re-sited into the kernel's canonical operand order here (the
    # scalar-prefetch block up front, x0/noise/sampcfg just before the
    # cache block) — ONE wrapper instead of a per-mode branch ladder,
    # so new mode compositions cannot silently miss a re-site.
    nc = 4 if dims.kv_quant else 2  # trailing cache-block operand count
    n_pre = int(bool(dims.page)) + int(dims.eos) + int(dims.ring)
    n_mid = int(dims.prefill) + int(dims.sampled) + int(dims.filtered)

    def run(kv_len, tokens, *args):
        pre, mid, wargs = (
            args[:n_pre], args[n_pre:n_pre + n_mid], args[n_pre + n_mid:]
        )
        return call(
            table, kv_len, tokens, *pre, *wargs[:-nc], *mid, *wargs[-nc:]
        )

    return run
