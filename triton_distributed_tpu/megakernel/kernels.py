"""Device-side megakernel task bodies.

Parity: reference ``mega_triton_kernel/kernels/*`` — the per-task device
code (linear 99, flash_attn 232, norm 227, allreduce 65, …) dispatched by
the generated megakernel, plus ``task_context.py``'s ``Scoreboard``
(:107 ``wait_deps``, :126 ``release_tile``).

TPU redesign (SURVEY.md §7 "megakernel scoreboard" hard part): the
sequential Pallas grid discharges intra-chip dependencies by schedule
order, so no scoreboard polling exists; tile-level overlap lives inside
each body as a double-buffered HBM→VMEM weight pipeline (the DMA engines
fetch tile ``j+1`` while the MXU consumes tile ``j``), and the only
cross-chip task (ALLREDUCE) synchronizes with DMA semaphores — dataflow,
not shared-memory spinning. Activations never touch HBM: the residual
stream ``x``, branch input ``h``, qkv, attention output, and MLP
activations all live in VMEM scratch for the whole decode step, which is
the megakernel's fusion win (the reference keeps them in L2/HBM between
task tiles).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from triton_distributed_tpu import language as dl
from triton_distributed_tpu.megakernel.registry import register_task
from triton_distributed_tpu.megakernel.task import TaskType


def _rms(x: jax.Array, w: jax.Array, eps: float) -> jax.Array:
    """f32 RMS-norm (matches ``models.qwen.rms_norm``)."""
    x = x * jax.lax.rsqrt(jnp.mean(x * x, axis=-1, keepdims=True) + eps)
    return x * w.astype(jnp.float32)


def _stream_cols(kctx, x_f32, w_hbm, n: int, tn: int, consume, col0: int = 0):
    """Column-streamed GEMM: ``x [B, K] @ w_hbm [K, col0:col0+n*tn]``
    tile-by-tile.

    Double-buffered: tile ``j+1``'s DMA runs under tile ``j``'s matmul
    (parity role: the reference linear task's tile pipeline,
    ``mega_triton_kernel/kernels/linear.py``). ``consume(j, val)`` sinks
    each ``[B, tn]`` f32 product.
    """
    stage, sem = kctx.colstage, kctx.wsem
    k = x_f32.shape[1]
    xa = x_f32.astype(kctx.wdtype)

    def copy(j, slot):
        return pltpu.make_async_copy(
            w_hbm.at[:, pl.ds(col0 + j * tn, tn)],
            stage.at[slot, :k, :tn],
            sem.at[slot],
        )

    copy(0, 0).start()

    def body(j, carry):
        slot = jax.lax.rem(j, 2)

        @pl.when(j + 1 < n)
        def _prefetch():
            copy(j + 1, 1 - slot).start()

        copy(j, slot).wait()
        val = jnp.dot(
            xa, stage[slot, :k, :tn], preferred_element_type=jnp.float32
        )
        consume(j, val)
        return carry

    jax.lax.fori_loop(0, n, body, 0, unroll=False)


def _stream_rows(kctx, x_ref, w_hbm, out_ref, n: int, tk: int):
    """Row-streamed GEMM with accumulation: ``out += x [B, K] @ w [K, d]``
    streaming K tiles (o-proj / fc2 shape class). Overwrites ``out_ref``.

    ``x_ref`` must be a (VMEM) ref: the K tile is sliced per step with a
    dynamic ``pl.ds`` on the ref — Mosaic has no lowering for
    ``dynamic_slice`` on register values, only for ref loads.
    """
    stage, sem = kctx.rowstage, kctx.wsem
    d = out_ref.shape[-1]

    def copy(j, slot):
        return pltpu.make_async_copy(
            w_hbm.at[pl.ds(j * tk, tk), :],
            stage.at[slot, :tk, :d],
            sem.at[slot],
        )

    copy(0, 0).start()
    out_ref[...] = jnp.zeros_like(out_ref)

    def body(j, carry):
        slot = jax.lax.rem(j, 2)

        @pl.when(j + 1 < n)
        def _prefetch():
            copy(j + 1, 1 - slot).start()

        copy(j, slot).wait()
        val = jnp.dot(
            x_ref[:, pl.ds(j * tk, tk)].astype(kctx.wdtype),
            stage[slot, :tk, :d],
            preferred_element_type=jnp.float32,
        )
        out_ref[...] = out_ref[...] + val
        return carry

    jax.lax.fori_loop(0, n, body, 0, unroll=False)


# -- task bodies -------------------------------------------------------------

@register_task(TaskType.EMBED)
def embed_body(kctx):
    def body():
        B = kctx.dims.batch

        def row(b):
            return pltpu.make_async_copy(
                kctx.embed.at[kctx.tokens[b]], kctx.estage.at[b], kctx.esem
            )

        for b in range(B):
            row(b).start()
        for b in range(B):
            row(b).wait()
        kctx.x[...] = kctx.estage[...].astype(jnp.float32)

    return body


@register_task(TaskType.NORM)
def norm_body(kctx):
    def body():
        eps = kctx.dims.rms_eps
        xv = kctx.x[...]

        @pl.when(kctx.arg0 == 0)
        def _ln1():
            kctx.h[...] = _rms(xv, kctx.ln1[kctx.layer], eps)

        @pl.when(kctx.arg0 == 1)
        def _ln2():
            kctx.h[...] = _rms(xv, kctx.ln2[kctx.layer], eps)

        @pl.when(kctx.arg0 == 2)
        def _final():
            kctx.h[...] = _rms(xv, kctx.normf[...], eps)

    return body


@register_task(TaskType.QKV_PROJ)
def qkv_body(kctx):
    def body():
        dims = kctx.dims
        tn = kctx.cfg.tn_qkv
        n = dims.qkv_loc // tn

        def sink(j, val):
            kctx.qkv[:, pl.ds(j * tn, tn)] = val

        _stream_cols(kctx, kctx.h[...], kctx.wqkv.at[kctx.layer], n, tn, sink)

    return body


@register_task(TaskType.ATTN)
def attn_body(kctx):
    """RoPE + QK-norm + cache append + GQA flash-decode (online softmax
    over double-buffered KV blocks). Parity: reference attn task
    (``mega_triton_kernel/kernels/flash_attn.py``) + paged-KV append."""

    def body():
        dims = kctx.dims
        B, hq, hkv, hd = dims.batch, dims.hq_loc, dims.hkv_loc, dims.head_dim
        g = hq // hkv
        eps, theta = dims.rms_eps, dims.rope_theta
        layer = kctx.layer
        pos = [kctx.kv_len[b] for b in range(B)]

        qkv = kctx.qkv[...]  # [B, (hq + 2 hkv) hd] f32
        q = qkv[:, : hq * hd].reshape(B, hq, hd)
        knew = qkv[:, hq * hd:(hq + hkv) * hd].reshape(B, hkv, hd)
        vnew = qkv[:, (hq + hkv) * hd:].reshape(B, hkv, hd)

        def headnorm(t, w):
            return t * jax.lax.rsqrt(
                jnp.mean(t * t, axis=-1, keepdims=True) + eps
            ) * w.astype(jnp.float32)

        q = headnorm(q, kctx.qn[layer])
        knew = headnorm(knew, kctx.kn[layer])

        # iota (not arange): concrete arrays would be captured consts,
        # which pallas_call rejects. Integer iota only — Mosaic's
        # tpu.iota verifier rejects float result types.
        i2 = (
            jax.lax.broadcasted_iota(jnp.int32, (1, hd // 2), 1)
            .astype(jnp.float32) * 2.0
        )
        inv = 1.0 / (theta ** (i2 / hd))  # [1, hd/2]

        def rope(t, p):  # t [h, hd], p scalar
            ang = p.astype(jnp.float32) * inv
            cos, sin = jnp.cos(ang), jnp.sin(ang)
            t1, t2 = t[:, : hd // 2], t[:, hd // 2:]
            return jnp.concatenate(
                [t1 * cos - t2 * sin, t2 * cos + t1 * sin], axis=-1
            )

        q = jnp.stack([rope(q[b], pos[b]) for b in range(B)])
        knew = jnp.stack([rope(knew[b], pos[b]) for b in range(B)])

        # Append at position kv_len[b] via staged DMA into the cache.
        kctx.knew_st[...] = knew.astype(kctx.cdtype)
        kctx.vnew_st[...] = vnew.astype(kctx.cdtype)

        def appends(b):
            return (
                pltpu.make_async_copy(
                    kctx.knew_st.at[b], kctx.kc.at[layer, b, :, pos[b], :],
                    kctx.osem,
                ),
                pltpu.make_async_copy(
                    kctx.vnew_st.at[b], kctx.vc.at[layer, b, :, pos[b], :],
                    kctx.osem,
                ),
            )

        for b in range(B):
            ka, va = appends(b)
            ka.start()
            va.start()
        for b in range(B):
            ka, va = appends(b)
            ka.wait()
            va.wait()

        # Online-softmax decode over KV blocks, double-buffered. The
        # block loop is bounded by the furthest live position, not
        # s_max — per-step cost is O(kv_len), the fori upper bound is
        # traced (parity role: the reference's split-KV sizing by
        # actual seq len, ``flash_decode.py:130``).
        sblk = kctx.cfg.s_blk
        maxpos = pos[0]
        for b in range(1, B):
            maxpos = jnp.maximum(maxpos, pos[b])
        nblk = maxpos // sblk + 1  # blocks overlapping [0, maxpos]
        scale = hd ** -0.5

        def kv_copy(j, slot):
            return (
                pltpu.make_async_copy(
                    kctx.kc.at[layer, :, :, pl.ds(j * sblk, sblk), :],
                    kctx.kstage.at[slot], kctx.ksem.at[slot],
                ),
                pltpu.make_async_copy(
                    kctx.vc.at[layer, :, :, pl.ds(j * sblk, sblk), :],
                    kctx.vstage.at[slot], kctx.vsem.at[slot],
                ),
            )

        kc0, vc0 = kv_copy(0, 0)
        kc0.start()
        vc0.start()

        neg = jnp.float32(-1e30)
        m0 = jnp.full((B, hq, 1), neg, jnp.float32)
        l0 = jnp.zeros((B, hq, 1), jnp.float32)
        a0 = jnp.zeros((B, hq, hd), jnp.float32)

        def blk(j, carry):
            m, l, acc = carry
            slot = jax.lax.rem(j, 2)

            @pl.when(j + 1 < nblk)
            def _prefetch():
                kn_, vn_ = kv_copy(j + 1, 1 - slot)
                kn_.start()
                vn_.start()

            kc_, vc_ = kv_copy(j, slot)
            kc_.wait()
            vc_.wait()
            kb = kctx.kstage[slot].astype(jnp.float32)  # [B, hkv, sblk, hd]
            vb = kctx.vstage[slot].astype(jnp.float32)
            idx = j * sblk + jax.lax.broadcasted_iota(jnp.int32, (1, sblk), 1)

            rows = []
            for b in range(B):
                valid = idx <= pos[b]  # [1, sblk] — includes appended token
                for h in range(hkv):
                    s = jnp.dot(
                        q[b, h * g:(h + 1) * g], kb[b, h].T,
                        preferred_element_type=jnp.float32,
                    ) * scale  # [g, sblk]
                    rows.append(jnp.where(valid, s, neg))
            s_all = jnp.stack(rows).reshape(B, hq, sblk)

            m_new = jnp.maximum(m, jnp.max(s_all, axis=-1, keepdims=True))
            p = jnp.exp(s_all - m_new)
            corr = jnp.exp(m - m_new)
            l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
            pv_rows = []
            for b in range(B):
                for h in range(hkv):
                    pv_rows.append(jnp.dot(
                        p[b, h * g:(h + 1) * g], vb[b, h],
                        preferred_element_type=jnp.float32,
                    ))  # [g, hd]
            pv = jnp.stack(pv_rows).reshape(B, hq, hd)
            acc = acc * corr + pv
            return m_new, l, acc

        _, l, acc = jax.lax.fori_loop(0, nblk, blk, (m0, l0, a0), unroll=False)
        kctx.ao[...] = (acc / l).reshape(B, hq * hd)

    return body


@register_task(TaskType.O_PROJ)
def o_proj_body(kctx):
    def body():
        dims = kctx.dims
        tk = kctx.cfg.tk_o
        n = (dims.hq_loc * dims.head_dim) // tk
        _stream_rows(
            kctx, kctx.ao, kctx.wo.at[kctx.layer], kctx.h, n, tk
        )

    return body


@register_task(TaskType.FC1)
def fc1_body(kctx):
    """Gate pass then up pass over the fused ``[d, gate_loc | up_loc]``
    shard layout (``models.qwen._fuse_by_shard``); silu·mul fused into
    the sinks — the reference's separate activation/elementwise tasks
    (``tasks/activation.py``) fold into this body on TPU."""

    def body():
        dims = kctx.dims
        tn = kctx.cfg.tn_fc1
        n = dims.f_loc // tn
        h = kctx.h[...]
        w1 = kctx.w1.at[kctx.layer]

        def sink_gate(j, val):
            kctx.mlp[:, pl.ds(j * tn, tn)] = val * jax.lax.logistic(val)

        _stream_cols(kctx, h, w1, n, tn, sink_gate, col0=0)

        def sink_up(j, val):
            sl = pl.ds(j * tn, tn)
            kctx.mlp[:, sl] = kctx.mlp[:, sl] * val

        _stream_cols(kctx, h, w1, n, tn, sink_up, col0=dims.f_loc)

    return body


@register_task(TaskType.FC2)
def fc2_body(kctx):
    def body():
        dims = kctx.dims
        tk = kctx.cfg.tk_fc2
        n = dims.f_loc // tk
        _stream_rows(
            kctx, kctx.mlp, kctx.w2.at[kctx.layer], kctx.h, n, tk
        )

    return body


@register_task(TaskType.ALLREDUCE)
def allreduce_body(kctx):
    """``x += psum(h)`` over the tp axis: one-shot broadcast into
    symmetric workspace slots + local reduction, trailing barrier.

    Parity: the reference's in-megakernel allreduce task
    (``tasks/allreduce.py``, ``kernels/allreduce.py``) which likewise
    pushes partials to peer symmetric buffers. The trailing barrier
    bounds cross-rank skew so slot reuse by the NEXT allreduce task is
    race-free — the role the reference's scoreboard release plays.
    """

    def body():
        axis = kctx.axis
        n = kctx.dims.n_ranks
        me = jax.lax.axis_index(axis)
        h = kctx.h[...]
        kctx.arsrc[...] = h

        def put(p):
            dst = jax.lax.rem(me + p, n)
            return pltpu.make_async_remote_copy(
                src_ref=kctx.arsrc,
                dst_ref=kctx.cbuf.at[me],
                send_sem=kctx.arsend,
                recv_sem=kctx.arrecv.at[me],
                device_id={axis: dst},
                device_id_type=pltpu.DeviceIdType.MESH,
            )

        for p in range(1, n):
            put(p).start()

        acc = kctx.x[...] + h
        for p in range(1, n):
            src = jax.lax.rem(me + p, n)
            pltpu.make_async_copy(
                kctx.cbuf.at[src], kctx.arsrc, kctx.arrecv.at[src]
            ).wait()
            # The DMA above waits arrival only (src == dst ref trick is
            # not used here: read the landed slot directly).
            acc = acc + kctx.cbuf[src]
        kctx.x[...] = acc
        for p in range(1, n):
            put(p).wait_send()
        dl.barrier_all(axis)

    return body


@register_task(TaskType.LM_HEAD)
def lm_head_body(kctx):
    def body():
        dims = kctx.dims
        tn = kctx.cfg.tn_lm
        n = dims.v_loc // tn

        def sink(j, val):
            kctx.logits[:, pl.ds(j * tn, tn)] = val

        _stream_cols(kctx, kctx.h[...], kctx.lm_head, n, tn, sink)

    return body


@register_task(TaskType.BARRIER)
def barrier_body(kctx):
    def body():
        dl.barrier_all(kctx.axis)

    return body
