"""Device-side megakernel task bodies.

Parity: reference ``mega_triton_kernel/kernels/*`` — the per-task device
code (linear 99, flash_attn 232, norm 227, allreduce 65, …) dispatched by
the generated megakernel, plus ``task_context.py``'s ``Scoreboard``
(:107 ``wait_deps``, :126 ``release_tile``).

TPU redesign (SURVEY.md §7 "megakernel scoreboard" hard part): the
sequential Pallas grid discharges intra-chip dependencies by schedule
order, so no scoreboard polling exists; tile-level overlap lives inside
each body as a double-buffered HBM→VMEM weight pipeline (the DMA engines
fetch tile ``j+1`` while the MXU consumes tile ``j``), and the only
cross-chip task (ALLREDUCE) synchronizes with DMA semaphores — dataflow,
not shared-memory spinning. Activations never touch HBM: the residual
stream ``x``, branch input ``h``, qkv, attention output, and MLP
activations all live in VMEM scratch for the whole decode step, which is
the megakernel's fusion win (the reference keeps them in L2/HBM between
task tiles).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from triton_distributed_tpu import language as dl
from triton_distributed_tpu.megakernel.registry import register_task
from triton_distributed_tpu.megakernel.task import TR_MID, TaskType


# -- device task tracer (docs/observability.md "Device task tracer") ---------
#
# Candidate cycle-counter primitives, probed in order: jaxlib 0.4.x
# exposes none publicly, so the tracer's default clock is a LOGICAL
# one — an SMEM counter bumped once per read. The Pallas grid is
# sequential on a TPU core, so the logical clock is monotonic and
# race-free by construction; under interpret it is fully deterministic.
# On a jaxlib that grows a cycle counter the same records carry real
# cycle timestamps with no decoder change (the decoder treats clock
# values as opaque monotonic ticks either way).
_CYCLE_PRIMS = ("get_cycle_count", "cycle_count", "get_timestamp")


def trace_tick(kctx):
    """One monotonic device-clock read for a trace-ring record: the
    TPU cycle counter when the installed Pallas exposes one (Mosaic
    builds only — interpret always uses the logical clock so tests are
    deterministic), else the SMEM logical clock."""
    if not kctx.interpret:
        for name in _CYCLE_PRIMS:
            prim = getattr(pltpu, name, None)
            if prim is not None:
                return prim().astype(jnp.int32)
    c = kctx.clk[0] + 1
    kctx.clk[0] = c
    return c


def trace_mid(kctx):
    """Stamp the CURRENT task's optional intra-task phase mark (the
    record's ``mid`` field) — the AR bodies call it where their comm
    phase hands off, so the decoder can split issue-time from blocked
    wait. A Python-level no-op when the build is untraced (the traced
    kernel carries zero extra ops with the tracer off)."""
    if getattr(kctx.dims, "trace", False) and kctx.trace_out is not None:
        kctx.trace_out[kctx.step, kctx.t, TR_MID] = trace_tick(kctx)


def trace_stamp(kctx, value):
    """Stamp an arbitrary VALUE (not a clock read) into the current
    task's ``mid`` column — the RING_POLL task records the doorbell it
    observed so ``validate_ring`` can prove the round consumed the
    ring state the host published (mid-as-payload records are exempt
    from the decoder's begin<=mid<=end clock check by opcode). No-op
    when untraced, same as :func:`trace_mid`."""
    if getattr(kctx.dims, "trace", False) and kctx.trace_out is not None:
        kctx.trace_out[kctx.step, kctx.t, TR_MID] = value


def _rms(x: jax.Array, w: jax.Array, eps: float) -> jax.Array:
    """f32 RMS-norm (matches ``models.qwen.rms_norm``)."""
    x = x * jax.lax.rsqrt(jnp.mean(x * x, axis=-1, keepdims=True) + eps)
    return x * w.astype(jnp.float32)


def _headnorm(t: jax.Array, w: jax.Array, eps: float) -> jax.Array:
    """Per-head QK RMS-norm on ``[r, hd]`` rows (shared by the decode
    and prefill attention tasks)."""
    return t * jax.lax.rsqrt(
        jnp.mean(t * t, axis=-1, keepdims=True) + eps
    ) * w.astype(jnp.float32)


def _make_rope(hd: int, theta: float):
    """RoPE over the full lane width as ``rope(t, ang_{cos,sin})``.

    The angle repeats per half and the rotate-half operand is a lane
    roll + sign flip — one tpu.rotate instead of the unaligned hd/2
    lane slices Mosaic can't form. iota (not arange): concrete arrays
    would be captured consts, which pallas_call rejects; integer iota
    only — Mosaic's tpu.iota verifier rejects float result types.

    Returns ``(angle, rope)``: ``angle(p)`` maps positions ``p``
    (broadcastable against ``[·, hd]``) to the per-lane angle, and
    ``rope(t, ang)`` applies the rotation.
    """
    lane = jax.lax.broadcasted_iota(jnp.int32, (1, hd), 1)
    half = jnp.remainder(lane, hd // 2).astype(jnp.float32)
    inv = 1.0 / (theta ** (2.0 * half / hd))  # [1, hd]
    sign = jnp.where(lane < hd // 2, -1.0, 1.0)

    def angle(p):
        return p.astype(jnp.float32) * inv

    def rope(t, ang):
        rot = pltpu.roll(t, hd // 2, 1) * sign
        return t * jnp.cos(ang) + rot * jnp.sin(ang)

    return angle, rope


def col_tile_copy(stage, sem, w_hbm, k, col0, w, slot):
    """The column-stream's tile DMA descriptor — ONE definition shared
    by ``_stream_cols`` and the cross_prefetch block in
    ``code_generator.py``: a prefetched tile-0 must BYTE-MATCH the
    stream's own ``copy(0)`` (same refs/slices/semaphore) or the wait
    accounting breaks, so both build it here."""
    return pltpu.make_async_copy(
        w_hbm.at[:, pl.ds(col0, w)], stage.at[slot, :k, :w], sem.at[slot]
    )


def row_tile_copy(stage, sem, w_hbm, row0, tk, d, slot):
    """Row-stream analog of :func:`col_tile_copy` (same sharing
    contract)."""
    return pltpu.make_async_copy(
        w_hbm.at[pl.ds(row0, tk), :], stage.at[slot, :tk, :d], sem.at[slot]
    )


# The ONE per-task-type table of tile-0 prefetch descriptors, kept next
# to the task bodies whose streams must match them (each entry mirrors
# its body's ``_stream_cols``/``_stream_rows`` call: same weight ref,
# same tile width, k == dims.d, col0/row0 == 0 — the streams assert
# those invariants when consuming the prefetch flag). The cross_prefetch
# block in ``code_generator.py`` builds its dispatch from this table.
# Entries take ``(nl, na0)`` — the NEXT task's layer id and arg0 (the
# local expert id for MOE_FFN; ignored by the dense entries). MoE
# builds swap the dense FC1/FC2 entries for MOE_FFN: their w1/w2
# operands are per-expert stacks there, and a dense-shaped descriptor
# would not even trace.
def stream_tile0_table(kctx):
    d = kctx.dims.d
    cfg = kctx.cfg
    col, row = [], []
    col.append((TaskType.QKV_PROJ, lambda nl, na0: col_tile_copy(
        kctx.colstage, kctx.wsem, kctx.wqkv.at[nl], d, 0, cfg.tn_qkv, 0)))
    if kctx.dims.moe:
        col.append((TaskType.MOE_FFN, lambda nl, na0: col_tile_copy(
            kctx.colstage, kctx.wsem, kctx.w1.at[nl, na0], d, 0,
            cfg.tn_fc1, 0)))
    else:
        col.append((TaskType.FC1, lambda nl, na0: col_tile_copy(
            kctx.colstage, kctx.wsem, kctx.w1.at[nl], d, 0, cfg.tn_fc1, 0)))
        row.append((TaskType.FC2, lambda nl, na0: row_tile_copy(
            kctx.rowstage, kctx.wsem, kctx.w2.at[nl], 0, cfg.tk_fc2, d, 0)))
    col.append((TaskType.LM_HEAD, lambda nl, na0: col_tile_copy(
        kctx.colstage, kctx.wsem, kctx.lm_head, d, 0, cfg.tn_lm, 0)))
    row.append((TaskType.O_PROJ, lambda nl, na0: row_tile_copy(
        kctx.rowstage, kctx.wsem, kctx.wo.at[nl], 0, cfg.tk_o, d, 0)))
    return col, row


def fire_next_tile0(kctx):
    """Start the NEXT task's first weight-tile DMA and set the
    cross_prefetch handshake flag — THE one implementation of the
    prefetch fire, shared by the generated per-task epilogue
    (``code_generator.py``) and the AR_WAIT/A2A_WAIT bodies (which fire
    it BEFORE blocking on the inbound partials, so the ICI hop hides
    under the next weight stream's tile-0 HBM traffic). Both sites must
    byte-match the stream's own ``copy(0)``; sharing the fire keeps
    that a structural guarantee."""
    T = pl.num_programs(1)
    t = kctx.t

    @pl.when(t + 1 < T)
    def _fire():
        nt = kctx.task_tab[t + 1, 0]
        nl = kctx.task_tab[t + 1, 1]
        na0 = kctx.task_tab[t + 1, 2]
        col_tab, row_tab = stream_tile0_table(kctx)

        for tt, make in col_tab:
            def fire(make=make):
                make(nl, na0).start()
                kctx.pre_col[0] = 1

            pl.when(nt == int(tt))(fire)
        for tt, make in row_tab:
            def fire(make=make):
                make(nl, na0).start()
                kctx.pre_row[0] = 1

            pl.when(nt == int(tt))(fire)


def _stream_cols(kctx, x_f32, w_hbm, n: int, tn: int, consume,
                 col0: int = 0, tail: int = 0, carry=None):
    """Column-streamed GEMM: ``x [B, K] @ w_hbm [K, col0:col0+n*tn]``
    tile-by-tile, plus an optional ``tail``-wide final tile when ``tn``
    doesn't divide the column count (the LM head's vocab axis).

    Depth-``nbuf`` pipelined: up to ``nbuf - 1`` tile DMAs stay in
    flight ahead of the consuming matmul (parity role: the reference
    linear task's tile pipeline,
    ``mega_triton_kernel/kernels/linear.py``); the tail tile joins the
    same pipeline. The weight stream is the decode step's HBM floor —
    per-tile control overhead is comparable to a 2 MB tile's wire time,
    so one-deep prefetch leaves the HBM controller idle between tiles.
    ``consume(j, val)`` sinks each f32 product — ``val.shape[1]`` is
    ``tn`` for main tiles and ``tail`` for the final one. With
    ``carry`` set, ``consume(j, val, carry) -> carry`` threads loop
    state through the tiles (the LM head's running argmax) and the
    final carry is returned.
    """
    stage, sem = kctx.colstage, kctx.wsem
    depth = stage.shape[0]
    k = x_f32.shape[1]
    xa = x_f32.astype(kctx.wdtype)
    stateful = carry is not None
    total = n + (1 if tail else 0)  # tile index n = the tail tile

    def copy(j, slot, w=None):
        w = tn if w is None else w
        return col_tile_copy(stage, sem, w_hbm, k, col0 + j * tn, w, slot)

    def start(j):
        return copy(j, j % depth, tail if j == n else None)

    # Prologue: fill the pipeline (static — n, tail, depth are Python
    # ints here). Under cross_prefetch, tile 0 may already be in flight
    # (started by the previous task's prefetch block with an identical
    # descriptor) — consume the flag and skip the duplicate start.
    if kctx.cfg.cross_prefetch:
        # Prefetched tile-0 descriptors (stream_tile0_table) assume
        # k == d, col0 == 0, and a full-width first tile (n >= 1 — a
        # tail-only stream's copy(0) would be tail-width and break the
        # byte match); fail at trace time instead of corrupting. A
        # hard raise (not assert): under ``python -O`` an assert would
        # vanish and the mismatch would become a silent DMA-descriptor
        # mismatch at run time.
        if not (col0 == 0 and k == kctx.dims.d and n >= 1):
            raise ValueError(
                "cross_prefetch byte-match invariant violated: need "
                f"col0 == 0, k == d ({kctx.dims.d}), n >= 1; got "
                f"col0={col0}, k={k}, n={n}"
            )
        pre = kctx.pre_col[0]
        kctx.pre_col[0] = 0
    for j in range(min(depth - 1, total)):
        if j == 0 and kctx.cfg.cross_prefetch:
            pl.when(pre == 0)(lambda: start(0).start())
        else:
            start(j).start()

    def wtile(slot, w):
        wt = stage[slot, :k, :w]
        # wq8: int8 staging tiles upcast at the MXU's doorstep (VPU op
        # pipelined under the next tile's DMA); scales apply in the
        # sinks, per output column.
        return wt.astype(xa.dtype) if wt.dtype == jnp.int8 else wt

    def body(j, c):
        slot = jax.lax.rem(j, depth)
        p = j + depth - 1  # tile to prefetch, keeping depth-1 in flight

        @pl.when(p < n)
        def _prefetch():
            copy(p, jax.lax.rem(p, depth)).start()

        if tail:
            @pl.when(p == n)
            def _prefetch_tail():
                copy(n, jax.lax.rem(p, depth), tail).start()

        copy(j, slot).wait()
        val = jnp.dot(
            xa, wtile(slot, tn), preferred_element_type=jnp.float32
        )
        if stateful:
            return consume(j, val, c)
        consume(j, val)
        return c

    carry = jax.lax.fori_loop(
        0, n, body, carry if stateful else 0, unroll=False
    )

    if tail:
        slot = n % depth
        if depth == 1:
            # Serial mode starts each tile at its own iteration; the
            # tail has no iteration of its own — start it here.
            copy(n, slot, tail).start()
        copy(n, slot, tail).wait()
        val = jnp.dot(
            xa, wtile(slot, tail), preferred_element_type=jnp.float32
        )
        if stateful:
            carry = consume(n, val, carry)
        else:
            consume(n, val)
    return carry


def _stream_rows(kctx, x_ref, w_hbm, out_ref, n: int, tk: int,
                 scale_row=None, col_scale=None, accumulate=False):
    """Row-streamed GEMM with accumulation: ``out += x [B, K] @ w [K, d]``
    streaming K tiles (o-proj / fc2 shape class). Overwrites ``out_ref``
    unless ``accumulate`` (the MoE expert loop folds every expert's
    weighted output into the same combine accumulator).

    ``x_ref`` must be a (VMEM) ref: the K tile is sliced per step with a
    dynamic ``pl.ds`` on the ref — Mosaic has no lowering for
    ``dynamic_slice`` on register values, only for ref loads.

    ``scale_row`` (wq8): a ``[1, d]`` f32 per-output-channel dequant
    row applied to every tile product — per-column constants distribute
    over the K-tile sum, so per-tile application is exact.

    ``col_scale``: a ``[B, 1]`` f32 per-BATCH-row scale (the MoE
    combine weight: gate probability of this expert per token, 0 for
    unrouted tokens) — per-row constants likewise distribute over the
    K-tile sum.
    """
    stage, sem = kctx.rowstage, kctx.wsem
    depth = stage.shape[0]
    d = out_ref.shape[-1]

    def copy(j, slot):
        return row_tile_copy(stage, sem, w_hbm, j * tk, tk, d, slot)

    # Pipeline fill; under cross_prefetch tile 0 may already be in
    # flight from the previous task's prefetch block (same descriptor).
    if kctx.cfg.cross_prefetch:
        # stream_tile0_table's byte-match assumption; raise (not
        # assert) so the guard survives ``python -O``.
        if d != kctx.dims.d:
            raise ValueError(
                "cross_prefetch byte-match invariant violated: row "
                f"stream width d={d} != model d={kctx.dims.d}"
            )
        pre = kctx.pre_row[0]
        kctx.pre_row[0] = 0
    for j in range(min(depth - 1, n)):
        if j == 0 and kctx.cfg.cross_prefetch:
            pl.when(pre == 0)(lambda: copy(0, 0).start())
        else:
            copy(j, j % depth).start()
    if not accumulate:
        out_ref[...] = jnp.zeros_like(out_ref)

    def body(j, carry):
        slot = jax.lax.rem(j, depth)
        p = j + depth - 1  # keep depth-1 tiles in flight

        @pl.when(p < n)
        def _prefetch():
            copy(p, jax.lax.rem(p, depth)).start()

        copy(j, slot).wait()
        wt = stage[slot, :tk, :d]
        if wt.dtype == jnp.int8:
            wt = wt.astype(kctx.wdtype)
        val = jnp.dot(
            x_ref[:, pl.ds(j * tk, tk)].astype(kctx.wdtype),
            wt,
            preferred_element_type=jnp.float32,
        )
        if scale_row is not None:
            val = val * scale_row
        if col_scale is not None:
            val = val * col_scale
        out_ref[...] = out_ref[...] + val
        return carry

    jax.lax.fori_loop(0, n, body, 0, unroll=False)


def _barrier(kctx):
    """Cross-rank barrier, skipped under the interpret path: discharge-
    based interpret executes every remote DMA synchronously at its
    program point, so the barrier's temporal ordering is vacuous there
    (and 0.4.x interpret has no barrier-semaphore support). Mosaic
    builds — including TPU-targeted AOT lowering traced on a CPU host —
    keep every barrier (``kctx.interpret`` comes from the build ctx,
    not the process backend)."""
    if not kctx.interpret:
        dl.barrier_all(kctx.axis)


def _ar_put_dmas(kctx):
    """The allreduce-workspace put descriptors (this rank's ``arsrc``
    into every peer's ``cbuf[me]`` slot) — ONE definition, because the
    split allreduce starts them in AR_SEND and send-waits them in
    AR_WAIT (a later grid iteration): reconstructed descriptors must
    byte-match or the semaphore accounting breaks (the col_tile_copy
    sharing contract, applied to remote copies)."""
    axis = kctx.axis
    nr = kctx.dims.n_ranks
    me = jax.lax.axis_index(axis)

    def put(p):
        dst = jax.lax.rem(me + p, nr)
        return pltpu.make_async_remote_copy(
            src_ref=kctx.arsrc,
            dst_ref=kctx.cbuf.at[me],
            send_sem=kctx.arsend,
            recv_sem=kctx.arrecv.at[me],
            device_id={axis: dst},
            device_id_type=pltpu.DeviceIdType.MESH,
        )

    return [put(p) for p in range(1, nr)]


def _ar_wait_recvs(kctx):
    """Wait every peer's inbound partial (the receive half of
    :func:`_ar_put_dmas`); afterwards all ``nr`` candidate slots of
    ``cbuf`` are valid."""
    nr = kctx.dims.n_ranks
    me = jax.lax.axis_index(kctx.axis)
    for p in range(1, nr):
        src = jax.lax.rem(me + p, nr)
        pltpu.make_async_copy(
            kctx.cbuf.at[src], kctx.arsrc, kctx.arrecv.at[src]
        ).wait()


def _a2a_put_dmas(kctx):
    """Phase-0 analog of :func:`_ar_put_dmas` over the dedicated MoE
    combine workspace (``a2src``/``a2buf``/``a2send``/``a2recv``): a
    separate buffer pair because phase 0's puts are still in flight
    while the second half of the expert GEMMs overwrites the combine
    accumulator — phase 1 then reuses the standard AR workspace, which
    the layer's attention allreduce has already quiesced. Same
    descriptor-sharing contract as ``_ar_put_dmas`` (A2A_SEND starts
    these, A2A_WAIT send-waits byte-matched reconstructions)."""
    axis = kctx.axis
    nr = kctx.dims.n_ranks
    me = jax.lax.axis_index(axis)

    def put(p):
        dst = jax.lax.rem(me + p, nr)
        return pltpu.make_async_remote_copy(
            src_ref=kctx.a2src,
            dst_ref=kctx.a2buf.at[me],
            send_sem=kctx.a2send,
            recv_sem=kctx.a2recv.at[me],
            device_id={axis: dst},
            device_id_type=pltpu.DeviceIdType.MESH,
        )

    return [put(p) for p in range(1, nr)]


def _a2a_wait_recvs(kctx):
    """Wait every peer's inbound phase-0 combine partial (the receive
    half of :func:`_a2a_put_dmas`)."""
    nr = kctx.dims.n_ranks
    me = jax.lax.axis_index(kctx.axis)
    for p in range(1, nr):
        src = jax.lax.rem(me + p, nr)
        pltpu.make_async_copy(
            kctx.a2buf.at[src], kctx.a2src, kctx.a2recv.at[src]
        ).wait()


def _workspace_bcast(kctx, payload):
    """One-shot broadcast through the allreduce workspace: every rank
    writes ``payload`` ([B, d] f32) to peer slot ``cbuf[me]`` and waits
    for all ``nr`` candidates to land. Returns nothing — read
    ``kctx.cbuf[r]`` afterwards. The caller owns quiescence: traffic
    into cbuf must be fenced (barrier) before the slots are reused.

    Shared by the ALLREDUCE task and the LM head's cross-rank argmax;
    the split AR_SEND/AR_WAIT pair is this same exchange pulled apart
    so independent work can run between the two halves.
    """
    me = jax.lax.axis_index(kctx.axis)
    kctx.arsrc[...] = payload
    kctx.cbuf[me] = payload

    puts = _ar_put_dmas(kctx)
    for dma in puts:
        dma.start()
    _ar_wait_recvs(kctx)
    for dma in puts:
        dma.wait_send()


# -- task bodies -------------------------------------------------------------

@register_task(TaskType.EMBED)
def embed_body(kctx):
    """Token embedding lookup.

    The table arrives as ``[V/8, 8, d]`` (see ``MegaQwen3.build``): a
    single-row slice of the ``[V, d]`` HBM table breaks Mosaic's (8,128)
    tiling (bf16 packs row pairs), so the DMA fetches the aligned 8-row
    group and a one-hot ``[1, 8] @ [8, d]`` matmul selects the row — a
    dynamic sublane extract Mosaic can't otherwise prove aligned.
    """

    def body():
        B = kctx.dims.batch

        def tok(b):
            # Multi-step: steps after the first read the token the LM
            # head's in-kernel argmax fed back through SMEM.
            t = kctx.tokens[b]
            if kctx.dims.nsteps > 1:
                t = jnp.where(kctx.step == 0, t, kctx.tok_smem[0, b])
            return t

        toks = [tok(b) for b in range(B)]

        def group(b):
            return pltpu.make_async_copy(
                kctx.embed.at[toks[b] // 8], kctx.estage.at[b],
                kctx.esem,
            )

        for b in range(B):
            group(b).start()
        for b in range(B):
            group(b).wait()
        sub = jax.lax.broadcasted_iota(jnp.int32, (1, 8), 1)
        for b in range(B):
            onehot = (sub == toks[b] % 8).astype(jnp.float32)
            kctx.x[b:b + 1, :] = jnp.dot(
                onehot, kctx.estage[b].astype(jnp.float32),
                preferred_element_type=jnp.float32,
            )

    return body


def _q8_scale(kctx, sref, layer, col0, val):
    """Apply the per-output-channel dequant scale slice to a tile
    product (``wq8`` only; identity otherwise). ``col0`` is the tile's
    first output column (traced ``j * tn`` is fine — tn is a
    128-multiple, so the lane slice is provably aligned); ``layer`` is
    the traced layer id for per-layer scale planes, None for the LM
    head's single plane."""
    if not kctx.cfg.wq8:
        return val
    w = val.shape[1]
    sl = pl.ds(col0, w)
    s = sref[:, sl] if layer is None else sref[layer, :, sl]
    return val * s


def _normed_input(kctx, which: int):
    """The consumer's [B, d] f32 input: the NORM task's output (``h``)
    normally, or — with ``fuse_norms`` — the norm computed inline from
    the residual ``x`` (which: 0 = ln1/qkv, 1 = ln2/fc1, 2 = final/lm).
    The inline norm is a [B, d] vector op — negligible next to the
    task boundary it replaces."""
    if not kctx.cfg.fuse_norms:
        return kctx.h[...]
    eps = kctx.dims.rms_eps
    xv = kctx.x[...]
    if which == 0:
        return _rms(xv, kctx.ln1[kctx.layer], eps)
    if which == 1:
        return _rms(xv, kctx.ln2[kctx.layer], eps)
    return _rms(xv, kctx.normf[...], eps)


@register_task(TaskType.NORM)
def norm_body(kctx):
    def body():
        eps = kctx.dims.rms_eps
        xv = kctx.x[...]
        # Weights arrive as [L, 1, d] (see MegaQwen3.build): indexing
        # the untiled leading dim with the traced layer id yields a
        # [1, d] vector — a dynamic sublane slice of [L, d] would need
        # an 8-aligned index Mosaic can't prove.
        layer = kctx.layer

        @pl.when(kctx.arg0 == 0)
        def _ln1():
            kctx.h[...] = _rms(xv, kctx.ln1[layer], eps)

        @pl.when(kctx.arg0 == 1)
        def _ln2():
            kctx.h[...] = _rms(xv, kctx.ln2[layer], eps)

        @pl.when(kctx.arg0 == 2)
        def _final():
            kctx.h[...] = _rms(xv, kctx.normf[...], eps)

    return body


@register_task(TaskType.QKV_PROJ)
def qkv_body(kctx):
    def body():
        dims = kctx.dims
        tn = kctx.cfg.tn_qkv
        n = dims.qkv_loc // tn

        def sink(j, val):
            val = _q8_scale(kctx, kctx.sc_qkv, kctx.layer, j * tn, val)
            kctx.qkv[:, pl.ds(j * tn, tn)] = val

        _stream_cols(
            kctx, _normed_input(kctx, 0), kctx.wqkv.at[kctx.layer],
            n, tn, sink,
        )

    return body


@register_task(TaskType.ATTN)
def attn_body(kctx):
    """RoPE + QK-norm + cache append + GQA flash-decode (online softmax
    over double-buffered KV blocks). Parity: reference attn task
    (``mega_triton_kernel/kernels/flash_attn.py``) + paged-KV append."""

    def body():
        dims = kctx.dims
        B, hq, hkv, hd = dims.batch, dims.hq_loc, dims.hkv_loc, dims.head_dim
        g = hq // hkv
        eps, theta = dims.rms_eps, dims.rope_theta
        layer = kctx.layer
        # cache_len masks the cached rows (the cache never holds this
        # launch's rows); pos is the CURRENT token's position — in
        # multi-step launches it advances with the in-launch step
        # (program_id(0), constant 0 in single-step builds).
        cache_len = [kctx.kv_len[b] for b in range(B)]
        pos = [cache_len[b] + kctx.step for b in range(B)]

        # Mosaic has no lane-splitting shape casts ([B, h·hd] → [B, h,
        # hd] is rejected by infer-vector-layout), so heads stay 2-D
        # throughout: per (batch, kv-head) the q group is assembled from
        # [1, hd] lane slices of the qkv vector (offsets are multiples
        # of hd = 128 on real configs) and all attention math runs on
        # [g, ·] tiles.
        qkv = kctx.qkv[...]  # [B, (hq + 2 hkv) hd] f32
        qn = kctx.qn[layer]  # [L, 1, hd] ref → [1, hd]
        kn = kctx.kn[layer]
        angle, rope_fn = _make_rope(hd, theta)

        def headnorm(t, w):
            return _headnorm(t, w, eps)

        def rope(t, p):  # t [r, hd], p scalar position
            return rope_fn(t, angle(p))

        def head(i):  # q head i as [1, hd] rows per batch
            return [
                qkv[b:b + 1, i * hd:(i + 1) * hd] for b in range(B)
            ]

        scale = hd ** -0.5
        # q groups: qg[b][h] = [g, hd], normed + roped + prescaled.
        qg = [
            [
                rope(
                    headnorm(
                        jnp.concatenate(
                            [head(h * g + i)[b] for i in range(g)], axis=0
                        ),
                        qn,
                    ),
                    pos[b],
                ) * scale
                for h in range(hkv)
            ]
            for b in range(B)
        ]

        # New K (normed + roped) and V per (b, kv-head). The cache is
        # NOT written here — appending one row at a dynamic position in
        # a (8,128)-tiled plane is an unaligned slice Mosaic rejects —
        # so the rows go to the knew/vnew outputs (caller appends via
        # XLA dynamic_update_slice) and the new token's own attention
        # contribution is merged analytically after the block loop.
        knew_v: list[list] = []
        vnew_v: list[list] = []
        for b in range(B):
            krow, vrow = [], []
            for h in range(hkv):
                kbh = rope(headnorm(head(hq + h)[b], kn), pos[b])
                vbh = head(hq + hkv + h)[b]
                kctx.knew_out[kctx.step, layer, b, h:h + 1, :] = (
                    kbh.astype(kctx.cdtype)
                )
                kctx.vnew_out[kctx.step, layer, b, h:h + 1, :] = (
                    vbh.astype(kctx.cdtype)
                )
                krow.append(kbh)
                vrow.append(vbh)
            knew_v.append(krow)
            vnew_v.append(vrow)

        # Online-softmax decode over KV blocks, double-buffered. The
        # block loop is bounded by the furthest live position, not
        # s_max — per-step cost is O(kv_len), the fori upper bound is
        # traced (parity role: the reference's split-KV sizing by
        # actual seq len, ``flash_decode.py:130``).
        sblk = kctx.cfg.s_blk
        maxpos = cache_len[0]
        for b in range(1, B):
            maxpos = jnp.maximum(maxpos, cache_len[b])
        nblk = maxpos // sblk + 1  # blocks overlapping [0, maxpos]

        # Dense: one DMA per buffer covering all (b, h) for the block.
        # Paged (kctx.table set): block j of row b is pool page
        # table[b, j] — one [hkv, page, hd] DMA per batch row, with
        # s_blk == page_size (enforced by MegaQwen3.build).
        def kv_dmas(j, slot):
            if kctx.table is None:
                return [
                    pltpu.make_async_copy(
                        kctx.kc.at[layer, :, :, pl.ds(j * sblk, sblk), :],
                        kctx.kstage.at[slot], kctx.ksem.at[slot],
                    ),
                    pltpu.make_async_copy(
                        kctx.vc.at[layer, :, :, pl.ds(j * sblk, sblk), :],
                        kctx.vstage.at[slot], kctx.vsem.at[slot],
                    ),
                ]
            dmas = []
            for b in range(B):
                pid = kctx.table[b, j]
                dmas.append(pltpu.make_async_copy(
                    kctx.kc.at[layer, pid],
                    kctx.kstage.at[slot, b], kctx.ksem.at[slot],
                ))
                dmas.append(pltpu.make_async_copy(
                    kctx.vc.at[layer, pid],
                    kctx.vstage.at[slot, b], kctx.vsem.at[slot],
                ))
            return dmas

        def kv_start(j, slot):
            for dma in kv_dmas(j, slot):
                dma.start()

        def kv_wait(j, slot):
            for dma in kv_dmas(j, slot):
                dma.wait()

        kv_start(0, 0)

        neg = jnp.float32(-1e30)
        nt = (((1,), (1,)), ((), ()))  # q [g, hd] · k [sblk, hd]ᵀ
        init = tuple(
            (
                jnp.full((g, 1), neg, jnp.float32),
                jnp.zeros((g, 1), jnp.float32),
                jnp.zeros((g, hd), jnp.float32),
            )
            for _ in range(B * hkv)
        )

        def blk(j, carry):
            slot = jax.lax.rem(j, 2)

            @pl.when(j + 1 < nblk)
            def _prefetch():
                kv_start(j + 1, 1 - slot)

            kv_wait(j, slot)
            idx = j * sblk + jax.lax.broadcasted_iota(jnp.int32, (1, sblk), 1)

            out = []
            for b in range(B):
                valid = idx < cache_len[b]  # [1, sblk] — cached tokens only
                for h in range(hkv):
                    m, l, acc = carry[b * hkv + h]
                    kb = kctx.kstage[slot, b, h].astype(jnp.float32)
                    vb = kctx.vstage[slot, b, h].astype(jnp.float32)
                    if dims.kv_quant:
                        # int8 pool: dequantize the staged page block
                        # in-register under its (layer, page, head)
                        # scale — scalar reads off the VMEM-resident
                        # [L, P, 1, Hkv] planes ([L, P, 1, H] keeps the
                        # dynamic layer/page indices on untiled leading
                        # dims, the norm-weight trick). Full-width KV
                        # never exists in HBM.
                        pid = kctx.table[b, j]
                        kb = kb * kctx.ksc[layer, pid, 0, h]
                        vb = vb * kctx.vsc[layer, pid, 0, h]
                    s = jax.lax.dot_general(
                        qg[b][h], kb, nt,
                        preferred_element_type=jnp.float32,
                    )  # [g, sblk]
                    s = jnp.where(valid, s, neg)
                    m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
                    # Re-mask p: with every position masked (pos lands
                    # on a block boundary) exp(neg - neg) would be 1.
                    p = jnp.where(valid, jnp.exp(s - m_new), 0.0)
                    corr = jnp.exp(m - m_new)
                    l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
                    acc = acc * corr + jnp.dot(
                        p, vb, preferred_element_type=jnp.float32
                    )
                    out.append((m_new, l, acc))
            return tuple(out)

        final = jax.lax.fori_loop(0, nblk, blk, init, unroll=False)

        # Multi-step band: this launch's earlier steps' K/V rows live in
        # the knew/vnew outputs (never in the cache) — merge them into
        # the online softmax. Rows at steps >= kctx.step are unwritten
        # (arbitrary bits): the column mask drops their scores and the
        # row mask zeroes their V so no garbage can reach the output.
        NS = dims.nsteps
        if NS > 1:
            merged = []
            bcol = jax.lax.broadcasted_iota(jnp.int32, (1, NS), 1)
            brow = jax.lax.broadcasted_iota(jnp.int32, (NS, 1), 0)
            col_ok = bcol < kctx.step
            row_ok = brow < kctx.step
            for b in range(B):
                for h in range(hkv):
                    m, l, acc = final[b * hkv + h]
                    kband = jnp.concatenate(
                        [
                            kctx.knew_out[s2, layer, b, h:h + 1, :]
                            .astype(jnp.float32)
                            for s2 in range(NS)
                        ],
                        axis=0,
                    )  # [NS, hd]
                    vband = jnp.concatenate(
                        [
                            kctx.vnew_out[s2, layer, b, h:h + 1, :]
                            .astype(jnp.float32)
                            for s2 in range(NS)
                        ],
                        axis=0,
                    )
                    vband = jnp.where(row_ok, vband, 0.0)
                    s_band = jax.lax.dot_general(
                        qg[b][h], kband, nt,
                        preferred_element_type=jnp.float32,
                    )  # [g, NS]
                    s_band = jnp.where(col_ok, s_band, neg)
                    m_new = jnp.maximum(
                        m, jnp.max(s_band, axis=-1, keepdims=True)
                    )
                    p = jnp.where(col_ok, jnp.exp(s_band - m_new), 0.0)
                    corr = jnp.exp(m - m_new)
                    l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
                    acc = acc * corr + jnp.dot(
                        p, vband, preferred_element_type=jnp.float32
                    )
                    merged.append((m_new, l, acc))
            final = tuple(merged)

        # Merge the new token's own K/V contribution (it never entered
        # the cache) and write the normalized output.
        for b in range(B):
            for h in range(hkv):
                m, l, acc = final[b * hkv + h]
                s_self = jax.lax.dot_general(
                    qg[b][h], knew_v[b][h], nt,
                    preferred_element_type=jnp.float32,
                )  # [g, 1]
                m_f = jnp.maximum(m, s_self)
                corr = jnp.exp(m - m_f)
                p_self = jnp.exp(s_self - m_f)
                l = l * corr + p_self
                # Outer product as a K=1 matmul: the [g,1]×[1,hd]
                # vector.broadcast path trips Mosaic's layout inference
                # on the sliced vnew row.
                pv_self = jnp.dot(
                    p_self, vnew_v[b][h], preferred_element_type=jnp.float32
                )
                o = (acc * corr + pv_self) / l  # [g, hd]
                for i in range(g):
                    col = (h * g + i) * hd
                    kctx.ao[b:b + 1, col:col + hd] = o[i:i + 1]

    return body


@register_task(TaskType.LOAD_X)
def load_x_body(kctx):
    """Prefill entry: the embedded prompt rows arrive as a kernel input
    (XLA does the S-row gather — an in-kernel per-row embed DMA would
    need S unrolled dynamic-sublane stores Mosaic can't prove aligned)."""

    def body():
        kctx.x[...] = kctx.x0[...].astype(jnp.float32)

    return body


@register_task(TaskType.ATTN_PREFILL)
def attn_prefill_body(kctx):
    """Causal self-attention over the S prompt rows in the qkv scratch.

    Parity: the reference megakernel's prefill attention tasks
    (``mega_triton_kernel/models/model_builder.py:189-352``). The whole
    [S, S] score tile fits VMEM at prompt scale, so no KV streaming —
    per (kv-head, q-head) everything is 2-D: lane slices of qkv, the
    roll-based RoPE from the decode task applied with per-row
    positions, one masked softmax, and [S, hd] writes of K/V to the
    ``knew``/``vnew`` outputs (the caller scatters them into the cache,
    same contract as decode).
    """

    def body():
        dims = kctx.dims
        S = dims.batch  # prefill: rows are the prompt positions
        hq, hkv, hd = dims.hq_loc, dims.hkv_loc, dims.head_dim
        g = hq // hkv
        eps, theta = dims.rms_eps, dims.rope_theta
        layer = kctx.layer

        qkv = kctx.qkv[...]  # [S, (hq + 2 hkv) hd] f32
        qn = kctx.qn[layer]  # [1, hd]
        kn = kctx.kn[layer]
        angle, rope_fn = _make_rope(hd, theta)
        pos = jax.lax.broadcasted_iota(jnp.int32, (S, 1), 0)
        ang = angle(pos)  # [S, hd] — row r rotated by position r

        def headnorm(t, w):
            return _headnorm(t, w, eps)

        def rope(t):  # [S, hd]
            return rope_fn(t, ang)

        def head(i):  # [S, hd]
            return qkv[:, i * hd:(i + 1) * hd]

        rows = jax.lax.broadcasted_iota(jnp.int32, (S, S), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (S, S), 1)
        causal = cols <= rows
        neg = jnp.float32(-1e30)
        scale = hd ** -0.5
        nt = (((1,), (1,)), ((), ()))

        for h in range(hkv):
            kh = rope(headnorm(head(hq + h), kn))       # [S, hd]
            vh = head(hq + hkv + h)
            kctx.knew_out[layer, h] = kh.astype(kctx.cdtype)
            kctx.vnew_out[layer, h] = vh.astype(kctx.cdtype)
            for i in range(g):
                qi = rope(headnorm(head(h * g + i), qn)) * scale
                s = jax.lax.dot_general(
                    qi, kh, nt, preferred_element_type=jnp.float32
                )  # [S, S]
                s = jnp.where(causal, s, neg)
                m = jnp.max(s, axis=-1, keepdims=True)
                p = jnp.exp(s - m)
                l = jnp.sum(p, axis=-1, keepdims=True)
                o = jnp.dot(
                    p, vh, preferred_element_type=jnp.float32
                ) / l  # [S, hd]
                col = (h * g + i) * hd
                kctx.ao[:, col:col + hd] = o

    return body


@register_task(TaskType.O_PROJ)
def o_proj_body(kctx):
    def body():
        dims = kctx.dims
        tk = kctx.cfg.tk_o
        n = (dims.hq_loc * dims.head_dim) // tk
        scale = kctx.sc_o[kctx.layer] if kctx.cfg.wq8 else None
        _stream_rows(
            kctx, kctx.ao, kctx.wo.at[kctx.layer], kctx.h, n, tk,
            scale_row=scale,
        )

    return body


@register_task(TaskType.FC1)
def fc1_body(kctx):
    """One continuous column stream over the fused ``[d, gate | up]``
    shard layout (``models.qwen._fuse_by_shard``): tiles ``j < n`` are
    gate columns (silu into ``mlp``), tiles ``j >= n`` the matching up
    columns (multiply in place) — silu·mul fused into the sinks, the
    reference's separate activation/elementwise tasks
    (``tasks/activation.py``) fold into this body on TPU."""

    def body():
        dims = kctx.dims
        tn = kctx.cfg.tn_fc1
        n = dims.f_loc // tn
        h = _normed_input(kctx, 1)
        w1 = kctx.w1.at[kctx.layer]

        # ONE continuous stream over the fused [d, gate|up] plane —
        # tiles j < n are gate columns, j >= n the matching up columns
        # (the shard layout guarantees the offset is exactly f_loc).
        # One pipeline fill instead of two per layer, and the depth-nbuf
        # rotation never drains between the passes.
        def sink(j, val):
            # wq8 dequant BEFORE the nonlinearity (val*s is the true
            # product); sc_w1 shares w1's [1, gate|up] column layout so
            # j*tn indexes both regions directly.
            val = _q8_scale(kctx, kctx.sc_w1, kctx.layer, j * tn, val)

            @pl.when(j < n)
            def _gate():
                kctx.mlp[:, pl.ds(j * tn, tn)] = val * jax.lax.logistic(val)

            @pl.when(j >= n)
            def _up():
                sl = pl.ds((j - n) * tn, tn)
                kctx.mlp[:, sl] = kctx.mlp[:, sl] * val

        _stream_cols(kctx, h, w1, 2 * n, tn, sink, col0=0)

    return body


@register_task(TaskType.FC2)
def fc2_body(kctx):
    def body():
        dims = kctx.dims
        tk = kctx.cfg.tk_fc2
        n = dims.f_loc // tk
        scale = kctx.sc_w2[kctx.layer] if kctx.cfg.wq8 else None
        _stream_rows(
            kctx, kctx.mlp, kctx.w2.at[kctx.layer], kctx.h, n, tk,
            scale_row=scale,
        )

    return body


@register_task(TaskType.ALLREDUCE)
def allreduce_body(kctx):
    """``x += psum(h)`` over the tp axis: one-shot broadcast into
    symmetric workspace slots + local reduction, trailing barrier.

    Parity: the reference's in-megakernel allreduce task
    (``tasks/allreduce.py``, ``kernels/allreduce.py``) which likewise
    pushes partials to peer symmetric buffers. The trailing barrier
    bounds cross-rank skew so slot reuse by the NEXT allreduce task is
    race-free — the role the reference's scoreboard release plays.
    """

    def body():
        axis = kctx.axis
        n = kctx.dims.n_ranks
        h = kctx.h[...]
        _workspace_bcast(kctx, h)
        # Tracer phase mark: partials landed — [begin, mid] is the
        # fused exchange's comm phase, [mid, end] the local fold.
        trace_mid(kctx)
        acc = kctx.x[...]
        for r in range(n):
            acc = acc + kctx.cbuf[r]
        kctx.x[...] = acc
        _barrier(kctx)

    return body


@register_task(TaskType.AR_SEND)
def ar_send_body(kctx):
    """First half of the split allreduce (``MegaConfig.overlap_ar``):
    stage this rank's GEMM partial into the workspace and START the
    remote puts — non-blocking, so the ICI transfer proceeds while the
    following grid iterations run. Parity: the gemm_ar ONE_SHOT
    producer's per-tile notify pipelining
    (``ops/overlap/gemm_ar.py::_gemm_ar_one_shot_kernel`` ``_produce``),
    adapted to the sequential megakernel grid — the payload here is the
    whole [B, d] partial (decode batches are tiny; the overlap lever is
    WHEN the put starts, not tiling it)."""

    def body():
        me = jax.lax.axis_index(kctx.axis)
        h = kctx.h[...]
        kctx.arsrc[...] = h
        kctx.cbuf[me] = h
        for dma in _ar_put_dmas(kctx):
            dma.start()
        # Tracer phase mark: every remote put is in flight — the comm
        # window the decoder's overlap-exposure measure opens here.
        trace_mid(kctx)

    return body


@register_task(TaskType.AR_WAIT)
def ar_wait_body(kctx):
    """Second half of the split allreduce: fire the NEXT weight
    stream's tile-0 DMA (the overlap window — the ICI hop from AR_SEND
    hides under that HBM traffic), then wait the inbound partials,
    fold ``x += sum(partials)``, drain the sends, and barrier so the
    workspace slots are reusable by the next exchange (the gemm_ar
    ONE_SHOT ``_reduce``/``_drain`` phases)."""

    def body():
        nr = kctx.dims.n_ranks
        if kctx.cfg.cross_prefetch:
            # Needs the cross_prefetch handshake (the consuming stream
            # must skip its own tile-0 start); without it the split
            # still moves the puts off the critical path.
            fire_next_tile0(kctx)
        # Tracer phase mark: the next stream's tile-0 DMA is issued
        # (the work hidden under the open comm window); [mid, end] is
        # the blocked wait + fold + drain the overlap exists to shrink.
        trace_mid(kctx)
        _ar_wait_recvs(kctx)
        acc = kctx.x[...]
        for r in range(nr):
            acc = acc + kctx.cbuf[r]
        kctx.x[...] = acc
        for dma in _ar_put_dmas(kctx):
            dma.wait_send()
        _barrier(kctx)

    return body


@register_task(TaskType.MOE_GATE)
def moe_gate_body(kctx):
    """MoE router (parity: ``ops/moe/routing.py::router_topk`` —
    softmax over all experts, top-k, optional renormalization): writes
    the per-(expert, token) combine weights to the ``moe_w`` scratch
    and zeroes the combine accumulator the MOE_FFN tasks fold into.

    All math runs in the ``[E, B]`` orientation (experts on the
    sublane axis): the gate needs per-token reductions over experts,
    and this layout gets them as axis-0 reductions without a transpose
    Mosaic would have to relayout. Top-k is the iterative
    max-and-retire loop (k is tiny and static); ties resolve to the
    lowest expert index, matching ``jax.lax.top_k``."""

    def body():
        dims = kctx.dims
        B, E, k = dims.batch, dims.num_experts, dims.moe_top_k
        h_in = _normed_input(kctx, 1)  # [B, d] f32
        if kctx.cfg.fuse_norms:
            # MOE_FFN tasks read the normed input from h (under
            # fuse_norms nothing else wrote it); without fuse_norms the
            # NORM task already put it there.
            kctx.h[...] = h_in
        wr = kctx.wrouter[kctx.layer].astype(jnp.float32)  # [d, E]
        logits = jax.lax.dot_general(
            wr, h_in, (((0,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [E, B]
        m = jnp.max(logits, axis=0, keepdims=True)
        p = jnp.exp(logits - m)
        p = p / jnp.sum(p, axis=0, keepdims=True)  # softmax over experts

        eidx = jax.lax.broadcasted_iota(jnp.int32, (E, B), 0)
        cw = jnp.zeros((E, B), jnp.float32)
        rem = p
        for _ in range(k):
            mv = jnp.max(rem, axis=0, keepdims=True)  # [1, B]
            sel = jnp.min(
                jnp.where(rem == mv, eidx, jnp.int32(1 << 30)),
                axis=0, keepdims=True,
            )
            onehot = eidx == sel
            cw = cw + jnp.where(onehot, rem, 0.0)
            rem = jnp.where(onehot, jnp.float32(-1.0), rem)
        if dims.norm_topk:
            cw = cw / jnp.sum(cw, axis=0, keepdims=True)
        # Row-wise writes into the [E, 1, B] scratch (static unroll —
        # a [E, B] → [E, 1, B] reshape would be a Mosaic relayout).
        for e in range(E):
            kctx.moe_w[e, 0:1, :] = cw[e:e + 1, :]
        kctx.moe_acc[...] = jnp.zeros_like(kctx.moe_acc)

    return body


@register_task(TaskType.MOE_FFN)
def moe_ffn_body(kctx):
    """One LOCAL expert's SwiGLU FFN over every token, weighted into
    the combine accumulator (parity: the expert-segment grouped GEMMs
    of ``moe_reduce_rs.py``/``allgather_group_gemm.py``, one expert per
    task so the tracer sees per-expert windows and the split-phase A2A
    can fire mid-FFN). Experts are EP-sharded: ``arg0`` is the local
    expert id; the combine weight for token b is
    ``moe_w[rank·E_loc + arg0, b]`` — zero for unrouted tokens, whose
    rows then contribute nothing (decode batches are tiny, so dense
    per-expert compute costs the same HBM bytes as a ragged dispatch
    and keeps the weight streams statically shaped)."""

    def body():
        dims = kctx.dims
        B, f = dims.batch, dims.f_loc  # f = FULL expert width under EP
        tn = kctx.cfg.tn_fc1
        n = f // tn
        tk = kctx.cfg.tk_fc2
        n2 = f // tk
        e_loc = kctx.arg0
        layer = kctx.layer
        ge = jax.lax.axis_index(kctx.axis) * dims.experts_loc + e_loc
        # [B, 1] combine-weight column from scalar reads of the
        # expert-leading moe_w scratch (ge is traced on the untiled
        # leading dim — the ksc/vsc scalar-read pattern).
        cw_col = jnp.concatenate(
            [
                jnp.full((1, 1), kctx.moe_w[ge, 0, b], jnp.float32)
                for b in range(B)
            ],
            axis=0,
        )
        h_in = kctx.h[...]  # normed input (MOE_GATE/NORM wrote it)

        # FC1: one continuous column stream over the expert's fused
        # [d, gate|up] plane (the dense fc1_body pattern, per expert).
        def sink(j, val):
            @pl.when(j < n)
            def _gate():
                kctx.mlp[:, pl.ds(j * tn, tn)] = val * jax.lax.logistic(val)

            @pl.when(j >= n)
            def _up():
                sl = pl.ds((j - n) * tn, tn)
                kctx.mlp[:, sl] = kctx.mlp[:, sl] * val

        _stream_cols(kctx, h_in, kctx.w1.at[layer, e_loc], 2 * n, tn, sink)
        # FC2: row stream of the expert's [f, d] down projection,
        # folded into the combine accumulator under the per-token gate
        # weight (per-row constants distribute over the K-tile sum).
        _stream_rows(
            kctx, kctx.mlp, kctx.w2.at[layer, e_loc], kctx.moe_acc,
            n2, tk, col_scale=cw_col, accumulate=True,
        )

        @pl.when(kctx.arg1 == 1)
        def _handoff():
            # Non-overlap path: the LAST local expert hands the combine
            # partial to the fused ALLREDUCE task, which reads h.
            kctx.h[...] = kctx.moe_acc[...]

    return body


@register_task(TaskType.A2A_SEND)
def a2a_send_body(kctx):
    """EP combine send (split-phase sibling of AR_SEND,
    docs/megakernel.md "MoE serving"): push this rank's combine partial
    — the weighted sum of its OWN experts' outputs — to every peer.
    ``arg0`` is the phase: phase 0 fires the moment the first half of
    the local expert GEMMs has landed, so its ICI bytes fly under the
    SECOND half's expert grouped GEMMs (the accumulator restarts at
    zero for them); phase 1 carries the rest and reuses the standard
    AR workspace, whose slots the layer's attention allreduce already
    quiesced. Dispatch needs no wire bytes on TPU decode: activations
    and router are replicated, so every rank already holds every
    token — the reference pays ``kernel_dispatch_token`` because its
    tokens live on their home ranks."""

    def body():
        me = jax.lax.axis_index(kctx.axis)
        payload = kctx.moe_acc[...]

        @pl.when(kctx.arg0 == 0)
        def _phase0():
            kctx.a2src[...] = payload
            kctx.a2buf[me] = payload
            for dma in _a2a_put_dmas(kctx):
                dma.start()
            # Fresh partial for the second half of the experts while
            # phase 0's bytes are in flight.
            kctx.moe_acc[...] = jnp.zeros_like(payload)

        @pl.when(kctx.arg0 == 1)
        def _phase1():
            kctx.arsrc[...] = payload
            kctx.cbuf[me] = payload
            for dma in _ar_put_dmas(kctx):
                dma.start()

        # Tracer phase mark: this phase's puts are in flight — the comm
        # window the decoder's A2A overlap measure opens here.
        trace_mid(kctx)

    return body


@register_task(TaskType.A2A_WAIT)
def a2a_wait_body(kctx):
    """EP combine wait (split-phase sibling of AR_WAIT): fire the NEXT
    weight stream's tile-0 DMA (the overlap lever — the combine's ICI
    hop hides under that HBM traffic), then wait both phases' inbound
    partials, fold ``x += Σ_ranks (phase0 + phase1)``, drain the sends,
    and barrier so both workspaces are reusable."""

    def body():
        nr = kctx.dims.n_ranks
        if kctx.cfg.cross_prefetch:
            fire_next_tile0(kctx)
        # Tracer phase mark: tile-0 is issued; [mid, end] is the
        # blocked wait + fold the overlap exists to shrink.
        trace_mid(kctx)
        _a2a_wait_recvs(kctx)
        _ar_wait_recvs(kctx)
        acc = kctx.x[...]
        for r in range(nr):
            acc = acc + kctx.a2buf[r] + kctx.cbuf[r]
        kctx.x[...] = acc
        for dma in _a2a_put_dmas(kctx):
            dma.wait_send()
        for dma in _ar_put_dmas(kctx):
            dma.wait_send()
        _barrier(kctx)

    return body


def _multi_step_tail(kctx, row, B):
    """Shared multi-step epilogue: publish this step's winning tokens
    (``row`` [1, B]) to the next EMBED (VMEM→SMEM DMA — scalar reads
    need SMEM) and the per-step token output, then — under ``dims.eos``
    — test each winner against its slot's stop token and record the
    FIRST hitting step into the ``stop_step`` SMEM output (``nsteps`` =
    never hit). The stamp is first-hit-wins: once a slot has stopped,
    later steps keep generating (their tokens are clamped host/shard
    side via ``min(n_valid, stop_step + 1)``) but cannot overwrite the
    retire step — that is what lets a finished slot retire without a
    host round trip while the co-batched survivor streams on."""
    dims = kctx.dims
    kctx.tokrow[...] = row
    kctx.toks_out[kctx.step] = row
    if dims.eos:
        ns = jnp.int32(dims.nsteps)
        for b in range(B):
            hit = row[0, b] == kctx.stop_tok[b]
            prev = jnp.where(kctx.step == 0, ns, kctx.stop_out[0, b])
            kctx.stop_out[0, b] = jnp.where(
                jnp.logical_and(hit, prev == ns), kctx.step, prev
            ).astype(jnp.int32)
    cp = pltpu.make_async_copy(kctx.tokrow, kctx.tok_smem, kctx.tsem)
    cp.start()
    cp.wait()


def _filtered_winner(kctx, B, v_real, NEGF):
    """Exact in-kernel top-k/top-p + Gumbel-max winner over the logits
    the tile stream just landed (dims.filtered, single-rank).

    Matches ``sampling.filter_logits`` + noisy argmax BIT-EXACTLY on
    the keep-set by reproducing its thresholds instead of its sorts:
    sorting a [B, v] tile-streamed buffer in-kernel is the expensive
    path, but both filters are threshold rules — top-k keeps
    ``ls >= kth`` (k-th largest, ties survive) and top-p keeps
    ``ls >= cutoff`` (cutoff = smallest kept sorted logit, which
    re-includes its ties) — and a threshold is findable by bisection
    on monotone counts. Per row, in the scaled domain
    ``ls = logits / temperature`` (pad columns at NEGF):

    * top-k: bisect t with invariant ``C(lo) >= k > C(hi)`` where
      ``C(t) = #{ls > t}``; after 64 halvings [lo, hi) brackets the
      k-th largest value so ``ls > lo`` == ``ls >= kth`` exactly
      (counting in f32 is exact below 2^24 >> vocab). Disabled top-k
      rows prefetch k = V → keep-all.
    * top-p: over top-k survivors, weights ``w = exp(ls - max)``; bisect
      with invariant ``H(lo) >= p*Z > H(hi)``, ``H(t) = sum{ls > t} w``,
      Z = sum w; converges to the host's cutoff including its tie
      re-inclusion. Host prep clamps p to [1e-6, 1] so ``H(hi0) = 0 <
      p*Z`` holds at init (Z > 0: the row max always contributes 1).

    64 fixed iterations shrink the bracket to width*2^-64 — far below
    the f32 ulp gap between distinct logits — so the bracket ends
    strictly between adjacent distinct values and the comparison
    ``ls > lo`` is exact, not approximate. Rows with ``enable = 0``
    (greedy or unfiltered-sampled) keep every real column; the winner
    is then argmax over ``logits + noise`` (noise = temperature *
    gumbel, zero for greedy rows) with jnp.argmax's first-occurrence
    tie-break, identical to the unfiltered carry path."""
    lg = kctx.logits[...]  # [B, v_loc] raw f32 (clean output stays)
    gidx = jax.lax.broadcasted_iota(jnp.int32, lg.shape, 1)
    real = gidx < v_real
    inv_t = kctx.sampcfg[:, 0:1]
    kk = kctx.sampcfg[:, 1:2]
    pp = kctx.sampcfg[:, 2:3]
    en = kctx.sampcfg[:, 3:4] > 0.0
    ls = jnp.where(real, lg * inv_t, NEGF)
    mx = jnp.max(ls, axis=-1, keepdims=True)
    mn = jnp.min(jnp.where(real, ls, -NEGF), axis=-1, keepdims=True)

    def bisect(count_ge):
        # Invariant: count_ge(lo) true, count_ge(hi) false.
        def it(_, c):
            lo, hi = c
            mid = 0.5 * (lo + hi)
            take = count_ge(mid)
            return jnp.where(take, mid, lo), jnp.where(take, hi, mid)

        lo, _ = jax.lax.fori_loop(0, 64, it, (mn - 1.0, mx))
        return lo

    lo_k = bisect(
        lambda t: jnp.sum(
            jnp.where(ls > t, 1.0, 0.0), axis=-1, keepdims=True
        ) >= kk
    )
    tk = ls > lo_k
    w = jnp.where(tk, jnp.exp(ls - mx), 0.0)
    z = jnp.sum(w, axis=-1, keepdims=True)
    lo_p = bisect(
        lambda t: jnp.sum(
            jnp.where(ls > t, w, 0.0), axis=-1, keepdims=True
        ) >= pp * z
    )
    keep = jnp.where(en, jnp.logical_and(tk, ls > lo_p), real)
    score = jnp.where(keep, lg + kctx.noise[0], NEGF)
    bestv = jnp.max(score, axis=-1, keepdims=True)
    return jnp.min(
        jnp.where(score == bestv, gidx, jnp.int32(1 << 30)),
        axis=-1, keepdims=True,
    )


@register_task(TaskType.LM_HEAD)
def lm_head_body(kctx):
    def body():
        dims = kctx.dims
        tn = kctx.cfg.tn_lm
        n = dims.v_loc // tn

        if dims.prefill:
            # Project only the last real prompt row (position
            # kv_len[0] - 1): a one-hot [1, S] @ [S, d] row select —
            # logits over all S rows would be an [S, v_loc] output.
            S = dims.batch
            sel = jax.lax.broadcasted_iota(jnp.int32, (1, S), 1)
            onehot = (sel == kctx.kv_len[0] - 1).astype(jnp.float32)
            x_in = jnp.dot(
                onehot, _normed_input(kctx, 2),
                preferred_element_type=jnp.float32,
            )  # [1, d]
        else:
            x_in = _normed_input(kctx, 2)

        # Tail tile when tn doesn't divide v_loc (wide lm tiles on an
        # unround vocab axis); must stay a 128-multiple for lane
        # alignment — guaranteed by the resolve() gate.
        rem = dims.v_loc - n * tn

        if dims.nsteps > 1:
            # Multi-step greedy: a running argmax threads through the
            # tile stream; the winning index feeds the next step's
            # EMBED via VMEM→SMEM DMA (scalar reads need SMEM) and the
            # per-step token output. Tie-break matches jnp.argmax
            # (first occurrence: min index within a tile, strict > for
            # later tiles; under TP, lower ranks hold lower global
            # indices and the ascending exchange loop keeps strict >).
            B = x_in.shape[0]
            nr = dims.n_ranks
            NEGF = jnp.float32(-3.0e38)
            v_total = dims.v_real or nr * dims.v_loc
            if nr > 1:
                me = jax.lax.axis_index(kctx.axis)
                # This rank's real (unpadded) column count.
                v_real = jnp.clip(v_total - me * dims.v_loc, 0, dims.v_loc)
            else:
                v_real = min(v_total, dims.v_loc)

            if dims.filtered:
                # Filtered sampling (dims.filtered, single-rank): the
                # stream writes raw logits only — no running carry; a
                # filter threshold cannot be known until every tile has
                # landed — then the post-stream pass derives the exact
                # host keep-set by per-row bisection and argmaxes
                # logits + noise over it (_filtered_winner).
                def fsink(j, val):
                    val = _q8_scale(kctx, kctx.sc_lm, None, j * tn, val)
                    kctx.logits[:, pl.ds(j * tn, val.shape[1])] = val

                _stream_cols(
                    kctx, x_in, kctx.lm_head, n, tn, fsink, tail=rem
                )
                besti = _filtered_winner(kctx, B, v_real, NEGF)
                row = jnp.concatenate(
                    [besti[b:b + 1, :] for b in range(B)], axis=1
                )  # [1, B]
                _multi_step_tail(kctx, row, B)
                return

            def sink(j, val, carry):
                val = _q8_scale(kctx, kctx.sc_lm, None, j * tn, val)
                kctx.logits[:, pl.ds(j * tn, val.shape[1])] = val
                bestv, besti = carry
                if dims.sampled:
                    # Gumbel-max sampling: argmax over logits + noise
                    # (noise = temperature * gumbel, host-drawn). The
                    # logits OUTPUT stays clean — noise only perturbs
                    # the argmax.
                    val = val + kctx.noise[0, :, pl.ds(j * tn, val.shape[1])]
                gidx = j * tn + jax.lax.broadcasted_iota(
                    jnp.int32, (B, val.shape[1]), 1
                )
                masked = jnp.where(gidx < v_real, val, NEGF)
                tmax = jnp.max(masked, axis=-1, keepdims=True)
                tidx = jnp.min(
                    jnp.where(masked == tmax, gidx, jnp.int32(1 << 30)),
                    axis=-1, keepdims=True,
                )
                upd = tmax > bestv
                return (
                    jnp.where(upd, tmax, bestv),
                    jnp.where(upd, tidx, besti),
                )

            init = (
                jnp.full((B, 1), NEGF, jnp.float32),
                jnp.zeros((B, 1), jnp.int32),
            )
            bestv, besti = _stream_cols(
                kctx, x_in, kctx.lm_head, n, tn, sink, tail=rem, carry=init
            )

            if nr > 1:
                # Cross-rank argmax: every rank one-shot-broadcasts its
                # (best value, best GLOBAL index) pair through the
                # allreduce workspace (quiesced: the preceding
                # allreduce task ends with a barrier) and reduces all
                # nr candidates identically.
                gbesti = (me * dims.v_loc + besti).astype(jnp.float32)
                d = kctx.arsrc.shape[1]
                pad = jnp.zeros((B, d - 2), jnp.float32)
                cand = jnp.concatenate([bestv, gbesti, pad], axis=1)
                # Race fixture (no-op when straggler_rank is None): lag
                # this rank's candidate push so any consumer missing
                # its wait reads stale slots.
                dl.straggle_if_rank(
                    dims.straggler_rank, kctx.axis, dims.straggler_nanos
                )
                _workspace_bcast(kctx, cand)
                bestv = kctx.cbuf[0, :, 0:1]
                besti = kctx.cbuf[0, :, 1:2].astype(jnp.int32)
                for r in range(1, nr):
                    v_r = kctx.cbuf[r, :, 0:1]
                    i_r = kctx.cbuf[r, :, 1:2].astype(jnp.int32)
                    upd = v_r > bestv
                    bestv = jnp.where(upd, v_r, bestv)
                    besti = jnp.where(upd, i_r, besti)
                # Slot reuse fence: the next step's exchange (or
                # allreduce) must not land before every rank has read
                # this round's candidates.
                _barrier(kctx)

            row = jnp.concatenate(
                [besti[b:b + 1, :] for b in range(B)], axis=1
            )  # [1, B]
            _multi_step_tail(kctx, row, B)
        else:
            def sink(j, val):
                val = _q8_scale(kctx, kctx.sc_lm, None, j * tn, val)
                kctx.logits[:, pl.ds(j * tn, val.shape[1])] = val

            _stream_cols(kctx, x_in, kctx.lm_head, n, tn, sink, tail=rem)

    return body


@register_task(TaskType.BARRIER)
def barrier_body(kctx):
    def body():
        _barrier(kctx)

    return body


@register_task(TaskType.RING_POLL)
def ring_poll_body(kctx):
    """Observe the host work ring (dims.ring): stamp the published
    doorbell from the scalar-prefetch ``[doorbell, head, tail,
    occupancy]`` snapshot into this task's trace mid column, proving
    the round ran against the ring state the host rang for it
    (validate_ring's doorbell check). Under interpret/CPU this is the
    whole task — the ring is consumed host-side at round boundaries;
    on hardware this is where the persistent loop spins on the
    doorbell semaphore and splices admitted slots into the task
    table (megakernel/ring.py module docs)."""

    def body():
        if kctx.ring_state is not None and kctx.dims.trace:
            trace_stamp(kctx, kctx.ring_state[0])

    return body
