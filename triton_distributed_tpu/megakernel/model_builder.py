"""ModelBuilder: whole-decode-step task graphs → one compiled megakernel.

Parity: reference ``mega_triton_kernel/models/model_builder.py`` —
``ModelBuilder.make_fc1/make_qkv_proj/make_attn/make_allreduce/…``
:189-352, ``compile()``:372 (schedule + codegen + triton compile),
``run()``:391 (launch the persistent kernel), and its symmetric-tensor
accounting ``create_symm_tensor``:119 (here: the kernel's workspace
output + semaphore scratch, allocated by the pallas_call itself).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

from triton_distributed_tpu.megakernel import kernels as _kernels  # noqa: F401  (registers bodies)
from triton_distributed_tpu.megakernel.code_generator import (
    MegaConfig,
    MegaDims,
    build_mega_call,
)
from triton_distributed_tpu.megakernel.scheduler import SchedulePolicy, schedule
from triton_distributed_tpu.megakernel.task import (
    Task,
    TaskDependency,
    TaskIDManager,
    TaskType,
    pack_table,
)
from triton_distributed_tpu.ops.common import next_collective_id
from triton_distributed_tpu.runtime.mesh import DistContext, current_context


class ModelBuilder:
    """Decoder-LM task-graph builder.

    ``make_*`` methods append tasks with explicit dependencies (default:
    the previously appended task — the sequential decode chain); the
    scheduler may then legally reorder independent tasks. ``compile()``
    freezes the graph into one Pallas megakernel.
    """

    def __init__(
        self,
        dims: MegaDims,
        *,
        cfg: MegaConfig | None = None,
        axis: str = "tp",
        ctx: DistContext | None = None,
        wdtype=jnp.bfloat16,
        cdtype=jnp.bfloat16,
    ):
        self.dims = dims
        self.cfg = cfg or MegaConfig()
        self.axis = axis
        self.ctx = ctx or current_context()
        self.wdtype = wdtype
        self.cdtype = cdtype
        self.tasks: list[Task] = []
        self._idm = TaskIDManager()
        self._last: int | None = None

    # -- graph construction (parity: make_* methods :189-352) ------------
    def _add(
        self,
        task_type: TaskType,
        layer: int = 0,
        arg0: int = 0,
        deps: list[int] | None = None,
    ) -> int:
        tid = self._idm.alloc()
        if deps is None:
            deps = [] if self._last is None else [self._last]
        self.tasks.append(
            Task(
                task_id=tid,
                task_type=task_type,
                layer_id=layer,
                arg0=arg0,
                deps=tuple(TaskDependency(d) for d in deps),
            )
        )
        self._last = tid
        return tid

    def make_embed(self, **kw) -> int:
        return self._add(TaskType.EMBED, **kw)

    def make_norm(self, layer: int, which: int, **kw) -> int | None:
        """which: 0 = input layernorm, 1 = post-attn, 2 = final.

        Under ``cfg.fuse_norms`` this is a no-op (returns None): the
        consumers (qkv/fc1/lm_head) compute the norm inline, and a NORM
        task slipping back into ANY graph would double-normalize — the
        guard lives here so no builder can forget it."""
        if self.cfg.fuse_norms:
            return None
        return self._add(TaskType.NORM, layer, arg0=which, **kw)

    def make_qkv_proj(self, layer: int, **kw) -> int:
        return self._add(TaskType.QKV_PROJ, layer, **kw)

    def make_attn(self, layer: int, **kw) -> int:
        return self._add(TaskType.ATTN, layer, **kw)

    def make_o_proj(self, layer: int, **kw) -> int:
        return self._add(TaskType.O_PROJ, layer, **kw)

    def make_fc1(self, layer: int, **kw) -> int:
        return self._add(TaskType.FC1, layer, **kw)

    def make_fc2(self, layer: int, **kw) -> int:
        return self._add(TaskType.FC2, layer, **kw)

    def make_allreduce(self, layer: int = 0, **kw) -> int:
        # Kept even for n_ranks == 1: the body also folds the residual
        # (x += h), degenerating to a plain add with zero remote puts.
        # Under ``cfg.overlap_ar`` (and only with real peers) the
        # exchange splits into AR_SEND (remote puts start the moment
        # the producing GEMM finished) + AR_WAIT (reduction waits only
        # after firing the next weight stream's tile-0 DMA) — the
        # gemm_ar ONE_SHOT overlap adapted to the sequential grid; the
        # n_ranks guard lives HERE so no graph builder pays two task
        # iterations for a single-rank exchange with nothing to hide.
        if self.cfg.overlap_ar and self.dims.n_ranks > 1:
            self._add(TaskType.AR_SEND, layer, **kw)
            return self._add(TaskType.AR_WAIT, layer)
        return self._add(TaskType.ALLREDUCE, layer, **kw)

    def make_moe_gate(self, layer: int, **kw) -> int:
        return self._add(TaskType.MOE_GATE, layer, **kw)

    def make_moe_ffn(self, layer: int, expert: int,
                     handoff: bool = False) -> int:
        """One LOCAL expert's FFN task (``arg0`` = local expert id).
        ``handoff`` marks the last expert of the NON-overlap path: its
        epilogue copies the combine accumulator into ``h`` so the fused
        ALLREDUCE task (which reads ``h``) carries the MoE combine."""
        tid = self._add(TaskType.MOE_FFN, layer, arg0=expert)
        if handoff:
            self.tasks[-1].arg1 = 1
        return tid

    def make_a2a_send(self, layer: int, phase: int) -> int:
        return self._add(TaskType.A2A_SEND, layer, arg0=phase)

    def make_a2a_wait(self, layer: int) -> int:
        return self._add(TaskType.A2A_WAIT, layer)

    def make_lm_head(self, **kw) -> int:
        return self._add(TaskType.LM_HEAD, **kw)

    def make_attn_prefill(self, layer: int, **kw) -> int:
        return self._add(TaskType.ATTN_PREFILL, layer, **kw)

    def make_load_x(self, **kw) -> int:
        return self._add(TaskType.LOAD_X, **kw)

    def make_barrier(self, **kw) -> int:
        return self._add(TaskType.BARRIER, **kw)

    def make_ring_poll(self, **kw) -> int:
        return self._add(TaskType.RING_POLL, **kw)

    def build_decoder_graph(self) -> None:
        """The standard decode-step chain (parity:
        ``models/qwen3.py:108`` build_fwd). With ``dims.moe`` the MLP
        section becomes router → per-local-expert grouped GEMMs → EP
        combine; under ``cfg.overlap_ar`` the combine splits into the
        A2A_SEND/A2A_WAIT pair with phase 0 fired MID-FFN, so its ICI
        bytes fly under the second half of the expert GEMMs and the
        final wait blocks only after the next weight stream's tile-0
        DMA is in flight (docs/megakernel.md "MoE serving")."""
        if self.dims.ring:
            # Ring-enabled rounds observe the host work ring FIRST: the
            # doorbell snapshot this task stamps is the proof that the
            # round ran against the ring state the host published for
            # it; on hardware this is where the resident loop spins and
            # splices admitted slots into the table (ring.py docs).
            self.make_ring_poll()
        if self.dims.n_ranks > 1:
            # Entry barrier: the first ALLREDUCE issues remote puts into
            # peers' VMEM scratch; without this, launch skew could land a
            # put before the peer has entered the kernel (scratch/semaphores
            # still owned by the previous program). Trailing barriers cover
            # all subsequent allreduces within the launch.
            self.make_barrier()
        self.make_embed()
        for l in range(self.dims.num_layers):
            self.make_norm(l, 0)  # no-op under cfg.fuse_norms
            self.make_qkv_proj(l)
            self.make_attn(l)
            self.make_o_proj(l)
            self.make_allreduce(l)
            self.make_norm(l, 1)
            if self.dims.moe:
                self._build_moe_mlp(l)
            else:
                self.make_fc1(l)
                self.make_fc2(l)
                self.make_allreduce(l)
        self.make_norm(0, 2)
        self.make_lm_head()

    def _build_moe_mlp(self, l: int) -> None:
        """The MoE MLP section of one layer: MOE_GATE, the local expert
        GEMM tasks, and the combine — split-phase A2A under
        ``overlap_ar`` (phase 0 after the first half of the experts,
        phase 1 + wait after the rest), the fused ALLREDUCE otherwise
        (the last expert's ``handoff`` hands it the accumulator)."""
        self.make_moe_gate(l)
        epr = self.dims.experts_loc
        overlap = self.cfg.overlap_ar
        split = max(-(-epr // 2), 1)  # ceil — phase 0 covers this many
        for e in range(epr):
            last = e == epr - 1
            self.make_moe_ffn(l, e, handoff=last and not overlap)
            if overlap and e == split - 1:
                self.make_a2a_send(l, phase=0)
        if overlap:
            self.make_a2a_send(l, phase=1)
            self.make_a2a_wait(l)
        else:
            self.make_allreduce(l)

    def build_prefill_graph(self) -> None:
        """The prompt-prefill chain (parity: the reference's prefill
        TaskBuilders, ``model_builder.py:189-352``): same per-layer
        pipeline as decode with causal self-attention over the S token
        rows; the embedding arrives as an input (LOAD_X) and the LM head
        projects only the last real row (arg0=1)."""
        if self.dims.n_ranks > 1:
            self.make_barrier()  # same entry-skew reasoning as decode
        self.make_load_x()
        for l in range(self.dims.num_layers):
            self.make_norm(l, 0)  # no-op under cfg.fuse_norms
            self.make_qkv_proj(l)
            self.make_attn_prefill(l)
            self.make_o_proj(l)
            self.make_allreduce(l)
            self.make_norm(l, 1)
            self.make_fc1(l)
            self.make_fc2(l)
            self.make_allreduce(l)
        self.make_norm(0, 2)
        # The LM head projects only the last real row in prefill graphs
        # (driven by dims.prefill inside lm_head_body, not a task arg).
        self.make_lm_head()

    # -- compile ---------------------------------------------------------
    def compile(
        self, policy: SchedulePolicy = SchedulePolicy.ROUND_ROBIN
    ) -> "CompiledMegaKernel":
        """Schedule + generate the single-kernel program
        (parity: ``ModelBuilder.compile``:372)."""
        order = schedule(self.tasks, policy)
        table = pack_table(order, trace=self.dims.trace)
        run = build_mega_call(
            self.dims,
            self.cfg,
            order,
            axis=self.axis,
            ctx=self.ctx,
            wdtype=self.wdtype,
            cdtype=self.cdtype,
            collective_id=next_collective_id(),
            table=jnp.asarray(table),
        )
        return CompiledMegaKernel(
            builder=self, order=order, per_shard=run
        )


@dataclasses.dataclass
class CompiledMegaKernel:
    """A scheduled, traced megakernel (parity: the compiled
    MEGA_TRITON_KERNEL + its ``run()``, ``model_builder.py:391``)."""

    builder: ModelBuilder
    order: list[Task]
    per_shard: Any  # per-shard callable (inside shard_map)

    @property
    def num_tasks(self) -> int:
        return len(self.order)
