"""Task registry: task type → device body factory.

Parity: reference ``mega_triton_kernel/core/registry.py`` —
``register_task``:38 maps a task key to its TaskBuilder + device kernel;
the code generator then emits only the branches a model actually uses.
"""

from __future__ import annotations

from typing import Callable, Protocol

from triton_distributed_tpu.megakernel.task import TaskType


class BodyFactory(Protocol):
    """Builds the device-side body for one task type.

    Called once at code-generation time with the static kernel context
    (dims, config, refs); returns a zero-arg callable executed under
    ``pl.when(task_type == value)`` with the current header in scope.
    """

    def __call__(self, kctx) -> Callable[[], None]: ...


_REGISTRY: dict[TaskType, BodyFactory] = {}


def register_task(task_type: TaskType):
    """Decorator (parity: ``@register_task``, ``core/registry.py:38``)."""

    def deco(factory: BodyFactory) -> BodyFactory:
        if task_type in _REGISTRY:
            raise ValueError(f"duplicate task body for {task_type!r}")
        _REGISTRY[task_type] = factory
        return factory

    return deco


def get_body_factory(task_type: TaskType) -> BodyFactory:
    try:
        return _REGISTRY[task_type]
    except KeyError:
        raise KeyError(
            f"no device body registered for {task_type!r}; "
            "import triton_distributed_tpu.megakernel.kernels"
        ) from None


def registered_types() -> tuple[TaskType, ...]:
    return tuple(sorted(_REGISTRY, key=int))
