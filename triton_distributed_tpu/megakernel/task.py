"""Megakernel task graph: task types, headers, ids, dependencies.

Parity: reference ``mega_triton_kernel/core/task_base.py`` —
``CodeGenKey``:36 (task_type/layer dispatch key), ``TaskIDManager``:75,
``TaskDependency``:112 — and its 8-int device-side task headers read by
the generated megakernel (``core/code_generator.py:92-174``).

TPU redesign: the reference schedules *tile*-granular tasks onto many SMs
and synchronizes them with a shared-memory scoreboard
(``kernels/task_context.py:107``). A TPU chip exposes one sequential
Pallas grid per core, so tasks here are *op*-granular (one task = one
fused op over the whole batch), tile-level parallelism lives INSIDE a
task body as a double-buffered DMA pipeline, and intra-chip dependencies
are discharged by schedule order (the grid is sequential under
``dimension_semantics=("arbitrary",)``) — the scoreboard survives only at
chip boundaries, as DMA-semaphore dataflow in the allreduce task.
"""

from __future__ import annotations

import dataclasses
import enum

import numpy as np

# Device-side header layout: HDR_INTS int32 per task.
# [0] task_type  [1] layer_id  [2] arg0  [3] arg1  [4] task_id
# (rest reserved). task_id rides in the header so the device task
# tracer (docs/observability.md "Device task tracer") can stamp ring
# records with the BUILDER's id, not the schedule position — the two
# differ whenever the scheduler legally reorders independent tasks.
HDR_INTS = 8

# Device trace-ring record layout (obs/kernel_trace.py decodes it):
# TRACE_INTS int32 per (step, task) record, same 8-int width as the
# task headers. ``mid`` is an optional intra-task phase stamp (the AR
# bodies mark when their comm phase hands off); ``flag`` is the
# written marker (the logical clock starts at 1, but a cycle-counter
# clock may legitimately read 0) — a zero flag means the record was
# never written, which is what the decoder's gap-free check keys on.
TRACE_INTS = 8
TR_TASK_ID = 0   # builder task id (header slot 4)
TR_OPCODE = 1    # TaskType value
TR_LAYER = 2     # layer_id
TR_SLOT = 3      # arg0 (e.g. the allreduce parity slot)
TR_BEGIN = 4     # clock at task entry
TR_END = 5       # clock at task exit (epilogue included)
TR_MID = 6       # optional intra-task phase stamp (0 = none)
TR_FLAG = 7      # 1 = record written


class TaskType(enum.IntEnum):
    """Dispatch key (parity: ``CodeGenKey.task_type``).

    Values index the generated ``pl.when`` dispatch chain, mirroring the
    reference's generated if/elif over task types
    (``core/code_generator.py:103-152``).
    """

    EMBED = 0        # x ← embed[tokens]
    NORM = 1         # h ← rms_norm(x) * w;  arg0: 0=ln1  1=ln2  2=final
    QKV_PROJ = 2     # qkv ← h @ wqkv[layer]
    ATTN = 3         # rope + cache append + GQA flash-decode → attn out
    O_PROJ = 4       # h ← attn_out @ wo[layer]   (partial sum over tp)
    FC1 = 5          # mlp ← silu(h @ gate) * (h @ up)
    FC2 = 6          # h ← mlp @ w2[layer]        (partial sum over tp)
    ALLREDUCE = 7    # x ← x + psum(h);  arg0: parity slot
    LM_HEAD = 8      # logits ← rms_norm(x) stage then tiled GEMM
    BARRIER = 9      # standalone cross-chip barrier (stress/test fixture)
    ATTN_PREFILL = 10  # causal self-attn over the S token rows + K/V out
    LOAD_X = 11      # x ← x0 input (prefill: embedding arrives via XLA)
    # Split allreduce (``MegaConfig.overlap_ar``): the producing GEMM's
    # partial is pushed to every peer's workspace slot the moment it is
    # ready (AR_SEND — non-blocking remote puts), and the reduction
    # waits for the inbound partials only AFTER starting the NEXT weight
    # stream's first tile DMA (AR_WAIT) — the megakernel adaptation of
    # the gemm_ar ONE_SHOT overlap (ops/overlap/gemm_ar.py): comm flies
    # under the next task's HBM traffic instead of serializing after
    # the GEMM.
    AR_SEND = 12     # start remote puts of h into peers' cbuf slots
    AR_WAIT = 13     # prefetch next tile-0, wait partials, x += sum
    # MoE decode (Qwen3MoE through the megakernel, docs/megakernel.md
    # "MoE serving"): the dense FC1/FC2 pair is replaced by a router
    # task plus one grouped-GEMM task per LOCAL expert (weights are
    # EP-sharded — each rank streams only the experts it owns, full FFN
    # width), and the EP combine enters the graph as split-phase
    # siblings of AR_SEND/AR_WAIT. On TPU decode the activations are
    # replicated ([B, d] after the attention allreduce) and the router
    # is replicated too, so the DISPATCH half of the reference's EP
    # all-to-all (kernels/nvidia/ep_a2a.py kernel_dispatch_token) is
    # data-free — every rank already holds every token; what crosses
    # the wire is the COMBINE (kernel_combine_token): each rank's
    # weighted sum over its own experts' outputs. A2A_SEND fires those
    # combine puts in two phases — phase 0 the moment the FIRST HALF of
    # the local experts' GEMMs land (so the exchange flies under the
    # second half's expert grouped GEMMs), phase 1 after the rest — and
    # A2A_WAIT blocks only after firing the next weight stream's tile-0
    # DMA (fire_next_tile0, the AR_WAIT overlap lever).
    MOE_GATE = 14    # router: softmax top-k over experts → combine weights
    MOE_FFN = 15     # one local expert's SwiGLU FFN; arg0: local expert id
    A2A_SEND = 16    # start combine puts of a phase partial; arg0: phase
    A2A_WAIT = 17    # prefetch next tile-0, wait partials, x += sum
    # Resident decode (docs/megakernel.md "Resident decode"): the first
    # task of every ring-enabled round observes the host work ring's
    # doorbell (a scalar-prefetch [4] i32 ``[doorbell, head, tail,
    # occupancy]`` snapshot) and stamps it into its trace record's mid
    # column, so the decoder can prove every round consumed the ring
    # state the host published for it (validate_ring's doorbell check).
    # Under interpret/CPU the ring is consumed at round boundaries —
    # the operand is re-prefetched per launch; on hardware the same
    # task is where the persistent loop would spin on the doorbell
    # semaphore and splice admitted slots into the task table.
    RING_POLL = 18   # observe host work-ring doorbell; stamp into trace


# Resource class used by the zig-zag scheduler: tasks whose cost is
# dominated by the MXU vs by DMA/ICI traffic (parity role: the
# reference's compute/comm SM partitioning heuristics).
COMM_TASKS = frozenset({
    TaskType.ALLREDUCE, TaskType.BARRIER, TaskType.EMBED,
    TaskType.AR_SEND, TaskType.AR_WAIT,
    TaskType.A2A_SEND, TaskType.A2A_WAIT, TaskType.RING_POLL,
})


@dataclasses.dataclass(frozen=True)
class TaskDependency:
    """Edge producer → consumer (parity: ``TaskDependency``,
    ``core/task_base.py:112``). Tile ranges collapse to whole-task edges
    in the op-granular design."""

    producer: int  # task id


@dataclasses.dataclass
class Task:
    """One schedulable unit (parity: the reference's task records built
    by ``TaskBuilderBase.build_tasks``, ``core/builder.py:62``)."""

    task_id: int
    task_type: TaskType
    layer_id: int = 0
    arg0: int = 0
    arg1: int = 0
    deps: tuple[TaskDependency, ...] = ()

    def header(self, trace: bool = False) -> list[int]:
        # The id column (slot 4) is a tracer-only operand extension:
        # untraced tables stay byte-identical to the pre-tracer layout
        # (nothing untraced reads past slot 3, and launch params must
        # not change when the tracer is off).
        h = [int(self.task_type), self.layer_id, self.arg0, self.arg1,
             self.task_id if trace else 0]
        return h + [0] * (HDR_INTS - len(h))


class TaskIDManager:
    """Monotone task-id allocator (parity: ``TaskIDManager``,
    ``core/task_base.py:75``)."""

    def __init__(self) -> None:
        self._next = 0

    def alloc(self) -> int:
        tid = self._next
        self._next += 1
        return tid

    @property
    def count(self) -> int:
        return self._next


def pack_table(tasks: list[Task], trace: bool = False) -> np.ndarray:
    """Flatten scheduled tasks into the int32 device table the kernel
    scalar-prefetches (parity: the per-SM int32 work queues,
    ``core/scheduler.py:40-63`` — collapsed to one queue for the
    sequential TPU grid). ``trace`` stamps each header's id column
    (slot 4) so the device task tracer can record builder ids; off,
    the table is byte-identical to the pre-tracer layout."""
    if not tasks:
        raise ValueError("empty task list")
    return np.asarray([t.header(trace) for t in tasks], np.int32)
