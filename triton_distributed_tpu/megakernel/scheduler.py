"""Task scheduler: dependency-respecting linearization policies.

Parity: reference ``mega_triton_kernel/core/scheduler.py`` — round-robin
:65 and zig-zag :73 placement of tile tasks onto per-SM int32 work
queues :40-63.

TPU redesign: the Pallas grid executes sequentially on the TensorCore,
so "placement" becomes "ordering". ROUND_ROBIN keeps build (program)
order. ZIG_ZAG list-schedules so that DMA/ICI-bound tasks (allreduce,
embed) are hoisted next to MXU-bound tasks whenever dependencies allow —
the async DMAs those bodies start then progress under the neighbors'
compute, which is the same overlap the reference's zig-zag SM
interleaving buys.
"""

from __future__ import annotations

import enum

from triton_distributed_tpu.megakernel.task import COMM_TASKS, Task


class SchedulePolicy(enum.Enum):
    ROUND_ROBIN = "round_robin"
    ZIG_ZAG = "zig_zag"


def _check_deps(tasks: list[Task]) -> None:
    ids = {t.task_id for t in tasks}
    for t in tasks:
        for d in t.deps:
            if d.producer not in ids:
                raise ValueError(
                    f"task {t.task_id} depends on unknown task {d.producer}"
                )


def schedule(
    tasks: list[Task], policy: SchedulePolicy = SchedulePolicy.ROUND_ROBIN
) -> list[Task]:
    """Return tasks in execution order; raises on dependency cycles."""
    _check_deps(tasks)
    if policy is SchedulePolicy.ROUND_ROBIN:
        order = _topo_stable(tasks)
    elif policy is SchedulePolicy.ZIG_ZAG:
        order = _topo_zigzag(tasks)
    else:
        raise ValueError(f"unknown policy {policy!r}")
    _validate(order)
    return order


def _topo_stable(tasks: list[Task]) -> list[Task]:
    """Kahn's algorithm, ties broken by build order."""
    return _list_schedule(tasks, prefer_comm_flip=False)


def _topo_zigzag(tasks: list[Task]) -> list[Task]:
    """List scheduling that alternates resource classes when possible."""
    return _list_schedule(tasks, prefer_comm_flip=True)


def _list_schedule(tasks: list[Task], *, prefer_comm_flip: bool) -> list[Task]:
    by_id = {t.task_id: t for t in tasks}
    indeg = {t.task_id: len(t.deps) for t in tasks}
    consumers: dict[int, list[int]] = {t.task_id: [] for t in tasks}
    for t in tasks:
        for d in t.deps:
            consumers[d.producer].append(t.task_id)
    ready = [t.task_id for t in tasks if indeg[t.task_id] == 0]
    order: list[Task] = []
    last_comm = True  # so the first pick prefers compute
    while ready:
        pick = ready[0]
        if prefer_comm_flip:
            for tid in ready:
                if (by_id[tid].task_type in COMM_TASKS) != last_comm:
                    pick = tid
                    break
        ready.remove(pick)
        t = by_id[pick]
        last_comm = t.task_type in COMM_TASKS
        order.append(t)
        for c in consumers[pick]:
            indeg[c] -= 1
            if indeg[c] == 0:
                ready.append(c)
    if len(order) != len(tasks):
        stuck = sorted(set(by_id) - {t.task_id for t in order})
        raise ValueError(f"dependency cycle among tasks {stuck}")
    return order


def _validate(order: list[Task]) -> None:
    """Every producer precedes its consumers (the sequential-grid analog
    of the reference scoreboard's runtime wait_deps check,
    ``kernels/task_context.py:107``)."""
    seen: set[int] = set()
    for t in order:
        for d in t.deps:
            if d.producer not in seen:
                raise AssertionError(
                    f"schedule places task {t.task_id} before its "
                    f"dependency {d.producer}"
                )
        seen.add(t.task_id)
