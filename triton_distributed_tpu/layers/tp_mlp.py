"""Tensor-parallel MLP (SwiGLU) with overlapped comm.

Parity: reference ``layers/nvidia/tp_mlp.py`` — ``TP_MLP`` with
``torch_fwd``:96, ``dist_triton_fwd``:143 (ag_gemm fc1 → silu-mul →
gemm_rs fc2) and the AR decode path :177 (local GEMMs → all_reduce).

TPU design: weights are column-sharded (gate/up fused into one fc1) and
row-sharded (down) over the ``tp`` axis. Activations are sequence-sharded
between layers (the reference's "scatter" activation layout), so the
prefill path is ag_gemm → silu·mul → gemm_rs with zero exposed
collectives; the decode path keeps activations replicated and all-reduces
the partial down-projection.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Literal

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from triton_distributed_tpu.ops.overlap.ag_gemm import ag_gemm
from triton_distributed_tpu.ops.overlap.gemm_ar import gemm_ar
from triton_distributed_tpu.ops.overlap.gemm_rs import gemm_rs
from triton_distributed_tpu.runtime.mesh import DistContext, current_context

Mode = Literal["xla", "pallas", "pallas_ar", "xla_ar"]


@dataclasses.dataclass
class TPMLPParams:
    """Per-shard weights. ``w1`` fuses gate and up projections
    (``[d_model, 2 * d_ff_loc]``, gate first) so prefill needs a single
    ag_gemm — same fusion the reference applies (``tp_mlp.py:51-76``
    concatenates gate/up into one fc1 weight)."""

    w1: jax.Array  # [d_model, 2 * d_ff_loc]
    w2: jax.Array  # [d_ff_loc, d_model]


from triton_distributed_tpu.runtime.pytree import register_param_dataclass

register_param_dataclass(TPMLPParams, ["w1", "w2"])


def _silu_mul(h: jax.Array) -> jax.Array:
    gate, up = jnp.split(h, 2, axis=-1)
    return (jax.nn.silu(gate.astype(jnp.float32)) * up.astype(jnp.float32)).astype(
        h.dtype
    )


def tp_mlp_fwd(
    params: TPMLPParams,
    x: jax.Array,
    *,
    axis: str = "tp",
    mode: Mode = "pallas",
    ctx: DistContext | None = None,
) -> jax.Array:
    """Per-shard forward, runs inside ``shard_map``.

    prefill modes (``x`` is the sequence shard ``[m_per, d]``; returns the
    sequence shard): ``pallas`` = overlapped ag_gemm/gemm_rs
    (parity ``dist_triton_fwd``); ``xla`` = lax collectives golden path.
    decode modes (``x`` replicated ``[m, d]``; returns replicated):
    ``pallas_ar`` / ``xla_ar`` = local GEMMs + all-reduce
    (parity ``tp_mlp.py:177``).
    """
    if mode == "pallas":
        h = _silu_mul(ag_gemm(x, params.w1, axis=axis, ctx=ctx))
        return gemm_rs(h, params.w2, axis=axis, ctx=ctx)
    if mode == "xla":
        full = jax.lax.all_gather(x, axis, axis=0, tiled=True)
        h = _silu_mul(jnp.dot(full, params.w1, preferred_element_type=jnp.float32)
                      .astype(x.dtype))
        part = jnp.dot(h, params.w2, preferred_element_type=jnp.float32)
        return jax.lax.psum_scatter(
            part, axis, scatter_dimension=0, tiled=True
        ).astype(x.dtype)
    if mode in ("pallas_ar", "xla_ar"):
        h = _silu_mul(
            jnp.dot(x, params.w1, preferred_element_type=jnp.float32).astype(x.dtype)
        )
        if mode == "xla_ar":
            part = jnp.dot(h, params.w2, preferred_element_type=jnp.float32)
            return jax.lax.psum(part.astype(x.dtype), axis)
        # Down-projection fused with its cross-rank sum (parity: the
        # reference AR decode path tp_mlp.py:177, here via the one-shot
        # per-tile-broadcast gemm_ar instead of GEMM-then-all_reduce).
        return gemm_ar(h, params.w2, axis=axis, ctx=ctx)
    raise ValueError(f"unknown mode {mode!r}")


class TPMLP:
    """Host-level layer: owns sharded weights + shard_map wrappers.

    Parity: ``TP_MLP`` (``layers/nvidia/tp_mlp.py:51``) — there the layer
    shards torch weights onto each rank and allocates symmetric contexts;
    here weights are ``jax.device_put`` with column/row shardings and the
    kernels allocate their own workspace.
    """

    def __init__(
        self,
        d_model: int,
        d_ff: int,
        *,
        dtype=jnp.bfloat16,
        axis: str = "tp",
        ctx: DistContext | None = None,
    ):
        self.ctx = ctx or current_context()
        self.axis = axis
        self.d_model = d_model
        self.d_ff = d_ff
        self.dtype = dtype
        self.params: TPMLPParams | None = None

    def init(self, key: jax.Array) -> TPMLPParams:
        k1, k2, k3 = jax.random.split(key, 3)
        scale = self.d_model**-0.5
        gate = jax.random.normal(k1, (self.d_model, self.d_ff), self.dtype) * scale
        up = jax.random.normal(k2, (self.d_model, self.d_ff), self.dtype) * scale
        down = jax.random.normal(k3, (self.d_ff, self.d_model), self.dtype) * scale
        return self.load(gate, up, down)

    def load(self, gate: jax.Array, up: jax.Array, down: jax.Array) -> TPMLPParams:
        """Shard full weights onto the mesh (parity: ``TP_MLP._init_parameters``)."""
        n = self.ctx.axis_size(self.axis)
        d_ff_loc = self.d_ff // n
        # Fuse gate/up per shard: [d, 2*ff_loc] blocks so each device's
        # w1 column shard is [gate_loc | up_loc].
        w1 = jnp.concatenate(
            [
                gate.reshape(self.d_model, n, d_ff_loc),
                up.reshape(self.d_model, n, d_ff_loc),
            ],
            axis=2,
        ).reshape(self.d_model, 2 * self.d_ff)
        self.params = TPMLPParams(
            w1=self.ctx.shard(w1.astype(self.dtype), None, self.axis),
            w2=self.ctx.shard(down.astype(self.dtype), self.axis, None),
        )
        return self.params

    def forward(self, x: jax.Array, mode: Mode = "pallas") -> jax.Array:
        """``x`` host-global ``[M, d]``. Prefill modes return ``[M, d]``
        sequence-sharded; AR modes return ``[M, d]`` replicated."""
        assert self.params is not None, "call init()/load() first"
        seq_modes = mode in ("pallas", "xla")
        xs = P(self.axis, None) if seq_modes else P()
        f = self.ctx.shard_map(
            functools.partial(tp_mlp_fwd, axis=self.axis, mode=mode, ctx=self.ctx),
            in_specs=(
                TPMLPParams(w1=P(None, self.axis), w2=P(self.axis, None)),
                xs,
            ),
            out_specs=xs,
        )
        return f(self.params, x)
