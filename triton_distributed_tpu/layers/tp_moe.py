"""Tensor-parallel MoE layer (experts' FFN dims sharded over tp).

Parity: reference ``layers/nvidia/tp_moe.py`` — ``TP_MoE``:48 with the
``dist_triton_fwd`` AG-scatter-groupGEMM → gather-RS pipeline (:237):
tokens all-gathered, every rank runs ALL experts on its column shard of
every expert's weights, outputs reduce-scattered back; the router and
sort mirror ``csrc`` moe_utils.

Modes: ``pallas`` / ``xla`` (prefill, sequence-sharded activations) and
``pallas_ar`` / ``xla_ar`` (decode, replicated activations + all-reduce).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Literal

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from triton_distributed_tpu.ops.collectives.all_gather import all_gather
from triton_distributed_tpu.ops.collectives.all_reduce import all_reduce
from triton_distributed_tpu.ops.collectives.reduce_scatter import reduce_scatter
from triton_distributed_tpu.ops.moe.grouped_gemm import grouped_ffn
from triton_distributed_tpu.ops.moe.routing import moe_combine, moe_sort, router_topk
from triton_distributed_tpu.runtime.mesh import DistContext, current_context

Mode = Literal["xla", "pallas", "ring", "pallas_ar", "xla_ar"]


@dataclasses.dataclass
class TPMoEParams:
    w_router: jax.Array  # [d, E] replicated
    w1: jax.Array        # [E, d, 2*f_loc] — gate|up fused, column shard
    w2: jax.Array        # [E, f_loc, d] — row shard


from triton_distributed_tpu.runtime.pytree import register_param_dataclass

register_param_dataclass(TPMoEParams, ["w_router", "w1", "w2"])


def tp_moe_fwd(
    params: TPMoEParams,
    x: jax.Array,
    k: int,
    *,
    axis: str = "tp",
    mode: Mode = "pallas",
    norm_topk_prob: bool = True,
    ctx: DistContext | None = None,
) -> jax.Array:
    """Per-shard forward inside ``shard_map``.

    Prefill (``x [t_loc, d]`` sequence shard → same): all-gather tokens,
    route + expert-sort, grouped SwiGLU over every expert's local column
    shard, weighted combine, reduce-scatter. Decode AR modes take
    replicated ``x [B, d]``.
    """
    num_experts = params.w1.shape[0]
    if mode == "ring":
        # Fused AG+GroupGEMM → RS: chunks + partials circulate via
        # ppermute, XLA overlaps transfer with the grouped FFN
        # (ops/moe/ring_moe.py; parity: allgather_group_gemm.py +
        # moe_reduce_rs.py).
        from triton_distributed_tpu.ops.moe.ring_moe import moe_ffn_ring

        return moe_ffn_ring(
            x, params.w_router, params.w1, params.w2, k,
            axis=axis, norm_topk_prob=norm_topk_prob,
        )
    seq_mode = mode in ("pallas", "xla")
    if seq_mode:
        if mode == "pallas":
            full = all_gather(x, axis=axis, ctx=ctx)
        else:
            full = jax.lax.all_gather(x, axis, axis=0, tiled=True)
    else:
        full = x
    t = full.shape[0]

    route = router_topk(full, params.w_router, k, norm_topk_prob=norm_topk_prob)
    st = moe_sort(route, num_experts)
    h = grouped_ffn(full[st.token_ids], params.w1, params.w2, st.group_sizes)
    part = moe_combine(h, st, t)  # [T, d] — partial (f is sharded)

    if seq_mode:
        if mode == "pallas":
            return reduce_scatter(part, axis=axis, ctx=ctx)
        return jax.lax.psum_scatter(
            part.astype(jnp.float32), axis, scatter_dimension=0, tiled=True
        ).astype(x.dtype)
    if mode == "xla_ar":
        return jax.lax.psum(part.astype(jnp.float32), axis).astype(x.dtype)
    return all_reduce(part, axis=axis, ctx=ctx)


class TPMoE:
    """Host-level layer (parity: ``TP_MoE``, ``tp_moe.py:48``)."""

    def __init__(
        self,
        d_model: int,
        d_ff: int,  # per-expert FFN width (moe_intermediate_size)
        num_experts: int,
        top_k: int,
        *,
        dtype=jnp.bfloat16,
        axis: str = "tp",
        ctx: DistContext | None = None,
    ):
        self.ctx = ctx or current_context()
        self.axis = axis
        n = self.ctx.axis_size(axis)
        if d_ff % n:
            raise ValueError(f"moe d_ff {d_ff} not divisible by tp={n}")
        self.d_model, self.d_ff = d_model, d_ff
        self.num_experts, self.top_k = num_experts, top_k
        self.dtype = dtype
        self.params: TPMoEParams | None = None

    @property
    def param_specs(self):
        return TPMoEParams(
            w_router=P(),
            w1=P(None, None, self.axis),
            w2=P(None, self.axis, None),
        )

    def load(
        self,
        w_router: jax.Array,  # [d, E]
        gate: jax.Array,      # [E, d, f]
        up: jax.Array,        # [E, d, f]
        down: jax.Array,      # [E, f, d]
    ) -> TPMoEParams:
        n = self.ctx.axis_size(self.axis)
        e, d, f = gate.shape
        f_loc = f // n
        # Fuse gate|up per shard: [E, d, n, 2*f_loc] → [E, d, 2*f].
        w1 = jnp.concatenate(
            [gate.reshape(e, d, n, f_loc), up.reshape(e, d, n, f_loc)], axis=3
        ).reshape(e, d, 2 * f)
        self.params = TPMoEParams(
            w_router=self.ctx.replicate(w_router.astype(self.dtype)),
            w1=self.ctx.shard(w1.astype(self.dtype), None, None, self.axis),
            w2=self.ctx.shard(down.astype(self.dtype), None, self.axis, None),
        )
        return self.params

    def init(self, key: jax.Array) -> TPMoEParams:
        e, d, f = self.num_experts, self.d_model, self.d_ff
        ks = jax.random.split(key, 4)
        s = d**-0.5
        return self.load(
            jax.random.normal(ks[0], (d, e), jnp.float32) * s,
            jax.random.normal(ks[1], (e, d, f), jnp.float32) * s,
            jax.random.normal(ks[2], (e, d, f), jnp.float32) * s,
            jax.random.normal(ks[3], (e, f, d), jnp.float32) * (f**-0.5),
        )

    def forward(self, x: jax.Array, mode: Mode = "pallas") -> jax.Array:
        assert self.params is not None
        seq = mode in ("pallas", "xla", "ring")
        xs = P(self.axis, None) if seq else P()
        f = self.ctx.shard_map(
            functools.partial(
                tp_moe_fwd, k=self.top_k, axis=self.axis, mode=mode,
                ctx=self.ctx,
            ),
            in_specs=(self.param_specs, xs),
            out_specs=xs,
        )
        return f(self.params, x)
