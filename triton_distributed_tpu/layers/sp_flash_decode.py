"""Sequence-parallel GQA flash-decode attention layer.

Parity: reference ``layers/nvidia/sp_flash_decode_layer.py`` —
``SpGQAFlashDecodeAttention.forward``:83: the KV cache is sharded across
ranks along the sequence, each rank attends its shard, and partials are
combined cross-rank (``flash_decode.py:482``), scaling decode with the
mesh instead of replicating the cache.

TPU design: cache shard ``[B, hkv, s_loc, hd]`` per device along the
``sp`` axis in rank order; the new token's K/V is appended by whichever
rank owns position ``kv_len``; attention = local split-KV kernel +
all-gather(partial O, LSE) + log-sum-exp merge.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from triton_distributed_tpu.ops.attention.flash_decode import (
    distributed_flash_decode,
)


def sp_append_kv(
    cache: jax.Array,  # [B, h, s_loc, hd] — this rank's sequence slice
    new: jax.Array,    # [B, h, hd] — replicated new-token K or V
    kv_len: jax.Array,  # [B] int32 GLOBAL positions to write
    axis: str = "sp",
) -> jax.Array:
    """Write ``new`` at global position ``kv_len[b]`` — a no-op on every
    rank but the owner of that position."""
    me = jax.lax.axis_index(axis)
    s_loc = cache.shape[2]
    local = kv_len - me * s_loc
    owner = jnp.logical_and(local >= 0, local < s_loc)
    safe = jnp.clip(local, 0, s_loc - 1)

    def one(c, x, p, ok):  # c [h, s_loc, hd]
        upd = jax.lax.dynamic_update_slice(c, x[:, None, :].astype(c.dtype),
                                           (0, p, 0))
        return jnp.where(ok, upd, c)

    return jax.vmap(one)(cache, new, safe, owner)


def sp_decode_attention(
    q: jax.Array,        # [B, hq, hd] replicated
    k_new: jax.Array,    # [B, hkv, hd] replicated
    v_new: jax.Array,
    k_cache: jax.Array,  # [B, hkv, s_loc, hd] — sequence shard
    v_cache: jax.Array,
    kv_len: jax.Array,   # [B] int32 GLOBAL context length (before append)
    *,
    axis: str = "sp",
    sm_scale: float | None = None,
    chunk_k: int = 256,
    method: str = "xla",
    ctx=None,
):
    """One SP decode-attention step inside ``shard_map``.

    Appends the new token's K/V to the owning rank's shard, then runs the
    distributed split-KV attention. Returns ``(o [B, hq, hd] replicated,
    k_cache, v_cache)`` — parity with
    ``SpGQAFlashDecodeAttention.forward``.
    """
    k_cache = sp_append_kv(k_cache, k_new, kv_len, axis)
    v_cache = sp_append_kv(v_cache, v_new, kv_len, axis)
    o = distributed_flash_decode(
        q, k_cache, v_cache, kv_len + 1,
        axis=axis, sm_scale=sm_scale, chunk_k=chunk_k, method=method, ctx=ctx,
    )
    return o, k_cache, v_cache
