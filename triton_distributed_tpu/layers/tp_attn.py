"""Tensor-parallel attention (GQA + RoPE + optional QK-norm).

Parity: reference ``layers/nvidia/tp_attn.py`` — ``TP_Attn`` with fused
qkv ag_gemm, rotary, flash attention, o-proj gemm_rs
(``dist_triton_fwd``:203-271) and the AR decode path (local GEMMs +
flash-decode + all_reduce). Heads are sharded over the ``tp`` axis; each
device owns ``hq/n`` query heads and ``hkv/n`` KV heads with the full
sequence — the KV cache is therefore head-sharded, and decode needs no
cross-device attention (that is the SP decode layer's job).

Prefill activations are sequence-sharded between layers; the qkv
projection is the overlapped ag_gemm and the output projection the
overlapped gemm_rs, mirroring the reference's zero-exposed-comm prefill.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Literal

import jax
import jax.numpy as jnp

from triton_distributed_tpu.ops.attention.flash_attention import flash_attention
from triton_distributed_tpu.ops.attention.flash_decode import flash_decode
from triton_distributed_tpu.ops.attention.rope import apply_rope
from triton_distributed_tpu.ops.overlap.gemm_ar import gemm_ar
from triton_distributed_tpu.ops.overlap.ag_gemm import ag_gemm
from triton_distributed_tpu.ops.overlap.gemm_rs import gemm_rs
from triton_distributed_tpu.runtime.mesh import DistContext, current_context

Mode = Literal["xla", "pallas", "pallas_ar", "xla_ar"]


@dataclasses.dataclass
class TPAttnParams:
    """Per-shard weights: ``wqkv [d, (hq_loc + 2*hkv_loc) * hd]``
    (q | k | v blocks), ``wo [hq_loc * hd, d]``, optional per-head RMS
    scales ``q_norm``/``k_norm`` ``[hd]`` (Qwen3)."""

    wqkv: jax.Array
    wo: jax.Array
    q_norm: jax.Array | None
    k_norm: jax.Array | None


from triton_distributed_tpu.runtime.pytree import register_param_dataclass

register_param_dataclass(TPAttnParams, ["wqkv", "wo", "q_norm", "k_norm"])


def _rms_head(x: jax.Array, scale: jax.Array | None, eps: float = 1e-6):
    if scale is None:
        return x
    xf = x.astype(jnp.float32)
    xf = xf * jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (xf * scale.astype(jnp.float32)).astype(x.dtype)


@dataclasses.dataclass(frozen=True)
class TPAttnDims:
    """Static head geometry for the local shard."""

    hq_loc: int
    hkv_loc: int
    head_dim: int
    rope_theta: float = 1e6

    @property
    def qkv_loc(self) -> int:
        return (self.hq_loc + 2 * self.hkv_loc) * self.head_dim

    def split_qkv(self, qkv: jax.Array):
        """``[..., qkv_loc] → q [..., hq_loc, hd], k/v [..., hkv_loc, hd]``."""
        hd = self.head_dim
        q, k, v = jnp.split(
            qkv, [self.hq_loc * hd, (self.hq_loc + self.hkv_loc) * hd], axis=-1
        )
        lead = qkv.shape[:-1]
        return (
            q.reshape(*lead, self.hq_loc, hd),
            k.reshape(*lead, self.hkv_loc, hd),
            v.reshape(*lead, self.hkv_loc, hd),
        )


def tp_attn_prefill(
    params: TPAttnParams,
    x: jax.Array,  # [s_loc, d] — sequence shard (batch folded upstream)
    dims: TPAttnDims,
    *,
    axis: str = "tp",
    mode: Mode = "pallas",
    ctx: DistContext | None = None,
):
    """Per-shard prefill forward (inside ``shard_map``).

    Returns ``(out [s_loc, d], k [hkv_loc, S, hd], v [hkv_loc, S, hd])``
    — k/v are the full-sequence local-head cache entries (parity:
    ``TP_Attn.dist_triton_fwd`` writing the KV cache, ``tp_attn.py:203``).
    """
    if mode == "pallas":
        qkv = ag_gemm(x, params.wqkv, axis=axis, ctx=ctx)  # [S, qkv_loc]
    else:
        full = jax.lax.all_gather(x, axis, axis=0, tiled=True)
        qkv = jnp.dot(
            full, params.wqkv, preferred_element_type=jnp.float32
        ).astype(x.dtype)
    s_full = qkv.shape[0]
    q, k, v = dims.split_qkv(qkv)  # [S, h, hd]
    q = _rms_head(q, params.q_norm)
    k = _rms_head(k, params.k_norm)
    pos = jnp.arange(s_full)
    q = apply_rope(q.swapaxes(0, 1), pos, dims.rope_theta)  # [h, S, hd]
    k = apply_rope(k.swapaxes(0, 1), pos, dims.rope_theta)
    v = v.swapaxes(0, 1)
    o = flash_attention(q[None], k[None], v[None], causal=True)[0]  # [h, S, hd]
    o_flat = o.swapaxes(0, 1).reshape(s_full, dims.hq_loc * dims.head_dim)
    o_flat = o_flat.astype(x.dtype)
    if mode == "pallas":
        out = gemm_rs(o_flat, params.wo, axis=axis, ctx=ctx)
    else:
        part = jnp.dot(o_flat, params.wo, preferred_element_type=jnp.float32)
        out = jax.lax.psum_scatter(
            part, axis, scatter_dimension=0, tiled=True
        ).astype(x.dtype)
    return out, k, v


def tp_attn_prefill_paged_chunk(
    params: TPAttnParams,
    x: jax.Array,           # [C, d] replicated — one chunk of ONE sequence
    k_pages: jax.Array,     # [P, hkv_loc, page, hd] — this layer's pool shard
    v_pages: jax.Array,
    table_row: jax.Array,   # [pages_per_seq] int32 — the sequence's pages
    q_offset: jax.Array,    # scalar int32 — tokens already cached
    dims: TPAttnDims,
    *,
    kv_pages: int | None = None,
    axis: str = "tp",
    mode: Mode = "xla_ar",
    ctx: DistContext | None = None,
    k_scale: jax.Array | None = None,  # [P, hkv_loc] f32 — int8 pool scales
    v_scale: jax.Array | None = None,
    q_end: jax.Array | None = None,    # scalar int32 — end of REAL rows
    rope_pos: jax.Array | None = None,  # [C] int32 — rope positions (tree)
    attn_bias: jax.Array | None = None,  # [C, S_kv] f32 additive mask
):
    """Per-shard chunked-prefill step over the paged pool (inside
    ``shard_map``): QKV for ``C`` suffix tokens, rope at absolute
    positions ``q_offset + i``, KV scattered through the page table, and
    flash attention of the chunk's queries against the WHOLE cached
    context (prefix pages + the chunk itself) via the dynamic
    ``kv_offset``. This is the prefix-cache suffix prefill: matched
    prefix pages are read, never recomputed.

    With ``k_scale``/``v_scale`` (int8 pool) the scatter quantizes the
    chunk's rows (growing/resetting the touched pages' scales) and the
    attention reads int8 codes with per-page scales dequantized inside
    the kernel (``block_k = page_size`` so pool pages ARE kv blocks).
    Quantized chunks route PAD rows (positions ≥ ``q_end``, the
    round_chunk right-padding) to the trash page: on the full-width
    path pad KV is inert (overwritten/masked), but a quantized pad row
    would grow — or, at page offset 0, seed — the touched page's scale
    with garbage amax, permanently requantizing accepted history
    against rows that are not part of the sequence.

    ``rope_pos``/``attn_bias`` serve the tree-speculation verify chunk:
    rows are tree NODES in DFS storage order, roped at their tree DEPTH
    (``rope_pos[i] = q_offset + depth_i``, which differs from the
    storage position for branched nodes) while the KV scatter keeps
    storage positions ``q_offset + i`` — accepted rows later row-move to
    their linear positions bit-identically, because K/V content depends
    only on token and rope position. ``attn_bias`` masks sibling
    branches out of each other's softmax (0 visible / -1e30 masked over
    the gathered dense view).

    Activations stay replicated (decode's AR layout, not prefill's
    sequence-sharded one): chunks are short, so the ag/rs overlap machinery
    would buy nothing, and replication keeps one compiled program valid for
    every chunk offset. Returns
    ``(out [C, d], k_pages, v_pages, k_scale, v_scale)``.
    """
    c = x.shape[0]
    page = k_pages.shape[2]
    pps = table_row.shape[0]
    quant = k_scale is not None
    qkv = jnp.dot(x, params.wqkv, preferred_element_type=jnp.float32).astype(
        x.dtype
    )
    q, k, v = dims.split_qkv(qkv)  # [C, h, hd]
    q = _rms_head(q, params.q_norm)
    k = _rms_head(k, params.k_norm)
    pos = q_offset + jnp.arange(c, dtype=jnp.int32)  # [C] absolute storage
    rpos = pos if rope_pos is None else rope_pos
    q = apply_rope(q.swapaxes(0, 1), rpos, dims.rope_theta)  # [h, C, hd]
    k = apply_rope(k.swapaxes(0, 1), rpos, dims.rope_theta)
    v = v.swapaxes(0, 1)

    # Scatter the chunk's KV through the table. Final-chunk right-padding
    # may run past the table's capacity; those rows are routed to the
    # trash page (id 0) instead of letting a clamped gather corrupt the
    # last real page.
    valid = pos < pps * page
    pids = jnp.where(
        valid, jnp.take(table_row, jnp.clip(pos // page, 0, pps - 1)), 0
    )
    offs = jnp.where(valid, pos % page, 0)
    if quant:
        from triton_distributed_tpu.models.paged_kv_cache import (
            quantized_row_scatter,
        )

        real = valid if q_end is None else valid & (pos < q_end)
        pids_q = jnp.where(real, pids, 0)
        offs_q = jnp.where(real, offs, 0)
        k_pages, k_scale = quantized_row_scatter(
            k_pages, k_scale, k.swapaxes(0, 1), pids_q, offs_q
        )
        v_pages, v_scale = quantized_row_scatter(
            v_pages, v_scale, v.swapaxes(0, 1), pids_q, offs_q
        )
    else:
        k_pages = k_pages.at[pids, :, offs, :].set(
            k.swapaxes(0, 1).astype(k_pages.dtype)
        )
        v_pages = v_pages.at[pids, :, offs, :].set(
            v.swapaxes(0, 1).astype(v_pages.dtype)
        )

    # Attend over the sequence's dense view (prefix + chunk). The
    # gather is bounded to ``kv_pages`` table entries — the caller's
    # static bucket covering q_offset + C — so a short suffix never
    # materializes the full max_length view (the causal skip saves the
    # COMPUTE past q_end, but gather traffic is paid for what's
    # gathered). Positions beyond q_offset + C inside the bucket are
    # masked by causality (rows live at q_offset..q_offset+C-1), so
    # stale/trash content there is inert.
    from triton_distributed_tpu.ops.attention.flash_decode import (
        pages_to_dense,
    )

    gather_row = table_row if kv_pages is None else table_row[:kv_pages]
    k_dense = pages_to_dense(k_pages, gather_row[None])  # [1, h, S_kv, hd]
    v_dense = pages_to_dense(v_pages, gather_row[None])
    s_max = gather_row.shape[0] * page
    if quant:
        # The gathered view keeps int8 codes; per-page scales gather
        # through the same bucket and dequantize inside the kernel
        # (block_k = page so pages and kv blocks coincide).
        ks_dense = jnp.take(k_scale, gather_row, axis=0).T[None]  # [1,h,pps]
        vs_dense = jnp.take(v_scale, gather_row, axis=0).T[None]
        o = flash_attention(
            q[None], k_dense, v_dense, causal=True, kv_offset=q_offset,
            block_k=page, k_scale=ks_dense, v_scale=vs_dense,
            bias=None if attn_bias is None else attn_bias[:, :s_max],
        )[0]  # [h, C, hd]
    else:
        o = flash_attention(
            q[None], k_dense, v_dense, causal=True, kv_offset=q_offset,
            block_k=128 if s_max % 128 == 0 else page,
            bias=None if attn_bias is None else attn_bias[:, :s_max],
        )[0]  # [h, C, hd]
    o_flat = o.swapaxes(0, 1).reshape(c, dims.hq_loc * dims.head_dim)
    o_flat = o_flat.astype(x.dtype)
    if mode in ("xla", "xla_ar"):
        part = jnp.dot(o_flat, params.wo, preferred_element_type=jnp.float32)
        out = jax.lax.psum(part.astype(x.dtype), axis)
    else:
        out = gemm_ar(o_flat, params.wo, axis=axis, ctx=ctx)
    return out, k_pages, v_pages, k_scale, v_scale


def tp_attn_prefill_paged_chunk_cold(
    params: TPAttnParams,
    x: jax.Array,           # [C, d] replicated — one chunk of ONE sequence
    k_pages: jax.Array,     # [P, hkv_loc, page, hd] — this layer's pool shard
    v_pages: jax.Array,
    table_row: jax.Array,   # [budget_pages] int32 — the slot's RESIDENT row
    k_cold: jax.Array,      # [hkv_loc, S_bucket, hd] — demoted-page window
    v_cold: jax.Array,
    s_cold: jax.Array,      # scalar int32 — valid cold tokens (≤ S_bucket)
    q_offset: jax.Array,    # scalar int32 — ABSOLUTE chunk start position
    dims: TPAttnDims,
    *,
    axis: str = "tp",
    mode: Mode = "xla_ar",
    ctx: DistContext | None = None,
    k_scale: jax.Array | None = None,   # [P, hkv_loc] f32 — int8 pool scales
    v_scale: jax.Array | None = None,
    ks_cold: jax.Array | None = None,   # [hkv_loc, S_bucket/page] f32
    vs_cold: jax.Array | None = None,
    q_end: jax.Array | None = None,     # scalar int32 — absolute end of REAL rows
):
    """Chunked-prefill step for a SHARDED long-context slot (inside
    ``shard_map``): the slot's history is split between ``s_cold``
    tier-demoted tokens (a read-only dense window, pool dtype + per-page
    scales, absolute positions ``[0, s_cold)``) and the resident paged
    region addressed by ``table_row`` at LOCAL positions (absolute
    position − ``s_cold``). The chunk's queries rope/mask at ABSOLUTE
    positions; attention runs as two partials merged by
    :func:`~triton_distributed_tpu.ops.attention.flash_decode.lse_combine`
    — the distributed-flash-decode combine, which is exactly what a real
    cross-rank sharded slot computes (each rank contributes its
    (o, lse) partial): cold columns are fully visible to every chunk row
    (they all precede it), masked only past ``s_cold`` (the bucket tail
    is garbage), while the resident view keeps causal masking at the
    local offset.

    ``S_bucket`` is a power-of-two page bucket so compile count stays
    logarithmic in cold length; ``s_cold`` is traced. With
    ``s_cold == 0`` the cold partial is fully masked and the combine
    returns the resident partial bit-exactly (weight 1 vs 0).
    Returns ``(out [C, d], k_pages, v_pages, k_scale, v_scale)``.
    """
    c = x.shape[0]
    page = k_pages.shape[2]
    n_res = table_row.shape[0]
    s_bucket = k_cold.shape[1]
    quant = k_scale is not None
    qkv = jnp.dot(x, params.wqkv, preferred_element_type=jnp.float32).astype(
        x.dtype
    )
    q, k, v = dims.split_qkv(qkv)  # [C, h, hd]
    q = _rms_head(q, params.q_norm)
    k = _rms_head(k, params.k_norm)
    pos = q_offset + jnp.arange(c, dtype=jnp.int32)  # [C] absolute
    q = apply_rope(q.swapaxes(0, 1), pos, dims.rope_theta)  # [h, C, hd]
    k = apply_rope(k.swapaxes(0, 1), pos, dims.rope_theta)
    v = v.swapaxes(0, 1)

    # Scatter at LOCAL resident positions. Final-chunk right-padding may
    # run past the resident capacity; route those rows (and any row that
    # would land before the resident window — impossible by the engine's
    # demote contract, but cheap to guard) to the trash page.
    lpos = pos - s_cold
    valid = (lpos >= 0) & (lpos < n_res * page)
    pids = jnp.where(
        valid, jnp.take(table_row, jnp.clip(lpos // page, 0, n_res - 1)), 0
    )
    offs = jnp.where(valid, lpos % page, 0)
    if quant:
        from triton_distributed_tpu.models.paged_kv_cache import (
            quantized_row_scatter,
        )

        real = valid if q_end is None else valid & (pos < q_end)
        pids_q = jnp.where(real, pids, 0)
        offs_q = jnp.where(real, offs, 0)
        k_pages, k_scale = quantized_row_scatter(
            k_pages, k_scale, k.swapaxes(0, 1), pids_q, offs_q
        )
        v_pages, v_scale = quantized_row_scatter(
            v_pages, v_scale, v.swapaxes(0, 1), pids_q, offs_q
        )
    else:
        k_pages = k_pages.at[pids, :, offs, :].set(
            k.swapaxes(0, 1).astype(k_pages.dtype)
        )
        v_pages = v_pages.at[pids, :, offs, :].set(
            v.swapaxes(0, 1).astype(v_pages.dtype)
        )

    from triton_distributed_tpu.ops.attention.flash_decode import (
        lse_combine,
        pages_to_dense,
    )

    # Resident partial: causal at the LOCAL offset (rows live at local
    # positions lpos), over the resident dense view.
    k_dense = pages_to_dense(k_pages, table_row[None])  # [1, h, S_res, hd]
    v_dense = pages_to_dense(v_pages, table_row[None])
    if quant:
        ks_dense = jnp.take(k_scale, table_row, axis=0).T[None]
        vs_dense = jnp.take(v_scale, table_row, axis=0).T[None]
        o_res, lse_res = flash_attention(
            q[None], k_dense, v_dense, causal=True, kv_offset=lpos[0],
            block_k=page, k_scale=ks_dense, v_scale=vs_dense,
            return_lse=True,
        )
    else:
        o_res, lse_res = flash_attention(
            q[None], k_dense, v_dense, causal=True, kv_offset=lpos[0],
            block_k=page, return_lse=True,
        )
    # Cold partial: every chunk row sees every VALID cold column (all of
    # them precede the chunk); the bucket tail past s_cold is masked.
    cold_mask = jnp.where(
        jnp.arange(s_bucket, dtype=jnp.int32)[None, :] < s_cold, 0.0, -1e30
    ) * jnp.ones((c, 1), jnp.float32)  # [C, S_bucket]
    if quant:
        o_cold, lse_cold = flash_attention(
            q[None], k_cold[None], v_cold[None], causal=False,
            block_k=page, k_scale=ks_cold[None], v_scale=vs_cold[None],
            bias=cold_mask, return_lse=True,
        )
    else:
        o_cold, lse_cold = flash_attention(
            q[None], k_cold[None], v_cold[None], causal=False,
            block_k=page, bias=cold_mask, return_lse=True,
        )
    o, _ = lse_combine(
        jnp.stack([o_cold.astype(jnp.float32), o_res.astype(jnp.float32)]),
        jnp.stack([lse_cold, lse_res]),
        part_axis=0,
    )
    o = o[0]  # [h, C, hd]
    o_flat = o.swapaxes(0, 1).reshape(c, dims.hq_loc * dims.head_dim)
    o_flat = o_flat.astype(x.dtype)
    if mode in ("xla", "xla_ar"):
        part = jnp.dot(o_flat, params.wo, preferred_element_type=jnp.float32)
        out = jax.lax.psum(part.astype(x.dtype), axis)
    else:
        out = gemm_ar(o_flat, params.wo, axis=axis, ctx=ctx)
    return out, k_pages, v_pages, k_scale, v_scale


def tp_attn_decode_sharded(
    params: TPAttnParams,
    x: jax.Array,           # [1, d] replicated — the slot's new token
    k_pages: jax.Array,     # [P, hkv_loc, page, hd] — this layer's pool shard
    v_pages: jax.Array,
    table_row: jax.Array,   # [budget_pages] int32 — the slot's RESIDENT row
    kv_len_loc: jax.Array,  # [1] int32 — tokens in the resident region
    k_cold: jax.Array,      # [hkv_loc, S_bucket, hd] — demoted-page window
    v_cold: jax.Array,
    s_cold: jax.Array,      # [1] int32 — valid cold tokens (≤ S_bucket)
    dims: TPAttnDims,
    *,
    axis: str = "tp",
    mode: Mode = "xla_ar",
    ctx: DistContext | None = None,
    k_scale: jax.Array | None = None,   # [P, hkv_loc] f32 — int8 pool scales
    v_scale: jax.Array | None = None,
    ks_cold: jax.Array | None = None,   # [hkv_loc, S_bucket/page] f32
    vs_cold: jax.Array | None = None,
):
    """Decode step for ONE sharded long-context slot (inside
    ``shard_map``): the new token appends at its LOCAL resident position
    (absolute position = ``s_cold + kv_len_loc``, which is where rope
    evaluates), then attention runs as two partials —
    :func:`paged_flash_decode` over the resident pages and
    :func:`flash_decode` over the cold dense window — merged by
    ``lse_combine``, the exact two-partition shape of
    ``distributed_flash_decode``'s gather-merge with the cold window
    standing in for the remote rank's shard. Returns
    ``(out [1, d], k_pages, v_pages, k_scale, v_scale)``.
    """
    from triton_distributed_tpu.ops.attention import paged_flash_decode
    from triton_distributed_tpu.ops.attention.flash_decode import (
        flash_decode as dense_flash_decode,
        lse_combine,
    )

    page = k_pages.shape[2]
    quant = k_scale is not None
    qkv = jnp.dot(x, params.wqkv, preferred_element_type=jnp.float32).astype(
        x.dtype
    )
    q, k, v = dims.split_qkv(qkv)  # [1, h, hd]
    q = _rms_head(q, params.q_norm)
    k = _rms_head(k, params.k_norm)
    pos_abs = s_cold + kv_len_loc  # [1] absolute position of the new token
    q = apply_rope(q, pos_abs[:, None], dims.rope_theta)
    k = apply_rope(k, pos_abs[:, None], dims.rope_theta)

    if quant:
        from triton_distributed_tpu.models.paged_kv_cache import (
            quantized_row_scatter,
        )

        pids = jnp.take(table_row, kv_len_loc // page)
        k_pages, k_scale = quantized_row_scatter(
            k_pages, k_scale, k, pids, kv_len_loc % page
        )
        v_pages, v_scale = quantized_row_scatter(
            v_pages, v_scale, v, pids, kv_len_loc % page
        )
    else:
        pid = jnp.take(table_row, kv_len_loc[0] // page)
        k_pages = jax.lax.dynamic_update_slice(
            k_pages, k[0][:, None, :].astype(k_pages.dtype)[None],
            (pid, 0, kv_len_loc[0] % page, 0),
        )
        v_pages = jax.lax.dynamic_update_slice(
            v_pages, v[0][:, None, :].astype(v_pages.dtype)[None],
            (pid, 0, kv_len_loc[0] % page, 0),
        )

    o_res, lse_res = paged_flash_decode(
        q, k_pages, v_pages, table_row[None], kv_len_loc + 1,
        return_lse=True, k_scale=k_scale, v_scale=v_scale,
    )
    o_cold, lse_cold = dense_flash_decode(
        q, k_cold[None], v_cold[None], s_cold, chunk_k=page,
        return_lse=True,
        k_scale=None if ks_cold is None else ks_cold[None],
        v_scale=None if vs_cold is None else vs_cold[None],
    )
    o, _ = lse_combine(
        jnp.stack([o_cold.astype(jnp.float32), o_res.astype(jnp.float32)]),
        jnp.stack([lse_cold, lse_res]),
        part_axis=0,
    )
    o_flat = o.reshape(1, dims.hq_loc * dims.head_dim).astype(x.dtype)
    if mode in ("xla", "xla_ar"):
        part = jnp.dot(o_flat, params.wo, preferred_element_type=jnp.float32)
        out = jax.lax.psum(part.astype(x.dtype), axis)
    elif mode in ("pallas", "pallas_ar"):
        out = gemm_ar(o_flat, params.wo, axis=axis, ctx=ctx)
    else:
        raise ValueError(f"unknown mode {mode!r}")
    return out, k_pages, v_pages, k_scale, v_scale


def tp_attn_decode(
    params: TPAttnParams,
    x: jax.Array,        # [B, d] replicated — one new token per sequence
    k_cache: jax.Array,  # [B, hkv_loc, S_max, hd]
    v_cache: jax.Array,
    kv_len: jax.Array,   # [B] int32 — tokens already in cache
    dims: TPAttnDims,
    *,
    axis: str = "tp",
    mode: Mode = "pallas_ar",
    ctx: DistContext | None = None,
):
    """Per-shard decode step (inside ``shard_map``).

    Local qkv GEMM → rope at position ``kv_len`` → cache append →
    GQA flash-decode over local heads → o-proj partial → all-reduce.
    Returns ``(out [B, d] replicated, k_cache, v_cache)``.
    """
    b = x.shape[0]
    qkv = jnp.dot(x, params.wqkv, preferred_element_type=jnp.float32).astype(
        x.dtype
    )
    q, k, v = dims.split_qkv(qkv)  # [B, h, hd]
    q = _rms_head(q, params.q_norm)
    k = _rms_head(k, params.k_norm)
    q = apply_rope(q, kv_len[:, None], dims.rope_theta)
    k = apply_rope(k, kv_len[:, None], dims.rope_theta)

    # Append at position kv_len[b] (per-sequence scatter).
    def upd(cache, new):  # cache [h, S, hd], new [h, hd], pos scalar
        def one(c, n, p):
            return jax.lax.dynamic_update_slice(c, n[:, None, :], (0, p, 0))
        return jax.vmap(one)(cache, new, kv_len)

    k_cache = upd(k_cache, k)
    v_cache = upd(v_cache, v)

    o = flash_decode(q, k_cache, v_cache, kv_len + 1)  # [B, hq_loc, hd]
    o_flat = o.reshape(b, dims.hq_loc * dims.head_dim).astype(x.dtype)
    if mode in ("xla", "xla_ar"):
        part = jnp.dot(o_flat, params.wo, preferred_element_type=jnp.float32)
        out = jax.lax.psum(part.astype(x.dtype), axis)
    elif mode in ("pallas", "pallas_ar"):
        # o-proj fused with its cross-rank sum (parity: the reference AR
        # decode o-proj + allreduce, tp_attn.py:261-271).
        out = gemm_ar(o_flat, params.wo, axis=axis, ctx=ctx)
    else:
        raise ValueError(f"unknown mode {mode!r}")
    return out, k_cache, v_cache


def tp_attn_decode_paged(
    params: TPAttnParams,
    x: jax.Array,          # [B, d] replicated — one new token per sequence
    k_pages: jax.Array,    # [P, hkv_loc, page, hd] — this layer's pool shard
    v_pages: jax.Array,
    page_table: jax.Array,  # [B, pages_per_seq] int32
    kv_len: jax.Array,      # [B] int32
    dims: TPAttnDims,
    *,
    axis: str = "tp",
    mode: Mode = "pallas_ar",
    ctx: DistContext | None = None,
    k_scale: jax.Array | None = None,  # [P, hkv_loc] f32 — int8 pool scales
    v_scale: jax.Array | None = None,
):
    """Per-shard decode step over a paged KV pool (inside ``shard_map``).

    Same dataflow as :func:`tp_attn_decode`, but the cache is the page
    pool: the append scatters through the page table and the attention
    is :func:`paged_flash_decode` (table-indexed BlockSpecs — no dense
    gather). Parity: the reference megakernel's paged decode
    (``mega_triton_kernel/models/paged_kv_cache.py``).

    With ``k_scale``/``v_scale`` (int8 pool) the append quantizes each
    new row into its page (growing the page scale, requantizing when it
    moves) and the attention streams int8 codes, dequantized inside the
    kernel — the decode step's KV read is half the bf16 bytes. Returns
    ``(out [B, d], k_pages, v_pages, k_scale, v_scale)``.
    """
    from triton_distributed_tpu.ops.attention import paged_flash_decode

    b = x.shape[0]
    page = k_pages.shape[2]
    quant = k_scale is not None
    qkv = jnp.dot(x, params.wqkv, preferred_element_type=jnp.float32).astype(
        x.dtype
    )
    q, k, v = dims.split_qkv(qkv)  # [B, h, hd]
    q = _rms_head(q, params.q_norm)
    k = _rms_head(k, params.k_norm)
    q = apply_rope(q, kv_len[:, None], dims.rope_theta)
    k = apply_rope(k, kv_len[:, None], dims.rope_theta)

    def upd(pages, new):  # pages [P, h, page, hd], new [B, h, hd]
        for i in range(b):
            pos = kv_len[i]
            pid = page_table[i, pos // page]
            pages = jax.lax.dynamic_update_slice(
                pages, new[i][None, :, None, :].astype(pages.dtype),
                (pid, 0, pos % page, 0),
            )
        return pages

    def upd_q(pages, scales, new):
        from triton_distributed_tpu.models.paged_kv_cache import (
            quantized_row_scatter,
        )

        # One batched scatter for all B sequences (active rows never
        # share a page; inactive rows fan into the trash page, where
        # the scatter's duplicate-pid contract holds).
        pids = page_table[jnp.arange(b), kv_len // page]
        return quantized_row_scatter(
            pages, scales, new, pids, kv_len % page
        )

    if quant:
        k_pages, k_scale = upd_q(k_pages, k_scale, k)
        v_pages, v_scale = upd_q(v_pages, v_scale, v)
    else:
        k_pages = upd(k_pages, k)
        v_pages = upd(v_pages, v)

    o = paged_flash_decode(
        q, k_pages, v_pages, page_table, kv_len + 1,
        k_scale=k_scale, v_scale=v_scale,
    )
    o_flat = o.reshape(b, dims.hq_loc * dims.head_dim).astype(x.dtype)
    if mode in ("xla", "xla_ar"):
        part = jnp.dot(o_flat, params.wo, preferred_element_type=jnp.float32)
        out = jax.lax.psum(part.astype(x.dtype), axis)
    elif mode in ("pallas", "pallas_ar"):
        out = gemm_ar(o_flat, params.wo, axis=axis, ctx=ctx)
    else:
        raise ValueError(f"unknown mode {mode!r}")
    return out, k_pages, v_pages, k_scale, v_scale


class TPAttn:
    """Host-level layer (parity: ``TP_Attn``, ``layers/nvidia/tp_attn.py:78``)."""

    def __init__(
        self,
        d_model: int,
        num_q_heads: int,
        num_kv_heads: int,
        head_dim: int,
        *,
        qk_norm: bool = True,
        rope_theta: float = 1e6,
        dtype=jnp.bfloat16,
        axis: str = "tp",
        ctx: DistContext | None = None,
    ):
        self.ctx = ctx or current_context()
        self.axis = axis
        n = self.ctx.axis_size(axis)
        if num_q_heads % n or num_kv_heads % n:
            raise ValueError(
                f"heads ({num_q_heads}, {num_kv_heads}) not divisible by tp={n}"
            )
        self.d_model = d_model
        self.num_q_heads = num_q_heads
        self.num_kv_heads = num_kv_heads
        self.dims = TPAttnDims(
            hq_loc=num_q_heads // n,
            hkv_loc=num_kv_heads // n,
            head_dim=head_dim,
            rope_theta=rope_theta,
        )
        self.qk_norm = qk_norm
        self.dtype = dtype
        self.params: TPAttnParams | None = None

    def load(
        self,
        wq: jax.Array,  # [d, hq * hd]
        wk: jax.Array,  # [d, hkv * hd]
        wv: jax.Array,  # [d, hkv * hd]
        wo: jax.Array,  # [hq * hd, d]
        q_norm: jax.Array | None = None,
        k_norm: jax.Array | None = None,
    ) -> TPAttnParams:
        """Shard full weights: per-device wqkv = [q_loc | k_loc | v_loc]."""
        n = self.ctx.axis_size(self.axis)
        hd = self.dims.head_dim
        d = self.d_model

        def by_shard(w, h):  # [d, h*hd] → [n, d, (h/n)*hd]
            return w.reshape(d, n, (h // n) * hd).swapaxes(0, 1)

        wqkv = jnp.concatenate(
            [
                by_shard(wq, self.num_q_heads),
                by_shard(wk, self.num_kv_heads),
                by_shard(wv, self.num_kv_heads),
            ],
            axis=2,
        )  # [n, d, qkv_loc]
        wqkv = wqkv.swapaxes(0, 1).reshape(d, n * self.dims.qkv_loc)
        self.params = TPAttnParams(
            wqkv=self.ctx.shard(wqkv.astype(self.dtype), None, self.axis),
            wo=self.ctx.shard(wo.astype(self.dtype), self.axis, None),
            q_norm=None if q_norm is None else self.ctx.replicate(q_norm),
            k_norm=None if k_norm is None else self.ctx.replicate(k_norm),
        )
        return self.params

    def init(self, key: jax.Array) -> TPAttnParams:
        hd = self.dims.head_dim
        ks = jax.random.split(key, 4)
        scale = self.d_model**-0.5
        wq = jax.random.normal(ks[0], (self.d_model, self.num_q_heads * hd)) * scale
        wk = jax.random.normal(ks[1], (self.d_model, self.num_kv_heads * hd)) * scale
        wv = jax.random.normal(ks[2], (self.d_model, self.num_kv_heads * hd)) * scale
        wo = jax.random.normal(ks[3], (self.num_q_heads * hd, self.d_model)) * scale
        qn = kn = jnp.ones((hd,)) if self.qk_norm else None
        return self.load(
            wq.astype(self.dtype), wk.astype(self.dtype), wv.astype(self.dtype),
            wo.astype(self.dtype), qn, kn,
        )

    @property
    def param_specs(self):
        from jax.sharding import PartitionSpec as P

        return TPAttnParams(
            wqkv=P(None, self.axis), wo=P(self.axis, None),
            q_norm=None if not self.qk_norm else P(),
            k_norm=None if not self.qk_norm else P(),
        )

    def prefill(self, x: jax.Array, mode: Mode = "pallas") -> jax.Array:
        """``x [S, d]`` host-global; returns ``[S, d]`` (seq-sharded)."""
        from jax.sharding import PartitionSpec as P

        assert self.params is not None
        f = self.ctx.shard_map(
            functools.partial(
                tp_attn_prefill, dims=self.dims, axis=self.axis, mode=mode,
                ctx=self.ctx,
            ),
            in_specs=(self.param_specs, P(self.axis, None)),
            out_specs=(P(self.axis, None), P(self.axis), P(self.axis)),
        )
        out, _, _ = f(self.params, x)
        return out
