"""Model layers: tensor/expert/sequence-parallel building blocks.

Parity: reference ``python/triton_dist/layers/nvidia/`` (SURVEY.md §2.2 L9)
— ``TP_MLP``, ``TP_Attn``, ``TP_MoE``, ``EPAll2AllLayer``,
``SpGQAFlashDecodeAttention``, ``CommOp``.

Design: each layer is a pure-JAX parameter pytree + per-shard forward
functions meant to run inside a model-level ``shard_map`` (every device
executes the same program on its shard — the analog of the reference's
one-process-per-GPU SPMD). Host-level ``*_op`` wrappers build the
``shard_map`` for standalone use/tests. Three forward modes mirror the
reference's per-layer ``torch`` / ``triton_dist`` / ``triton_dist_AR``
switch (``models/qwen.py:84-96``):

- ``xla``      — jax.lax collectives (golden path; NCCL-analog)
- ``pallas``   — fused overlap kernels (ag_gemm / gemm_rs; prefill)
- ``pallas_ar``— all-reduce decode path (small-batch latency)
"""

from triton_distributed_tpu.layers.tp_mlp import TPMLP  # noqa: F401
from triton_distributed_tpu.layers.tp_attn import TPAttn  # noqa: F401
