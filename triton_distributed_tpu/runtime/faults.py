"""Deterministic fault injection for the serving stack.

Triton-distributed ships its overlap kernels with correctness
scaffolding because async resource-sharing bugs are silent until they
corrupt outputs (arXiv:2504.19442); the same discipline applies to the
shared-page serving loop: a refcount leak after a mid-batch failure is
invisible until the pool wedges under load. This module makes those
failures *reproducible*: a seeded :class:`FaultPlan` arms named seams in
the engine/pool/server code, and the chaos suite (``tests/test_faults.py``)
proves every injected fault leaves the engine serviceable and the
pool/radix audit clean.

Seams currently instrumented (grep for ``fault_point``/``mutate_point``):

=================  =====================================================
``pool.allocate``  ``PagePool.allocate`` — pool-exhaustion faults
``engine.admit``   ``ContinuousEngine._admit`` — prefill-time failures
``engine.decode``  ``ContinuousEngine._decode_once`` — decode-step
                   exceptions (attributable via ``slot=``)
``engine.logits``  decode logits mutation hook — NaN/Inf injection
``engine.mega_drain``  ``ContinuousEngine._drain_launch`` — a mega
                   drain that raises mid-resident-round (proves the
                   just-issued next launch is parked in ``_pend`` for
                   the guard's ``_abort_pend``, never orphaned)
``spec.verify``    ``speculative.spec_verify_slot`` — verify failures
``server.recv``    ``ModelServer._serve_lines`` read side — socket
                   drops / slow clients (``delay=``)
``server.send``    ``ModelServer._serve_lines`` write side
``stream.send``    one streaming token frame's bytes (mutate-style,
                   the wire-seam pattern: drop via a raising rule —
                   the server reads it as a client disconnect and
                   CANCELS the payload's requests — garble via
                   corruption the client's JSON parse catches)
``engine.cancel``  ``ContinuousEngine._apply_cancels`` — between the
                   pending-cancel snapshot and its application, so a
                   cancel can be raced deterministically against a
                   slot's natural finish (``delay=``)
``replica.run``    ``EngineReplica._run_batch`` — replica-kill /
                   replica-hang for the multi-engine router tier
                   (``replica=`` narrows to one replica by name)
``wire.connect``   ``serving/remote.py`` client connect — refused /
                   partitioned replica processes (raise-style)
``wire.send``      remote batch payload bytes (mutate-style: drop via
                   a raising rule, garble via corruption)
``wire.recv``      remote response line bytes (mutate-style, same
                   drop/garble rules as ``wire.send``)
``proc.kill``      the replica child's pid, offered mid-batch — a
                   ``kill_proc`` rule SIGKILLs the process while its
                   batch is in flight (``serving/supervisor.py``)
``proc.hang``      same offer point — a ``hang_proc`` rule SIGSTOPs
                   the child so heartbeats wedge without the process
                   exiting (resume with ``os.kill(pid, SIGCONT)``)
``migrate.export`` ``models/slot_state.py::export_slot`` — a slot
                   export dies before any state is read (the slot
                   keeps decoding; handoff retries or finishes local)
``migrate.import`` ``models/slot_state.py::import_slot`` — a snapshot
                   import dies before pages are claimed (the engine
                   falls back to replay-from-prompt)
``tier.put``       ``models/kv_tier.py::PageStore.put`` — mutate-style
                   (one hit counter, wire-seam pattern): a spill /
                   snapshot persist refuses (raising mutate: the entry
                   is simply not stored, the page drops as pre-tier),
                   stalls, or is corrupted in flight (the checksum
                   catches it at the next ``get``)
``tier.get``       ``PageStore.get`` — mutate-style: a fault-back read
                   refuses (treated as a transient miss, the request
                   re-prefills/replays), stalls, or is corrupted (the
                   integrity check drops the entry and degrades —
                   wrong bits can never come out)
``fabric.probe``   ``kv_tier.FabricClient`` peer probe — mutate-style:
                   a dead/refusing peer (raising mutate) cools down
                   and the fetch falls through to the local-miss path;
                   a stall trips the fetch deadline (``peer=`` narrows
                   to one peer by name)
``fabric.get``     the pulled entry's wire bytes — mutate-style: a
                   garbled remote entry CRC-drops to re-prefill
                   exactly like a corrupt local one (the PR 12 codec
                   is the transport); a stall past the pull deadline
                   discards even valid late bytes
``launcher.spawn`` ``serving/launcher.py`` — offered (``replica=``,
                   ``host=``) before any spawn work; an armed rule
                   surfaces as ``SpawnError``, driving the
                   supervisor's spawn-FAILOVER path
                   (``refuse_spawn``)
``host.down``      the replica's host TAG, offered mid-batch next to
                   ``proc.kill`` — a ``kill_host``/``hang_host`` rule
                   takes the WHOLE fake host down while a batch is in
                   flight (``host=`` narrows; the mutate closure
                   holds the ``FakeHostLauncher`` that owns the
                   process groups)
=================  =====================================================

The ``wire.*``/``proc.*`` seams live on the *router-process* side of
the socket (``RemoteReplica``'s send/recv path): a ``FaultPlan`` is
process-global, so arming the parent is what makes cross-process chaos
deterministic — the child never needs a plan.

Usage::

    plan = (FaultPlan(seed=7)
            .exhaust_pool(at=2)          # 2nd allocation raises
            .nan_logits(at=3, slot=1))   # 3rd decode step: slot 1 NaN
    with plan:
        results = engine.run(reqs, results=True)
    assert plan.fired  # every firing is logged for assertions

A plan is deterministic by construction: rules fire on exact per-seam
hit counts (``at``/``every``) or on a coin drawn from the plan's own
seeded RNG (``prob``) — same seed, same call order, same faults. When
no plan is active every seam is a single ``is None`` check.
"""

from __future__ import annotations

import dataclasses
import random
import threading
import time
from collections import Counter
from typing import Any, Callable

from triton_distributed_tpu.obs import events as obs_events


class FaultError(RuntimeError):
    """An injected fault. ``seam`` names the injection point; ``slot``
    (when not None) attributes the fault to one engine slot, so the
    engine's per-request isolation evicts exactly that request instead
    of failing the whole batch."""

    def __init__(self, seam: str, note: str = "injected fault",
                 slot: int | None = None):
        where = f"{note} at seam '{seam}'"
        if slot is not None:
            where += f" (slot {slot})"
        super().__init__(where)
        self.seam = seam
        self.slot = slot


@dataclasses.dataclass
class FaultRule:
    """One arming of one seam. Fires when the seam's hit count is in
    ``at``, or divides ``every``, or the seeded coin lands under
    ``prob`` — at most ``times`` total — and then raises ``exc`` (a
    :class:`FaultError` by default), sleeps ``delay`` seconds, or runs
    ``mutate(value, ctx)`` over the seam's value (mutation seams
    only). ``match`` keys must equal the seam's context kwargs."""

    seam: str
    at: tuple[int, ...] = ()
    every: int = 0
    prob: float = 0.0
    times: int = 1
    slot: int | None = None
    exc: BaseException | None = None
    mutate: Callable[[Any, dict], Any] | None = None
    delay: float = 0.0
    match: dict = dataclasses.field(default_factory=dict)
    fired: int = 0


def _event_fields(ctx: dict, seam: str, hit: int) -> dict:
    """Fault-event fields from an arbitrary seam ctx: the event's own
    keys always win; colliding ctx keys survive under a ``ctx_``
    prefix (see :func:`obs.events.safe_fields`) instead of
    TypeError-ing out of an injection site or being dropped."""
    fields = obs_events.safe_fields(ctx, reserved=("seam", "hit"))
    fields["seam"] = seam
    fields["hit"] = hit
    return fields


class FaultPlan:
    """A seeded, self-logging set of :class:`FaultRule`\\ s.

    Activate with ``with plan:`` — activation is process-global (the
    server thread must see the same plan as the test thread), guarded
    against nesting. ``plan.fired`` records ``(seam, hit, ctx)`` for
    every firing so tests can assert the plan actually exercised its
    seams."""

    def __init__(self, seed: int = 0):
        self.rng = random.Random(seed)
        self.rules: list[FaultRule] = []
        self.hits: Counter = Counter()
        self.fired: list[tuple[str, int, dict]] = []
        # Seams fire from multiple threads (the server is
        # thread-per-connection): hit counting and rule bookkeeping
        # must be atomic or times=1 rules double-fire under races.
        self._lock = threading.Lock()

    # -- arming ----------------------------------------------------------

    def on(
        self,
        seam: str,
        *,
        at: int | tuple[int, ...] | None = None,
        every: int = 0,
        prob: float = 0.0,
        times: int = 1,
        slot: int | None = None,
        exc: BaseException | None = None,
        mutate: Callable[[Any, dict], Any] | None = None,
        delay: float = 0.0,
        **match,
    ) -> "FaultPlan":
        """Arm ``seam``; returns ``self`` for chaining."""
        ats = () if at is None else (
            (int(at),) if isinstance(at, int) else tuple(int(a) for a in at)
        )
        if not ats and not every and prob <= 0.0:
            ats = (1,)  # default: fire on the first hit
        self.rules.append(FaultRule(
            seam=seam, at=ats, every=int(every), prob=float(prob),
            times=int(times), slot=slot, exc=exc, mutate=mutate,
            delay=float(delay), match=dict(match),
        ))
        return self

    # Named-seam conveniences (the chaos suite reads as a fault menu).

    def exhaust_pool(self, at: int = 1, times: int = 1) -> "FaultPlan":
        """Nth ``PagePool.allocate`` raises as if the pool were empty."""
        return self.on("pool.allocate", at=at, times=times,
                       exc=RuntimeError("page pool exhausted (injected)"))

    def admit_exc(self, at: int = 1, times: int = 1) -> "FaultPlan":
        """Nth admission prefill raises."""
        return self.on("engine.admit", at=at, times=times)

    def decode_exc(self, at: int = 1, slot: int | None = None,
                   times: int = 1) -> "FaultPlan":
        """Nth decode step raises; ``slot`` attributes the fault so
        only that request fails (None → the whole step is poisoned)."""
        return self.on("engine.decode", at=at, slot=slot, times=times)

    def nan_logits(self, at: int = 1, slot: int = 0,
                   times: int = 1) -> "FaultPlan":
        """Nth decode step's logits for ``slot`` become NaN."""

        def _nanify(value, _ctx):
            import jax.numpy as jnp
            import numpy as np

            arr = np.array(value, np.float32)
            arr[slot] = np.nan
            return jnp.asarray(arr)

        return self.on("engine.logits", at=at, times=times, mutate=_nanify)

    def verify_exc(self, at: int = 1, times: int = 1) -> "FaultPlan":
        """Nth speculative verify raises (attributed to its slot by the
        seam's own context)."""
        return self.on("spec.verify", at=at, times=times)

    def drop_connection(self, at: int = 1, times: int = 1) -> "FaultPlan":
        """Nth server response write raises mid-stream (client vanishes
        between request and response)."""
        return self.on("server.send", at=at, times=times,
                       exc=BrokenPipeError("connection dropped (injected)"))

    def slow_client(self, delay: float, at: int = 1,
                    times: int = 1) -> "FaultPlan":
        """Nth server read stalls ``delay`` seconds before proceeding."""
        return self.on("server.recv", at=at, times=times, delay=delay)

    def drop_stream(self, at: int = 1, times: int = 1,
                    **match) -> "FaultPlan":
        """The Nth streaming token-frame write raises as if the client
        vanished mid-stream: the server's stream sink marks itself
        broken and CANCELS the payload's requests — slots torn down,
        pages freed, survivors untouched (docs/serving.md 'Streaming &
        cancellation'). Narrow with ``tid=``."""

        def _raise(_value, _ctx):
            raise BrokenPipeError("stream client vanished (injected)")

        return self.on("stream.send", at=at, times=times, mutate=_raise,
                       **match)

    def garble_stream(self, at: int = 1, times: int = 1,
                      **match) -> "FaultPlan":
        """The Nth streaming frame's bytes are reversed in flight
        (valid JSON never survives it): the CLIENT's frame parse fails
        mid-stream — exercising the consumer-side protocol-error path
        while the server keeps serving."""

        def _garble(value, _ctx):
            return bytes(reversed(bytes(value)))

        return self.on("stream.send", at=at, times=times, mutate=_garble,
                       **match)

    def slow_cancel(self, delay: float, at: int = 1,
                    times: int = 1) -> "FaultPlan":
        """The Nth cancel application stalls ``delay`` seconds between
        snapshotting the pending ids and applying them — the
        deterministic handle on the cancel-vs-natural-finish race
        (whichever side the test wants to win, it sequences here)."""
        return self.on("engine.cancel", at=at, times=times, delay=delay)

    def kill_replica(self, replica: str | None = None, at: int = 0,
                     times: int = 1) -> "FaultPlan":
        """A router-tier replica's batch run raises as if its engine
        thread crashed. ``replica`` (the replica's name) narrows the
        seam to one replica and fires on its FIRST matching run;
        ``at`` instead fires on the Nth ``replica.run`` hit across all
        replicas (hit counts are per-seam, not per-replica)."""
        match = {} if replica is None else {"replica": replica}
        if at:
            return self.on("replica.run", at=at, times=times, **match)
        return self.on("replica.run", every=1, times=times, **match)

    def hang_replica(self, delay: float, replica: str | None = None,
                     times: int = 1) -> "FaultPlan":
        """A replica's batch run stalls ``delay`` seconds before
        touching its engine — the router-observed-timeout scenario
        (the router marks it unhealthy and re-routes; the late run's
        results latch harmlessly)."""
        match = {} if replica is None else {"replica": replica}
        return self.on("replica.run", every=1, times=times, delay=delay,
                       **match)

    def fail_export(self, at: int = 1, times: int = 1) -> "FaultPlan":
        """Nth slot export raises mid-migration (the source end of a
        handoff dies): the request keeps decoding locally — a handoff
        drain stays lossless, just slower (docs/scale-out.md 'Slot
        migration & handoff'). ``at=0`` fires on EVERY export (up to
        ``times``) — the export path is retried at round boundaries,
        so killing one attempt only delays the handoff."""
        kw = {"at": at} if at else {"every": 1}
        return self.on("migrate.export", times=times, **kw)

    # Tier seams (docs/serving.md "Tiered KV"). Like the wire seams,
    # refuse/corrupt/slow all ride ONE mutate-style seam per direction
    # (``tier.put``/``tier.get``), so they share a single deterministic
    # hit counter: refuse is a raising mutate, slow a sleeping one.

    def refuse_tier(self, op: str = "put", at: int = 0,
                    times: int = 1, **match) -> "FaultPlan":
        """The Nth ``tier.put``/``tier.get`` refuses: a refused put
        drops the spill exactly like the pre-tier eviction, a refused
        get reads as a transient miss (the entry survives) — both
        degrade to re-prefill/replay, never corrupt. ``at=0`` fires on
        every matching hit up to ``times``; narrow with
        ``kind=``/``key=``."""
        if op not in ("put", "get"):
            raise ValueError(f"op must be 'put' or 'get', got {op!r}")

        def _refuse(_value, _ctx):
            raise FaultError(f"tier.{op}", "tier refused (injected)")

        kw = {"at": at} if at else {"every": 1}
        return self.on(f"tier.{op}", times=times, mutate=_refuse,
                       **kw, **match)

    def corrupt_tier(self, op: str = "get", at: int = 0,
                     times: int = 1, **match) -> "FaultPlan":
        """The Nth matching tier entry's bytes are corrupted in flight
        (a middle byte flipped — the CRC can never validate it):
        exercises the integrity-drop path, proving a bad entry yields
        a degraded re-prefill and NEVER wrong KV bits."""
        if op not in ("put", "get"):
            raise ValueError(f"op must be 'put' or 'get', got {op!r}")

        def _flip(value, _ctx):
            b = bytearray(bytes(value))
            if b:
                b[len(b) // 2] ^= 0xFF
            return bytes(b)

        kw = {"at": at} if at else {"every": 1}
        return self.on(f"tier.{op}", times=times, mutate=_flip,
                       **kw, **match)

    def slow_tier(self, delay: float, op: str = "get", at: int = 0,
                  times: int = 1, **match) -> "FaultPlan":
        """The Nth matching tier access stalls ``delay`` seconds (a
        cold disk / contended host) before proceeding normally (a
        sleeping mutate, so it shares the seam's one hit counter)."""
        if op not in ("put", "get"):
            raise ValueError(f"op must be 'put' or 'get', got {op!r}")

        def _stall(value, _ctx):
            time.sleep(delay)
            return value

        kw = {"at": at} if at else {"every": 1}
        return self.on(f"tier.{op}", times=times, mutate=_stall,
                       **kw, **match)

    # Fabric seams (docs/scale-out.md "KV fabric") — same one-seam-per-
    # direction discipline as the tier seams: refuse is a raising
    # mutate, slow a sleeping one, garble a byte flip the puller's CRC
    # catches. Narrow with ``peer=`` (peer name) / ``kind=`` / ``key=``.

    def refuse_fabric(self, op: str = "get", at: int = 0,
                      times: int = 1, **match) -> "FaultPlan":
        """The Nth matching fabric probe/pull raises as if the peer
        were dead or refusing: the peer cools down and the fetch
        degrades to the local-miss path (re-prefill) without blocking
        admission. ``at=0`` fires on every matching hit up to
        ``times``."""
        if op not in ("probe", "get"):
            raise ValueError(f"op must be 'probe' or 'get', got {op!r}")

        def _refuse(_value, _ctx):
            raise FaultError(f"fabric.{op}", "fabric peer refused (injected)")

        kw = {"at": at} if at else {"every": 1}
        return self.on(f"fabric.{op}", times=times, mutate=_refuse,
                       **kw, **match)

    def corrupt_fabric(self, at: int = 0, times: int = 1,
                       **match) -> "FaultPlan":
        """The Nth matching pulled entry's wire bytes are corrupted in
        flight (a middle byte flipped — the CRC can never validate
        it): the puller drops the entry and re-prefills BIT-EXACTLY,
        proving a garbled remote entry dies at the same containment
        boundary as a corrupt local one."""

        def _flip(value, _ctx):
            b = bytearray(bytes(value))
            if b:
                b[len(b) // 2] ^= 0xFF
            return bytes(b)

        kw = {"at": at} if at else {"every": 1}
        return self.on("fabric.get", times=times, mutate=_flip,
                       **kw, **match)

    def slow_fabric(self, delay: float, op: str = "get", at: int = 0,
                    times: int = 1, **match) -> "FaultPlan":
        """The Nth matching fabric access stalls ``delay`` seconds (a
        hung peer): a stall past the client's ``pull_timeout_s`` trips
        the fetch deadline — the pull fails, admission re-prefills and
        never waits the peer out."""
        if op not in ("probe", "get"):
            raise ValueError(f"op must be 'probe' or 'get', got {op!r}")

        def _stall(value, _ctx):
            time.sleep(delay)
            return value

        kw = {"at": at} if at else {"every": 1}
        return self.on(f"fabric.{op}", times=times, mutate=_stall,
                       **kw, **match)

    def fail_import(self, at: int = 1, times: int = 1) -> "FaultPlan":
        """Nth snapshot import raises mid-migration (the target end
        dies before claiming pages): the engine falls back to a full
        replay from the prompt — correct output, saved work lost.
        ``at=0`` fires on every import up to ``times``."""
        kw = {"at": at} if at else {"every": 1}
        return self.on("migrate.import", times=times, **kw)

    # Wire/process seams for the cross-process fleet (docs/scale-out.md
    # "Process fleet"). ``replica=`` narrows every one of these to one
    # RemoteReplica by name; ``side`` picks the wire direction. The
    # wire seams fire for BOTH generation batches and probes
    # (heartbeats, remote audits) and share one hit counter — so the
    # conveniences match ``what="batch"`` by default: with a
    # supervisor's timer-driven heartbeats in the same process, a
    # what-unnarrowed times=1 rule would nondeterministically land on
    # a probe instead of the intended mid-batch fault. Pass
    # ``what="probe"`` to target heartbeats, ``what=None`` for either.

    def refuse_connect(self, replica: str | None = None, at: int = 0,
                       times: int = 1,
                       what: str | None = "batch") -> "FaultPlan":
        """A RemoteReplica's connect raises as if the child's listener
        were gone (partition / process death between batches)."""
        match = {} if replica is None else {"replica": replica}
        if what is not None:
            match["what"] = what
        kw = {"at": at} if at else {"every": 1}
        return self.on(
            "wire.connect", times=times,
            exc=ConnectionRefusedError("connection refused (injected)"),
            **kw, **match,
        )

    def drop_wire(self, side: str = "recv", replica: str | None = None,
                  at: int = 0, times: int = 1,
                  what: str | None = "batch") -> "FaultPlan":
        """The wire dies mid-batch: the Nth matching send/recv raises
        ``ConnectionResetError`` (the RST a killed or partitioned child
        produces). Implemented as a raising mutate rule so drop and
        garble share one seam and one hit counter per direction."""
        if side not in ("send", "recv"):
            raise ValueError(f"side must be 'send' or 'recv', got {side!r}")

        def _raise(_value, _ctx):
            raise ConnectionResetError(
                f"wire.{side} reset (injected)"
            )

        match = {} if replica is None else {"replica": replica}
        if what is not None:
            match["what"] = what
        kw = {"at": at} if at else {"every": 1}
        return self.on(f"wire.{side}", times=times, mutate=_raise,
                       **kw, **match)

    def garble_wire(self, side: str = "recv",
                    replica: str | None = None, at: int = 0,
                    times: int = 1,
                    what: str | None = "batch") -> "FaultPlan":
        """The Nth matching wire payload is corrupted in flight (bytes
        reversed — valid UTF-8 JSON never survives it), exercising the
        protocol-error detection path rather than the clean-close one."""
        if side not in ("send", "recv"):
            raise ValueError(f"side must be 'send' or 'recv', got {side!r}")

        def _garble(value, _ctx):
            return bytes(reversed(bytes(value)))

        match = {} if replica is None else {"replica": replica}
        if what is not None:
            match["what"] = what
        kw = {"at": at} if at else {"every": 1}
        return self.on(f"wire.{side}", times=times, mutate=_garble,
                       **kw, **match)

    def kill_proc(self, replica: str | None = None, at: int = 0,
                  times: int = 1, after_s: float = 0.0) -> "FaultPlan":
        """SIGKILL the replica child process mid-batch: the seam offers
        the child's pid right after the batch payload went out, so the
        kill lands while the batch is in flight — the OS then closes
        the socket and the parent's recv sees the crash exactly as a
        real OOM-kill would read. ``after_s`` sleeps before the kill
        (on the waiting worker thread, so the batch stays in flight):
        the child makes real progress first — what the snapshot-based
        recovery tests need a mid-generation kill for."""
        import os
        import signal

        def _kill(pid, _ctx):
            if after_s:
                time.sleep(after_s)
            if pid:
                try:
                    os.kill(pid, signal.SIGKILL)
                except ProcessLookupError:
                    pass  # already gone — the failure is still real
            return pid

        match = {} if replica is None else {"replica": replica}
        kw = {"at": at} if at else {"every": 1}
        return self.on("proc.kill", times=times, mutate=_kill,
                       **kw, **match)

    def hang_proc(self, replica: str | None = None, at: int = 0,
                  times: int = 1) -> "FaultPlan":
        """SIGSTOP the replica child mid-batch: the process stays alive
        (no exit code, no RST) but stops answering heartbeats — the
        wedged-process scenario only a heartbeat deadline can detect.
        Tests resume the child with ``os.kill(pid, SIGCONT)`` to drive
        the late-result latch race."""
        import os
        import signal

        if not hasattr(signal, "SIGSTOP"):  # pragma: no cover
            raise RuntimeError("platform has no SIGSTOP")

        def _stop(pid, _ctx):
            if pid:
                try:
                    os.kill(pid, signal.SIGSTOP)
                except ProcessLookupError:
                    pass
            return pid

        match = {} if replica is None else {"replica": replica}
        kw = {"at": at} if at else {"every": 1}
        return self.on("proc.hang", times=times, mutate=_stop,
                       **kw, **match)

    def refuse_spawn(self, host: str | None = None,
                     replica: str | None = None, at: int = 0,
                     times: int = 1) -> "FaultPlan":
        """A launcher refuses to spawn: the ``launcher.spawn`` seam
        raises, which every launcher converts to ``SpawnError`` — the
        exact failure the supervisor's spawn-FAILOVER path re-places
        around (``host=`` / ``replica=`` narrow the target)."""
        match = {}
        if host is not None:
            match["host"] = host
        if replica is not None:
            match["replica"] = replica
        kw = {"at": at} if at else {"every": 1}
        return self.on(
            "launcher.spawn", times=times,
            exc=ConnectionRefusedError("host refused spawn (injected)"),
            **kw, **match,
        )

    def kill_host(self, launcher, host: str | None = None,
                  at: int = 0, times: int = 1,
                  after_s: float = 0.0) -> "FaultPlan":
        """SIGKILL a WHOLE fake host mid-batch: the ``host.down`` seam
        offers the host tag right after a batch payload went out to a
        replica living there, and the rule kills every process group
        the launcher tagged with that host — losing the machine while
        its work is in flight, deterministically. ``after_s`` sleeps
        first (on the waiting worker thread) so the host makes real
        progress before it dies."""

        def _down(tag, _ctx):
            if after_s:
                time.sleep(after_s)
            launcher.kill_host(tag)
            return tag

        match = {} if host is None else {"host": host}
        kw = {"at": at} if at else {"every": 1}
        return self.on("host.down", times=times, mutate=_down,
                       **kw, **match)

    def hang_host(self, launcher, host: str | None = None,
                  at: int = 0, times: int = 1) -> "FaultPlan":
        """SIGSTOP a WHOLE fake host mid-batch: every process on it
        stays alive but stops answering — the correlated wedge only
        the supervisor's host-window classification reads as ONE
        ``host_down``. Thaw later with ``launcher.thaw_host`` to drive
        the zombie-vs-epoch-fence race."""

        def _freeze(tag, _ctx):
            launcher.hang_host(tag)
            return tag

        match = {} if host is None else {"host": host}
        kw = {"at": at} if at else {"every": 1}
        return self.on("host.down", times=times, mutate=_freeze,
                       **kw, **match)

    # -- firing ----------------------------------------------------------

    def _matches(self, rule: FaultRule, hit: int, ctx: dict) -> bool:
        if rule.fired >= rule.times:
            return False
        for k, v in rule.match.items():
            if ctx.get(k) != v:
                return False
        if hit in rule.at:
            return True
        if rule.every and hit % rule.every == 0:
            return True
        if rule.prob > 0.0 and self.rng.random() < rule.prob:
            return True
        return False

    def fire(self, seam: str, **ctx) -> None:
        """Raise/sleep per the armed rules; no-op if nothing matches.
        The decision runs under the plan lock (atomic hit counting);
        the sleep/raise happens outside it so a delay rule can't
        serialize every other seam."""
        delay = 0.0
        exc: BaseException | None = None
        fired_hit: int | None = None
        with self._lock:
            self.hits[seam] += 1
            hit = self.hits[seam]
            for rule in self.rules:
                if rule.seam != seam or rule.mutate is not None:
                    continue
                if not self._matches(rule, hit, ctx):
                    continue
                rule.fired += 1
                self.fired.append((seam, hit, dict(ctx)))
                fired_hit = hit
                if rule.delay:
                    delay = rule.delay
                    continue
                exc = rule.exc if rule.exc is not None else FaultError(
                    seam, slot=rule.slot
                )
                break
        if fired_hit is not None:
            # Telemetry (docs/observability.md): every activation lands
            # in the event ring, so a chaos run's injected faults line
            # up with the shed/deadline/nan events they trigger.
            obs_events.emit("fault", **_event_fields(ctx, seam, fired_hit))
        if delay:
            time.sleep(delay)
        if exc is not None:
            raise exc

    def mutate(self, seam: str, value: Any, **ctx) -> Any:
        """Pass ``value`` through the armed mutation rules."""
        matched: list[FaultRule] = []
        with self._lock:
            self.hits[seam] += 1
            hit = self.hits[seam]
            for rule in self.rules:
                if rule.seam != seam or rule.mutate is None:
                    continue
                if not self._matches(rule, hit, ctx):
                    continue
                rule.fired += 1
                self.fired.append((seam, hit, dict(ctx)))
                matched.append(rule)
        if matched:
            obs_events.emit("fault", **_event_fields(ctx, seam, hit))
        for rule in matched:
            value = rule.mutate(value, ctx)
        return value

    # -- activation ------------------------------------------------------

    def __enter__(self) -> "FaultPlan":
        global _ACTIVE
        with _LOCK:
            if _ACTIVE is not None:
                raise RuntimeError("a FaultPlan is already active")
            _ACTIVE = self
        return self

    def __exit__(self, *exc) -> None:
        global _ACTIVE
        with _LOCK:
            _ACTIVE = None


_ACTIVE: FaultPlan | None = None
_LOCK = threading.Lock()


def fault_point(seam: str, **ctx) -> None:
    """A raise-style seam: no-op unless a plan is active and armed."""
    plan = _ACTIVE
    if plan is not None:
        plan.fire(seam, **ctx)


def mutate_point(seam: str, value: Any, **ctx) -> Any:
    """A value-corruption seam: identity unless a plan is armed."""
    plan = _ACTIVE
    if plan is not None:
        return plan.mutate(seam, value, **ctx)
    return value
