"""Hardware probes: measured HBM / ICI bandwidth + topology summary.

Parity: reference ``utils.py:592-867`` — NVLink full-mesh detection,
link-speed and PCIe-bandwidth probes, NUMA maps — which feed its perf
models and method dispatch. The TPU analog measures what the hardware
actually delivers (the relay, driver, and DVFS all shave the datasheet
number) and reports it alongside the static :class:`ChipSpec` and the
detected :class:`MeshTopology`.

Timing follows the relay rules (see ``perf/OVERLAP_RESULTS.md``): every
iteration is data-dependent on the previous one inside a single jit,
the fence is a host fetch, and the statistic is a median over reps.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from triton_distributed_tpu.runtime.mesh import DistContext, current_context
from triton_distributed_tpu.runtime.utils import median_time as _median_time


def measure_hbm_bandwidth_gbs(
    nbytes: int = 256 * 1024 * 1024, iters: int = 32, device=None
) -> float:
    """Measured HBM copy bandwidth (read + write counted) in GB/s.

    The relay adds a large fixed per-call cost (tens of ms), so a single
    timed call understates bandwidth badly; timing ``iters`` and
    ``2 * iters`` and differencing cancels every per-call constant.
    """
    n = nbytes // 4
    x = jnp.arange(n, dtype=jnp.float32)
    if device is not None:
        x = jax.device_put(x, device)

    @functools.partial(jax.jit, static_argnums=1)
    def chained(x, m):
        def body(_, acc):
            # A full read + write of nbytes, chained iteration to
            # iteration by the sub-ulp add.
            return acc + 1e-30

        return jnp.sum(jax.lax.fori_loop(0, m, body, x)[::4096])

    t1 = _median_time(lambda: np.asarray(chained(x, iters)))
    t2 = _median_time(lambda: np.asarray(chained(x, 2 * iters)))
    dt = max(t2 - t1, 1e-9)
    return 2 * nbytes * iters / dt / 1e9


def measure_ici_bandwidth_gbs(
    axis: str = "tp",
    nbytes: int = 64 * 1024 * 1024,
    iters: int = 8,
    ctx: DistContext | None = None,
) -> float:
    """Measured per-link ICI bandwidth via a ring ``ppermute`` chain.

    Each iteration shifts ``nbytes`` to the ring neighbor; with every
    device sending concurrently the timed rate is one link's one-way
    bandwidth. On a CPU simulator mesh this measures memcpy, not ICI —
    meaningful only on real multi-chip hardware; single-chip meshes
    return 0.0 (nothing to permute).
    """
    ctx = ctx or current_context()
    n_dev = ctx.axis_size(axis)
    if n_dev < 2:
        return 0.0
    perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]
    x = jnp.arange(nbytes // 4, dtype=jnp.float32)

    from jax.sharding import PartitionSpec as P

    def make(m):
        def body_fn(x):
            def body(_, acc):
                y = jax.lax.ppermute(acc, axis, perm)
                return y + 1e-30  # chain iterations

            return jnp.sum(jax.lax.fori_loop(0, m, body, x)[::4096])

        return jax.jit(ctx.shard_map(body_fn, in_specs=(P(),), out_specs=P()))

    xs = ctx.replicate(x)
    f1, f2 = make(iters), make(2 * iters)
    # Difference two iteration counts: cancels fixed per-call cost.
    t1 = _median_time(lambda: np.asarray(f1(xs)))
    t2 = _median_time(lambda: np.asarray(f2(xs)))
    dt = max(t2 - t1, 1e-9)
    return nbytes * iters / dt / 1e9


def probe_topology(ctx: DistContext | None = None) -> dict[str, Any]:
    """Topology + spec summary (reference's probe-suite report analog).

    Static facts come from :class:`MeshTopology` (device coords) and
    :func:`chip_spec` (datasheet); ``measured`` adds the live HBM probe
    on TPU. Keys are stable for logging/JSON.
    """
    from triton_distributed_tpu.tools.perf_model import chip_spec

    ctx = ctx or current_context()
    topo = ctx.topology
    spec = chip_spec()
    out = {
        "mesh": {k: int(v) for k, v in ctx.mesh.shape.items()},
        "platform": topo.platform,
        "chip": spec.name,
        "torus_shape": topo.torus_shape,
        "has_wraparound": topo.has_wraparound,
        "num_processes": topo.num_processes,
        "multi_slice": topo.multi_slice,
        "spec": {
            "bf16_tflops": spec.bf16_tflops,
            "hbm_gbs": spec.hbm_gbs,
            "ici_gbs_per_link": spec.ici_gbs_per_link,
            "ici_links": spec.ici_links,
            "dcn_gbs": spec.dcn_gbs,
        },
    }
    if topo.on_tpu:
        out["measured"] = {
            "hbm_gbs": round(measure_hbm_bandwidth_gbs(), 1),
        }
    return out
