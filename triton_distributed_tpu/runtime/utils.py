"""Host-side utilities: rank-filtered printing, timing, seeding, tolerances.

Reference parity: ``python/triton_dist/utils.py`` —
``perf_func``:274, ``dist_print``:289, ``init_seed``:77,
``assert_allclose``:870-899, ``sleep_async``:1018.
"""

from __future__ import annotations

import contextlib
import os
import random
import time
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np


def init_seed(seed: int = 42) -> jax.Array:
    """Deterministic seeding across python/numpy + a jax PRNG key.

    Parity: reference ``init_seed`` (utils.py:77-96) which seeds torch /
    cuda / numpy / random for reproducible multi-rank tests. JAX is
    functional: we seed the host RNGs and hand back a key.
    """
    random.seed(seed)
    np.random.seed(seed)
    return jax.random.key(seed)


def dist_print(*args, prefix: bool = True, allowed_ranks="0", **kwargs) -> None:
    """Print only on the allowed process ranks (parity: utils.py:289-318).

    ``allowed_ranks`` is "all" or an int-list/comma string of process
    indices. On single-process meshes rank is always 0.
    """
    rank = jax.process_index()
    if allowed_ranks != "all":
        if isinstance(allowed_ranks, str):
            allowed = {int(r) for r in allowed_ranks.split(",") if r != ""}
        else:
            allowed = {int(r) for r in allowed_ranks}
        if rank not in allowed:
            return
    if prefix:
        print(f"[rank {rank}]", *args, **kwargs)
    else:
        print(*args, **kwargs)


def perf_func(
    func: Callable[[], object],
    iters: int = 10,
    warmup_iters: int = 5,
) -> tuple[object, float]:
    """Time a thunk, returning (last_output, mean_ms).

    Parity: reference ``perf_func`` (utils.py:274-287) which uses CUDA
    events around a stream; on TPU we block on the returned arrays
    (``jax.block_until_ready``) which is the dispatch-queue analog.
    """
    def _sync(out):
        # On some TPU transports (axon relay) ``block_until_ready`` resolves
        # before device work completes; fetching bytes to host is the only
        # reliable fence. Pull one element per output leaf.
        leaves = [x for x in jax.tree_util.tree_leaves(out) if hasattr(x, "ravel")]
        if leaves:
            jax.device_get([x.ravel()[:1] for x in leaves])

    output = None
    for _ in range(warmup_iters):
        output = func()
    _sync(output)
    start = time.perf_counter()
    for _ in range(iters):
        output = func()
    _sync(output)
    elapsed_ms = (time.perf_counter() - start) * 1e3 / max(iters, 1)
    return output, elapsed_ms


def median_time(run: Callable[[], object], reps: int = 5) -> float:
    """Median wall-time (seconds) of ``run()`` over ``reps`` calls after
    one warmup. ``run`` must fence its own device work (host fetch).

    Median, not min: high-overhead transports (the axon relay) can leak
    one call's device work into the next measurement window — min()
    latches onto the leaked, impossibly-fast rep (see
    perf/OVERLAP_RESULTS.md methodology notes). Shared by bench.py and
    runtime/probe.py.
    """
    run()  # warm (compile on first use)
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        run()
        ts.append(time.perf_counter() - t0)
    return sorted(ts)[len(ts) // 2]


def assert_allclose(x, y, atol=1e-3, rtol=1e-3, verbose: bool = True) -> None:
    """Tolerant comparison with a mismatch report (parity: utils.py:870-899)."""
    x = np.asarray(jax.device_get(x), dtype=np.float64)
    y = np.asarray(jax.device_get(y), dtype=np.float64)
    if x.shape != y.shape:
        raise AssertionError(f"shape mismatch {x.shape} vs {y.shape}")
    close = np.isclose(x, y, atol=atol, rtol=rtol)
    if close.all():
        return
    mismatch = (~close).sum()
    frac = mismatch / close.size
    idx = np.unravel_index(np.argmax(np.abs(x - y)), x.shape)
    raise AssertionError(
        f"{mismatch}/{close.size} ({frac:.2%}) mismatched "
        f"(atol={atol}, rtol={rtol}); worst at {idx}: {x[idx]} vs {y[idx]}"
        + (f"\n x={x}\n y={y}" if verbose and x.size <= 64 else "")
    )


def sleep_async(ms: float):
    """Straggler injection: return a delay thunk to run before a collective.

    Parity: reference ``sleep_async`` (utils.py:1018-1031) which launches a
    spin-kernel on the stream. On TPU we cannot spin a device core from
    Python cheaply, so straggler injection is host-side sleep before
    dispatch — it skews this rank's arrival the same way. Kernels with a
    ``straggler_option`` use ``pl.delay`` on-device instead.
    """

    def _delay():
        time.sleep(ms / 1e3)

    return _delay


@contextlib.contextmanager
def with_env(**env: str):
    """Temporarily set environment variables (test helper)."""
    old = {k: os.environ.get(k) for k in env}
    os.environ.update({k: str(v) for k, v in env.items()})
    try:
        yield
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def bytes_of(tree) -> int:
    """Total bytes of a pytree of arrays (for bandwidth reporting)."""
    leaves = jax.tree_util.tree_leaves(tree)
    return sum(x.size * x.dtype.itemsize for x in leaves if hasattr(x, "dtype"))


def to_bf16(tree):
    return jax.tree.map(
        lambda x: x.astype(jnp.bfloat16)
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)
        else x,
        tree,
    )
