"""Profiling: per-process trace capture with a merged one-file timeline.

Reference parity: ``group_profile`` (``python/triton_dist/utils.py:505-589``)
wraps ``torch.profiler``, exports one chrome trace per rank, gathers them
to rank 0, remaps pids per rank and merges + gzips into a SINGLE
timeline. The TPU-native analog wraps ``jax.profiler`` (XPlane +
chrome-trace export): each process traces into ``<dir>/<name>/rank<i>``,
then rank 0 merges every rank's chrome trace into
``<dir>/<name>/merged.trace.json.gz`` — one file, one timeline, pids
namespaced per rank exactly like the reference's ``merge_json_files``
(``utils.py:370-502``).
"""

from __future__ import annotations

import contextlib
import glob
import gzip
import json
import os
import time

import jax

from triton_distributed_tpu.obs import events as obs_events

# Rank pid namespace stride: chrome-trace pids from one process stay
# below this, so ``rank * _PID_STRIDE + pid`` never collides across
# ranks (the reference remaps pids the same way, ``utils.py:430-470``).
_PID_STRIDE = 10_000_000

# Whether the installed profiler accepts float metadata values. Settled
# by the first float-carrying span (None = not yet probed): a profiler
# that rejects floats costs ONE failed TraceAnnotation construction
# ever, not exception-driven control flow on every spec:rollback span
# in the serving loop. Unsynchronized on purpose — a race just repeats
# the probe.
_FLOAT_META_OK: bool | None = None

# Whether the profiler demands ALL-string metadata: set only when a
# span SUCCEEDED on the uniform-stringify rung after a lower rung was
# rejected — a proven, deterministic type restriction. A span on which
# every rung failed settles nothing beyond the float probe: that
# failure may be transient (capture teardown race), and one transient
# error must not downgrade every future span's metadata to strings.
_STR_META_ONLY: bool = False

@contextlib.contextmanager
def trace_span(name: str, **args):
    """Named host-side span on the jax.profiler timeline AND the
    telemetry event ring.

    The serving engines wrap control-plane phases (prefix-cache
    admission, chunk prefills, evictions, speculative verify/rollback)
    so they land on the same merged trace as the device programs they
    interleave with. For the profiler, arg values outside its metadata
    types are stringified rather than risking the whole span — floats
    (e.g. spec accept rates) are tried natively first and the span is
    RETRIED with them stringified if the installed profiler rejects
    them, so a float-metadata mismatch costs precision, never the
    span — and the rejection is remembered process-wide
    (``_FLOAT_META_OK``), so later float spans go straight to the
    stringified form. The final rung stringifies EVERY arg uniformly,
    so a profiler that rejects some other type too (an out-of-range
    int, say) still gets the span with all-string args instead of
    losing it. Outside an active capture the annotation is free; a profiler
    API mismatch must never sink serving, so entry failures degrade to
    a plain yield (body exceptions still propagate).

    On exit the span also lands in the event ring (kind ``span``, with
    the span's wall duration and its args — numerics kept native), so
    host spans are visible through ``{"cmd": "events"}`` without an
    active profiler capture (docs/observability.md). A span whose site
    already emits a dedicated, richer ring event (e.g. ``spec_verify``)
    passes ``_ring=False`` to skip the duplicate ``span`` entry —
    bounded ring space shouldn't hold the same moment twice."""
    global _FLOAT_META_OK, _STR_META_ONLY
    ring_emit = args.pop("_ring", True)
    span = None
    has_float = any(
        isinstance(v, float) and not isinstance(v, bool)
        for v in args.values()
    )
    # Fallback ladder: floats native → ints native → EVERYTHING
    # stringified. The last rung is the uniform stringify fallback: a
    # profiler that also rejects some non-float type (an int out of
    # its range, say) used to lose the span entirely on the retry
    # path — now such a span survives with all-string args, which is
    # the documented degradation (precision, never the span). Both
    # ladder positions are remembered (_FLOAT_META_OK /
    # _STR_META_ONLY), so a persistently strict profiler costs one
    # construction per span, not the ladder.
    if _STR_META_ONLY:
        variants = ((str,),)
    elif has_float and _FLOAT_META_OK is not False:
        variants = ((int, str, float), (int, str), (str,))
    else:
        variants = ((int, str), (str,))
    for num_ok in variants:
        try:
            prof_args = {
                k: (v if isinstance(v, num_ok) else str(v))
                for k, v in args.items()
            }
            span = jax.profiler.TraceAnnotation(name, **prof_args)
            span.__enter__()
            if has_float:
                # Probe settled: either floats passed natively, or a
                # stringified retry succeeded where the float attempt
                # failed (so the floats were the rejection's cause —
                # a wholly broken profiler never reaches here).
                _FLOAT_META_OK = float in num_ok
            if num_ok == (str,) and len(variants) > 1:
                # A lower rung rejected native numerics beyond floats:
                # later spans skip straight to uniform stringify.
                _STR_META_ONLY = True
            break
        except Exception:
            span = None
    if span is None and has_float and _FLOAT_META_OK is None:
        # Every rung failed (profiler wholly broken, not a float
        # rejection): settle the float probe so later float spans
        # skip the native-float rung. _STR_META_ONLY is NOT set here
        # — a wholly-failed span proves nothing about accepted types,
        # and the failure may be transient.
        _FLOAT_META_OK = False
    # Honor the disabled-mode contract (attribute check + return):
    # skip the clock reads and the kwargs coercion entirely when the
    # ring won't record the event anyway.
    ring = obs_events.default_ring()
    t0 = time.monotonic() if (ring_emit and ring.enabled) else None
    try:
        yield
    finally:
        if span is not None:
            try:
                span.__exit__(None, None, None)
            except Exception:
                pass
        if t0 is not None:
            try:
                # Arg keys colliding with the event's own fields
                # survive under a ctx_ prefix (the shared
                # collision-escape rule, obs.events.safe_fields).
                fields = obs_events.safe_fields(
                    args, reserved=("name", "dur_s")
                )
                ring.emit("span", name=name,
                          dur_s=time.monotonic() - t0, **fields)
            except Exception:
                # Telemetry must never sink the span's body.
                pass


def _load_chrome_trace(path: str) -> dict:
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rt") as f:
        return json.load(f)


def _newest_session_trace(rank_dir: str) -> tuple[str, str] | None:
    """The newest-by-MTIME profiler session under a rank dir →
    ``(session_name, trace_path)``. jax.profiler lays out
    ``<rank_dir>/plugins/profile/<session>/<host>.trace.json.gz``; a
    lexicographic sort of session names picked whichever string
    compared last, so a stale session surviving from a prior run under
    the same profile name could silently win (ADVICE r4). Sessions
    with no exported trace (a failed export) are skipped rather than
    masking an older complete one."""
    root = os.path.join(rank_dir, "plugins", "profile")
    sessions = [s for s in glob.glob(os.path.join(root, "*"))
                if os.path.isdir(s)]
    for s in sorted(sessions, key=os.path.getmtime, reverse=True):
        traces = sorted(glob.glob(os.path.join(s, "*.trace.json.gz")))
        if traces:
            return os.path.basename(s), traces[-1]
    flat = sorted(glob.glob(os.path.join(rank_dir, "*.trace.json.gz")),
                  key=os.path.getmtime)
    if flat:
        # Sentinel session name: a rank resolved via the flat fallback
        # must still participate in the mixed-sessions check — mixing
        # one rank's session-dir trace with another's flat-layout trace
        # is exactly the capture skew the warning exists for (ADVICE r5).
        return "<flat>", flat[-1]
    return None


def merge_group_profile(name: str, out_dir: str = "prof") -> str | None:
    """Merge every rank's chrome trace under ``<out_dir>/<name>`` into
    ONE gzipped timeline, ``<out_dir>/<name>/merged.trace.json.gz``.

    Each rank's events keep their relative pid/tid structure but move
    into a per-rank pid namespace, and every process-name metadata row
    is prefixed ``rank<i>:`` so the merged view in Perfetto/chrome
    reads like the reference's merged ``group_profile`` output. Returns
    the merged path, or None when no rank traces exist (e.g. profiling
    was off).

    Each rank's newest session is picked by MTIME; when ranks resolve
    to DIFFERENT session names (one rank's export failed and an older
    session won, or stale dirs persist under a reused profile name) a
    warning is emitted — the merge still proceeds (partial evidence
    beats none) but the timeline may mix capture sessions (ADVICE r4).
    """
    root = os.path.join(out_dir, name)
    rank_dirs = sorted(
        d for d in glob.glob(os.path.join(root, "rank*"))
        if os.path.isdir(d)
    )
    merged: list = []
    meta: dict = {}
    found = False
    sessions_used: dict[int, str] = {}
    for d in rank_dirs:
        try:
            rank = int(os.path.basename(d).removeprefix("rank"))
        except ValueError:
            continue
        picked = _newest_session_trace(d)
        if picked is None:
            continue
        session, trace_path = picked
        sessions_used[rank] = session
        found = True
        data = _load_chrome_trace(trace_path)
        base = rank * _PID_STRIDE
        for ev in data.get("traceEvents", []):
            ev = dict(ev)
            if isinstance(ev.get("pid"), int):
                ev["pid"] = base + ev["pid"]
            if (ev.get("ph") == "M" and ev.get("name") == "process_name"
                    and isinstance(ev.get("args"), dict)):
                ev["args"] = dict(ev["args"])
                ev["args"]["name"] = (
                    f"rank{rank}: {ev['args'].get('name', '')}"
                )
            merged.append(ev)
        for k, v in data.items():
            if k != "traceEvents":
                meta.setdefault(k, v)
    if not found:
        return None
    if len(set(sessions_used.values())) > 1:
        import warnings

        warnings.warn(
            "merge_group_profile: ranks resolved different capture "
            f"sessions {sessions_used} — the merged timeline may mix "
            "sessions (a rank's export failed, or stale session dirs "
            "persist under this profile name)",
            stacklevel=2,
        )
    out_path = os.path.join(root, "merged.trace.json.gz")
    with gzip.open(out_path, "wt") as f:
        json.dump({**meta, "traceEvents": merged}, f)
    return out_path


@contextlib.contextmanager
def group_profile(
    name: str | None = None,
    do_prof: bool = True,
    out_dir: str = "prof",
    merge: bool = True,
):
    """Context manager capturing a jax.profiler trace for all processes,
    merged to one timeline on exit.

    Usage parity with the reference (``test_ag_gemm.py:109``):

        with group_profile("ag_gemm", do_prof=args.profile):
            run_the_kernel()

    On exit, process 0 merges every rank's chrome trace it can see into
    ``<out_dir>/<name>/merged.trace.json.gz`` (ranks write to a shared
    filesystem in the torchrun-style launches this mirrors; without one,
    gather the ``rank*`` dirs and call :func:`merge_group_profile`
    post-hoc)."""
    if not do_prof or name is None:
        yield
        return
    path = os.path.join(out_dir, name, f"rank{jax.process_index()}")
    os.makedirs(path, exist_ok=True)
    jax.profiler.start_trace(path)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        if merge:
            try:
                if jax.process_count() > 1:
                    # EVERY process joins the sync (it is a collective —
                    # rank-0-only would deadlock); it fences the other
                    # ranks' trace export before rank 0 reads their
                    # files (the reference gathers over the process
                    # group at the same point).
                    from jax.experimental import multihost_utils

                    multihost_utils.sync_global_devices(
                        f"group_profile:{name}"
                    )
                if jax.process_index() == 0:
                    merge_group_profile(name, out_dir)
            except Exception:
                # A failed merge must never sink the profiled run; the
                # per-rank traces are still on disk.
                pass
