"""Profiling: per-process trace capture with a merged timeline.

Reference parity: ``group_profile`` (``python/triton_dist/utils.py:505-589``)
wraps ``torch.profiler``, exports one chrome trace per rank, gathers them to
rank 0 and merges into a single timeline. The TPU-native analog wraps
``jax.profiler`` (XPlane/Perfetto): each process traces into
``<dir>/<name>/rank<i>``; on shared filesystems the result is already merged
by directory layout and loads as one timeline in XProf/Perfetto.
"""

from __future__ import annotations

import contextlib
import os

import jax


@contextlib.contextmanager
def group_profile(
    name: str | None = None,
    do_prof: bool = True,
    out_dir: str = "prof",
):
    """Context manager capturing a jax.profiler trace for all processes.

    Usage parity with the reference (``test_ag_gemm.py:109``):

        with group_profile("ag_gemm", do_prof=args.profile):
            run_the_kernel()
    """
    if not do_prof or name is None:
        yield
        return
    path = os.path.join(out_dir, name, f"rank{jax.process_index()}")
    os.makedirs(path, exist_ok=True)
    jax.profiler.start_trace(path)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
