"""Pytree registration helper for parameter/state dataclasses.

Registers a dataclass both as a JAX pytree node and with ``jax.export``'s
PyTreeDef serializer, so any function over our param/state containers can
be AOT-exported (SURVEY.md §2.1 "AOT runtime": the TPU analog of the
reference's algo-info structs riding beside compiled kernels).
"""

from __future__ import annotations

import jax
from jax import export as jax_export


def register_param_dataclass(cls, data_fields: list[str]):
    """``jax.tree_util.register_dataclass`` (no meta fields) + export
    serialization. Returns ``cls`` for decorator-style use."""
    jax.tree_util.register_dataclass(cls, data_fields, [])
    jax_export.register_pytree_node_serialization(
        cls,
        serialized_name=f"triton_distributed_tpu.{cls.__name__}",
        # No-meta dataclasses flatten with auxdata () — nothing to store.
        serialize_auxdata=lambda aux: b"",
        deserialize_auxdata=lambda b: (),
    )
    return cls
