"""Runtime core: mesh/topology, distributed init, perf and logging utilities.

Parity with the reference's host runtime layer (``python/triton_dist/utils.py``,
see SURVEY.md §2.2 "Host runtime"): ``initialize_distributed`` (utils.py:182),
symmetric-tensor allocation analog, barriers, ``perf_func`` (utils.py:274),
``dist_print`` (utils.py:289), topology probes (utils.py:592-867) — all
re-designed for JAX: process bootstrap is ``jax.distributed.initialize``, the
"symmetric heap" is per-device shards inside ``shard_map`` over a Mesh, and
topology is the TPU ICI/DCN mesh rather than NVLink/NUMA probing.
"""

from triton_distributed_tpu.runtime.mesh import (  # noqa: F401
    DistContext,
    MeshTopology,
    current_context,
    initialize_distributed,
    finalize_distributed,
    set_context,
)
from triton_distributed_tpu.runtime.utils import (  # noqa: F401
    assert_allclose,
    dist_print,
    init_seed,
    perf_func,
    sleep_async,
)
from triton_distributed_tpu.runtime.profiling import group_profile  # noqa: F401
