"""Guarded compatibility aliases for older JAX releases.

The package targets the current JAX API surface; a few names it uses
were introduced after 0.4.x:

- ``jax.lax.axis_size(name)``       — static axis size inside shard_map
- ``pltpu.CompilerParams``          — renamed from ``TPUCompilerParams``
- ``pltpu.InterpretParams``         — structured interpret-mode params

Each alias below is installed ONLY when the running JAX lacks the name
(pure ``hasattr`` guards), so on a current JAX this module is a no-op.
Imported from the package ``__init__`` so every entry point (tests,
benches, serving) sees a uniform surface.
"""

from __future__ import annotations

import jax


def _install() -> None:
    if not hasattr(jax.lax, "axis_size"):
        def axis_size(axis_name):
            # Pre-0.6 the static size lives on the axis frame (newer
            # 0.4.x returns the bare int directly).
            frame = jax.core.axis_frame(axis_name)
            return frame if isinstance(frame, int) else frame.size

        jax.lax.axis_size = axis_size

    from jax.experimental.pallas import tpu as pltpu

    if not hasattr(pltpu, "CompilerParams"):
        import dataclasses as _dc

        _known = {f.name for f in _dc.fields(pltpu.TPUCompilerParams)}

        def _compiler_params(**kw):
            # Fields added after this JAX release (e.g.
            # ``has_side_effects``) are advisory on the interpret path
            # the old release runs here — drop them rather than fail.
            return pltpu.TPUCompilerParams(
                **{k: v for k, v in kw.items() if k in _known}
            )

        pltpu.CompilerParams = _compiler_params

        # Same-era quirk: this release rejects ``unroll`` (even the
        # default-equivalent ``unroll=False``) when fori_loop bounds are
        # traced; current JAX accepts it. Retry without the kwarg —
        # semantics identical (False IS the no-unroll default).
        _orig_fori = jax.lax.fori_loop

        def _fori_loop(lower, upper, body_fun, init_val, **kw):
            try:
                return _orig_fori(lower, upper, body_fun, init_val, **kw)
            except ValueError as e:
                if kw.get("unroll") is False and "unroll" in str(e):
                    kw = dict(kw)
                    kw.pop("unroll")
                    return _orig_fori(lower, upper, body_fun, init_val, **kw)
                raise

        jax.lax.fori_loop = _fori_loop

    if not hasattr(pltpu, "InterpretParams"):
        # Older Pallas takes ``interpret=True`` (plain bool) instead of a
        # params object; the call sites only ever pass the result through
        # to ``pallas_call(interpret=...)``, so truthy-bool is faithful.
        def _interpret_params(**_kw):
            return True

        pltpu.InterpretParams = _interpret_params


_install()
