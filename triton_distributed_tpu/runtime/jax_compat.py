"""Guarded compatibility aliases for older JAX releases.

The package targets the current JAX API surface; a few names it uses
were introduced after 0.4.x:

- ``jax.lax.axis_size(name)``       — static axis size inside shard_map
- ``pltpu.CompilerParams``          — renamed from ``TPUCompilerParams``
- ``pltpu.InterpretParams``         — structured interpret-mode params

Each alias below is installed ONLY when the running JAX lacks the name
(pure ``hasattr`` guards), so on a current JAX this module is a no-op.
Imported from the package ``__init__`` so every entry point (tests,
benches, serving) sees a uniform surface.
"""

from __future__ import annotations

import jax


def _install() -> None:
    if not hasattr(jax.lax, "axis_size"):
        def axis_size(axis_name):
            # Pre-0.6 the static size lives on the axis frame (newer
            # 0.4.x returns the bare int directly).
            frame = jax.core.axis_frame(axis_name)
            return frame if isinstance(frame, int) else frame.size

        jax.lax.axis_size = axis_size

    from jax.experimental.pallas import tpu as pltpu

    if not hasattr(pltpu, "CompilerParams"):
        import dataclasses as _dc

        _known = {f.name for f in _dc.fields(pltpu.TPUCompilerParams)}

        def _compiler_params(**kw):
            # Fields added after this JAX release (e.g.
            # ``has_side_effects``) are advisory on the interpret path
            # the old release runs here — drop them rather than fail.
            return pltpu.TPUCompilerParams(
                **{k: v for k, v in kw.items() if k in _known}
            )

        pltpu.CompilerParams = _compiler_params

        # Same-era quirk: this release rejects ``unroll`` (even the
        # default-equivalent ``unroll=False``) when fori_loop bounds are
        # traced; current JAX accepts it. Retry without the kwarg —
        # semantics identical (False IS the no-unroll default).
        _orig_fori = jax.lax.fori_loop

        def _fori_loop(lower, upper, body_fun, init_val, **kw):
            try:
                return _orig_fori(lower, upper, body_fun, init_val, **kw)
            except ValueError as e:
                if kw.get("unroll") is False and "unroll" in str(e):
                    kw = dict(kw)
                    kw.pop("unroll")
                    return _orig_fori(lower, upper, body_fun, init_val, **kw)
                raise

        jax.lax.fori_loop = _fori_loop

    if not hasattr(pltpu, "InterpretParams"):
        # Older Pallas takes ``interpret=True`` (plain bool) instead of a
        # params object; the call sites only ever pass the result through
        # to ``pallas_call(interpret=...)``, so truthy-bool is faithful.
        def _interpret_params(**_kw):
            return True

        pltpu.InterpretParams = _interpret_params

    _install_dma_discharge_shim()


def _install_dma_discharge_shim() -> None:
    """0.4.x interpret-mode fix: remote-DMA discharge with a mesh-dict
    ``device_id``.

    Every remote copy in this package names its target as
    ``device_id={axis: dst}`` with ``DeviceIdType.MESH`` — the form
    Mosaic lowers on real TPU. The 0.4.x interpret path discharges
    ``dma_start`` by all-gathering the target ids and comparing against
    the local axis index (``dma_start_discharge_rule``), but it feeds
    the DICT straight into ``all_gather(...) == my_axis`` and dies with
    ``tracer == dict`` — so every kernel with an in-kernel collective
    (the megakernel allreduce, put_signal rings) fails under the CPU
    simulator mesh. For a single-axis mesh the dict carries exactly one
    scalar; unwrapping it to that scalar before the stock rule runs is
    semantically identical (the rule's own ``jax.Array`` branch) and
    leaf-count-preserving, so the returned new-values line up with the
    eqn invars unchanged. Newer JAX (which replaced this rule) keeps
    its own behavior — the wrap only installs when the stock rule both
    exists and exhibits the bug (probed structurally by version)."""
    if not jax.__version__.startswith("0.4."):
        return
    try:
        from jax import tree_util as _tu
        from jax._src.pallas.mosaic import primitives as _pmp
        from jax._src.state import discharge as _sd
    except ImportError:  # pragma: no cover - layout differs → leave be
        return
    orig = _sd._discharge_rules.get(_pmp.dma_start_p)
    if orig is None or getattr(orig, "_tdt_dict_device_id_shim", False):
        return

    def rule(in_avals, out_avals, *args, tree, device_id_type):
        vals = list(_tu.tree_unflatten(tree, args))
        dev = vals[-1]
        if isinstance(dev, dict) and len(dev) == 1:
            vals[-1] = next(iter(dev.values()))
            new_args, new_tree = _tu.tree_flatten(tuple(vals))
            avals = list(_tu.tree_unflatten(tree, in_avals))
            avals[-1] = next(iter(avals[-1].values()))
            return orig(
                _tu.tree_leaves(tuple(avals)), out_avals, *new_args,
                tree=new_tree, device_id_type=device_id_type,
            )
        return orig(
            in_avals, out_avals, *args, tree=tree,
            device_id_type=device_id_type,
        )

    rule._tdt_dict_device_id_shim = True
    _sd._discharge_rules[_pmp.dma_start_p] = rule


_install()
