"""Device mesh, topology, and distributed initialization.

Reference parity: ``python/triton_dist/utils.py:182-205``
(``initialize_distributed``: torchrun env → process group → NVSHMEM uid init)
and the NVLink/PCIe/NUMA topology probes (``utils.py:592-867``).

TPU-native design: there is no NVSHMEM symmetric heap to map — the data plane
is the ICI mesh that XLA already knows about. "Initialization" therefore means:

1. (multi-host only) ``jax.distributed.initialize`` — the control-plane
   rendezvous, analog of ``torch.distributed.init_process_group``.
2. Building a named ``jax.sharding.Mesh`` over the device grid with the
   parallelism axes the caller asks for (dp/pp/tp/sp/ep), in an order that
   keeps the fastest-varying (most-communicating) axes on contiguous ICI
   neighbors.
3. Recording topology facts kernels need (axis sizes, ring neighbors,
   whether we are on real TPU or the CPU simulator) — the analog of the
   reference's NVLink fullmesh/NUMA probes, except on TPU the answer comes
   from the platform, not from sysfs crawling.

Symmetric memory: the reference allocates NVSHMEM symmetric tensors
(``utils.py:114-136``). In JAX the same thing is an identically-shaped
per-device shard inside ``shard_map`` — every device holds the same local
shape at the same logical name, and Pallas remote DMAs address peers by mesh
index. No allocator is needed; ``DistContext.shard_map`` is the entry point.
"""

from __future__ import annotations

import dataclasses
import functools
import os
from typing import Any, Callable, Mapping, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.6 exposes shard_map at top level (check_vma kwarg)
    shard_map = jax.shard_map
except AttributeError:  # pragma: no cover - older jax uses check_rep
    from jax.experimental.shard_map import shard_map as _legacy_shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=False):
        return _legacy_shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=check_vma,
        )

# Canonical axis names, outermost (least communication) to innermost
# (most communication → contiguous ICI). Mirrors the scaling-book recipe:
# data axes outside, model axes inside.
# dcn (cross-slice) outermost; tp innermost (contiguous ICI neighbors).
AXIS_ORDER = ("dcn", "dp", "pp", "ep", "sp", "tp")


@dataclasses.dataclass(frozen=True)
class MeshTopology:
    """Static facts about the device grid a kernel may want.

    Analog of the reference's topology probe results (nvlink fullmesh,
    NUMA grouping — ``utils.py:592-867``): on TPU the useful facts are the
    ICI axis structure and whether multiple slices (DCN hops) are involved.
    """

    num_devices: int
    num_processes: int
    process_index: int
    platform: str  # "tpu" | "cpu" | ...
    devices_per_process: int
    torus_shape: tuple[int, ...] | None = None  # physical ICI grid dims
    has_wraparound: bool | None = None  # any torus dim with wrap links

    @property
    def on_tpu(self) -> bool:
        return self.platform == "tpu"

    @property
    def multi_slice(self) -> bool:
        """True when the mesh spans a DCN boundary (multi-process TPU)."""
        return self.num_processes > 1


class DistContext:
    """Global distributed context: mesh + axis layout + topology.

    The analog of the reference's ``initialize_distributed()`` return state
    (process groups + NVSHMEM heap). Everything downstream (collectives,
    overlap kernels, model layers) takes a ``DistContext`` the way the
    reference ops take their per-op ``*Context`` dataclasses.
    """

    def __init__(self, mesh: Mesh, topology: MeshTopology):
        self.mesh = mesh
        self.topology = topology

    # -- identity ---------------------------------------------------------
    @property
    def axis_names(self) -> tuple[str, ...]:
        return tuple(self.mesh.axis_names)

    def axis_size(self, axis: str) -> int:
        return self.mesh.shape[axis]

    @property
    def world_size(self) -> int:
        return int(np.prod(list(self.mesh.shape.values())))

    @property
    def on_tpu(self) -> bool:
        return self.topology.on_tpu

    def axis_is_ici(self, axis: str) -> bool:
        """True when neighbors along ``axis`` share a SLICE — i.e. the
        axis is reachable by device-initiated remote DMA (ICI). A
        DCN-spanning axis must use XLA collectives: DCN transfers are
        host-driven (SURVEY.md §7 "inter-slice paths can't be
        device-initiated"). AUTO method dispatchers consult this so a
        device-push kernel is never selected across a slice boundary.

        Slice identity comes from ``device.slice_index`` — ICI spans
        HOSTS inside one slice (a v4-32 has 4 processes and one
        all-ICI slice), so process boundaries must NOT be the signal.
        Devices without a ``slice_index`` attribute (CPU sim, older
        stacks) are treated as one slice."""
        devs = np.asarray(self.mesh.devices)
        ids = np.vectorize(
            lambda d: getattr(d, "slice_index", None) or 0
        )(devs)
        if (ids == ids.flat[0]).all():
            return True  # one slice: every axis is ICI
        return self._axis_within_group(ids, self.axis_names.index(axis))

    @staticmethod
    def _axis_within_group(ids: "np.ndarray", ax_i: int) -> bool:
        """Pure check: every move along mesh dim ``ax_i`` stays inside
        one slice-id group (split out so the DCN/ICI classification is
        unit-testable without a real multi-slice mesh)."""
        moved = np.moveaxis(ids, ax_i, 0)
        return bool((moved == moved[0]).all())

    # -- pallas helpers ---------------------------------------------------
    def pallas_interpret(self):
        """Interpret-mode params for Pallas on non-TPU backends.

        On the CPU simulator mesh, Pallas TPU kernels (including remote
        DMAs and semaphores) run under ``pltpu.InterpretParams`` with full
        TPU memory semantics; on real TPU this returns False so kernels
        compile through Mosaic.
        """
        if self.on_tpu:
            return False
        from jax.experimental.pallas import tpu as pltpu

        return pltpu.InterpretParams()

    # -- shard_map entry point -------------------------------------------
    def shard_map(
        self,
        f: Callable,
        in_specs: Any,
        out_specs: Any,
        check_vma: bool = False,
    ) -> Callable:
        """Wrap ``f`` in a ``shard_map`` over this mesh.

        This is the "symmetric memory" entry point: inside ``f`` every
        device sees its local shard and may address peers via Pallas remote
        DMA or ``jax.lax`` collectives by axis name.
        """
        return shard_map(
            f,
            mesh=self.mesh,
            in_specs=in_specs,
            out_specs=out_specs,
            check_vma=check_vma,
        )

    # -- teams / sub-groups ----------------------------------------------
    def split_axis(
        self,
        axis: str,
        names: tuple[str, str],
        sizes: tuple[int, int],
        *,
        set_as_current: bool = False,
    ) -> "DistContext":
        """Split a mesh axis into two (parity: NVSHMEM team split —
        ``nvshmem_team_split_strided`` / ``team_my_pe``,
        ``libnvshmem_device.py:130,1343``, ``test_team_split.py``).

        A rank's ids along the new axes are ``(old // sizes[1],
        old % sizes[1])`` — the strided/round-robin split of the
        reference's 2D protocols (NUMA-aware ring, 2D allgather).
        Collectives and remote DMAs then target either sub-axis by name.
        """
        if sizes[0] * sizes[1] != self.axis_size(axis):
            raise ValueError(
                f"split {sizes} does not cover axis {axis!r} of size "
                f"{self.axis_size(axis)}"
            )
        idx = self.mesh.axis_names.index(axis)
        new_names = (
            self.mesh.axis_names[:idx] + names
            + self.mesh.axis_names[idx + 1:]
        )
        shape = self.mesh.devices.shape
        new_shape = shape[:idx] + sizes + shape[idx + 1:]
        ctx = DistContext(
            Mesh(self.mesh.devices.reshape(new_shape), new_names),
            self.topology,
        )
        if set_as_current:
            set_context(ctx)
        return ctx

    def sharding(self, *spec) -> NamedSharding:
        return NamedSharding(self.mesh, P(*spec))

    def replicate(self, x):
        return jax.device_put(x, self.sharding())

    def shard(self, x, *spec):
        return jax.device_put(x, self.sharding(*spec))


_CURRENT: DistContext | None = None


def set_context(ctx: DistContext | None) -> None:
    global _CURRENT
    _CURRENT = ctx


def current_context() -> DistContext:
    if _CURRENT is None:
        raise RuntimeError(
            "Distributed context not initialized; call "
            "triton_distributed_tpu.initialize_distributed() first."
        )
    return _CURRENT


def snake_ring_order(coords: np.ndarray) -> np.ndarray:
    """Permutation of device indices whose consecutive entries are physical
    ICI neighbors (boustrophedon walk of the torus).

    Parity role: the reference's topology probes (``utils.py:592-867``)
    answer "which ranks are one NVLink hop apart"; on TPU the analog is
    "which chips are one ICI hop apart", answered from device coords.
    Works for any full n-D grid; the closing hop (last → first) is also
    distance 1 whenever every inner dim is even (the usual torus case).
    """
    coords = np.asarray(coords)
    lo = coords.min(axis=0)
    sizes = coords.max(axis=0) - lo + 1
    norm = coords - lo

    def snake_key(c) -> int:
        key = 0
        for v, s in zip(c, sizes):
            vv = int(s) - 1 - int(v) if key % 2 else int(v)
            key = key * int(s) + vv
        return key

    return np.argsort([snake_key(c) for c in norm], kind="stable")


def _tpu_device_grid(
    devices: list[jax.Device], shape: tuple[int, ...]
) -> np.ndarray:
    """Arrange TPU devices so the innermost mesh axis rides contiguous ICI.

    ``jax.experimental.mesh_utils.create_device_mesh`` does the real
    assignment from physical coords; it requires the full device set of
    the slice. For subsets (or when it declines), fall back to the snake
    ring over coords so consecutive innermost-axis entries are still
    one-hop neighbors; last resort is enumeration order.
    """
    if len(devices) == len(jax.devices()):
        try:
            from jax.experimental import mesh_utils

            return mesh_utils.create_device_mesh(shape, devices=devices)
        except Exception:
            pass
    try:
        coords = np.asarray([d.coords for d in devices])
        order = snake_ring_order(coords)
        return np.asarray(devices)[order].reshape(shape)
    except Exception:
        return np.asarray(devices).reshape(shape)


def _detect_topology(devices: Sequence[jax.Device]) -> MeshTopology:
    platform = devices[0].platform
    num_processes = jax.process_count()
    torus_shape = None
    has_wrap = None
    if platform == "tpu":
        try:
            coords = np.asarray([d.coords for d in devices])
            dims = tuple(int(x) for x in coords.max(0) - coords.min(0) + 1)
            torus_shape = dims
            kind = devices[0].device_kind.lower()
            if "v4" in kind or "v5p" in kind:
                # 3D-torus generations: wraparound links on dims >= 4.
                has_wrap = any(d >= 4 for d in dims)
            elif "lite" in kind or "v5e" in kind or "v6e" in kind:
                has_wrap = False  # 2D-mesh generations: no wrap links
            # else: unknown generation — leave None
        except Exception:
            pass
    return MeshTopology(
        num_devices=len(devices),
        num_processes=num_processes,
        process_index=jax.process_index(),
        platform=platform,
        devices_per_process=max(1, len(devices) // num_processes),
        torus_shape=torus_shape,
        has_wraparound=has_wrap,
    )


def initialize_distributed(
    axes: Mapping[str, int] | None = None,
    *,
    tp: int | None = None,
    dp: int | None = None,
    pp: int | None = None,
    sp: int | None = None,
    ep: int | None = None,
    devices: Sequence[jax.Device] | None = None,
    multihost: bool | None = None,
    set_as_current: bool = True,
) -> DistContext:
    """Create the global mesh + context.

    Analog of reference ``initialize_distributed`` (``utils.py:182``):
    where the reference wires torchrun env vars → NCCL/gloo groups → NVSHMEM
    heap, we wire (optionally) ``jax.distributed.initialize`` → a named
    ``Mesh`` whose axes map onto ICI.

    Axis sizes may be given either as an ``axes`` mapping or via the
    keyword shorthands; unspecified parallelism consumes no axis. If the
    product is smaller than the device count, a ``dp`` axis absorbs the
    remainder (data parallelism is free on TPU — it is just a sharded
    leading axis).
    """
    if multihost is None:
        multihost = bool(int(os.environ.get("TDT_MULTIHOST", "0")))
    if multihost:
        # Control-plane rendezvous across hosts (DCN). Must run before any
        # JAX call that initializes an XLA backend, so we don't probe
        # jax.process_count() first; re-initialization raises and is ignored.
        try:
            jax.distributed.initialize()
        except RuntimeError:
            pass  # already initialized (or single-process run)

    if devices is None:
        devices = jax.devices()
    devices = list(devices)

    sizes: dict[str, int] = dict(axes or {})
    for name, val in (("tp", tp), ("dp", dp), ("pp", pp), ("sp", sp), ("ep", ep)):
        if val is not None:
            sizes[name] = val

    used = int(np.prod(list(sizes.values()))) if sizes else 1
    n = len(devices)
    if n % used != 0:
        raise ValueError(
            f"device count {n} not divisible by requested axes {sizes}"
        )
    if used < n and "dp" not in sizes:
        sizes = {"dp": n // used, **sizes}
    elif used < n:
        sizes["dp"] = sizes["dp"] * (n // used)

    # Order axes canonically: dp/pp outermost, tp innermost (contiguous ICI).
    ordered = [a for a in AXIS_ORDER if a in sizes]
    ordered += [a for a in sizes if a not in ordered]
    shape = tuple(sizes[a] for a in ordered)
    if not ordered:
        ordered, shape = ["dp"], (n,)

    if devices[0].platform == "tpu":
        dev_array = _tpu_device_grid(devices, shape)
    else:
        dev_array = np.asarray(devices).reshape(shape)
    mesh = Mesh(dev_array, tuple(ordered))
    ctx = DistContext(mesh, _detect_topology(devices))
    if set_as_current:
        set_context(ctx)
    return ctx


def finalize_distributed() -> None:
    """Tear down the global context (and multihost runtime if we own it)."""
    set_context(None)


@functools.lru_cache(maxsize=None)
def cpu_sim_devices(n: int) -> tuple[jax.Device, ...]:
    """Return ``n`` CPU devices for simulator meshes (tests, dry runs)."""
    cpus = jax.devices("cpu")
    if len(cpus) < n:
        raise RuntimeError(
            f"need {n} CPU devices; launch with "
            f"XLA_FLAGS=--xla_force_host_platform_device_count={n}"
        )
    return tuple(cpus[:n])
