"""Parallelism transports and schedules beyond the collective ops.

Parity: reference ``layers/nvidia/p2p.py`` (``CommOp`` pipeline
transport) — plus, TPU-natively, everything expressed over the mesh axes
(dp is a sharded leading axis; tp/sp/ep live in ops/ and layers/).
"""

from triton_distributed_tpu.parallel.p2p import (  # noqa: F401
    pp_recv_from_prev,
    pp_send_recv,
    pp_shift,
)
