"""Point-to-point transport for pipeline parallelism.

Parity: reference ``kernels/nvidia/p2p.py`` (85 LoC) +
``layers/nvidia/p2p.py:43`` ``CommOp`` — N symmetric buffers with signal
set/wait/read used by ``test/nvidia/test_pp.py`` send (:77) / recv (:96)
to move activations between pipeline stages.

TPU design: a pipeline hop is a neighbor shift along the ``pp`` mesh
axis. Two methods:

- ``xla``: ``jax.lax.ppermute`` — XLA schedules the collective-permute
  asynchronously (the copy-engine-stream analog) and overlaps it with
  unrelated compute automatically.
- ``pallas``: one kernel where every stage ``put_signal``s its payload to
  the next stage's landing buffer and waits its own arrival — the
  device-initiated ``putmem_signal`` path, fusable into larger kernels.

The reference's ``CommOp`` double-buffers N slots to pipeline multiple
in-flight micro-batches; in JAX that buffering falls out of SPMD
dataflow (each microbatch's shift is its own value), so no buffer pool
object is needed.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from triton_distributed_tpu import language as dl
from triton_distributed_tpu.ops.common import (
    comm_pallas_call,
    next_collective_id,
    device_initiable,
)

_P2P_COLLECTIVE_ID = next_collective_id()


def _shift_kernel(x_ref, o_ref, send_sem, recv_sem, *, axis: str, wrap: bool):
    """Every stage pushes to ``me+1`` (ring if ``wrap``); stage 0's
    landing buffer is zeroed when not wrapping (nothing arrives)."""
    me = dl.rank(axis)
    n = dl.num_ranks(axis)
    nxt = jax.lax.rem(me + 1, n)

    dl.barrier_all(axis)
    send = jnp.logical_or(wrap, me < n - 1)
    recv = jnp.logical_or(wrap, me > 0)

    @pl.when(send)
    def _send():
        dl.put_signal(x_ref, o_ref, nxt, send_sem, recv_sem, axis=axis)

    @pl.when(jnp.logical_not(recv))
    def _zero():
        o_ref[:] = jnp.zeros_like(o_ref)

    @pl.when(recv)
    def _recv():
        dl.wait_recv(recv_sem, o_ref)

    @pl.when(send)
    def _drain():
        pltpu.make_async_copy(x_ref, x_ref, send_sem).wait()


def pp_shift(
    x: jax.Array,
    axis: str = "pp",
    *,
    wrap: bool = False,
    method: str = "auto",
    ctx=None,
) -> jax.Array:
    """Shift ``x`` one stage forward along ``axis`` (inside ``shard_map``):
    stage i's output becomes stage i+1's input; stage 0 receives zeros
    (or stage n-1's payload when ``wrap``)."""
    n = jax.lax.axis_size(axis)
    if method == "auto":
        method = "pallas" if device_initiable(axis, ctx) and x.ndim >= 2 else "xla"
    if n == 1:
        return x if wrap else jnp.zeros_like(x)
    if method == "xla":
        if wrap:
            perm = [(i, (i + 1) % n) for i in range(n)]
        else:
            perm = [(i, i + 1) for i in range(n - 1)]
        return jax.lax.ppermute(x, axis, perm)
    return comm_pallas_call(
        functools.partial(_shift_kernel, axis=axis, wrap=wrap),
        jax.ShapeDtypeStruct(x.shape, x.dtype),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA(()),
        ],
        collective_id=_P2P_COLLECTIVE_ID,
        ctx=ctx,
    )(x)


def pp_send_recv(
    x: jax.Array,
    src: int,
    dst: int,
    axis: str = "pp",
) -> jax.Array:
    """Single directed hop: ``src``'s payload lands on ``dst``; everyone
    else receives zeros (parity: ``CommOp.send``/``recv`` pairs in
    ``test_pp.py:77-96``)."""
    out = jax.lax.ppermute(x, axis, [(src, dst)])
    return out


def pp_recv_from_prev(x: jax.Array, axis: str = "pp", **kw) -> jax.Array:
    """Alias with the receiving-stage viewpoint (reference ``CommOp.read``)."""
    return pp_shift(x, axis, **kw)
