"""``triton_distributed_tpu.language`` — device-side primitive facade.

Import as ``from triton_distributed_tpu import language as dl`` for parity
with the reference's ``import triton_dist.language as dl``
(``python/triton_dist/language/__init__.py:26-28``).
"""

from triton_distributed_tpu.language.primitives import (  # noqa: F401
    barrier_all,
    barrier_cross,
    barrier_neighbors,
    local_copy,
    maybe_delay,
    num_ranks,
    put_signal,
    quiet,
    rank,
    read,
    remote_copy,
    request,
    serve_get,
    signal,
    signal_set,
    straggle_if_rank,
    team_my_pe,
    team_n_pes,
    translate_rank,
    wait,
    wait_recv,
    wait_until,
)
