"""Device-side communication primitives for Pallas TPU kernels.

This is the TPU-native analog of the reference's device language
(``python/triton_dist/language/distributed_ops.py:56-111`` — ``wait``,
``consume_token``, ``rank``, ``num_ranks``, ``symm_at``, ``notify`` — and the
NVSHMEM device API ``backends/nvidia/language/cuda/libnvshmem_device.py``:
``putmem_signal``:589, ``signal_wait_until``:782, ``barrier_all``:240,
``quiet``:371/``fence``:385).

Mapping (see SURVEY.md §2.4):

| reference (NVSHMEM/Triton)       | here (Pallas/Mosaic over ICI)           |
|----------------------------------|-----------------------------------------|
| ``dl.rank()`` / ``num_ranks``    | ``rank(axis)`` / ``num_ranks(axis)``    |
| ``dl.notify(rank, sem, ADD)``    | ``signal(sem, inc, dst=...)``           |
| ``dl.notify(rank, sig, SET)``    | ``signal_set(value, ...)`` (value-      |
| + ``signal_wait_until(cmp, v)``  | carrying put) + ``wait_until(cmp, v)``  |
| ``dl.wait(sem, n)`` + token      | ``wait(sem, n)`` (ordering is by       |
|                                  | semaphore dataflow, no token needed —   |
|                                  | Mosaic orders the dependent DMA/loads)  |
| ``symm_at(buf, rank)`` + put     | ``remote_copy(src, dst, dst_dev, ...)`` |
| ``putmem_signal[_nbi]``          | ``put_signal(...)`` (recv semaphore IS  |
|                                  | the arrival signal)                     |
| ``getmem_*`` (pull)              | ``request(...)`` + ``serve_get(...)``   |
|                                  | (receiver-initiated rendezvous — see    |
|                                  | the pull section below)                 |
| ``barrier_all``                  | ``barrier_all(axis)``                   |
| ``quiet``/``fence``              | ``quiet(*dmas)`` (drain started sends)  |

Semantics notes:
- NVSHMEM's ``consume_token`` exists because Triton must thread a dataflow
  edge between a spin-wait and the subsequent load so the compiler cannot
  reorder them. In Pallas the same guarantee comes from semaphores:
  ``semaphore_wait`` has side-effect ordering against subsequent memory
  ops in program order, so no token plumbing is exposed.
- All primitives must run inside a ``pl.pallas_call`` that executes under
  ``shard_map`` so ``jax.lax.axis_index`` resolves, and remote DMAs need
  ``compiler_params=pltpu.CompilerParams(collective_id=...)``.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


# -- identity ---------------------------------------------------------------

def rank(axis: str | Sequence[str]) -> jax.Array:
    """This device's index along ``axis`` (parity: ``dl.rank``; with an
    axis tuple this is the row-major team rank —
    ``nvshmem_team_my_pe`` for teams-as-axis-tuples)."""
    return jax.lax.axis_index(axis)


def num_ranks(axis: str | Sequence[str]) -> int:
    """Axis size (parity: ``dl.num_ranks``)."""
    if isinstance(axis, str):
        return jax.lax.axis_size(axis)
    out = 1
    for a in axis:
        out *= jax.lax.axis_size(a)
    return out


# Teams are mesh axes (or axis tuples); the NVSHMEM team API maps to
# the same three calls the reference exposes on devices
# (``libnvshmem_device.py:130,1199-1343``):
team_my_pe = rank
team_n_pes = num_ranks


def translate_rank(
    r: int | jax.Array,
    from_axis: str | Sequence[str],
    to_axis: str | Sequence[str],
) -> jax.Array:
    """Translate rank ``r`` in team ``from_axis`` to its index in team
    ``to_axis`` — device-side team translation (parity:
    ``nvshmem_team_translate_pe``, ``libnvshmem_device.py:1343``; the
    host-side analog is ``DistContext.split_axis``).

    Teams are mesh axes (or axis tuples): "PE ``r`` of team
    ``from_axis``" is the device sharing the caller's coordinates on
    every other axis, with its ``from_axis`` coordinate(s) replaced by
    ``r`` (row-major when ``from_axis`` is a tuple). Returns that
    device's row-major index within ``to_axis``. Axes of ``to_axis``
    not covered by ``from_axis`` keep the caller's coordinate — e.g.
    ``translate_rank(r, "tp", ("dp", "tp"))`` is the world rank of
    this device's tp-peer ``r``.
    """
    axes_from = (from_axis,) if isinstance(from_axis, str) else tuple(from_axis)
    axes_to = (to_axis,) if isinstance(to_axis, str) else tuple(to_axis)
    # Decompose r into the target device's coords along `axes_from`.
    coords = {}
    rem = jnp.asarray(r)
    for a in reversed(axes_from):
        s = jax.lax.axis_size(a)
        coords[a] = jax.lax.rem(rem, s)
        rem = rem // s
    # Row-major linearization along `axes_to`.
    idx = jnp.zeros((), rem.dtype)
    for a in axes_to:
        c = coords[a] if a in coords else jax.lax.axis_index(a)
        idx = idx * jax.lax.axis_size(a) + c
    return idx


# -- signal / wait ----------------------------------------------------------

def signal(
    sem,
    inc: int | jax.Array = 1,
    dst: jax.Array | int | None = None,
    axis: str | None = None,
):
    """Increment a semaphore, locally or on a remote device.

    Parity: ``dl.notify(..., sig_op=ADD)`` / ``nvshmemx_signal_op``.
    NVSHMEM's SET mode has no Mosaic analog (semaphores are counters);
    all our protocols are formulated with ADD, which the reference's
    kernels also support.

    ``dst``: peer index *along* ``axis`` (other mesh axes stay fixed, so
    e.g. a tp-ring signal never crosses dp replicas); None = local.
    """
    if dst is None:
        pltpu.semaphore_signal(sem, inc=inc)
    else:
        if axis is None:
            raise ValueError("signal(dst=...) requires the mesh axis name")
        pltpu.semaphore_signal(
            sem, inc=inc, device_id={axis: dst},
            device_id_type=pltpu.DeviceIdType.MESH,
        )


def wait(sem, value: int | jax.Array = 1):
    """Block until ``sem >= value``, then decrement by ``value``.

    Parity: ``dl.wait(barrier, n)`` + ``dl.consume_token`` — ordering of
    subsequent loads is guaranteed by Mosaic's semaphore semantics, so no
    token is returned.
    """
    pltpu.semaphore_wait(sem, value)


def read(sem) -> jax.Array:
    """Non-blocking semaphore read (parity: spin-poll fast paths)."""
    return pltpu.semaphore_read(sem)


def signal_set(
    value: jax.Array,
    stage_ref,
    flag_ref,
    dst: jax.Array | int,
    send_sem,
    recv_sem,
    axis: str,
):
    """Publish a VALUE to a peer's flag — SET-mode signaling (parity:
    ``nvshmemx_signal_op(..., NVSHMEM_SIGNAL_SET, pe)``,
    ``libnvshmem_device.py:756``).

    Mosaic semaphores are pure counters, so a value-carrying signal is a
    tiny put: ``value`` is staged into the local ``stage_ref`` and
    DMA'd into the peer's symmetric ``flag_ref``; the DMA's recv
    semaphore is the arrival notification (data lands before the
    signal, same ordering NVSHMEM guarantees). Both refs are ``(1, 1)``
    int32 buffers. Single writer per flag, as with NVSHMEM SET — two
    racing setters leave the last writer's value.

    Returns the started DMA (``.wait_send()`` to reuse ``stage_ref``).
    """
    stage_ref[0, 0] = value
    return put_signal(
        stage_ref, flag_ref, dst, send_sem, recv_sem, axis=axis
    )


def wait_until(flag_ref, recv_sem, value: jax.Array | int, cmp: str = "ge"):
    """Block until this rank's flag, published via :func:`signal_set`,
    satisfies ``flag <cmp> value``; returns the flag's final value.

    Parity: ``nvshmem_signal_wait_until(sig, NVSHMEM_CMP_{GE,EQ,GT,NE},
    value)`` (``libnvshmem_device.py:782``). NVSHMEM spin-reads the
    flag; here each check is gated on one DMA arrival (a spin would
    burn the issue stream), CONSUME-FIRST: the wait always drains at
    least one set, then keeps draining until the comparison holds.
    Checking the flag before the first arrival instead would race — a
    set landing just before the check would satisfy it without being
    consumed, leaking its arrival count nondeterministically.

    Consequences for protocol design (the epoch-publication pattern,
    e.g. the LL a2a's per-call-count phase flags,
    ``low_latency_all_to_all.py:36-125``):
    - each ``wait_until`` phase must pair with a set whose value makes
      the condition true — an already-satisfying stale flag does NOT
      exit the wait;
    - leak-free exactly when the satisfying set is the phase's last
      (single-set phases trivially; monotone multi-set runs when
      same-path DMA completion is in order);
    - do NOT reuse one flag+semaphore pair across phases: same-path
      puts may land out of order (observed in the interpreter), so a
      later phase's set can satisfy an earlier wait, strand the earlier
      value, and deadlock the later wait. Give each phase its own flag
      slot — the reference double-buffers its LL flags by call count
      for the same reason (``low_latency_all_to_all.py:95-125``).
    """
    cmps = {
        "ge": lambda v: v >= value,
        "gt": lambda v: v > value,
        "eq": lambda v: v == value,
        "ne": lambda v: v != value,
    }
    try:
        ok = cmps[cmp]
    except KeyError:
        raise ValueError(f"cmp must be one of {sorted(cmps)}, got {cmp!r}")

    def cond(satisfied):
        return jnp.logical_not(satisfied)

    def body(_):
        wait_recv(recv_sem, flag_ref)  # one more set has landed
        return ok(flag_ref[0, 0])

    jax.lax.while_loop(cond, body, jnp.bool_(False))
    return flag_ref[0, 0]


# -- remote DMA -------------------------------------------------------------

def remote_copy(src_ref, dst_ref, dst_dev, send_sem, recv_sem, axis: str = "tp"):
    """Async put: copy ``src_ref`` (local) into ``dst_ref`` on peer
    ``dst_dev`` along mesh ``axis`` (other axes stay fixed).

    Returns the DMA descriptor; call ``.start()`` / ``.wait()`` /
    ``.wait_send()`` / ``.wait_recv()`` on it. Parity:
    ``libnvshmem_device.putmem_nbi_block`` (nonblocking put).
    """
    return pltpu.make_async_remote_copy(
        src_ref=src_ref,
        dst_ref=dst_ref,
        send_sem=send_sem,
        recv_sem=recv_sem,
        device_id={axis: dst_dev},
        device_id_type=pltpu.DeviceIdType.MESH,
    )


def put_signal(src_ref, dst_ref, dst_dev, send_sem, recv_sem, axis: str = "tp"):
    """Start an async put whose arrival the receiver observes on recv_sem.

    Parity: ``putmem_signal_nbi`` (``libnvshmem_device.py:589-754``) — the
    remote rank does ``wait(recv_sem)`` to learn the data has landed. On
    TPU the recv semaphore is signaled by the DMA engine on completion of
    the remote write, which gives exactly the put-with-signal contract
    (data visibility before signal) without a separate flag write.

    Returns the started DMA (caller may ``.wait_send()`` to drain).
    """
    dma = remote_copy(src_ref, dst_ref, dst_dev, send_sem, recv_sem, axis=axis)
    dma.start()
    return dma


def request(req_sem, src_dev, axis: str, inc: int | jax.Array = 1):
    """Pull-mode request: ask peer ``src_dev`` to serve data to this rank.

    Parity: the initiator side of ``nvshmem_getmem_signal``
    (``libnvshmem_device.py:399-492``). The ICI DMA engine is push-only
    (no remote-read descriptor), so a TPU "get" is a receiver-initiated
    rendezvous: the receiver signals the source's request semaphore and
    the source — running the same SPMD kernel — answers with a
    ``put_signal`` (:func:`serve_get`). The flow-control property that
    makes NVSHMEM pull producers worth having survives the translation:
    no byte moves until the RECEIVER asks, so a receiver can pace its
    requests (window them) and incast onto a hot link never builds up.

    A second property comes free: a pull protocol needs NO entry
    barrier. A push kernel must barrier first so peers' buffers exist
    before blind writes (see ``_ring_kernel``); a served pull is gated
    on the receiver's own request, which it can only issue after
    entering the kernel — the request IS the proof of liveness.
    """
    signal(req_sem, inc, dst=src_dev, axis=axis)


def serve_get(
    req_sem,
    src_ref,
    dst_ref,
    dst_dev,
    send_sem,
    recv_sem,
    axis: str,
    requests: int | jax.Array = 1,
):
    """Responder side of a pull: block until ``requests`` arrivals on the
    local ``req_sem``, then push ``src_ref`` into ``dst_ref`` on the
    requester (parity: the remote agent that a ``getmem`` RDMA read
    engages in hardware). Returns the started DMA."""
    wait(req_sem, requests)
    return put_signal(src_ref, dst_ref, dst_dev, send_sem, recv_sem, axis=axis)


def local_copy(src_ref, dst_ref, sem):
    """Async local (same-chip) DMA, e.g. HBM→VMEM staging."""
    return pltpu.make_async_copy(src_ref, dst_ref, sem)


def wait_recv(recv_sem, landed_ref):
    """Receiver side of ``put_signal``: block until the put into
    ``landed_ref`` has fully arrived (parity: ``signal_wait_until`` on the
    consumer, ``libnvshmem_device.py:782``).

    DMA semaphores count bytes; waiting is expressed by a descriptor of the
    landed buffer so Mosaic knows how many to expect.
    """
    pltpu.make_async_copy(landed_ref, landed_ref, recv_sem).wait()


def quiet(*dmas):
    """Drain outstanding sends (parity: ``nvshmem_quiet``).

    DMA send semaphores count bytes, not operations, so the fence is
    expressed through the descriptors: pass the started DMAs and each is
    ``wait_send``-ed, after which its source buffer is reusable.
    """
    for dma in dmas:
        dma.wait_send()


# -- barriers ---------------------------------------------------------------

def barrier_all(axis: str):
    """Full barrier across the mesh axis inside a kernel.

    Parity: ``nvshmem_barrier_all`` / ``barrier_all_intra_node``
    (``common_ops.py:142-210``). Signals every peer's barrier semaphore and
    waits for all peers' signals. Requires
    ``compiler_params=pltpu.CompilerParams(collective_id=...)``.
    """
    n = jax.lax.axis_size(axis)
    me = jax.lax.axis_index(axis)
    bsem = pltpu.get_barrier_semaphore()

    def body(i, _):
        peer = jax.lax.rem(me + i, n)
        pltpu.semaphore_signal(
            bsem, inc=1, device_id={axis: peer},
            device_id_type=pltpu.DeviceIdType.MESH,
        )
        return _

    jax.lax.fori_loop(1, n, body, None)
    pltpu.semaphore_wait(bsem, n - 1)


def barrier_cross(*axes: str):
    """Barrier with the UNION of per-axis peers (this device's row and
    column on a 2D torus), as ONE signal/wait round.

    Needed instead of sequential ``barrier_all(ax); barrier_all(ay)``:
    both would share the kernel's single barrier semaphore
    (``get_barrier_semaphore`` is per-kernel), so a fast peer's
    second-barrier signal could satisfy a neighbor's still-pending
    first-barrier wait and let it pass before all first-axis peers have
    entered — anonymous increments cannot be attributed to a phase. One
    combined round has no second phase to alias: after the wait, every
    device this rank exchanges data with (its row + column) has
    provably entered the kernel.
    """
    bsem = pltpu.get_barrier_semaphore()
    expected = 0
    for axis in axes:
        n = jax.lax.axis_size(axis)
        me = jax.lax.axis_index(axis)
        for i in range(1, n):
            peer = jax.lax.rem(me + i, n)
            pltpu.semaphore_signal(
                bsem, inc=1, device_id={axis: peer},
                device_id_type=pltpu.DeviceIdType.MESH,
            )
        expected += n - 1
    pltpu.semaphore_wait(bsem, expected)


def barrier_neighbors(axis: str):
    """Barrier with ring neighbors only (cheaper; parity: ring protocols)."""
    n = jax.lax.axis_size(axis)
    me = jax.lax.axis_index(axis)
    left = jax.lax.rem(me - 1 + n, n)
    right = jax.lax.rem(me + 1, n)
    bsem = pltpu.get_barrier_semaphore()
    for peer in (left, right):
        pltpu.semaphore_signal(
            bsem, inc=1, device_id={axis: peer},
            device_id_type=pltpu.DeviceIdType.MESH,
        )
    pltpu.semaphore_wait(bsem, 2)


# -- straggler / correctness hooks -----------------------------------------

def maybe_delay(nanos: int | None):
    """On-device delay for race-provocation tests.

    Parity: the reference's ``for_correctness`` producer sleeps
    (``allgather_gemm.py:507-508``) and straggler injection
    (``allreduce.py:137``). ``pl.delay`` stalls this core's issue stream.
    """
    if nanos:
        pl.delay(nanos)


def straggle_if_rank(straggler_rank: int | None, axis: str, nanos: int):
    """Delay only on one rank — the straggler fixture (parity:
    ``straggler_option`` / ``_run_straggler``, ``allreduce.py:137``).
    Static ``straggler_rank`` (None = no-op) so production traces carry
    zero overhead."""
    if straggler_rank is None or not nanos:
        return

    @pl.when(rank(axis) == straggler_rank)
    def _lag():
        pl.delay(nanos)
