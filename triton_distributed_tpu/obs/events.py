"""Bounded structured-event ring with sequence numbers.

The serving loop's interesting moments — admissions, evictions, COW
clones, load shedding, deadline expiries, NaN guards, speculative
accept/rollback, injected faults, host trace spans — are low-rate but
high-value when diagnosing a stall after the fact. This ring keeps the
last ``capacity`` of them in memory with a monotonically increasing
``seq`` per event, so a consumer tailing the ring (e.g. the server's
``{"cmd": "events"}`` verb) can detect drops exactly: request
``since=<last seq seen>`` and the reply carries how many events were
overwritten in between — tailing is drop-AWARE even though the ring
itself is bounded.

Writers never block readers for long: emit is one lock-guarded slot
write; there is no per-event allocation beyond the event itself.
``default_ring()`` is the process-global ring the serving stack emits
into; ``enabled = False`` (or ``TDT_OBS=0``) turns ``emit`` into an
attribute check + return.
"""

from __future__ import annotations

import os
import threading
import time


class Event:
    """One structured event: ``seq`` (1-based, gap-free across the
    ring's lifetime), monotonic timestamp ``t``, a ``kind`` tag, and
    free-form ``fields``. Numeric field values stay numeric — the
    profiler may stringify its metadata, the ring never does."""

    __slots__ = ("seq", "t", "kind", "fields")

    def __init__(self, seq: int, t: float, kind: str, fields: dict):
        self.seq = seq
        self.t = t
        self.kind = kind
        self.fields = fields

    def as_dict(self) -> dict:
        return {"seq": self.seq, "t": self.t, "kind": self.kind,
                "fields": self.fields}

    def __repr__(self):  # pragma: no cover - debugging aid
        return f"Event(seq={self.seq}, kind={self.kind!r}, {self.fields})"


def safe_fields(raw: dict, reserved: tuple = ()) -> dict:
    """Make arbitrary caller-supplied fields safe to ``emit``: keys
    colliding with ``emit``'s positional ``kind`` or with the caller's
    ``reserved`` event keys survive under a ``ctx_`` prefix (never a
    TypeError out of an instrumentation site), and non-primitive
    values are stringified so a ring consumer (``{"cmd": "events"}``)
    can always JSON-serialize them. The ONE implementation of the
    collision-escape rule — spans and fault events both use it."""
    out = {}
    for k, v in raw.items():
        if v is not None and not isinstance(v, (bool, int, float, str)):
            v = str(v)
        out["ctx_" + k if (k == "kind" or k in reserved) else k] = v
    return out


class EventRing:
    """Fixed-capacity ring of :class:`Event`\\ s."""

    def __init__(self, capacity: int = 2048, enabled: bool | None = None):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self._buf: list[Event | None] = [None] * capacity
        self._next_seq = 1
        self._floor = 0  # events with seq <= floor were cleared
        self._lock = threading.Lock()
        if enabled is None:
            enabled = os.environ.get("TDT_OBS", "1") != "0"
        self.enabled = enabled

    def emit(self, kind: str, **fields) -> int:
        """Record one event; returns its seq (0 when disabled)."""
        if not self.enabled:
            return 0
        t = time.monotonic()
        with self._lock:
            seq = self._next_seq
            self._next_seq += 1
            self._buf[seq % self.capacity] = Event(seq, t, kind, fields)
        return seq

    @property
    def next_seq(self) -> int:
        return self._next_seq

    def tail(self, since: int = 0, limit: int | None = None,
             kind: str | None = None) -> tuple[list[Event], int]:
        """Events with ``seq > since``, oldest first, plus how many such
        events are GONE (overwritten by the ring). ``limit`` is a page
        size: it keeps the OLDEST ``limit`` so ``since=<last seq seen>``
        pages through a backlog without skipping anything still
        buffered. ``dropped == 0`` means the consumer saw (or will see,
        on later pages) everything since its last call. A negative
        ``since`` clamps to 0 (the before-everything cursor) — it must
        not read as phantom drops to a drop-summing consumer (the
        server additionally rejects it wire-side as ``bad_request``).

        ``kind`` filters to one event stream (``span`` /
        ``mega:launch`` / ``fault`` / ...) server-side, so stream
        consumers stop re-filtering the full firehose client-side.
        The filter applies AFTER the drop count (the ring cannot know
        an overwritten event's kind) and BEFORE ``limit`` (a page is
        ``limit`` MATCHING events, not ``limit`` scanned)."""
        since = max(since, 0)
        with self._lock:
            newest = self._next_seq - 1
            oldest = max(self._floor + 1, self._next_seq - self.capacity)
            start = max(since + 1, oldest)
            events = [self._buf[s % self.capacity]
                      for s in range(start, newest + 1)]
        if events:
            dropped = events[0].seq - since - 1
        else:
            dropped = max(0, newest - since)
        if kind is not None:
            events = [e for e in events if e.kind == kind]
        if limit is not None and limit >= 0:
            events = events[:limit]
        return events, dropped

    def clear(self) -> None:
        """Drop buffered events; seq numbering keeps increasing, so a
        tailer across a clear correctly observes a drop, not a reset."""
        with self._lock:
            self._buf = [None] * self.capacity
            self._floor = self._next_seq - 1

    def reset(self) -> None:
        """Hard reset (tests only): empty ring AND seq back to 1."""
        with self._lock:
            self._buf = [None] * self.capacity
            self._next_seq = 1
            self._floor = 0


_DEFAULT = EventRing()


def default_ring() -> EventRing:
    """The process-global ring the serving stack emits into."""
    return _DEFAULT


def emit(kind: str, **fields) -> int:
    """Emit into the default ring (the serving stack's one-liner)."""
    return _DEFAULT.emit(kind, **fields)
