"""Metrics registry: counters, gauges, log-bucketed histograms.

Zero-dependency serving telemetry (docs/observability.md). The serving
stack's only pre-existing observability was the merged chrome-trace
profiler (``runtime/profiling.py``) — fine for offline kernel work,
useless for a fleet: no latency distributions, no way to scrape a
server. This registry is the aggregation layer under the
``{"cmd": "metrics"}`` server verb:

- **Counters / gauges** — labeled, thread-safe, monotonically
  increasing / last-write-wins.
- **Histograms** — FIXED log-spaced bucket edges chosen at
  construction, so ``observe`` is one bisect + two adds and a snapshot
  is allocation-free (no per-sample storage, ever). p50/p90/p99 are
  derived from the bucket counts by interpolation — accurate to one
  bucket's width, which the default edges keep under ~33% relative
  error across nine decades.
- **Exposition** — :func:`prometheus_text` renders the whole registry
  in the Prometheus text format (HELP/TYPE comments, cumulative
  ``_bucket{le=...}`` rows, ``_sum``/``_count``); :meth:`Registry.snapshot`
  returns the same data as a JSON-ready dict with the derived
  quantiles inlined.
- **Disabled mode** — ``registry.enabled = False`` (or ``TDT_OBS=0``)
  turns every mutation into a single attribute check + return, so the
  telemetry can be priced at ~zero without recompiling anything. The
  token path never reads a metric, so outputs are bit-identical either
  way (``perf/obs_overhead_bench.py`` proves both properties).

One process-global default registry (:func:`default_registry`) backs
the engines and the server; tests reset it with ``Registry.clear``.
"""

from __future__ import annotations

import bisect
import math
import os
import re
import threading

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")


def log_buckets(lo: float, hi: float, per_decade: int = 4) -> tuple:
    """Geometric bucket edges from ``lo`` to (at least) ``hi`` with
    ``per_decade`` edges per factor of 10. The default latency edges
    (:data:`LATENCY_BUCKETS`) span 100 µs .. ~100 s."""
    if lo <= 0 or hi <= lo or per_decade < 1:
        raise ValueError(f"bad bucket spec lo={lo} hi={hi}/{per_decade}")
    edges = []
    k = math.ceil(math.log10(lo) * per_decade)
    while True:
        e = 10.0 ** (k / per_decade)
        edges.append(e)
        if e >= hi:
            return tuple(edges)
        k += 1


# Shared latency edges: ~78%-wide buckets over 1e-4 .. ~1e2 seconds.
LATENCY_BUCKETS = log_buckets(1e-4, 100.0, per_decade=4)
# Token-count edges for size-ish histograms (1 .. ~1e6).
SIZE_BUCKETS = log_buckets(1.0, 1e6, per_decade=2)


def _fmt(v: float) -> str:
    """Prometheus sample value: integers stay integral, floats use
    shortest-repr ``g`` formatting."""
    if isinstance(v, bool):
        return str(int(v))
    if isinstance(v, int) or (isinstance(v, float) and v.is_integer()
                              and abs(v) < 1e15):
        return str(int(v))
    return format(v, ".10g")


def _escape(v) -> str:
    """Escape a label value per the exposition grammar."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


class _Metric:
    """Base: a named, labeled family of series. Series are keyed by the
    tuple of label VALUES in the family's declared label-name order."""

    kind = "untyped"

    def __init__(self, registry: "Registry", name: str, help: str,
                 label_names: tuple):
        self._registry = registry
        self.name = name
        self.help = help
        self.label_names = label_names
        self._series: dict = {}

    def _key(self, labels: dict) -> tuple:
        if tuple(sorted(labels)) != tuple(sorted(self.label_names)):
            raise ValueError(
                f"{self.name}: got labels {sorted(labels)}, declared "
                f"{sorted(self.label_names)}"
            )
        return tuple(labels[k] for k in self.label_names)

    def _label_str(self, key: tuple, extra: str = "") -> str:
        parts = [f'{n}="{_escape(v)}"'
                 for n, v in zip(self.label_names, key)]
        if extra:
            parts.append(extra)
        return "{" + ",".join(parts) + "}" if parts else ""


class Counter(_Metric):
    """Monotonic counter. ``inc`` is a no-op when the owning registry
    is disabled."""

    kind = "counter"

    def inc(self, n: float = 1, **labels) -> None:
        reg = self._registry
        if not reg.enabled:
            return
        if n < 0:
            raise ValueError(f"{self.name}: counters only go up (n={n})")
        key = self._key(labels)
        with reg._lock:
            self._series[key] = self._series.get(key, 0) + n

    def value(self, **labels) -> float:
        return self._series.get(self._key(labels), 0)

    def _render(self, out: list) -> None:
        for key in sorted(self._series):
            out.append(f"{self.name}{self._label_str(key)} "
                       f"{_fmt(self._series[key])}")

    def _snap(self):
        return [{"labels": dict(zip(self.label_names, k)), "value": v}
                for k, v in sorted(self._series.items())]


class Gauge(_Metric):
    """Last-write-wins instantaneous value."""

    kind = "gauge"

    def set(self, v: float, **labels) -> None:
        reg = self._registry
        if not reg.enabled:
            return
        key = self._key(labels)
        with reg._lock:
            self._series[key] = v

    def add(self, n: float, **labels) -> None:
        reg = self._registry
        if not reg.enabled:
            return
        key = self._key(labels)
        with reg._lock:
            self._series[key] = self._series.get(key, 0) + n

    def value(self, **labels) -> float:
        return self._series.get(self._key(labels), 0)

    _render = Counter._render
    _snap = Counter._snap


class Histogram(_Metric):
    """Log-bucketed histogram with FIXED edges.

    A series is ``[counts, sum]`` where ``counts[i]`` holds
    observations ``<= edges[i]`` (exclusive of lower edges) and
    ``counts[-1]`` is the +Inf overflow — per-bucket, cumulated only at
    exposition time. No per-sample state: snapshots cost O(buckets)."""

    kind = "histogram"

    def __init__(self, registry, name, help, label_names,
                 buckets: tuple = LATENCY_BUCKETS):
        super().__init__(registry, name, help, label_names)
        self.edges = tuple(float(e) for e in buckets)
        if list(self.edges) != sorted(set(self.edges)):
            raise ValueError(f"{name}: bucket edges must strictly increase")

    def observe(self, v: float, **labels) -> None:
        self.observe_n(v, 1, **labels)

    def observe_n(self, v: float, n: int = 1, **labels) -> None:
        """``n`` observations of the same value in one bucket
        increment — the bulk path for high-rate emitters that can
        pre-group identical samples (the device task tracer folds a
        whole launch's per-task durations grouped by (opcode, ticks),
        so a launch costs O(distinct durations) registry ops, not
        O(records))."""
        reg = self._registry
        if not reg.enabled or n <= 0:
            return
        key = self._key(labels)
        i = bisect.bisect_left(self.edges, v)
        with reg._lock:
            series = self._series.get(key)
            if series is None:
                series = self._series[key] = [
                    [0] * (len(self.edges) + 1), 0.0
                ]
            series[0][i] += n
            series[1] += v * n

    def count(self, **labels) -> int:
        s = self._series.get(self._key(labels))
        return sum(s[0]) if s else 0

    def quantile(self, q: float, **labels) -> float | None:
        """Derive quantile ``q`` (0..1) from the bucket counts by
        linear interpolation inside the holding bucket; None when the
        series is empty. Accurate to one bucket's width."""
        s = self._series.get(self._key(labels))
        if not s or not sum(s[0]):
            return None
        counts = s[0]
        total = sum(counts)
        rank = q * total
        cum = 0
        for i, c in enumerate(counts):
            cum += c
            if cum >= rank and c > 0:
                if i >= len(self.edges):
                    return self.edges[-1]  # overflow bucket: clamp
                hi = self.edges[i]
                lo = self.edges[i - 1] if i > 0 else 0.0
                frac = (rank - (cum - c)) / c
                return lo + (hi - lo) * frac
        return self.edges[-1]

    def _render(self, out: list) -> None:
        for key in sorted(self._series):
            counts, total = self._series[key]
            cum = 0
            for i, edge in enumerate(self.edges):
                cum += counts[i]
                le = f'le="{_fmt(edge)}"'
                out.append(f"{self.name}_bucket{self._label_str(key, le)} "
                           f"{cum}")
            cum += counts[-1]
            inf = 'le="+Inf"'
            out.append(f"{self.name}_bucket{self._label_str(key, inf)} "
                       f"{cum}")
            out.append(f"{self.name}_sum{self._label_str(key)} "
                       f"{_fmt(total)}")
            out.append(f"{self.name}_count{self._label_str(key)} {cum}")

    def _snap(self):
        snaps = []
        for key, (counts, total) in sorted(self._series.items()):
            labels = dict(zip(self.label_names, key))
            snaps.append({
                "labels": labels,
                "count": sum(counts),
                "sum": total,
                "p50": self.quantile(0.50, **labels),
                "p90": self.quantile(0.90, **labels),
                "p99": self.quantile(0.99, **labels),
                "buckets": {"edges": list(self.edges),
                            "counts": list(counts)},
            })
        return snaps


class Registry:
    """Thread-safe named-metric registry. Re-registering a name with
    the same kind/labels returns the existing family (many engine
    instances share one process registry); a mismatched redeclaration
    raises."""

    def __init__(self, enabled: bool | None = None):
        self._metrics: dict[str, _Metric] = {}
        self._lock = threading.Lock()
        if enabled is None:
            enabled = os.environ.get("TDT_OBS", "1") != "0"
        self.enabled = enabled

    def _get_or_create(self, cls, name, help, labels, **kw):
        if not _NAME_RE.match(name):
            raise ValueError(f"bad metric name {name!r}")
        label_names = tuple(labels)
        for ln in label_names:
            if not _LABEL_RE.match(ln):
                raise ValueError(f"bad label name {ln!r} on {name}")
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls) or m.label_names != label_names:
                    raise ValueError(
                        f"metric {name} redeclared as {cls.kind}"
                        f"{sorted(label_names)} but exists as {m.kind}"
                        f"{sorted(m.label_names)}"
                    )
                want = kw.get("buckets")
                if (want is not None
                        and tuple(float(e) for e in want) != m.edges):
                    raise ValueError(
                        f"metric {name} redeclared with buckets "
                        f"{tuple(want)} but exists with {m.edges}"
                    )
                return m
            m = cls(self, name, help, label_names, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "", labels=()) -> Counter:
        return self._get_or_create(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "", labels=()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labels)

    def histogram(self, name: str, help: str = "", labels=(),
                  buckets: tuple = LATENCY_BUCKETS) -> Histogram:
        return self._get_or_create(Histogram, name, help, labels,
                                   buckets=buckets)

    def get(self, name: str) -> _Metric | None:
        return self._metrics.get(name)

    def clear(self) -> None:
        """Zero every series IN PLACE: cached metric handles held by
        long-lived engines stay valid (tests reset between cases)."""
        with self._lock:
            for m in self._metrics.values():
                m._series.clear()

    def snapshot(self) -> dict:
        """JSON-ready view: per family kind/help + series with derived
        p50/p90/p99 for histograms. Families with no series yet are
        omitted, same as :func:`prometheus_text` (registration alone —
        e.g. eagerly cached handles — is not data)."""
        with self._lock:
            return {
                name: {"type": m.kind, "help": m.help, "series": m._snap()}
                for name, m in sorted(self._metrics.items())
                if m._series
            }


def prometheus_text(registry: "Registry | None" = None) -> str:
    """Render the registry in the Prometheus text exposition format.
    Every emitted line matches the grammar (tests parse it back)."""
    reg = registry if registry is not None else default_registry()
    out: list[str] = []
    with reg._lock:
        for name in sorted(reg._metrics):
            m = reg._metrics[name]
            if not m._series:
                continue
            if m.help:
                out.append(f"# HELP {name} {_escape_help(m.help)}")
            out.append(f"# TYPE {name} {m.kind}")
            m._render(out)
    return "\n".join(out) + ("\n" if out else "")


def _escape_help(text: str) -> str:
    return text.replace("\\", "\\\\").replace("\n", "\\n")


# One exposition sample line: name, optional {labels}, value(+timestamp
# tail, kept verbatim). Greedy label body: a label VALUE containing the
# literal sequence `"} ` could in principle misparse, but _escape never
# produces one and our own exposition is the only input.
_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s(.+)$"
)


def merge_expositions(parts: dict[str, str],
                      label: str = "replica") -> str:
    """Merge several Prometheus text expositions into ONE, tagging
    every sample with ``label="<source key>"`` (inserted first; the
    source key is escaped per the exposition grammar, so a respawned
    replica's ``r0#2`` or any quoted name survives). ``# HELP`` /
    ``# TYPE`` lines are kept once per family (first seen wins — the
    registry's redeclaration rule already guarantees they agree), and
    samples are regrouped by family across sources so TYPE adjacency
    stays valid. Histogram child series (``_bucket``/``_sum``/
    ``_count``) follow their declared family.

    This is the fleet-scope scrape's merge half (docs/scale-out.md
    "Fleet-scope telemetry"): each child process owns a process-local
    registry; ``FleetSupervisor.fleet_metrics`` fans the ``metrics``
    verb out and hands the texts here, so one scrape sees every
    replica's counters as distinct ``{replica=...}`` series whose sum
    equals the children's own scrapes."""
    helps: dict[str, str] = {}
    types: dict[str, str] = {}
    family_of: dict[str, str] = {}
    samples: dict[str, list[str]] = {}
    order: list[str] = []

    def family(name: str) -> str:
        fam = family_of.get(name)
        if fam is not None:
            return fam
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix):
                base = name[: -len(suffix)]
                if base in types:
                    return base
        return name

    for src, text in parts.items():
        esc = _escape(src)
        for line in (text or "").splitlines():
            line = line.strip()
            if not line:
                continue
            if line.startswith("# HELP ") or line.startswith("# TYPE "):
                kind, name = line.split(None, 3)[1:3]
                store = helps if kind == "HELP" else types
                if name not in store:
                    store[name] = line
                if kind == "TYPE" and "histogram" in line:
                    for suffix in ("_bucket", "_sum", "_count"):
                        family_of[name + suffix] = name
                continue
            m = _SAMPLE_RE.match(line)
            if m is None:
                continue  # foreign noise: never corrupt the merge
            name, labels, value = m.groups()
            if labels and f'{label}="' in labels:
                # The sample already carries the merge label (the
                # router's tdt_router_*{replica=...} series name the
                # child they DESCRIBE): keep it — a duplicate label
                # name would make the line grammar-invalid.
                tagged = labels
            elif labels:
                tagged = f'{label}="{esc}",{labels}'
            else:
                tagged = f'{label}="{esc}"'
            fam = family(name)
            if fam not in samples:
                samples[fam] = []
                order.append(fam)
            samples[fam].append(f"{name}{{{tagged}}} {value}")
    out: list[str] = []
    for fam in order:
        if fam in helps:
            out.append(helps[fam])
        if fam in types:
            out.append(types[fam])
        out.extend(samples[fam])
    return "\n".join(out) + ("\n" if out else "")


_DEFAULT = Registry()


def default_registry() -> Registry:
    """The process-global registry the engines and server publish to."""
    return _DEFAULT


def counter(name: str, help: str = "", labels=()) -> Counter:
    return _DEFAULT.counter(name, help, labels)


def gauge(name: str, help: str = "", labels=()) -> Gauge:
    return _DEFAULT.gauge(name, help, labels)


def histogram(name: str, help: str = "", labels=(),
              buckets: tuple = LATENCY_BUCKETS) -> Histogram:
    return _DEFAULT.histogram(name, help, labels, buckets)
