"""Serving telemetry (docs/observability.md): metrics registry,
per-request timelines, bounded event ring, Prometheus exposition.

Zero-dependency and host-side only — nothing here touches the token
path, so engine outputs are bit-identical with telemetry on or off
(``perf/obs_overhead_bench.py`` proves it, along with <1% decode-step
overhead enabled). ``set_enabled(False)`` (or env ``TDT_OBS=0``)
drops every mutation to an attribute check.

- :mod:`~triton_distributed_tpu.obs.metrics` — counters, gauges,
  log-bucketed histograms; :func:`prometheus_text` renders the
  process-global registry for the server's ``{"cmd": "metrics"}`` verb.
- :mod:`~triton_distributed_tpu.obs.timeline` — per-request lifecycle
  stamps yielding queue-wait/TTFT/TPOT/e2e histograms labeled by the
  PR 3 finish-status taxonomy.
- :mod:`~triton_distributed_tpu.obs.events` — bounded structured-event
  ring with gap-free seq numbers for drop-aware tailing
  (``{"cmd": "events"}``).
- :mod:`~triton_distributed_tpu.obs.slo` — declarative SLO deadlines
  and wire-side goodput accounting (``{"cmd": "slo"}``,
  docs/observability.md "SLO goodput").
- :mod:`~triton_distributed_tpu.obs.kernel_trace` — decoder for the
  megakernel's device task-tracer ring (docs/observability.md "Device
  task tracer"). NOT imported here: it pulls the megakernel package
  (and therefore jax), while this top-level import stays host-only.
"""

from triton_distributed_tpu.obs.events import (  # noqa: F401
    Event,
    EventRing,
    default_ring,
    emit,
)
from triton_distributed_tpu.obs.metrics import (  # noqa: F401
    LATENCY_BUCKETS,
    SIZE_BUCKETS,
    Registry,
    counter,
    default_registry,
    gauge,
    histogram,
    log_buckets,
    prometheus_text,
)
from triton_distributed_tpu.obs.slo import SLOSpec  # noqa: F401
from triton_distributed_tpu.obs.timeline import (  # noqa: F401
    FINISH_STATUSES,
    Timeline,
    observe_request,
)


def set_enabled(flag: bool) -> None:
    """Master switch for the process-global telemetry (registry AND
    event ring). Off turns every emit/inc/observe into an attribute
    check + return; the token path is untouched either way."""
    default_registry().enabled = bool(flag)
    default_ring().enabled = bool(flag)


def is_enabled() -> bool:
    return default_registry().enabled
