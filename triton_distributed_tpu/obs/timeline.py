"""Per-request lifecycle timelines: TTFT, TPOT, queue-wait, e2e.

Each serving :class:`~triton_distributed_tpu.models.continuous.Request`
carries one :class:`Timeline` with monotonic stamps at the lifecycle
transitions the engines drive:

=================  ====================================================
``enqueue``        the request entered the system (server payload
                   decode, or ``run()`` entry for direct callers)
``admit``          a decode slot + pages were assigned
``first_chunk``    its first prefill chunk program was dispatched
``first_token``    its first token was sampled (admission prefill)
``finish``         terminal: evicted on success, or torn down with a
                   PR 3 failure status
=================  ====================================================

Derived durations: ``queue_wait_s`` (enqueue→admit),
``prefill_dispatch_s`` (admit→first chunk: how long an admitted
request waited for the chunked-prefill scheduler to first touch it),
``ttft_s`` (enqueue→first token), ``e2e_s`` (enqueue→finish), and
``tpot_s`` — per-output-token time over the steady decode phase,
``(finish - first_token) / (tokens_out - 1)`` (undefined until a
second token exists).

:func:`observe_request` folds a finished timeline into the default
metrics registry: one histogram per duration (TTFT/TPOT/e2e labeled by
finish ``status`` from the PR 3 taxonomy), ``tdt_requests_total`` by
status, and tokens-in/out counters plus per-request size histograms. ``finish`` is latch-once, so a
request can never be observed twice no matter how many teardown paths
race over it.
"""

from __future__ import annotations

import time

from triton_distributed_tpu.obs import metrics as _metrics

# PR 3 failure taxonomy (models/continuous.py) + success + the
# client-initiated ``cancelled`` terminal (docs/serving.md "Streaming
# & cancellation"). Exposition labels come from Request.status, which
# is always one of these.
FINISH_STATUSES = (
    "ok",
    "unservable",
    "overloaded",
    "deadline_exceeded",
    "nan_logits",
    "failed",
    "aborted",
    "cancelled",
)


class Timeline:
    """Monotonic lifecycle stamps for one request. Stamps latch on
    first write (a retried admission keeps the FIRST admit time — the
    queue-wait the client actually experienced).

    ``token_ts`` is the per-token stamp trail the STREAMING path fills
    (docs/serving.md "Streaming & cancellation"): one monotonic stamp
    per token frame, taken at the wire write — so TTFT/TPOT derived
    from a streamed timeline measure when tokens reached the socket,
    not when the engine latched them. Engine-side timelines leave it
    empty and keep the PR 5 first-token/finish arithmetic."""

    __slots__ = ("enqueue_t", "admit_t", "first_chunk_t", "first_token_t",
                 "finish_t", "tokens_in", "tokens_out", "status",
                 "reroutes", "token_ts")

    def __init__(self):
        self.enqueue_t: float | None = None
        self.admit_t: float | None = None
        self.first_chunk_t: float | None = None
        self.first_token_t: float | None = None
        self.finish_t: float | None = None
        self.tokens_in = 0
        self.tokens_out = 0
        self.status: str | None = None
        # Multi-replica serving (docs/scale-out.md): how many times the
        # router re-routed this request off a dead/timed-out replica
        # before this attempt. Stamped by the router, folded into
        # ``tdt_request_reroutes_total`` at finish.
        self.reroutes = 0
        # Wire-side per-token stamps (streaming path only).
        self.token_ts: list[float] = []

    def _stamp(self, attr: str) -> None:
        if getattr(self, attr) is None:
            setattr(self, attr, time.monotonic())

    def stamp_enqueue(self) -> None:
        self._stamp("enqueue_t")

    def stamp_admit(self) -> None:
        self._stamp("admit_t")

    def stamp_first_chunk(self) -> None:
        self._stamp("first_chunk_t")

    def stamp_first_token(self) -> None:
        self._stamp("first_token_t")

    def stamp_token(self) -> None:
        """One per-token stamp (streaming wire writes). The first one
        also latches ``first_token_t``, so a wire-side timeline's TTFT
        is the first FRAME's departure, not an engine-side latch."""
        t = time.monotonic()
        if self.first_token_t is None:
            self.first_token_t = t
        self.token_ts.append(t)

    def finish(self, status: str) -> bool:
        """Latch the terminal stamp + status; True exactly once (the
        caller observes metrics only on True, so racing teardown paths
        can't double-count a request)."""
        if self.status is not None:
            return False
        self.status = status
        self._stamp("finish_t")
        return True

    # -- derived durations -------------------------------------------------

    @staticmethod
    def _delta(a: float | None, b: float | None) -> float | None:
        if a is None or b is None:
            return None
        return max(b - a, 0.0)

    @property
    def queue_wait_s(self) -> float | None:
        return self._delta(self.enqueue_t, self.admit_t)

    @property
    def prefill_dispatch_s(self) -> float | None:
        return self._delta(self.admit_t, self.first_chunk_t)

    @property
    def ttft_s(self) -> float | None:
        return self._delta(self.enqueue_t, self.first_token_t)

    @property
    def e2e_s(self) -> float | None:
        return self._delta(self.enqueue_t, self.finish_t)

    @property
    def tpot_s(self) -> float | None:
        """Steady-state per-output-token time: decode time after the
        first token, averaged over the remaining tokens. None until a
        second token exists (a 1-token request has no decode phase).
        With per-token wire stamps (streaming) the span is measured
        frame-to-frame — finish-side slack (summary construction)
        never inflates it."""
        if len(self.token_ts) >= 2:
            return ((self.token_ts[-1] - self.token_ts[0])
                    / (len(self.token_ts) - 1))
        span = self._delta(self.first_token_t, self.finish_t)
        if span is None or self.tokens_out < 2:
            return None
        return span / (self.tokens_out - 1)


def _handles(reg) -> dict:
    """Per-registry metric handles, resolved ONCE and cached on the
    registry instance — a request completion must not pay nine
    get-or-create lookups (name-regex + registry lock) the way the
    engines' cached ``_bump`` handles already avoid. ``Registry.clear``
    zeroes series in place, so cached handles survive test resets; a
    racing double-build is harmless (get-or-create is idempotent)."""
    h = getattr(reg, "_timeline_handles", None)
    if h is None:
        h = {
            "requests": reg.counter(
                "tdt_requests_total",
                "Requests finished, by terminal status (PR 3 taxonomy).",
                labels=("status",),
            ),
            "tokens_in": reg.counter(
                "tdt_tokens_in_total", "Prompt tokens accepted."
            ),
            "tokens_in_size": reg.histogram(
                "tdt_request_tokens_in", "Prompt tokens per request.",
                buckets=_metrics.SIZE_BUCKETS,
            ),
            "tokens_out": reg.counter(
                "tdt_tokens_out_total",
                "Tokens generated (partials included).",
            ),
            "tokens_out_size": reg.histogram(
                "tdt_request_tokens_out", "Output tokens per request.",
                buckets=_metrics.SIZE_BUCKETS,
            ),
            "reroutes": reg.counter(
                "tdt_request_reroutes_total",
                "Times requests were re-routed off a dead or "
                "timed-out replica (docs/scale-out.md).",
            ),
            "queue_wait": reg.histogram(
                "tdt_request_queue_wait_seconds",
                "Enqueue-to-admission wait.",
            ),
            "prefill_dispatch": reg.histogram(
                "tdt_request_prefill_dispatch_seconds",
                "Admission-to-first-prefill-chunk wait.",
            ),
            "ttft": reg.histogram(
                "tdt_request_ttft_seconds",
                "Time to first token, by finish status.",
                labels=("status",),
            ),
            "tpot": reg.histogram(
                "tdt_request_tpot_seconds",
                "Per-output-token time after the first token, by finish "
                "status.",
                labels=("status",),
            ),
            "e2e": reg.histogram(
                "tdt_request_e2e_seconds",
                "Enqueue-to-finish latency, by finish status.",
                labels=("status",),
            ),
        }
        reg._timeline_handles = h
    return h


def observe_request(tl: Timeline, registry=None) -> None:
    """Fold one FINISHED timeline into the metrics registry. Durations
    that never happened (a shed request has no admit stamp) are simply
    skipped — the status-labeled ``tdt_requests_total`` still counts
    the request."""
    reg = registry if registry is not None else _metrics.default_registry()
    h = _handles(reg)
    status = tl.status or "ok"
    h["requests"].inc(status=status)
    if tl.reroutes:
        h["reroutes"].inc(tl.reroutes)
    if tl.tokens_in:
        h["tokens_in"].inc(tl.tokens_in)
        h["tokens_in_size"].observe(tl.tokens_in)
    if tl.tokens_out:
        h["tokens_out"].inc(tl.tokens_out)
        h["tokens_out_size"].observe(tl.tokens_out)
    qw = tl.queue_wait_s
    if qw is not None:
        h["queue_wait"].observe(qw)
    pd = tl.prefill_dispatch_s
    if pd is not None:
        h["prefill_dispatch"].observe(pd)
    ttft = tl.ttft_s
    if ttft is not None:
        h["ttft"].observe(ttft, status=status)
    tpot = tl.tpot_s
    if tpot is not None:
        h["tpot"].observe(tpot, status=status)
    e2e = tl.e2e_s
    if e2e is not None:
        h["e2e"].observe(e2e, status=status)
